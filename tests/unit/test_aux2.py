"""Tests: compressed comm, curriculum/data pipeline, compression, LoRA,
eigenvalue."""

import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.compression.compress import (CompressionScheduler,
                                                fake_quantize, init_compression,
                                                prune_mask)
from deepspeed_tpu.linear.optimized_linear import (LoRAConfig, init_lora_linear,
                                                   lora_linear,
                                                   trainable_lora_params)
from deepspeed_tpu.parallel.mesh import DATA_AXIS, MeshTopology
from deepspeed_tpu.utils.jax_compat import shard_map
from deepspeed_tpu.runtime.comm.compressed import compressed_all_reduce
from deepspeed_tpu.runtime.config import MeshConfig
from deepspeed_tpu.runtime.data_pipeline.curriculum import (
    CurriculumConfig, CurriculumScheduler, VariableBatchConfig,
    apply_seqlen_curriculum, batch_by_token_budget)
from deepspeed_tpu.runtime.eigenvalue import top_eigenvalue


def test_compressed_allreduce_error_feedback(devices8):
    topo = MeshTopology(MeshConfig(data=-1), devices8)

    def body(g, e):
        return compressed_all_reduce(g, e, DATA_AXIS)

    f = shard_map(body, check_vma=False, mesh=topo.mesh,
                  in_specs=(P(DATA_AXIS, None), P(DATA_AXIS, None)),
                  out_specs=(P(DATA_AXIS, None), P(DATA_AXIS, None)))
    rng = np.random.RandomState(0)
    g = jnp.asarray(rng.randn(8, 256).astype(np.float32))
    e = jnp.zeros_like(g)
    out, new_e = f(g, e)
    # each rank's result approximates the global mean of its own row? No:
    # pmean over data of per-rank rows -> all rows equal the mean
    expect = np.mean(np.asarray(g), axis=0)
    np.testing.assert_allclose(np.asarray(out)[0], expect, atol=0.05)
    # error feedback: residual is bounded by the quant step and nonzero
    assert float(jnp.max(jnp.abs(new_e))) < 0.1


def test_curriculum_linear_ladder():
    cfg = CurriculumConfig(enabled=True, min_difficulty=64, max_difficulty=512,
                           total_curriculum_step=100, difficulty_step=64)
    s = CurriculumScheduler(cfg)
    assert s.get_difficulty(0) == 64
    assert s.get_difficulty(100) == 512
    mid = s.get_difficulty(50)
    assert 64 <= mid <= 512 and mid % 64 == 0
    # ladder => few distinct shapes
    shapes = {s.get_difficulty(t) for t in range(100)}
    assert len(shapes) <= 8


def test_curriculum_discrete_and_truncation():
    cfg = CurriculumConfig(enabled=True, schedule_type="fixed_discrete",
                           difficulty=[32, 64, 128], max_step=[10, 20])
    s = CurriculumScheduler(cfg)
    assert s.get_difficulty(5) == 32
    assert s.get_difficulty(15) == 64
    assert s.get_difficulty(25) == 128
    batch = {"input_ids": jnp.ones((2, 128), jnp.int32)}
    out = apply_seqlen_curriculum(batch, 32)
    assert out["input_ids"].shape == (2, 32)


def test_variable_batch_token_budget():
    lens = np.array([100, 200, 300, 1000, 50, 60])
    batches, mults = batch_by_token_budget(lens, VariableBatchConfig(
        max_tokens_per_batch=600))
    covered = sorted(int(i) for b in batches for i in b)
    assert covered == list(range(6))
    for b in batches:
        max_len = max(int(lens[i]) for i in b)
        assert max_len * len(b) <= 600 or len(b) == 1
    assert len(mults) == len(batches)


def test_fake_quantize_ste_gradient():
    w = jnp.linspace(-1, 1, 64)
    g = jax.grad(lambda w: jnp.sum(fake_quantize(w, 4) ** 2))(w)
    assert np.all(np.isfinite(np.asarray(g)))
    q = fake_quantize(w, 4)
    assert len(np.unique(np.asarray(q).round(6))) <= 16


def test_prune_and_scheduler():
    params = {"layer": {"w": jnp.asarray(np.random.RandomState(0).randn(32, 32),
                                         jnp.float32),
                        "b": jnp.zeros(32)}}
    cfg = {"compression_training": {
        "sparse_pruning": {"shared_parameters": {"enabled": True, "ratio": 0.5,
                                                 "schedule_offset": 0}}}}
    out, sched = init_compression(params, cfg)
    w = np.asarray(out["layer"]["w"])
    assert (w == 0).mean() == pytest.approx(0.5, abs=0.05)
    # before offset nothing happens
    sched2 = CompressionScheduler({"sparse_pruning": {
        "shared_parameters": {"enabled": True, "ratio": 0.5,
                              "schedule_offset": 100}}})
    out2 = sched2.transform_params(params, global_step=0)
    assert (np.asarray(out2["layer"]["w"]) == 0).mean() < 0.1


def test_lora_linear_trains_only_adapters():
    lora = LoRAConfig(lora_r=4, lora_alpha=8)
    params = init_lora_linear(jax.random.PRNGKey(0), 16, 8, lora)
    x = jnp.ones((2, 16))

    def loss(p):
        return jnp.sum(lora_linear(p, x, lora) ** 2)

    g = jax.grad(loss)(params)
    assert float(jnp.max(jnp.abs(g["base"]))) == 0.0  # frozen
    # lora_b starts at zero so grad_a is zero at init; grad_b carries signal
    assert float(jnp.max(jnp.abs(g["lora_b"]))) > 0.0
    mask = trainable_lora_params(params)
    assert mask["lora_a"] and not mask["base"]


def test_lora_quantized_base():
    lora = LoRAConfig(lora_r=4)
    from deepspeed_tpu.linear.optimized_linear import QuantizationConfig

    params = init_lora_linear(jax.random.PRNGKey(0), 16, 8, lora,
                              quantize=QuantizationConfig())
    out = lora_linear(params, jnp.ones((2, 16)), lora)
    assert out.shape == (2, 8)


def test_eigenvalue_power_iteration():
    # quadratic loss: 0.5 x^T A x has hessian A; top |eig| of diag(1..4) = 4
    A = jnp.diag(jnp.asarray([1.0, 2.0, 3.0, 4.0]))

    def loss(x):
        return 0.5 * x @ A @ x

    eig = top_eigenvalue(loss, jnp.ones(4), jax.random.PRNGKey(0), max_iters=50)
    np.testing.assert_allclose(float(eig), 4.0, rtol=1e-3)


def test_structured_pruning_and_physical_clean():
    """Head + channel pruning masks whole structures during training, and
    redundancy_clean PHYSICALLY shrinks the arrays: the sliced model (new
    config) computes the same loss as the masked model (reference
    basic_layer.py head/channel pruning + redundancy_clean folding)."""
    import jax

    from deepspeed_tpu.compression.compress import redundancy_clean
    from deepspeed_tpu.models.llama import llama_config
    from deepspeed_tpu.models.transformer import (causal_lm_loss,
                                                  init_transformer_params)

    cfg = llama_config("tiny", max_seq_len=16, attn_impl="xla")  # MHA tiny
    params = init_transformer_params(cfg, jax.random.PRNGKey(0))
    comp = {"compression_training": {
        "head_pruning": {"shared_parameters": {"enabled": True,
                                               "dense_ratio": 0.5}},
        "channel_pruning": {"shared_parameters": {"enabled": True,
                                                  "dense_ratio": 0.5}},
    }}
    masked, sched = init_compression(params, comp, n_heads=cfg.n_heads)
    # whole FFN channels went to zero
    up = np.asarray(masked["layers"]["mlp"]["w_up"])
    zero_cols = np.all(up == 0, axis=1)  # [L, F]
    assert (zero_cols.sum(-1) == cfg.ffn_size // 2).all()

    ids = {"input_ids": jnp.asarray(
        np.random.RandomState(0).randint(0, 256, (2, 16)), jnp.int32)}
    masked_loss = float(causal_lm_loss(cfg, masked, ids, None))

    shrunk, new_cfg = redundancy_clean(params, sched, cfg)
    assert new_cfg.ffn_size == cfg.ffn_size // 2
    assert new_cfg.n_heads == cfg.n_heads // 2
    assert shrunk["layers"]["mlp"]["w_up"].shape[-1] == cfg.ffn_size // 2
    assert shrunk["layers"]["attn"]["wo"].shape[1] == \
        (cfg.n_heads // 2) * cfg.head_dim
    shrunk_loss = float(causal_lm_loss(new_cfg, shrunk, ids, None))
    np.testing.assert_allclose(shrunk_loss, masked_loss, rtol=1e-5)


def test_structured_pruning_respects_per_method_offsets():
    """head offset 0 / channel offset 1000: at step 0 only heads prune
    (code-review r3 finding)."""
    import jax

    from deepspeed_tpu.models.llama import llama_config
    from deepspeed_tpu.models.transformer import init_transformer_params

    cfg = llama_config("tiny", max_seq_len=16)
    params = init_transformer_params(cfg, jax.random.PRNGKey(0))
    comp = {"compression_training": {
        "head_pruning": {"shared_parameters": {"enabled": True,
                                               "dense_ratio": 0.5,
                                               "schedule_offset": 0}},
        "channel_pruning": {"shared_parameters": {"enabled": True,
                                                  "dense_ratio": 0.5,
                                                  "schedule_offset": 1000}},
    }}
    masked, sched = init_compression(params, comp, n_heads=cfg.n_heads)
    up = np.asarray(masked["layers"]["mlp"]["w_up"])
    assert not np.any(np.all(up == 0, axis=1)), "channels pruned early"
    wo = np.asarray(masked["layers"]["attn"]["wo"])
    assert np.any(np.all(wo == 0, axis=2)), "heads not pruned at offset 0"
    # at step 1000, channels join
    masked2 = sched.transform_params(params, 1000, n_heads=cfg.n_heads)
    up2 = np.asarray(masked2["layers"]["mlp"]["w_up"])
    assert np.any(np.all(up2 == 0, axis=1))


def test_structured_pruning_non_transformer_degrades_gracefully():
    """Wrong layout: warn + disable, do NOT crash (code-review r3)."""
    params = {"w1": jnp.ones((8, 8)), "w2": jnp.ones((8, 4))}
    comp = {"compression_training": {
        "head_pruning": {"shared_parameters": {"enabled": True}}}}
    out, sched = init_compression(params, comp, n_heads=4)
    assert not sched.head_prune.enabled
    np.testing.assert_allclose(np.asarray(out["w1"]), np.ones((8, 8)))


def test_bench_sweep_tool_routing(tmp_path, monkeypatch):
    """The sweep drives bench.py for train rungs and the named tool for
    _tool rungs, with ambient DSTPU_BENCH_/DSTPU_IBENCH_ vars scrubbed so
    a leaked export cannot silently reshape a rung."""
    import importlib.util
    import subprocess as sp

    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    spec = importlib.util.spec_from_file_location(
        "bench_sweep", os.path.join(repo, "tools", "bench_sweep.py"))
    sweep = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sweep)

    calls = []

    def fake_run(cmd, capture_output, text, env, timeout):
        calls.append((cmd, env))

        class R:
            stdout = '{"value": 1, "unit": "x"}'
            stderr = ""
        return R()

    monkeypatch.setattr(sp, "run", fake_run)
    monkeypatch.setattr(sweep, "subprocess", sp)
    monkeypatch.setattr(sweep, "ROOT", str(tmp_path))
    os.makedirs(tmp_path / "docs", exist_ok=True)
    monkeypatch.setenv("DSTPU_BENCH_SIZE", "leaked")
    monkeypatch.setenv("DSTPU_IBENCH_GEN", "leaked")
    # routing under test, not the PR-11 contract gate (its subprocess call
    # would hit the fake_run signature); the provenance stamp still rides
    monkeypatch.setenv("DSTPU_SWEEP_SKIP_CONTRACTS", "1")
    monkeypatch.setattr(sweep.sys, "argv", ["bench_sweep.py", "flagship",
                                            "serving-160m"])
    assert sweep.main() == 0
    (cmd1, env1), (cmd2, env2) = calls
    # ROOT points at an empty artifact tree: the stamped hash is the
    # explicit no-goldens sentinel, never a hash-of-nothing
    with open(tmp_path / "docs" / "BENCH_SWEEP.json") as f:
        recs = json.load(f)
    assert all(r["contract_set_hash"] == "no-goldens" for r in recs)
    assert cmd1[1].endswith("bench.py")
    assert env1["DSTPU_BENCH_SIZE"] == "160m"  # rung wins over ambient
    assert "DSTPU_IBENCH_GEN" not in env1
    assert cmd2[1].endswith(os.path.join("tools", "bench_inference.py"))
    assert env2["DSTPU_IBENCH_GEN"] == "128"
    assert "_tool" not in env2 and "DSTPU_BENCH_SIZE" not in env2


def _load_bench():
    import importlib.util
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    spec = importlib.util.spec_from_file_location(
        "bench_mod", os.path.join(repo, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class _Proc:
    def __init__(self, rc=0, out="", err=""):
        self.returncode, self.stdout, self.stderr = rc, out, err


def test_bench_parent_ladder_classification(monkeypatch):
    """The hang-proof ladder: OOM steps down the bs ladder, a hang kills
    the child and re-probes, a wedged lease goes straight to the CPU
    fallback, and Pallas lowering failures enter the XLA phase."""
    import subprocess as sp
    bench = _load_bench()
    monkeypatch.setenv("DSTPU_BENCH_RUNG_TIMEOUT", "7")
    calls = []

    def run_script(script):
        def fake_run(cmd, **kw):
            if "--cpu" in cmd:
                calls.append(("cpu", kw["env"].get(
                    "DSTPU_BENCH_FALLBACK_REASON", "")))
                return _Proc(rc=0)
            ev = kw["env"]
            calls.append((ev["DSTPU_BENCH_ATTN"], ev["DSTPU_BENCH_BS"]))
            act = script.pop(0)
            if act == "hang":
                raise sp.TimeoutExpired(cmd, kw["timeout"])
            if act == "oom":  # real child contract: marker on stdout
                return _Proc(rc=1, out='{"child_error": "JaxRuntimeError: RESOURCE_EXHAUSTED: out of memory"}\n')
            if act == "mosaic":
                return _Proc(rc=1, out='{"child_error": "MosaicError: Mosaic lowering failed: op xyz"}\n')
            if act == "sigkill":  # no marker: stderr tail is the fallback
                return _Proc(rc=-9, err="Killed")
            return _Proc(rc=0, out='{"value": 1}\n')

        monkeypatch.setattr(bench.subprocess, "run", fake_run)
        calls.clear()
        return bench._parent_ladder()

    # OOM at 32 and 16, success at 8 — stays in the flash phase
    assert run_script(["oom", "oom", "ok"]) == 0
    assert calls == [("flash", "32"), ("flash", "16"), ("flash", "8")]

    # hang at 32, probe says lease ok -> next rung succeeds
    monkeypatch.setattr(bench, "_backend_usable",
                        lambda: (True, "", "TPU v0"))
    assert run_script(["hang", "ok"]) == 0
    assert calls == [("flash", "32"), ("flash", "16")]

    # hang at 32, kill wedged the lease -> one CPU fallback, reason recorded
    monkeypatch.setattr(bench, "_backend_usable", lambda: (False, "dead", ""))
    assert run_script(["hang"]) == 0
    assert calls[-1][0] == "cpu" and "wedged" in calls[-1][1]
    assert len(calls) == 2

    # mosaic failure -> xla phase with the bs ladder capped at 8
    assert run_script(["mosaic", "ok"]) == 0
    assert calls == [("flash", "32"), ("xla", "8")]

    # OOM all the way down -> CPU fallback, no pointless xla phase
    assert run_script(["oom", "oom", "oom"]) == 0
    assert calls[-1][0] == "cpu" and "smallest rung" in calls[-1][1]
    assert len(calls) == 4


def test_bench_child_error_marker_contract():
    """A failing --child exits nonzero with a machine-readable marker as
    its last stdout line — what the parent ladder classifies on."""
    import json as _json
    import subprocess as sp

    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = dict(os.environ, DSTPU_BENCH_MODEL="not-a-family",
               JAX_PLATFORMS="cpu", DSTPU_BENCH_BS="1",
               DSTPU_BENCH_SIZE="tiny", DSTPU_BENCH_SEQ="16",
               DSTPU_BENCH_STEPS="1", DSTPU_BENCH_ATTN="xla")
    proc = sp.run([sys.executable, os.path.join(repo, "bench.py"),
                   "--cpu", "--child"], capture_output=True, text=True,
                  env=env, timeout=240)
    assert proc.returncode != 0
    marker = _json.loads(proc.stdout.strip().splitlines()[-1])
    assert "ValueError" in marker["child_error"]
    assert "not-a-family" in marker["child_error"]


def test_layer_reduction_student_init():
    """Reference student_initialization (compression/compress.py:192): the
    student's stacked layers are the teacher's configured layers; the
    embeddings/head come from the teacher; bad maps raise."""
    from deepspeed_tpu.compression.compress import init_compression
    from deepspeed_tpu.models.llama import llama_config
    from deepspeed_tpu.models.transformer import init_transformer_params

    t_cfg = llama_config("tiny", max_seq_len=32)
    t_cfg.n_layers = 4
    s_cfg = llama_config("tiny", max_seq_len=32)
    s_cfg.n_layers = 2
    teacher = init_transformer_params(t_cfg, jax.random.PRNGKey(0))
    student = init_transformer_params(s_cfg, jax.random.PRNGKey(1))

    config = {"compression_training": {"layer_reduction": {
        "enabled": True, "keep_number_layer": 2, "teacher_layer": [1, 3]}}}
    out, _ = init_compression(student, config, teacher_params=teacher)

    np.testing.assert_array_equal(np.asarray(out["layers"]["attn"]["wq"]),
                                  np.asarray(teacher["layers"]["attn"]["wq"])[[1, 3]])
    np.testing.assert_array_equal(np.asarray(out["embed"]["tok"]),
                                  np.asarray(teacher["embed"]["tok"]))
    # bad layer map raises
    bad = {"compression_training": {"layer_reduction": {
        "enabled": True, "keep_number_layer": 2, "teacher_layer": [1, 9]}}}
    with pytest.raises(ValueError, match="out of range"):
        init_compression(student, bad, teacher_params=teacher)
    # wrong-depth student raises (3 layers vs keep 2)
    s3 = llama_config("tiny", max_seq_len=32)
    s3.n_layers = 3
    with pytest.raises(ValueError, match="shape mismatch"):
        init_compression(init_transformer_params(s3, jax.random.PRNGKey(2)),
                         config, teacher_params=teacher)


@pytest.mark.slow
def test_layer_reduction_student_beats_random_init():
    """A 2-layer student initialized from a trained 4-layer teacher starts
    at a lower loss than a randomly initialized 2-layer student (the point
    of the reference's student_initialization), and the KD loss against
    the teacher's logits is differentiable."""
    import deepspeed_tpu
    from deepspeed_tpu.compression.compress import (distillation_loss,
                                                    init_compression)
    from deepspeed_tpu.models.llama import llama_model

    teacher_model = llama_model("tiny", max_seq_len=32, n_layers=4)
    config = {"train_micro_batch_size_per_gpu": 8,
              "optimizer": {"type": "Adam", "params": {"lr": 5e-3}},
              "bf16": {"enabled": True}}
    engine, *_ = deepspeed_tpu.initialize(model=teacher_model, config=config)
    ids = np.random.RandomState(0).randint(0, 256, (1, 8, 32)).astype(np.int32)
    batch = {"input_ids": jnp.asarray(ids)}
    for _ in range(25):
        engine.train_batch(batch)
    teacher = jax.tree_util.tree_map(lambda x: x.astype(jnp.float32),
                                     engine.state.params)

    student_model = llama_model("tiny", max_seq_len=32, n_layers=2)
    random_student = student_model.init_params(jax.random.PRNGKey(7))
    kd_cfg = {"compression_training": {"layer_reduction": {
        "enabled": True, "keep_number_layer": 2, "teacher_layer": [0, 3]}}}
    distilled, _ = init_compression(random_student, kd_cfg,
                                    teacher_params=teacher)

    b0 = jax.tree_util.tree_map(lambda x: x[0], batch)
    l_rand = float(student_model.loss_fn(random_student, b0, None))
    l_dist = float(student_model.loss_fn(distilled, b0, None))
    assert l_dist < l_rand, (l_dist, l_rand)

    # KD loss: finite, positive, and grads vanish at logit equality
    r = np.random.RandomState(3)
    t_logits = jnp.asarray(r.randn(8, 32, 256).astype(np.float32))
    s_logits = jnp.asarray(r.randn(8, 32, 256).astype(np.float32))
    kd = distillation_loss(s_logits, t_logits, temperature=2.0)
    assert np.isfinite(float(kd)) and float(kd) > 0
    g = jax.grad(lambda s: distillation_loss(s, t_logits))(t_logits)
    assert float(jnp.max(jnp.abs(g))) < 1e-3  # cross-entropy min at s == t
