"""Automatic prefix caching tests.

Fast tier: allocator refcount/LRU/eviction invariants and the hash-chain
match — pure host logic, no model.  Slow tier: engine-level oracles —
cache-on generations must be BIT-IDENTICAL to cache-off for shared-prefix
batches, copy-on-write isolates fully-cached prompts, and a preempted
sequence's re-prefill hits the cache it populated.
"""

import jax
import numpy as np
import pytest

from deepspeed_tpu.inference.v2 import (BlockAllocator, InferenceEngineV2,
                                        PrefixCache, RaggedInferenceConfig,
                                        RaggedRequest)


# ----------------------------- fast: allocator/index invariants -------------
def test_refcount_no_free_while_referenced():
    a = BlockAllocator(4)
    (p,) = a.alloc(1)
    a.share(p)
    assert a.refcount(p) == 2
    a.free([p])  # one ref dropped: page must NOT return to the pool
    assert a.refcount(p) == 1 and a.free_pages == 3
    with pytest.raises(MemoryError):
        a.alloc(4)
    a.free([p])
    assert a.free_pages == 4
    with pytest.raises(ValueError):
        a.free([p])  # double free
    with pytest.raises(ValueError):
        a.share(p)  # unreferenced + unregistered: nothing to share


def test_lru_evicts_only_unreferenced_and_in_order():
    a = BlockAllocator(4)
    pc = PrefixCache(2, a)
    pages = a.alloc(3)
    keys = [pc.chain_key(None, [i, i]) for i in range(3)]
    for p, k in zip(pages, keys):
        a.register(p, k)
    a.free([pages[1]])  # parked first -> LRU-oldest
    a.free([pages[0]])
    # pages[2] stays referenced: never an eviction candidate
    assert a.free_pages == 3  # 1 raw free + 2 cached-unreferenced
    got = a.alloc(3)  # raw free page, then LRU order: pages[1], pages[0]
    assert a.evictions == 2
    assert pages[1] in got and pages[0] in got and pages[2] not in got
    assert a.lookup(keys[1]) is None and a.lookup(keys[0]) is None
    assert a.lookup(keys[2]) == pages[2]  # referenced page still cached


def test_share_revives_cached_page_from_lru():
    a = BlockAllocator(2)
    pc = PrefixCache(2, a)
    (p,) = a.alloc(1)
    a.register(p, pc.chain_key(None, [7, 7]))
    a.free([p])
    assert a.free_pages == 2  # cached page counts as allocatable
    a.share(p)  # re-mapped by a new sequence: leaves the LRU
    assert a.refcount(p) == 1 and a.free_pages == 1
    a.alloc(1)
    assert a.evictions == 0  # the revived page was not evicted


def test_cache_cap_trims_unreferenced_cached_pages():
    a = BlockAllocator(8, cache_pages=2)
    pc = PrefixCache(2, a)
    pages = a.alloc(4)
    for i, p in enumerate(pages):
        a.register(p, pc.chain_key(None, [i, i]))
    a.free(pages)  # all unreferenced: LRU must trim to the 2 newest
    assert a.evictions == 2 and a.cached_pages == 2
    assert a.free_pages == 8


def test_prefix_match_chain_and_counters():
    """Hash-chain match walks full pages until divergence; hit/miss/
    eviction counters are exposed and move as specified."""
    a = BlockAllocator(8)
    pc = PrefixCache(4, a)
    tokens = list(range(12))  # 3 full pages
    keys = pc.page_keys(tokens, 3)
    pages = a.alloc(3)
    for p, k in zip(pages, keys):
        a.register(p, k)

    got, gkeys = pc.match(tokens)
    assert got == pages and gkeys == keys
    # same first page, diverges in page 2
    got2, _ = pc.match(tokens[:4] + [99] * 8)
    assert got2 == pages[:1]
    # divergence INSIDE page 1: chain root differs, nothing matches
    got3, _ = pc.match([99] + tokens[1:])
    assert got3 == []
    # partial tail page never matches beyond the last full page
    got4, _ = pc.match(tokens + [1, 2])
    assert got4 == pages

    assert (pc.hits, pc.misses) == (0, 0)  # match() is pure
    pc.count(len(got), len(tokens) // 4)
    pc.count(len(got2), 3)
    assert (pc.hits, pc.misses) == (4, 1)
    a.free(pages)
    a.alloc(8)
    assert a.evictions == 3


def test_engine_exposes_cache_stats_via_monitor():
    """publish_metrics surfaces serving/* counters through any
    write_events sink (MonitorMaster-compatible)."""
    from deepspeed_tpu.models.llama import llama_model

    eng = InferenceEngineV2(
        llama_model("tiny", max_seq_len=64),
        RaggedInferenceConfig(dtype="fp32", page_size=8, num_pages=16,
                              max_seqs=2, max_pages_per_seq=8,
                              enable_prefix_cache=True))
    events = []

    class Sink:
        def write_events(self, ev):
            events.extend(ev)

    eng.publish_metrics(Sink(), step=3)
    tags = {t for t, _v, _s in events}
    for want in ("serving/cache_hits", "serving/cache_misses",
                 "serving/cache_evictions", "serving/prefix_hit_rate",
                 "serving/prefill_admitted_tokens",
                 "serving/prefill_computed_tokens"):
        assert want in tags, (want, tags)
    assert all(s == 3 for _t, _v, s in events)


# ----------------------------- slow: engine oracles -------------------------
@pytest.fixture(scope="module")
def tiny_model():
    from deepspeed_tpu.models.llama import llama_model

    model = llama_model("tiny", max_seq_len=256)
    return model, model.init_params(jax.random.PRNGKey(0))


def _engine(model, params, **kw):
    return InferenceEngineV2(model, RaggedInferenceConfig(
        dtype="fp32", page_size=8, num_pages=64, max_seqs=2,
        max_pages_per_seq=10, **kw), params=params)


@pytest.mark.slow
@pytest.mark.parametrize("extra", [{}, {"prefill_chunk": 16}])
def test_shared_prefix_bit_exact_and_counted(tiny_model, extra):
    """Shared-prefix batch: cache-on generations equal cache-off
    token-for-token; hit/computed counters reflect the reuse."""
    model, params = tiny_model
    rng = np.random.RandomState(2)
    prefix = list(rng.randint(0, model.config.vocab_size, 24))
    prompts = [prefix + list(rng.randint(0, model.config.vocab_size, n))
               for n in (13, 5, 28)]
    reqs = lambda: [RaggedRequest(prompt_ids=p, max_new_tokens=6)  # noqa: E731
                    for p in prompts]

    want = _engine(model, params, **extra).generate_all(reqs())
    eng = _engine(model, params, enable_prefix_cache=True, **extra)
    got = eng.generate_all(reqs())
    assert got == want, (got, want)
    st = eng.cache_stats()
    assert st["cache_hits"] > 0 and st["prefix_hit_tokens"] >= 24
    assert st["prefill_computed_tokens"] < st["prefill_admitted_tokens"]
    assert st["prefix_hit_rate"] > 0


@pytest.mark.slow
@pytest.mark.parametrize("extra", [{}, {"prefill_chunk": 8}])
def test_full_prompt_cached_copy_on_write(tiny_model, extra):
    """A page-aligned prompt whose every page is cached enters through
    the decode program with its last page COPY-ON-WRITTEN: the cached
    page is never mutated, the sharer gets a private copy, and the
    generation equals the cache-off run exactly — whole-prompt AND
    chunked prefill (decode_entry must stay out of the pending list)."""
    model, params = tiny_model
    rng = np.random.RandomState(7)
    prompt = list(rng.randint(0, model.config.vocab_size, 16))  # 2 pages

    want = _engine(model, params, **extra).generate_all(
        [RaggedRequest(prompt_ids=prompt, max_new_tokens=5)])
    eng = _engine(model, params, enable_prefix_cache=True, **extra)
    first = eng.generate_all([RaggedRequest(prompt_ids=prompt,
                                            max_new_tokens=5)])
    assert list(first.values())[0] == list(want.values())[0]

    # second identical prompt: full hit -> decode-entry + CoW
    keys = eng.prefix_cache.page_keys(prompt, 2)
    src = eng.allocator.lookup(keys[1])
    assert src is not None
    eng.put(RaggedRequest(prompt_ids=prompt, max_new_tokens=5))
    out = eng.step()  # admission + first decode step in one engine step
    seq = next(s for s in eng._slots if s is not None)
    assert seq.decode_entry
    assert seq.pages[0] == eng.allocator.lookup(keys[0])  # shared directly
    assert seq.pages[1] != src  # private CoW copy, shared page untouched
    assert eng.allocator.lookup(keys[1]) == src
    toks = list(out.values())[0]["tokens"]
    while eng.has_work():
        for _u, rec in eng.step().items():
            toks.extend(rec["tokens"])
    assert toks == list(want.values())[0]
    st = eng.cache_stats()
    assert st["prefix_hit_tokens"] >= 15  # length-1 of the second request


@pytest.mark.slow
def test_preempt_readmit_hits_cache(tiny_model):
    """A preempted sequence's re-prefill must hit the pages it populated
    before eviction — recompute becomes a table lookup."""
    model, params = tiny_model
    rng = np.random.RandomState(3)
    prompt = list(rng.randint(0, model.config.vocab_size, 28))

    eng = _engine(model, params, enable_prefix_cache=True)
    uid = eng.put(RaggedRequest(prompt_ids=prompt, max_new_tokens=10))
    got = []
    for _ in range(3):
        for u, rec in eng.step().items():
            if u == uid:
                got.extend(rec["tokens"])
    seq = next(s for s in eng._slots if s is not None)
    eng._preempt(seq)  # KV-pressure relief, mid-generation
    eng.reset_cache_stats()
    while eng.has_work():
        for _u, rec in eng.step().items():
            got.extend(rec["tokens"])
    st = eng.cache_stats()
    assert st["cache_hits"] >= 3, st  # 28-token prompt = 3 full pages
    assert st["prefix_hit_tokens"] >= 24
    want = _engine(model, params).generate_all(
        [RaggedRequest(prompt_ids=prompt, max_new_tokens=10)])
    assert got == list(want.values())[0]


@pytest.mark.slow
def test_cache_under_pool_pressure_stays_exact(tiny_model):
    """Tight pool + caching: LRU eviction of unreferenced cached pages
    keeps admission/growth alive and generations exact (referenced pages
    are never stolen)."""
    model, params = tiny_model
    rng = np.random.RandomState(4)
    prompts = [list(rng.randint(0, model.config.vocab_size, 28))
               for _ in range(2)]
    reqs = lambda: [RaggedRequest(prompt_ids=p, max_new_tokens=10)  # noqa: E731
                    for p in prompts]

    want = InferenceEngineV2(model, RaggedInferenceConfig(
        dtype="fp32", page_size=8, num_pages=8, max_seqs=2,
        max_pages_per_seq=8), params=params).generate_all(reqs())
    eng = InferenceEngineV2(model, RaggedInferenceConfig(
        dtype="fp32", page_size=8, num_pages=8, max_seqs=2,
        max_pages_per_seq=8, enable_prefix_cache=True), params=params)
    got = eng.generate_all(reqs())
    assert got == want, (got, want)
    assert eng.allocator.free_pages == 8  # everything returned or parked
