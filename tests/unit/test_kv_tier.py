"""Tiered KV cache tests (serving/kv_tier.py + the engine wiring).

Fast tier: the host LRU's byte budget / eviction order / CRC refusal,
the allocator's spill-pin machinery (capture on eviction, pin-until-
commit, slack accounting, invariant audit), and the prefix-cache host
consult — pure host logic, no model.

Slow tier: engine-level oracles — a device prefix cache capped BELOW
the distinct-prefix working set plus the host tier must reproduce an
UNCAPPED engine's streams bit-identically, across plain prefix caching,
chunked prefill, speculative decoding, kv_quant pools (spill in pool
dtype), and preemption; restore-prefetch stages pages for queued
requests; a corrupt host page refuses loudly and costs only recompute.
"""

import dataclasses

import numpy as np
import pytest

from deepspeed_tpu.inference.v2 import (BlockAllocator, InferenceEngineV2,
                                        PrefixCache, RaggedInferenceConfig,
                                        RaggedRequest)
from deepspeed_tpu.serving.config import KVTierConfig, ServingConfig
from deepspeed_tpu.serving.kv_tier import HostKVTier, batch_page_crcs

PS = 8  # page size for the engine oracles


def _page(v, nbytes=256):
    """A fake gathered page: one leaf, [L=1, 1, ...] float32."""
    return {"k": np.full((1, 1, nbytes // 4), float(v), np.float32)}


def _put(tier, key, v, nbytes=256):
    arrays = _page(v, nbytes)
    return tier.insert(key, arrays, batch_page_crcs(arrays)[0])


# ----------------------------- fast: host LRU -------------------------------
def test_host_lru_byte_budget_and_eviction_order():
    tier = HostKVTier(KVTierConfig(enabled=True, host_bytes=3 * 256))
    for i in range(3):
        assert _put(tier, f"k{i}".encode(), i)
    assert tier.host_pages == 3 and tier.host_bytes == 3 * 256
    _put(tier, b"k3", 3)  # over budget: k0 (oldest) evicted
    assert tier.host_pages == 3 and not tier.has(b"k0") and tier.has(b"k3")
    assert tier.host_evictions == 1
    # a hit refreshes recency: k1 touched, so k2 is next to go
    assert tier.get(b"k1") is not None
    _put(tier, b"k4", 4)
    assert tier.has(b"k1") and not tier.has(b"k2")


def test_host_lru_restore_is_bit_identical_and_reput_replaces():
    tier = HostKVTier(KVTierConfig(enabled=True, host_bytes=1 << 20))
    arrays = _page(7)
    tier.insert(b"a", arrays, batch_page_crcs(arrays)[0])
    got = tier.get(b"a")
    np.testing.assert_array_equal(got["k"], arrays["k"])
    # re-put under the same key replaces without double-counting bytes
    arrays2 = _page(9)
    tier.insert(b"a", arrays2, batch_page_crcs(arrays2)[0])
    assert tier.host_pages == 1 and tier.host_bytes == arrays2["k"].nbytes
    np.testing.assert_array_equal(tier.get(b"a")["k"], arrays2["k"])


def test_crc_refusal_drops_entry_loudly():
    tier = HostKVTier(KVTierConfig(enabled=True, host_bytes=1 << 20))
    _put(tier, b"good", 1)
    _put(tier, b"bad", 2)
    # simulate a host-RAM bit flip inside the stored page
    tier._lru[b"bad"][0]["k"].view(np.uint8).reshape(-1)[3] ^= 0x40
    assert tier.get(b"bad") is None          # refused, not garbage
    assert not tier.has(b"bad")              # entry dropped
    assert tier.corrupt_pages == 1
    assert tier.get(b"good") is not None     # neighbors untouched


def test_oversized_page_refused():
    tier = HostKVTier(KVTierConfig(enabled=True, host_bytes=100))
    assert not _put(tier, b"big", 1, nbytes=256)
    assert tier.host_pages == 0 and tier.dropped_spills == 1


def test_config_validation():
    with pytest.raises(ValueError):
        KVTierConfig(enabled=True, host_bytes=-1).validate()
    with pytest.raises(ValueError):
        KVTierConfig(enabled=True, spill_inflight=0).validate()
    with pytest.raises(ValueError):
        KVTierConfig(enabled=True, prefetch_requests=-1).validate()
    # ds-config dict coercion through the serving block
    sc = ServingConfig.from_dict(
        {"kv_tier": {"enabled": True, "host_bytes": 1024}})
    assert isinstance(sc.kv_tier, KVTierConfig)
    assert sc.kv_tier.host_bytes == 1024
    with pytest.raises(ValueError):
        ServingConfig.from_dict({"kv_tier": {"enabled": True,
                                             "spill_inflight": 0}})


# ----------------------------- fast: allocator spill pins -------------------
def test_spill_hook_pins_until_release():
    a = BlockAllocator(4)
    pc = PrefixCache(2, a)
    captured = []
    a.spill_hook = lambda page, key: captured.append((page, key)) or True
    pages = a.alloc(2)
    keys = [pc.chain_key(None, [i, i]) for i in range(2)]
    for p, k in zip(pages, keys):
        a.register(p, k)
    a.free(pages)  # both park in the LRU
    assert a.free_pages == 4
    got = a.alloc(3)  # 2 truly free + 1 eviction; hook captures evictees
    # the hook captured LRU pages until slack ran out (slack = 4-3 = 1):
    # exactly one capture, then the next evictee was handed out
    assert len(captured) == 1 and captured[0][1] == keys[0]
    pinned = captured[0][0]
    assert a.spill_pinned_pages == 1 and pinned not in got
    # pinned page is allocatable by NOBODY until the commit lands
    assert a.free_pages == 0
    with pytest.raises(MemoryError):
        a.alloc(1)
    a.check_invariants()          # pins are a legal partition state
    a.assert_no_leaks([got])      # exact audit accounts the pin
    a.release_spill_pin(pinned)   # D2H commit landed
    assert a.free_pages == 1 and a.spill_pinned_pages == 0
    assert a.alloc(1) == [pinned]
    with pytest.raises(ValueError):
        a.release_spill_pin(pinned)  # double release


def test_alloc_slack_never_starves_allocation():
    """With zero headroom beyond the request, the hook is never offered
    a page: the allocation itself always wins."""
    a = BlockAllocator(2)
    pc = PrefixCache(2, a)
    a.spill_hook = lambda page, key: True  # greedy: captures anything
    pages = a.alloc(2)
    for i, p in enumerate(pages):
        a.register(p, pc.chain_key(None, [i]))
    a.free(pages)
    got = a.alloc(2)  # needs everything: no slack, no captures
    assert sorted(got) == sorted(pages) and a.spill_pinned_pages == 0


def test_trim_capture_does_not_over_evict():
    """Cap-trim with a capturing hook removes exactly the overage: a
    captured page must not trigger an extra eviction of content still
    within the cap."""
    a = BlockAllocator(8, cache_pages=2)
    pc = PrefixCache(2, a)
    pages = a.alloc(3)
    for i, p in enumerate(pages):
        a.register(p, pc.chain_key(None, [i]))
    a.spill_hook = lambda page, key: True
    a.free(pages)  # parks 3, cap 2: ONE eviction, captured
    assert a.lru_pages == 2 and a.spill_pinned_pages == 1
    assert a.cached_pages == 2  # the two in-cap pages stay registered
    a.check_invariants()


def test_invariants_flag_spill_pin_corruption():
    a = BlockAllocator(4, cache_pages=1)
    pc = PrefixCache(2, a)
    a.spill_hook = lambda page, key: True
    pages = a.alloc(2)
    for i, p in enumerate(pages):
        a.register(p, pc.chain_key(None, [i]))
    a.free(pages)  # cap 1 -> one eviction, captured + pinned
    assert a.spill_pinned_pages == 1
    a.assert_no_leaks([])  # pin accounted, no live owners
    # a pin whose refcount was lost is a use-after-free in waiting
    (pin,) = a._spill_pinned
    a._ref[pin] = 0
    with pytest.raises(AssertionError):
        a.check_invariants()


def test_prefix_match_consults_host_tier():
    a = BlockAllocator(8)
    pc = PrefixCache(2, a)
    tokens = [1, 2, 3, 4, 5, 6, 7, 8]  # 4 full pages
    keys = pc.page_keys(tokens, 4)
    # device holds page 0; host holds pages 1 and 3 (not 2)
    (p0,) = a.alloc(1)
    a.register(p0, keys[0])

    class FakeTier:
        def has(self, k):
            return k in (keys[1], keys[3])

    pages, got_keys, host_keys = pc.match(tokens, host_tier=FakeTier())
    assert pages == [p0] and got_keys == [keys[0]]
    # host extension is CONSECUTIVE: page 1 hits, page 2 misses, page 3
    # is unreachable past the gap
    assert host_keys == [keys[1]]
    # without a tier the 2-tuple contract is unchanged
    assert pc.match(tokens) == ([p0], [keys[0]])


# ----------------------------- fast: NVMe third tier ------------------------
def _nvme_cfg(tmp_path, nvme_bytes=16 << 30, host_bytes=1 << 30):
    return KVTierConfig(enabled=True, host_bytes=host_bytes,
                        nvme_enabled=True, nvme_dir=str(tmp_path),
                        nvme_bytes=nvme_bytes)


def test_nvme_lru_budget_eviction_and_bit_identical_promote(tmp_path):
    from deepspeed_tpu.serving.kv_tier import NVMeKVTier

    # measure one record's on-disk size, then budget for exactly three
    tier = NVMeKVTier(_nvme_cfg(tmp_path))
    assert tier.put(b"k0", _page(0))
    rec = tier.nvme_bytes
    tier.pop(b"k0")
    # (records differ by a few header bytes — CRC digit counts — so
    # budget three records with slack, not an exact multiple)
    tier = NVMeKVTier(_nvme_cfg(tmp_path, nvme_bytes=3 * rec + 64))
    for i in range(3):
        assert tier.put(f"k{i}".encode(), _page(i))
    assert tier.nvme_pages == 3
    assert tier.put(b"k3", _page(3))  # over budget: k0 unlinked
    assert tier.nvme_pages == 3 and not tier.has(b"k0")
    assert tier.evicted_pages == 1
    # files on disk are exactly the LRU's view, DSTPUKV2 records
    files = [f for f in __import__("os").listdir(tier.dir)
             if f.endswith(".kvpage")]
    assert len(files) == 3
    # promote is bit-identical and refreshes recency
    got = tier.get(b"k1")
    assert got is not None and np.array_equal(got["k"], _page(1)["k"])
    assert got["k"].dtype == np.float32
    tier.put(b"k4", _page(4))  # k2 (not the refreshed k1) goes
    assert tier.has(b"k1") and not tier.has(b"k2")
    # a miss is counted; pop drops the entry AND the file
    assert tier.get(b"nope") is None and tier.misses == 1
    tier.pop(b"k1")
    assert not tier.has(b"k1")


def test_nvme_corrupt_file_refused_loudly_and_unlinked(tmp_path):
    import os

    from deepspeed_tpu.serving.kv_tier import NVMeKVTier

    tier = NVMeKVTier(_nvme_cfg(tmp_path))
    assert tier.put(b"\x05" * 8, _page(5))
    path, _nb = tier._lru[b"\x05" * 8]
    blob = bytearray(open(path, "rb").read())
    blob[-3] ^= 0xFF  # bit-flip in the leaf bytes
    with open(path, "wb") as f:
        f.write(bytes(blob))
    assert tier.get(b"\x05" * 8) is None  # refused, not wrong data
    assert tier.corrupt_pages == 1 and not os.path.exists(path)
    assert not tier.has(b"\x05" * 8)  # dropped: the walk recomputes
    # truncated file (torn write that dodged the atomic rename) too
    assert tier.put(b"\x06" * 8, _page(6))
    path, _nb = tier._lru[b"\x06" * 8]
    blob = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(blob[:len(blob) // 2])
    assert tier.get(b"\x06" * 8) is None
    assert tier.corrupt_pages == 2


def test_host_tier_demotes_to_nvme_and_promotes_back(tmp_path):
    """The integration contract: host-LRU eviction demotes to a file
    instead of dropping; a host miss consults the files and promotes
    the page back up-tier, bit-identical, moving ownership."""
    tier = HostKVTier(_nvme_cfg(tmp_path, host_bytes=3 * 256))
    for i in range(3):
        assert _put(tier, f"k{i}".encode(), i)
    _put(tier, b"k3", 3)  # host over budget: k0 demotes to NVMe
    assert tier.host_evictions == 1
    assert tier.nvme.nvme_pages == 1 and tier.nvme.has(b"k0")
    assert tier.has(b"k0")  # membership spans both tiers
    got = tier.get(b"k0")  # host miss -> file read -> promote
    assert got is not None and np.array_equal(got["k"], _page(0)["k"])
    assert tier.nvme.restored_pages == 1
    assert not tier.nvme.has(b"k0")  # ownership moved up-tier
    assert b"k0" in tier._lru  # ...and the promote itself demoted the
    assert tier.nvme.has(b"k1")  # then-oldest host page, never k0
    st = tier.stats()
    assert st["nvme_spilled_pages"] == 2 and st["nvme_restored_pages"] == 1
    assert st["nvme_hit_rate"] == 1.0


def test_nvme_bundle_spill_restore_rebases_deadline(tmp_path):
    """Satellite fix: a restored bundle's ``deadline_left_s`` passes
    through the SAME transit clamp as the wire import — time spent
    spilled consumes the budget, and skew-negative transit (a restore
    clock behind the spill clock) clamps to zero consumption rather
    than GRANTING deadline."""
    import json
    import time

    from deepspeed_tpu.inference.v2 import KVPageBundle
    from deepspeed_tpu.serving.kv_tier import NVMeKVTier
    from deepspeed_tpu.serving.kv_transfer import (_MAGIC,
                                                   rebase_deadline_left)

    tier = NVMeKVTier(_nvme_cfg(tmp_path))
    arrays = {"k": np.arange(32, dtype=np.float32).reshape(1, 1, 8, 2, 2)}
    b = KVPageBundle(uid=9, tokens=list(range(10)), prompt_len=9,
                     max_new_tokens=4, temperature=0.0, eos_id=None,
                     prefilled=9, decode_entry=False, page_size=8,
                     page_keys=[b"\x09" * 32],
                     src_pages=[{"page": 1, "refcount": 1, "key": None}],
                     arrays=arrays, model_sig=(1, 2, 2), kv_quant=False,
                     dtype="fp32", deadline=time.perf_counter() + 10.0)
    path = tier.spill_bundle(b)
    # doctor the spilled record's sent_unix to simulate 4s on disk
    raw = open(path, "rb").read()
    hlen = int.from_bytes(raw[len(_MAGIC):len(_MAGIC) + 8], "little")
    hdr = json.loads(raw[len(_MAGIC) + 8:len(_MAGIC) + 8 + hlen].decode())
    assert 9.5 < hdr["deadline_left_s"] <= 10.0
    hdr["sent_unix"] = time.time() - 4.0
    enc = json.dumps(hdr).encode()
    with open(path, "wb") as f:
        f.write(_MAGIC + len(enc).to_bytes(8, "little") + enc
                + raw[len(_MAGIC) + 8 + hlen:])
    rt = tier.restore_bundle(path)
    left = rt.deadline - time.perf_counter()
    assert 5.0 < left < 6.5  # ~10s budget minus ~4s spilled
    assert np.array_equal(rt.arrays["k"], arrays["k"])  # bit identical
    # REGRESSION (skew-negative): sent_unix in the FUTURE must clamp
    # transit to zero — never increase the budget
    hdr["sent_unix"] = time.time() + 3600.0
    enc = json.dumps(hdr).encode()
    with open(path, "wb") as f:
        f.write(_MAGIC + len(enc).to_bytes(8, "little") + enc
                + raw[len(_MAGIC) + 8 + hlen:])
    rt = tier.restore_bundle(path)
    assert rt.deadline - time.perf_counter() <= 10.01
    # and the clamp itself floors at zero, never negative
    assert rebase_deadline_left(1.0, time.time() - 50.0) == 0.0
    assert rebase_deadline_left(5.0, time.time() + 50.0) == 5.0
    assert rebase_deadline_left(None, time.time()) is None


def test_nvme_config_validation():
    with pytest.raises(ValueError):
        ServingConfig(kv_tier=KVTierConfig(
            enabled=True, nvme_enabled=True, nvme_bytes=-1)).validate()
    # dict-coercion carries the nvme knobs through
    sc = ServingConfig(kv_tier={"enabled": True, "nvme_enabled": True,
                                "nvme_bytes": 1 << 20})
    sc.validate()
    assert sc.kv_tier.nvme_bytes == 1 << 20


# ----------------------------- slow: engine oracles -------------------------
def _tiny(max_seq_len=128):
    import jax

    from deepspeed_tpu.models.llama import llama_model

    model = llama_model("tiny", max_seq_len=max_seq_len)
    return model, model.init_params(jax.random.PRNGKey(0))


def _engine(model, params, cap=3, tier=True, num_pages=48, max_seqs=4,
            **kw):
    cfg = RaggedInferenceConfig(
        dtype=kw.pop("dtype", "fp32"), page_size=PS, num_pages=num_pages,
        max_seqs=max_seqs, max_pages_per_seq=12, enable_prefix_cache=True,
        prefix_cache_pages=cap,
        kv_tier=(KVTierConfig(enabled=True) if tier else None), **kw)
    return InferenceEngineV2(model, cfg, params=params)


def _family_waves(vocab, n_fams=3, per_fam=2, rounds=2, gen=6, seed=11):
    """Distinct-prefix family waves: families cycle so a capped cache
    must evict (spill) each family before it returns (restore)."""
    rng = np.random.RandomState(seed)
    fams = [list(rng.randint(0, vocab, 2 * PS)) for _ in range(n_fams)]
    waves = []
    for _ in range(rounds):
        for f in fams:
            waves.append([RaggedRequest(
                prompt_ids=f + list(rng.randint(0, vocab, 3 + i)),
                max_new_tokens=gen) for i in range(per_fam)])
    return waves


def _play(eng, waves):
    out = []
    for wave in waves:
        got = eng.generate_all([dataclasses.replace(r) for r in wave])
        out.append([got[u] for u in sorted(got)])
    return out


@pytest.mark.slow
@pytest.mark.parametrize("variant", ["plain", "chunked", "speculative",
                                     "kv_quant"])
def test_tier_bit_exact_vs_uncapped(variant):
    """The headline contract: a capped device cache + host tier streams
    bit-identically to an UNCAPPED engine (never-evicted), across the
    serving feature matrix.  ``kv_quant`` is the spill-in-pool-dtype
    parity proof: int8 codes + scales spill and restore bit-identical
    to pages that never left the device."""
    from deepspeed_tpu.inference.v2 import SpeculativeConfig

    kw = {}
    if variant == "chunked":
        kw["prefill_chunk"] = PS
    elif variant == "speculative":
        kw["speculative"] = SpeculativeConfig(mode="ngram", k=4)
    elif variant == "kv_quant":
        kw["kv_quant"] = True
    model, params = _tiny()
    waves = _family_waves(model.config.vocab_size)
    ctl = _engine(model, params, cap=0, tier=False, num_pages=64, **kw)
    want = _play(ctl, waves)
    ctl.close()
    eng = _engine(model, params, cap=3, tier=True, **kw)
    got = _play(eng, waves)
    ts = eng.tier_stats()
    assert got == want, f"{variant}: tiered streams diverged"
    assert ts["spilled_pages"] > 0 and ts["restored_pages"] > 0, \
        f"{variant}: the tier never engaged ({ts})"
    assert ts["corrupt_pages"] == 0
    eng.assert_no_leaks()
    eng.close()


@pytest.mark.slow
def test_tier_bit_exact_under_preemption():
    """A pool tight enough to preempt running sequences composes with
    the tier: preempted prefixes re-admit through the cache/tier and
    streams stay bit-identical to a roomy uncapped control."""
    model, params = _tiny()
    waves = _family_waves(model.config.vocab_size, n_fams=2, per_fam=3,
                          gen=10)
    ctl = _engine(model, params, cap=0, tier=False, num_pages=64)
    want = _play(ctl, waves)
    ctl.close()
    # 18 pages: 3 concurrent sequences x ~5 pages + cache pressure
    eng = _engine(model, params, cap=2, tier=True, num_pages=18,
                  max_seqs=3)
    got = _play(eng, waves)
    assert got == want
    eng.assert_no_leaks()
    eng.close()


def _junk_wave(eng, vocab, salt=77, gen=4):
    """Push earlier families out of a CAPPED LRU: a junk family's wave
    parks its pages on retire, the cap trims the oldest — which the
    spill hook captures (pinned, pending the next drain)."""
    rng = np.random.RandomState(salt)
    junk = list(rng.randint(0, vocab, 2 * PS))
    eng.generate_all([RaggedRequest(prompt_ids=junk, max_new_tokens=gen)])


@pytest.mark.slow
def test_pin_until_commit_under_slow_drain():
    """The async-spill window: between eviction and the step-boundary
    drain (the 'slow copy'), captured pages stay ref-pinned — not
    allocatable, not yet in the host tier, and the exact allocator
    audit stays green.  The commit (flush) moves them host-side and
    returns the pages."""
    model, params = _tiny()
    rng = np.random.RandomState(3)
    vocab = model.config.vocab_size
    fam = list(rng.randint(0, vocab, 2 * PS))
    eng = _engine(model, params, cap=2, tier=True, num_pages=32,
                  max_seqs=2)
    eng.generate_all([RaggedRequest(prompt_ids=fam, max_new_tokens=4)])
    _junk_wave(eng, vocab)  # trims fam's pages out: captured, pending
    assert eng.allocator.spill_pinned_pages == 2
    pinned = set(eng.allocator._spill_pinned)
    assert eng.kv_tier.host_pages == 0          # D2H not committed yet
    # pinned pages are allocatable by nobody until the commit lands
    free0 = eng.allocator.free_pages
    grabbed = eng.allocator.alloc(free0)
    assert not (pinned & set(grabbed))
    eng.allocator.free(grabbed)
    eng.assert_no_leaks()                       # pins accounted exactly
    eng.flush_spills()                          # the commit lands
    assert eng.kv_tier.host_pages == len(pinned)
    assert eng.allocator.spill_pinned_pages == 0
    assert eng.allocator.free_pages == free0 + len(pinned)
    eng.assert_no_leaks()
    eng.close()


@pytest.mark.slow
def test_restore_prefetch_for_queued_request():
    """While an admitted batch decodes, the queue head's host-held
    prefix is prefetched back into the device pool (registered +
    LRU-parked), so its admission is a pure device hit — and the output
    is bit-identical to an uncapped control."""
    model, params = _tiny()
    rng = np.random.RandomState(5)
    vocab = model.config.vocab_size
    fam = list(rng.randint(0, vocab, 2 * PS))
    queued_req = RaggedRequest(
        prompt_ids=fam + list(rng.randint(0, vocab, 3)), max_new_tokens=4)
    long_req = RaggedRequest(
        prompt_ids=list(rng.randint(0, vocab, 12)), max_new_tokens=24)
    ctl = _engine(model, params, cap=0, tier=False, num_pages=64)
    want = ctl.generate_all([dataclasses.replace(queued_req)])[0]
    ctl.close()

    eng = _engine(model, params, cap=2, tier=True, num_pages=32,
                  max_seqs=1)  # ONE slot: the second request queues
    eng.generate_all([RaggedRequest(prompt_ids=fam, max_new_tokens=4)])
    _junk_wave(eng, vocab)  # fam evicted + captured
    eng.flush_spills()      # ...and committed host-side
    assert eng.kv_tier.host_pages >= 2
    keys = eng.prefix_cache.page_keys(fam, 2)
    assert all(eng.allocator.lookup(k) is None for k in keys)  # device-cold
    # give the prefetch LRU-cap headroom for the restore-ahead phase
    eng.allocator.cache_cap = 8

    u_q = None
    eng.put(long_req)
    u_q = eng.put(queued_req)
    prefetched_while_queued = False
    got = {}
    for _ in range(300):
        for uid, rec in eng.step().items():
            got.setdefault(uid, []).extend(rec["tokens"])
        if (any(s.uid == u_q for s in eng._queue)
                and all(eng.allocator.lookup(k) is not None
                        for k in keys)):
            prefetched_while_queued = True
        if not eng.has_work():
            break
    assert prefetched_while_queued, \
        "queue-head prefix was never staged back while waiting"
    assert eng.kv_tier.restored_pages >= 2
    assert got[u_q] == want  # bit-identical through the prefetch path
    eng.assert_no_leaks()
    eng.close()


@pytest.mark.slow
def test_corrupt_host_page_refused_costs_only_recompute():
    """A bit-flipped host page refuses restore LOUDLY; the request
    recomputes its suffix and the stream is STILL bit-identical — the
    device loses nothing on refusal."""
    model, params = _tiny()
    rng = np.random.RandomState(9)
    vocab = model.config.vocab_size
    fam = list(rng.randint(0, vocab, 2 * PS))
    req = RaggedRequest(prompt_ids=fam + [1, 2, 3], max_new_tokens=6)
    ctl = _engine(model, params, cap=0, tier=False, num_pages=64)
    want = ctl.generate_all([dataclasses.replace(req)])[0]
    ctl.close()

    eng = _engine(model, params, cap=2, tier=True, num_pages=32,
                  max_seqs=2)
    eng.generate_all([RaggedRequest(prompt_ids=fam, max_new_tokens=4)])
    _junk_wave(eng, vocab)
    eng.flush_spills()
    # flip one byte inside the family's FIRST spilled page
    keys = eng.prefix_cache.page_keys(fam, 2)
    assert eng.kv_tier.has(keys[0])
    arrays0 = eng.kv_tier._lru[keys[0]][0]
    next(iter(arrays0.values())).view(np.uint8).reshape(-1)[5] ^= 0x10
    out = eng.generate_all([dataclasses.replace(req)])
    got = out[max(out)]  # uids keep counting on a reused engine
    assert got == want
    assert eng.kv_tier.corrupt_pages >= 1
    assert not eng.kv_tier.has(keys[0])  # refused entry dropped
    eng.assert_no_leaks()
    eng.close()


@pytest.mark.slow
def test_restore_alloc_never_evicts_matched_pages():
    """Regression: with the free list EMPTY and the request's device-
    matched prefix pages sitting LRU-parked, the restore's own alloc
    must not evict them (that would alias two prefix positions onto
    one physical page).  The admission claims the matches first; when
    nothing is left to allocate from it blocks instead of corrupting,
    and admits bit-identically once pages free up."""
    model, params = _tiny()
    rng = np.random.RandomState(21)
    vocab = model.config.vocab_size
    fam = list(rng.randint(0, vocab, 3 * PS))  # 3 full prefix pages
    req = RaggedRequest(prompt_ids=fam + [5, 6, 7], max_new_tokens=4)
    ctl = _engine(model, params, cap=0, tier=False, num_pages=64)
    want = ctl.generate_all([dataclasses.replace(req)])[0]
    ctl.close()

    eng = _engine(model, params, cap=2, tier=True, num_pages=24,
                  max_seqs=1)
    # warm: fam's 3 pages registered, then pushed out wholesale (junk
    # wave + cap 2) and committed host-side
    eng.generate_all([RaggedRequest(prompt_ids=fam, max_new_tokens=2)])
    _junk_wave(eng, vocab)
    eng.flush_spills()
    # restore the chain HEAD back to the device: a 2-page-prefix
    # request re-admits pages 0-1 (host hit), retires, parks them
    eng.generate_all([RaggedRequest(prompt_ids=fam[:2 * PS] + [9, 9],
                                    max_new_tokens=2)])
    eng.flush_spills()
    keys = eng.prefix_cache.page_keys(fam, 3)
    dev = [eng.allocator.lookup(k) for k in keys]
    host = [eng.kv_tier.has(k) for k in keys]
    # the finding's shape: device-matched head + host-held continuation
    assert dev[0] is not None and dev[1] is not None, (dev, host)
    assert dev[2] is None and host[2], (dev, host)
    # drain the free list completely (hold every truly-free page)
    held = eng.allocator.alloc(len(eng.allocator._free))
    assert not eng.allocator._free
    uid = eng.put(dataclasses.replace(req))
    out = dict(eng.step())  # admission must block or admit — not alias
    for s in list(eng._slots):
        if s is not None:
            assert len(set(s.pages)) == len(s.pages), \
                f"aliased page table: {s.pages}"
    eng.allocator.free(held)  # capacity returns
    got = {uid: []}
    for uid_, rec in out.items():
        got.setdefault(uid_, []).extend(rec.get("tokens", []))
    while eng.has_work():
        for uid_, rec in eng.step().items():
            got.setdefault(uid_, []).extend(rec["tokens"])
        for s in list(eng._slots):
            if s is not None:
                assert len(set(s.pages)) == len(s.pages), \
                    f"aliased page table: {s.pages}"
    assert got[uid] == want
    eng.assert_no_leaks()
    eng.close()


@pytest.mark.slow
def test_close_releases_pending_spill_pins():
    model, params = _tiny()
    rng = np.random.RandomState(13)
    vocab = model.config.vocab_size
    fam = list(rng.randint(0, vocab, 2 * PS))
    eng = _engine(model, params, cap=2, tier=True, num_pages=32,
                  max_seqs=2)
    eng.generate_all([RaggedRequest(prompt_ids=fam, max_new_tokens=4)])
    _junk_wave(eng, vocab)
    assert eng.allocator.spill_pinned_pages > 0
    # leave a request MID-FLIGHT: close()'s abort_all frees its pages,
    # which parks + cap-trims — the detached hook must not pin anew
    eng.put(RaggedRequest(prompt_ids=list(rng.randint(0, vocab, 2 * PS)),
                          max_new_tokens=16))
    for _ in range(3):
        eng.step()
    eng.close()  # releases pins WITHOUT committing (tier dies too)
    assert eng.allocator.spill_pinned_pages == 0
    eng.allocator.assert_no_leaks([])
