"""REAL multi-process distributed tests (reference tests/unit/common.py
DistributedTest spawns worker processes with a file-store rendezvous).

Everything else in this suite simulates multi-host as one process with 8
virtual devices; these tests spawn TWO actual processes that rendezvous
through ``comm.init_distributed``'s launcher env contract
(DSTPU_COORDINATOR/NUM_PROCESSES/PROCESS_ID) and exercise the code that
only runs when ``jax.process_count() > 1``:

  * cross-process collectives through the engine (data-parallel training
    step over a 2-process mesh, loss identical on both ranks);
  * ``monitored_barrier``'s coordination-service path against the REAL
    distributed client (wait_at_barrier or KV fallback);
  * the multi-host partitioned checkpoint writer (per-process shard files
    + load back).
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

WORKER = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")

    import numpy as np
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.comm import comm

    comm.init_distributed()  # env contract: DSTPU_COORDINATOR/.../PROCESS_ID
    assert jax.process_count() == 2, jax.process_count()
    rank = jax.process_index()

    # REAL coordination-service barrier (single-process tests can't reach it)
    comm.monitored_barrier("mp-entry", timeout_s=60.0)

    from tests.unit.simple_model import random_batch, simple_mlp_spec

    engine, *_ = deepspeed_tpu.initialize(
        model=simple_mlp_spec(),
        config={"train_micro_batch_size_per_gpu": 8,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                "zero_optimization": {"stage": int(os.environ["T_STAGE"])},
                "mesh": {"data": 2}})
    losses = []
    fixed = random_batch(batch_size=16, seed=0, gas=1)
    for i in range(10):
        losses.append(float(engine.train_batch(fixed)))
    assert losses[-1] < losses[0], losses
    # data-parallel math: both ranks must see the IDENTICAL loss
    print(f"RANK{rank} LOSSES {' '.join(f'{l:.6f}' for l in losses)}",
          flush=True)

    # multi-host partitioned checkpoint (jax.process_count() > 1 path)
    ckpt = os.environ["T_CKPT"]
    engine.save_checkpoint(ckpt, "mp")  # partitioned=None -> multi-host auto
    comm.monitored_barrier("mp-saved", timeout_s=60.0)
    engine2, *_ = deepspeed_tpu.initialize(
        model=simple_mlp_spec(),
        config={"train_micro_batch_size_per_gpu": 8,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                "zero_optimization": {"stage": int(os.environ["T_STAGE"])},
                "mesh": {"data": 2}})
    engine2.load_checkpoint(ckpt, "mp")
    for a, b in zip(jax.tree_util.tree_leaves(engine.state.params),
                    jax.tree_util.tree_leaves(engine2.state.params)):
        # multi-host arrays: only this process's shards are addressable
        for sa, sb in zip(a.addressable_shards, b.addressable_shards):
            np.testing.assert_allclose(np.asarray(sa.data),
                                       np.asarray(sb.data), rtol=1e-6)
    print(f"RANK{rank} CKPT-OK", flush=True)
""")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.parametrize("stage", [1, 2, 3])
def test_two_process_train_barrier_checkpoint(tmp_path, stage):
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    port = _free_port()
    env_base = {k: v for k, v in os.environ.items()
                if not k.startswith(("DSTPU_", "XLA_FLAGS"))}
    procs = []
    for r in range(2):
        env = dict(env_base,
                   DSTPU_COORDINATOR=f"127.0.0.1:{port}",
                   DSTPU_NUM_PROCESSES="2", DSTPU_PROCESS_ID=str(r),
                   T_STAGE=str(stage), T_CKPT=str(tmp_path / "ckpt"),
                   PYTHONPATH=REPO)
        procs.append(subprocess.Popen(
            [sys.executable, str(script)], env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    finally:
        for p in procs:  # a hung rank must not leak past the test
            if p.poll() is None:
                p.kill()
                p.wait()
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} failed:\n{out[-3000:]}"
        assert f"RANK{r} CKPT-OK" in out, out[-2000:]
    # identical loss trajectory on both ranks (true data-parallel reduce)
    l0 = [ln for ln in outs[0].splitlines() if "LOSSES" in ln][0].split()[2:]
    l1 = [ln for ln in outs[1].splitlines() if "LOSSES" in ln][0].split()[2:]
    assert l0 == l1, (l0, l1)

    # RESIZE-RESUME: the 2-process partitioned checkpoint reloads in THIS
    # single process on the 8-virtual-device mesh (the elastic/universal
    # reshard story across real process counts)
    import jax
    import numpy as np

    import deepspeed_tpu
    from tests.unit.simple_model import random_batch, simple_mlp_spec

    engine, *_ = deepspeed_tpu.initialize(
        model=simple_mlp_spec(),
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                "zero_optimization": {"stage": stage}})
    engine.load_checkpoint(str(tmp_path / "ckpt"), "mp")
    assert engine.global_steps == 10  # the workers' training step count
    # the reloaded leaves must BYTE-match the workers' saved shards — a
    # silently-skipped or misassembled leaf would still train finitely
    from deepspeed_tpu.checkpoint.partitioned import _assemble

    full = _assemble(str(tmp_path / "ckpt" / "mp"), prefix=".params")
    import re as _re

    for key, want in full.items():
        cur = engine.state.params
        parts = _re.findall(r"\['([^']+)'\]", key)
        for p in parts:
            cur = cur[p]
        got = np.asarray(jax.device_get(cur))
        np.testing.assert_allclose(got, want.reshape(got.shape), rtol=1e-6,
                                   err_msg=key)
    loss = float(engine.train_batch(random_batch(batch_size=16, seed=3,
                                                 gas=1)))
    assert np.isfinite(loss)


def test_launcher_cli_end_to_end(tmp_path):
    """The `deepspeed`-CLI analogue actually launches the job: a 2-entry
    hostfile (both local) -> launcher assigns the coordinator env contract
    -> two REAL worker processes rendezvous, train data-parallel, and
    write per-rank proof files."""

    worker = tmp_path / "train.py"
    worker.write_text(textwrap.dedent("""
        import os, sys
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")
        from deepspeed_tpu.comm import comm
        comm.init_distributed()
        assert jax.process_count() == 2
        import numpy as np, jax.numpy as jnp
        import deepspeed_tpu
        from tests.unit.simple_model import random_batch, simple_mlp_spec
        engine, *_ = deepspeed_tpu.initialize(
            model=simple_mlp_spec(),
            config={"train_micro_batch_size_per_gpu": 8,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                    "mesh": {"data": 2}})
        loss = float(engine.train_batch(random_batch(batch_size=16, gas=1)))
        out = sys.argv[1]
        with open(f"{out}/rank{jax.process_index()}.ok", "w") as f:
            f.write(f"{loss:.6f}")
    """))
    hf = tmp_path / "hostfile"
    hf.write_text("localhost slots=1\n127.0.0.1 slots=1\n")
    # the launcher passes the environment through for all-local jobs:
    # strip the pytest harness's 8-virtual-device XLA_FLAGS and stale
    # contract vars so each worker sees 1 local device.  Run the CLI in a
    # subprocess session so a hung worker can't wedge pytest.
    env = {k: v for k, v in os.environ.items()
           if not (k.startswith("DSTPU_") or k == "XLA_FLAGS")}
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-m", "deepspeed_tpu.launcher.runner",
         "--hostfile", str(hf), "--master_port", str(_free_port()),
         str(worker), str(tmp_path)],
        env=env, cwd=REPO, start_new_session=True,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    try:
        out, _ = proc.communicate(timeout=300)
    except subprocess.TimeoutExpired:
        import signal

        os.killpg(proc.pid, signal.SIGKILL)
        proc.wait()
        raise
    assert proc.returncode == 0, out[-3000:]
    losses = [(tmp_path / f"rank{r}.ok").read_text() for r in range(2)]
    assert losses[0] == losses[1], losses  # same reduced loss on both ranks
