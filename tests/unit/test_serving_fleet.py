"""Serving fleet tests: router, KV-page migration, replica lifecycle.

Fast tier: pure routing policy (affinity hashing determinism, HRW
stability, least-loaded tie-breaks), allocator ref-count adoption,
bundle wire-format round trip, config validation, and the close()
loudness fix — all host logic, no model steps.

Slow tier: engine-level oracles — KV page export/import round-trips
bit-identically (including copy-on-write pages), a disaggregated fleet
reproduces single-engine greedy streams token-for-token, a replica
death mid-stream recovers every request via re-dispatch, and drain()
finishes in-flight work while handing queued requests back.
"""

from types import SimpleNamespace

import numpy as np
import pytest

from deepspeed_tpu.inference.v2 import (BlockAllocator, InferenceEngineV2,
                                        PrefixCache, RaggedInferenceConfig,
                                        RaggedRequest)
from deepspeed_tpu.serving import ServingConfig
from deepspeed_tpu.serving.kv_transfer import (bundle_from_bytes,
                                               bundle_to_bytes,
                                               migrate_sequence)
from deepspeed_tpu.serving.router import (affinity_key, build_fleet,
                                          hrw_score, pick_replica)


def _cand(name, load=0):
    return SimpleNamespace(name=name, load=lambda load=load: load)


# ----------------------------- fast: routing policy -------------------------
def test_affinity_key_deterministic_and_prefix_grouped():
    ps = 8
    prompt = list(range(40))
    assert affinity_key(prompt, ps) == affinity_key(list(prompt), ps)
    # same leading pages, different tail beyond affinity_pages => same key
    other = prompt[:2 * ps] + [99] * 10
    assert (affinity_key(prompt, ps, affinity_pages=2)
            == affinity_key(other, ps, affinity_pages=2))
    # divergence INSIDE the hashed pages changes the key
    assert (affinity_key(prompt, ps, affinity_pages=2)
            != affinity_key([1] + prompt[1:], ps, affinity_pages=2))
    # sub-page prompts still hash (whole prompt), deterministically
    assert affinity_key([1, 2, 3], ps) == affinity_key([1, 2, 3], ps)
    assert affinity_key([1, 2, 3], ps) != affinity_key([1, 2, 4], ps)


def test_hrw_pick_deterministic_and_stable():
    key = affinity_key(list(range(16)), 8)
    cands = [_cand(n) for n in ("a", "b", "c")]
    first, via = pick_replica(key, cands, load_gap=4)
    assert via == "affinity"
    for _ in range(3):  # deterministic across calls and candidate order
        again, _ = pick_replica(key, list(reversed(cands)), load_gap=4)
        assert again.name == first.name
    # HRW stability: removing a NON-chosen replica keeps the placement
    losers = [c for c in cands if c.name != first.name]
    kept, _ = pick_replica(key, [c for c in cands if c is not losers[0]],
                           load_gap=4)
    assert kept.name == first.name


def test_least_loaded_fallback_and_tie_break():
    key = affinity_key(list(range(16)), 8)
    hot = max(("a", "b", "c"), key=lambda n: (hrw_score(key, n), n))
    cold = sorted(n for n in ("a", "b", "c") if n != hot)
    # favorite within the gap: affinity wins despite nonzero load
    cands = [_cand(hot, 4)] + [_cand(n, 1) for n in cold]
    got, via = pick_replica(key, cands, load_gap=4)
    assert (got.name, via) == (hot, "affinity")
    # favorite too hot: least-loaded, ties broken by name (deterministic)
    cands = [_cand(hot, 9)] + [_cand(n, 1) for n in cold]
    got, via = pick_replica(key, cands, load_gap=4)
    assert (got.name, via) == (cold[0], "least_loaded")


# ----------------------------- fast: ref-count adoption ---------------------
def test_allocator_adopt_shares_registered_and_allocs_fresh():
    a = BlockAllocator(8)
    pc = PrefixCache(2, a)
    keys = pc.page_keys(list(range(8)), 4)
    owned = a.alloc(2)
    for p, k in zip(owned, keys[:2]):
        a.register(p, k)
    pages, reused = a.adopt([keys[0], keys[1], keys[2], None])
    assert reused == [True, True, False, False]
    assert pages[:2] == owned  # adopted the canonical local pages
    assert a.refcount(owned[0]) == 2 and a.refcount(owned[1]) == 2
    assert a.refcount(pages[2]) == 1 and a.refcount(pages[3]) == 1


def test_allocator_adopt_revives_lru_and_is_all_or_nothing():
    a = BlockAllocator(4)
    pc = PrefixCache(2, a)
    keys = pc.page_keys(list(range(8)), 4)
    owned = a.alloc(3)
    for p, k in zip(owned, keys[:3]):
        a.register(p, k)
    a.free(owned)  # all parked in the LRU, free_pages == 4
    # adoption revives parked pages instead of evicting them for fresh
    pages, reused = a.adopt([keys[0], None])
    assert reused == [True, False] and pages[0] == owned[0]
    assert a.evictions <= 1  # fresh page may evict ONE lru page, not keys[0]
    assert a.lookup(keys[0]) == owned[0]
    # all-or-nothing: over-capacity adopt leaves refcounts untouched
    before = [a.refcount(p) for p in range(4)]
    with pytest.raises(MemoryError):
        a.adopt([keys[1], None, None, None])
    assert [a.refcount(p) for p in range(4)] == before


def test_serving_config_validation():
    cfg = ServingConfig.from_dict({"enabled": True, "prefill_replicas": 2,
                                   "decode_replicas": 3})
    assert (cfg.prefill_replicas, cfg.decode_replicas) == (2, 3)
    with pytest.raises(ValueError):
        ServingConfig.from_dict({"enabled": True, "disaggregated": True,
                                 "prefill_replicas": 0})
    with pytest.raises(ValueError):
        ServingConfig.from_dict({"affinity_pages": 0})
    with pytest.raises(ValueError):
        ServingConfig.from_dict({"prefill_replicas": 0,
                                 "decode_replicas": 0})
    # the ds-config json surface parses the block
    from deepspeed_tpu.runtime.config import DeepSpeedConfig

    ds = DeepSpeedConfig({"serving": {"enabled": True, "load_gap": 2}})
    assert ds.serving.enabled and ds.serving.load_gap == 2


# ----------------------------- engine fixtures ------------------------------
@pytest.fixture(scope="module")
def tiny_model():
    import jax

    from deepspeed_tpu.models.llama import llama_model

    model = llama_model("tiny", max_seq_len=128)
    return model, model.init_params(jax.random.PRNGKey(0))


def _engine(model, params, cache=True, **kw):
    cfg = RaggedInferenceConfig(dtype="fp32", page_size=8, num_pages=64,
                                max_seqs=4, max_pages_per_seq=12,
                                enable_prefix_cache=cache, **kw)
    return InferenceEngineV2(model, cfg, params=params)


def _prompt(n, seed=0, vocab=256):
    return list(np.random.RandomState(seed).randint(0, vocab, n))


# ----------------------------- fast: close() loudness -----------------------
def test_close_aborts_inflight_loudly(tiny_model):
    import logging

    from deepspeed_tpu.utils.logging import logger as ds_logger

    model, params = tiny_model
    eng = _engine(model, params)
    eng.put(RaggedRequest(prompt_ids=_prompt(12), max_new_tokens=4))
    assert eng.has_work()
    messages = []
    handler = logging.Handler()
    handler.emit = lambda rec: messages.append(rec.getMessage())
    ds_logger.addHandler(handler)  # the package logger propagates nowhere
    try:
        eng.close()
    finally:
        ds_logger.removeHandler(handler)
    assert not eng.has_work()  # aborted, not leaked
    assert any("aborted 1 unfinished" in m for m in messages), messages


def test_bundle_bytes_roundtrip_without_engine():
    from deepspeed_tpu.inference.v2 import KVPageBundle

    arrays = {"k": np.arange(2 * 3 * 8 * 4 * 4, dtype=np.float32)
              .reshape(2, 3, 8, 4, 4),
              "v": np.ones((2, 3, 8, 4, 4), np.float32) * 0.5}
    b = KVPageBundle(uid=7, tokens=list(range(20)), prompt_len=18,
                     max_new_tokens=8, temperature=0.0, eos_id=None,
                     prefilled=19, decode_entry=False, page_size=8,
                     page_keys=[b"\x01" * 32, b"\x02" * 32],
                     src_pages=[{"page": 3, "refcount": 1, "key": b"\x01" * 32},
                                {"page": 5, "refcount": 2, "key": None},
                                {"page": 9, "refcount": 1, "key": None}],
                     arrays=arrays, model_sig=(2, 4, 4), kv_quant=False,
                     dtype="fp32")
    rt = bundle_from_bytes(bundle_to_bytes(b))
    assert rt.uid == 7 and rt.tokens == b.tokens and rt.prefilled == 19
    assert rt.page_keys == b.page_keys and rt.model_sig == (2, 4, 4)
    assert rt.src_pages[0]["key"] == b"\x01" * 32
    for leaf in arrays:
        assert rt.arrays[leaf].dtype == arrays[leaf].dtype
        assert np.array_equal(rt.arrays[leaf], arrays[leaf])
    assert rt.trace is None  # no trace attached -> none invented


def _trace_bundle(trace):
    from deepspeed_tpu.inference.v2 import KVPageBundle

    arrays = {"k": np.arange(1 * 1 * 8 * 2 * 2, dtype=np.float32)
              .reshape(1, 1, 8, 2, 2)}
    return KVPageBundle(uid=3, tokens=list(range(10)), prompt_len=9,
                        max_new_tokens=4, temperature=0.0, eos_id=None,
                        prefilled=9, decode_entry=False, page_size=8,
                        page_keys=[b"\x07" * 32],
                        src_pages=[{"page": 1, "refcount": 1,
                                    "key": b"\x07" * 32}],
                        arrays=arrays, model_sig=(1, 2, 2), kv_quant=False,
                        dtype="fp32", trace=trace)


def test_bundle_wire_preserves_trace_context():
    """The optional trace block survives the CRC-guarded wire: id and
    ledger snapshot intact, one hop appended with send/receive stamps,
    and transit measured on the receive side."""
    snap = {"trace_id": "r1-7", "elapsed_s": 0.25,
            "phases": [["prefill", "prefill0", 0.25]]}
    rt = bundle_from_bytes(bundle_to_bytes(_trace_bundle(snap)))
    assert rt.trace is not None
    assert rt.trace["trace_id"] == "r1-7"
    assert rt.trace["phases"] == [["prefill", "prefill0", 0.25]]
    hops = rt.trace["hops"]
    assert len(hops) == 1
    assert "sent_unix" in hops[0] and "recv_unix" in hops[0]
    assert rt.trace["transit_s"] >= 0.0
    # a second hop (re-migration) appends, never overwrites
    rt2 = bundle_from_bytes(bundle_to_bytes(_trace_bundle(rt.trace)))
    assert len(rt2.trace["hops"]) == 2


def test_bundle_wire_legacy_no_trace_imports_with_null_trace():
    """A bundle serialized WITHOUT a trace block (legacy sender) must
    import cleanly with ``trace=None`` — the block is optional by
    construction, not a new wire version."""
    wire = bundle_to_bytes(_trace_bundle(None))
    assert b'"trace_crc"' not in wire  # header simply omits the block
    rt = bundle_from_bytes(wire)
    assert rt.trace is None
    assert rt.uid == 3 and np.array_equal(
        rt.arrays["k"].ravel(), np.arange(32, dtype=np.float32))


def test_bundle_wire_torn_trace_block_refused_by_name():
    """A trace block whose CRC no longer matches (torn/bit-flipped in
    transport) is refused with an error naming the trace block — never
    silently imported with a wrong trace."""
    from deepspeed_tpu.serving.kv_transfer import (CorruptBundleError,
                                                   _MAGIC)
    import json as _json

    wire = bundle_to_bytes(_trace_bundle({"trace_id": "r1-9", "hops": []}))
    off = len(_MAGIC)
    hlen = int.from_bytes(wire[off:off + 8], "little")
    header = _json.loads(wire[off + 8:off + 8 + hlen].decode())
    header["trace"]["trace_id"] = "r1-FORGED"  # flip a byte, keep old CRC
    hdr = _json.dumps(header).encode()
    torn = (_MAGIC + len(hdr).to_bytes(8, "little") + hdr
            + wire[off + 8 + hlen:])
    with pytest.raises(CorruptBundleError, match="trace block"):
        bundle_from_bytes(torn)
    # page payload itself is intact: stripping the trace keys imports fine
    header.pop("trace"), header.pop("trace_crc")
    hdr = _json.dumps(header).encode()
    ok = (_MAGIC + len(hdr).to_bytes(8, "little") + hdr
          + wire[off + 8 + hlen:])
    assert bundle_from_bytes(ok).trace is None


# ------------------- fast: rebalance / elastic membership -------------------
class _StubEngine:
    """Pure-python engine for routing-policy tests: holds decode-ready
    uids, moves them via the real migrate_sequence plumbing."""

    def __init__(self, uids=(), queue=0):
        from types import SimpleNamespace as NS

        self.block = NS(page_size=8)
        self.allocator = NS(free_pages=32, num_pages=64)
        self.queue_depth = queue
        self.uids = list(uids)
        self.imported = []
        self.released = []
        self.trace_owner = None

    @property
    def active_count(self):
        return len(self.uids)

    def has_work(self):
        return bool(self.uids) or self.queue_depth > 0

    def ready_uids(self):
        return list(self.uids)

    def export_sequence(self, uid):
        return SimpleNamespace(uid=uid, n_pages=2, trace=None)

    def import_sequence(self, bundle):
        self.uids.append(bundle.uid)
        self.imported.append(bundle.uid)
        return True

    def release_sequence(self, uid, reason=""):
        self.uids.remove(uid)
        self.released.append(uid)

    def abort_all(self, reason="abort"):
        out, self.uids = list(self.uids), []
        return out


def _stub_fleet(*engines, config=None, role=None):
    from deepspeed_tpu.serving.replica import ROLE_MIXED, EngineReplica
    from deepspeed_tpu.serving.router import FleetRouter

    reps = [EngineReplica(f"s{i}", e, role=role or ROLE_MIXED)
            for i, e in enumerate(engines)]
    return FleetRouter(reps, config or ServingConfig())


def test_rebalance_moves_bounded_load_off_hot_replica():
    cfg = ServingConfig(rebalance_enabled=True, rebalance_load_gap=4,
                        rebalance_max_per_pump=2)
    hot, cold = _StubEngine(uids=[1, 2, 3, 4, 5, 6]), _StubEngine()
    router = _stub_fleet(hot, cold, config=cfg)
    router._rebalance_decode()
    # bounded per pump, routed through the real migration plumbing
    assert cold.imported == [1, 2] and hot.released == [1, 2]
    assert sorted(hot.uids) == [3, 4, 5, 6]
    # gap now 4, NOT > rebalance_load_gap: hysteresis holds, no move
    router._rebalance_decode()
    assert cold.imported == [1, 2]


def test_rebalance_skips_deadline_starved_streams():
    from deepspeed_tpu.serving.router import _RequestRecord

    cfg = ServingConfig(rebalance_enabled=True, rebalance_load_gap=2,
                        rebalance_max_per_pump=8,
                        rebalance_min_deadline_s=0.5)
    hot, cold = _StubEngine(uids=[1, 2, 3, 4]), _StubEngine()
    router = _stub_fleet(hot, cold, config=cfg)
    # uid 2 has ~no deadline budget left: the move costs time it
    # doesn't have — it must stay put while the others go
    starved = RaggedRequest(prompt_ids=[1], uid=2, deadline_s=1e-9)
    router._requests[2] = _RequestRecord(starved)
    router._rebalance_decode()
    assert 2 in hot.uids and 2 not in cold.imported
    assert sorted(cold.imported) == [1, 3, 4]


def test_rebalance_p50_signal_spots_warm_replica():
    """The latency rule relieves a warm (gray-degrading) replica at a
    LOWER threshold than the breaker declares it failed."""
    cfg = ServingConfig(rebalance_enabled=True, rebalance_p50_factor=2.0,
                        breaker_enabled=True)
    eng = [_StubEngine(uids=[1]), _StubEngine(uids=[2]),
           _StubEngine(uids=[3])]
    router = _stub_fleet(*eng, config=cfg)
    reps = list(router.replicas.values())
    need = cfg.breaker_min_samples
    for r in reps:  # equal load; only latency distinguishes them
        for _ in range(need):
            r._record_step(0.01, error=False)
    assert router._hot_decode_replica(reps) is None  # healthy: no pick
    # a WARM replica is slow on every step: the rolling MEDIAN moves
    for _ in range(2 * need + 1):
        reps[1]._record_step(10 * cfg.breaker_min_latency_s, error=False)
    assert router._hot_decode_replica(reps) is reps[1]


def test_add_replica_checks_name_and_geometry():
    router = _stub_fleet(_StubEngine())
    from deepspeed_tpu.serving.replica import EngineReplica

    router.add_replica(EngineReplica("joined", _StubEngine()))
    assert set(router.replicas) == {"s0", "joined"}
    with pytest.raises(ValueError, match="already in"):
        router.add_replica(EngineReplica("joined", _StubEngine()))
    wrong = _StubEngine()
    wrong.block.page_size = 16
    with pytest.raises(ValueError, match="one geometry"):
        router.add_replica(EngineReplica("odd", wrong))


def test_rebalance_config_validation():
    with pytest.raises(ValueError):
        ServingConfig(rebalance_enabled=True,
                      rebalance_max_per_pump=0).validate()
    # rebalance must fire BELOW the breaker's latency threshold, or the
    # breaker recomputes everything before rebalancing ever helps
    with pytest.raises(ValueError, match="breaker_latency_factor"):
        ServingConfig(rebalance_enabled=True, breaker_enabled=True,
                      rebalance_p50_factor=50.0).validate()


# ----------------------------- fast: autoscaler -----------------------------
def _autoscaler(router, spawn=None, **kw):
    from deepspeed_tpu.serving import AutoscaleConfig
    from deepspeed_tpu.serving.autoscale import FleetAutoscaler

    kw.setdefault("enabled", True)
    return FleetAutoscaler(router, AutoscaleConfig(**kw),
                           spawn_replica=spawn)


def test_autoscaler_grows_on_sustained_queue_pressure():
    from deepspeed_tpu.serving.replica import EngineReplica

    router = _stub_fleet(_StubEngine(queue=9))
    spawned = []

    def spawn(i):
        spawned.append(i)
        return EngineReplica(f"auto{i}", _StubEngine())

    a = _autoscaler(router, spawn, grow_queue_per_replica=4.0,
                    grow_streak=2, grow_on_ttft_violations=False,
                    max_replicas=2, cooldown_pumps=3)
    assert a.evaluate() is None  # streak 1: pressure must SUSTAIN
    assert a.evaluate() == "grow"
    assert spawned == [0] and "auto0" in router.replicas
    assert a.grown == ["auto0"]
    # cooldown: the fresh replica absorbs load before signals re-arm;
    # then max_replicas caps growth even under pressure
    for _ in range(10):
        a.evaluate()
    assert len(router.replicas) == 2


def test_autoscaler_grows_on_new_ttft_violations():
    from deepspeed_tpu.serving.replica import EngineReplica
    from deepspeed_tpu.telemetry import get_registry

    router = _stub_fleet(_StubEngine(queue=1))
    a = _autoscaler(router,
                    lambda i: EngineReplica(f"auto{i}", _StubEngine()),
                    grow_queue_per_replica=100.0, grow_streak=99,
                    max_replicas=2)
    assert a.evaluate() is None  # queue alone is quiet
    get_registry().counter(
        "deepspeed_tpu_serving_slo_ttft_violations_total",
        "ttft violations").inc(3)
    assert a.evaluate() == "grow"  # latency debt is the leading signal


def test_autoscaler_shrinks_lifo_via_evacuation_never_drops():
    from deepspeed_tpu.serving.replica import EngineReplica

    base, extra = _StubEngine(), _StubEngine(uids=[7, 8])
    router = _stub_fleet(base)
    router.add_replica(EngineReplica("auto0", extra))
    a = _autoscaler(router, shrink_queue_per_replica=0.5,
                    shrink_streak=2, min_replicas=1, cooldown_pumps=0,
                    grow_streak=99, grow_on_ttft_violations=False)
    a.grown = ["auto0"]
    assert a.evaluate() is None
    assert a.evaluate() == "shrink"
    r = router.replicas["auto0"]
    assert r.retired and not extra.uids  # engine left empty...
    assert sorted(base.imported) == [7, 8]  # ...streams MIGRATED out
    assert a.grown == []
    # min_replicas floor: never shrinks the last replica
    for _ in range(8):
        assert a.evaluate() is None
    assert not router.replicas["s0"].retired


def test_autoscaler_spawn_failure_backs_off_bounded():
    router = _stub_fleet(_StubEngine(queue=50))

    def bad_spawn(i):
        raise RuntimeError("factory broke")

    a = _autoscaler(router, bad_spawn, grow_queue_per_replica=1.0,
                    grow_streak=1, max_replicas=4, cooldown_pumps=0)
    fails, skips = 0, 0
    for _ in range(40):
        a.evaluate()
        if a._spawn_backoff and a._spawn_failures:
            skips += 1
        fails = a._spawn_failures
    # pressure is constant, but attempts decay exponentially: far
    # fewer than 40 factory calls, and the backoff keeps growing
    assert 0 < fails < 8 and skips > fails
    assert len(router.replicas) == 1


def test_autoscale_config_validation():
    from deepspeed_tpu.serving import AutoscaleConfig

    with pytest.raises(ValueError):
        AutoscaleConfig(min_replicas=3, max_replicas=2).validate()
    with pytest.raises(ValueError, match="hysteresis"):
        AutoscaleConfig(grow_queue_per_replica=1.0,
                        shrink_queue_per_replica=2.0).validate()
    sc = ServingConfig(autoscale={"enabled": True, "max_replicas": 3})
    sc.validate()
    assert sc.autoscale.max_replicas == 3


# ----------------------------- slow: engine oracles -------------------------
@pytest.mark.slow
@pytest.mark.parametrize("cache", [False, True])
def test_kv_export_import_bit_identical_roundtrip(tiny_model, cache):
    """Export a mid-decode sequence, import into a fresh engine: page
    contents must round-trip bit-identically and the continued stream
    must match the uninterrupted one token-for-token."""
    from deepspeed_tpu.inference.v2.model_runner import paged_gather_pages

    model, params = tiny_model
    src = _engine(model, params, cache=cache)
    uid = src.put(RaggedRequest(prompt_ids=_prompt(20, seed=1),
                                max_new_tokens=8))
    for _ in range(3):  # prefill + 2 decode steps: mid-stream
        src.step()
    bundle = src.export_sequence(uid)
    assert bundle.n_pages == len(src._find_slotted(uid).pages)

    dst = _engine(model, params, cache=cache)
    assert dst.import_sequence(bundle)
    got = paged_gather_pages(dst._pools, dst._find_slotted(uid).pages)
    for leaf, arr in bundle.arrays.items():
        assert got[leaf].dtype == arr.dtype
        assert np.array_equal(got[leaf], arr), leaf

    # streams: source continues undisturbed, the import continues too
    src_rest, dst_rest = [], []
    for _ in range(20):
        for u, rec in src.step().items():
            src_rest.extend(rec["tokens"])
        for u, rec in dst.step().items():
            dst_rest.extend(rec["tokens"])
        if not src.has_work() and not dst.has_work():
            break
    assert src_rest == dst_rest and len(dst_rest) > 0


@pytest.mark.slow
def test_kv_export_import_covers_copy_on_write_page(tiny_model):
    """A fully-cached prompt admits via a copy-on-write page
    (decode_entry); its bundle must transfer that private page by value
    and the migrated stream must match the donor engine's."""
    from deepspeed_tpu.inference.v2.model_runner import paged_gather_pages

    model, params = tiny_model
    src = _engine(model, params, cache=True)
    prompt = _prompt(16, seed=2)  # page-aligned: full-hit on re-admission
    first = src.generate_all([RaggedRequest(prompt_ids=list(prompt),
                                            max_new_tokens=6)])
    uid = src.put(RaggedRequest(prompt_ids=list(prompt), max_new_tokens=6))
    # drive admission WITHOUT a decode step: the full cache hit maps a
    # private copy-on-write last page (decode_entry), still unwritten —
    # the migration case where the CoW page must move by value
    src._admit()
    seq = src._find_slotted(uid)
    assert seq.decode_entry and seq.generated == 0
    bundle = src.export_sequence(uid)
    # the CoW page (last) is NOT adoptable — transferred by value
    assert len(bundle.page_keys) < bundle.n_pages

    dst = _engine(model, params, cache=True)
    assert dst.import_sequence(bundle)
    got = paged_gather_pages(dst._pools, dst._find_slotted(uid).pages)
    for leaf, arr in bundle.arrays.items():
        assert np.array_equal(got[leaf], arr), leaf
    src.release_sequence(uid)
    toks = []
    for _ in range(20):
        for _u, rec in dst.step().items():
            toks.extend(rec["tokens"])
        if not dst.has_work():
            break
    assert toks == first[0], (toks, first[0])


@pytest.mark.slow
def test_import_rejects_dtype_mismatch(tiny_model):
    """A dtype-mismatched bundle must raise even when every page could
    be adopted by content key (the scatter — the only other dtype
    check — never runs on an all-adopted import)."""
    import dataclasses

    model, params = tiny_model
    src = _engine(model, params, cache=True)
    uid = src.put(RaggedRequest(prompt_ids=_prompt(20, seed=5),
                                max_new_tokens=8))
    for _ in range(3):  # prefill + 2 decode steps: mid-stream
        src.step()
    bundle = dataclasses.replace(src.export_sequence(uid), dtype="bf16")
    dst = _engine(model, params, cache=True)
    with pytest.raises(ValueError, match="dtype"):
        dst.import_sequence(bundle)


@pytest.mark.slow
def test_planned_retirement_spares_redispatch_budget(tiny_model):
    """retire_replica(migrate=False) hands queued work back without
    consuming the max_redispatch replica-loss budget: with
    max_redispatch=0 every drained-back request must still complete."""
    model, params = tiny_model
    base = RaggedInferenceConfig(dtype="fp32", page_size=8, num_pages=64,
                                 max_seqs=4, max_pages_per_seq=12)
    reqs = [RaggedRequest(prompt_ids=_prompt(10 + i, seed=40 + i),
                          max_new_tokens=4) for i in range(4)]
    control = InferenceEngineV2(model, base, params=params)
    want = control.generate_all([RaggedRequest(prompt_ids=list(r.prompt_ids),
                                               max_new_tokens=r.max_new_tokens)
                                 for r in reqs])
    fleet = build_fleet(
        model, ServingConfig(enabled=True, prefill_replicas=1,
                             decode_replicas=1, disaggregated=False,
                             max_redispatch=0),
        engine_config=base, params=params)
    uids = [fleet.submit(r) for r in reqs]
    victim = next(fleet.request_state(u)["replica"] for u in uids)
    fleet.retire_replica(victim, migrate=False)  # nothing admitted yet:
    for _ in range(200):                         # all its work requeues
        if not fleet.has_work():
            break
        fleet.step()
    assert not fleet.has_work()
    states = [fleet.request_state(u) for u in uids]
    assert not any(s["failed"] for s in states)
    assert all(s["redispatches"] == 0 for s in states)  # planned: uncharged
    assert [s["emitted"] for s in states] == [want[i] for i in range(4)]


@pytest.mark.slow
def test_disaggregated_fleet_matches_single_engine(tiny_model):
    model, params = tiny_model
    base = RaggedInferenceConfig(dtype="fp32", page_size=8, num_pages=64,
                                 max_seqs=4, max_pages_per_seq=12,
                                 enable_prefix_cache=True)
    shared = _prompt(16, seed=3)
    reqs = [RaggedRequest(prompt_ids=shared + _prompt(3 + i, seed=10 + i),
                          max_new_tokens=6) for i in range(3)]
    control = InferenceEngineV2(model, base, params=params)
    want = control.generate_all([RaggedRequest(prompt_ids=list(r.prompt_ids),
                                               max_new_tokens=r.max_new_tokens)
                                 for r in reqs])
    fleet = build_fleet(
        model, ServingConfig(enabled=True, prefill_replicas=1,
                             decode_replicas=1, prefill_chunk=8),
        engine_config=base, params=params)
    got = fleet.run_all(reqs)
    assert [got[i] for i in range(3)] == [want[i] for i in range(3)]
    # disaggregation actually ran: the decode pool carried the decoding.
    # (The prefill engine may decode each sequence at most once — the
    # SplitFuse step that finishes a prefill interleaves one decode
    # before the router can migrate; steady-state decode must move.)
    assert fleet.replicas["decode0"].engine._decode_steps >= 3
    assert fleet.replicas["prefill0"].engine._decode_steps <= len(reqs)


@pytest.mark.slow
def test_redispatch_after_replica_death(tiny_model):
    model, params = tiny_model
    base = RaggedInferenceConfig(dtype="fp32", page_size=8, num_pages=64,
                                 max_seqs=4, max_pages_per_seq=12,
                                 enable_prefix_cache=True)
    shared = _prompt(16, seed=4)
    reqs = [RaggedRequest(prompt_ids=shared + _prompt(3 + i, seed=20 + i),
                          max_new_tokens=8) for i in range(3)]
    control = InferenceEngineV2(model, base, params=params)
    want = control.generate_all([RaggedRequest(prompt_ids=list(r.prompt_ids),
                                               max_new_tokens=r.max_new_tokens)
                                 for r in reqs])
    fleet = build_fleet(
        model, ServingConfig(enabled=True, prefill_replicas=1,
                             decode_replicas=2, prefill_chunk=8),
        engine_config=base, params=params)
    uids = [fleet.submit(r) for r in reqs]
    for _ in range(60):
        fleet.step()
        states = [fleet.request_state(u) for u in uids]
        if any((s["replica"] or "").startswith("decode")
               and 1 <= len(s["emitted"]) < 8 for s in states):
            break
    victims = [s["replica"] for s in states
               if (s["replica"] or "").startswith("decode")]
    assert victims, states
    fleet.kill_replica(victims[0])
    for _ in range(200):
        if not fleet.has_work():
            break
        fleet.step()
    assert not fleet.has_work()
    got = [fleet.request_state(u)["emitted"] for u in uids]
    assert got == [want[i] for i in range(3)]
    assert any(fleet.request_state(u)["redispatches"] >= 1 for u in uids)
    assert not any(fleet.request_state(u)["failed"] for u in uids)


@pytest.mark.slow
def test_engine_drain_finishes_inflight_and_returns_queued(tiny_model):
    model, params = tiny_model
    eng = _engine(model, params, cache=False)
    # more requests than decode slots: some stay queued at drain time
    uids = [eng.put(RaggedRequest(prompt_ids=_prompt(10 + i, seed=30 + i),
                                  max_new_tokens=4)) for i in range(6)]
    eng.step()  # admits up to max_seqs=4; 2 remain queued
    result = eng.drain()
    finished, pending = result["finished"], result["pending"]
    assert len(finished) + len(pending) == 6
    assert all(s.done for s in finished.values())
    assert all(s.generated == 4 for s in finished.values())
    assert all(s.generated == 0 for s in pending)  # handed back UN-run
    assert not eng.has_work()
    with pytest.raises(RuntimeError):  # retired: no new admissions
        eng.put(RaggedRequest(prompt_ids=_prompt(8), max_new_tokens=2))
    assert set(finished) | {s.uid for s in pending} == set(uids)
