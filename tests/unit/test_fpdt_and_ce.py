"""FPDT chunked attention + vocab-parallel cross-entropy parity tests
(reference sequence/fpdt_layer.py, sequence/cross_entropy.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.transformer import xla_attention
from deepspeed_tpu.parallel.mesh import MeshConfig, initialize_topology
from deepspeed_tpu.sequence.cross_entropy import vocab_parallel_cross_entropy
from deepspeed_tpu.sequence.fpdt import (FPDTAttention, chunked_mlp,
                                         fpdt_attention)


def _qkv(b=2, s=64, nh=4, d=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (b, s, nh, d)) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
def test_fpdt_matches_dense(causal):
    q, k, v = _qkv()
    ref = xla_attention(q, k, v, causal)
    out = jax.jit(lambda q, k, v: fpdt_attention(q, k, v, causal, chunk_size=16))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=1e-4)


def test_fpdt_padding_mask_matches():
    q, k, v = _qkv(s=32)
    mask = jnp.concatenate([jnp.ones((2, 24)), jnp.zeros((2, 8))], axis=1)
    ref = xla_attention(q, k, v, False, mask)
    out = jax.jit(lambda q, k, v: fpdt_attention(
        q, k, v, causal=False, chunk_size=8, mask=mask))(q, k, v)
    np.testing.assert_allclose(np.asarray(out)[:, :24], np.asarray(ref)[:, :24],
                               atol=2e-5, rtol=1e-4)


def test_fpdt_uneven_seq_picks_divisor_chunk():
    from deepspeed_tpu.models.transformer import (TransformerConfig,
                                                  causal_lm_loss,
                                                  init_transformer_params)

    cfg = TransformerConfig(vocab_size=64, hidden_size=32, n_layers=1,
                            n_heads=2, intermediate_size=64, max_seq_len=48,
                            attn_impl="fpdt")
    params = init_transformer_params(cfg, jax.random.PRNGKey(0))
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 48)))
    loss = causal_lm_loss(cfg, params, ids)  # 48 not a multiple of 1024
    assert np.isfinite(float(loss))


def test_fpdt_gradients_match():
    q, k, v = _qkv(b=1, s=32, nh=2, d=8)
    g_ref = jax.grad(lambda q: jnp.sum(xla_attention(q, k, v, True) ** 2))(q)
    g = jax.jit(jax.grad(
        lambda q: jnp.sum(fpdt_attention(q, k, v, True, chunk_size=8) ** 2)))(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), atol=5e-4, rtol=1e-3)


@pytest.mark.parametrize("causal", [True, False])
def test_fpdt_offload_matches_dense(causal):
    q, k, v = _qkv(s=64)
    ref = xla_attention(q, k, v, causal)
    attn = FPDTAttention(chunk_size=16, causal=causal)
    out = attn(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=1e-4)


def test_chunked_mlp_matches():
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 32, 8))
    w = jax.random.normal(jax.random.PRNGKey(1), (8, 8))
    fn = lambda t: jax.nn.gelu(t @ w)  # noqa: E731
    np.testing.assert_allclose(np.asarray(chunked_mlp(fn, x, num_chunks=4)),
                               np.asarray(fn(x)), atol=1e-6)


def test_fpdt_attn_impl_trains():
    from deepspeed_tpu.models import llama_model

    model = llama_model("tiny", max_seq_len=32, attn_impl="fpdt")
    engine, *_ = deepspeed_tpu.initialize(
        model=model,
        config={"train_micro_batch_size_per_gpu": 4,
                "optimizer": {"type": "Adam", "params": {"lr": 5e-3}}})
    ids = np.random.RandomState(0).randint(0, 256, (1, 8, 32)).astype(np.int32)
    losses = [float(engine.train_batch({"input_ids": jnp.asarray(ids)}))
              for _ in range(5)]
    assert losses[-1] < losses[0]


# ------------------------------------------------------------- cross entropy
def _ref_ce(logits, targets):
    x = np.asarray(logits, np.float32)
    t = np.asarray(targets)
    m = x.max(-1, keepdims=True)
    lse = np.log(np.exp(x - m).sum(-1)) + m[..., 0]
    return lse - np.take_along_axis(x, t[..., None], -1)[..., 0]


def test_vocab_parallel_ce_unsharded():
    logits = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 32))
    targets = jnp.asarray(np.random.RandomState(0).randint(0, 32, (2, 8)))
    out = vocab_parallel_cross_entropy(logits, targets)
    np.testing.assert_allclose(np.asarray(out), _ref_ce(logits, targets),
                               atol=1e-5, rtol=1e-5)


def test_vocab_parallel_ce_sharded(devices8):
    initialize_topology(MeshConfig(data=2, model=4), devices8)
    logits = jax.random.normal(jax.random.PRNGKey(0), (4, 8, 64))
    targets = jnp.asarray(np.random.RandomState(0).randint(0, 64, (4, 8)))
    topo = deepspeed_tpu.get_topology()
    with topo.mesh:
        out = jax.jit(vocab_parallel_cross_entropy)(logits, targets)
    np.testing.assert_allclose(np.asarray(out), _ref_ce(logits, targets),
                               atol=1e-5, rtol=1e-5)


def test_vocab_parallel_ce_grad(devices8):
    initialize_topology(MeshConfig(data=1, model=8), devices8)
    logits = jax.random.normal(jax.random.PRNGKey(1), (2, 4, 32))
    targets = jnp.asarray(np.random.RandomState(1).randint(0, 32, (2, 4)))

    def ref_loss(x):
        x = x.astype(jnp.float32)
        lse = jax.nn.logsumexp(x, axis=-1)
        tl = jnp.take_along_axis(x, targets[..., None], -1)[..., 0]
        return jnp.mean(lse - tl)

    g_ref = jax.grad(ref_loss)(logits)
    topo = deepspeed_tpu.get_topology()
    with topo.mesh:
        g = jax.jit(jax.grad(
            lambda x: jnp.mean(vocab_parallel_cross_entropy(x, targets))))(logits)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), atol=1e-5, rtol=1e-4)
