"""Telemetry subsystem tests.

Fast tier: registry semantics (counter/gauge/histogram + percentile
math), Prometheus exposition round-trip, JSONL event schema, timer sync
behavior, CSV monitor handle reuse, stall watchdog, MFU helpers, and the
training engine's registry wiring on the tiny MLP.  Slow tier: serving
metrics emission from InferenceEngineV2 on a tiny CPU llama.
"""

import json
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.telemetry import (JSONLWriter, MetricsRegistry,
                                     PrometheusFileExporter, StallWatchdog,
                                     mfu, parse_prometheus_text,
                                     peak_flops_for_kind, to_prometheus_text)


# ----------------------------- registry semantics ---------------------------
def test_counter_gauge_semantics():
    reg = MetricsRegistry()
    c = reg.counter("deepspeed_tpu_t_requests_total", "h", labelnames=("op",))
    c.inc(op="a")
    c.inc(2.5, op="a")
    c.inc(op="b")
    assert c.value(op="a") == 3.5 and c.value(op="b") == 1.0
    assert c.total() == 4.5
    with pytest.raises(ValueError):
        c.inc(-1, op="a")  # counters only go up
    with pytest.raises(ValueError):
        c.inc(1)  # missing label
    g = reg.gauge("deepspeed_tpu_t_depth")
    g.set(7)
    g.dec(2)
    assert g.value() == 5.0
    # get-or-create: same name+type returns the same object
    assert reg.counter("deepspeed_tpu_t_requests_total",
                       labelnames=("op",)) is c
    # same name, different type: loud failure
    with pytest.raises(ValueError):
        reg.gauge("deepspeed_tpu_t_requests_total")
    # label-set mismatch on re-registration: loud failure
    with pytest.raises(ValueError):
        reg.counter("deepspeed_tpu_t_requests_total", labelnames=("other",))


def test_metric_name_validation():
    reg = MetricsRegistry()
    for bad in ("loss", "deepspeed_tpu_CamelCase", "deepspeed_tpu_",
                "other_ns_loss", "deepspeed_tpu_x-y"):
        with pytest.raises(ValueError):
            reg.gauge(bad)


def test_histogram_percentiles():
    reg = MetricsRegistry()
    h = reg.histogram("deepspeed_tpu_t_latency_seconds", "h",
                      buckets=(0.1, 0.2, 0.4, 0.8, 1.6))
    # 100 uniform samples on (0, 1]: p50 ~ 0.5, p95 ~ 0.95, p99 ~ 0.99,
    # each within its owning bucket's interpolation error
    for i in range(1, 101):
        h.observe(i / 100.0)
    assert h.count() == 100
    assert h.sum() == pytest.approx(50.5)
    assert 0.4 <= h.quantile(0.5) <= 0.8  # p50 interpolated in (0.4, 0.8]
    p = h.percentiles()
    assert 0.8 <= p["p95"] <= 1.6 and 0.8 <= p["p99"] <= 1.6
    assert p["p50"] <= p["p95"] <= p["p99"]
    # +Inf bucket clamps to the top finite bound
    h2 = reg.histogram("deepspeed_tpu_t_big_seconds", buckets=(1.0, 2.0))
    h2.observe(100.0)
    assert h2.quantile(0.99) == 2.0
    # empty series: NaN, not a crash
    assert math.isnan(h.quantile(0.5, **{})) is False  # has data
    h3 = reg.histogram("deepspeed_tpu_t_empty_seconds")
    assert math.isnan(h3.quantile(0.5))


def test_histogram_exact_bucket_math():
    """Deterministic check of the interpolation formula: 10 samples in
    [0, 1) bucket, 10 in [1, 2) bucket (bounds 1 and 2): the median rank
    10 falls exactly at the first bucket's upper bound."""
    reg = MetricsRegistry()
    h = reg.histogram("deepspeed_tpu_t_exact_seconds", buckets=(1.0, 2.0))
    for _ in range(10):
        h.observe(0.5)
    for _ in range(10):
        h.observe(1.5)
    assert h.quantile(0.5) == pytest.approx(1.0)
    assert h.quantile(0.25) == pytest.approx(0.5)
    assert h.quantile(0.75) == pytest.approx(1.5)


def test_snapshot_events():
    reg = MetricsRegistry()
    reg.counter("deepspeed_tpu_t_x_total").inc(3)
    h = reg.histogram("deepspeed_tpu_t_h_seconds", labelnames=("phase",))
    h.observe(0.1, phase="fwd")
    events = reg.snapshot_events(step=7)
    tags = {t for t, _v, _s in events}
    assert ("deepspeed_tpu_t_x_total", 3.0, 7) in events
    assert "deepspeed_tpu_t_h_seconds/phase=fwd/p50" in tags
    assert "deepspeed_tpu_t_h_seconds/phase=fwd/count" in tags


# ----------------------------- exposition round-trip ------------------------
def test_prometheus_round_trip(tmp_path):
    reg = MetricsRegistry()
    c = reg.counter("deepspeed_tpu_t_ops_total", "ops so far",
                    labelnames=("op", "axis"))
    c.inc(5, op="all_reduce", axis="data")
    c.inc(2, op="all_gather", axis="d,x\"y")  # label escaping
    reg.gauge("deepspeed_tpu_t_util", "utilization").set(0.54)
    h = reg.histogram("deepspeed_tpu_t_lat_seconds", buckets=(0.5, 1.0))
    h.observe(0.2)
    h.observe(0.7)
    h.observe(3.0)

    text = to_prometheus_text(reg)
    assert "# TYPE deepspeed_tpu_t_ops_total counter" in text
    assert "# HELP deepspeed_tpu_t_ops_total ops so far" in text
    assert "# TYPE deepspeed_tpu_t_lat_seconds histogram" in text

    parsed = parse_prometheus_text(text)
    assert parsed[("deepspeed_tpu_t_ops_total",
                   (("axis", "data"), ("op", "all_reduce")))] == 5.0
    assert parsed[("deepspeed_tpu_t_ops_total",
                   (("axis", 'd,x"y'), ("op", "all_gather")))] == 2.0
    assert parsed[("deepspeed_tpu_t_util", ())] == pytest.approx(0.54)
    # histogram: cumulative buckets, +Inf == count, sum preserved
    assert parsed[("deepspeed_tpu_t_lat_seconds_bucket",
                   (("le", "0.5"),))] == 1.0
    assert parsed[("deepspeed_tpu_t_lat_seconds_bucket",
                   (("le", "1.0"),))] == 2.0
    assert parsed[("deepspeed_tpu_t_lat_seconds_bucket",
                   (("le", "+Inf"),))] == 3.0
    assert parsed[("deepspeed_tpu_t_lat_seconds_count", ())] == 3.0
    assert parsed[("deepspeed_tpu_t_lat_seconds_sum", ())] == pytest.approx(3.9)

    # file exporter writes the same bytes atomically
    path = tmp_path / "m.prom"
    PrometheusFileExporter(str(path), reg).write()
    assert parse_prometheus_text(path.read_text()) == parsed


def test_jsonl_event_schema(tmp_path):
    reg = MetricsRegistry()
    reg.gauge("deepspeed_tpu_t_v").set(1.25)
    h = reg.histogram("deepspeed_tpu_t_s_seconds")
    h.observe(0.01)
    path = tmp_path / "events.jsonl"
    w = JSONLWriter(str(path))
    w.emit("run_started", run="demo", size=3)
    w.emit_snapshot(reg, step=11)
    w.close()
    lines = [json.loads(line) for line in path.read_text().splitlines()]
    assert len(lines) == 2
    ev, snap = lines
    assert ev["kind"] == "event" and ev["name"] == "run_started"
    assert ev["run"] == "demo" and ev["size"] == 3 and "ts" in ev
    assert snap["kind"] == "snapshot" and snap["step"] == 11 and "ts" in snap
    assert snap["metrics"]["deepspeed_tpu_t_v"][0]["value"] == 1.25
    hrow = snap["metrics"]["deepspeed_tpu_t_s_seconds"][0]
    assert {"count", "sum", "p50", "p95", "p99"} <= set(hrow)
    # writes after close are dropped, not a crash
    w.emit("late")


# ----------------------------- timer sync + sink ----------------------------
def test_timer_sync_blocks_and_reports():
    from deepspeed_tpu.utils.timer import SynchronizedWallClockTimer

    seen = []
    timers = SynchronizedWallClockTimer(sink=lambda n, dt: seen.append((n, dt)))
    t = timers("fwd")
    t.start()
    x = jnp.ones((256, 256)) @ jnp.ones((256, 256))  # dispatched async work
    t.stop(sync=True)  # must block on a device sentinel, not effects_barrier
    assert not t.started and t.count == 1
    assert t.elapsed(reset=False) > 0.0
    assert len(seen) == 1 and seen[0][0] == "fwd" and seen[0][1] > 0.0
    np.asarray(x)  # keep the computation alive to its end


def test_timer_sync_uses_device_sentinel(monkeypatch):
    """The old implementation leaned on jax.effects_barrier, which does
    NOT wait on pending computations; the fix must go through a
    block_until_ready'd device sentinel instead."""
    from deepspeed_tpu.utils import timer as timer_mod

    called = {"sync": 0}
    monkeypatch.setattr(timer_mod, "_device_sync",
                        lambda: called.__setitem__("sync", called["sync"] + 1))
    t = timer_mod._Timer("x")
    t.start()
    t.stop(sync=True)
    assert called["sync"] == 1
    t.start()
    t.stop(sync=False)
    assert called["sync"] == 1  # unsynced stop stays cheap


# ----------------------------- CSV monitor handles --------------------------
def test_csv_monitor_persistent_handles(tmp_path):
    from deepspeed_tpu.monitor.monitor import CSVMonitor

    mon = CSVMonitor(str(tmp_path), "job")
    mon.write_events([("Train/loss", 1.5, 0)])
    first_handle = mon._files["Train/loss"]
    mon.write_events([("Train/loss", 1.2, 1), ("Train/loss", 1.1, 2)])
    # the handle is reused, not reopened per event
    assert mon._files["Train/loss"] is first_handle
    files = list(tmp_path.rglob("*.csv"))
    assert len(files) == 1
    rows = files[0].read_text().splitlines()
    # header exactly once, then one row per event (flushed without close)
    assert rows[0] == "step,Train/loss"
    assert len(rows) == 4
    assert sum(1 for r in rows if r.startswith("step,")) == 1
    mon.close()
    assert not mon._files
    # writing after close reopens cleanly and does NOT re-write the header
    mon.write_events([("Train/loss", 1.0, 3)])
    mon.close()
    rows = files[0].read_text().splitlines()
    assert len(rows) == 5
    assert sum(1 for r in rows if r.startswith("step,")) == 1


def test_monitor_master_close_and_registry_fanout(tmp_path):
    from deepspeed_tpu.monitor.monitor import MonitorMaster
    from deepspeed_tpu.runtime.config import DeepSpeedConfig

    cfg = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 1,
        "csv_monitor": {"enabled": True, "output_path": str(tmp_path),
                        "job_name": "j"}})
    master = MonitorMaster(cfg)
    reg = MetricsRegistry()
    reg.gauge("deepspeed_tpu_t_fanout").set(3.5)
    h = reg.histogram("deepspeed_tpu_t_fan_seconds", labelnames=("phase",))
    h.observe(0.2, phase="fwd")
    master.write_registry(reg, step=4)
    master.close()
    master.close()  # idempotent
    tags = {f.name for f in tmp_path.rglob("*.csv")}
    assert "deepspeed_tpu_t_fanout.csv" in tags
    assert any("deepspeed_tpu_t_fan_seconds" in t and "p50" in t for t in tags)


# ----------------------------- watchdog + MFU -------------------------------
def test_stall_watchdog_flags_outlier():
    reg = MetricsRegistry()
    wd = StallWatchdog(multiple=3.0, window=16, min_samples=5, name="t",
                       registry=reg)
    for _ in range(10):
        assert not wd.observe(0.1)
    assert wd.observe(1.0)  # 10x the median
    assert wd.stall_count == 1
    assert not wd.observe(0.1)  # recovery
    # the stall itself joined the window but the median is robust to it
    assert not wd.observe(0.12)
    assert reg.get("deepspeed_tpu_stall_ratio").value(loop="t") < 3.0


def test_mfu_helpers(monkeypatch):
    assert peak_flops_for_kind("TPU v4") == 275e12
    assert peak_flops_for_kind("TPU v5e") == 197e12
    assert peak_flops_for_kind("whatever") == 1e12  # cpu fallback
    monkeypatch.setenv("DSTPU_PEAK_FLOPS", "2e12")
    assert peak_flops_for_kind("TPU v4") == 2e12
    monkeypatch.delenv("DSTPU_PEAK_FLOPS")
    assert mfu(1e12, 1.0, n_chips=1, peak_flops=2e12) == 0.5
    assert mfu(1e12, 1.0, n_chips=2, peak_flops=1e12) == 0.5
    assert mfu(1e12, 0.0, peak_flops=1e12) == 0.0  # degenerate inputs


# ----------------------------- engine wiring (fast) -------------------------
def test_engine_telemetry_wiring(tmp_path):
    import deepspeed_tpu
    from tests.unit.simple_model import random_batch, simple_mlp_spec

    prom = tmp_path / "metrics.prom"
    jsonl = tmp_path / "events.jsonl"
    engine, *_ = deepspeed_tpu.initialize(
        model=simple_mlp_spec(),
        config={"train_micro_batch_size_per_gpu": 4,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "steps_per_print": 2,
                "telemetry": {"enabled": True,
                              "prometheus_path": str(prom),
                              "jsonl_path": str(jsonl),
                              "export_interval": 2}})
    # the registry is the shared process default — another telemetry-
    # enabled test's train_batches land in the same phase series, so
    # assert the DELTA this engine contributes, not the absolute count
    ph = engine.telemetry.registry.get("deepspeed_tpu_train_phase_seconds")
    ph_before = ph.count(phase="train_batch")
    for i in range(4):
        engine.train_batch(random_batch(batch_size=4, gas=1, seed=i))
    engine.close()

    reg = engine.telemetry.registry
    assert reg.get("deepspeed_tpu_train_steps_total").value() >= 4
    assert ph.count(phase="train_batch") - ph_before == 4
    assert reg.get("deepspeed_tpu_train_loss").value() > 0
    assert reg.get("deepspeed_tpu_train_samples_per_second").value() > 0
    # MFU gauge set from the XLA cost analysis fallback (no token batch)
    assert reg.get("deepspeed_tpu_train_mfu").value() > 0

    parsed = parse_prometheus_text(prom.read_text())
    assert any(n == "deepspeed_tpu_train_phase_seconds_bucket"
               for n, _l in parsed)
    lines = [json.loads(line) for line in jsonl.read_text().splitlines()]
    assert any(rec["kind"] == "snapshot" for rec in lines)


# ----------------------------- serving wiring (slow) ------------------------
@pytest.mark.slow
def test_engine_v2_serving_metrics():
    from deepspeed_tpu.inference.v2 import (InferenceEngineV2,
                                            RaggedInferenceConfig,
                                            RaggedRequest)
    from deepspeed_tpu.models.llama import llama_model
    from deepspeed_tpu.telemetry import get_registry

    reg = get_registry()
    dec = reg.histogram("deepspeed_tpu_serving_decode_seconds")
    pre = reg.histogram("deepspeed_tpu_serving_prefill_seconds")
    dec0, pre0 = dec.count(), pre.count()
    gen = reg.counter("deepspeed_tpu_serving_tokens_generated_total")
    adm = reg.counter("deepspeed_tpu_serving_prefill_admitted_tokens_total")
    gen0, adm0 = gen.value(), adm.value()

    model = llama_model("tiny", max_seq_len=64)
    eng = InferenceEngineV2(model, RaggedInferenceConfig(
        dtype="fp32", page_size=8, num_pages=16, max_seqs=2,
        max_pages_per_seq=4))
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, model.config.vocab_size, 9).tolist()
               for _ in range(2)]
    got = eng.generate_all([RaggedRequest(prompt_ids=p, max_new_tokens=3)
                            for p in prompts])
    assert all(len(v) == 3 for v in got.values())

    assert pre.count() - pre0 == 2        # one prefill per request
    assert dec.count() - dec0 >= 2        # batched decode steps
    assert gen.value() - gen0 >= 2        # decode-program tokens
    assert adm.value() - adm0 == sum(len(p) for p in prompts)
    assert reg.get("deepspeed_tpu_serving_queue_depth").value() == 0
    assert reg.get("deepspeed_tpu_serving_batch_occupancy").value() <= 1.0
    p = pre.percentiles()
    assert p["p50"] <= p["p95"] <= p["p99"]
    # cache_stats keeps its per-engine face on top of the registry
    stats = eng.cache_stats()
    assert stats["prefill_admitted_tokens"] == sum(len(p) for p in prompts)


# ----------------------------- comms busbw ----------------------------------
def test_comms_logger_bus_bandwidth():
    from deepspeed_tpu.comm.comms_logger import CommsLogger, bus_factor

    assert bus_factor("all_reduce", 8) == pytest.approx(2 * 7 / 8)
    assert bus_factor("all_gather", 8) == pytest.approx(7 / 8)
    assert bus_factor("reduce_scatter", 4) == pytest.approx(3 / 4)
    assert bus_factor("all_reduce", 1) == 0.0  # no wire traffic on 1 rank

    cl = CommsLogger(enabled=True)
    cl.append("all_reduce", "data", 1000)
    cl.append("all_reduce", "data", 1000)
    cl.append("all_gather", "model", 500)
    out = cl.log_summary(axis_sizes={"data": 8, "model": 4}, elapsed_s=2.0)
    assert "busbw GB/s" in out and "bus MB" in out
    assert "all_reduce" in out and "all_gather" in out

    reg = MetricsRegistry()
    cl.publish(reg, axis_sizes={"data": 8, "model": 4})
    ops = reg.get("deepspeed_tpu_comm_ops_total")
    byts = reg.get("deepspeed_tpu_comm_bytes_total")
    bus = reg.get("deepspeed_tpu_comm_bus_bytes_total")
    assert ops.value(op="all_reduce", axis="data") == 2
    assert byts.value(op="all_reduce", axis="data") == 2000
    assert bus.value(op="all_reduce", axis="data") == pytest.approx(
        2000 * 2 * 7 / 8)
    # re-publish without new traffic: deltas only, no double count
    cl.publish(reg, axis_sizes={"data": 8, "model": 4})
    assert ops.value(op="all_reduce", axis="data") == 2
    cl.append("all_reduce", "data", 100)
    cl.publish(reg, axis_sizes={"data": 8})
    assert byts.value(op="all_reduce", axis="data") == 2100
