"""Serving-SLO tests: admission control, deadlines, priorities,
circuit breakers, gray-failure chaos, CRC'd KV transport, and the
never-kill-a-step telemetry export guard.

Fast tier: pure policy — the admission controller's shed rules over
fake replicas, retry hints, the breaker state machine driven by hand,
the chaos injectors, wire-format CRC rejection, config validation, and
the export-failure guard.  No model steps.

Slow tier: engine-level oracles — bounded-queue rejection at put(),
deadline expiry with ``finish_reason="deadline"`` (queued AND
mid-decode), priority-ordered admission, priority preemption under
pool pressure, and a fleet whose flaky replica trips the breaker on
consecutive errors while every stream still finishes bit-identically.
"""

import time
from types import SimpleNamespace

import numpy as np
import pytest

from deepspeed_tpu.inference.v2 import (PRIORITY_BATCH,
                                        PRIORITY_INTERACTIVE,
                                        PRIORITY_NORMAL, InferenceEngineV2,
                                        RaggedInferenceConfig, RaggedRequest,
                                        RejectedError)
from deepspeed_tpu.resilience.chaos import (ChaosStepError, FlakyStep,
                                            PoolSqueeze, SlowReplica)
from deepspeed_tpu.serving import ServingConfig
from deepspeed_tpu.serving.admission import (AdmissionController,
                                             estimate_pages,
                                             retry_after_hint)
from deepspeed_tpu.serving.kv_transfer import (CorruptBundleError,
                                               bundle_from_bytes,
                                               bundle_to_bytes)
from deepspeed_tpu.serving.replica import (BREAKER_CLOSED, BREAKER_HALF_OPEN,
                                           BREAKER_OPEN, EngineReplica)


# ----------------------------- fakes ----------------------------------------
def _fake_replica(name="r0", queue_depth=0, free_pages=32, num_pages=32,
                  page_size=8):
    return SimpleNamespace(
        name=name,
        engine=SimpleNamespace(
            queue_depth=queue_depth,
            allocator=SimpleNamespace(free_pages=free_pages,
                                      num_pages=num_pages),
            block=SimpleNamespace(page_size=page_size)))


def _req(prompt=16, new=16, priority=PRIORITY_NORMAL):
    return RaggedRequest(prompt_ids=list(range(prompt)), max_new_tokens=new,
                         priority=priority)


# ----------------------------- fast: admission policy -----------------------
def test_admission_queue_bound_sheds_by_priority():
    cfg = ServingConfig(max_queue_depth=4, protect_priority=0)
    ac = AdmissionController(cfg)
    cands = [_fake_replica(queue_depth=4)]
    with pytest.raises(RejectedError) as ei:
        ac.check(_req(priority=PRIORITY_BATCH), cands)
    assert ei.value.reason == "queue_full"
    assert 0.1 <= ei.value.retry_after_s <= 30.0
    assert ei.value.priority == PRIORITY_BATCH
    # protected class rides through the same full queue
    assert ac.check(_req(priority=PRIORITY_INTERACTIVE), cands) > 0
    # under the bound: everyone admitted
    assert ac.check(_req(priority=PRIORITY_BATCH),
                    [_fake_replica(queue_depth=3)]) > 0


def test_admission_pool_pressure_uses_coolest_candidate():
    cfg = ServingConfig(shed_occupancy=0.85, protect_priority=0)
    ac = AdmissionController(cfg)
    # one hot replica, one cool: the COOL one decides -> admit
    hot = _fake_replica("hot", free_pages=0)
    cool = _fake_replica("cool", free_pages=28)
    assert ac.check(_req(priority=PRIORITY_BATCH), [hot, cool]) > 0
    with pytest.raises(RejectedError) as ei:
        ac.check(_req(priority=PRIORITY_BATCH), [hot])
    assert ei.value.reason == "pool_pressure"
    # protected priority never sheds on pool pressure either
    assert ac.check(_req(priority=PRIORITY_INTERACTIVE), [hot]) > 0


def test_admission_disabled_by_default():
    ac = AdmissionController(ServingConfig())  # both rules off
    assert ac.check(_req(priority=PRIORITY_BATCH),
                    [_fake_replica(queue_depth=10 ** 6, free_pages=0)]) > 0


def test_retry_hint_and_page_estimate():
    assert retry_after_hint(0) == 0.1
    assert retry_after_hint(10 ** 9) == 30.0
    assert retry_after_hint(10) > retry_after_hint(1)
    assert estimate_pages(16, 16, 8) == 4
    assert estimate_pages(17, 16, 8) == 5  # rounds up


def test_shed_counter_labels_by_priority():
    from deepspeed_tpu.serving.admission import shed_counter

    c = shed_counter()
    before = c.value(priority="2")
    cfg = ServingConfig(max_queue_depth=1, protect_priority=0)
    with pytest.raises(RejectedError):
        AdmissionController(cfg).check(
            _req(priority=PRIORITY_BATCH), [_fake_replica(queue_depth=1)])
    assert c.value(priority="2") == before + 1


# ----------------------------- fast: breaker state machine ------------------
def _breaker_cfg(**kw):
    base = dict(breaker_latency_factor=3.0, breaker_consec_errors=3,
                breaker_window=16, breaker_min_samples=4,
                breaker_min_latency_s=0.0, breaker_cooldown_pumps=3,
                breaker_probe_steps=2)
    base.update(kw)
    return ServingConfig(**base)


def _bare_replica(window=16):
    eng = SimpleNamespace(queue_depth=0, active_count=0,
                          allocator=SimpleNamespace(free_pages=32,
                                                    num_pages=32))
    return EngineReplica("r0", eng, breaker_window=window)


def test_breaker_latency_trip_recovery_cycle():
    cfg = _breaker_cfg()
    r = _bare_replica()
    for _ in range(6):
        r._record_step(0.100, error=False)  # sustained 100ms
    # no fleet signal -> never trips on latency alone
    assert r.breaker_eval(0.0, cfg) is None
    # fleet median 10ms, factor 3 -> 100ms trips
    assert r.breaker_eval(0.010, cfg) == "trip"
    assert r.breaker == BREAKER_OPEN and not r.accepts_new()
    # cooldown: 3 pumps to half-open
    assert r.breaker_eval(0.010, cfg) is None
    assert r.breaker_eval(0.010, cfg) is None
    assert r.breaker_eval(0.010, cfg) == "probe"
    assert r.breaker == BREAKER_HALF_OPEN and r.accepts_new()
    # window was cleared: old latencies gone
    assert r.lat_samples == 0
    # two healthy steps close it
    r._record_step(0.005, error=False)
    assert r.breaker_eval(0.010, cfg) is None
    r._record_step(0.005, error=False)
    assert r.breaker_eval(0.010, cfg) == "recover"
    assert r.breaker == BREAKER_CLOSED


def test_breaker_median_rule_ignores_spikes():
    """A one-off compile/GC spike lifts p95 but not the median — the
    breaker must NOT trip (the gray-failure rule wants SUSTAINED
    slowness)."""
    cfg = _breaker_cfg()
    r = _bare_replica()
    for _ in range(10):
        r._record_step(0.005, error=False)
    r._record_step(1.5, error=False)  # one compile spike
    assert r.step_p95() > 1.0 > 0.01 > r.step_p50()
    assert r.breaker_eval(0.005, cfg) is None
    assert r.breaker == BREAKER_CLOSED


def test_breaker_consecutive_error_trip_and_reset():
    cfg = _breaker_cfg(breaker_consec_errors=3)
    r = _bare_replica()
    r._record_step(0.01, error=True)
    r._record_step(0.01, error=True)
    r._record_step(0.01, error=False)  # healthy step resets the run
    assert r.consec_errors == 0
    assert r.breaker_eval(0.0, cfg) is None
    for _ in range(3):
        r._record_step(0.01, error=True)
    assert r.breaker_eval(0.0, cfg) == "trip"
    assert r.step_errors == 5


def test_breaker_half_open_retrip_on_errors():
    cfg = _breaker_cfg(breaker_cooldown_pumps=1)
    r = _bare_replica()
    for _ in range(3):
        r._record_step(0.01, error=True)
    assert r.breaker_eval(0.0, cfg) == "trip"
    assert r.breaker_eval(0.0, cfg) == "probe"
    for _ in range(3):  # probe traffic still failing
        r._record_step(0.01, error=True)
    assert r.breaker_eval(0.0, cfg) == "trip"
    assert r.breaker == BREAKER_OPEN


def test_breaker_intermittent_errors_trip_majority_window():
    """A replica failing every other step never runs up consec_errors
    and its ~0s error returns must not drag p50 down — the majority-
    erroring window rule catches the intermittent-fault profile."""
    cfg = _breaker_cfg(breaker_consec_errors=3, breaker_min_samples=4)
    r = _bare_replica()
    for _ in range(4):
        r._record_step(0.000001, error=True)   # fast failures
        r._record_step(0.010, error=False)
    assert r.consec_errors == 0
    # error steps stayed out of the latency window
    assert r.step_p50() == pytest.approx(0.010, abs=1e-3)
    assert r.breaker_eval(0.0, cfg) == "trip"


def test_breaker_half_open_single_error_retrips():
    """Docs contract: ANY error during the half-open probe re-trips —
    interleaved healthy steps must not let a flaky replica 'recover'."""
    cfg = _breaker_cfg(breaker_cooldown_pumps=1, breaker_probe_steps=2,
                       breaker_consec_errors=3)
    r = _bare_replica()
    for _ in range(3):
        r._record_step(0.01, error=True)
    assert r.breaker_eval(0.0, cfg) == "trip"
    assert r.breaker_eval(0.0, cfg) == "probe"
    r._record_step(0.01, error=False)
    r._record_step(0.01, error=True)   # one probe error
    r._record_step(0.01, error=False)  # healthy steps don't save it
    r._record_step(0.01, error=False)
    assert r.breaker_eval(0.0, cfg) == "trip"
    assert r.breaker == BREAKER_OPEN


def test_breaker_half_open_still_slow_retrips_not_recovers():
    """A persistently slow (error-free) replica must RE-TRIP at the
    half-open decision point, not recover and flap: the probe steps are
    the latency evidence even though they are fewer than
    breaker_min_samples."""
    cfg = _breaker_cfg(breaker_cooldown_pumps=1, breaker_probe_steps=2,
                       breaker_min_samples=8, breaker_window=16)
    r = _bare_replica()
    for _ in range(8):
        r._record_step(0.100, error=False)
    assert r.breaker_eval(0.010, cfg) == "trip"
    assert r.breaker_eval(0.010, cfg) == "probe"
    r._record_step(0.100, error=False)  # probe traffic: still 10x slow
    assert r.breaker_eval(0.010, cfg) is None  # probe not complete yet
    r._record_step(0.100, error=False)
    assert r.breaker_eval(0.010, cfg) == "trip"
    assert r.breaker == BREAKER_OPEN
    # ...whereas a probe at healthy speed recovers as before
    assert r.breaker_eval(0.010, cfg) == "probe"
    r._record_step(0.008, error=False)
    r._record_step(0.008, error=False)
    assert r.breaker_eval(0.010, cfg) == "recover"


def test_breaker_health_surface():
    r = _bare_replica()
    r._record_step(0.004, error=False)
    h = r.health()
    assert h["breaker"] == "closed" and h["step_errors"] == 0
    assert h["step_p50_s"] == pytest.approx(0.004, abs=1e-3)


# ----------------------------- fast: chaos injectors ------------------------
def test_flaky_step_deterministic_then_clean():
    hook = FlakyStep(fail_steps=2, seed=3)
    for _ in range(2):
        with pytest.raises(ChaosStepError):
            hook()
    hook()  # passes afterwards
    assert (hook.calls, hook.raised) == (3, 2)
    # seeded probabilistic mode replays identically
    a = FlakyStep(fail_steps=0, p=0.5, seed=11)
    b = FlakyStep(fail_steps=0, p=0.5, seed=11)

    def trace(h):
        out = []
        for _ in range(20):
            try:
                h()
                out.append(0)
            except ChaosStepError:
                out.append(1)
        return out

    assert trace(a) == trace(b) and sum(trace(FlakyStep(0, p=0.5, seed=11)))


def test_slow_replica_injects_latency():
    hook = SlowReplica(delay_s=0.02, seed=0)
    t0 = time.perf_counter()
    hook()
    assert time.perf_counter() - t0 >= 0.015
    assert hook.calls == 1


def test_pool_squeeze_holds_and_releases():
    from deepspeed_tpu.inference.v2 import BlockAllocator

    alloc = BlockAllocator(16)
    eng = SimpleNamespace(allocator=alloc)
    with PoolSqueeze(eng, 10) as sq:
        assert sq.pages == 10 and alloc.free_pages == 6
    assert alloc.free_pages == 16
    # over-asking clamps to what is truly free
    sq = PoolSqueeze(eng, 99)
    assert sq.pages == 16 and alloc.free_pages == 0
    sq.release()
    assert alloc.free_pages == 16


# ----------------------------- fast: CRC'd wire format ----------------------
def _bundle(n_pages=3, ps=4):
    from deepspeed_tpu.inference.v2 import KVPageBundle

    rng = np.random.RandomState(0)
    arrays = {"k": rng.randn(2, n_pages, ps, 1, 2).astype(np.float32),
              "v": rng.randn(2, n_pages, ps, 1, 2).astype(np.float32)}
    return KVPageBundle(
        uid=7, tokens=list(range(ps * n_pages - 1)),
        prompt_len=ps * (n_pages - 1), max_new_tokens=8, temperature=0.0,
        eos_id=None, prefilled=ps * n_pages - 2, decode_entry=False,
        page_size=ps, page_keys=[b"\x01" * 32, b"\x02" * 32],
        src_pages=[{"page": i, "refcount": 1, "key": None}
                   for i in range(n_pages)],
        arrays=arrays, model_sig=(2, 1, 2), kv_quant=False, dtype="fp32",
        priority=PRIORITY_BATCH, deadline=time.perf_counter() + 60.0)


def test_bundle_crc_roundtrip_carries_slo_identity():
    b = _bundle()
    rt = bundle_from_bytes(bundle_to_bytes(b))
    for leaf in b.arrays:
        assert np.array_equal(rt.arrays[leaf], b.arrays[leaf])
    assert rt.priority == PRIORITY_BATCH
    # deadline re-based as seconds-left: still in the future, ~60s out
    assert 50.0 < rt.deadline - time.perf_counter() <= 60.5
    # no deadline stays no deadline
    b2 = _bundle()
    b2.deadline = 0.0
    assert bundle_from_bytes(bundle_to_bytes(b2)).deadline == 0.0


def test_bundle_bitflip_rejected_naming_page():
    data = bytearray(bundle_to_bytes(_bundle()))
    data[-3] ^= 0x10  # payload tail = last page of leaf "v"
    with pytest.raises(CorruptBundleError, match=r"CRC32 mismatch.*\[2\]"):
        bundle_from_bytes(bytes(data))


def test_bundle_truncation_and_version_rejected():
    data = bundle_to_bytes(_bundle())
    with pytest.raises(CorruptBundleError, match="truncated"):
        bundle_from_bytes(data[:-10])
    with pytest.raises(CorruptBundleError, match="truncated"):
        bundle_from_bytes(data[:10])
    old = b"DSTPUKV1" + data[8:]
    with pytest.raises(CorruptBundleError, match="retired wire version"):
        bundle_from_bytes(old)
    with pytest.raises(CorruptBundleError, match="bad magic"):
        bundle_from_bytes(b"garbage!" + data[8:])


# ----------------------------- fast: export never kills a step --------------
def test_telemetry_export_failures_counted_not_raised():
    from deepspeed_tpu.telemetry import Telemetry
    from deepspeed_tpu.telemetry.registry import MetricsRegistry

    reg = MetricsRegistry()
    tm = Telemetry(None, registry=reg)

    class _Broken:
        def write(self):
            raise OSError("disk full")

        def emit_snapshot(self, *a, **kw):
            raise OSError("disk full")

        def close(self):
            raise OSError("disk full")

    tm.prom_file = _Broken()
    tm.jsonl = _Broken()
    tm.export(1, force=True)  # must NOT raise
    tm.export(2, force=True)
    c = reg.get("deepspeed_tpu_telemetry_export_failures_total")
    assert c.value(sink="prometheus_file") == 2
    assert c.value(sink="jsonl") == 2
    tm.close()  # broken close paths counted too, still no raise
    assert c.value(sink="prometheus_file") == 3


# ----------------------------- fast: config + request surface ---------------
def test_serving_config_slo_validation():
    ServingConfig(max_queue_depth=8, shed_occupancy=0.9,
                  breaker_latency_factor=2.5).validate()
    with pytest.raises(ValueError):
        ServingConfig(shed_occupancy=1.5).validate()
    with pytest.raises(ValueError):
        ServingConfig(breaker_latency_factor=1.0).validate()
    with pytest.raises(ValueError):
        ServingConfig(breaker_min_samples=64, breaker_window=8).validate()
    with pytest.raises(ValueError):
        ServingConfig(max_queue_depth=-1).validate()
    # ds-config style parse picks the new knobs up
    cfg = ServingConfig.from_dict({"max_queue_depth": 6,
                                   "shed_occupancy": 0.8,
                                   "breaker_consec_errors": 5})
    assert (cfg.max_queue_depth, cfg.shed_occupancy,
            cfg.breaker_consec_errors) == (6, 0.8, 5)


def test_request_slo_defaults():
    r = RaggedRequest(prompt_ids=[1, 2])
    assert r.priority == PRIORITY_NORMAL and r.deadline_s is None
    e = RejectedError("test", retry_after_s=2.5, priority=1)
    assert e.retry_after_s == 2.5 and "retry after 2.50s" in str(e)


# ----------------------------- slow: engine oracles -------------------------
@pytest.fixture(scope="module")
def tiny_model():
    import jax

    from deepspeed_tpu.models.llama import llama_model

    model = llama_model("tiny", max_seq_len=128)
    return model, model.init_params(jax.random.PRNGKey(0))


def _engine(model, params, **kw):
    cfg = RaggedInferenceConfig(dtype="fp32", page_size=8, num_pages=64,
                                max_seqs=4, max_pages_per_seq=12, **kw)
    return InferenceEngineV2(model, cfg, params=params)


def _prompt(n, seed=0, vocab=256):
    return list(np.random.RandomState(seed).randint(0, vocab, n))


@pytest.mark.slow
def test_engine_bounded_queue_rejects(tiny_model):
    from deepspeed_tpu.serving.admission import shed_counter

    model, params = tiny_model
    eng = _engine(model, params, max_queue_depth=2)
    eng.put(RaggedRequest(prompt_ids=_prompt(10), max_new_tokens=4))
    eng.put(RaggedRequest(prompt_ids=_prompt(10, 1), max_new_tokens=4))
    s0 = shed_counter().total()
    with pytest.raises(RejectedError) as ei:
        eng.put(RaggedRequest(prompt_ids=_prompt(10, 2), max_new_tokens=4,
                              priority=PRIORITY_BATCH))
    assert ei.value.reason == "engine_queue_full"
    assert ei.value.retry_after_s > 0
    assert shed_counter().total() == s0 + 1
    # multi-candidate placers (the fleet router) own shed accounting:
    # a refusal with record_shed=False raises but counts NOTHING
    with pytest.raises(RejectedError):
        eng.put(RaggedRequest(prompt_ids=_prompt(10, 3), max_new_tokens=4,
                              priority=PRIORITY_BATCH), record_shed=False)
    assert shed_counter().total() == s0 + 1
    # queue drains -> accepts again
    for _ in range(30):
        if not eng.has_work():
            break
        eng.step()
    eng.put(RaggedRequest(prompt_ids=_prompt(10, 2), max_new_tokens=4))
    eng.close()


@pytest.mark.slow
def test_engine_deadline_expiry_queued_and_mid_decode(tiny_model):
    from deepspeed_tpu.telemetry import get_registry

    model, params = tiny_model
    c = get_registry().get(
        "deepspeed_tpu_serving_slo_deadline_exceeded_total")
    eng = _engine(model, params)
    d0 = c.total()
    # (1) queued request with an exhausted budget: expires before admission
    u1 = eng.put(RaggedRequest(prompt_ids=_prompt(10), max_new_tokens=8,
                               deadline_s=0.0))
    out = eng.step()
    assert out[u1] == {"tokens": [], "done": True,
                      "finish_reason": "deadline"}
    assert c.total() == d0 + 1
    # (2) mid-decode: admit with a live budget, then let it run out
    u2 = eng.put(RaggedRequest(prompt_ids=_prompt(10, 1), max_new_tokens=20,
                               deadline_s=60.0))
    for _ in range(3):
        eng.step()
    seq = eng._find_slotted(u2)
    assert 0 < seq.generated < 20
    seq.deadline = time.perf_counter() - 1.0  # budget exhausted mid-stream
    out = eng.step()
    assert out[u2]["done"] and out[u2]["finish_reason"] == "deadline"
    assert c.total() == d0 + 2
    eng.assert_no_leaks()
    assert not eng.has_work()
    eng.close()


@pytest.mark.slow
def test_engine_priority_orders_admission(tiny_model):
    model, params = tiny_model
    cfg = RaggedInferenceConfig(dtype="fp32", page_size=8, num_pages=64,
                                max_seqs=1, max_pages_per_seq=12)
    eng = InferenceEngineV2(model, cfg, params=params)
    lo = eng.put(RaggedRequest(prompt_ids=_prompt(10), max_new_tokens=4,
                               priority=PRIORITY_BATCH))
    hi = eng.put(RaggedRequest(prompt_ids=_prompt(10, 1), max_new_tokens=4,
                               priority=PRIORITY_INTERACTIVE))
    got = {}

    def pump():
        for u, rec in eng.step().items():
            got.setdefault(u, []).extend(rec["tokens"])

    pump()
    # one slot: the LATER-submitted interactive request got it
    assert eng._find_slotted(hi).uid == hi
    assert [s.uid for s in eng._queue] == [lo]
    # FCFS within a class: both streams still complete
    for _ in range(40):
        if not eng.has_work():
            break
        pump()
    assert len(got[lo]) == 4 and len(got[hi]) == 4
    eng.close()


@pytest.mark.slow
def test_engine_priority_preempts_batch_under_pool_pressure(tiny_model):
    from deepspeed_tpu.telemetry import get_registry

    model, params = tiny_model
    cfg = RaggedInferenceConfig(dtype="fp32", page_size=8, num_pages=6,
                                max_seqs=2, max_pages_per_seq=6)
    eng = InferenceEngineV2(model, cfg, params=params)
    pre = get_registry().get("deepspeed_tpu_serving_preemptions_total")
    p0 = pre.total()
    got = {}

    def pump():
        for u, rec in eng.step().items():
            got.setdefault(u, []).extend(rec["tokens"])

    lo = eng.put(RaggedRequest(prompt_ids=_prompt(32), max_new_tokens=16,
                               priority=PRIORITY_BATCH))  # 4 of 6 pages
    pump()
    assert eng._find_slotted(lo).uid == lo
    hi = eng.put(RaggedRequest(prompt_ids=_prompt(20, 1), max_new_tokens=8,
                               priority=PRIORITY_INTERACTIVE))  # needs 3
    pump()
    # the batch sequence was evicted to make room for the interactive one
    assert pre.total() == p0 + 1
    assert eng._find_slotted(hi).uid == hi
    assert lo in [s.uid for s in eng._queue]
    # both still finish (batch re-prefills after the interactive frees)
    for _ in range(80):
        if not eng.has_work():
            break
        pump()
    assert len(got[hi]) == 8 and len(got[lo]) == 16
    eng.assert_no_leaks()
    eng.close()


@pytest.mark.slow
def test_decode_pool_pressure_never_evicts_more_urgent(tiny_model):
    """Mid-decode page exhaustion: a batch sequence needing its next KV
    page must self-preempt rather than evict a running interactive
    sequence (the decode-path mirror of the admission victim rule)."""
    model, params = tiny_model
    cfg = RaggedInferenceConfig(dtype="fp32", page_size=8, num_pages=4,
                                max_seqs=2, max_pages_per_seq=4)
    eng = InferenceEngineV2(model, cfg, params=params)
    got, hi_done = {}, False
    lo = eng.put(RaggedRequest(prompt_ids=_prompt(15), max_new_tokens=10,
                               priority=PRIORITY_BATCH))
    for u, rec in eng.step().items():  # admit the batch sequence alone
        got.setdefault(u, []).extend(rec["tokens"])
    hi = eng.put(RaggedRequest(prompt_ids=_prompt(9, 1), max_new_tokens=10,
                               priority=PRIORITY_INTERACTIVE))
    for _ in range(160):
        if not eng.has_work():
            break
        for u, rec in eng.step().items():
            got.setdefault(u, []).extend(rec["tokens"])
            if u == hi and rec.get("done"):
                hi_done = True
        if (not hi_done and got.get(hi)
                and eng._find_slotted(hi) is None):
            raise AssertionError(
                "interactive sequence was evicted by batch work")
    assert len(got[hi]) == 10 and len(got[lo]) == 10
    eng.assert_no_leaks()
    eng.close()


@pytest.mark.slow
def test_fleet_submit_failure_leaves_no_ghost_record(tiny_model):
    """A submit() that fails for a non-shed reason (e.g. prompt too
    long for the engine) must not leave a done=False record wedging
    has_work() True forever."""
    from deepspeed_tpu.serving import build_fleet

    model, params = tiny_model
    base = RaggedInferenceConfig(dtype="fp32", page_size=8, num_pages=64,
                                 max_seqs=4, max_pages_per_seq=12)
    serving = ServingConfig(enabled=True, prefill_replicas=1,
                            decode_replicas=1, disaggregated=True,
                            prefill_chunk=8)
    fleet = build_fleet(model, serving, engine_config=base, params=params)
    with pytest.raises(ValueError):
        fleet.submit(RaggedRequest(prompt_ids=_prompt(500),
                                   max_new_tokens=4))
    assert not fleet.has_work()
    # the fleet still serves normally afterwards
    u = fleet.submit(RaggedRequest(prompt_ids=_prompt(12), max_new_tokens=4))
    while fleet.has_work():
        fleet.step()
    assert len(fleet.request_state(u)["emitted"]) == 4


@pytest.mark.slow
def test_fleet_flaky_replica_trips_breaker_streams_bit_identical(tiny_model):
    from deepspeed_tpu.serving import build_fleet
    from deepspeed_tpu.telemetry import get_registry

    model, params = tiny_model
    base = RaggedInferenceConfig(dtype="fp32", page_size=8, num_pages=64,
                                 max_seqs=4, max_pages_per_seq=12)
    serving = ServingConfig(enabled=True, prefill_replicas=1,
                            decode_replicas=2, disaggregated=True,
                            prefill_chunk=8, breaker_consec_errors=3,
                            breaker_cooldown_pumps=50)
    fleet = build_fleet(model, serving, engine_config=base, params=params)
    reqs = [RaggedRequest(prompt_ids=_prompt(18 + i, seed=i),
                          max_new_tokens=8) for i in range(4)]
    ctl = InferenceEngineV2(model, base, params=params)
    want = ctl.generate_all([RaggedRequest(prompt_ids=list(r.prompt_ids),
                                           max_new_tokens=8) for r in reqs])
    want = [want[u] for u in sorted(want)]
    uids = [fleet.submit(r) for r in reqs]
    for _ in range(100):  # get streams onto the decode pool
        fleet.step()
        if any((fleet.request_state(u)["replica"] or "").startswith("decode")
               for u in uids):
            break
    victim = next(n for n, r in fleet.replicas.items()
                  if n.startswith("decode")
                  and any(fleet.request_state(u)["replica"] == n
                          for u in uids))
    trips = get_registry().get(
        "deepspeed_tpu_serving_slo_breaker_trips_total")
    t0 = trips.total()
    fleet.replicas[victim].inject_chaos(FlakyStep(fail_steps=3, seed=0))
    for _ in range(300):
        if not fleet.has_work():
            break
        fleet.step()
    assert fleet.replicas[victim].breaker == BREAKER_OPEN
    assert trips.total() == t0 + 1
    got = [fleet.request_state(u)["emitted"] for u in uids]
    assert got == want  # bit-identical through the gray failure
    assert all(not fleet.request_state(u)["failed"] for u in uids)
    ctl.close()
