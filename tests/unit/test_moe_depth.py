"""MoE depth tests: dropless routing, grouped matmul, PR-MoE residual
(reference moe/layer.py:17 use_residual, sharded_moe.py drop_tokens)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # multi-minute integration tier

import deepspeed_tpu
from deepspeed_tpu.moe.sharded_moe import (MoEConfig, _gate_and_aux, moe_ffn,
                                           moe_ffn_dropless)
from deepspeed_tpu.ops.pallas.grouped_matmul import grouped_matmul


def test_grouped_matmul_parity():
    rng = np.random.RandomState(0)
    E, H, F, BS = 3, 32, 48, 8
    x = jnp.asarray(rng.randn(5 * BS, H).astype(np.float32))
    w = jnp.asarray(rng.randn(E, H, F).astype(np.float32))
    be = jnp.asarray([0, 2, 1, 1, 0], jnp.int32)
    ref = jnp.concatenate([x[i * BS:(i + 1) * BS] @ w[int(be[i])]
                           for i in range(5)])
    for impl in ("xla", "pallas"):
        y = grouped_matmul(x, w, be, block_rows=BS, impl=impl)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5, err_msg=impl)


def _naive_moe(x, gate_w, experts, cfg, activation="gelu"):
    """Per-token loop: out[t] = sum_k gate[t,k] * FFN_{e}(x[t]) — the exact
    semantics drop_tokens=False must reproduce."""
    B, S, H = x.shape
    xt = np.asarray(x.reshape(-1, H), np.float64)
    logits = jnp.asarray(xt, jnp.float32) @ gate_w
    gates, expert_idx, gate_k, aux = _gate_and_aux(logits, cfg)
    expert_idx, gate_k = np.asarray(expert_idx), np.asarray(gate_k, np.float64)
    out = np.zeros_like(xt)
    for t in range(xt.shape[0]):
        for k in range(cfg.top_k):
            e = int(expert_idx[t, k])
            up = np.asarray(experts["w_up"][e], np.float64)
            down = np.asarray(experts["w_down"][e], np.float64)
            if activation == "swiglu":
                g = np.asarray(experts["w_gate"][e], np.float64)
                h = (xt[t] @ g) * (1 / (1 + np.exp(-(xt[t] @ g)))) * (xt[t] @ up)
            else:
                z = xt[t] @ up
                h = 0.5 * z * (1 + np.tanh(np.sqrt(2 / np.pi) * (z + 0.044715 * z**3)))
            out[t] += gate_k[t, k] * (h @ down)
    return out.reshape(B, S, H), float(aux)


@pytest.mark.parametrize("topk", [1, 2])
def test_dropless_matches_per_token_semantics(topk):
    """drop_tokens=False processes EVERY token through its top-k experts —
    exact match with the per-token loop (no capacity, no drops)."""
    rng = np.random.RandomState(1)
    B, S, H, F, E = 2, 6, 16, 24, 4
    x = jnp.asarray(rng.randn(B, S, H).astype(np.float32))
    gate_w = jnp.asarray(rng.randn(H, E).astype(np.float32))
    experts = {"w_up": jnp.asarray(rng.randn(E, H, F).astype(np.float32) * 0.3),
               "w_down": jnp.asarray(rng.randn(E, F, H).astype(np.float32) * 0.3)}
    cfg = MoEConfig(num_experts=E, top_k=topk, drop_tokens=False)
    out, aux = moe_ffn_dropless(x, gate_w, experts, cfg, activation="gelu",
                                block_rows=8)
    ref, ref_aux = _naive_moe(x, gate_w, experts, cfg, "gelu")
    np.testing.assert_allclose(np.asarray(out, np.float64), ref,
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(float(aux), ref_aux, rtol=1e-5)


def test_dropless_no_tokens_dropped_under_pressure():
    """The capacity path drops under load imbalance; dropless must not:
    route everything to one expert and check the output is still the full
    FFN for every token."""
    rng = np.random.RandomState(2)
    B, S, H, F, E = 1, 16, 8, 12, 4
    x = jnp.asarray(rng.randn(B, S, H).astype(np.float32))
    gate_w = jnp.zeros((H, E), jnp.float32).at[:, 0].set(10.0)  # all -> e0
    experts = {"w_up": jnp.asarray(rng.randn(E, H, F).astype(np.float32) * 0.3),
               "w_down": jnp.asarray(rng.randn(E, F, H).astype(np.float32) * 0.3)}
    ncfg = MoEConfig(num_experts=E, top_k=1, drop_tokens=False)
    dcfg = MoEConfig(num_experts=E, top_k=1, drop_tokens=True,
                     capacity_factor=0.25, min_capacity=1)
    out_nd, _ = moe_ffn(x, gate_w, experts, ncfg, activation="gelu")
    out_drop, _ = moe_ffn(x, gate_w, experts, dcfg, activation="gelu")
    ref, _ = _naive_moe(x, gate_w, experts, ncfg, "gelu")
    np.testing.assert_allclose(np.asarray(out_nd, np.float64), ref,
                               rtol=1e-4, atol=1e-4)
    # sanity: the capacity path really dropped (outputs zero for overflow)
    dropped = np.mean(np.all(np.asarray(out_drop) == 0, axis=-1))
    assert dropped > 0.5, "capacity path should have dropped tokens here"


def test_prmoe_residual_trains(devices8):
    """PR-MoE: residual dense MLP + learned coefficient beside the MoE
    (reference moe/layer.py use_residual); params exist and the model
    trains with the dropless path."""
    from deepspeed_tpu.models.mixtral import mixtral_model

    from deepspeed_tpu.parallel.mesh import MeshConfig, initialize_topology

    initialize_topology(MeshConfig(data=2, expert=4), jax.devices()[:8])
    model = mixtral_model("tiny", max_seq_len=16, moe_use_residual=True,
                          moe_drop_tokens=False, attn_impl="xla")
    params = model.init_params(jax.random.PRNGKey(0))
    assert "res_w_up" in params["layers"]["mlp"]
    assert "coef" in params["layers"]["mlp"]

    engine, *_ = deepspeed_tpu.initialize(
        model=model,
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 3e-3}},
                "zero_optimization": {"stage": 1},
                "mesh": {"data": 2, "expert": 4}},
        topology=deepspeed_tpu.get_topology())
    r = np.random.RandomState(0)
    fixed = [jnp.asarray(r.randint(0, 256, (1, 8, 16)).astype(np.int32))
             for _ in range(2)]
    losses = [float(engine.train_batch({"input_ids": fixed[i % 2]}))
              for i in range(14)]
    assert np.isfinite(losses).all()
    assert np.mean(losses[-4:]) < np.mean(losses[:4]), losses


def test_shared_expert_trains_and_gets_grads(devices8):
    """qwen2-moe shared expert: always-on branch beside the routed MoE;
    grads must flow into shared weights AND its sigmoid gate."""
    import deepspeed_tpu
    from deepspeed_tpu.models.mixtral import mixtral_config, mixtral_model
    from deepspeed_tpu.parallel.mesh import MeshConfig, initialize_topology

    initialize_topology(MeshConfig(expert=2, data=-1), jax.devices()[:8])
    cfg = mixtral_config("tiny", max_seq_len=16, attn_impl="xla",
                         moe_drop_tokens=False, moe_shared_expert=48,
                         moe_norm_topk=False)
    engine, *_ = deepspeed_tpu.initialize(
        model=mixtral_model(config=cfg),
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "AdamW", "params": {"lr": 3e-3}},
                "zero_optimization": {"stage": 1},
                "mesh": {"expert": 2, "data": -1}},
        topology=deepspeed_tpu.get_topology())
    before = np.asarray(
        engine.state.params["layers"]["mlp"]["shared_w_down"]).copy()
    r = np.random.RandomState(0)
    corpus = r.randint(0, cfg.vocab_size, (4, 8, 16)).astype(np.int32)
    losses = [float(engine.train_batch({"input_ids": jnp.asarray(corpus[i % 4][None])}))
              for i in range(12)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0]
    after = np.asarray(engine.state.params["layers"]["mlp"]["shared_w_down"])
    assert np.abs(after - before).max() > 0, "shared expert never updated"
