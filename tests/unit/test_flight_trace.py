"""Span tracing, flight recorder, and recompile sentinel tests.

Covers: the span ring + Chrome-trace schema (the Perfetto-required
``ph/ts/dur/pid/tid/name`` keys), cross-step begin/end spans, the
flight recorder's JSONL dump (manual, watchdog-trip, and
exception-in-step triggers), recompile-counter semantics on a forced
shape change (monitoring and shape-fallback modes, steady-state
detection), TTFT/TPOT histogram wiring in ``InferenceEngineV2``, and
the log-level env override.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.telemetry import (FlightRecorder, MetricsRegistry,
                                     RecompileSentinel, SpanRecorder,
                                     get_span_recorder,
                                     install_flight_recorder,
                                     set_span_recorder, trace_dump)

TRACE_EVENT_KEYS = ("ph", "ts", "dur", "pid", "tid", "name")


@pytest.fixture
def fresh_spans():
    """Install a fresh default span recorder; restore the old one."""
    old = get_span_recorder()
    rec = SpanRecorder(ring_size=256)
    set_span_recorder(rec)
    yield rec
    set_span_recorder(old)


@pytest.fixture
def fresh_registry():
    """Install a fresh default registry so engines constructed here do
    not pollute the shared process registry other tests assert absolute
    counts against (and vice versa)."""
    from deepspeed_tpu.telemetry import get_registry, set_registry

    old = get_registry()
    reg = MetricsRegistry()
    set_registry(reg)
    yield reg
    set_registry(old)


# ----------------------------- span ring + Chrome schema --------------------
def test_chrome_trace_schema_round_trip(tmp_path, fresh_spans):
    rec = fresh_spans
    with rec.span("loading", cat="demo", shard=3):
        pass
    h = rec.begin("request", cat="serve", uid=7)
    rec.event("admit", cat="serve", uid=7, cache_hit_pages=2)
    rec.end(h, generated=5)

    path = trace_dump(str(tmp_path / "trace.json"))
    doc = json.load(open(path))
    assert "traceEvents" in doc and doc["displayTimeUnit"] == "ms"
    events = doc["traceEvents"]
    assert len(events) == 3
    for ev in events:
        for k in TRACE_EVENT_KEYS:
            assert k in ev, f"missing Perfetto key {k} in {ev}"
        assert ev["ph"] == "X"
        assert isinstance(ev["ts"], (int, float))
        assert isinstance(ev["dur"], (int, float)) and ev["dur"] >= 0
        assert isinstance(ev["pid"], int) and isinstance(ev["tid"], int)
    by_name = {ev["name"]: ev for ev in events}
    assert by_name["loading"]["args"]["shard"] == 3
    assert by_name["admit"]["dur"] == 0.0  # point event
    req = by_name["request"]
    assert req["args"]["uid"] == 7 and req["args"]["generated"] == 5
    # the request began before the admit event and spans past it
    assert req["ts"] <= by_name["admit"]["ts"] <= req["ts"] + req["dur"]


def test_span_ring_is_bounded_and_togglable():
    rec = SpanRecorder(ring_size=32)
    for i in range(100):
        rec.event("tick", i=i)
    spans = rec.spans()
    assert len(spans) == 32
    assert rec.dropped == 100 - 32
    assert spans[-1].attrs["i"] == 99  # newest kept, oldest dropped
    rec.configure(enabled=False)
    rec.event("tock")
    with rec.span("quiet"):
        pass
    assert len(rec.spans()) == 32  # nothing recorded while disabled
    assert rec.begin("open") is None
    rec.end(None)  # no-op, not a crash
    rec.clear()
    assert rec.spans() == [] and rec.dropped == 0


def test_phase_timer_records_span(fresh_spans):
    from deepspeed_tpu.telemetry.tracing import PhaseTimer

    seen = []
    with PhaseTimer("decode", sink=lambda n, dt: seen.append((n, dt)),
                    batch=4):
        pass
    assert len(seen) == 1 and seen[0][0] == "decode"
    spans = fresh_spans.spans()
    assert len(spans) == 1
    sp = spans[0]
    assert sp.name == "decode" and sp.cat == "phase"
    assert sp.attrs["batch"] == 4
    assert sp.dur_us == pytest.approx(seen[0][1] * 1e6, rel=0.5)


# ----------------------------- flight recorder ------------------------------
def test_flight_dump_contents(tmp_path, fresh_spans):
    reg = MetricsRegistry()
    reg.gauge("deepspeed_tpu_t_flight_v").set(2.5)
    fr = FlightRecorder(path=str(tmp_path), max_events=16, registry=reg)
    with fresh_spans.span("step", step=3):
        pass
    fr.note("loss_spike", step=3, loss=9.9)
    path = fr.dump(reason="manual")
    recs = [json.loads(line) for line in open(path)]
    assert recs[0]["kind"] == "flight_header"
    assert recs[0]["reason"] == "manual" and recs[0]["spans"] == 1
    kinds = [r["kind"] for r in recs]
    assert kinds.count("span") == 1 and kinds.count("log") == 1
    sp = next(r for r in recs if r["kind"] == "span")
    assert sp["name"] == "step" and sp["args"]["step"] == 3
    log = next(r for r in recs if r["kind"] == "log")
    assert log["name"] == "loss_spike" and log["loss"] == 9.9
    snap = recs[-1]
    assert snap["kind"] == "snapshot"
    assert snap["metrics"]["deepspeed_tpu_t_flight_v"][0]["value"] == 2.5
    # the dump itself is counted (trigger = text before the colon)
    assert reg.get("deepspeed_tpu_flight_dumps_total").value(
        trigger="manual") == 1
    # log-event ring is bounded
    for i in range(40):
        fr.note("e", i=i)
    recs = [json.loads(line) for line in open(fr.dump(reason="again"))]
    assert sum(1 for r in recs if r["kind"] == "log") == 16


def test_watchdog_trip_dumps_flight(tmp_path, fresh_spans):
    from deepspeed_tpu.runtime.config import TelemetryConfig
    from deepspeed_tpu.telemetry import Telemetry

    cfg = TelemetryConfig.from_dict({
        "enabled": True,
        "flight_recorder": {"path": str(tmp_path / "fl")},
        "stall_watchdog": {"enabled": True, "multiple": 2.0, "window": 8},
    })
    tm = Telemetry(cfg, loop="train", registry=MetricsRegistry())
    try:
        for step in range(6):
            assert not tm.observe_step_time(0.01, step)
        assert tm.observe_step_time(1.0, step=6)  # 100x the median: stall
        dumps = list((tmp_path / "fl").glob("flight_*watchdog*.jsonl"))
        assert len(dumps) == 1
        recs = [json.loads(line) for line in open(dumps[0])]
        assert recs[0]["reason"] == "watchdog:train"
        # the stall note itself rode along in the event ring
        assert any(r.get("name") == "stall" and r.get("step") == 6
                   for r in recs if r["kind"] == "log")
        # sustained stall: no second dump until the incident clears
        tm.observe_step_time(1.0, step=7)
        assert len(list((tmp_path / "fl").glob("flight_*.jsonl"))) == 1
    finally:
        tm.close()


def test_exception_in_train_step_dumps(tmp_path, fresh_spans, monkeypatch, fresh_registry):
    import deepspeed_tpu
    from tests.unit.simple_model import random_batch, simple_mlp_spec

    engine, *_ = deepspeed_tpu.initialize(
        model=simple_mlp_spec(),
        config={"train_micro_batch_size_per_gpu": 4,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "telemetry": {"enabled": True,
                              "flight_recorder": {"path": str(tmp_path)}}})
    try:
        engine.train_batch(random_batch(batch_size=4, gas=1, seed=0))

        def boom(*a, **k):
            raise RuntimeError("device on fire")

        monkeypatch.setattr(engine, "_train_batch", boom)
        with pytest.raises(RuntimeError, match="device on fire"):
            engine.train_batch(random_batch(batch_size=4, gas=1, seed=1))
        dumps = list(tmp_path.glob("flight_*exception*.jsonl"))
        assert len(dumps) == 1
        recs = [json.loads(line) for line in open(dumps[0])]
        assert recs[0]["reason"] == "exception:engine.train_batch"
        # the black box carries the healthy step's span and a snapshot
        assert any(r["kind"] == "span" and r["name"] == "train_batch"
                   for r in recs)
        assert recs[-1]["kind"] == "snapshot" and recs[-1]["metrics"]
    finally:
        engine.close()


def test_exception_in_serving_step_dumps(tmp_path, fresh_spans, monkeypatch, fresh_registry):
    from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                      RaggedInferenceConfig,
                                                      RaggedRequest)
    from deepspeed_tpu.models.llama import llama_model

    fr = FlightRecorder(path=str(tmp_path), registry=MetricsRegistry())
    install_flight_recorder(fr)
    try:
        eng = InferenceEngineV2(
            llama_model("tiny", max_seq_len=64),
            RaggedInferenceConfig(dtype="fp32", page_size=8, num_pages=16,
                                  max_seqs=2, max_pages_per_seq=4))
        eng.put(RaggedRequest(prompt_ids=[1, 2, 3], max_new_tokens=2))

        def boom():
            raise RuntimeError("kv pool corrupt")

        monkeypatch.setattr(eng, "_step_impl", boom)
        with pytest.raises(RuntimeError, match="kv pool corrupt"):
            eng.step()
        dumps = list(tmp_path.glob("flight_*exception*.jsonl"))
        assert len(dumps) == 1
        assert json.loads(open(dumps[0]).readline())["reason"] == \
            "exception:engine_v2.step"
    finally:
        install_flight_recorder(None)


# ----------------------------- recompile sentinel ---------------------------
def test_recompile_counter_on_forced_shape_change(fresh_spans):
    from deepspeed_tpu.compile.backend import shape_signature

    reg = MetricsRegistry()
    s = RecompileSentinel(loop="t1", registry=reg, steady_after=3)
    f = jax.jit(lambda x: x * 2 + 1)

    x3 = jnp.asarray(np.ones(3, np.float32))
    f(x3).block_until_ready()
    sig3 = shape_signature(x3)
    assert s.observe_step([("f", sig3)], step=0)  # first compile: expected
    for step in range(1, 4):
        f(x3).block_until_ready()
        assert not s.observe_step([("f", sig3)], step=step)  # cache hits
    assert s.recompiles == 1

    # forced shape change: exactly one more recompiled step, not flagged
    # as steady-state (the signature component is new)
    x5 = jnp.asarray(np.ones(5, np.float32))
    f(x5).block_until_ready()
    assert s.observe_step([("f", shape_signature(x5))], step=4)
    assert s.recompiles == 2
    assert s.steady_recompiles == 0
    # the recompile left a point event in the trace ring
    names = [sp.name for sp in fresh_spans.spans()]
    assert "recompile" in names


def test_recompile_sentinel_steady_state_warn(fresh_spans, caplog):
    reg = MetricsRegistry()
    s = RecompileSentinel(loop="t2", registry=reg, steady_after=2)
    sig = [("step", ((4,), "float32"))]
    x = jnp.asarray(np.ones(4, np.float32))
    f = jax.jit(lambda v: v + 1)
    f(x).block_until_ready()
    s.observe_step(sig, step=0)
    for step in range(1, 4):  # steady: no compiles, same signature
        s.observe_step(sig, step=step)
    if not s.monitoring:
        pytest.skip("jax.monitoring unavailable: steady-state recompiles "
                    "are not detectable in fallback mode")
    # a compile fires with UNCHANGED shapes after >= steady_after steps
    g = jax.jit(lambda v: v - 1)
    g(x).block_until_ready()
    assert s.observe_step(sig, step=4)
    assert s.steady_recompiles == 1
    # the WORST pathology — recompiling every step with unchanged shapes
    # — must keep counting (the steady window tracks steps since the
    # last shape change, not since the last recompile)
    g2 = jax.jit(lambda v: v * 5)
    g2(x).block_until_ready()
    assert s.observe_step(sig, step=5)
    assert s.steady_recompiles == 2
    # an ANNOUNCED re-jit with the same signature is not flagged
    h = jax.jit(lambda v: v * 3)
    for step in range(6, 9):
        s.observe_step(sig, step=step)
    s.expect_recompile("test_rebuild")
    h(x).block_until_ready()
    assert s.observe_step(sig, step=9)
    assert s.steady_recompiles == 2


def test_recompile_single_attribution_across_sentinels(fresh_spans):
    """Compiles are a process-wide stream: the first observing sentinel
    claims them; a co-located loop must not count the same compile."""
    reg = MetricsRegistry()
    a = RecompileSentinel(loop="ta", registry=reg, steady_after=99)
    b = RecompileSentinel(loop="tb", registry=reg, steady_after=99)
    if not a.monitoring:
        pytest.skip("jax.monitoring unavailable: claim path inactive")
    a.observe_step(["drain"], step=-1)  # absorb any stray compiles
    a0, b0 = a.recompiles, b.recompiles
    x = jnp.asarray(np.ones(6, np.float32))
    f = jax.jit(lambda v: v + 7)
    f(x).block_until_ready()
    assert a.observe_step(["p"], step=0)      # first observer claims it
    assert not b.observe_step(["p"], step=0)  # nothing left to claim
    assert a.recompiles - a0 == 1 and b.recompiles - b0 == 0


def test_recompile_sentinel_shape_fallback():
    """Without jax.monitoring, a never-seen signature counts as the
    compile signal (compile/backend.py arg-shape fallback)."""
    reg = MetricsRegistry()
    s = RecompileSentinel(loop="t3", registry=reg, steady_after=2)
    s.monitoring = False  # force the fallback path
    assert s.observe_step([("p", (8,))], step=0)
    assert not s.observe_step([("p", (8,))], step=1)
    assert s.observe_step([("p", (16,))], step=2)  # new bucket
    assert not s.observe_step([("p", (8,)), ("p", (16,))], step=3)  # both seen
    assert s.recompiles == 2 and s.steady_recompiles == 0


# ----------------------------- serving TTFT/TPOT + request spans ------------
def test_engine_v2_ttft_tpot_and_request_spans(fresh_spans, fresh_registry):
    from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                      RaggedInferenceConfig,
                                                      RaggedRequest)
    from deepspeed_tpu.models.llama import llama_model
    from deepspeed_tpu.telemetry import get_registry

    reg = get_registry()
    ttft = reg.histogram("deepspeed_tpu_serving_ttft_seconds")
    tpot = reg.histogram("deepspeed_tpu_serving_tpot_seconds")
    ttft0, tpot0 = ttft.count(), tpot.count()

    model = llama_model("tiny", max_seq_len=64)
    eng = InferenceEngineV2(model, RaggedInferenceConfig(
        dtype="fp32", page_size=8, num_pages=16, max_seqs=2,
        max_pages_per_seq=4))
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, model.config.vocab_size, 9).tolist()
               for _ in range(2)]
    got = eng.generate_all([RaggedRequest(prompt_ids=p, max_new_tokens=3)
                            for p in prompts])
    assert all(len(v) == 3 for v in got.values())

    assert ttft.count() - ttft0 == 2  # one TTFT per request
    assert tpot.count() - tpot0 == 2  # >1 token each -> one TPOT each
    assert ttft.sum() > 0 and tpot.sum() >= 0
    # request spans closed with the generation count; admit events inside
    spans = fresh_spans.spans()
    reqs = [sp for sp in spans if sp.name == "request"]
    assert len(reqs) == 2
    assert all(sp.attrs["generated"] == 3 for sp in reqs)
    admits = [sp for sp in spans if sp.name == "admit"]
    assert len(admits) == 2 and all(sp.dur_us == 0.0 for sp in admits)
    assert {sp.attrs["uid"] for sp in reqs} == \
        {sp.attrs["uid"] for sp in admits}
    assert eng._req_meta == {}  # all lifecycle state reclaimed


# ----------------------------- satellites -----------------------------------
def test_log_level_env_override(monkeypatch):
    import logging

    from deepspeed_tpu.utils.logging import _env_log_level

    monkeypatch.delenv("DEEPSPEED_TPU_LOG_LEVEL", raising=False)
    monkeypatch.delenv("DSTPU_LOG_LEVEL", raising=False)
    assert _env_log_level() == logging.INFO
    monkeypatch.setenv("DSTPU_LOG_LEVEL", "warning")
    assert _env_log_level() == logging.WARNING
    # the spelled-out name wins over the short one
    monkeypatch.setenv("DEEPSPEED_TPU_LOG_LEVEL", "debug")
    assert _env_log_level() == logging.DEBUG
    monkeypatch.setenv("DEEPSPEED_TPU_LOG_LEVEL", "not-a-level")
    assert _env_log_level() == logging.INFO


def test_log_dist_carries_rank(caplog):
    from deepspeed_tpu.utils.logging import log_dist, logger

    logger.propagate = True
    try:
        with caplog.at_level("INFO", logger="DeepSpeedTPU"):
            log_dist("attributable message", ranks=[-1])
    finally:
        logger.propagate = False
    assert any("[Rank 0] attributable message" in r.message
               for r in caplog.records)


def test_flops_profiler_publishes_gauges(monkeypatch, fresh_registry):
    import deepspeed_tpu
    from deepspeed_tpu.telemetry import get_registry
    from tests.unit.simple_model import random_batch, simple_mlp_spec

    engine, *_ = deepspeed_tpu.initialize(
        model=simple_mlp_spec(),
        config={"train_micro_batch_size_per_gpu": 4,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "flops_profiler": {"enabled": True, "profile_step": 1}})
    try:
        for i in range(2):
            engine.train_batch(random_batch(batch_size=4, gas=1, seed=i))
        reg = get_registry()
        assert reg.get("deepspeed_tpu_profile_params").value() > 0
        assert reg.get("deepspeed_tpu_profile_flops_per_micro_step").value() > 0
        assert reg.get("deepspeed_tpu_profile_achieved_tflops").value() >= 0
    finally:
        engine.close()


def test_engine_close_emits_comms_summary(monkeypatch, fresh_registry):
    import deepspeed_tpu
    from deepspeed_tpu import comm
    from tests.unit.simple_model import random_batch, simple_mlp_spec

    engine, *_ = deepspeed_tpu.initialize(
        model=simple_mlp_spec(),
        config={"train_micro_batch_size_per_gpu": 4,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "comms_logger": {"enabled": True}})
    cl = comm.get_comms_logger()
    cl.append("all_reduce", "data", 4096)  # give the summary content
    calls = []
    monkeypatch.setattr(type(cl), "log_summary",
                        lambda self, **kw: calls.append(kw) or "")
    engine.train_batch(random_batch(batch_size=4, gas=1, seed=0))
    engine.close()
    engine.close()  # idempotent: summary exactly once
    assert len(calls) == 1
    assert calls[0]["axis_sizes"] == engine.topology.axis_sizes
