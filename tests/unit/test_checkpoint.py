"""Partitioned / universal checkpoint tests
(reference tests/unit/checkpoint/: save->load->compare roundtrips incl.
layout changes)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.checkpoint.partitioned import (load_universal, to_universal,
                                                  zero_to_fp32)
from deepspeed_tpu.runtime.checkpoint_engine.engines import (
    DecoupledCheckpointEngine, FastCheckpointEngine, NumpyCheckpointEngine)
from tests.unit.simple_model import random_batch, simple_mlp_spec


def _engine(stage=3, mesh=None):
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": stage},
    }
    if mesh:
        cfg["mesh"] = mesh
    engine, *_ = deepspeed_tpu.initialize(model=simple_mlp_spec(), config=cfg)
    return engine


def _params_equal(a, b):
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_allclose(np.asarray(jax.device_get(x)),
                                                np.asarray(jax.device_get(y)),
                                                rtol=1e-6), a, b)


def test_partitioned_roundtrip_sharded(tmp_path, devices8):
    e1 = _engine(stage=3)
    for i in range(3):
        e1.train_batch(random_batch(batch_size=8, seed=i, gas=1))
    e1.save_checkpoint(str(tmp_path), partitioned=True)
    files = os.listdir(tmp_path / "global_step3")
    assert any(f.startswith("zero_shard_rank_") for f in files)

    e2 = _engine(stage=3)
    path, _ = e2.load_checkpoint(str(tmp_path))
    assert path is not None
    assert e2.global_steps == 3
    _params_equal(e1.state.params, e2.state.params)
    e2.train_batch(random_batch(batch_size=8, gas=1))


def test_partitioned_reshard_stage3_to_stage0(tmp_path, devices8):
    e1 = _engine(stage=3)
    e1.train_batch(random_batch(batch_size=8, gas=1))
    e1.save_checkpoint(str(tmp_path), partitioned=True)

    e0 = _engine(stage=0)
    e0.load_checkpoint(str(tmp_path))
    _params_equal(e1.state.params, e0.state.params)


def test_partitioned_reshard_across_mesh(tmp_path, devices8):
    e1 = _engine(stage=2, mesh={"data": 8})
    e1.train_batch(random_batch(batch_size=8, gas=1))
    e1.save_checkpoint(str(tmp_path), partitioned=True)

    from deepspeed_tpu.parallel import mesh as mesh_mod

    mesh_mod.reset_topology()
    e2 = _engine(stage=3, mesh={"data": 4, "model": 2})
    e2.load_checkpoint(str(tmp_path))
    _params_equal(e1.state.params, e2.state.params)


def test_universal_conversion_and_load(tmp_path, devices8):
    e1 = _engine(stage=3)
    e1.train_batch(random_batch(batch_size=8, gas=1))
    e1.save_checkpoint(str(tmp_path / "ckpt"), partitioned=True)

    out = to_universal(str(tmp_path / "ckpt"), "global_step1",
                       str(tmp_path / "universal"))
    assert os.path.exists(os.path.join(out, "universal_meta.json"))

    e2 = _engine(stage=0)
    load_universal(e2, out)
    _params_equal(e1.state.params, e2.state.params)
    assert e2.global_steps == 1


def test_zero_to_fp32_export(tmp_path, devices8):
    e1 = _engine(stage=3)
    e1.train_batch(random_batch(batch_size=8, gas=1))
    e1.save_checkpoint(str(tmp_path / "c"), partitioned=True)
    out = zero_to_fp32(str(tmp_path / "c"), "global_step1",
                       str(tmp_path / "fp32.npz"))
    data = np.load(out)
    assert any("params" in k for k in data.files)
    w = [data[k] for k in data.files if "layer_0" in k and "/w" in k.replace("']['", "/")]
    assert w, f"missing layer_0 w in {data.files}"


def test_fast_checkpoint_engine_roundtrip(tmp_path):
    ce = FastCheckpointEngine(thread_count=2)
    arrays = {"a": np.arange(1000, dtype=np.float32).reshape(10, 100),
              "b": np.ones(7, np.int32)}
    ce.save(arrays, str(tmp_path / "fast"))
    out = ce.load(str(tmp_path / "fast"))
    np.testing.assert_array_equal(out["a"], arrays["a"])
    np.testing.assert_array_equal(out["b"], arrays["b"])


def test_decoupled_engine_commits_in_background(tmp_path):
    ce = DecoupledCheckpointEngine()
    arrays = {"x": np.random.RandomState(0).randn(256, 256).astype(np.float32)}
    ce.save(arrays, str(tmp_path / "async_ckpt.npz"))
    assert ce.commit("tag")
    out = NumpyCheckpointEngine().load(str(tmp_path / "async_ckpt.npz"))
    np.testing.assert_array_equal(out["x"], arrays["x"])


def test_async_save_config_roundtrip(tmp_path, devices8):
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "checkpoint": {"async_save": True},
    }
    e1, *_ = deepspeed_tpu.initialize(model=simple_mlp_spec(), config=cfg)
    e1.train_batch(random_batch(batch_size=8, gas=1))
    e1.save_checkpoint(str(tmp_path), partitioned=True)
    e2, *_ = deepspeed_tpu.initialize(model=simple_mlp_spec(), config=cfg)
    e2.load_checkpoint(str(tmp_path))
    _params_equal(e1.state.params, e2.state.params)
