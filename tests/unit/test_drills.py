"""In-process smoke tests for the chaos/fleet drill entrypoints.

The drills are acceptance gates (``--demo`` must exit 0 on CPU) but
used to live outside CI entirely — a refactor could bit-rot them and
nobody would notice until the next manual run.  These slow-marked tests
call each tool's ``main()`` **in-process** (entrypoint call, not
subprocess) so a broken import, flag, or drill leg fails tier-"slow"
loudly, with the real traceback.

The drills themselves still spawn ElasticAgent subprocesses internally
(the chaos kill leg ``os._exit``s an *attempt*, never this process).
"""

import importlib.util
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _load_tool(name):
    path = os.path.join(REPO, "tools", f"{name}.py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules.setdefault(name, mod)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.slow
def test_fleet_drill_demo_inprocess(tmp_path):
    drill = _load_tool("fleet_drill")
    out = str(tmp_path / "fleet")
    rc = drill.main(["--demo", "--out", out, "--seed", "7"])
    assert rc == 0
    summary = json.load(open(os.path.join(out, "fleet_drill.json")))
    assert summary["ok"] and summary["seed"] == 7
    failed = [c for c in summary["checks"] if not c["ok"]]
    assert not failed, failed
    # the overload/SLO legs actually ran (not silently skipped)
    names = {c["check"] for c in summary["checks"]}
    for leg in ("overload_sheds_only_low_priority",
                "deadlines_fire_with_finish_reason",
                "slow_replica_breaker_tripped",
                "breaker_recovered_via_half_open_probe",
                "slow_leg_bit_identical_to_single_engine"):
        assert leg in names, f"missing drill leg {leg}"


@pytest.mark.slow
def test_chaos_drill_demo_inprocess(tmp_path):
    drill = _load_tool("chaos_drill")
    out = str(tmp_path / "chaos")
    rc = drill.main(["--demo", "--out", out, "--seed", "0"])
    assert rc == 0
    summary = json.load(open(os.path.join(out, "chaos_drill.json")))
    assert summary["ok"] and summary["seed"] == 0
    failed = [c for c in summary["checks"] if not c["ok"]]
    assert not failed, failed
    # the goodput leg actually ran (kill→resume recompute attributed to
    # restart badput; union-of-attempts matches the control)
    names = {c["check"] for c in summary["checks"]}
    for leg in ("goodput_recompute_attributed_to_restart",
                "goodput_union_matches_control"):
        assert leg in names, f"missing drill leg {leg}"


@pytest.mark.slow
def test_goodput_report_demo_inprocess(tmp_path):
    report = _load_tool("goodput_report")
    out = str(tmp_path / "goodput")
    rc = report.main(["--demo", "--out", out, "--steps", "8"])
    assert rc == 0
    summary = json.load(open(os.path.join(out, "goodput_report.json")))
    assert summary["ok"]
    failed = [c for c in summary["checks"] if not c["ok"]]
    assert not failed, failed
    # the hard gates actually ran (not silently skipped)
    names = {c["check"] for c in summary["checks"]}
    for gate in ("categories_sum_to_wall", "measured_flag_honest",
                 "buckets_sum_to_lifetime", "goodput_fraction_above_floor",
                 "chrome_trace_parses"):
        assert gate in names, f"missing gate {gate}"
