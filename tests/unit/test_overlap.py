"""Compute/collective overlap tests (runtime/zero/overlap.py,
comm/collectives/bucketer.py, telemetry/overlap.py; docs/COMM.md
"Overlap & scheduling").

Fast tier: the bucketer as a pure function, the plan builder, the
exposure accounting math, the latency-hiding flag helpers, and the
``grad-overlap`` lint rule.  Slow tier (engine oracles, like
test_zeropp): bit-exact loss parity of the overlap scheduling knobs at
ZeRO 1 and 3 — with and without int8 compression — plus the in-loop
collective structure in compiled HLO.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.collectives.bucketer import (assign_buckets,
                                                     coalesce_flat,
                                                     leaf_bytes, split_flat)
from deepspeed_tpu.models.llama import llama_model
from deepspeed_tpu.parallel.mesh import MeshConfig, initialize_topology

SEQ = 16
VOCAB = 64


def _engine(zero_extra, mesh=None, n_layers=4, **model_over):
    model = llama_model("tiny", max_seq_len=SEQ, vocab_size=VOCAB,
                        n_layers=n_layers, attn_impl="xla", **model_over)
    mesh = mesh or {"data": 8}
    initialize_topology(MeshConfig(**mesh), jax.devices()[:8])
    cfg = {"train_micro_batch_size_per_gpu": 4,
           "gradient_accumulation_steps": 1,
           "optimizer": {"type": "Adam", "params": {"lr": 5e-3}},
           "zero_optimization": dict(zero_extra),
           "mesh": mesh}
    return deepspeed_tpu.initialize(
        model=model, config=cfg, topology=deepspeed_tpu.get_topology())[0]


def _ids(n, seed=0):
    return jnp.asarray(np.random.RandomState(seed).randint(
        0, VOCAB, (1, n, SEQ)).astype(np.int32))


def _losses(engine, steps=4, bs=8):
    return [float(engine.train_batch({"input_ids": _ids(bs, seed=i)}))
            for i in range(steps)]


# --------------------------------------------------------------- bucketer
def test_assign_buckets_properties():
    """Deterministic, order-stable, size-bounded, exhaustive."""
    sizes = [100, 50, 900, 10, 10, 500, 2000, 1]
    buckets = assign_buckets(sizes, 1000)
    # same input -> same output (pure function of the flatten order)
    assert buckets == assign_buckets(sizes, 1000)
    # covers every index exactly once, in order
    flat = [i for b in buckets for i in b]
    assert flat == list(range(len(sizes)))
    # size bound: a bucket closes once it reaches the target, so no
    # bucket exceeds target + its last (largest-possible) leaf
    for b in buckets:
        total = sum(sizes[i] for i in b)
        assert total < 1000 + max(sizes) or len(b) == 1
    # bucket_bytes <= 0 -> per-leaf (the pre-bucketing behavior)
    assert assign_buckets(sizes, 0) == [[i] for i in range(len(sizes))]
    assert assign_buckets([], 1000) == []


def test_coalesce_split_roundtrip():
    rng = np.random.RandomState(0)
    leaves = [jnp.asarray(rng.randn(4, 6).astype(np.float32)),
              jnp.asarray(rng.randn(7).astype(np.float32)),
              jnp.asarray(rng.randn(2, 3, 5).astype("bfloat16"))]
    flat, layout = coalesce_flat(leaves)
    assert flat.dtype == jnp.float32
    assert flat.size == sum(l.size for l in leaves)
    back = split_flat(flat, layout, [l.dtype for l in leaves])
    for a, b in zip(leaves, back):
        assert a.shape == b.shape and a.dtype == b.dtype
        np.testing.assert_array_equal(np.asarray(a, np.float32),
                                      np.asarray(b, np.float32))
    assert leaf_bytes(jnp.zeros((3, 4), jnp.bfloat16)) == 24


# ---------------------------------------------------------- flag helpers
def test_latency_hiding_flag_helpers():
    from deepspeed_tpu.compile.backend import (LATENCY_HIDING_FLAGS,
                                               latency_hiding_flag_status,
                                               parse_xla_flags,
                                               pin_latency_hiding_flags)

    env = {"XLA_FLAGS": "--xla_force_host_platform_device_count=8"}
    st = latency_hiding_flag_status(env)
    assert all(v == "missing" for v in st.values())
    added = pin_latency_hiding_flags(env)
    assert len(added) == len(LATENCY_HIDING_FLAGS)
    assert all(v == "pinned"
               for v in latency_hiding_flag_status(env).values())
    # idempotent; explicit operator overrides are reported, never clobbered
    assert pin_latency_hiding_flags(env) == []
    flag = next(iter(LATENCY_HIDING_FLAGS))
    env2 = {"XLA_FLAGS": f"{flag}=false"}
    assert latency_hiding_flag_status(env2)[flag] == "overridden=false"
    pin_latency_hiding_flags(env2)
    assert parse_xla_flags(env2["XLA_FLAGS"])[flag] == "false"


def test_bench_flag_copy_in_sync():
    """bench.py's parent deliberately never imports the package, so it
    carries a copy of the flag set — this pin keeps the copies equal."""
    import importlib.util
    import os

    from deepspeed_tpu.compile.backend import LATENCY_HIDING_FLAGS

    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    src = open(os.path.join(root, "bench.py")).read()
    for flag, val in LATENCY_HIDING_FLAGS.items():
        assert f'"{flag}"' in src, f"bench.py lost pinned flag {flag}"


# ------------------------------------------------------------- plan build
def test_overlap_plan_build_and_struct(devices8):
    e = _engine({"stage": 1, "overlap_grad_reduce": True})
    plan = e._overlap_plan
    assert plan is not None
    # every layer leaf assigned to exactly one bucket, in order
    n = len(plan.paths)
    assert sorted(i for b in plan.buckets for i in b) == list(range(n))
    assert all(d is None for d in plan.gather_dims)  # stage 1: no gathers
    struct = e._overlap_struct
    assert struct["overlapped_bytes"] > 0
    assert struct["total_bytes"] > struct["overlapped_bytes"]  # embed tail
    rep = e.overlap_report()
    assert 0.0 < rep.overlapped_fraction < 1.0
    assert rep.buckets == len(plan.buckets)
    assert rep.exposed_seconds_per_step > 0

    # bucket_mb=0 -> per-leaf buckets
    e0 = _engine({"stage": 1, "overlap_grad_reduce": True,
                  "overlap_bucket_mb": 0})
    assert len(e0._overlap_plan.buckets) == len(e0._overlap_plan.paths)


def test_overlap_plan_stage3_gather_dims(devices8):
    e = _engine({"stage": 3, "overlap_grad_reduce": True})
    plan = e._overlap_plan
    assert plan is not None
    # the big matmul leaves must enter the body as ZeRO shards with an
    # explicit gather dim; their in-body spec shards exactly that dim
    gathered = [d for d in plan.gather_dims if d is not None]
    assert len(gathered) >= 7, plan.gather_dims
    for spec, d in zip(plan.leaf_specs, plan.gather_dims):
        if d is not None:
            assert tuple(spec)[d] == "data"


def test_overlap_disabled_reasons(devices8):
    # qgZ + overlap now COMPOSES (compressed overlap, docs/COMM.md):
    # the wrap takes the exchange with int8 + EF in-loop...
    e = _engine({"stage": 1, "overlap_grad_reduce": True,
                 "zero_quantized_gradients": True})
    assert e._overlap_plan is not None
    assert e._overlap_plan.compression is not None
    assert e._overlap_plan.error_feedback
    assert "overlap" in e.state.comm_errors
    # ...unless overlap_compression=False forces the exact wrap, which
    # stands down under qgZ exactly as before (the reducers own it)
    e0 = _engine({"stage": 1, "overlap_grad_reduce": True,
                  "zero_quantized_gradients": True,
                  "overlap_compression": False})
    assert e0._overlap_plan is None
    assert e0._overlap_struct["overlapped_bytes"] == 0
    # non-transformer models have no hook point
    from deepspeed_tpu.analysis.contracts import _mlp_spec

    initialize_topology(MeshConfig(data=8), jax.devices()[:8])
    e2, *_ = deepspeed_tpu.initialize(model=_mlp_spec(), config={
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 1, "overlap_grad_reduce": True}})
    assert e2._overlap_plan is None and e2._overlap_struct is None


# ------------------------------------------------------------- accounting
def test_overlap_reports():
    from deepspeed_tpu.telemetry.overlap import (interconnect_bytes_per_s,
                                                 report_from_spans,
                                                 structural_report)

    struct = {"total_bytes": 1000, "overlapped_bytes": 900, "buckets": 3}
    rep = structural_report(struct, world=8, device_kind="cpu")
    assert rep.overlapped_fraction == pytest.approx(0.9)
    assert rep.exposed_bytes == 100
    # bus factor 2(n-1)/n for all_reduce over the nominal cpu bandwidth
    assert rep.exposed_seconds_per_step == pytest.approx(
        100 * 2 * 7 / 8 / interconnect_bytes_per_s("cpu"))
    assert structural_report(struct, world=1) is None
    assert structural_report(None, world=8) is None

    # span-derived view: bucket events dedupe by index across retraces
    from deepspeed_tpu.telemetry.spans import SpanRecorder

    rec = SpanRecorder()
    for _trace in range(2):
        rec.event("grad_bucket_reduce", cat="comm", bytes=450, bucket=0,
                  overlapped=True)
        rec.event("grad_bucket_reduce", cat="comm", bytes=450, bucket=1,
                  overlapped=True)
        rec.event("grad_tail_reduce", cat="comm", bytes=100,
                  overlapped=False)
    rep2 = report_from_spans(rec, world=8, device_kind="cpu")
    assert rep2.total_bytes == 1000 and rep2.overlapped_bytes == 900
    assert rep2.buckets == 2
    assert report_from_spans(SpanRecorder(), world=8) is None


# -------------------------------------------------------------- lint rule
def test_grad_overlap_lint_rule(tmp_path):
    import os

    from deepspeed_tpu.analysis import lint

    rel = os.path.join("deepspeed_tpu", "runtime", "zero", "zeropp.py")
    bad = tmp_path / "zeropp.py"
    bad.write_text(
        "def quantized_grad_reduce(grads, specs, mesh):\n"
        "    return [reduce_one(g) for g in grads]\n")
    out = lint.scan_file(str(bad), rel)
    assert any(v.rule == "grad-overlap" and "monolithic" in v.message
               for v in out), out
    # the compressed in-loop reducer has the same contract: a rewrite
    # that quantizes + reduces leaf-by-leaf without the shared bucketer
    # (a monolithic quantized reduce reappearing) fails BY NAME
    rel_ov = os.path.join("deepspeed_tpu", "runtime", "zero", "overlap.py")
    bad_ov = tmp_path / "overlap.py"
    bad_ov.write_text(
        "def _compressed_bucket_reduce(leaves, error, spec, axis, inner):\n"
        "    return [quantized_all_reduce(l, spec) for l in leaves], None\n")
    out_ov = lint.scan_file(str(bad_ov), rel_ov)
    assert any(v.rule == "grad-overlap" and "quantized" in v.message
               for v in out_ov), out_ov
    # the real tree is clean (also enforced package-wide by tier-1's
    # dstpu_lint run; this pins the rule itself)
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    for r in (rel, rel_ov):
        real = lint.scan_file(os.path.join(root, r), r)
        assert not [v for v in real if v.rule == "grad-overlap"]


# -------------------------------------------------- engine oracles (slow)
@pytest.mark.slow
def test_overlap_bit_exact_and_parity_zero1(devices8):
    """The overlap scheduling knobs are pure scheduling: bucketed ==
    unbucketed BIT-EXACT.  vs the legacy GSPMD step the wrap pins a
    canonical per-shard summation order, so parity is reassociation-
    sized (GSPMD's own strategy already differs between stages at
    HEAD)."""
    l_off = _losses(_engine({"stage": 1}))
    l_on = _losses(_engine({"stage": 1, "overlap_grad_reduce": True}))
    l_unb = _losses(_engine({"stage": 1, "overlap_grad_reduce": True,
                             "overlap_bucket_mb": 0}))
    assert l_on == l_unb, "bucketing changed the math"
    for a, b in zip(l_off, l_on):
        assert abs(a - b) / max(abs(a), 1e-9) < 1e-4, (l_off, l_on)
    assert l_on[0] == l_off[0], "forward pass must be bit-identical"


@pytest.mark.slow
def test_overlap_bit_exact_zero3_and_prefetch(devices8):
    l_on = _losses(_engine({"stage": 3, "overlap_grad_reduce": True}))
    l_pf = _losses(_engine({"stage": 3, "overlap_grad_reduce": True,
                            "zero3_param_prefetch": True}))
    assert l_on == l_pf, "the 2x-unrolled prefetch changed the math"
    l_off = _losses(_engine({"stage": 3}))
    for a, b in zip(l_off, l_on):
        assert abs(a - b) / max(abs(a), 1e-9) < 1e-4, (l_off, l_on)


@pytest.mark.slow
def test_overlap_bit_exact_with_int8_qgz(devices8):
    """With qgZ + overlap_compression=False the explicit bucketed
    reducers own the exchange and the wrap stands down — the overlap
    flag must not change a single bit on that arm.  The DEFAULT compose
    (compressed overlap) is covered by test_compressed_overlap_*."""
    z = {"stage": 1, "zero_quantized_gradients": True}
    l_off = _losses(_engine(dict(z)))
    l_on = _losses(_engine(dict(z, overlap_grad_reduce=True,
                                overlap_compression=False)))
    assert l_on == l_off


@pytest.mark.slow
def test_overlap_stands_down_for_qwz_stage3(devices8):
    e = _engine({"stage": 3, "zero_quantized_weights": True,
                 "overlap_grad_reduce": True})
    assert e._overlap_plan is None  # qwZ owns the stage-3 gathers
    ls = _losses(e)
    assert np.isfinite(ls).all()


def _hlo_of(e, bs=8):
    with e.topology.mesh:
        return e._train_batch.lower(
            e.state, {"input_ids": _ids(bs)}, jax.random.PRNGKey(0)
        ).compile().as_text()


def _loop_collectives(hlo):
    """{kind: (in_loop, top_level)} by reachability from while bodies."""
    comps, name = {}, None
    for ln in hlo.splitlines():
        m = re.match(r"^(?:ENTRY )?%?([\w\.\-]+) \(.*\{", ln)
        if m:
            name = m.group(1)
            comps[name] = []
        if name:
            comps[name].append(ln)
    bodies = set(re.findall(r"body=%([\w\.\-]+)", hlo))
    reach = set(bodies)
    frontier = list(bodies)
    while frontier:
        c = frontier.pop()
        joined = "\n".join(comps.get(c, []))
        for o in comps:
            if o not in reach and re.search(
                    rf"%{re.escape(o)}(?![\w.\-])", joined):
                reach.add(o)
                frontier.append(o)
    out = {}
    for kind in ("all-reduce", "all-gather", "reduce-scatter"):
        inside = outside = 0
        for k, v in comps.items():
            t = "\n".join(v)
            c = len(re.findall(
                rf"=\s*(?:\([^()]*\)|\S+)\s+{kind}(?:-start)?\(", t))
            if k in reach:
                inside += c
            else:
                outside += c
        out[kind] = (inside, outside)
    return out


@pytest.mark.slow
def test_overlap_in_loop_collective_structure(devices8):
    """THE tentpole property: the grad exchange rides the layer loops.
    Stage 1: one explicit psum per layer leaf inside the backward scan
    (the off arm reduces the stacked grads at top level).  Stage 3: the
    wrap's explicit reduce-scatters and all-gathers live in the loops;
    the off arm has no reduce-scatter anywhere."""
    on1 = _loop_collectives(_hlo_of(_engine(
        {"stage": 1, "overlap_grad_reduce": True})))
    # >= one in-loop all-reduce per layer leaf (9 on this llama block)
    assert on1["all-reduce"][0] >= 9, on1

    e3 = _engine({"stage": 3, "overlap_grad_reduce": True,
                  "zero3_param_prefetch": True})
    on3 = _loop_collectives(_hlo_of(e3))
    off3 = _loop_collectives(_hlo_of(_engine({"stage": 3})))
    assert on3["reduce-scatter"][0] > 0, on3
    assert on3["reduce-scatter"][1] == 0, on3  # none escape the loops
    assert on3["all-gather"][0] > 0, on3
    assert off3["reduce-scatter"] == (0, 0), off3


@pytest.mark.slow
def test_overlap_gauges_and_events(devices8):
    """Boundary telemetry: the overlapped-fraction gauge and the
    exposure counter publish, and the span ring carries the bucket /
    tail collective events the accountant reads."""
    from deepspeed_tpu.telemetry.spans import (SpanRecorder,
                                               set_span_recorder)

    rec = SpanRecorder()
    set_span_recorder(rec)
    try:
        model = llama_model("tiny", max_seq_len=SEQ, vocab_size=VOCAB,
                            n_layers=2, attn_impl="xla")
        initialize_topology(MeshConfig(data=8), jax.devices()[:8])
        engine, *_ = deepspeed_tpu.initialize(
            model=model,
            config={"train_micro_batch_size_per_gpu": 1,
                    "gradient_accumulation_steps": 1,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "zero_optimization": {"stage": 1,
                                          "overlap_grad_reduce": True},
                    "steps_per_print": 1,
                    "telemetry": {"enabled": True}},
            topology=deepspeed_tpu.get_topology())
        engine.train_batch({"input_ids": _ids(8)})
        assert 0.0 < engine._m_overlap_frac.value() < 1.0
        assert engine._m_exposed.value() > 0
        names = {sp.name for sp in rec.spans()}
        assert "grad_bucket_reduce" in names
        assert "grad_tail_reduce" in names
        from deepspeed_tpu.telemetry.overlap import report_from_spans

        rep = report_from_spans(rec, world=8)
        assert rep is not None and 0.0 < rep.overlapped_fraction < 1.0
        engine.close()
    finally:
        set_span_recorder(None)


@pytest.mark.slow
def test_bucketed_all_reduce_one_residual_per_bucket(devices8):
    """comm/collectives.bucketed_all_reduce: leaves coalesce into flat
    buckets — one collective chain and ONE error-feedback residual per
    bucket — and the reduced values match the exact mean within codec
    tolerance."""
    from jax.sharding import Mesh, PartitionSpec as P

    from deepspeed_tpu.comm.collectives import (CompressionSpec,
                                                bucketed_all_reduce)
    from deepspeed_tpu.utils.jax_compat import shard_map

    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
    rng = np.random.RandomState(0)
    # ~3 leaves / ~two buckets at a 4 KiB target
    leaves = [rng.randn(8, 16, 16).astype(np.float32),
              rng.randn(8, 7).astype(np.float32),
              rng.randn(8, 33).astype(np.float32)]
    spec = CompressionSpec(format="int8", error_feedback=True)

    def body(*shards):
        outs, errs = bucketed_all_reduce(
            [s[0] for s in shards], op="mean", axis="data", spec=spec,
            bucket_bytes=1 << 10)
        return tuple(outs) + tuple(e[None] for e in errs)

    n_buckets = 2
    fn = shard_map(
        body, mesh=mesh,
        in_specs=tuple(P("data") for _ in leaves),
        out_specs=tuple(P() for _ in leaves)
        + tuple(P("data") for _ in range(n_buckets)),
        check_vma=False)
    with mesh:
        out = fn(*[jnp.asarray(l) for l in leaves])
    reduced, errors = out[:len(leaves)], out[len(leaves):]
    assert len(errors) == n_buckets
    for l, r in zip(leaves, reduced):
        exact = l.mean(axis=0)
        err = np.abs(np.asarray(r) - exact).max()
        assert err <= np.abs(l).max() / 50, err  # int8 blockwise tolerance
    # per-bucket residual structure is stable: feeding the residuals
    # back round-trips (shape contract of the EF API)
    assert errors[0].shape[0] == 8


# ------------------------------------------- compressed overlap (slow)
@pytest.mark.slow
def test_compressed_overlap_parity_and_bucketing_zero1(devices8):
    """THE PR-15 tentpole contract at stage 1: qgZ + overlap composes —
    the in-loop exchange is int8 + EF, deterministic, bucketed ==
    unbucketed BIT-EXACT (block-aligned coalescing + layout-stable
    hop-1 residuals), and loss parity vs the fp32-overlap arm is codec-
    sized (the PR-11 tolerance)."""
    z = {"stage": 1, "overlap_grad_reduce": True,
         "zero_quantized_gradients": True}
    l_c = _losses(_engine(dict(z)))
    l_c2 = _losses(_engine(dict(z)))
    assert l_c == l_c2, "compressed overlap is not deterministic"
    l_u = _losses(_engine(dict(z, overlap_bucket_mb=0)))
    assert l_c == l_u, "bucketing changed the compressed math"
    l_fp = _losses(_engine({"stage": 1, "overlap_grad_reduce": True}))
    assert l_c[0] == l_fp[0], "forward must be bit-identical"
    par = max(abs(a - b) / max(abs(a), 1e-9) for a, b in zip(l_fp, l_c))
    assert par < 0.05, (l_fp, l_c)


@pytest.mark.slow
def test_compressed_overlap_stage3_and_hier(devices8):
    """Stage 3 (overlap_compression knob): the in-loop psum_scatters
    become quantized reduce-scatters, per-leaf regardless of bucketing
    (bit-exact), at codec-sized parity.  Hierarchical: the in-loop
    reduce takes the three-hop shape and stays parity-close."""
    z3 = {"stage": 3, "overlap_grad_reduce": True,
          "zero3_param_prefetch": True, "overlap_compression": "int8"}
    e3 = _engine(dict(z3))
    assert e3._overlap_plan.compression is not None
    assert sum(d is not None for d in e3._overlap_plan.gather_dims) >= 7
    l3 = _losses(e3)
    assert l3 == _losses(_engine(dict(z3, overlap_bucket_mb=0)))
    l3fp = _losses(_engine({"stage": 3, "overlap_grad_reduce": True,
                            "zero3_param_prefetch": True}))
    par = max(abs(a - b) / max(abs(a), 1e-9) for a, b in zip(l3fp, l3))
    assert par < 0.05, (l3fp, l3)

    zh = {"stage": 1, "overlap_grad_reduce": True,
          "zero_quantized_gradients": True,
          "zero_hierarchical_grad_reduce": True, "zero_hierarchy_inner": 2}
    eh = _engine(dict(zh))
    assert eh._overlap_plan.hier_inner == 2
    lh = _losses(eh)
    l_c = _losses(_engine({"stage": 1, "overlap_grad_reduce": True,
                           "zero_quantized_gradients": True}))
    par_h = max(abs(a - b) / max(abs(a), 1e-9) for a, b in zip(l_c, lh))
    assert par_h < 0.05, (l_c, lh)


@pytest.mark.slow
def test_compressed_overlap_in_loop_s8(devices8):
    """The wire claim in compiled HLO: with compression on, the layer
    loops carry s8-operand collectives and the stage<=2 per-leaf fp
    psums are GONE from the loops (replaced by the two-hop, whose codes
    ride all_to_all/all_gather)."""
    from deepspeed_tpu.analysis.contracts import s8_collective_count

    e = _engine({"stage": 1, "overlap_grad_reduce": True,
                 "zero_quantized_gradients": True})
    hlo = _hlo_of(e)
    assert s8_collective_count(hlo) >= 1
    on1 = _loop_collectives(hlo)
    fp1 = _loop_collectives(_hlo_of(_engine(
        {"stage": 1, "overlap_grad_reduce": True})))
    # fp arm: >= 9 in-loop psums; compressed arm: the per-leaf psums are
    # replaced by the bucket's quantized exchange (far fewer all-reduces
    # in-loop; the remaining ones are the model's own e.g. norm/loss)
    assert on1["all-reduce"][0] < fp1["all-reduce"][0], (on1, fp1)


@pytest.mark.slow
def test_compressed_overlap_resume_parity(devices8):
    """The EF-residual lifecycle contract (chaos-drill shape): train,
    checkpoint mid-run, resume into a FRESH engine — the residuals ride
    TrainState.comm_errors through the checkpoint, so the post-resume
    steps are bit-identical to an uninterrupted run."""
    import tempfile

    import numpy as _np

    z = {"stage": 1, "overlap_grad_reduce": True,
         "zero_quantized_gradients": True}
    batches = [{"input_ids": _ids(8, seed=i)} for i in range(4)]
    e_ctrl = _engine(dict(z))
    ctrl = [float(e_ctrl.train_batch(b)) for b in batches]

    d = tempfile.mkdtemp()
    e1 = _engine(dict(z))
    part1 = [float(e1.train_batch(b)) for b in batches[:2]]
    r_saved = _np.asarray(jax.device_get(
        e1.state.comm_errors["overlap"]["b000"]))
    assert _np.abs(r_saved).max() > 0, "EF residual never populated"
    e1.save_checkpoint(d, tag="mid")
    e2 = _engine(dict(z))
    e2.load_checkpoint(d, tag="mid")
    r_loaded = _np.asarray(jax.device_get(
        e2.state.comm_errors["overlap"]["b000"]))
    assert (r_saved == r_loaded).all(), "residual round-trip not bit-exact"
    part2 = [float(e2.train_batch(b)) for b in batches[2:]]
    assert ctrl == part1 + part2, (ctrl, part1 + part2)


@pytest.mark.slow
def test_qgz_post_backward_ef_resume_parity(devices8):
    """Same lifecycle contract for the POST-backward qgZ path
    (grad_reduce_error_feedback): residuals live under
    comm_errors['reduce'] and checkpoint/resume keeps the trajectory
    bit-identical; the EF arm stays parity-close to plain qgZ."""
    import tempfile

    z = {"stage": 1, "zero_quantized_gradients": True,
         "grad_reduce_error_feedback": True}
    batches = [{"input_ids": _ids(8, seed=i)} for i in range(4)]
    e_ctrl = _engine(dict(z))
    ctrl = [float(e_ctrl.train_batch(b)) for b in batches]
    e_q = _engine({"stage": 1, "zero_quantized_gradients": True})
    lq = [float(e_q.train_batch(b)) for b in batches]
    par = max(abs(a - b) / max(abs(a), 1e-9) for a, b in zip(lq, ctrl))
    assert par < 0.05, (lq, ctrl)

    d = tempfile.mkdtemp()
    e1 = _engine(dict(z))
    part1 = [float(e1.train_batch(b)) for b in batches[:2]]
    assert "reduce" in e1.state.comm_errors
    e1.save_checkpoint(d, tag="mid")
    e2 = _engine(dict(z))
    e2.load_checkpoint(d, tag="mid")
    part2 = [float(e2.train_batch(b)) for b in batches[2:]]
    assert ctrl == part1 + part2, (ctrl, part1 + part2)


@pytest.mark.slow
def test_compressed_overlap_gauges(devices8):
    """The residual-bytes gauge publishes and the bucket events carry
    the compressed marker."""
    from deepspeed_tpu.telemetry.spans import (SpanRecorder,
                                               set_span_recorder)

    rec = SpanRecorder()
    set_span_recorder(rec)
    try:
        model = llama_model("tiny", max_seq_len=SEQ, vocab_size=VOCAB,
                            n_layers=2, attn_impl="xla")
        initialize_topology(MeshConfig(data=8), jax.devices()[:8])
        engine, *_ = deepspeed_tpu.initialize(
            model=model,
            config={"train_micro_batch_size_per_gpu": 1,
                    "gradient_accumulation_steps": 1,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "zero_optimization": {
                        "stage": 1, "overlap_grad_reduce": True,
                        "zero_quantized_gradients": True},
                    "steps_per_print": 1,
                    "telemetry": {"enabled": True}},
            topology=deepspeed_tpu.get_topology())
        engine.train_batch({"input_ids": _ids(8)})
        assert engine._m_comp_residual.value() > 0
        rep = engine.overlap_report()
        assert rep.compression == "int8"
        assert rep.residual_bytes > 0
        ev = [sp for sp in rec.spans() if sp.name == "grad_bucket_reduce"]
        assert ev and any(sp.attrs.get("compressed") for sp in ev)
        engine.close()
    finally:
        set_span_recorder(None)


@pytest.mark.slow
def test_compressed_overlap_fp16_overflow_keeps_residuals_finite(devices8):
    """Review finding: an fp16 overflow step must not poison the carried
    EF residuals — the optimizer skip never touches comm_errors, so the
    engine gates the residual update on the same finiteness signal.  The
    2^20 initial dynamic scale overflows the first backwards;
    the residuals must stay finite throughout and training must
    recover once the scaler backs off."""
    model = llama_model("tiny", max_seq_len=SEQ, vocab_size=VOCAB,
                        n_layers=2, attn_impl="xla")
    initialize_topology(MeshConfig(data=8), jax.devices()[:8])
    engine, *_ = deepspeed_tpu.initialize(
        model=model,
        config={"train_micro_batch_size_per_gpu": 1,
                "gradient_accumulation_steps": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "fp16": {"enabled": True, "initial_scale_power": 20},
                "zero_optimization": {"stage": 1,
                                      "overlap_grad_reduce": True,
                                      "zero_quantized_gradients": True}},
        topology=deepspeed_tpu.get_topology())
    for i in range(10):
        engine.train_batch({"input_ids": _ids(8, seed=i % 6)})
        res = np.asarray(jax.device_get(
            engine.state.comm_errors["overlap"]["b000"]))
        assert np.isfinite(res).all(), f"residuals poisoned at step {i}"
    assert int(engine.state.skipped_steps) >= 1, \
        "test premise broken: no overflow step ever happened"
    assert int(engine.state.step) >= 1, "training never recovered"
