"""Monitor writers (reference tests/unit/monitor/test_monitor.py)."""

import csv

import deepspeed_tpu
from deepspeed_tpu.monitor.monitor import CSVMonitor, MonitorMaster
from deepspeed_tpu.runtime.config import DeepSpeedConfig


def test_csv_monitor_writes_events(tmp_path):
    mon = CSVMonitor(str(tmp_path), "job")
    mon.write_events([("Train/loss", 1.5, 0), ("Train/loss", 1.2, 1),
                      ("Train/lr", 1e-3, 1)])
    files = list(tmp_path.rglob("*.csv"))
    assert files, "no csv written"
    rows = [r for f in files for r in csv.reader(open(f))]
    assert any("1.5" in c for r in rows for c in r)


def test_monitor_master_gating(tmp_path, monkeypatch):
    import sys

    # force comet_ml absent regardless of the environment so the failing-
    # writer path is deterministic (and no network/artifacts if installed)
    monkeypatch.setitem(sys.modules, "comet_ml", None)
    cfg = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 1,
        "csv_monitor": {"enabled": True, "output_path": str(tmp_path),
                        "job_name": "j"},
        "comet": {"enabled": True, "project": "p"},
    })
    master = MonitorMaster(cfg)
    # csv made it in; the comet writer failed its import and was skipped
    assert len(master.monitors) == 1
    master.write_events([("a", 1.0, 0)])
    assert list(tmp_path.rglob("*.csv"))

    off = MonitorMaster(DeepSpeedConfig({"train_micro_batch_size_per_gpu": 1}))
    assert not off.enabled


def test_engine_reports_through_monitor(tmp_path):
    from tests.unit.simple_model import random_batch, simple_mlp_spec

    engine, *_ = deepspeed_tpu.initialize(
        model=simple_mlp_spec(),
        config={"train_micro_batch_size_per_gpu": 4,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "steps_per_print": 1,
                "csv_monitor": {"enabled": True, "output_path": str(tmp_path),
                                "job_name": "train"}})
    engine.train_batch(random_batch(batch_size=4, gas=1))
    assert list(tmp_path.rglob("*.csv")), "engine did not report to monitor"
