"""C++ native op tests (reference tests/unit/ops/{adam,aio}): numeric parity
of SIMD CPU Adam vs the reference update, and AIO roundtrips."""

import os

import numpy as np
import pytest

from deepspeed_tpu.ops.cpu.adam import DeepSpeedCPUAdam
from deepspeed_tpu.ops.cpu.aio import AsyncIOHandle
from deepspeed_tpu.ops.op_builder import CPUAdamBuilder


def _ref_adamw(p, g, m, v, step, lr, b1, b2, eps, wd):
    m = b1 * m + (1 - b1) * g
    v = b2 * v + (1 - b2) * g * g
    mhat = m / (1 - b1 ** step)
    vhat = v / (1 - b2 ** step)
    return p - lr * (mhat / (np.sqrt(vhat) + eps) + wd * p), m, v


def test_builder_compiles():
    lib = CPUAdamBuilder().load()
    assert lib.dstpu_simd_width() >= 1


def test_cpu_adam_matches_reference():
    rng = np.random.RandomState(0)
    n = 10007
    p = rng.randn(n).astype(np.float32)
    g = rng.randn(n).astype(np.float32)
    ref_p, ref_m, ref_v = p.copy(), np.zeros(n, np.float32), np.zeros(n, np.float32)
    opt = DeepSpeedCPUAdam(lr=1e-3, weight_decay=0.01)
    cp = p.copy()
    for step in (1, 2, 3):
        ref_p, ref_m, ref_v = _ref_adamw(ref_p, g, ref_m, ref_v, step,
                                         1e-3, 0.9, 0.999, 1e-8, 0.01)
        opt.step(cp, g)
    np.testing.assert_allclose(cp, ref_p, atol=1e-6, rtol=1e-5)


def test_cpu_adam_bf16_grads():
    import jax.numpy as jnp

    rng = np.random.RandomState(1)
    n = 4096
    p = rng.randn(n).astype(np.float32)
    g32 = rng.randn(n).astype(np.float32)
    g_bf16 = np.asarray(jnp.asarray(g32, jnp.bfloat16))
    opt = DeepSpeedCPUAdam(lr=1e-3)
    cp = p.copy()
    out_bf16 = opt.step_bf16_grads(cp, g_bf16)
    # master matches fp32 path within bf16 grad precision
    opt2 = DeepSpeedCPUAdam(lr=1e-3)
    cp2 = p.copy()
    opt2.step(cp2, g32)
    np.testing.assert_allclose(cp, cp2, atol=2e-2)
    # bf16 output is the rounded master
    back = np.asarray(out_bf16).view(np.uint16)
    assert back.shape == (n,)


def test_cpu_adam_vs_pallas_kernel():
    """Host path and device (pallas) path are interchangeable."""
    import jax.numpy as jnp

    from deepspeed_tpu.ops.pallas.fused_adam import fused_adam_update

    rng = np.random.RandomState(2)
    n = 2048
    p = rng.randn(n).astype(np.float32)
    g = rng.randn(n).astype(np.float32)

    cp = p.copy()
    DeepSpeedCPUAdam(lr=1e-3, weight_decay=0.01).step(cp, g)

    p2, _, _ = fused_adam_update(jnp.asarray(p), jnp.asarray(g),
                                 jnp.zeros(n), jnp.zeros(n),
                                 jnp.asarray(1), 1e-3, weight_decay=0.01)
    np.testing.assert_allclose(cp, np.asarray(p2), atol=1e-5, rtol=1e-4)


def test_aio_write_read_roundtrip(tmp_path):
    h = AsyncIOHandle(thread_count=2)
    data = np.random.RandomState(0).bytes(1 << 20)
    arr = np.frombuffer(data, np.uint8).copy()
    path = str(tmp_path / "swap.bin")
    h.async_pwrite(arr, path)
    h.drain()
    out = np.empty_like(arr)
    h.async_pread(out, path)
    h.drain()
    np.testing.assert_array_equal(arr, out)


def test_aio_many_concurrent_ops(tmp_path):
    h = AsyncIOHandle(thread_count=4)
    arrays = [np.full(100_000, i, np.float32) for i in range(16)]
    paths = [str(tmp_path / f"f{i}.bin") for i in range(16)]
    for a, p in zip(arrays, paths):
        h.async_pwrite(a, p)
    h.drain()
    outs = [np.empty_like(a) for a in arrays]
    for o, p in zip(outs, paths):
        h.async_pread(o, p)
    h.drain()
    for a, o in zip(arrays, outs):
        np.testing.assert_array_equal(a, o)


def test_aio_read_missing_file_raises(tmp_path):
    h = AsyncIOHandle()
    out = np.empty(16, np.uint8)
    h.async_pread(out, str(tmp_path / "nope.bin"))
    with pytest.raises(IOError):
        h.drain()


def test_aio_uring_backend_roundtrip(tmp_path):
    """The io_uring engine (kernel async I/O): multi-chunk ops, per-op wait,
    error surfacing.  Skips where the container forbids io_uring_setup."""
    try:
        h = AsyncIOHandle(backend="uring", block_size=1 << 16)
    except OSError:
        pytest.skip("io_uring unavailable in this kernel/container")
    assert h.backend == "uring"
    # 1MB at 64KB chunks = 16 sqes: multi-chunk accounting + out-of-order
    # completions are genuinely exercised
    arr = np.frombuffer(np.random.RandomState(1).bytes(1 << 20), np.uint8).copy()
    path = str(tmp_path / "u.bin")
    op_w = h.async_pwrite(arr, path)
    h.wait_op(op_w)  # per-op wait, not global drain
    out = np.empty_like(arr)
    op_r = h.async_pread(out, path)
    h.wait_op(op_r)
    np.testing.assert_array_equal(arr, out)
    # error per-op
    bad = np.empty(64, np.uint8)
    op_bad = h.async_pread(bad, str(tmp_path / "missing.bin"))
    with pytest.raises(IOError):
        h.wait_op(op_bad)
    # queue stays usable afterwards
    h.async_pwrite(arr, str(tmp_path / "u2.bin"))
    h.drain()


def test_aio_uring_op_larger_than_ring(tmp_path):
    """A single op needing more sqes than the 256-entry ring must flush
    incrementally instead of deadlocking (32MB / 64KB = 512 chunks)."""
    try:
        h = AsyncIOHandle(backend="uring", block_size=1 << 16)
    except OSError:
        pytest.skip("io_uring unavailable in this kernel/container")
    arr = np.frombuffer(np.random.RandomState(3).bytes(32 << 20), np.uint8).copy()
    path = str(tmp_path / "big.bin")
    h.wait_op(h.async_pwrite(arr, path))
    out = np.empty_like(arr)
    h.wait_op(h.async_pread(out, path))
    np.testing.assert_array_equal(arr, out)


def test_aio_fd_cache_many_paths(tmp_path):
    """More distinct files than the fd-cache cap: idle fds must be evicted,
    not exhaust RLIMIT_NOFILE (cap is 128; write+read 200 paths)."""
    h = AsyncIOHandle(backend="auto")
    a = np.arange(512, dtype=np.uint8)
    paths = [str(tmp_path / f"n{i}.bin") for i in range(200)]
    for p in paths:
        h.async_pwrite(a, p)
    h.drain()
    outs = [np.empty_like(a) for _ in paths]
    for o, p in zip(outs, paths):
        h.async_pread(o, p)
    h.drain()
    for o in outs:
        np.testing.assert_array_equal(a, o)


def test_aio_threads_wait_op(tmp_path):
    h = AsyncIOHandle(backend="threads", thread_count=2)
    assert h.backend == "threads"
    a = np.full(4096, 7, np.uint8)
    op = h.async_pwrite(a, str(tmp_path / "t.bin"))
    h.wait_op(op)
    out = np.empty_like(a)
    h.wait_op(h.async_pread(out, str(tmp_path / "t.bin")))
    np.testing.assert_array_equal(a, out)


def test_pinned_buffer_pool_reuse(tmp_path):
    from deepspeed_tpu.ops.cpu.aio import PinnedBufferPool

    pool = PinnedBufferPool()
    buf = pool.get(1 << 16)
    assert buf.nbytes == 1 << 16
    assert buf.ctypes.data % 4096 == 0  # page-aligned for O_DIRECT
    buf[:] = 42
    addr = buf.ctypes.data
    pool.put(buf)
    buf2 = pool.get(1 << 16)
    assert buf2.ctypes.data == addr  # recycled, not reallocated
    # pinned buffer works as an aio target
    h = AsyncIOHandle()
    buf2[:] = np.frombuffer(np.random.RandomState(2).bytes(1 << 16), np.uint8)
    h.wait_op(h.async_pwrite(buf2, str(tmp_path / "p.bin")))
    out = pool.get(1 << 16)
    h.wait_op(h.async_pread(out, str(tmp_path / "p.bin")))
    np.testing.assert_array_equal(buf2, out)
    pool.put(buf2)
    pool.put(out)
    pool.close()


def test_cpu_lion_matches_reference():
    """C++ Lion vs a numpy reference implementation."""
    from deepspeed_tpu.ops.cpu.lion import DeepSpeedCPULion

    rng = np.random.RandomState(5)
    p = rng.randn(1000).astype(np.float32)
    ref_p, ref_m = p.copy(), np.zeros_like(p)
    lion = DeepSpeedCPULion(lr=1e-3, betas=(0.9, 0.99), weight_decay=0.01)
    for _ in range(5):
        g = rng.randn(1000).astype(np.float32)
        lion.step(p, g, key=0)
        c = 0.9 * ref_m + 0.1 * g
        ref_p *= (1 - 1e-3 * 0.01)
        ref_p -= 1e-3 * np.sign(c)
        ref_m = 0.99 * ref_m + 0.01 * g
    np.testing.assert_allclose(p, ref_p, rtol=1e-5, atol=1e-6)


def test_cpu_adagrad_matches_reference():
    from deepspeed_tpu.ops.cpu.adagrad import DeepSpeedCPUAdagrad

    rng = np.random.RandomState(6)
    p = rng.randn(777).astype(np.float32)
    ref_p, ref_v = p.copy(), np.zeros_like(p)
    ada = DeepSpeedCPUAdagrad(lr=1e-2, eps=1e-10)
    for _ in range(4):
        g = rng.randn(777).astype(np.float32)
        ada.step(p, g, key=0)
        ref_v += g * g
        ref_p -= 1e-2 * g / (np.sqrt(ref_v) + 1e-10)
    np.testing.assert_allclose(p, ref_p, rtol=1e-5, atol=1e-6)


def test_offload_with_lion_and_adagrad():
    """Host-offload path selects the matching CPU kernel by optimizer type."""
    import deepspeed_tpu
    from deepspeed_tpu.ops.cpu.adagrad import DeepSpeedCPUAdagrad
    from deepspeed_tpu.ops.cpu.lion import DeepSpeedCPULion
    from tests.unit.simple_model import random_batch, simple_mlp_spec

    for opt, cls, lr in [("Lion", DeepSpeedCPULion, 1e-3),
                         ("Adagrad", DeepSpeedCPUAdagrad, 5e-2)]:
        engine, *_ = deepspeed_tpu.initialize(
            model=simple_mlp_spec(),
            config={"train_micro_batch_size_per_gpu": 4,
                    "optimizer": {"type": opt, "params": {"lr": lr}},
                    "bf16": {"enabled": True},
                    "zero_optimization": {"stage": 2,
                                          "offload_optimizer": {"device": "cpu"}}})
        assert isinstance(engine.offload_optimizer.cpu_adam, cls)
        losses = [float(engine.train_batch(random_batch(batch_size=16, seed=0, gas=1)))
                  for _ in range(10)]
        assert losses[-1] < losses[0], (opt, losses)


def test_offload_nvme_lion_spills(tmp_path):
    import os

    import deepspeed_tpu
    from tests.unit.simple_model import random_batch, simple_mlp_spec

    engine, *_ = deepspeed_tpu.initialize(
        model=simple_mlp_spec(),
        config={"train_micro_batch_size_per_gpu": 4,
                "optimizer": {"type": "Lion", "params": {"lr": 1e-3}},
                "bf16": {"enabled": True},
                "zero_optimization": {"stage": 2,
                                      "offload_optimizer": {
                                          "device": "nvme",
                                          "nvme_path": str(tmp_path / "nv")}}})
    for i in range(3):
        engine.train_batch(random_batch(batch_size=8, seed=i, gas=1))
    names = os.listdir(tmp_path / "nv")
    assert any(n.startswith("m_") for n in names)  # lion spills m only
    assert not any(n.startswith("v_") for n in names)
