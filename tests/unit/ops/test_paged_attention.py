"""Paged decode attention kernel vs the XLA gather reference
(reference tests: inference/v2 ragged_ops numeric parity)."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.pallas.paged_attention import paged_decode_attention


def _reference(q, k_pool, v_pool, page_table, positions):
    """The gather formulation paged_decode used before the kernel."""
    B, NH, D = q.shape
    P, ps, KVH, _ = k_pool.shape
    S = page_table.shape[1] * ps
    kk = k_pool[page_table].reshape(B, S, KVH, D)
    vv = v_pool[page_table].reshape(B, S, KVH, D)
    kk = jnp.repeat(kk, NH // KVH, axis=2)
    vv = jnp.repeat(vv, NH // KVH, axis=2)
    s = jnp.einsum("bnd,bsnd->bns", q, kk).astype(jnp.float32) / math.sqrt(D)
    vis = jnp.arange(S)[None, None, :] <= positions[:, None, None]
    s = jnp.where(vis, s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bns,bsnd->bnd", p, vv)


@pytest.mark.parametrize("kvh", [4, 1, 2])
def test_paged_decode_matches_gather(kvh):
    rng = np.random.RandomState(0)
    B, NH, D, ps, MP = 3, 4, 16, 8, 4
    P = B * MP + 1  # +1 trash
    trash = P - 1
    q = jnp.asarray(rng.randn(B, NH, D), jnp.float32)
    k_pool = jnp.asarray(rng.randn(P, ps, kvh, D), jnp.float32)
    v_pool = jnp.asarray(rng.randn(P, ps, kvh, D), jnp.float32)
    # each sequence: random distinct pages, trash beyond its length
    positions = jnp.asarray([5, 17, 30], jnp.int32)  # 1, 3, 4 pages used
    table = np.full((B, MP), trash, np.int64)
    perm = rng.permutation(P - 1)
    n = 0
    for b, pos in enumerate([5, 17, 30]):
        used = pos // ps + 1
        table[b, :used] = perm[n:n + used]
        n += used
    page_table = jnp.asarray(table, jnp.int32)

    out = paged_decode_attention(q, k_pool, v_pool, page_table, positions)
    ref = _reference(q, k_pool, v_pool, page_table, positions)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_paged_decode_trash_pages_ignored():
    """Garbage in the trash page must not leak: only slots <= position
    contribute, and pages past the length are trash by construction."""
    rng = np.random.RandomState(1)
    B, NH, D, ps, MP = 1, 2, 8, 4, 3
    P = 4
    q = jnp.asarray(rng.randn(B, NH, D), jnp.float32)
    k_pool = jnp.asarray(rng.randn(P, ps, NH, D), jnp.float32)
    v_pool = jnp.asarray(rng.randn(P, ps, NH, D), jnp.float32)
    k_huge = k_pool.at[-1].set(1e4)  # poison the trash page
    v_huge = v_pool.at[-1].set(1e4)
    positions = jnp.asarray([3], jnp.int32)  # one page used
    page_table = jnp.asarray([[0, P - 1, P - 1]], jnp.int32)
    out = paged_decode_attention(q, k_huge, v_huge, page_table, positions)
    clean = paged_decode_attention(
        q, k_pool.at[-1].set(0), v_pool.at[-1].set(0), page_table, positions)
    np.testing.assert_allclose(np.asarray(out), np.asarray(clean),
                               rtol=1e-5, atol=1e-6)


def test_paged_decode_quantized_matches_dequant():
    """Kernel dequant-in-VMEM path vs dequantize-then-gather reference."""
    rng = np.random.RandomState(2)
    B, NH, D, ps, MP, KVH = 2, 4, 16, 8, 3, 2
    P = 8
    q = jnp.asarray(rng.randn(B, NH, D), jnp.float32)
    codes_k = jnp.asarray(rng.randint(-127, 128, (P, ps, KVH, D)), jnp.int8)
    codes_v = jnp.asarray(rng.randint(-127, 128, (P, ps, KVH, D)), jnp.int8)
    ks = jnp.asarray(rng.rand(P, ps, KVH) * 0.05 + 0.01, jnp.float32)
    vs = jnp.asarray(rng.rand(P, ps, KVH) * 0.05 + 0.01, jnp.float32)
    positions = jnp.asarray([10, 20], jnp.int32)
    table = jnp.asarray([[0, 1, 7], [2, 3, 4]], jnp.int32)

    out = paged_decode_attention(q, codes_k, codes_v, table, positions,
                                 k_scale=ks, v_scale=vs)
    ref = _reference(q, codes_k.astype(jnp.float32) * ks[..., None],
                     codes_v.astype(jnp.float32) * vs[..., None],
                     table, positions)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)
