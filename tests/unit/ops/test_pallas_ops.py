"""Numeric parity for fused Adam and int8 quantization kernels
(reference tests/unit/ops/{adam,quantizer})."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deepspeed_tpu.ops.pallas.fused_adam import fused_adam_update
from deepspeed_tpu.ops.pallas.quantization import dequantize_int8, quantize_int8


def _ref_adamw(p, g, m, v, step, lr, b1, b2, eps, wd):
    m = b1 * m + (1 - b1) * g
    v = b2 * v + (1 - b2) * g * g
    mhat = m / (1 - b1 ** step)
    vhat = v / (1 - b2 ** step)
    p = p - lr * (mhat / (np.sqrt(vhat) + eps) + wd * p)
    return p, m, v


@pytest.mark.parametrize("n", [1000, 128 * 50])
def test_fused_adam_matches_reference(n):
    rng = np.random.RandomState(0)
    p = rng.randn(n).astype(np.float32)
    g = rng.randn(n).astype(np.float32)
    m = np.zeros(n, np.float32)
    v = np.zeros(n, np.float32)
    lr, b1, b2, eps, wd = 1e-3, 0.9, 0.999, 1e-8, 0.01

    p1, m1, v1 = p, m, v
    for step in (1, 2, 3):
        p1, m1, v1 = _ref_adamw(p1, g, m1, v1, step, lr, b1, b2, eps, wd)

    p2, m2, v2 = jnp.asarray(p), jnp.asarray(m), jnp.asarray(v)
    for step in (1, 2, 3):
        p2, m2, v2 = fused_adam_update(p2, jnp.asarray(g), m2, v2,
                                       jnp.asarray(step), lr, b1, b2, eps, wd)
    np.testing.assert_allclose(np.asarray(p2), p1, atol=1e-6, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(m2), m1, atol=1e-6, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(v2), v1, atol=1e-6, rtol=1e-5)


def test_fused_adam_plain_adam_l2_mode():
    rng = np.random.RandomState(1)
    n = 512
    p = jnp.asarray(rng.randn(n).astype(np.float32))
    g = jnp.asarray(rng.randn(n).astype(np.float32))
    m = jnp.zeros(n)
    v = jnp.zeros(n)
    p2, _, _ = fused_adam_update(p, g, m, v, jnp.asarray(1), 1e-3,
                                 weight_decay=0.01, adam_w_mode=False)
    # L2 mode folds decay into the gradient
    g_l2 = g + 0.01 * p
    mm = 0.1 * g_l2
    vv = 0.001 * g_l2 * g_l2
    ref = p - 1e-3 * (mm / 0.1) / (jnp.sqrt(vv / 0.001) + 1e-8)
    np.testing.assert_allclose(np.asarray(p2), np.asarray(ref), atol=1e-6)


@pytest.mark.parametrize("n", [1000, 4096])
def test_int8_quant_roundtrip(n):
    rng = np.random.RandomState(0)
    x = jnp.asarray((rng.randn(n) * 3).astype(np.float32))
    q, s, orig = quantize_int8(x)
    assert q.dtype == jnp.int8
    out = dequantize_int8(q, s, orig)
    # per-128-block symmetric int8: error bounded by scale/2 per element
    err = np.abs(np.asarray(out) - np.asarray(x))
    bound = np.repeat(np.asarray(s)[:, 0], 128)[:n] * 0.5 + 1e-6
    assert np.all(err <= bound)


def test_int8_quant_compresses():
    x = jnp.ones(128 * 8, jnp.float32)
    q, s, _ = quantize_int8(x)
    assert q.size + 4 * s.size < x.size * 4 / 3


def test_fused_adam_traced_lr():
    """lr rides in SMEM, so a traced schedule value works under jit."""
    import jax

    rng = np.random.RandomState(2)
    p = jnp.asarray(rng.randn(300).astype(np.float32))
    g = jnp.asarray(rng.randn(300).astype(np.float32))
    m = jnp.zeros(300); v = jnp.zeros(300)

    @jax.jit
    def step(p, g, m, v, lr):
        return fused_adam_update(p, g, m, v, jnp.asarray(1), lr)

    p_t, m_t, v_t = step(p, g, m, v, jnp.asarray(2e-3, jnp.float32))
    p_s, m_s, v_s = fused_adam_update(p, g, m, v, jnp.asarray(1), 2e-3)
    np.testing.assert_allclose(np.asarray(p_t), np.asarray(p_s), atol=1e-7)
    np.testing.assert_allclose(np.asarray(v_t), np.asarray(v_s), atol=1e-7)


def test_engine_fused_kernel_matches_optax_path():
    """config optimizer params {"fused_kernel": true}: the engine updates
    params through the single-pass Pallas kernel; 5 steps must land on the
    same weights as the optax path (identical seed/data/config)."""
    import deepspeed_tpu
    from deepspeed_tpu.models.llama import llama_model
    from deepspeed_tpu.parallel.mesh import MeshConfig, initialize_topology
    import jax

    def train(fused):
        initialize_topology(MeshConfig(), jax.devices()[:1])
        model = llama_model("tiny", max_seq_len=16, attn_impl="xla")
        engine, *_ = deepspeed_tpu.initialize(
            model=model,
            config={"train_micro_batch_size_per_gpu": 2,
                    "optimizer": {"type": "FusedAdam",
                                  "params": {"lr": 1e-3, "weight_decay": 0.01,
                                             "fused_kernel": fused}},
                    # non-constant schedule: pins the 0-based schedule
                    # index convention (an off-by-one changes every lr)
                    "scheduler": {"type": "WarmupLR",
                                  "params": {"warmup_min_lr": 0.0,
                                             "warmup_max_lr": 1e-3,
                                             "warmup_num_steps": 4}},
                    "gradient_clipping": 1.0,
                    "zero_optimization": {"stage": 0}},
            topology=deepspeed_tpu.get_topology())
        r = np.random.RandomState(0)
        ids = r.randint(0, 256, (5, 1, 2, 16)).astype(np.int32)
        losses = [float(engine.train_batch({"input_ids": jnp.asarray(b)}))
                  for b in ids]
        return losses, engine.state.params

    l_ref, p_ref = train(False)
    l_fused, p_fused = train(True)
    np.testing.assert_allclose(l_fused, l_ref, rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(p_fused),
                    jax.tree_util.tree_leaves(p_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-6, rtol=1e-4)


@pytest.mark.parametrize("stage", [1, 3])
def test_engine_fused_kernel_sharded_matches_optax(stage, devices8):
    """On a sharded mesh the fused kernel runs on each device's LOCAL
    master shard via shard_map (no gather); trained params must equal the
    optax path's bit-for-bit modulo fp rounding."""
    import deepspeed_tpu
    from deepspeed_tpu.models.llama import llama_model
    from deepspeed_tpu.parallel.mesh import MeshConfig, initialize_topology
    import jax

    def train(fused):
        initialize_topology(MeshConfig(data=8), jax.devices()[:8])
        model = llama_model("tiny", max_seq_len=16, attn_impl="xla")
        engine, *_ = deepspeed_tpu.initialize(
            model=model,
            config={"train_micro_batch_size_per_gpu": 1,
                    "optimizer": {"type": "FusedAdam",
                                  "params": {"lr": 1e-3, "weight_decay": 0.01,
                                             "fused_kernel": fused}},
                    "gradient_clipping": 1.0,
                    "zero_optimization": {"stage": stage},
                    "mesh": {"data": 8}},
            topology=deepspeed_tpu.get_topology())
        if fused:
            assert getattr(engine.optimizer, "direct_update", None) is not None
        r = np.random.RandomState(0)
        ids = r.randint(0, 256, (4, 1, 8, 16)).astype(np.int32)
        losses = [float(engine.train_batch({"input_ids": jnp.asarray(b)}))
                  for b in ids]
        return losses, engine.state.params

    l_ref, p_ref = train(False)
    l_fused, p_fused = train(True)
    np.testing.assert_allclose(l_fused, l_ref, rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(p_fused),
                    jax.tree_util.tree_leaves(p_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-6, rtol=1e-4)


@pytest.mark.parametrize("fused", [False, True])
def test_mu_dtype_bf16_moment_storage(fused):
    """optimizer params {"mu_dtype": "bf16"}: the first moment is stored
    bf16 in BOTH the optax and the Pallas fused paths; training stays
    finite and close to the fp32-moment run."""
    import deepspeed_tpu
    from deepspeed_tpu.models.llama import llama_model
    from deepspeed_tpu.parallel.mesh import MeshConfig, initialize_topology
    import jax

    def train(mu):
        initialize_topology(MeshConfig(), jax.devices()[:1])
        model = llama_model("tiny", max_seq_len=16, attn_impl="xla")
        params = {"lr": 1e-3, "weight_decay": 0.01, "fused_kernel": fused}
        if mu:
            params["mu_dtype"] = mu
        engine, *_ = deepspeed_tpu.initialize(
            model=model,
            config={"train_micro_batch_size_per_gpu": 2,
                    "optimizer": {"type": "AdamW", "params": params},
                    "zero_optimization": {"stage": 0}},
            topology=deepspeed_tpu.get_topology())
        r = np.random.RandomState(0)
        ids = r.randint(0, 256, (5, 1, 2, 16)).astype(np.int32)
        losses = [float(engine.train_batch({"input_ids": jnp.asarray(b)}))
                  for b in ids]
        return losses, engine.state.opt_state

    l16, opt16 = train("bf16")
    l32, _ = train(None)
    mus = [l for l in jax.tree_util.tree_leaves(opt16)
           if getattr(l, "dtype", None) == jnp.bfloat16]
    assert mus, "no bf16 moment found in opt state"
    assert np.isfinite(l16).all()
    np.testing.assert_allclose(l16, l32, rtol=2e-2)
