"""Numeric parity for fused Adam and int8 quantization kernels
(reference tests/unit/ops/{adam,quantizer})."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deepspeed_tpu.ops.pallas.fused_adam import fused_adam_update
from deepspeed_tpu.ops.pallas.quantization import dequantize_int8, quantize_int8


def _ref_adamw(p, g, m, v, step, lr, b1, b2, eps, wd):
    m = b1 * m + (1 - b1) * g
    v = b2 * v + (1 - b2) * g * g
    mhat = m / (1 - b1 ** step)
    vhat = v / (1 - b2 ** step)
    p = p - lr * (mhat / (np.sqrt(vhat) + eps) + wd * p)
    return p, m, v


@pytest.mark.parametrize("n", [1000, 128 * 50])
def test_fused_adam_matches_reference(n):
    rng = np.random.RandomState(0)
    p = rng.randn(n).astype(np.float32)
    g = rng.randn(n).astype(np.float32)
    m = np.zeros(n, np.float32)
    v = np.zeros(n, np.float32)
    lr, b1, b2, eps, wd = 1e-3, 0.9, 0.999, 1e-8, 0.01

    p1, m1, v1 = p, m, v
    for step in (1, 2, 3):
        p1, m1, v1 = _ref_adamw(p1, g, m1, v1, step, lr, b1, b2, eps, wd)

    p2, m2, v2 = jnp.asarray(p), jnp.asarray(m), jnp.asarray(v)
    for step in (1, 2, 3):
        p2, m2, v2 = fused_adam_update(p2, jnp.asarray(g), m2, v2,
                                       jnp.asarray(step), lr, b1, b2, eps, wd)
    np.testing.assert_allclose(np.asarray(p2), p1, atol=1e-6, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(m2), m1, atol=1e-6, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(v2), v1, atol=1e-6, rtol=1e-5)


def test_fused_adam_plain_adam_l2_mode():
    rng = np.random.RandomState(1)
    n = 512
    p = jnp.asarray(rng.randn(n).astype(np.float32))
    g = jnp.asarray(rng.randn(n).astype(np.float32))
    m = jnp.zeros(n)
    v = jnp.zeros(n)
    p2, _, _ = fused_adam_update(p, g, m, v, jnp.asarray(1), 1e-3,
                                 weight_decay=0.01, adam_w_mode=False)
    # L2 mode folds decay into the gradient
    g_l2 = g + 0.01 * p
    mm = 0.1 * g_l2
    vv = 0.001 * g_l2 * g_l2
    ref = p - 1e-3 * (mm / 0.1) / (jnp.sqrt(vv / 0.001) + 1e-8)
    np.testing.assert_allclose(np.asarray(p2), np.asarray(ref), atol=1e-6)


@pytest.mark.parametrize("n", [1000, 4096])
def test_int8_quant_roundtrip(n):
    rng = np.random.RandomState(0)
    x = jnp.asarray((rng.randn(n) * 3).astype(np.float32))
    q, s, orig = quantize_int8(x)
    assert q.dtype == jnp.int8
    out = dequantize_int8(q, s, orig)
    # per-128-block symmetric int8: error bounded by scale/2 per element
    err = np.abs(np.asarray(out) - np.asarray(x))
    bound = np.repeat(np.asarray(s)[:, 0], 128)[:n] * 0.5 + 1e-6
    assert np.all(err <= bound)


def test_int8_quant_compresses():
    x = jnp.ones(128 * 8, jnp.float32)
    q, s, _ = quantize_int8(x)
    assert q.size + 4 * s.size < x.size * 4 / 3
