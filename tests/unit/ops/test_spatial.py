"""Spatial / diffusers op tests (reference tests/unit/ops/spatial)."""

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.spatial import (diffusers_attention,
                                       diffusers_transformer_block,
                                       group_norm, nhwc_bias_add,
                                       nhwc_bias_add_add,
                                       nhwc_bias_add_bias_add)


def test_bias_add_variants():
    rng = np.random.RandomState(0)
    a = jnp.asarray(rng.randn(2, 16, 8), jnp.float32)
    b = jnp.asarray(rng.randn(8), jnp.float32)
    o = jnp.asarray(rng.randn(2, 16, 8), jnp.float32)
    ob = jnp.asarray(rng.randn(8), jnp.float32)
    np.testing.assert_allclose(np.asarray(nhwc_bias_add(a, b)),
                               np.asarray(a) + np.asarray(b), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(nhwc_bias_add_add(a, b, o)),
                               np.asarray(a) + np.asarray(b) + np.asarray(o),
                               rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(nhwc_bias_add_bias_add(a, b, o, ob)),
        np.asarray(a) + np.asarray(b) + np.asarray(o) + np.asarray(ob),
        rtol=1e-6)


def test_group_norm_matches_manual():
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(2, 12, 16), jnp.float32)
    scale = jnp.asarray(rng.randn(16), jnp.float32)
    bias = jnp.asarray(rng.randn(16), jnp.float32)
    out = group_norm(x, num_groups=4, scale=scale, bias=bias)
    xn = np.asarray(x).reshape(2, 12, 4, 4)
    mu = xn.mean(axis=(1, 3), keepdims=True)
    var = xn.var(axis=(1, 3), keepdims=True)
    want = ((xn - mu) / np.sqrt(var + 1e-5)).reshape(2, 12, 16) \
        * np.asarray(scale) + np.asarray(bias)
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-4, atol=1e-5)


def _attn_params(rng, c, c_ctx=None, bias=False):
    c_ctx = c_ctx or c
    p = {"wq": jnp.asarray(rng.randn(c, c) * 0.1, jnp.float32),
         "wk": jnp.asarray(rng.randn(c_ctx, c) * 0.1, jnp.float32),
         "wv": jnp.asarray(rng.randn(c_ctx, c) * 0.1, jnp.float32),
         "wo": jnp.asarray(rng.randn(c, c) * 0.1, jnp.float32)}
    for k in ("bq", "bk", "bv", "bo"):
        p[k] = jnp.asarray(rng.randn(c) * 0.1, jnp.float32) if bias else None
    return p


def test_diffusers_self_and_cross_attention():
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(2, 16, 8), jnp.float32)
    ctx = jnp.asarray(rng.randn(2, 5, 12), jnp.float32)
    p_self = _attn_params(rng, 8, bias=True)
    out = diffusers_attention(x, p_self, n_heads=2)
    assert out.shape == x.shape
    # manual check
    q = (np.asarray(x) @ np.asarray(p_self["wq"]) + np.asarray(p_self["bq"])
         ).reshape(2, 16, 2, 4)
    k = (np.asarray(x) @ np.asarray(p_self["wk"]) + np.asarray(p_self["bk"])
         ).reshape(2, 16, 2, 4)
    v = (np.asarray(x) @ np.asarray(p_self["wv"]) + np.asarray(p_self["bv"])
         ).reshape(2, 16, 2, 4)
    s = np.einsum("bqnd,bknd->bnqk", q, k) / 2.0
    pr = np.asarray(jax.nn.softmax(jnp.asarray(s), -1))
    want = np.einsum("bnqk,bknd->bqnd", pr, v).reshape(2, 16, 8)
    want = want @ np.asarray(p_self["wo"]) + np.asarray(p_self["bo"])
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-4, atol=1e-5)

    p_cross = _attn_params(rng, 8, c_ctx=12)
    out_c = diffusers_attention(x, p_cross, n_heads=2, context=ctx)
    assert out_c.shape == x.shape
    assert np.isfinite(np.asarray(out_c)).all()


def test_diffusers_transformer_block_runs_and_differentiates():
    rng = np.random.RandomState(3)
    C, HW, T = 8, 16, 5
    x = jnp.asarray(rng.randn(1, HW, C), jnp.float32)
    ctx = jnp.asarray(rng.randn(1, T, C), jnp.float32)
    ln = lambda: {"scale": jnp.ones((C,)), "bias": jnp.zeros((C,))}  # noqa: E731
    params = {
        "norm1": ln(), "norm2": ln(), "norm3": ln(),
        "attn1": _attn_params(rng, C),
        "attn2": _attn_params(rng, C, c_ctx=C),
        "ff": {"w_in": jnp.asarray(rng.randn(C, 4 * C) * 0.1, jnp.float32),
               "w_out": jnp.asarray(rng.randn(2 * C, C) * 0.1, jnp.float32)},
    }
    out = diffusers_transformer_block(x, params, n_heads=2, context=ctx)
    assert out.shape == x.shape

    g = jax.grad(lambda p: jnp.sum(jnp.square(
        diffusers_transformer_block(x, p, 2, ctx))))(params)
    for leaf in jax.tree_util.tree_leaves(g):
        assert np.isfinite(np.asarray(leaf)).all()
