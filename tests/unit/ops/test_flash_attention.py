"""Numeric parity of the Pallas flash attention vs the XLA reference
(reference test style: tests/unit/ops numeric parity vs torch)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models.transformer import xla_attention
from deepspeed_tpu.ops.pallas.flash_attention import flash_attention


def _qkv(b=2, s=128, nh=4, d=64, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (b, s, nh, d)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
def test_forward_matches_xla(causal):
    q, k, v = _qkv()
    ref = xla_attention(q, k, v, causal)
    out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=1e-4)


def test_forward_uneven_blocks():
    # seq not a multiple of block size exercises edge blocks
    q, k, v = _qkv(s=96)
    ref = xla_attention(q, k, v, True)
    out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=1e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_gradients_match_xla(causal):
    q, k, v = _qkv(b=1, s=64, nh=2, d=32)

    def loss_ref(q, k, v):
        return jnp.sum(xla_attention(q, k, v, causal) ** 2)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal,
                                       block_q=32, block_k=32) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_out = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_out, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4,
                                   rtol=1e-3, err_msg=f"d{name}")


def test_gqa_via_repeat():
    # models repeat kv heads before calling attention; just check shape flow
    q, k, v = _qkv(s=64)
    out = flash_attention(q, k, v, causal=True, block_q=32, block_k=32)
    assert out.shape == q.shape


@pytest.mark.parametrize("causal", [True, False])
def test_gqa_native_matches_repeated(causal):
    """GQA-native path (KVH < NH through kernel index maps) vs explicitly
    repeated kv: forward and all three gradients."""
    from deepspeed_tpu.models.transformer import _repeat_kv

    b, s, nh, kvh, d = 2, 64, 8, 2, 32
    ks = jax.random.split(jax.random.PRNGKey(7), 3)
    q = jax.random.normal(ks[0], (b, s, nh, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, s, kvh, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, s, kvh, d), jnp.float32)

    out = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
    ref = flash_attention(q, _repeat_kv(k, nh // kvh), _repeat_kv(v, nh // kvh),
                          causal=causal, block_q=32, block_k=32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=1e-4)

    def loss_gqa(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal,
                                       block_q=32, block_k=32) ** 2)

    def loss_rep(q, k, v):
        return jnp.sum(flash_attention(
            q, _repeat_kv(k, nh // kvh), _repeat_kv(v, nh // kvh),
            causal=causal, block_q=32, block_k=32) ** 2)

    g_gqa = jax.grad(loss_gqa, argnums=(0, 1, 2))(q, k, v)
    # the repeat's VJP sums each group back to [b, s, kvh, d] for us
    g_rep = jax.grad(loss_rep, argnums=(0, 1, 2))(q, k, v)
    for a, r, name in zip(g_gqa, g_rep, ("dq", "dk", "dv")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r), atol=5e-4,
                                   rtol=1e-3, err_msg=name)


@pytest.mark.slow
def test_random_shape_sweep_forward():
    """Randomized shapes: uneven seqs, GQA ratios, odd head dims, cross
    attention (Sq != Sk), tiny blocks — forward parity vs XLA."""
    rng = np.random.RandomState(11)
    from deepspeed_tpu.models.transformer import _repeat_kv

    for trial in range(8):
        b = int(rng.randint(1, 3))
        nh = int(rng.choice([1, 2, 4, 8]))
        kvh = int(rng.choice([h for h in (1, 2, 4, 8) if nh % h == 0]))
        d = int(rng.choice([8, 16, 32]))
        sq = int(rng.randint(3, 97))
        causal = bool(rng.randint(2))
        sk = sq if causal else int(rng.randint(3, 97))
        bq = int(rng.choice([16, 32, 64]))
        bk = int(rng.choice([16, 32, 64]))
        ks = jax.random.split(jax.random.PRNGKey(trial), 3)
        q = jax.random.normal(ks[0], (b, sq, nh, d), jnp.float32)
        k = jax.random.normal(ks[1], (b, sk, kvh, d), jnp.float32)
        v = jax.random.normal(ks[2], (b, sk, kvh, d), jnp.float32)
        ref = xla_attention(q, _repeat_kv(k, nh // kvh),
                            _repeat_kv(v, nh // kvh), causal)
        out = flash_attention(q, k, v, causal=causal, block_q=bq, block_k=bk)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=5e-5, rtol=5e-4,
            err_msg=f"trial {trial}: b={b} sq={sq} sk={sk} nh={nh} "
                    f"kvh={kvh} d={d} causal={causal} bq={bq} bk={bk}")


@pytest.mark.slow
def test_random_shape_sweep_gradients():
    """Two randomized gradient-parity draws (full pipeline incl. padding)."""
    from deepspeed_tpu.models.transformer import _repeat_kv

    for trial, (sq, nh, kvh, d, bq) in enumerate(
            [(45, 4, 2, 16, 16), (70, 2, 1, 8, 32)]):
        ks = jax.random.split(jax.random.PRNGKey(100 + trial), 3)
        q = jax.random.normal(ks[0], (1, sq, nh, d), jnp.float32)
        k = jax.random.normal(ks[1], (1, sq, kvh, d), jnp.float32)
        v = jax.random.normal(ks[2], (1, sq, kvh, d), jnp.float32)
        g_ref = jax.grad(lambda q, k, v: jnp.sum(xla_attention(
            q, _repeat_kv(k, nh // kvh), _repeat_kv(v, nh // kvh), True) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        g_fl = jax.grad(lambda q, k, v: jnp.sum(flash_attention(
            q, k, v, causal=True, block_q=bq, block_k=bq) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        for a, r, nm in zip(g_fl, g_ref, ("dq", "dk", "dv")):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                       atol=1e-3, rtol=2e-3,
                                       err_msg=f"trial {trial} {nm}")


@pytest.mark.parametrize("bwd_bq,bwd_bk", [(16, 16), (64, 32), (32, 64)])
def test_gradients_with_independent_bwd_blocks(bwd_bq, bwd_bk):
    """bwd tiling decoupled from fwd tiling (incl. non-divisible mixes
    that force lcm padding) must not change any gradient."""
    q, k, v = _qkv(b=1, s=48, nh=2, d=32)  # 48: not a multiple of 32

    def loss_ref(q, k, v):
        return jnp.sum(xla_attention(q, k, v, True) ** 2)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       block_q=32, block_k=32,
                                       bwd_block_q=bwd_bq,
                                       bwd_block_k=bwd_bk) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_out = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_out, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4,
                                   rtol=1e-3, err_msg=f"d{name}")


def test_flash_alibi_matches_xla_bias_fwd_bwd():
    """In-kernel ALiBi (bias from block indices, never materializing
    [S, S]) must match the XLA additive-bias formulation in outputs AND
    q/k/v gradients, across GQA and multi-block shapes."""
    from deepspeed_tpu.models.transformer import (_repeat_kv, alibi_slopes,
                                                  xla_attention)

    rng = np.random.RandomState(7)
    B, S, NH, KVH, D = 2, 96, 4, 2, 16  # multi-block at block 32, GQA 2x
    q = jnp.asarray(rng.randn(B, S, NH, D).astype(np.float32)) * 0.3
    k = jnp.asarray(rng.randn(B, S, KVH, D).astype(np.float32)) * 0.3
    v = jnp.asarray(rng.randn(B, S, KVH, D).astype(np.float32)) * 0.3
    slopes = alibi_slopes(NH)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=True, block_q=32, block_k=32,
                            alibi_slopes=slopes)
        return jnp.sum(o * o)

    def loss_xla(q, k, v):
        rel = (jnp.arange(S)[:, None] - jnp.arange(S)[None, :]).astype(
            jnp.float32)
        bias = -slopes[None, :, None, None] * rel
        o = xla_attention(q, _repeat_kv(k, NH // KVH),
                          _repeat_kv(v, NH // KVH), True, bias=bias)
        return jnp.sum(o * o)

    lf, gf = jax.value_and_grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    lx, gx = jax.value_and_grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(float(lf), float(lx), rtol=1e-5)
    for a, b, name in zip(gf, gx, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5, rtol=2e-4, err_msg=name)
    # without slopes the default path is untouched (regression guard)
    o_plain = flash_attention(q, k, v, causal=True, block_q=32, block_k=32)
    o_xla = xla_attention(q, _repeat_kv(k, NH // KVH),
                          _repeat_kv(v, NH // KVH), True)
    np.testing.assert_allclose(np.asarray(o_plain), np.asarray(o_xla),
                               atol=2e-5, rtol=2e-4)
