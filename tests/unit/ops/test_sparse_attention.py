"""Block-sparse attention + fp quantizer tests (reference:
tests/unit/ops/sparse_attention, tests/unit/ops/fp_quantizer)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.fp_quantizer import (FP_Quantize, dequantize_fp8,
                                            quantize_fp8)
from deepspeed_tpu.ops.pallas.sparse_attention import (
    BigBirdSparsityConfig, BSLongformerSparsityConfig, DenseSparsityConfig,
    FixedSparsityConfig, sparse_attention)

B, S, H, D = 2, 512, 2, 64
BLOCK = 128


def _qkv(seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(B, S, H, D), jnp.float32) * 0.3
    return mk(), mk(), mk()


def _dense_masked(q, k, v, layout, causal):
    """Numeric oracle: dense attention with the block mask expanded."""
    mask = np.kron(np.asarray(layout), np.ones((BLOCK, BLOCK)))  # [H, S, S]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D)
    s = jnp.where(jnp.asarray(mask[None]) > 0, s, -jnp.inf)
    if causal:
        cm = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(cm[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(jnp.isnan(p), 0.0, p)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


CONFIGS = [
    DenseSparsityConfig(num_heads=H, block=BLOCK),
    FixedSparsityConfig(num_heads=H, block=BLOCK, num_local_blocks=2,
                        num_global_blocks=1),
    BSLongformerSparsityConfig(num_heads=H, block=BLOCK,
                               num_sliding_window_blocks=3,
                               global_block_indices=(0,)),
    BigBirdSparsityConfig(num_heads=H, block=BLOCK, num_random_blocks=1,
                          num_sliding_window_blocks=3, num_global_blocks=1),
]


@pytest.mark.parametrize("cfg", CONFIGS, ids=lambda c: type(c).__name__)
@pytest.mark.parametrize("causal", [True, False])
def test_sparse_matches_dense_masked(cfg, causal):
    q, k, v = _qkv()
    layout = cfg.make_layout(S)
    want = _dense_masked(q, k, v, layout, causal)
    got = sparse_attention(q, k, v, cfg, causal=causal, impl="pallas")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_xla_impl_matches_pallas():
    cfg = FixedSparsityConfig(num_heads=H, block=BLOCK, num_local_blocks=2)
    q, k, v = _qkv(1)
    a = sparse_attention(q, k, v, cfg, impl="pallas")
    b = sparse_attention(q, k, v, cfg, impl="xla")
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4)


def test_layout_shapes_and_coverage():
    cfg = BigBirdSparsityConfig(num_heads=4, block=BLOCK)
    lay = cfg.make_layout(8 * BLOCK)
    assert lay.shape == (4, 8, 8)
    assert lay.any(axis=-1).all()  # every q block sees something
    with pytest.raises(ValueError):
        cfg.make_layout(BLOCK + 1)


# ------------------------------------------------------------- fp quantizer
def test_fp8_roundtrip_error():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(1000), jnp.float32)
    codes, scales = quantize_fp8(x, group_size=256)
    y = dequantize_fp8(codes, scales, x.shape, group_size=256)
    # e4m3 has ~2 decimal digits; relative error per element is bounded by
    # 2^-3 after absmax scaling
    err = np.abs(np.asarray(y) - np.asarray(x))
    assert np.median(err / (np.abs(np.asarray(x)) + 1e-6)) < 0.07


@pytest.mark.parametrize("q_bits,bound", [(8, 0.07), (6, 0.15), (4, 0.3)])
def test_fp_bits_roundtrip(q_bits, bound):
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(512) * 3.0, jnp.float32)
    qz = FP_Quantize(group_size=128, q_bits=q_bits)
    codes, scales = qz.quantize(x)
    y = qz.dequantize(codes, scales, x.shape)
    rel = np.abs(np.asarray(y) - np.asarray(x)) / (np.abs(np.asarray(x)) + 1e-6)
    assert np.median(rel) < bound
    # narrower formats must be (weakly) worse than wider ones
    assert codes.dtype == jnp.float8_e4m3fn


def test_fp_quantize_validation():
    with pytest.raises(ValueError):
        FP_Quantize(q_bits=5)
    with pytest.raises(ValueError):
        FP_Quantize(fmt="e2m5")


def test_selective_dequantize():
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(4, 128), jnp.float32)
    qz = FP_Quantize(group_size=128)
    codes, scales = qz.quantize(x)
    sel = qz.selective_dequantize(codes, scales, jnp.asarray([0, 2]), (2, 128))
    full = qz.dequantize(codes, scales, (4, 128))
    np.testing.assert_allclose(np.asarray(sel),
                               np.asarray(full).reshape(4, 128)[[0, 2]])
