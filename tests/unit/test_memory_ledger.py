"""Memory ledger and OOM forensics tests.

Covers: structural byte attribution (device/host split, explicit byte
dicts, informational components, the unattributed residual), per-phase
peak watermarks off span/PhaseTimer boundaries (including monotonicity
of the exit log), the engine's TrainState attribution across ZeRO
stages and host offload, ``see_memory_usage``'s always-on gauge
publication with the empty-stats CPU fallback, the serving engine's KV
page-pool occupancy gauges, and the RESOURCE_EXHAUSTED incident-dump
schema (hints + ledger breakdown through the flight recorder).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.telemetry import (FlightRecorder, MemoryLedger,
                                     MetricsRegistry, get_memory_ledger,
                                     is_resource_exhausted, oom_hints,
                                     set_memory_ledger)
from deepspeed_tpu.telemetry.spans import set_phase_listener


class FakeAccelerator:
    """Scripted ``memory_stats`` so watermark/residual math is exact."""

    def __init__(self, stats=None):
        self.stats = stats if stats is not None else {
            "bytes_in_use": 1000, "peak_bytes_in_use": 1500,
            "bytes_limit": 4000}

    def aggregate_memory_stats(self):
        return dict(self.stats)

    def memory_stats(self, device_index=None):
        return dict(self.stats)


@pytest.fixture
def fresh_registry():
    from deepspeed_tpu.telemetry import get_registry, set_registry

    old = get_registry()
    reg = MetricsRegistry()
    set_registry(reg)
    yield reg
    set_registry(old)


@pytest.fixture
def fresh_ledger(fresh_registry):
    """Install a fresh default ledger (own registry via fresh_registry);
    restore the old one and drop any phase listener installed here."""
    old = get_memory_ledger()
    led = MemoryLedger(registry=fresh_registry,
                       accelerator=FakeAccelerator())
    set_memory_ledger(led)
    yield led
    set_phase_listener(None)
    set_memory_ledger(old)


def _structural_bytes(tree) -> int:
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        try:
            total += sum(s.data.nbytes for s in leaf.addressable_shards)
        except Exception:
            total += int(getattr(leaf, "nbytes", 0) or 0)
    return total


# ----------------------------- structural attribution ------------------------
def test_component_attribution_and_residual(fresh_ledger):
    led = fresh_ledger
    tree = {"w": jnp.zeros((8, 8), jnp.float32), "host": np.zeros((4,), np.float32)}
    led.attach("state", lambda: tree)
    led.attach("explicit", lambda: {"device": 100, "host": 7})
    led.attach("info", lambda: {"device": 50}, informational=True)
    led.attach("broken", lambda: 1 / 0)  # provider errors count 0, not crash
    report = led.publish()
    comp = report["components"]
    dev_w = _structural_bytes(tree["w"])  # replicated: counts every shard
    assert comp["state"] == {"device": dev_w, "host": 16,
                             "informational": False}
    assert comp["explicit"] == {"device": 100, "host": 7,
                                "informational": False}
    assert comp["info"]["informational"] is True
    assert comp["broken"] == {"device": 0, "host": 0, "informational": False}
    # informational components are published but NOT attributed
    assert report["attributed_device_bytes"] == dev_w + 100
    assert report["attributed_host_bytes"] == 16 + 7
    assert report["unattributed_bytes"] == 1000 - (dev_w + 100)
    g = led.registry.get("deepspeed_tpu_memory_component_bytes")
    assert g.value(component="state", space="device") == dev_w
    assert g.value(component="info", space="device") == 50
    assert led.registry.get(
        "deepspeed_tpu_memory_bytes_in_use").value() == 1000
    assert led.registry.get(
        "deepspeed_tpu_memory_unattributed_bytes").value() == \
        report["unattributed_bytes"]
    # detach zeroes the gauge rows and leaves the sums honest
    led.detach("explicit")
    assert g.value(component="explicit", space="device") == 0
    assert led.collect()["attributed_device_bytes"] == dev_w


def test_host_placed_arrays_count_as_device_on_cpu(fresh_ledger):
    """On the CPU backend the default memory space IS host memory:
    plain arrays must land in the device column (the accelerator's
    default space), not be misread as offloaded."""
    x = jnp.ones((4, 4), jnp.float32)
    fresh_ledger.attach("x", lambda: x)
    row = fresh_ledger.collect()["components"]["x"]
    assert row["device"] == _structural_bytes(x) and row["host"] == 0


# ----------------------------- phase watermarks ------------------------------
def test_phase_watermarks_from_spans(fresh_ledger):
    from deepspeed_tpu.telemetry.spans import SpanRecorder, set_span_recorder

    led = fresh_ledger
    acc = led._acc
    old_rec = None
    try:
        from deepspeed_tpu.telemetry.spans import get_span_recorder

        old_rec = get_span_recorder()
        set_span_recorder(SpanRecorder(ring_size=64))
        led.install_phase_watch()
        from deepspeed_tpu.telemetry.spans import record_event, span

        acc.stats = {"bytes_in_use": 100, "peak_bytes_in_use": 100}
        with span("forward"):
            # occupancy spikes inside the phase; the process peak moved,
            # so the new high-water mark is attributed to this phase
            acc.stats = {"bytes_in_use": 80, "peak_bytes_in_use": 300}
        with span("not_watched"):
            pass
        record_event("backward")  # point sample
        acc.stats = {"bytes_in_use": 150, "peak_bytes_in_use": 350}
        with span("optimizer_step"):
            acc.stats = {"bytes_in_use": 120, "peak_bytes_in_use": 350}
        marks = led.watermarks()
        assert marks["forward"] == 300  # the in-phase peak, not the exit use
        assert marks["backward"] == 80  # point sample of bytes_in_use
        assert marks["optimizer_step"] == 150  # enter occupancy was highest
        assert "not_watched" not in marks
        # exit log carries the process peak: monotone within the step
        peaks = [p for _n, p in led.phase_exit_log()]
        assert peaks == sorted(peaks)
        led.publish()
        g = led.registry.get("deepspeed_tpu_memory_phase_peak_bytes")
        assert g.value(phase="forward") == 300
        led.reset_watermarks()
        assert led.watermarks() == {} and led.phase_exit_log() == []
    finally:
        set_span_recorder(old_rec)


def test_phase_watch_through_phase_timer(fresh_ledger):
    from deepspeed_tpu.telemetry.tracing import PhaseTimer

    led = fresh_ledger
    led.install_phase_watch()
    led._acc.stats = {"bytes_in_use": 222, "peak_bytes_in_use": 222}
    with PhaseTimer("decode", sink=lambda n, dt: None, batch=2):
        pass
    assert led.watermarks()["decode"] == 222
    led.uninstall_phase_watch()
    led._acc.stats = {"bytes_in_use": 999, "peak_bytes_in_use": 999}
    with PhaseTimer("decode", sink=lambda n, dt: None):
        pass
    assert led.watermarks()["decode"] == 222  # watch removed


# ----------------------------- see_memory_usage ------------------------------
def test_see_memory_usage_always_publishes(fresh_ledger):
    from deepspeed_tpu.runtime.utils import see_memory_usage

    led = fresh_ledger
    see_memory_usage("probe", force=False)  # no longer a silent no-op
    assert led.registry.get(
        "deepspeed_tpu_memory_bytes_in_use").value() == 1000
    assert led.registry.get(
        "deepspeed_tpu_memory_peak_bytes_in_use").value() == 1500
    # empty stats (bare-CPU accelerator): graceful, gauges untouched
    led._acc.stats = {}
    see_memory_usage("probe2", force=True)  # force path must not crash
    assert led.registry.get(
        "deepspeed_tpu_memory_bytes_in_use").value() == 1000


# ----------------------------- OOM detection + forensics ---------------------
def test_is_resource_exhausted():
    assert is_resource_exhausted(MemoryError("KV pool exhausted"))
    assert is_resource_exhausted(RuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory while trying to allocate 123."))
    assert is_resource_exhausted(RuntimeError("hbm: out of memory"))
    assert not is_resource_exhausted(ValueError("shapes mismatch"))
    assert not is_resource_exhausted(None)


def test_oom_hints_cover_context():
    report = {"components": {
        "optimizer_state": {"device": 0, "host": 0},
        "master_params": {"device": 1000, "host": 0},
        "kv_pool": {"device": 5000, "host": 0},
        "kv_prefix_pinned": {"device": 600, "host": 0}},
        "bytes_in_use": 10000, "unattributed_bytes": 4000}
    hints = oom_hints({"zero_stage": 1, "offload_optimizer": False,
                       "compute_dtype": "float32", "gas": 1,
                       "kv_quant": False}, report)
    text = " ".join(hints)
    for needle in ("zero_optimization.stage", "offload_optimizer", "bf16",
                   "KV page pool", "kv_quant", "prefix_cache_pages",
                   "unattributed"):
        assert needle in text, f"missing hint about {needle}: {hints}"
    # no context at all still yields a fallback hint
    assert oom_hints({}, {"components": {}, "bytes_in_use": 0,
                          "unattributed_bytes": 0})


def test_oom_incident_dump_schema(tmp_path, fresh_ledger):
    from deepspeed_tpu.telemetry.flight import (dump_on_exception,
                                                install_flight_recorder)

    led = fresh_ledger
    led.attach("params", lambda: {"device": 4096})
    led.update_context(zero_stage=0, offload_optimizer=False)
    fr = FlightRecorder(path=str(tmp_path), registry=led.registry)
    err = RuntimeError("RESOURCE_EXHAUSTED: Out of memory allocating 1 GiB")
    install_flight_recorder(fr)
    try:
        path = dump_on_exception("engine.train_batch", err)
    finally:
        install_flight_recorder(None)
    assert path is not None and "oom" in path
    recs = [json.loads(line) for line in open(path)]
    kinds = [r["kind"] for r in recs]
    assert kinds[0] == "flight_header"
    assert "memory" in kinds  # every dump carries the ledger section
    inc = next(r for r in recs if r["kind"] == "oom_incident")
    assert inc["where"] == "engine.train_batch"
    assert "RESOURCE_EXHAUSTED" in inc["error"]
    assert inc["ledger"]["components"]["params"]["device"] == 4096
    assert inc["memory_stats"]["bytes_in_use"] == 1000
    assert inc["hints"] and isinstance(inc["hints"], list)
    assert led.registry.get(
        "deepspeed_tpu_memory_oom_incidents_total").value(
        where="engine.train_batch") == 1


def test_non_oom_exception_keeps_plain_dump(tmp_path, fresh_ledger):
    from deepspeed_tpu.telemetry.flight import (dump_on_exception,
                                                install_flight_recorder)

    fr = FlightRecorder(path=str(tmp_path), registry=fresh_ledger.registry)
    install_flight_recorder(fr)
    try:
        path = dump_on_exception("engine.step", ValueError("not memory"))
    finally:
        install_flight_recorder(None)
    recs = [json.loads(line) for line in open(path)]
    kinds = [r["kind"] for r in recs]
    assert "oom_incident" not in kinds
    assert "memory" in kinds  # the snapshot section rides every dump


# ----------------------------- engine wiring ---------------------------------
@pytest.mark.parametrize("stage", [0, 3])
def test_engine_trainstate_attribution(stage, fresh_ledger):
    import deepspeed_tpu
    from tests.unit.simple_model import random_batch, simple_mlp_spec

    engine, *_ = deepspeed_tpu.initialize(
        model=simple_mlp_spec(),
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": stage},
                "telemetry": {"enabled": True}})
    engine.train_batch(random_batch(batch_size=8, gas=1, seed=0))
    report = fresh_ledger.publish()
    comp = report["components"]
    got = sum(comp[c]["device"] + comp[c]["host"]
              for c in ("master_params", "optimizer_state", "grads",
                        "train_scalars"))
    assert got == _structural_bytes(engine.state)
    assert comp["master_params"]["device"] > 0
    assert report["watermarks"].get("train_batch", 0) > 0
    ctx = fresh_ledger.context
    assert ctx["zero_stage"] == stage and ctx["offload_optimizer"] is False


def test_engine_offload_host_attribution(fresh_ledger):
    import deepspeed_tpu
    from tests.unit.simple_model import random_batch, simple_mlp_spec

    engine, *_ = deepspeed_tpu.initialize(
        model=simple_mlp_spec(),
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
                "zero_optimization": {
                    "offload_optimizer": {"device": "cpu"}},
                "telemetry": {"enabled": True}})
    engine.train_batch(random_batch(batch_size=8, gas=1, seed=0))
    comp = fresh_ledger.collect()["components"]
    off = engine.offload_optimizer
    assert comp["master_params"]["host"] == off.master_bytes() > 0
    assert comp["optimizer_state"]["host"] == off.moment_bytes() > 0
    # the device side still sums exactly to the TrainState
    dev = sum(comp[c]["device"]
              for c in ("params", "grads", "train_scalars"))
    assert dev == _structural_bytes(engine.state)
    assert fresh_ledger.context["offload_optimizer"] is True


# ----------------------------- serving pool gauges ---------------------------
def test_engine_v2_pool_gauges_and_kv_attribution(fresh_ledger,
                                                  fresh_registry):
    from deepspeed_tpu.inference.v2 import (InferenceEngineV2,
                                            RaggedInferenceConfig,
                                            RaggedRequest)
    from deepspeed_tpu.models.llama import llama_model

    model = llama_model("tiny", max_seq_len=64)
    eng = InferenceEngineV2(model, RaggedInferenceConfig(
        dtype="fp32", page_size=8, num_pages=16, max_seqs=2,
        max_pages_per_seq=4, enable_prefix_cache=True))
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, model.config.vocab_size, 9).tolist()
               for _ in range(2)]
    eng.generate_all([RaggedRequest(prompt_ids=p, max_new_tokens=3)
                      for p in prompts])
    used = fresh_registry.get("deepspeed_tpu_serving_kv_pages_used")
    free = fresh_registry.get("deepspeed_tpu_serving_kv_pages_free")
    pinned = fresh_registry.get("deepspeed_tpu_serving_kv_pages_pinned")
    assert used.value() == eng.allocator.used_pages
    assert free.value() == eng.allocator.free_pages
    assert pinned.value() == eng.allocator.lru_pages
    assert used.value() + free.value() == eng.block.num_pages
    # retired sequences parked their registered pages in the LRU
    assert pinned.value() > 0
    # admission/preemption events carry the pool occupancy
    from deepspeed_tpu.telemetry.spans import get_span_recorder

    admits = [s for s in get_span_recorder().spans() if s.name == "admit"]
    assert admits and {"pages_used", "pages_free",
                       "pages_pinned"} <= set(admits[-1].attrs)
    # ledger: pool + weights attributed exactly; pinned slice informational
    comp = fresh_ledger.collect()["components"]
    assert comp["kv_pool"]["device"] == _structural_bytes(eng._pools)
    assert comp["serving_params"]["device"] == _structural_bytes(eng.params)
    per_page = _structural_bytes(eng._pools) // (eng.block.num_pages + 1)
    assert comp["kv_prefix_pinned"]["device"] == \
        per_page * eng.allocator.lru_pages
    assert comp["kv_prefix_pinned"]["informational"] is True


def test_engine_rebuild_and_close_release_ledger_slots(fresh_ledger):
    """An offload engine attaches a 'params' slot; a non-offload rebuild
    must clear it (or attribution double-counts), and close() must
    release the closures that would pin the TrainState — unless a newer
    engine already owns the name (provider identity guard)."""
    import deepspeed_tpu
    from tests.unit.simple_model import simple_mlp_spec

    e1, *_ = deepspeed_tpu.initialize(
        model=simple_mlp_spec(),
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
                "zero_optimization": {
                    "offload_optimizer": {"device": "cpu"}},
                "telemetry": {"enabled": True}})
    assert "params" in fresh_ledger.collect()["components"]
    e2, *_ = deepspeed_tpu.initialize(
        model=simple_mlp_spec(),
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
                "telemetry": {"enabled": True}})
    comp = fresh_ledger.collect()["components"]
    assert "params" not in comp  # e1's offload-only slot was cleared
    got = sum(comp[c]["device"] + comp[c]["host"]
              for c in ("master_params", "optimizer_state", "grads",
                        "train_scalars"))
    assert got == _structural_bytes(e2.state)
    # e1.close() must NOT detach the names e2 now owns
    e1.close()
    assert "master_params" in fresh_ledger.collect()["components"]
    e2.close()
    assert not any(
        c in fresh_ledger.collect()["components"]
        for c in ("params", "master_params", "optimizer_state", "grads",
                  "train_scalars"))
    e2.close()  # idempotent


def test_phase_watch_survives_disabled_span_ring(fresh_ledger):
    """Watermarks ride span boundaries even with span RECORDING off —
    the ring and the phase watch are orthogonal."""
    from deepspeed_tpu.telemetry.spans import SpanRecorder, set_span_recorder

    old = None
    try:
        from deepspeed_tpu.telemetry.spans import get_span_recorder

        old = get_span_recorder()
        rec = SpanRecorder(ring_size=32, enabled=False)
        set_span_recorder(rec)
        fresh_ledger.install_phase_watch()
        fresh_ledger._acc.stats = {"bytes_in_use": 77,
                                   "peak_bytes_in_use": 77}
        with rec.span("forward"):
            pass
        assert fresh_ledger.watermarks()["forward"] == 77
        assert rec.spans() == []  # nothing recorded, only observed
    finally:
        set_span_recorder(old)


def test_oom_forensics_failure_falls_back_to_plain_dump(tmp_path,
                                                        fresh_ledger,
                                                        monkeypatch):
    """If the incident report itself fails, the plain exception dump
    must still be written (the pre-forensics guarantee)."""
    from deepspeed_tpu.telemetry import flight as flight_mod
    from deepspeed_tpu.telemetry import memory as memory_mod

    fr = FlightRecorder(path=str(tmp_path), registry=fresh_ledger.registry)
    flight_mod.install_flight_recorder(fr)
    monkeypatch.setattr(memory_mod, "record_oom_incident",
                        lambda *a, **k: None)
    try:
        path = flight_mod.dump_on_exception(
            "engine.step", RuntimeError("RESOURCE_EXHAUSTED: oom"))
    finally:
        flight_mod.install_flight_recorder(None)
    assert path is not None and "exception" in path


def test_allocator_occupancy_properties():
    from deepspeed_tpu.inference.v2.ragged import BlockAllocator

    a = BlockAllocator(8)
    assert (a.used_pages, a.free_pages, a.lru_pages) == (0, 8, 0)
    pages = a.alloc(3)
    assert (a.used_pages, a.free_pages, a.lru_pages) == (3, 5, 0)
    a.register(pages[0], b"key0")
    a.free(pages)
    # the registered page parks in the LRU; the others return to free
    assert (a.used_pages, a.free_pages, a.lru_pages) == (0, 8, 1)
    a.alloc(8)  # pool-wide alloc evicts the LRU page too
    assert (a.used_pages, a.free_pages, a.lru_pages) == (8, 0, 0)
