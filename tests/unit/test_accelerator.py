"""Accelerator ABI tests (reference: tests/unit/accelerator/)."""

import jax.numpy as jnp

from deepspeed_tpu.accelerator import (CPUAccelerator, TPUAccelerator, get_accelerator,
                                       set_accelerator)


def test_detection_cpu_sim():
    set_accelerator(None)  # type: ignore[arg-type]
    acc = get_accelerator()
    # conftest pins JAX_PLATFORMS=cpu → CPU accelerator with 8 virtual devices
    assert isinstance(acc, CPUAccelerator)
    assert acc.device_count() == 8
    assert acc.is_available()
    assert acc.device_name() == "cpu"
    assert acc.device_name(3) == "cpu:3"


def test_stream_event_shims():
    acc = get_accelerator()
    with acc.stream(acc.Stream()):
        pass
    ev = acc.Event()
    ev.record()
    ev.synchronize()
    acc.synchronize()


def test_dtype_and_comm_surface():
    acc = get_accelerator()
    assert acc.is_bf16_supported()
    assert jnp.bfloat16 in acc.supported_dtypes()
    assert acc.communication_backend_name().startswith("xla")
    assert acc.device_supports_graphs()


def test_rng_and_memory():
    acc = get_accelerator()
    acc.manual_seed(1234)
    assert acc.initial_seed() == 1234
    key = acc.default_generator()
    assert key.shape == (2,)
    assert acc.memory_allocated() >= 0


def test_op_builder_dispatch():
    acc = get_accelerator()
    b = acc.create_op_builder("CPUAdamBuilder")
    assert b is not None


def test_tpu_accelerator_props():
    tpu = TPUAccelerator()
    # no real TPU in CI: device list is empty but the ABI must not raise
    assert tpu.communication_backend_name() == "xla:ici"
    assert isinstance(tpu.device_kind(), str)
    assert isinstance(tpu.is_fp8_supported(), bool)
