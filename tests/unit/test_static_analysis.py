"""Tier-1 gates for the static-analysis subsystem (docs/STATIC_ANALYSIS.md).

Three layers, mirroring test_metric_names.py's pattern of gating the tree
AND unit-testing the analyzer itself so a silently-broken scanner can't
green-light a bad tree:

* hazard lint: the package is clean (zero unexplained suppressions), and
  each rule fires on fixture snippets — including the acceptance
  mutation: an ``.item()`` seeded into the decode loop turns the lint
  red with a message naming the rule and the hot path.
* HLO contracts: extraction on a toy shard_map program yields the known
  collective counts; the checked-in goldens (>= 6 programs) hold against
  a fresh extraction on this CPU harness; a seeded all-gather mutation
  produces a named, actionable diff; extraction + golden serialization
  round-trips byte-identically (--update-goldens is idempotent); and the
  3-step train-loop replay pins recompiles-after-warmup at 0.
"""

import importlib.util
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _load_by_path(name, *rel):
    path = os.path.join(REPO, *rel)
    if name in sys.modules:
        return sys.modules[name]
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def _hazard_lint():
    return _load_by_path("dstpu_hazard_lint", "deepspeed_tpu", "analysis",
                         "lint.py")


# ------------------------------------------------------------ hazard lint
def test_package_hazard_clean_with_documented_suppressions():
    """The tree lints clean, and every allow marker carries a reason —
    the 'zero unexplained suppressions' acceptance gate."""
    hl = _hazard_lint()
    violations = hl.check(REPO)
    assert not violations, "\n".join(str(v) for v in violations)
    sups = hl.suppressions(REPO)
    assert sups, "expected documented suppressions from the remediation pass"
    for rel, ln, rules, reason in sups:
        assert reason.strip(), f"{rel}:{ln}: allow[{rules}] without a reason"


def _write_tree(tmp_path, files):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
    (tmp_path / "tools").mkdir(exist_ok=True)
    return str(tmp_path)


def test_hazard_item_in_decode_loop_fails(tmp_path):
    """The acceptance mutation: an .item() seeded into the engine_v2 step
    loop exits non-zero, naming the rule and the hot path."""
    hl = _hazard_lint()
    root = _write_tree(tmp_path, {
        "deepspeed_tpu/inference/v2/engine_v2.py":
            "def _step_impl(self):\n"
            "    tok = logits.item()\n"
            "    return tok\n"})
    violations = hl.check(root)
    assert len(violations) == 1
    v = violations[0]
    assert v.rule == "host-sync" and ".item()" in v.message
    assert "_step_impl" in v.message
    # the same sync OUTSIDE any hot root passes (not reachable)
    root2 = _write_tree(tmp_path / "cold", {
        "deepspeed_tpu/inference/v2/engine_v2.py":
            "def _debug_dump(self):\n    return logits.item()\n"})
    assert hl.check(root2) == []


def test_hazard_blocking_socket_in_step_root_fails(tmp_path):
    """Seeded fail-by-name: a blocking socket ``recv`` reachable from a
    router/engine step root is a host-sync-class hazard (``socket-hot``)
    — the cross-process transport keeps ALL socket I/O on its sender
    thread precisely so the real tree stays clean of this."""
    hl = _hazard_lint()
    root = _write_tree(tmp_path, {
        "deepspeed_tpu/serving/router.py":
            "def step(self):\n"
            "    return self._poll_remote()\n"
            "def _poll_remote(self):\n"
            "    data = self._sock.recv(4096)\n"
            "    return data\n"})
    violations = hl.check(root)
    assert [v.rule for v in violations] == ["socket-hot"]
    assert ".recv()" in violations[0].message
    assert "_poll_remote" in violations[0].message
    # accept() inside an engine step root fails too
    root2 = _write_tree(tmp_path / "acc", {
        "deepspeed_tpu/inference/v2/engine_v2.py":
            "def step(self):\n"
            "    conn, _ = self.listener.accept()\n"
            "    return conn\n"})
    violations = hl.check(root2)
    assert [v.rule for v in violations] == ["socket-hot"]
    # the SAME call outside any hot root passes: the server/sender
    # threads are exactly where blocking socket I/O belongs
    root3 = _write_tree(tmp_path / "cold", {
        "deepspeed_tpu/serving/router.py":
            "def _sender_thread(self):\n"
            "    return self._sock.recv(4096)\n"})
    assert hl.check(root3) == []


def test_hazard_reachability_through_helpers(tmp_path):
    """A sync hidden two calls deep under train_batch is still found."""
    hl = _hazard_lint()
    root = _write_tree(tmp_path, {
        "deepspeed_tpu/runtime/engine.py":
            "def train_batch(self, batch):\n"
            "    self._report(1.0)\n"
            "def _report(self, loss):\n"
            "    self._publish(loss)\n"
            "def _publish(self, loss):\n"
            "    v = float(loss)\n"})
    violations = hl.check(root)
    assert [v.rule for v in violations] == ["host-sync"]
    assert "_publish" in violations[0].message


def test_hazard_pipe_tick_body_is_hot(tmp_path):
    """Pipe gates: a host sync seeded inside the pipe tick body
    (_pipe_body runs T = M + P - 1 times per step) fails by name, and
    the pipe overlap reducer must keep routing leaves through the
    shared bucketer — losing it is the monolithic-fp-all-reduce
    regression, named after the pipeline."""
    hl = _hazard_lint()
    root = _write_tree(tmp_path, {
        "deepspeed_tpu/runtime/pipe/engine.py":
            "def _pipe_body(params, ids, labels, stage_arr, pipe_comm):\n"
            "    s = float(stage_arr)\n"
            "    return s\n"})
    violations = hl.check(root)
    assert [v.rule for v in violations] == ["host-sync"]
    assert "_pipe_body" in violations[0].message

    root2 = _write_tree(tmp_path / "mono", {
        "deepspeed_tpu/runtime/pipe/overlap.py":
            "def reduce_stage_grads(self, dlayers):\n"
            "    return psum_tree(dlayers)\n"})
    violations = hl.check(root2)
    assert [v.rule for v in violations] == ["grad-overlap"]
    assert "monolithic fp post-backward all-reduce" in violations[0].message
    root3 = _write_tree(tmp_path / "ok", {
        "deepspeed_tpu/runtime/pipe/overlap.py":
            "def reduce_stage_grads(self, dlayers):\n"
            "    return coalesce_flat(bucketed_map(dlayers))\n"})
    assert hl.check(root3) == []


def test_hazard_numerics_stats_pull_is_boundary_cadence_only(tmp_path):
    """The numerics observatory's contract: the in-graph stats tree is
    device-resident until the steps_per_print boundary pulls it.  An
    eager `.item()` on the stats tree seeded into the fused train_batch
    path fails the host-sync rule by name — turning numerics on must not
    grow the hot path a per-step sync."""
    hl = _hazard_lint()
    root = _write_tree(tmp_path, {
        "deepspeed_tpu/runtime/engine.py":
            "def train_batch(self, batch):\n"
            "    state, loss, stats = self._fused(batch)\n"
            "    self._last_numerics = stats\n"
            "    gn = stats['grad_norm'].item()\n"
            "    return loss\n"})
    violations = hl.check(root)
    assert [v.rule for v in violations] == ["host-sync"]
    assert ".item()" in violations[0].message
    assert "train_batch" in violations[0].message
    # the legitimate shape — one documented device_get at the reporting
    # boundary, off the per-step path — lints clean
    root2 = _write_tree(tmp_path / "boundary", {
        "deepspeed_tpu/runtime/engine.py":
            "def train_batch(self, batch):\n"
            "    state, loss, stats = self._fused(batch)\n"
            "    self._last_numerics = stats\n"
            "    self._numerics_boundary()\n"
            "    return loss\n"
            "def _numerics_boundary(self):\n"
            "    # dstpu-lint: allow[host-sync] boundary cadence pull\n"
            "    host = jax.device_get(self._last_numerics)\n"
            "    return host\n"})
    assert hl.check(root2) == []


def test_hazard_rules_fire_and_allowlist_suppresses(tmp_path):
    hl = _hazard_lint()
    root = _write_tree(tmp_path, {
        "deepspeed_tpu/runtime/worker.py":
            "import time, random\n"
            "t0 = time.time()\n"
            "x = random.randint(0, 3)\n"
            "def f(acc=[]):\n"
            "    try:\n"
            "        pass\n"
            "    except Exception:\n"
            "        pass\n",
        "deepspeed_tpu/runtime/zero/strategy.py":
            "def specs(tree):\n"
            "    return [k for k in set(tree)]\n"})
    rules = sorted(v.rule for v in hl.check(root))
    assert rules == ["mutable-default", "pytree-order", "swallow",
                     "unseeded-random", "wall-clock"], rules

    # every violation suppressible with a REASONED marker; reasonless
    # markers and unknown rules are themselves violations
    root2 = _write_tree(tmp_path / "ok", {
        "deepspeed_tpu/runtime/worker.py":
            "import time, random\n"
            "t0 = time.time()  # dstpu-lint: allow[wall-clock] record stamp\n"
            "# dstpu-lint: allow[unseeded-random] fixture only\n"
            "x = random.randint(0, 3)\n"})
    assert hl.check(root2) == []
    root3 = _write_tree(tmp_path / "bad", {
        "deepspeed_tpu/runtime/worker.py":
            "import time\n"
            "t0 = time.time()  # dstpu-lint: allow[wall-clock]\n"
            "t1 = time.time()  # dstpu-lint: allow[wall-clok] typoed rule\n"})
    msgs = "\n".join(v.message for v in hl.check(root3))
    assert "without a reason" in msgs
    assert "unknown rule" in msgs


def test_hazard_docstring_marker_is_not_a_suppression(tmp_path):
    """A marker EXAMPLE quoted in a docstring must neither suppress the
    violation below it nor count as a documented suppression."""
    hl = _hazard_lint()
    root = _write_tree(tmp_path, {
        "deepspeed_tpu/runtime/engine.py":
            "def train_batch(self, loss):\n"
            '    """Example:\n'
            "    # dstpu-lint: allow[host-sync] docs only\n"
            '    """\n'
            "    return float(loss)\n"})
    violations = hl.check(root)
    assert [v.rule for v in violations] == ["host-sync"]
    assert hl.suppressions(root) == []


def test_hazard_nested_def_reported_once(tmp_path):
    """A sync inside a nested def is one violation, not one per
    reachability path."""
    hl = _hazard_lint()
    root = _write_tree(tmp_path, {
        "deepspeed_tpu/runtime/engine.py":
            "def train_batch(self, x):\n"
            "    def inner():\n"
            "        return float(x)\n"
            "    return inner()\n"})
    violations = hl.check(root)
    assert len(violations) == 1, violations


def test_hazard_marker_rides_comment_block_and_statement(tmp_path):
    """A marker whose reason wraps, sitting above a multi-line statement,
    still covers syncs on the statement's later lines."""
    hl = _hazard_lint()
    root = _write_tree(tmp_path, {
        "deepspeed_tpu/runtime/engine.py":
            "def train_batch(self, loss, scale):\n"
            "    # dstpu-lint: allow[host-sync] boundary cadence; the\n"
            "    # queue is already drained here\n"
            "    log(f'{float(loss)} '\n"
            "        f'{float(scale)}')\n"})
    assert hl.check(root) == []


def test_hazard_slo_exemplar_contract_fails_by_name(tmp_path):
    """The exemplar-coverage contract: a `deepspeed_tpu_serving_slo_*`
    `.inc()` inside a function that never calls `slo_exemplar` fails by
    name — for BOTH counter idioms (name/attribute bound at
    registration, and an accessor function returning a registration)."""
    hl = _hazard_lint()
    root = _write_tree(tmp_path, {
        "deepspeed_tpu/serving/slo_x.py":
            "from deepspeed_tpu.telemetry.reqtrace import slo_exemplar\n"
            "class Shed:\n"
            "    def __init__(self, reg):\n"
            "        self._m_shed = reg.counter(\n"
            "            'deepspeed_tpu_serving_slo_shed_total', 'h',\n"
            "            labelnames=('reason',))\n"
            "    def bad(self):\n"
            "        self._m_shed.inc(reason='queue_full')\n"
            "    def good(self, tid):\n"
            "        self._m_shed.inc(reason='queue_full')\n"
            "        slo_exemplar('deepspeed_tpu_serving_slo_shed_total',\n"
            "                     tid, reason='queue_full')\n"
            "def ttft_counter(reg):\n"
            "    return reg.counter(\n"
            "        'deepspeed_tpu_serving_slo_ttft_violations_total', 'h')\n"
            "def also_bad(reg):\n"
            "    ttft_counter(reg).inc()\n"})
    vs = [v for v in hl.check(root) if v.rule == "slo-exemplar"]
    msgs = "\n".join(v.message for v in vs)
    assert len(vs) == 2, msgs                    # bad + also_bad, not good
    assert "deepspeed_tpu_serving_slo_shed_total.inc() in 'bad'" in msgs
    assert ("deepspeed_tpu_serving_slo_ttft_violations_total.inc() "
            "in 'also_bad'") in msgs
    assert "offending trace_id" in msgs

    # no-single-request increments (breaker recovery) suppress with a
    # REASONED marker like every other rule
    root2 = _write_tree(tmp_path / "ok", {
        "deepspeed_tpu/serving/slo_x.py":
            "class B:\n"
            "    def __init__(self, reg):\n"
            "        self._m_rec = reg.counter(\n"
            "            'deepspeed_tpu_serving_slo_breaker_recoveries_total'"
            ", 'h')\n"
            "    def recover(self):\n"
            "        # dstpu-lint: allow[slo-exemplar] a recovery clears a\n"
            "        # replica-level state; there is no offending request\n"
            "        self._m_rec.inc()\n"})
    assert [v for v in hl.check(root2) if v.rule == "slo-exemplar"] == []


# ---------------------------------------------------------- HLO contracts
@pytest.fixture(scope="module")
def contracts_mod():
    from deepspeed_tpu.analysis import contracts

    return contracts


@pytest.fixture(scope="module")
def extracted(contracts_mod):
    """One full extraction shared by the golden/idempotency/replay tests
    (it lowers + compiles every program; don't repeat it per test)."""
    devs = __import__("jax").devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return contracts_mod.extract_all()


def test_toy_contract_extraction_counts_collectives(contracts_mod, devices8):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from deepspeed_tpu.utils.jax_compat import shard_map

    mesh = Mesh(np.array(devices8).reshape(8), ("data",))

    def body(x):
        return jax.lax.psum(x, "data") + jax.lax.all_gather(
            x, "data").sum(axis=0)

    fn = jax.jit(shard_map(body, mesh=mesh, in_specs=P("data"),
                           out_specs=P("data"), check_vma=False),
                 donate_argnums=(0,))
    x = jax.device_put(jnp.ones((8, 4)), NamedSharding(mesh, P("data")))
    c = contracts_mod.extract_contract(fn, (x,), mesh)
    assert c["collectives"]["all-reduce"] == 1
    assert c["collectives"]["all-gather"] == 1
    assert c["collectives"]["all-to-all"] == 0
    assert c["flops"] > 0 and c["bytes_accessed"] > 0
    assert c["arg_shapes"] == ["float32[8, 4]"]

    def body2(x):  # the seeded mutation: one extra all-gather
        return jax.lax.psum(x, "data") + jax.lax.all_gather(
            x, "data").sum(axis=0) + jax.lax.all_gather(
            x * 2.0, "data").sum(axis=0)

    fn2 = jax.jit(shard_map(body2, mesh=mesh, in_specs=P("data"),
                            out_specs=P("data"), check_vma=False))
    c2 = contracts_mod.extract_contract(fn2, (x,), mesh)
    errs = contracts_mod.diff_contract(
        "toy", {"contract": c, "tolerances": {"flops": 10, "bytes_accessed": 10}},
        {"contract": c2})
    joined = "\n".join(errs)
    assert "toy: grew all-gather 1 -> 2" in joined, joined


def test_golden_contracts_hold(contracts_mod, extracted):
    """The headline tier-1 gate: every checked-in golden matches a fresh
    extraction; >= 6 programs covering train stages 0/1/3 + the serving
    programs (acceptance criteria)."""
    goldens = contracts_mod.load_goldens(REPO)
    assert len(goldens) >= 6, sorted(goldens)
    for required in ("train_step_zero0", "train_step_zero1",
                     "train_step_zero3", "prefill", "decode",
                     "paged_verify", "decode_multistep",
                     "train_step_zero1_hier",
                     "moe_dispatch_quantized", "train_step_zero1_overlap",
                     "train_step_zero3_prefetch",
                     "train_step_zero1_overlap_int8",
                     "train_step_zero3_prefetch_int8",
                     "train_step_pipe2"):
        assert required in goldens, f"missing golden for {required}"
    errors = contracts_mod.diff_all(goldens, extracted)
    assert not errors, "\n".join(errors)


def test_compressed_collective_contracts_pin_wire_shape(contracts_mod,
                                                        extracted):
    """The PR-11 programs pin the compressed-collective wire shape: the
    hierarchical train step keeps its reduce-scatter + all-gather hops
    and the quantized MoE dispatch keeps its all-to-alls (codes + scales
    ride combined ops; a fallback to full-precision dispatch or a
    lost/duplicated exchange changes these counts)."""
    hier = extracted["train_step_zero1_hier"]["contract"]["collectives"]
    assert hier["reduce-scatter"] >= 1, hier
    assert hier["all-gather"] >= 2, hier
    moe = extracted["moe_dispatch_quantized"]["contract"]["collectives"]
    assert moe["all-to-all"] >= 1, moe
    # the compressed-overlap programs (this PR) pin s8 ON THE WIRE inside
    # the loop: int8 codes ride combined collective ops, and the
    # residual state is a real donated train-state leaf
    ov1 = extracted["train_step_zero1_overlap_int8"]["contract"]
    assert ov1["s8_collectives"] >= 1, ov1
    assert ov1["collectives"]["all-to-all"] >= 1, ov1  # the two-hop hop 1
    assert ov1["comm_residual_bytes"] > 0, ov1
    ov3 = extracted["train_step_zero3_prefetch_int8"]["contract"]
    assert ov3["s8_collectives"] >= 1, ov3
    # the fp psum_scatters are GONE: the quantized reduce-scatter is an
    # all_to_all of codes + scales
    assert ov3["collectives"]["reduce-scatter"] == 0, ov3
    assert ov3["collectives"]["all-to-all"] >= 1, ov3


def test_pipe_contract_pins_hops_and_bubble(contracts_mod, extracted):
    """The pipe program pins the hop ring and the schedule shape: int8
    codes ride the collective-permutes (a silent fp32 hop fall-back
    changes s8_collectives), the EF residual slot is real state bytes,
    and the computed (P-1)/(M+P-1) bubble fraction diffs by name when
    the schedule degenerates."""
    c = extracted["train_step_pipe2"]["contract"]
    assert c["collectives"]["collective-permute"] >= 1, c
    assert c["s8_collectives"] >= 1, c
    assert c["comm_residual_bytes"] > 0, c
    assert abs(c["pipe_bubble_fraction"] - 1.0 / 3.0) < 1e-5, c
    replay = c.get("replay")
    assert replay is not None and replay["steps"] == 3
    if replay["compiles_after_warmup"] is not None:
        assert replay["compiles_after_warmup"] == 0, replay

    import copy

    golden = copy.deepcopy(extracted["train_step_pipe2"])
    golden["contract"]["pipe_bubble_fraction"] = 0.5
    golden["contract"]["collectives"]["collective-permute"] -= 1
    errs = contracts_mod.diff_contract(
        "train_step_pipe2", golden, extracted["train_step_pipe2"])
    joined = "\n".join(errs)
    assert "pipe_bubble_fraction" in joined, joined
    assert "collective-permute" in joined, joined


def test_seeded_collective_mutation_is_named(contracts_mod, extracted):
    """Tampering the stage-3 golden (as if the step grew two all-gathers)
    produces the named, actionable failure from the ISSUE."""
    import copy

    golden = copy.deepcopy(extracted["train_step_zero3"])
    golden["contract"]["collectives"]["all-gather"] -= 2
    errs = contracts_mod.diff_contract("train_step_zero3", golden,
                                       extracted["train_step_zero3"])
    assert len(errs) == 1
    g = golden["contract"]["collectives"]["all-gather"]
    assert f"grew all-gather {g} -> {g + 2}" in errs[0]
    assert "train_step_zero3" in errs[0]


@pytest.mark.parametrize("program", ["prefill", "moe_dispatch_quantized",
                                     "train_step_zero1_hier",
                                     "train_step_zero1_overlap",
                                     "train_step_zero3_prefetch",
                                     "train_step_zero1_overlap_int8",
                                     "train_step_zero3_prefetch_int8",
                                     "train_step_pipe2",
                                     "decode_multistep"])
def test_update_goldens_idempotent(contracts_mod, extracted, tmp_path,
                                   program):
    """Writing goldens twice — the second time from a fresh extraction of
    the same program — is byte-identical (covers the PR-11 compressed-
    collective programs AND the overlap/prefetch programs: their engine
    + replay setup must not leak state between extractions)."""
    first = {program: extracted[program]}
    contracts_mod.write_goldens(str(tmp_path), first)
    path = os.path.join(contracts_mod.goldens_dir(str(tmp_path)),
                        f"{program}.json")
    with open(path) as f:
        bytes1 = f.read()
    again = contracts_mod.extract_program(program)
    contracts_mod.write_goldens(str(tmp_path), {program: again})
    with open(path) as f:
        bytes2 = f.read()
    assert bytes1 == bytes2
    # and the round-trip loads back as the same contract
    loaded = contracts_mod.load_goldens(str(tmp_path))
    assert contracts_mod.diff_all(loaded, {program: again}) == []


def test_train_replay_recompile_contract(contracts_mod, extracted):
    """ROADMAP item 5 follow-through: the 3-step replay of the tiny train
    loop compiles ONLY on the first step — shape-signature churn the PR 3
    sentinel merely warns about at runtime is a hard failure here."""
    for prog in ("train_step_zero0", "train_step_zero1", "train_step_zero3"):
        replay = extracted[prog]["contract"].get("replay")
        assert replay is not None, prog
        assert replay["steps"] == 3
        if replay["compiles_after_warmup"] is not None:
            assert replay["compiles_after_warmup"] == 0, (
                f"{prog}: steady-state steps recompiled "
                f"{replay['compiles_after_warmup']}x")


def test_multistep_decode_replay_and_donation_contract(contracts_mod,
                                                       extracted):
    """The fused multi-step decode program's contract: the KV pool
    buffers stay donated (a lost donation doubles the pool's HBM), and
    the 3-dispatch replay across MIXED per-row produced lengths —
    different budget/EOS mixes, same shapes — compiles exactly once."""
    c = extracted["decode_multistep"]["contract"]
    assert c["donated_inputs"] >= 2, c  # the k/v pool leaves
    replay = c.get("replay")
    assert replay is not None and replay["steps"] == 3
    if replay["compiles_after_warmup"] is not None:
        assert replay["compiles_after_warmup"] == 0, (
            "fused decode recompiled across mixed produced-lengths: "
            f"{replay['compiles_after_warmup']}x (budgets/EOS must be "
            "data, never shapes)")


def test_contract_set_hash_tracks_goldens(contracts_mod, tmp_path):
    h = contracts_mod.contract_set_hash(REPO)
    assert len(h) == 64 and int(h, 16) >= 0
    # the hash follows the golden bytes (bench JSON provenance)
    import shutil

    dst = tmp_path / "tests" / "contracts"
    shutil.copytree(os.path.join(REPO, "tests", "contracts"), dst)
    assert contracts_mod.contract_set_hash(str(tmp_path)) == h
    with open(dst / "decode.json", "r+") as f:
        data = json.load(f)
        data["contract"]["collectives"]["all-gather"] += 1
        f.seek(0)
        json.dump(data, f)
        f.truncate()
    assert contracts_mod.contract_set_hash(str(tmp_path)) != h
    # no goldens at all -> explicit sentinel, never a hash-of-nothing
    # that would compare equal across unrelated contract sets
    assert contracts_mod.contract_set_hash(str(tmp_path / "void")) == \
        "no-goldens"


# -------------------------------------------------------- unified driver
def test_dstpu_lint_driver_merges_and_gates(tmp_path):
    import tools.dstpu_lint as dl

    # the real tree passes the AST sections
    assert dl.main(["--root", REPO]) == 0
    # a seeded violation turns the merged exit code red
    root = _write_tree(tmp_path, {
        "deepspeed_tpu/runtime/engine.py":
            "def train_batch(self, loss):\n    return loss.item()\n"})
    assert dl.main(["--root", root]) == 1


def test_check_metric_names_shim_back_compat():
    """The moved metric lint keeps its old entry point and API."""
    shim = _load_by_path("check_metric_names_shim", "tools",
                         "check_metric_names.py")
    assert shim.check(REPO) == []
    assert "deepspeed_tpu_train_phase_seconds" in shim.collect(REPO)
    assert shim.METRIC_NAME_RE.match("deepspeed_tpu_ok_total")
