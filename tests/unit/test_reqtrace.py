"""Request-trace ledger tests (`telemetry/reqtrace.py`).

All host logic, fast tier: the phase state machine and its partition
invariant (phases sum to end-to-end latency by construction), the
recompute rename on re-dispatch/preemption, the clock-free wire
snapshot round trip (including transit folding), ledger terminal
accounting into the `deepspeed_tpu_serving_reqtrace_*` family, the SLO
exemplar store, and the merged Perfetto artifact's schema.
"""

import json

import pytest

from deepspeed_tpu.telemetry.registry import MetricsRegistry
from deepspeed_tpu.telemetry.reqtrace import (PHASES, ReqTraceLedger,
                                              RequestTrace,
                                              get_reqtrace_ledger,
                                              merged_trace_events,
                                              set_reqtrace_ledger,
                                              slo_exemplar,
                                              write_merged_trace)


@pytest.fixture
def ledger():
    led = ReqTraceLedger(registry=MetricsRegistry())
    set_reqtrace_ledger(led)
    yield led
    set_reqtrace_ledger(None)


# ------------------------------------------------- phase state machine
def test_phases_partition_submit_to_finish_exactly():
    """transition() closes the open interval at the instant the next
    opens, so per-phase seconds sum to elapsed_s with no gap/overlap."""
    tr = RequestTrace("r1-0", uid=5, now=100.0)
    assert tr.phase == "queue_wait"
    tr.transition("prefill", "prefill0", now=100.5)
    tr.transition("kv_transfer", "prefill0", now=101.25)
    tr.transition("decode", "decode0", now=101.5)
    tr.note_first_token(now=101.75)
    tr.finish("complete", now=103.0)
    ph = tr.phase_seconds()
    assert ph["queue_wait"] == pytest.approx(0.5)
    assert ph["prefill"] == pytest.approx(0.75)
    assert ph["kv_transfer"] == pytest.approx(0.25)
    assert ph["decode"] == pytest.approx(1.5)
    assert sum(ph.values()) == pytest.approx(tr.elapsed_s(), abs=1e-12)
    assert tr.first_token_s == pytest.approx(1.75)
    assert tr.owners == ["router", "prefill0", "decode0"]
    # terminal: further transitions are ignored, not corrupting
    tr.transition("decode", "decode1", now=104.0)
    assert tr.elapsed_s() == pytest.approx(3.0)


def test_redispatch_keeps_original_clock_and_renames_to_recompute():
    """Satellite: re-dispatch does NOT restart the end-to-end clock,
    and the replacement prefill classifies as recompute."""
    tr = RequestTrace("r1-1", now=10.0)
    tr.transition("prefill", "prefill0", now=10.2)
    tr.transition("decode", "decode0", now=10.6)
    tr.note_redispatch(now=10.9)            # replica died mid-decode
    assert tr.phase == "queue_wait" and tr.attempts == 1
    tr.transition("prefill", "decode1", now=11.0)
    assert tr.phase == "recompute"          # renamed, not first-attempt
    tr.note_first_token(now=11.3)
    tr.finish("complete", now=11.5)
    assert tr.first_token_s == pytest.approx(1.3)   # from FIRST submit
    ph = tr.phase_seconds()
    assert ph["prefill"] == pytest.approx(0.4)      # first attempt only
    assert ph["recompute"] == pytest.approx(0.5)    # the re-run
    assert ph["queue_wait"] == pytest.approx(0.3)   # incl. re-dispatch gap
    assert sum(ph.values()) == pytest.approx(tr.elapsed_s(), abs=1e-12)


def test_preempt_renames_next_prefill_to_recompute():
    tr = RequestTrace("r1-2", now=0.0)
    tr.transition("prefill", "p0", now=0.1)
    tr.note_preempt("p0", now=0.3)
    tr.transition("prefill", "p0", now=0.4)
    assert tr.phase == "recompute"
    tr.finish("complete", now=0.6)
    assert tr.phase_seconds()["recompute"] == pytest.approx(0.2)


def test_unknown_phase_rejected():
    tr = RequestTrace("r1-3", now=0.0)
    with pytest.raises(ValueError, match="unknown reqtrace phase"):
        tr.transition("warmup", "router")


# ----------------------------------------------------- wire round trip
def test_wire_snapshot_round_trip_preserves_partition_invariant():
    """The snapshot is clock-free (durations only); re-anchoring on the
    importing host keeps phases summing to elapsed, with transit folded
    in as kv_transfer time."""
    import time

    t0 = time.perf_counter() - 0.8          # "submitted 0.8s ago"
    tr = RequestTrace("r2-0", uid=9, priority=1, now=t0)
    tr.transition("prefill", "prefill0", now=t0 + 0.3)
    tr.transition("kv_transfer", "prefill0", now=t0 + 0.8)  # open at export
    snap = tr.wire_snapshot()
    assert snap["trace_id"] == "r2-0" and snap["open_phase"] == "kv_transfer"
    assert all(len(p) == 3 for p in snap["phases"])     # durations only
    assert snap["elapsed_s"] >= 0.8                     # wall kept running

    n2 = time.perf_counter() + 5.0          # importing host, its own clock
    rt = RequestTrace.from_wire_snapshot(snap, transit_s=0.25, now=n2)
    assert rt.trace_id == "r2-0" and rt.uid == 9 and rt.priority == 1
    assert rt.transit_s == pytest.approx(0.25)
    ph = rt.phase_seconds()
    # remote elapsed + transit tile [submit_t, n2] on the LOCAL clock
    total = snap["elapsed_s"] + 0.25
    assert rt.elapsed_s(now=n2) == pytest.approx(total, abs=1e-9)
    assert sum(ph.values()) == pytest.approx(total, abs=1e-9)
    assert ph["queue_wait"] == pytest.approx(0.3)
    assert ph["prefill"] == pytest.approx(0.5)
    assert ph["kv_transfer"] >= 0.25                    # transit rides here
    # intervals are contiguous — no gaps, no overlaps
    spans = sorted(rt.intervals, key=lambda iv: iv[2])
    for a, b in zip(spans, spans[1:]):
        assert b[2] == pytest.approx(a[3], abs=1e-9)


def test_ledger_adopt_installs_wire_snapshot_as_open_trace(ledger):
    tr = ledger.begin("r2-1", uid=3)
    tr.transition("prefill", "p0")
    snap = tr.wire_snapshot()
    ledger.discard("r2-1")                  # left the exporting side
    adopted = ledger.adopt(snap, transit_s=0.0)
    assert ledger.get("r2-1") is adopted
    ledger.finish("r2-1", "complete")
    assert ledger.lookup("r2-1").done


# ---------------------------------------------------- ledger accounting
def test_ledger_terminal_accounting_feeds_reqtrace_metrics(ledger):
    reg = ledger._m_requests  # registered on the fixture registry
    tr = ledger.begin("r3-0", uid=1)
    tr.transition("prefill", "p0")
    tr.transition("decode", "d0")
    ledger.begin("r3-1", uid=2)
    assert ledger._m_open.value() == 2
    ledger.finish("r3-0", "complete")
    ledger.finish("r3-1", "shed")
    assert ledger._m_open.value() == 0
    assert reg.total() == 2
    s = ledger.summary()
    assert s["finished"] == 2 and s["reasons"] == {"complete": 1, "shed": 1}
    assert sum(s["phase_seconds"].values()) >= 0.0
    # finish is idempotent; discard of unknown ids is a no-op
    ledger.finish("r3-0", "complete")
    ledger.discard("never-began")
    assert reg.total() == 2


def test_ledger_finished_phase_seconds_sum_to_e2e(ledger):
    tr = ledger.begin("r3-2")
    tr.transition("prefill", "p0")
    tr.transition("decode", "d0")
    ledger.finish("r3-2", "complete")
    done = ledger.lookup("r3-2")
    assert done.done
    assert (sum(done.phase_seconds().values())
            == pytest.approx(done.elapsed_s(), abs=1e-9))


# ----------------------------------------------------------- exemplars
def test_slo_exemplar_records_trace_id_with_attrs(ledger):
    slo_exemplar("deepspeed_tpu_serving_slo_shed_total", "r4-0",
                 reason="queue_full", priority=2)
    slo_exemplar("deepspeed_tpu_serving_slo_shed_total", None)  # no ctx: noop
    ex = ledger.exemplars()
    rows = ex["deepspeed_tpu_serving_slo_shed_total"]
    assert rows == [{"metric": "deepspeed_tpu_serving_slo_shed_total",
                     "trace_id": "r4-0", "reason": "queue_full",
                     "priority": 2}]
    assert ledger._m_exemplars.total() == 1


def test_slo_exemplar_noop_without_ledger():
    set_reqtrace_ledger(None)
    assert get_reqtrace_ledger() is None
    slo_exemplar("deepspeed_tpu_serving_slo_shed_total", "r4-1")  # no raise


def test_exemplar_ring_is_bounded(ledger):
    for i in range(40):
        slo_exemplar("deepspeed_tpu_serving_slo_ttft_violations_total",
                     f"r4-{i}")
    rows = ledger.exemplars()[
        "deepspeed_tpu_serving_slo_ttft_violations_total"]
    assert len(rows) == 32                      # ring, not unbounded
    assert rows[-1]["trace_id"] == "r4-39"      # newest kept


# ------------------------------------------------------- merged artifact
def test_merged_trace_artifact_schema_and_tracks(ledger, tmp_path):
    for i, owner in enumerate(["decode0", "decode1"]):
        tr = ledger.begin(f"r5-{i}", uid=i)
        tr.transition("prefill", "prefill0")
        tr.transition("kv_transfer", "prefill0")
        tr.transition("decode", owner)
        ledger.finish(f"r5-{i}", "complete")
    events = merged_trace_events(ledger=ledger)
    assert events, "finished traces must produce events"
    for ev in events:
        assert {"ph", "ts", "dur", "pid", "tid", "name"} <= set(ev)
        assert ev["ph"] in ("X", "M")
    owners = {ev["args"]["name"] for ev in events
              if ev["ph"] == "M" and ev["name"] == "process_name"}
    assert owners == {"router", "prefill0", "decode0", "decode1"}
    tracks = {ev["args"]["name"] for ev in events
              if ev["ph"] == "M" and ev["name"] == "thread_name"}
    assert tracks == {"r5-0", "r5-1"}           # one thread per trace_id
    for tid in ("r5-0", "r5-1"):
        slices = {ev["name"] for ev in events if ev["ph"] == "X"
                  and ev.get("args", {}).get("trace_id") == tid}
        assert {"queue_wait", "prefill", "kv_transfer", "decode"} <= slices

    path = str(tmp_path / "fleet_trace.json")
    n = write_merged_trace(path, ledger=ledger)
    with open(path) as f:
        doc = json.load(f)
    assert len(doc["traceEvents"]) == n == len(events)


def test_merged_trace_empty_without_ledger():
    set_reqtrace_ledger(None)
    assert merged_trace_events() == []


def test_phase_taxonomy_is_frozen():
    """The docs' sums-to-latency contract names exactly these phases;
    adding one is a docs + catalog change, not a drive-by."""
    assert PHASES == ("queue_wait", "prefill", "recompute", "kv_transfer",
                      "decode")
