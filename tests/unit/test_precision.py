"""Loss-scaler semantics (reference tests/unit/runtime/half_precision)."""

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.runtime.config import FP16Config
from deepspeed_tpu.runtime.precision import (LossScaleState, check_overflow,
                                             update_loss_scale)
from tests.unit.simple_model import random_batch, simple_mlp_spec


def test_default_scale_is_representable_in_fp32_path():
    cfg = FP16Config.from_dict({"enabled": True})
    s = LossScaleState.create(cfg)
    assert float(s.cur_scale) == 65536.0


def test_persistent_overflow_halves_scale():
    """With default hysteresis=2, repeated overflow must eventually halve."""
    cfg = FP16Config.from_dict({"enabled": True, "initial_scale_power": 16})
    s = LossScaleState.create(cfg)
    overflow = jnp.asarray(True)
    s = update_loss_scale(s, overflow, cfg)  # consumes hysteresis 2->1
    assert float(s.cur_scale) == 65536.0
    s = update_loss_scale(s, overflow, cfg)  # 1->0: halves
    assert float(s.cur_scale) == 32768.0
    s = update_loss_scale(s, overflow, cfg)  # keeps halving
    assert float(s.cur_scale) == 16384.0


def test_clean_steps_replenish_hysteresis_and_grow():
    cfg = FP16Config.from_dict({"enabled": True, "loss_scale_window": 2, "hysteresis": 2})
    s = LossScaleState.create(cfg)
    s = update_loss_scale(s, jnp.asarray(True), cfg)  # hyst 2->1
    s = update_loss_scale(s, jnp.asarray(False), cfg)  # replenishes to 2
    assert int(s.hysteresis_tracker) == 2
    s = update_loss_scale(s, jnp.asarray(False), cfg)  # window hit: doubles
    assert float(s.cur_scale) == 2 * 65536.0


def test_static_scale_never_changes():
    cfg = FP16Config.from_dict({"enabled": True, "loss_scale": 128.0})
    s = LossScaleState.create(cfg)
    s = update_loss_scale(s, jnp.asarray(True), cfg)
    assert float(s.cur_scale) == 128.0


def test_check_overflow():
    good = {"a": jnp.ones(3)}
    bad = {"a": jnp.asarray([1.0, jnp.inf])}
    assert not bool(check_overflow(good))
    assert bool(check_overflow(bad))


def test_fp16_training_default_scale_not_inf():
    """fp16 with DEFAULT initial_scale_power=16 must not produce inf loss
    (scale multiply must happen in fp32)."""
    engine, *_ = deepspeed_tpu.initialize(
        model=simple_mlp_spec(),
        config={"train_micro_batch_size_per_gpu": 2,
                "fp16": {"enabled": True},
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}})
    for i in range(5):
        loss = engine.train_batch(random_batch(batch_size=8, seed=i, gas=1))
        assert np.isfinite(float(loss))
    # defaults must not skip every step
    assert int(engine.state.step) > 0
