"""engine.compile() pass tests (reference: tests/unit/v1/compile, deepspeed/compile/)."""

import numpy as np
import pytest

import deepspeed_tpu
from tests.unit.simple_model import random_batch, simple_mlp_spec


def _engine(**cfg_extra):
    cfg = {
        "train_micro_batch_size_per_gpu": 4,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
        "gradient_clipping": 1.0,
    }
    cfg.update(cfg_extra)
    engine, *_ = deepspeed_tpu.initialize(model=simple_mlp_spec(), config=cfg)
    return engine


def test_compile_default_passes():
    engine = _engine()
    out = engine.compile()
    assert out is engine
    assert engine.is_compiled
    assert "zero3_compile" in engine.compile_passes_applied
    losses = [float(engine.train_batch(random_batch(batch_size=16, seed=i % 4, gas=1)))
              for i in range(10)]
    assert losses[-1] < losses[0]


def test_compile_unknown_pass_raises():
    engine = _engine()
    with pytest.raises(KeyError):
        engine.compile(passes=["not_a_pass"])
    with pytest.raises(ValueError):
        engine.compile(backend="tvm")


def test_compile_offload_adam_states_still_trains():
    engine = _engine()
    l0 = float(engine.train_batch(random_batch(batch_size=16, seed=0, gas=1)))
    engine.compile(passes=["offload_adam_states"])
    losses = [float(engine.train_batch(random_batch(batch_size=16, seed=i % 4, gas=1)))
              for i in range(10)]
    assert losses[-1] < l0


def test_compile_offload_activation_remat():
    from deepspeed_tpu.models.llama import llama_model

    model = llama_model("tiny", max_seq_len=32)
    assert not model.config.remat
    engine, *_ = deepspeed_tpu.initialize(
        model=model,
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "AdamW", "params": {"lr": 5e-3}}})
    engine.compile(passes=["offload_activation"])
    assert model.config.remat
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 256, (1, 2, 32)).astype(np.int32)
    import jax.numpy as jnp

    batch = {"input_ids": jnp.asarray(ids)}
    losses = [float(engine.train_batch(batch)) for _ in range(8)]
    assert losses[-1] < losses[0]
