"""Numerics observatory (telemetry/numerics.py): in-graph per-layer
training-health stats, the anomaly sentinel + flight dump + checkpoint
incident annotation, and the cross-data-rank divergence audit.

The engine-level tests run the REAL fused path (stats ride the step as a
third output, pulled only at the steps_per_print boundary) so they prove
the wiring, not just the pure functions.
"""

import dataclasses
import glob
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.telemetry import numerics as nm

from tests.unit.simple_model import simple_mlp_spec

HIDDEN = 16


def _mlp_engine(tmp_path, extra_cfg=None, numerics_cfg=None):
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "steps_per_print": 1,
        "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
        "telemetry": {
            "enabled": True,
            "numerics": dict({"enabled": True}, **(numerics_cfg or {})),
            # keep anomaly dumps inside the test sandbox (the recorder
            # is on by default with a cwd-relative dir)
            "flight_recorder": {"enabled": True,
                                "path": str(tmp_path / "flight")},
        },
    }
    cfg.update(extra_cfg or {})
    engine, *_ = deepspeed_tpu.initialize(model=simple_mlp_spec(HIDDEN),
                                          config=cfg)
    return engine


def _mlp_batch(engine, seed=0, scale=1.0, poison=None):
    rng = np.random.RandomState(seed)
    B = engine.config.train_batch_size
    x = (rng.randn(B, HIDDEN) * scale).astype(np.float32)
    y = (x * 0.5).astype(np.float32)
    if poison is not None:
        x[:] = poison
    return (jnp.asarray(x[None]), jnp.asarray(y[None]))


# --------------------------------------------------------------- pure parts

def test_tree_health_and_stacked_health():
    tree = {"a": jnp.array([3.0, 4.0]), "b": jnp.array([[0.0, jnp.inf]])}
    h = jax.device_get(nm.tree_health(tree))
    assert int(h["nonfinite"]) == 1
    # max_abs reports the RAW magnitude — an inf there is the signal
    assert float(h["max_abs"]) == float("inf")
    stacked = {"w": jnp.ones((3, 4)), "b": jnp.zeros((3,))}
    s = jax.device_get(nm.stacked_health(stacked))
    assert s["norm"].shape == (3,)
    assert np.allclose(s["norm"], 2.0)  # sqrt(4*1 + 0)
    # not a stacked tree (leading dims disagree) -> None, callers gate
    assert nm.stacked_health({"w": jnp.ones((3, 4)),
                              "v": jnp.ones((2, 4))}) is None


def test_compare_rank_checksums_names_first_diverging_leaf():
    ok = nm.compare_rank_checksums({0: {"a/w": 1, "b/w": 2},
                                    1: {"a/w": 1, "b/w": 2}})
    assert ok["ok"] and ok["first_diverging_leaf"] is None
    bad = nm.compare_rank_checksums({0: {"a/w": 1, "b/w": 2},
                                     1: {"a/w": 1, "b/w": 3}})
    assert not bad["ok"]
    assert bad["first_diverging_leaf"] == "b/w"
    assert bad["diverging"] == ["b/w"]
    # a single rank is vacuously consistent
    assert nm.compare_rank_checksums({0: {"a/w": 7}})["ok"]


def test_shape_boundary_report_first_nonfinite_layer():
    host = {
        "loss": np.float32(2.0), "grad_norm": np.float32(1.0),
        "skipped_steps": np.int32(0), "opt_nonfinite": np.int32(0),
        "grad": {"norm": np.float32(1.0), "max_abs": np.float32(0.5),
                 "nonfinite": np.int32(3)},
        "param": {"norm": np.float32(9.0), "max_abs": np.float32(1.0),
                  "nonfinite": np.int32(0)},
        "grad_leaf_nonfinite": {"layer_1/w": np.int32(3),
                                "layer_0/w": np.int32(0)},
        # [L, 3] act stats: layer 0 healthy, layer 2 went nonfinite
        "act_layers": np.array([[1.0, 0.5, 0.0],
                                [2.0, 0.7, 0.0],
                                [np.inf, np.inf, 4.0]], np.float32),
    }
    rep = nm.shape_boundary_report(host)
    assert rep["grad_nonfinite"] == 3
    assert rep["first_nonfinite_layer"] == 2
    assert rep["first_nonfinite_leaf"] == "layer_1/w"
    assert rep["layers"]["act_nonfinite"] == [0, 0, 4]
    # the report is JSON-serializable as-is (flight dumps write it)
    json.dumps(nm._json_safe(rep))


def test_ledger_detects_and_state_roundtrips():
    led = nm.NumericsLedger(None)
    base = {"step": 0, "loss": 1.0, "grad_norm": 1.0, "skipped_steps": 0,
            "grad_nonfinite": 0}
    for i in range(8):
        # slight drift keeps the stagnant-loss detector quiet
        assert led.observe_boundary(dict(base, step=i,
                                         loss=1.0 + 0.01 * i)) == []
    # loss spike vs the rolling median fires, and records an incident
    spiked = led.observe_boundary(dict(base, step=8, loss=100.0))
    assert [a["kind"] for a in spiked] == ["loss_spike"]
    assert led.anomaly_counts["loss_spike"] == 1
    inc = led.pending_incident()
    assert inc and inc["kinds"] == ["loss_spike"]
    # round-trip: a restored ledger carries the window AND the incident
    led2 = nm.NumericsLedger(None)
    led2.load_state_dict(json.loads(json.dumps(led.state_dict())))
    assert led2.summary()["boundaries"] == led.summary()["boundaries"]
    assert led2.anomaly_counts == led.anomaly_counts
    assert led2.consume_incident() == inc
    assert led2.consume_incident() is None  # consume-once
    # overflow storm: skipped-step delta between boundaries >= threshold
    led3 = nm.NumericsLedger(None)
    led3.observe_boundary(dict(base, skipped_steps=0))
    storm = led3.observe_boundary(dict(base, step=1, skipped_steps=4))
    assert [a["kind"] for a in storm] == ["overflow_storm"]
    assert storm[0]["skipped_since_last_boundary"] == 4


# ---------------------------------------------------------- engine wiring

def test_nan_injection_names_layer_in_report_and_dump(tmp_path):
    """NaN poisoned into the batch goes nonfinite in layer 0 first: the
    boundary report attributes it, the sentinel counts it, the flight
    dump carries the per-layer breakdown, and the next checkpoint tag's
    manifest is annotated for resume-time triage."""
    engine = _mlp_engine(tmp_path)
    engine.train_batch(_mlp_batch(engine, 0))
    engine.train_batch(_mlp_batch(engine, 1, poison=np.nan))
    rep = engine.numerics_report()
    assert rep is not None
    assert rep["anomaly_counts"].get("nonfinite", 0) >= 1
    last = rep["last_report"]
    assert last["grad_nonfinite"] > 0
    # leaf attribution: the first (lexicographic) nonfinite grad leaf
    assert last["first_nonfinite_leaf"].startswith("layer_0/")
    assert any(l.startswith("layer_0/") for l in last["nonfinite_leaves"])
    # the dump fired with the numerics record naming the same leaf
    dumps = glob.glob(str(tmp_path / "flight" / "*numerics_nonfinite*"))
    assert dumps, "anomaly must fire a flight dump"
    recs = [json.loads(l) for l in open(dumps[0])]
    numrec = [r for r in recs if r.get("kind") == "numerics"]
    assert numrec and numrec[0]["last_report"]["first_nonfinite_leaf"] \
        .startswith("layer_0/")
    # checkpoint annotation: the incident rides the next tag's manifest
    from deepspeed_tpu.resilience.commit import manifest_meta

    ckpt = str(tmp_path / "ckpt")
    engine.save_checkpoint(ckpt, tag="incident")
    inc = manifest_meta(ckpt, "incident").get("numerics_incident")
    assert inc and "nonfinite" in inc["kinds"]
    first = inc["anomalies"][0]
    assert first["first_nonfinite_leaf"].startswith("layer_0/")
    # consume-once: a later clean save is NOT re-stamped
    engine.save_checkpoint(ckpt, tag="clean")
    assert "numerics_incident" not in manifest_meta(ckpt, "clean")


def test_overflow_storm_trips_sentinel(tmp_path):
    """fp16 at 2^20 loss scale with huge activations overflows every
    early step; the skipped-step delta inside one reporting window trips
    the overflow_storm detector (the first boundary only seeds the
    skipped baseline, so the storm fires at the second)."""
    engine = _mlp_engine(
        tmp_path,
        extra_cfg={"fp16": {"enabled": True, "initial_scale_power": 20},
                   "steps_per_print": 4},
        numerics_cfg={"overflow_storm": 3})
    for i in range(8):
        engine.train_batch(_mlp_batch(engine, i, scale=1e3))
    assert int(engine.state.skipped_steps) >= 6
    rep = engine.numerics_report()
    assert rep["anomaly_counts"].get("overflow_storm", 0) >= 1
    # the loss-scale state rode the stats tree to the boundary report
    # (backed off from the forced 2^20 start by the overflow skips)
    assert rep["last_report"]["loss_scale"] < 2 ** 20


def test_divergence_audit_catches_bit_flip(tmp_path, devices8):
    """Master params are replicated across the data axis at ZeRO 0/1:
    the boundary checksum audit is bit-exact, and a single flipped bit
    in ONE rank's local replica fails the audit naming the leaf."""
    engine = _mlp_engine(tmp_path)
    if engine.topology.axis_size("data") < 2:
        pytest.skip("needs a >=2-way data axis")
    engine.train_batch(_mlp_batch(engine, 0))
    div = engine.divergence_audit()
    assert div is not None and div["ok"], div
    assert div["ranks"] >= 2

    p = engine.state.params["layer_0"]["w"]
    shards = sorted(p.addressable_shards, key=lambda s: s.device.id)
    bufs = []
    for i, sh in enumerate(shards):
        arr = np.array(sh.data)
        if i == 0:  # one rank's replica, one bit
            arr.view(np.uint32).ravel()[0] ^= 1
        bufs.append(jax.device_put(arr, sh.device))
    flipped = jax.make_array_from_single_device_arrays(
        p.shape, p.sharding, bufs)
    engine.state.params["layer_0"] = dict(
        engine.state.params["layer_0"], w=flipped)

    div = engine.divergence_audit()
    assert not div["ok"]
    assert div["first_diverging_leaf"] == "layer_0/w"
    assert div["diverging"] == ["layer_0/w"]

    # the flip survives an (identical-across-ranks) optimizer update, so
    # the NEXT boundary's audit catches it end-to-end: anomaly counted,
    # flight dump fired naming the leaf
    engine.train_batch(_mlp_batch(engine, 1))
    rep = engine.numerics_report()
    assert rep["anomaly_counts"].get("divergence", 0) >= 1
    dumps = glob.glob(str(tmp_path / "flight" / "*numerics_divergence*"))
    assert dumps, "divergence anomaly must fire a flight dump"


def test_sentinel_state_survives_checkpoint_roundtrip(tmp_path):
    """The rolling windows ride checkpoint client_state: a spike right
    after restore is judged against the pre-crash history."""
    e1 = _mlp_engine(tmp_path)
    for i in range(3):
        e1.train_batch(_mlp_batch(e1, i))
    before = e1._numerics.summary()
    assert before["boundaries"] == 3
    ckpt = str(tmp_path / "ckpt")
    e1.save_checkpoint(ckpt)

    from deepspeed_tpu.parallel import mesh as _mesh

    _mesh.reset_topology()
    e2 = _mlp_engine(tmp_path)
    assert e2._numerics.summary()["boundaries"] == 0
    e2.load_checkpoint(ckpt)
    after = e2._numerics.summary()
    assert after["boundaries"] == 3
    assert after["grad_norm_median"] == pytest.approx(
        before["grad_norm_median"])


def test_replay_recompiles_zero_with_numerics_on(tmp_path):
    """The acceptance pin: turning the observatory on must not grow the
    replay path a recompile (the stats tree is a fixed extra output of
    the SAME fused program)."""
    from deepspeed_tpu.telemetry.compile_sentinel import (
        compile_counts, install_compile_listener)

    install_compile_listener()
    engine = _mlp_engine(tmp_path)
    for i in range(2):  # warm-up: trace + donation-variant compiles
        engine.train_batch(_mlp_batch(engine, i))
    c0 = compile_counts()[0]
    for i in range(4):
        engine.train_batch(_mlp_batch(engine, 2 + i))
    assert compile_counts()[0] == c0, "replay must not recompile"
    rep = engine.numerics_report()
    assert rep["boundaries"] == 6
