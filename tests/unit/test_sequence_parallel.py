"""Ulysses + ring attention parity tests
(reference tests/unit/sequence_parallelism/test_ulysses.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.transformer import xla_attention
from deepspeed_tpu.parallel.mesh import MeshTopology, initialize_topology
from deepspeed_tpu.runtime.config import MeshConfig
from deepspeed_tpu.sequence.ring_attention import ring_attention
from deepspeed_tpu.sequence.ulysses import ulysses_attention
from tests.unit.simple_model import random_batch


def _qkv(b=2, s=64, nh=8, d=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (b, s, nh, d)) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_matches_dense(causal, devices8):
    initialize_topology(MeshConfig(data=1, sequence=8), devices8)
    q, k, v = _qkv()
    ref = xla_attention(q, k, v, causal)
    with deepspeed_tpu.get_topology().mesh:
        out = jax.jit(lambda q, k, v: ulysses_attention(q, k, v, causal))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=1e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_dense(causal, devices8):
    initialize_topology(MeshConfig(data=1, sequence=8), devices8)
    q, k, v = _qkv()
    ref = xla_attention(q, k, v, causal)
    out = jax.jit(lambda q, k, v: ring_attention(q, k, v, causal))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=1e-4)


def test_ring_gradients_match(devices8):
    initialize_topology(MeshConfig(data=1, sequence=8), devices8)
    q, k, v = _qkv(b=1, s=32, nh=4, d=8)

    g_ref = jax.grad(lambda q: jnp.sum(xla_attention(q, k, v, True) ** 2))(q)
    g_ring = jax.jit(jax.grad(
        lambda q: jnp.sum(ring_attention(q, k, v, True) ** 2)))(q)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_ref),
                               atol=5e-4, rtol=1e-3)


def test_llama_trains_with_ulysses(devices8):
    from deepspeed_tpu.models import llama_model

    model = llama_model("tiny", max_seq_len=32, attn_impl="ulysses")
    engine, *_ = deepspeed_tpu.initialize(
        model=model,
        config={"train_micro_batch_size_per_gpu": 4,
                "optimizer": {"type": "Adam", "params": {"lr": 5e-3}},
                "mesh": {"sequence": 4, "data": -1}})
    ids = np.random.RandomState(0).randint(0, 256, (1, 8, 32)).astype(np.int32)
    losses = [float(engine.train_batch({"input_ids": jnp.asarray(ids)}))
              for _ in range(5)]
    assert losses[-1] < losses[0]


def test_llama_trains_with_ring(devices8):
    from deepspeed_tpu.models import llama_model

    model = llama_model("tiny", max_seq_len=32, attn_impl="ring")
    engine, *_ = deepspeed_tpu.initialize(
        model=model,
        config={"train_micro_batch_size_per_gpu": 4,
                "optimizer": {"type": "Adam", "params": {"lr": 5e-3}},
                "mesh": {"sequence": 4, "data": -1}})
    ids = np.random.RandomState(0).randint(0, 256, (1, 8, 32)).astype(np.int32)
    losses = [float(engine.train_batch({"input_ids": jnp.asarray(ids)}))
              for _ in range(5)]
    assert losses[-1] < losses[0]
