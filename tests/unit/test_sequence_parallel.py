"""Ulysses + ring attention parity tests
(reference tests/unit/sequence_parallelism/test_ulysses.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # multi-minute integration tier

import deepspeed_tpu
from deepspeed_tpu.models.transformer import xla_attention
from deepspeed_tpu.parallel.mesh import MeshTopology, initialize_topology
from deepspeed_tpu.runtime.config import MeshConfig
from deepspeed_tpu.sequence.ring_attention import ring_attention
from deepspeed_tpu.sequence.ulysses import ulysses_attention
from tests.unit.simple_model import random_batch


def _qkv(b=2, s=64, nh=8, d=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (b, s, nh, d)) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_matches_dense(causal, devices8):
    initialize_topology(MeshConfig(data=1, sequence=8), devices8)
    q, k, v = _qkv()
    ref = xla_attention(q, k, v, causal)
    with deepspeed_tpu.get_topology().mesh:
        out = jax.jit(lambda q, k, v: ulysses_attention(q, k, v, causal))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=1e-4)


@pytest.mark.parametrize("nh", [6, 3])
@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_uneven_heads_matches_replicated(causal, nh, devices8):
    """Uneven heads (nh % sp != 0) run the first-class padded head
    scatter, not a replicated fallback: outputs must match the
    replicated/dense path exactly for 6 and 3 heads on an 8-way
    sequence group (pad heads are zeros and independent of real ones)."""
    initialize_topology(MeshConfig(data=1, sequence=8), devices8)
    q, k, v = _qkv(nh=nh)
    ref = xla_attention(q, k, v, causal)  # the old replicated path
    with deepspeed_tpu.get_topology().mesh:
        out = jax.jit(lambda q, k, v: ulysses_attention(q, k, v, causal))(q, k, v)
    assert out.shape == q.shape
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5,
                               rtol=1e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_dense(causal, devices8):
    initialize_topology(MeshConfig(data=1, sequence=8), devices8)
    q, k, v = _qkv()
    ref = xla_attention(q, k, v, causal)
    out = jax.jit(lambda q, k, v: ring_attention(q, k, v, causal))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=1e-4)


def test_ring_gradients_match(devices8):
    initialize_topology(MeshConfig(data=1, sequence=8), devices8)
    q, k, v = _qkv(b=1, s=32, nh=4, d=8)

    g_ref = jax.grad(lambda q: jnp.sum(xla_attention(q, k, v, True) ** 2))(q)
    g_ring = jax.jit(jax.grad(
        lambda q: jnp.sum(ring_attention(q, k, v, True) ** 2)))(q)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_ref),
                               atol=5e-4, rtol=1e-3)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_kv_subchunking_matches(causal, devices8, monkeypatch):
    """The memory-bounding k sub-chunk scan (nc > 1 per ring step) must be
    numerically identical to the whole-block path — fwd and grads."""
    monkeypatch.setenv("DSTPU_RING_CHUNK", "4")  # S_local 8 -> 2 sub-chunks
    initialize_topology(MeshConfig(data=1, sequence=8), devices8)
    q, k, v = _qkv(b=1, s=64, nh=2, d=8)
    ref = xla_attention(q, k, v, causal)
    out = jax.jit(lambda q, k, v: ring_attention(q, k, v, causal))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5,
                               rtol=1e-4)
    g_ref = jax.grad(lambda q: jnp.sum(xla_attention(q, k, v, causal) ** 2))(q)
    g_ring = jax.jit(jax.grad(
        lambda q: jnp.sum(ring_attention(q, k, v, causal) ** 2)))(q)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_ref),
                               atol=5e-4, rtol=1e-3)


def test_llama_trains_with_ulysses(devices8):
    from deepspeed_tpu.models import llama_model

    model = llama_model("tiny", max_seq_len=32, attn_impl="ulysses")
    engine, *_ = deepspeed_tpu.initialize(
        model=model,
        config={"train_micro_batch_size_per_gpu": 4,
                "optimizer": {"type": "Adam", "params": {"lr": 5e-3}},
                "mesh": {"sequence": 4, "data": -1}})
    ids = np.random.RandomState(0).randint(0, 256, (1, 8, 32)).astype(np.int32)
    losses = [float(engine.train_batch({"input_ids": jnp.asarray(ids)}))
              for _ in range(5)]
    assert losses[-1] < losses[0]


def test_llama_trains_with_ring(devices8):
    from deepspeed_tpu.models import llama_model

    model = llama_model("tiny", max_seq_len=32, attn_impl="ring")
    engine, *_ = deepspeed_tpu.initialize(
        model=model,
        config={"train_micro_batch_size_per_gpu": 4,
                "optimizer": {"type": "Adam", "params": {"lr": 5e-3}},
                "mesh": {"sequence": 4, "data": -1}})
    ids = np.random.RandomState(0).randint(0, 256, (1, 8, 32)).astype(np.int32)
    losses = [float(engine.train_batch({"input_ids": jnp.asarray(ids)}))
              for _ in range(5)]
    assert losses[-1] < losses[0]


# ---------------------------------------------------------------------------
# ALST adapter for EXTERNAL models (reference runtime/sequence_parallel/
# ulysses_sp.py:49,471,838,960)
# ---------------------------------------------------------------------------
def _external_lm(vocab=64, hid=32, nh=4, seq=32):
    """A user model written WITHOUT deepspeed_tpu.models — plain jnp code
    that adopts the ALST adapters."""
    from deepspeed_tpu.sequence.alst import (sequence_tiled_compute,
                                             tiled_fused_logits_loss,
                                             ulysses_sp_attention)

    d = hid // nh

    def init(rng):
        ks = jax.random.split(rng, 5)
        f = lambda k, *s: jax.random.normal(k, s) * 0.05
        return {"emb": f(ks[0], vocab, hid), "wqkv": f(ks[1], hid, 3 * hid),
                "wo": f(ks[2], hid, hid), "w1": f(ks[3], hid, 4 * hid),
                "w2": f(ks[4], 4 * hid, hid)}

    attn = ulysses_sp_attention(inner=xla_attention)

    def loss_fn(p, ids, tiled=True):
        B, S = ids.shape
        x = p["emb"][ids]
        qkv = (x @ p["wqkv"]).reshape(B, S, 3, nh, d)
        a = attn(qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2], causal=True)
        x = x + a.reshape(B, S, hid) @ p["wo"]

        mlp = lambda h: jax.nn.gelu(h @ p["w1"]) @ p["w2"]
        x = x + (sequence_tiled_compute(mlp, chunk=8)(x) if tiled else mlp(x))

        h, t = x[:, :-1], ids[:, 1:]

        def head_ce(hc, tc):
            logits = hc @ p["emb"].T  # tied head inside the chunk
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
            nll = -jnp.take_along_axis(logp, tc[..., None], -1)[..., 0]
            return jnp.sum(nll), jnp.asarray(nll.size, jnp.float32)

        if tiled:
            return tiled_fused_logits_loss(head_ce, h, t, chunk=31)
        s, w = head_ce(h, t)
        return s / w

    return init, loss_fn


def test_alst_external_model_matches_dense(devices8):
    """Tiled MLP + tiled logits-loss + Ulysses attention on an external
    model == its own dense computation (loss AND grads), under a
    sequence=4 x data=2 mesh."""
    initialize_topology(MeshConfig(data=2, sequence=4), devices8)
    init, loss_fn = _external_lm()
    params = init(jax.random.PRNGKey(0))
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (4, 32)),
                      jnp.int32)
    with deepspeed_tpu.get_topology().mesh:
        lt = jax.jit(lambda p: loss_fn(p, ids, tiled=True))(params)
        ld = jax.jit(lambda p: loss_fn(p, ids, tiled=False))(params)
        np.testing.assert_allclose(float(lt), float(ld), rtol=1e-5)
        gt = jax.jit(jax.grad(lambda p: loss_fn(p, ids, tiled=True)))(params)
        gd = jax.jit(jax.grad(lambda p: loss_fn(p, ids, tiled=False)))(params)
    for k in params:
        np.testing.assert_allclose(np.asarray(gt[k]), np.asarray(gd[k]),
                                   atol=2e-5, rtol=1e-4, err_msg=k)


def test_alst_external_model_trains_with_engine(devices8):
    """The adapted external model trains through deepspeed_tpu.initialize
    with the sequence-sharded dataloader adapter feeding it."""
    from deepspeed_tpu.sequence.alst import UlyssesSPDataLoaderAdapter

    initialize_topology(MeshConfig(data=2, sequence=4), devices8)
    init, loss_fn = _external_lm()
    spec = deepspeed_tpu.ModelSpec(
        init_params=init,
        loss_fn=lambda p, batch, rng: loss_fn(p, batch["input_ids"][0]
                                              if batch["input_ids"].ndim == 3
                                              else batch["input_ids"]))
    engine, *_ = deepspeed_tpu.initialize(
        model=spec,
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 5e-3}},
                "zero_optimization": {"stage": 1},
                "mesh": {"data": 2, "sequence": 4}},
        topology=deepspeed_tpu.get_topology())

    r = np.random.RandomState(1)
    fixed = [{"input_ids": r.randint(0, 64, (4, 32)).astype(np.int32)}
             for _ in range(2)]
    loader = UlyssesSPDataLoaderAdapter(fixed * 8, seq_dim=1)
    batches = list(loader)
    # seq dim really lands on the 'sequence' axis
    assert "sequence" in str(batches[0]["input_ids"].sharding.spec)
    losses = [float(engine.train_batch(
        {"input_ids": b["input_ids"][None]})) for b in batches]
    assert losses[-1] < losses[0], (losses[0], losses[-1])
