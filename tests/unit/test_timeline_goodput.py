"""Measured step-time attribution (`telemetry/timeline.py`) and the
run-level goodput ledger (`telemetry/goodput.py`).

Covers the trace-event categorizer (synthetic fixtures per category;
unknown ops land in `other_compute`, never dropped), the interval-sweep
decomposition (categories sum to wall by construction, overlap
attribution, clock-skew scaling, pipe-bubble carve), goodput bucket
arithmetic on a fake clock (buckets sum to lifetime, restart
attribution through the union run file, overflow-skip steps are
productive), the CPU capture fallback (`measured: false`, honest), and
the flight-dump integration (timeline + goodput records land before the
snapshot; a capture that raises mid-step propagates without leaving a
torn record).
"""

import json
import os

import pytest

from deepspeed_tpu.telemetry.flight import FlightRecorder
from deepspeed_tpu.telemetry.goodput import (BUCKETS, GoodputLedger,
                                             set_goodput_ledger)
from deepspeed_tpu.telemetry.registry import MetricsRegistry
from deepspeed_tpu.telemetry.timeline import (StepTimeline, capture_thunk,
                                              categorize_op,
                                              decompose_events)


# ------------------------------------------------------------ categorizer
@pytest.mark.parametrize("name,cat", [
    ("all-reduce.17", "all_reduce"),
    ("fusion.all_reduce.3", "all_reduce"),
    ("all-gather-start", "all_gather"),
    ("reduce-scatter.2", "reduce_scatter"),
    ("all-to-all.1", "all_to_all"),
    ("collective-permute.9", "collective_permute"),
    ("ppermute", "collective_permute"),
    ("dot_general.5", "gemm"),
    ("fusion.matmul", "gemm"),
    ("custom-call.flash_attention", "attention"),
    ("softmax.12", "attention"),
    ("copy.4", "copy"),
    ("transpose.8", "copy"),
    ("dynamic-update-slice.2", "other_compute"),
    ("some_op_nobody_has_heard_of", "other_compute"),
])
def test_categorize_op(name, cat):
    assert categorize_op(name) == cat


def test_collective_shadows_compute_in_fused_names():
    # a fusion name embedding BOTH signals must categorize as the
    # collective: that is the scarcer (and perf-relevant) signal
    assert categorize_op("fusion.dot.all-reduce.1") == "all_reduce"


# ---------------------------------------------------------- decomposition
def test_decompose_sums_to_wall_and_splits_overlap():
    events = [
        {"name": "dot.1", "ts": 0.0, "dur": 0.4},          # gemm
        {"name": "all-reduce.1", "ts": 0.2, "dur": 0.4},   # 0.2 hidden, 0.2 exposed
        {"name": "copy.1", "ts": 0.7, "dur": 0.1},
    ]
    d = decompose_events(events, wall_s=1.0)
    cats = d["categories"]
    assert abs(sum(cats.values()) - 1.0) < 1e-9
    assert abs(cats["gemm"] - 0.4) < 1e-9
    assert abs(cats["all_reduce"] - 0.2) < 1e-9      # only the exposed part
    assert abs(cats["copy"] - 0.1) < 1e-9
    assert abs(cats["host_gap"] - 0.3) < 1e-9        # 1.0 - 0.7 device busy
    assert abs(d["exposed_collective_seconds"] - 0.2) < 1e-9
    assert abs(d["overlapped_collective_seconds"] - 0.2) < 1e-9


def test_decompose_unknown_ops_never_dropped():
    d = decompose_events([{"name": "mystery", "ts": 0.0, "dur": 0.5}], 1.0)
    assert abs(d["categories"]["other_compute"] - 0.5) < 1e-9
    assert abs(sum(d["categories"].values()) - 1.0) < 1e-9


def test_decompose_scales_on_clock_skew():
    # device busy (2.0s) exceeding the host wall (1.0s) is clock skew:
    # everything scales down so the identity still holds
    d = decompose_events([{"name": "dot", "ts": 0.0, "dur": 2.0}], 1.0)
    assert d["scale"] == pytest.approx(0.5)
    assert d["categories"]["gemm"] == pytest.approx(1.0)
    assert sum(d["categories"].values()) == pytest.approx(1.0)


def test_decompose_pipe_bubble_carved_from_gap():
    d = decompose_events([{"name": "dot", "ts": 0.0, "dur": 0.4}], 1.0,
                         pipe_bubble_fraction=0.25)
    assert d["categories"]["pipe_bubble"] == pytest.approx(0.25)
    assert d["categories"]["host_gap"] == pytest.approx(0.35)
    assert sum(d["categories"].values()) == pytest.approx(1.0)
    # the bubble can never exceed the measured gap, whatever the claim
    d2 = decompose_events([{"name": "dot", "ts": 0.0, "dur": 0.9}], 1.0,
                          pipe_bubble_fraction=0.5)
    assert d2["categories"]["pipe_bubble"] == pytest.approx(0.1)
    assert d2["categories"]["host_gap"] == pytest.approx(0.0)


def test_decompose_empty_trace_is_all_gap():
    d = decompose_events([], 2.0)
    assert d["categories"]["host_gap"] == pytest.approx(2.0)
    assert sum(d["categories"].values()) == pytest.approx(2.0)


# -------------------------------------------------------- goodput ledger
class _Clock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


def test_goodput_buckets_sum_to_lifetime():
    clk = _Clock()
    led = GoodputLedger(registry=MetricsRegistry(), now_fn=clk)
    led.observe_step(2.0, step=1)
    led.observe_phase("checkpoint_save", 0.5)
    led.observe_phase("eval", 0.25)
    clk.t += 10.0
    s = led.summary()
    assert set(s["buckets"]) == set(BUCKETS)
    assert sum(s["buckets"].values()) == pytest.approx(s["lifetime_seconds"])
    assert s["buckets"]["step"] == pytest.approx(2.0)
    assert s["buckets"]["idle"] == pytest.approx(10.0 - 2.75)
    assert s["goodput_fraction"] == pytest.approx(0.2)
    assert s["productive_steps"] == 1


def test_goodput_stall_and_skip_classification():
    led = GoodputLedger(registry=MetricsRegistry(), now_fn=_Clock())
    led.observe_step(1.0, step=1, stalled=True)   # whole step is badput
    led.observe_step(1.0, step=2, skipped=True)   # overflow skip: productive
    s = led.summary()
    assert s["buckets"]["stall"] == pytest.approx(1.0)
    assert s["buckets"]["step"] == pytest.approx(1.0)
    assert s["productive_steps"] == 1


def test_goodput_rejects_step_idle_and_unknown_phases():
    led = GoodputLedger(registry=MetricsRegistry(), now_fn=_Clock())
    for bad in ("step", "idle", "lunch"):
        with pytest.raises(ValueError):
            led.observe_phase(bad, 1.0)


def test_goodput_override_reroutes_phases():
    led = GoodputLedger(registry=MetricsRegistry(), now_fn=_Clock())
    with led.override("restart"):
        led.observe_phase("checkpoint_load", 0.75)
    s = led.summary()
    assert s["buckets"]["restart"] == pytest.approx(0.75)
    assert s["buckets"]["checkpoint_load"] == pytest.approx(0.0)


def test_goodput_union_run_file_restart_attribution(tmp_path):
    run = str(tmp_path / "goodput_run.json")
    # attempt 1: steps 1..3 productive, then dies (no close())
    a1 = GoodputLedger(registry=MetricsRegistry(), run_file=run,
                       now_fn=_Clock())
    for st in (1, 2, 3):
        a1.observe_step(1.0, step=st)
    rec = json.load(open(run))
    assert rec["high_water"] == 3 and rec["productive_steps"] == 3
    assert rec["attempts"] == 1
    # attempt 2: resumes behind the high water — step 3 is recompute
    # (restart badput), steps 4..5 are fresh progress
    a2 = GoodputLedger(registry=MetricsRegistry(), run_file=run,
                       now_fn=_Clock())
    a2.observe_step(1.0, step=3)
    for st in (4, 5):
        a2.observe_step(1.0, step=st)
    rec = json.load(open(run))
    assert rec["attempts"] == 2
    assert rec["high_water"] == 5
    assert rec["recomputed_steps"] == 1
    assert rec["buckets"]["restart"] == pytest.approx(1.0)
    # union productive matches an uninterrupted 5-step run
    assert rec["productive_steps"] == 5
    assert rec["buckets"]["step"] == pytest.approx(5.0)


def test_goodput_publish_folds_into_registry():
    reg = MetricsRegistry()
    clk = _Clock()
    led = GoodputLedger(registry=reg, now_fn=clk)
    led.observe_step(2.0, step=1)
    clk.t += 4.0
    led.close()
    sec = reg.get("deepspeed_tpu_goodput_seconds_total")
    frac = reg.get("deepspeed_tpu_goodput_fraction")
    assert sec is not None and sec.total() == pytest.approx(4.0)
    assert frac is not None and frac.value() == pytest.approx(0.5)


# ------------------------------------------------- capture + flight dump
def test_capture_thunk_cpu_fallback_is_honest(tmp_path):
    import jax.numpy as jnp

    from deepspeed_tpu.telemetry.spans import span

    tl = StepTimeline(every_n_steps=0, artifact_dir=str(tmp_path / "art"),
                      registry=MetricsRegistry())

    def work():
        with span("timeline_test_work"):
            return float(jnp.asarray([1.0, 2.0]).sum())

    out, rec = capture_thunk(work, step=5, timeline=tl)
    assert out == 3.0
    assert rec is not None and rec["step"] == 5
    import jax

    if jax.default_backend() == "cpu":
        # no device timeline on CPU: the record must say so, not guess
        assert rec["measured"] is False
    cats = rec["categories"]
    assert sum(cats.values()) == pytest.approx(rec["wall_seconds"], abs=1e-6)
    # the merged Chrome-trace artifact parses and carries events
    arts = os.listdir(str(tmp_path / "art"))
    assert arts
    trace = json.load(open(str(tmp_path / "art" / arts[0])))
    assert trace.get("traceEvents")


def test_capture_exception_propagates_without_torn_record():
    tl = StepTimeline(every_n_steps=0, registry=MetricsRegistry())
    before = tl.last_record()

    class Boom(RuntimeError):
        pass

    tl.force_next()
    with pytest.raises(Boom):
        with tl.capture(step=1):
            raise Boom("step died mid-capture")
    # the failed capture never publishes a half-built record
    assert tl.last_record() == before
    # and the timeline is reusable afterwards (not wedged "active")
    assert tl.should_capture(0) is False
    tl.force_next()
    assert tl.should_capture(0) is True


def test_flight_dump_carries_timeline_and_goodput(tmp_path):
    from deepspeed_tpu.telemetry import timeline as tl_mod

    tl_mod._set_last_record({"step": 7, "measured": False,
                             "categories": {"host_gap": 1.0},
                             "wall_seconds": 1.0})
    clk = _Clock()
    led = GoodputLedger(registry=MetricsRegistry(), now_fn=clk)
    led.observe_step(1.0, step=1)
    clk.t += 2.0
    set_goodput_ledger(led)
    try:
        fr = FlightRecorder(path=str(tmp_path), registry=MetricsRegistry())
        path = fr.dump(reason="manual:test")
        kinds = [json.loads(line)["kind"] for line in open(path)]
        assert "timeline" in kinds and "goodput" in kinds
        # both land BEFORE the final snapshot, like the memory section
        assert kinds.index("timeline") < kinds.index("snapshot")
        assert kinds.index("goodput") < kinds.index("snapshot")
        recs = [json.loads(line) for line in open(path)]
        tl_rec = next(r for r in recs if r["kind"] == "timeline")
        assert tl_rec["step"] == 7 and tl_rec["measured"] is False
        gp_rec = next(r for r in recs if r["kind"] == "goodput")
        assert gp_rec["buckets"]["step"] == pytest.approx(1.0)
    finally:
        set_goodput_ledger(None)
