"""Speculative decoding tests.

Fast tier: n-gram proposer semantics, the greedy accept rule, the
``speculative`` config block, and the BlockAllocator leak/invariant
audit — pure host logic, no model.  Slow tier: engine-level oracles —
greedy speculative generations must be BIT-IDENTICAL to the
non-speculative baseline (cache off/on, decode-entry CoW, chunked
prefill, pool pressure), the sampling guard must keep non-greedy
streams untouched, rollback must survive preemption and KV migration
without leaking pages, and a speculative decode pool must stay
token-identical to a single-engine control.
"""

import jax
import numpy as np
import pytest

from deepspeed_tpu.inference.v2 import (BlockAllocator, InferenceEngineV2,
                                        PrefixCache, RaggedInferenceConfig,
                                        RaggedRequest, SpeculativeConfig)
from deepspeed_tpu.inference.v2.speculative import (NgramProposer,
                                                    longest_accepted)


# ----------------------------- fast: proposer -------------------------------
def test_ngram_proposes_cycle_continuation():
    p = NgramProposer(ngram_min=1, ngram_max=3)
    # history ends in the same trigram it contains earlier; the
    # continuation of the earlier occurrence is the proposal
    tokens = [1, 2, 3, 9, 8, 7, 1, 2, 3]
    assert p.propose(tokens, 3) == [9, 8, 7]
    assert p.propose(tokens, 2) == [9, 8]  # k-cap

def test_ngram_miss_and_empty_history():
    p = NgramProposer()
    assert p.propose([1, 2, 3, 4, 5], 4) == []  # no repeated n-gram
    assert p.propose([], 4) == []
    assert p.propose([7], 4) == []  # too short for any (tail, match) pair
    assert p.propose([1, 2, 3, 1], 0) == []  # k=0: nothing to propose


def test_ngram_longest_ngram_wins():
    p = NgramProposer(ngram_min=1, ngram_max=2)
    # tail bigram (2, 3) matches at index 1 -> continuation [5];
    # a 1-gram match of (3,) at index 4 would propose [6]
    tokens = [1, 2, 3, 5, 3, 6, 2, 3]
    assert p.propose(tokens, 1) == [5]


def test_ngram_prefers_continuation_that_fills_k():
    p = NgramProposer(ngram_min=1, ngram_max=2)
    # the MOST RECENT (4,) match is right before the tail — continuation
    # clipped to [5]; one period earlier the same 1-gram supplies k=3
    tokens = [4, 5, 6, 7, 4, 5, 4]
    assert p.propose(tokens, 3) == [5, 6, 7]
    # when no occurrence can fill k, the longest clipped one wins
    assert p.propose([4, 5, 4], 3) == [5, 4]


def test_longest_accepted_rule():
    # verified[w] = model argmax after consuming draft[:w]
    assert longest_accepted([5, 6, 7], [5, 6, 7, 8]) == ([5, 6, 7], 8)
    assert longest_accepted([5, 9, 7], [5, 6, 7, 8]) == ([5], 6)
    assert longest_accepted([9], [5, 6]) == ([], 5)
    assert longest_accepted([], [5]) == ([], 5)  # empty draft: plain decode


# ----------------------------- fast: config ---------------------------------
def test_speculative_config_validation():
    SpeculativeConfig(mode="ngram", k=4).validate()
    with pytest.raises(ValueError):
        SpeculativeConfig(mode="bogus").validate()
    with pytest.raises(ValueError):
        SpeculativeConfig(mode="ngram", k=0).validate()
    with pytest.raises(ValueError):
        SpeculativeConfig(mode="ngram", ngram_min=3, ngram_max=2).validate()
    with pytest.raises(ValueError):
        SpeculativeConfig(mode="draft").validate()  # needs draft_model


def test_speculative_config_parses_through_ds_config():
    from deepspeed_tpu.runtime.config import DeepSpeedConfig

    cfg = DeepSpeedConfig({"serving": {
        "enabled": True,
        "speculative": {"mode": "ngram", "k": 6, "ngram_max": 4}}})
    assert cfg.serving.speculative.k == 6
    assert cfg.serving.speculative.ngram_max == 4
    assert DeepSpeedConfig({}).serving.speculative is None
    with pytest.raises(ValueError):
        DeepSpeedConfig({"serving": {"speculative": {"mode": "bogus"}}})
    # engine-level block coerces the same way
    r = RaggedInferenceConfig.from_dict({"speculative": {"mode": "ngram",
                                                         "k": 2}})
    assert r.speculative.k == 2 and r.speculative.enabled


# ----------------------------- fast: allocator audit ------------------------
def test_allocator_audit_clean_and_live_refcounts():
    a = BlockAllocator(8)
    a.check_invariants()
    seq_a, seq_b = a.alloc(2), a.alloc(1)
    a.share(seq_a[0])  # seq_b also maps seq_a's first page
    a.assert_no_leaks([seq_a, seq_b + [seq_a[0]]])
    a.free(seq_b + [seq_a[0]])
    a.free(seq_a)
    a.assert_no_leaks()  # nothing live: every page free or parked


def test_allocator_audit_detects_leak_and_use_after_free():
    a = BlockAllocator(4)
    pages = a.alloc(2)
    with pytest.raises(AssertionError, match="leak"):
        a.assert_no_leaks([])  # refcounts held with no live owner
    with pytest.raises(AssertionError, match="use-after-free"):
        a.assert_no_leaks([pages, pages])  # more owners than refs
    a.free(pages)


def test_allocator_audit_detects_structural_corruption():
    a = BlockAllocator(4)
    (p,) = a.alloc(1)
    a._ref[p] = 0  # simulate a lost refcount: page now in no partition
    with pytest.raises(AssertionError, match="partition"):
        a.check_invariants()
    a._ref[p] = 1
    a.free([p])
    a._free.append(a._free[-1])  # duplicate free-list entry
    with pytest.raises(AssertionError, match="duplicates"):
        a.check_invariants()


def test_allocator_audit_lru_pages_registered():
    a = BlockAllocator(4)
    pc = PrefixCache(2, a)
    (p,) = a.alloc(1)
    a.register(p, pc.chain_key(None, [1, 1]))
    a.free([p])  # parks in LRU
    a.check_invariants()
    del a._key_of[p]  # registry torn: LRU page no longer registered
    with pytest.raises(AssertionError):
        a.check_invariants()


# ----------------------------- slow: engine oracles -------------------------
@pytest.fixture(scope="module")
def tiny_model():
    from deepspeed_tpu.models.llama import llama_model

    model = llama_model("tiny", max_seq_len=256)
    return model, model.init_params(jax.random.PRNGKey(0))


def _engine(model, params, spec=False, k=4, **kw):
    cfg = dict(dtype="fp32", page_size=8, num_pages=64, max_seqs=2,
               max_pages_per_seq=16)
    cfg.update(kw)
    return InferenceEngineV2(model, RaggedInferenceConfig(
        speculative=SpeculativeConfig(mode="ngram" if spec else "off", k=k),
        **cfg), params=params)


def _reqs(prompts, n=24, temperature=0.0):
    return [RaggedRequest(prompt_ids=list(p), max_new_tokens=n,
                          temperature=temperature) for p in prompts]


@pytest.mark.slow
@pytest.mark.parametrize("extra", [{}, {"enable_prefix_cache": True},
                                   {"prefill_chunk": 16}])
def test_spec_greedy_bit_exact(tiny_model, extra):
    """Greedy speculative generations equal the non-speculative baseline
    token-for-token — cache off, cache on, and chunked prefill — while
    using fewer model invocations, and leak no pages."""
    model, params = tiny_model
    rng = np.random.RandomState(2)
    shared = list(rng.randint(0, model.config.vocab_size, 16))
    prompts = [shared + list(rng.randint(0, model.config.vocab_size, m))
               for m in (5, 11)]

    base = _engine(model, params, **extra)
    want = base.generate_all(_reqs(prompts))
    eng = _engine(model, params, spec=True, **extra)
    got = eng.generate_all(_reqs(prompts))
    assert got == want, (got, want)
    st, st0 = eng.decode_stats(), base.decode_stats()
    assert st["spec_verify_calls"] > 0
    assert st["decode_model_invocations"] <= st0["decode_model_invocations"]
    assert st["decode_tokens"] == st0["decode_tokens"]
    eng.assert_no_leaks()
    base.assert_no_leaks()


@pytest.mark.slow
def test_spec_empty_drafts_use_plain_decode(tiny_model):
    """Rounds where the proposer draws blanks everywhere run the 1-wide
    decode program, not the k+1-wide verify — low-acceptance traffic
    costs exactly what speculation-off costs."""
    model, params = tiny_model
    rng = np.random.RandomState(3)
    prompts = [list(rng.randint(0, model.config.vocab_size, m))
               for m in (7, 12)]

    class Blank:
        def propose(self, tokens, k):
            return []

    base = _engine(model, params)
    want = base.generate_all(_reqs(prompts, n=10))
    eng = InferenceEngineV2(
        model, RaggedInferenceConfig(
            dtype="fp32", page_size=8, num_pages=64, max_seqs=2,
            max_pages_per_seq=16,
            speculative=SpeculativeConfig(mode="ngram")),
        params=params, proposer=Blank())
    got = eng.generate_all(_reqs(prompts, n=10))
    assert got == want
    st = eng.decode_stats()
    assert st["spec_verify_calls"] == 0
    assert (st["decode_model_invocations"]
            == base.decode_stats()["decode_model_invocations"])
    eng.assert_no_leaks()


@pytest.mark.slow
def test_spec_decode_entry_cow_bit_exact(tiny_model):
    """A fully-cached page-aligned prompt enters through the verify
    program (decode_entry): its first window recomputes the final
    prompt token's KV into the private CoW page — the cached page is
    never touched and the stream equals the baseline."""
    model, params = tiny_model
    rng = np.random.RandomState(7)
    prompt = list(rng.randint(0, model.config.vocab_size, 16))  # 2 pages

    want = _engine(model, params).generate_all(_reqs([prompt], n=8))
    eng = _engine(model, params, spec=True, enable_prefix_cache=True)
    first = eng.generate_all(_reqs([prompt], n=8))
    assert list(first.values())[0] == list(want.values())[0]
    # cached page content must survive the second, fully-cached run
    keys = eng.prefix_cache.page_keys(prompt, 2)
    src = eng.allocator.lookup(keys[1])
    assert src is not None
    again = eng.generate_all(_reqs([prompt], n=8))
    assert list(again.values())[0] == list(want.values())[0]
    eng.assert_no_leaks()


@pytest.mark.slow
def test_spec_under_pool_pressure_and_preemption(tiny_model):
    """Tight pool: draft reservation must never starve admission (it
    spends only truly-free pages), preemption mid-speculation must roll
    back cleanly, and generations stay exact."""
    model, params = tiny_model
    rng = np.random.RandomState(4)
    prompts = [list(rng.randint(0, model.config.vocab_size, 28))
               for _ in range(2)]

    want = _engine(model, params, num_pages=8, max_pages_per_seq=8
                   ).generate_all(_reqs(prompts, n=10))
    eng = _engine(model, params, spec=True, num_pages=8, max_pages_per_seq=8)
    got = eng.generate_all(_reqs(prompts, n=10))
    assert got == want, (got, want)
    assert eng.allocator.free_pages == 8
    eng.assert_no_leaks()


@pytest.mark.slow
def test_spec_preempt_midstream_recovers_exact(tiny_model):
    """Forced preemption right after a speculative round: the evicted
    sequence re-prefills its (speculatively grown) prefix and the final
    stream still equals the baseline."""
    model, params = tiny_model
    rng = np.random.RandomState(5)
    prompt = list(rng.randint(0, model.config.vocab_size, 12))

    want = _engine(model, params).generate_all(_reqs([prompt], n=16))
    eng = _engine(model, params, spec=True)
    uid = eng.put(_reqs([prompt], n=16)[0])
    got = []
    for _ in range(3):  # a few speculative rounds
        for u, rec in eng.step().items():
            if u == uid:
                got.extend(rec["tokens"])
    seq = next(s for s in eng._slots if s is not None)
    eng._preempt(seq)
    eng.assert_no_leaks()  # rollback + preemption left exact refcounts
    while eng.has_work():
        for u, rec in eng.step().items():
            if u == uid:
                got.extend(rec["tokens"])
    assert got == list(want.values())[0]
    eng.assert_no_leaks()


@pytest.mark.slow
def test_spec_sampling_guard_falls_back(tiny_model):
    """Non-greedy requests on a speculative engine route through the
    plain decode program: streams are identical to a non-speculative
    engine with the same seed (distribution untouched), the fallback is
    counted, and no verify call runs."""
    model, params = tiny_model
    rng = np.random.RandomState(6)
    prompts = [list(rng.randint(0, model.config.vocab_size, 9))
               for _ in range(2)]

    want = _engine(model, params).generate_all(
        _reqs(prompts, n=8, temperature=0.7))
    eng = _engine(model, params, spec=True)
    got = eng.generate_all(_reqs(prompts, n=8, temperature=0.7))
    assert got == want, (got, want)
    st = eng.decode_stats()
    assert st["spec_fallback_requests"] == 2
    assert st["spec_verify_calls"] == 0 and st["spec_proposed_tokens"] == 0
    assert eng._spec_fallback_warned  # the guard warned, loudly, once


@pytest.mark.slow
def test_spec_export_import_midstream_bit_exact(tiny_model):
    """KV migration out of a speculative engine mid-stream: the bundle
    reflects the post-rollback state exactly, the importing (also
    speculative) engine finishes the stream bit-identically, and
    neither side leaks pages."""
    model, params = tiny_model
    rng = np.random.RandomState(8)
    prompt = list(rng.randint(0, model.config.vocab_size, 12))

    want = _engine(model, params).generate_all(_reqs([prompt], n=16))
    src = _engine(model, params, spec=True)
    dst = _engine(model, params, spec=True)
    uid = src.put(_reqs([prompt], n=16)[0])
    got = []
    for _ in range(2):  # speculative rounds before the handoff
        for u, rec in src.step().items():
            got.extend(rec["tokens"])
    bundle = src.export_sequence(uid)
    assert dst.import_sequence(bundle)
    src.release_sequence(uid)
    src.assert_no_leaks()
    while dst.has_work():
        for u, rec in dst.step().items():
            got.extend(rec["tokens"])
    assert got == list(want.values())[0]
    dst.assert_no_leaks()


@pytest.mark.slow
def test_fleet_decode_pool_with_speculation_token_identical(tiny_model):
    """A disaggregated fleet whose replicas speculate (fleet-wide
    ``serving.speculative`` block) stays token-identical to a single
    NON-speculative engine control, with the verify program carrying
    the decode pool's load."""
    from deepspeed_tpu.serving import ServingConfig, build_fleet

    model, params = tiny_model
    base = RaggedInferenceConfig(dtype="fp32", page_size=8, num_pages=64,
                                 max_seqs=4, max_pages_per_seq=12,
                                 enable_prefix_cache=True)
    rng = np.random.RandomState(9)
    shared = list(rng.randint(0, model.config.vocab_size, 16))
    reqs = [RaggedRequest(
        prompt_ids=shared + list(rng.randint(0, model.config.vocab_size,
                                             3 + i)),
        max_new_tokens=12) for i in range(3)]

    control = InferenceEngineV2(model, base, params=params)
    want = control.generate_all([RaggedRequest(prompt_ids=list(r.prompt_ids),
                                               max_new_tokens=r.max_new_tokens)
                                 for r in reqs])
    fleet = build_fleet(
        model, ServingConfig(enabled=True, prefill_replicas=1,
                             decode_replicas=1, prefill_chunk=8,
                             speculative=SpeculativeConfig(mode="ngram",
                                                           k=4)),
        engine_config=base, params=params)

    class Echo:  # always-drafting proposer: lossless for ANY drafts,
        def propose(self, tokens, k):  # so verify provably carries the
            return [int(tokens[-1])] * k  # decode load deterministically
                                          # (n-gram hits depend on the
                                          # tiny model's output repeating)
    decode_eng = fleet.replicas["decode0"].engine
    decode_eng._proposer = Echo()
    got = fleet.run_all(reqs)
    assert [got[i] for i in range(3)] == [want[i] for i in range(3)]
    assert decode_eng.decode_stats()["spec_verify_calls"] > 0
    for rep in fleet.replicas.values():
        rep.engine.assert_no_leaks()
