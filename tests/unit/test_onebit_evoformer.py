"""1-bit optimizers + evoformer attention + checkpoint engine flavors
(reference: tests/onebit/, tests/unit/ops/deepspeed4science/)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.ops.evoformer_attn import DS4Sci_EvoformerAttention
from deepspeed_tpu.runtime.fp16.onebit import (one_bit_adam, one_bit_lamb,
                                               zero_one_adam)
from tests.unit.simple_model import random_batch, simple_mlp_spec


# ---------------------------------------------------------------- 1-bit
def test_onebit_adam_warmup_matches_adamw():
    """During warmup (count <= freeze_step) OneBitAdam is exact AdamW."""
    import optax

    params = {"w": jnp.asarray(np.random.RandomState(0).randn(8, 8), jnp.float32)}
    g = {"w": jnp.asarray(np.random.RandomState(1).randn(8, 8), jnp.float32)}
    ob = one_bit_adam(1e-2, freeze_step=10)
    ref = optax.adam(1e-2)
    s1, s2 = ob.init(params), ref.init(params)
    p1, p2 = params, params
    for _ in range(3):
        u1, s1 = ob.update(g, s1, p1)
        u2, s2 = ref.update(g, s2, p2)
        p1 = optax.apply_updates(p1, u1)
        p2 = optax.apply_updates(p2, u2)
    np.testing.assert_allclose(np.asarray(p1["w"]), np.asarray(p2["w"]),
                               rtol=1e-5, atol=1e-6)


def test_onebit_adam_freezes_variance():
    params = {"w": jnp.ones((4, 4), jnp.float32)}
    ob = one_bit_adam(1e-2, freeze_step=2)
    s = ob.init(params)
    rng = np.random.RandomState(2)
    for i in range(5):
        g = {"w": jnp.asarray(rng.randn(4, 4), jnp.float32)}
        _, s_next = ob.update(g, s, params)
        if i >= 2:  # past freeze: variance must not change
            np.testing.assert_array_equal(np.asarray(s.v["w"]),
                                          np.asarray(s_next.v["w"]))
        s = s_next


def test_zero_one_adam_refreshes_variance_on_interval():
    params = {"w": jnp.ones((4,), jnp.float32)}
    zo = zero_one_adam(1e-2, var_freeze_step=1, var_update_interval=3)
    s = zo.init(params)
    changed = []
    rng = np.random.RandomState(3)
    for i in range(7):
        g = {"w": jnp.asarray(rng.randn(4), jnp.float32)}
        _, s_next = zo.update(g, s, params)
        changed.append(not np.array_equal(np.asarray(s.v["w"]),
                                          np.asarray(s_next.v["w"])))
        s = s_next
    # step counts 1..7: warm at 1; refresh at 3 and 6
    assert changed == [True, False, True, False, False, True, False]


@pytest.mark.parametrize("opt_name,lr", [("OneBitAdam", 1e-2),
                                         ("ZeroOneAdam", 1e-2),
                                         ("OneBitLamb", 2e-3)])
def test_onebit_engine_trains(opt_name, lr):
    engine, *_ = deepspeed_tpu.initialize(
        model=simple_mlp_spec(),
        config={"train_micro_batch_size_per_gpu": 4,
                "optimizer": {"type": opt_name,
                              "params": {"lr": lr, "freeze_step": 3}},
                "gradient_clipping": 1.0})
    losses = [float(engine.train_batch(random_batch(batch_size=16, seed=i % 4, gas=1)))
              for i in range(16)]  # crosses the freeze boundary
    # batches cycle over 4 seeds: compare losses on the same batch
    assert losses[12] < losses[0]
    assert np.isfinite(losses).all()


def test_onebit_error_feedback_accumulates():
    params = {"w": jnp.zeros((256,), jnp.float32)}
    ob = one_bit_adam(1e-2, freeze_step=1)
    s = ob.init(params)
    g = {"w": jnp.asarray(np.random.RandomState(4).randn(256) * 1e-3,
                          jnp.float32)}
    _, s = ob.update(g, s, params)  # warmup step: no error
    assert float(jnp.abs(s.error["w"]).max()) == 0.0
    _, s = ob.update(g, s, params)  # compressed step: residual retained
    assert float(jnp.abs(s.error["w"]).max()) > 0.0


# ------------------------------------------------------------ evoformer
def test_evoformer_matches_naive():
    rng = np.random.RandomState(0)
    B, S, N, H, D = 2, 3, 8, 2, 4
    q = jnp.asarray(rng.randn(B, S, N, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, S, N, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, S, N, H, D), jnp.float32)
    bias1 = jnp.asarray(rng.randn(B, S, 1, 1, N), jnp.float32)  # mask bias
    bias2 = jnp.asarray(rng.randn(B, 1, H, N, N), jnp.float32)  # pair bias

    out = DS4Sci_EvoformerAttention(q, k, v, [bias1, bias2])
    # naive per-element
    s = np.einsum("bsqhd,bskhd->bshqk", q, k) / np.sqrt(D)
    s = s + np.asarray(bias1) + np.asarray(bias2)
    p = jax.nn.softmax(jnp.asarray(s), axis=-1)
    want = np.einsum("bshqk,bskhd->bsqhd", np.asarray(p), v)
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-5, atol=1e-5)
    assert out.shape == (B, S, N, H, D)


def test_evoformer_pallas_matches_xla():
    """Fused Pallas kernels (interpret mode on CPU) vs the unfused XLA
    path: values AND all five gradients, incl. both bias grads — the part
    the reference hand-writes in kernel_backward.h."""
    from deepspeed_tpu.ops.evoformer_attn import evoformer_attention_xla
    from deepspeed_tpu.ops.pallas.evoformer_attn import (
        evoformer_attention_pallas)

    rng = np.random.RandomState(1)
    B, S, N, H, D = 2, 3, 20, 2, 16  # N=20 vs block 8 -> padded tail blocks
    q = jnp.asarray(rng.randn(B, S, N, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, S, N, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, S, N, H, D), jnp.float32)
    b1 = jnp.asarray(rng.randn(B, S, 1, 1, N), jnp.float32)
    b2 = jnp.asarray(rng.randn(B, 1, H, N, N), jnp.float32)

    for biases in ([], [b1], [b1, b2], [None, b2]):
        out_p = evoformer_attention_pallas(q, k, v, biases, block_q=8, block_k=8)
        out_x = evoformer_attention_xla(q, k, v, biases)
        np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_x),
                                   rtol=2e-4, atol=2e-4)

    # gradient parity WITHOUT biases (the default autodiff path must not
    # assume the bias-grad outputs exist)
    g_nb_p = jax.grad(lambda q: jnp.sum(jnp.square(
        evoformer_attention_pallas(q, k, v, [], block_q=8, block_k=8))))(q)
    g_nb_x = jax.grad(lambda q: jnp.sum(jnp.square(
        evoformer_attention_xla(q, k, v, []))))(q)
    np.testing.assert_allclose(np.asarray(g_nb_p), np.asarray(g_nb_x),
                               rtol=2e-3, atol=2e-3, err_msg="no-bias dq")
    # and with only the pair bias in slot 1
    g_b2_p = jax.grad(lambda b2: jnp.sum(jnp.square(
        evoformer_attention_pallas(q, k, v, [None, b2], block_q=8, block_k=8))))(b2)
    g_b2_x = jax.grad(lambda b2: jnp.sum(jnp.square(
        evoformer_attention_xla(q, k, v, [None, b2]))))(b2)
    np.testing.assert_allclose(np.asarray(g_b2_p), np.asarray(g_b2_x),
                               rtol=2e-3, atol=2e-3, err_msg="lone dbias2")

    def loss_p(q, k, v, b1, b2):
        return jnp.sum(jnp.square(evoformer_attention_pallas(
            q, k, v, [b1, b2], block_q=8, block_k=8)))

    def loss_x(q, k, v, b1, b2):
        return jnp.sum(jnp.square(evoformer_attention_xla(q, k, v, [b1, b2])))

    gp = jax.grad(loss_p, argnums=(0, 1, 2, 3, 4))(q, k, v, b1, b2)
    gx = jax.grad(loss_x, argnums=(0, 1, 2, 3, 4))(q, k, v, b1, b2)
    for name, a, b in zip("q k v bias1 bias2".split(), gp, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-3,
                                   err_msg=f"grad mismatch: {name}")


def test_evoformer_lone_pair_bias_broadcasts():
    """A pair-shaped bias in slot 0 must take the broadcasting XLA path
    under impl='auto' (the kernel's positional bias1 would reject it)."""
    from deepspeed_tpu.ops.evoformer_attn import (evoformer_attention,
                                                  evoformer_attention_xla)

    rng = np.random.RandomState(4)
    B, S, N, H, D = 1, 2, 8, 2, 16  # D=16 would qualify for pallas
    q = jnp.asarray(rng.randn(B, S, N, H, D), jnp.float32)
    pair = jnp.asarray(rng.randn(B, 1, H, N, N), jnp.float32)
    out = evoformer_attention(q, q, q, [pair])  # must not raise
    want = evoformer_attention_xla(q, q, q, [pair])
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_evoformer_grad_and_bias_validation():
    q = jnp.ones((1, 2, 4, 1, 4))
    loss = lambda q: DS4Sci_EvoformerAttention(q, q, q).sum()  # noqa: E731
    g = jax.grad(loss)(q)
    assert np.isfinite(np.asarray(g)).all()
    with pytest.raises(ValueError):
        DS4Sci_EvoformerAttention(q, q, q, [None, None, None])


# ------------------------------------------------- checkpoint engine flavors
def test_nebula_datastates_engines(tmp_path):
    from deepspeed_tpu.runtime.checkpoint_engine.engines import (
        DataStatesCheckpointEngine, NebulaCheckpointEngine,
        make_checkpoint_engine)
    from deepspeed_tpu.runtime.config import DeepSpeedConfig

    for writer, cls in [("nebula", NebulaCheckpointEngine),
                        ("datastates", DataStatesCheckpointEngine)]:
        cfg = DeepSpeedConfig({"checkpoint": {"writer": writer}})
        eng = make_checkpoint_engine(cfg)
        assert isinstance(eng, cls)
        arrays = {"a": np.arange(8, dtype=np.float32)}
        path = str(tmp_path / f"{writer}.ckpt")
        eng.save(arrays, path)
        assert eng.commit("tag")
        got = eng.load(path)
        np.testing.assert_array_equal(got["a"], arrays["a"])


def test_onebit_weight_decay_requires_params():
    """params=None with weight_decay/LAMB must raise, not silently use grads
    as params (ADVICE r1 onebit.py:141)."""
    from deepspeed_tpu.runtime.fp16.onebit import one_bit_adam, one_bit_lamb

    g = {"w": jnp.ones((4,))}
    for opt in (one_bit_adam(1e-3, weight_decay=0.1), one_bit_lamb(1e-3)):
        state = opt.init(g)
        with pytest.raises(ValueError, match="needs params"):
            opt.update(g, state, None)
    # without decay/lamb, params=None stays fine
    opt = one_bit_adam(1e-3)
    state = opt.init(g)
    upd, _ = opt.update(g, state, None)
    assert jnp.all(jnp.isfinite(upd["w"]))
