"""Elastic training tests (reference tests/unit/elasticity/ +
DSElasticAgent, elasticity/elastic_agent.py:32)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # multi-minute integration tier

import deepspeed_tpu
from deepspeed_tpu.elasticity.elastic_agent import ElasticAgent
from deepspeed_tpu.parallel.mesh import MeshConfig, initialize_topology
from tests.unit.simple_model import random_batch, simple_mlp_spec

EL = {"enabled": True, "max_train_batch_size": 32,
      "micro_batch_sizes": [2, 4], "min_gpus": 1, "max_gpus": 64}


def _cfg(**extra):
    cfg = {"optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
           "zero_optimization": {"stage": 1},
           "elasticity": dict(EL)}
    cfg.update(extra)
    return cfg


def test_initialize_derives_batch_from_world(devices8):
    """With elasticity on, micro/gas come from the world size and the
    GLOBAL batch is world-size independent."""
    initialize_topology(MeshConfig(data=4), jax.devices()[:4])
    e4, *_ = deepspeed_tpu.initialize(
        model=simple_mlp_spec(), config=_cfg(mesh={"data": 4}),
        topology=deepspeed_tpu.get_topology())
    initialize_topology(MeshConfig(data=8), jax.devices()[:8])
    e8, *_ = deepspeed_tpu.initialize(
        model=simple_mlp_spec(), config=_cfg(mesh={"data": 8}),
        topology=deepspeed_tpu.get_topology())
    assert e4.train_batch_size() == e8.train_batch_size()
    assert e4.train_micro_batch_size_per_gpu() * 4 * \
        e4.gradient_accumulation_steps() == e4.train_batch_size()
    assert e8.train_micro_batch_size_per_gpu() * 8 * \
        e8.gradient_accumulation_steps() == e8.train_batch_size()


def test_initialize_rejects_explicit_batch_with_elasticity(devices8):
    initialize_topology(MeshConfig(data=8), jax.devices()[:8])
    with pytest.raises(ValueError, match="elasticity"):
        deepspeed_tpu.initialize(
            model=simple_mlp_spec(),
            config=_cfg(train_micro_batch_size_per_gpu=4, mesh={"data": 8}),
            topology=deepspeed_tpu.get_topology())


def test_elastic_resume_4_to_8_devices(devices8, tmp_path):
    """The VERDICT done-criterion: train on 4 devices, save, resume on 8 —
    the loss continuation is identical to an uninterrupted 8-device run
    (same global batches, exact fp32 state round-trip, resharded load)."""
    def batch(i, bs):
        return random_batch(batch_size=bs, seed=i % 3, gas=1)

    def make(ndev):
        initialize_topology(MeshConfig(data=ndev), jax.devices()[:ndev])
        e, *_ = deepspeed_tpu.initialize(
            model=simple_mlp_spec(), config=_cfg(mesh={"data": ndev}),
            topology=deepspeed_tpu.get_topology())
        return e

    # uninterrupted control on 8 devices
    ctrl = make(8)
    gb = ctrl.train_batch_size()
    ctrl_losses = [float(ctrl.train_batch(batch(i, gb))) for i in range(6)]

    # elastic run: 3 steps on 4 devices -> save -> resume on 8 -> 3 steps
    e4 = make(4)
    assert e4.train_batch_size() == gb  # same global batch at both scales
    for i in range(3):
        e4.train_batch(batch(i, gb))
    e4.save_checkpoint(str(tmp_path), tag="resize", partitioned=True)

    e8 = make(8)
    e8.load_checkpoint(str(tmp_path))
    assert e8.global_steps == 3
    resumed = [float(e8.train_batch(batch(i, gb))) for i in range(3, 6)]
    np.testing.assert_allclose(resumed, ctrl_losses[3:], rtol=1e-5, atol=1e-6)


def test_elastic_resume_immutability_enforced(devices8, tmp_path):
    """A drifted elastic config across a resize must be rejected
    (reference ensure_immutable_elastic_config, elasticity.py:208)."""
    initialize_topology(MeshConfig(data=4), jax.devices()[:4])
    e4, *_ = deepspeed_tpu.initialize(
        model=simple_mlp_spec(), config=_cfg(mesh={"data": 4}),
        topology=deepspeed_tpu.get_topology())
    e4.train_batch(random_batch(batch_size=e4.train_batch_size(), seed=0, gas=1))
    e4.save_checkpoint(str(tmp_path), tag="t", partitioned=True)

    drifted = dict(EL, max_train_batch_size=16)
    initialize_topology(MeshConfig(data=8), jax.devices()[:8])
    e8, *_ = deepspeed_tpu.initialize(
        model=simple_mlp_spec(), config=_cfg(elasticity=drifted, mesh={"data": 8}),
        topology=deepspeed_tpu.get_topology())
    with pytest.raises(ValueError, match="elastic config changed"):
        e8.load_checkpoint(str(tmp_path))


def test_elastic_agent_restarts_until_success(tmp_path):
    """The watchdog relaunches a failing job; the third attempt succeeds."""
    marker = tmp_path / "attempts"
    script = tmp_path / "job.py"
    script.write_text(
        "import sys, pathlib\n"
        f"p = pathlib.Path({str(marker)!r})\n"
        "n = int(p.read_text()) if p.exists() else 0\n"
        "p.write_text(str(n + 1))\n"
        "sys.exit(0 if n >= 2 else 1)\n")
    agent = ElasticAgent(max_restarts=5, restart_delay_s=0.0)
    rc = agent.run(str(script))
    assert rc == 0
    assert agent.attempts == 3
    assert int(marker.read_text()) == 3


def test_elastic_agent_gives_up_after_max_restarts(tmp_path):
    script = tmp_path / "always_fail.py"
    script.write_text("import sys; sys.exit(7)\n")
    agent = ElasticAgent(max_restarts=2, restart_delay_s=0.0)
    rc = agent.run(str(script))
    assert rc != 0
    assert agent.attempts == 3  # 1 try + 2 restarts


def test_elastic_agent_rediscovers_hosts_each_attempt(tmp_path, monkeypatch):
    """Membership change between attempts: the hostfile is re-read, and the
    relaunch uses the NEW world size (the reference agent's rendezvous
    membership change -> restart at new scale)."""
    hf = tmp_path / "hostfile"
    hf.write_text("localhost slots=1\n")
    agent = ElasticAgent(hostfile=str(hf), max_restarts=2, restart_delay_s=0.0)

    calls = []

    def fake_attempt(cmds):
        calls.append(len(cmds))
        if len(calls) == 1:
            hf.write_text("hostA slots=1\nhostB slots=1\n")  # resize up
            return 1  # first attempt dies
        return 0

    monkeypatch.setattr(agent, "_run_attempt", fake_attempt)
    rc = agent.run("train.py")
    assert rc == 0
    assert agent.world_sizes == [1, 2], agent.world_sizes
    assert calls == [1, 2]


def test_launcher_elastic_flag(tmp_path):
    """--elastic_training routes through the agent end-to-end."""
    from deepspeed_tpu.launcher import runner

    marker = tmp_path / "n"
    script = tmp_path / "job.py"
    script.write_text(
        "import sys, pathlib\n"
        f"p = pathlib.Path({str(marker)!r})\n"
        "n = int(p.read_text()) if p.exists() else 0\n"
        "p.write_text(str(n + 1))\n"
        "sys.exit(0 if n >= 1 else 1)\n")
    rc = runner.main(["--elastic_training", "--max_elastic_restarts", "3",
                      str(script)])
    assert rc == 0
    assert int(marker.read_text()) == 2


def test_elastic_immutability_checked_at_same_scale(devices8, tmp_path):
    """Config drift is rejected even when the mesh did NOT change (the
    most common restart; code-review r3 finding)."""
    initialize_topology(MeshConfig(data=8), jax.devices()[:8])
    e, *_ = deepspeed_tpu.initialize(
        model=simple_mlp_spec(), config=_cfg(mesh={"data": 8}),
        topology=deepspeed_tpu.get_topology())
    e.train_batch(random_batch(batch_size=e.train_batch_size(), seed=0, gas=1))
    e.save_checkpoint(str(tmp_path), tag="t", partitioned=True)

    initialize_topology(MeshConfig(data=8), jax.devices()[:8])
    e2, *_ = deepspeed_tpu.initialize(
        model=simple_mlp_spec(),
        config=_cfg(elasticity=dict(EL, max_train_batch_size=16),
                    mesh={"data": 8}),
        topology=deepspeed_tpu.get_topology())
    with pytest.raises(ValueError, match="elastic config changed"):
        e2.load_checkpoint(str(tmp_path))


def test_elasticity_accepts_auto_batch(devices8):
    """'auto' batch values are unset, not explicit — elasticity must accept
    them (HF integrations pass 'auto')."""
    initialize_topology(MeshConfig(data=8), jax.devices()[:8])
    e, *_ = deepspeed_tpu.initialize(
        model=simple_mlp_spec(),
        config=_cfg(train_batch_size="auto", mesh={"data": 8}),
        topology=deepspeed_tpu.get_topology())
    assert e.train_batch_size() == 16  # the most world-size-compatible batch
