"""Tiny model fixtures (analogue of reference tests/unit/simple_model.py)."""

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.runtime.module import ModelSpec

HIDDEN = 16


def simple_mlp_spec(hidden_dim: int = HIDDEN, nlayers: int = 2) -> ModelSpec:
    """An MLP regression model returning MSE loss — the SimpleModel of the
    reference test suite."""

    def init_params(rng):
        keys = jax.random.split(rng, nlayers)
        params = {}
        for i, k in enumerate(keys):
            params[f"layer_{i}"] = {
                "w": jax.random.normal(k, (hidden_dim, hidden_dim)) * 0.1,
                "b": jnp.zeros((hidden_dim,)),
            }
        return params

    def forward(params, x):
        for i in range(nlayers):
            layer = params[f"layer_{i}"]
            x = x @ layer["w"] + layer["b"]
            if i < nlayers - 1:
                x = jax.nn.relu(x)
        return x

    def loss_fn(params, batch, rng):
        x, y = batch
        out = forward(params, x)
        return jnp.mean((out - y.astype(out.dtype)) ** 2)

    return ModelSpec(init_params, loss_fn, apply_fn=lambda p, b: forward(p, b[0]))


def _true_map(hidden_dim: int) -> np.ndarray:
    """Fixed ground-truth linear map so the regression task is learnable."""
    rng = np.random.RandomState(42)
    return (rng.randn(hidden_dim, hidden_dim) * 0.3).astype(np.float32)


def random_dataset(n_samples: int = 128, hidden_dim: int = HIDDEN, seed: int = 0):
    """List of (x, y) numpy pairs (reference random_dataloader)."""
    rng = np.random.RandomState(seed)
    xs = rng.randn(n_samples, hidden_dim).astype(np.float32)
    ys = xs @ _true_map(hidden_dim)
    return [(xs[i], ys[i]) for i in range(n_samples)]


def random_batch(batch_size: int = 8, hidden_dim: int = HIDDEN, seed: int = 0,
                 gas: int = 0):
    rng = np.random.RandomState(seed)
    shape = (gas, batch_size, hidden_dim) if gas else (batch_size, hidden_dim)
    xs = rng.randn(*shape).astype(np.float32)
    ys = xs @ _true_map(hidden_dim)
    return jnp.asarray(xs), jnp.asarray(ys)
