"""Pipeline engine tests (reference tests/unit/pipe/).

The key correctness property: the pipelined loss/gradients equal the
non-pipelined model's (same params, same data), because the pipeline is
just an execution schedule.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # multi-minute integration tier

import deepspeed_tpu
from deepspeed_tpu.models.llama import llama_config
from deepspeed_tpu.models.transformer import causal_lm_loss
from deepspeed_tpu.parallel.mesh import MeshConfig, initialize_topology
from deepspeed_tpu.runtime.pipe.engine import pipelined_causal_lm

@pytest.fixture(autouse=True, scope="module")
def _fresh_executable_cache():
    """The pipe shard_map programs have twice SIGABRTed XLA's CPU backend
    when first executed after ~100 other tests' accumulated compiled
    programs (never reproducible in isolation or short chains).  Clearing
    the executable caches at this module boundary bounds that state; the
    recompiles cost a few seconds."""
    jax.clear_caches()
    yield


SEQ = 16
VOCAB = 64


def _cfg():
    return llama_config("tiny", max_seq_len=SEQ, vocab_size=VOCAB,
                        n_layers=4, attn_impl="xla")


def _ids(m=4, b=2, seed=0):
    return np.random.RandomState(seed).randint(0, VOCAB, (m * b, SEQ)).astype(np.int32)


def test_pipeline_loss_matches_dense(devices8):
    initialize_topology(MeshConfig(pipe=4, data=-1), jax.devices()[:8])
    cfg = _cfg()
    model = pipelined_causal_lm(cfg, num_microbatches=4)
    params = model.init_params(jax.random.PRNGKey(0))
    ids = jnp.asarray(_ids())

    with deepspeed_tpu.get_topology().mesh:
        pipe_loss = jax.jit(model.loss_fn)(params, {"input_ids": ids}, None)
    dense_loss = causal_lm_loss(cfg, params, {"input_ids": ids}, None)
    np.testing.assert_allclose(float(pipe_loss), float(dense_loss), rtol=1e-5)


def test_pipeline_grads_match_dense(devices8):
    initialize_topology(MeshConfig(pipe=4, data=-1), jax.devices()[:8])
    cfg = _cfg()
    model = pipelined_causal_lm(cfg, num_microbatches=2)
    params = model.init_params(jax.random.PRNGKey(1))
    ids = jnp.asarray(_ids(m=2))

    with deepspeed_tpu.get_topology().mesh:
        g_pipe = jax.jit(jax.grad(
            lambda p: model.loss_fn(p, {"input_ids": ids}, None)))(params)
    g_dense = jax.grad(
        lambda p: causal_lm_loss(cfg, p, {"input_ids": ids}, None))(params)
    flat_p, _ = jax.tree_util.tree_flatten_with_path(g_pipe)
    flat_d, _ = jax.tree_util.tree_flatten_with_path(g_dense)
    for (kp, a), (_, b) in zip(flat_p, flat_d):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=3e-5, rtol=2e-3,
            err_msg=jax.tree_util.keystr(kp))


def test_pipeline_trains_end_to_end(devices8):
    initialize_topology(MeshConfig(pipe=2, data=-1), jax.devices()[:8])
    cfg = _cfg()
    model = pipelined_causal_lm(cfg, num_microbatches=2)
    engine, *_ = deepspeed_tpu.initialize(
        model=model,
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 5e-3}},
                "zero_optimization": {"stage": 1},
                "mesh": {"pipe": 2, "data": -1}},
        topology=deepspeed_tpu.get_topology())
    # global batch per step: micro_bs(2) * dp(4) * num_micro... engine sees
    # [1, dp*micro, seq]; pipeline splits micro dim internally
    ids = _ids(m=2, b=4, seed=3).reshape(1, 8, SEQ)
    losses = [float(engine.train_batch({"input_ids": jnp.asarray(ids)}))
              for _ in range(6)]
    assert losses[-1] < losses[0]


def test_pipeline_param_sharded_over_pipe(devices8):
    initialize_topology(MeshConfig(pipe=4, data=-1), jax.devices()[:8])
    cfg = _cfg()
    model = pipelined_causal_lm(cfg, num_microbatches=2)
    engine, *_ = deepspeed_tpu.initialize(
        model=model,
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "mesh": {"pipe": 4, "data": -1}},
        topology=deepspeed_tpu.get_topology())
    wq = engine.state.params["layers"]["attn"]["wq"]
    assert wq.sharding.spec[0] == "pipe"


# ---------------------------------------------------------------------------
# Generic PipelineModule (reference runtime/pipe/module.py:86)
# ---------------------------------------------------------------------------
from deepspeed_tpu.runtime.pipe.module import (LayerSpec, PipelineModule,
                                               TiedLayerSpec,
                                               partition_balanced)

HID = 16


def _linear_spec(key, din, dout, act=True, name="linear"):
    def init(rng):
        k1, _ = jax.random.split(jax.random.fold_in(rng, key))
        return {"w": jax.random.normal(k1, (din, dout)) * 0.3,
                "b": jnp.zeros((dout,))}

    def apply(p, x):
        y = x @ p["w"] + p["b"]
        return jnp.tanh(y) if act else y

    return LayerSpec(init, apply, name=name)


def _mlp_layers(n=8):
    """A non-transformer user model: a plain tanh-MLP regression stack."""
    return [_linear_spec(i, HID, HID, name=f"mlp{i}") for i in range(n)]


def _mse(out, y):
    return jnp.mean((out - y) ** 2)


def _xy(n=8, seed=0):
    r = np.random.RandomState(seed)
    x = r.randn(n, HID).astype(np.float32)
    y = np.tanh(x @ r.randn(HID, HID).astype(np.float32) * 0.3)
    return jnp.asarray(x), jnp.asarray(y)


def test_partition_balanced():
    # equal weights -> equal split
    assert partition_balanced([1.0] * 8, 4) == [0, 2, 4, 6, 8]
    # one heavy layer gets its own stage
    b = partition_balanced([10.0, 1.0, 1.0, 1.0], 2)
    assert b == [0, 1, 4]
    # weights spread: every stage non-empty
    b = partition_balanced([3, 1, 1, 1, 1, 1, 1, 3], 4)
    assert b[0] == 0 and b[-1] == 8 and all(b[i] < b[i + 1] for i in range(4))


def test_generic_pipeline_matches_dense(devices8):
    """A user MLP (not the in-repo transformer) pipelined through the public
    API: pipeline loss AND grads == dense execution of the same layers."""
    initialize_topology(MeshConfig(pipe=4, data=-1), jax.devices()[:8])
    pm = PipelineModule(_mlp_layers(8), loss_fn=_mse, num_microbatches=4,
                        partition_method="uniform")
    assert pm.stackable  # uniform 8/4 -> identical groups -> pipe-sharded
    params = pm.init_params(jax.random.PRNGKey(0))
    x, y = _xy(8)

    with deepspeed_tpu.get_topology().mesh:
        loss_p = jax.jit(pm.loss_fn)(params, (x, y))
        g_pipe = jax.jit(jax.grad(lambda p: pm.loss_fn(p, (x, y))))(params)
    loss_d = pm._dense_loss(params, x, y)
    np.testing.assert_allclose(float(loss_p), float(loss_d), rtol=1e-5)
    g_dense = jax.grad(lambda p: pm._dense_loss(p, x, y))(params)
    flat_p, _ = jax.tree_util.tree_flatten_with_path(g_pipe)
    flat_d, _ = jax.tree_util.tree_flatten_with_path(g_dense)
    assert len(flat_p) == len(flat_d) and len(flat_p) > 0
    for (kp, a), (_, b) in zip(flat_p, flat_d):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-4,
                                   err_msg=jax.tree_util.keystr(kp))


def test_generic_pipeline_tied_layers_grads(devices8):
    """Tied first/last layers (embedding-style reuse): the shared params get
    summed gradient contributions from BOTH stages (reference
    allreduce_tied_weight_gradients, pipe/module.py:454)."""
    initialize_topology(MeshConfig(pipe=2, data=-1), jax.devices()[:8])

    def tied_init(rng):
        return {"w": jax.random.normal(rng, (HID, HID)) * 0.3}

    first = TiedLayerSpec(init_fn=tied_init, key="emb",
                          apply_fn=lambda p, x: jnp.tanh(x @ p["w"]),
                          name="tied-in")
    last = TiedLayerSpec(init_fn=None, key="emb",
                         apply_fn=lambda p, x: x @ p["w"].T, name="tied-out")
    layers = [first, _linear_spec(1, HID, HID), _linear_spec(2, HID, HID), last]
    pm = PipelineModule(layers, loss_fn=_mse, num_microbatches=2,
                        partition_method="uniform")
    params = pm.init_params(jax.random.PRNGKey(1))
    assert "emb" in params["tied"]
    x, y = _xy(8, seed=2)  # dp=4 x M=2 x b=1
    with deepspeed_tpu.get_topology().mesh:
        g_pipe = jax.jit(jax.grad(lambda p: pm.loss_fn(p, (x, y))))(params)
    g_dense = jax.grad(lambda p: pm._dense_loss(p, x, y))(params)
    np.testing.assert_allclose(np.asarray(g_pipe["tied"]["emb"]["w"]),
                               np.asarray(g_dense["tied"]["emb"]["w"]),
                               atol=1e-5, rtol=1e-4)
    assert np.abs(np.asarray(g_dense["tied"]["emb"]["w"])).max() > 0


def test_generic_pipeline_heterogeneous_stage_local(devices8):
    """Layer groups with different structures (embed/middle/head-style) get
    flat-packed per-stage params SHARDED over the pipe axis — no full
    replication (VERDICT r3 weak #4; reference always stage-locals,
    pipe/module.py:393) — and loss AND grads still match dense."""
    initialize_topology(MeshConfig(pipe=2, data=-1), jax.devices()[:8])
    layers = [
        _linear_spec(0, HID, HID),
        LayerSpec(None, lambda p, x: jax.nn.relu(x), name="act"),  # paramless
        _linear_spec(1, HID, HID),
        _linear_spec(2, HID, HID, act=False, name="head"),
    ]
    pm = PipelineModule(layers, loss_fn=_mse, num_microbatches=2,
                        partition_method="uniform")
    assert not pm.stackable
    params = pm.init_params(jax.random.PRNGKey(2))
    # flat-packed representation: per-dtype [num_stages, maxlen] buffers
    assert "stages_flat" in params and "stages" not in params
    for v in params["stages_flat"].values():
        assert v.shape[0] == 2
    # the partition rules place the stage dim on the pipe axis
    rules = dict(pm.partition_rules())
    assert any("stages_flat" in k for k in rules)
    x, y = _xy(8, seed=3)
    with deepspeed_tpu.get_topology().mesh:
        loss_p = jax.jit(pm.loss_fn)(params, (x, y))
        g_pipe = jax.jit(jax.grad(lambda p: pm.loss_fn(p, (x, y))))(params)
    np.testing.assert_allclose(float(loss_p),
                               float(pm._dense_loss(params, x, y)), rtol=1e-5)
    g_dense = jax.grad(lambda p: pm._dense_loss(p, x, y))(params)
    for (kp, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(g_pipe)[0],
            jax.tree_util.tree_flatten_with_path(g_dense)[0]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5,
                                   rtol=1e-4, err_msg=jax.tree_util.keystr(kp))


def test_generic_pipeline_heterogeneous_engine_sharded(devices8):
    """Through the engine: heterogeneous stage params land pipe-sharded on
    devices and the model trains."""
    initialize_topology(MeshConfig(pipe=2, data=-1), jax.devices()[:8])
    layers = [
        _linear_spec(0, HID, HID),
        LayerSpec(None, lambda p, x: jax.nn.relu(x), name="act"),
        _linear_spec(1, HID, HID),
        _linear_spec(2, HID, HID, act=False, name="head"),
    ]
    pm = PipelineModule(layers, loss_fn=_mse, num_microbatches=2,
                        partition_method="uniform")
    engine, *_ = deepspeed_tpu.initialize(
        model=pm.to_model_spec(),
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 5e-3}},
                "zero_optimization": {"stage": 1},
                "mesh": {"pipe": 2, "data": -1}},
        topology=deepspeed_tpu.get_topology())
    leaf = next(iter(engine.state.params["stages_flat"].values()))
    assert "pipe" in str(leaf.sharding.spec)
    x, y = _xy(8, seed=11)
    losses = [float(engine.train_batch((x[None], y[None]))) for _ in range(8)]
    assert losses[-1] < losses[0]


def test_pipeline_memory_bounded_in_microbatches(devices8):
    """1F1B-equivalent memory bound (VERDICT r3 missing #1): the compiled
    backward's temp memory must NOT scale with num_microbatches — per-tick
    remat keeps live residuals at O(ring carry), so more micro-batches mean
    less bubble, not more memory (reference TrainSchedule,
    pipe/schedule.py:189)."""
    initialize_topology(MeshConfig(pipe=2, data=-1), jax.devices()[:8])

    def temp_bytes(M, checkpoint_ticks=True):
        pm = PipelineModule(_mlp_layers(8), loss_fn=_mse, num_microbatches=M,
                            partition_method="uniform",
                            checkpoint_ticks=checkpoint_ticks)
        params = pm.init_params(jax.random.PRNGKey(0))
        r = np.random.RandomState(0)
        # fixed LOCAL micro-batch size of 1 per data shard: total batch
        # scales with M, per-tick work constant
        n = 4 * M  # dp=4 shards x M micro x b=1
        x = jnp.asarray(r.randn(n, HID).astype(np.float32))
        y = jnp.asarray(r.randn(n, HID).astype(np.float32))
        grad_fn = jax.grad(lambda p: pm.loss_fn(p, (x, y)))
        with deepspeed_tpu.get_topology().mesh:
            compiled = jax.jit(grad_fn).lower(params).compile()
        stats = compiled.memory_analysis()
        if stats is None or not getattr(stats, "temp_size_in_bytes", 0):
            pytest.skip("backend reports no memory analysis")
        return stats.temp_size_in_bytes

    t4, t16 = temp_bytes(4), temp_bytes(16)
    # inputs scale 4x; the residual pool must stay near-flat.  Allow the
    # O(M) ring carries + per-micro loss bookkeeping, but nothing more:
    # measured ~200 B/micro with per-tick remat vs ~1400 B/micro without
    # (per-layer tanh/matmul residuals for every tick) on this model.
    per_m = (t16 - t4) / 12  # marginal temp bytes per extra micro-batch
    ring_bytes = 4 * HID * 4  # one fp32 micro-batch boundary activation/shard
    assert per_m <= 4 * ring_bytes, (
        f"temp grows {per_m:.0f} B/microbatch (ring={ring_bytes} B): "
        f"residuals scale with M; t4={t4} t16={t16}")


def test_generic_pipeline_last_stage_shape_change(devices8):
    """The LAST group may change output shape (classifier head): ring shape
    is the stage-boundary shape; loss consumes the head output."""
    initialize_topology(MeshConfig(pipe=2, data=-1), jax.devices()[:8])
    layers = [_linear_spec(0, HID, HID), _linear_spec(1, HID, HID),
              _linear_spec(2, HID, HID),
              _linear_spec(3, HID, 4, act=False, name="head")]  # 16 -> 4
    pm = PipelineModule(layers, loss_fn=_mse, num_microbatches=2,
                        partition_method="uniform")
    params = pm.init_params(jax.random.PRNGKey(3))
    r = np.random.RandomState(5)
    x = jnp.asarray(r.randn(8, HID).astype(np.float32))
    y = jnp.asarray(r.randn(8, 4).astype(np.float32))
    with deepspeed_tpu.get_topology().mesh:
        loss_p = jax.jit(pm.loss_fn)(params, (x, y))
        g_pipe = jax.jit(jax.grad(lambda p: pm.loss_fn(p, (x, y))))(params)
    np.testing.assert_allclose(float(loss_p),
                               float(pm._dense_loss(params, x, y)), rtol=1e-5)
    g_dense = jax.grad(lambda p: pm._dense_loss(p, x, y))(params)
    for (kp, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(g_pipe)[0],
            jax.tree_util.tree_flatten_with_path(g_dense)[0]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5,
                                   rtol=1e-4, err_msg=jax.tree_util.keystr(kp))


def test_generic_pipeline_engine_3d(devices8):
    """pipe(2) x data(2) x model(2) composition through the engine: the
    generic module trains under ZeRO-1 with TP-sharded inner layers."""
    initialize_topology(MeshConfig(pipe=2, data=2, model=2),
                        jax.devices()[:8])
    pm = PipelineModule(_mlp_layers(8), loss_fn=_mse, num_microbatches=2,
                        partition_method="parameters")
    spec = pm.to_model_spec()
    engine, *_ = deepspeed_tpu.initialize(
        model=spec,
        config={"train_micro_batch_size_per_gpu": 4,
                "optimizer": {"type": "Adam", "params": {"lr": 5e-3}},
                "zero_optimization": {"stage": 1},
                "mesh": {"pipe": 2, "data": 2, "model": 2}},
        topology=deepspeed_tpu.get_topology())
    x, y = _xy(8, seed=7)  # dp=2 * micro_bs=4
    batch = (x[None], y[None])  # leading gas dim
    losses = [float(engine.train_batch(batch)) for _ in range(8)]
    assert losses[-1] < losses[0]
    # pipe sharding really happened
    leaf = jax.tree_util.tree_leaves(engine.state.params["stages"])[0]
    assert "pipe" in str(leaf.sharding.spec)


def test_pipeline_moe_aux_matches_dense(devices8):
    """MoE aux loss under the pipeline: every stage's router aux counts,
    garbage warm-up ticks don't (code-review r3 finding)."""
    initialize_topology(MeshConfig(pipe=2, data=-1), jax.devices()[:8])
    cfg = llama_config("tiny", max_seq_len=SEQ, vocab_size=VOCAB, n_layers=4,
                       attn_impl="xla", moe_experts=2, moe_top_k=1)
    model = pipelined_causal_lm(cfg, num_microbatches=2)
    params = model.init_params(jax.random.PRNGKey(4))
    ids = jnp.asarray(_ids(m=2, b=4, seed=6))
    with deepspeed_tpu.get_topology().mesh:
        pipe_loss = jax.jit(model.loss_fn)(params, {"input_ids": ids}, None)
    dense_loss = causal_lm_loss(cfg, params, {"input_ids": ids}, None)
    np.testing.assert_allclose(float(pipe_loss), float(dense_loss), rtol=1e-4)


def test_pipe_stage_resharding_2_to_4(devices8):
    """Reference 3D-reshape parity (checkpoint/reshape_3d_utils): params
    trained at pipe=2 regroup losslessly to pipe=4 (stackable path) and to
    a heterogeneous flat-packed partitioning; dense loss is identical."""
    from deepspeed_tpu.parallel.mesh import MeshConfig, initialize_topology

    r = np.random.RandomState(0)
    x = r.randn(8, 16).astype(np.float32)
    y = r.randint(0, 4, (8,)).astype(np.int32)

    def mlp_layers(hetero):
        def lin(key, din, dout):
            def init(rng):
                k = jax.random.fold_in(rng, key)
                return {"w": jax.random.normal(k, (din, dout)) * 0.1,
                        "b": jnp.zeros((dout,))}
            return LayerSpec(init, lambda p, h: jnp.tanh(h @ p["w"] + p["b"]),
                             name=f"lin{key}")
        if hetero:
            # distinct widths force the flat-packed representation, and
            # the tied in/out pair exercises the None placeholders in the
            # per-layer canonical view (a desync there corrupts every
            # later layer's params)
            def temb(rng):
                return {"w": jax.random.normal(rng, (16, 16)) * 0.2}

            return [TiedLayerSpec(init_fn=temb, key="emb",
                                  apply_fn=lambda p, h: jnp.tanh(h @ p["w"]),
                                  name="tin"),
                    lin(1, 16, 24), lin(2, 24, 16),
                    TiedLayerSpec(init_fn=None, key="emb",
                                  apply_fn=lambda p, h: h @ p["w"].T,
                                  name="tout"),
                    lin(3, 16, 4)]
        dims = [16, 16, 16, 16, 4]
        return [lin(i, dims[i], dims[i + 1]) for i in range(4)]

    def xent(logits, y):
        lp = jax.nn.log_softmax(logits, -1)
        return -jnp.mean(jnp.take_along_axis(lp, y[..., None], -1))

    for hetero in (False, True):
        initialize_topology(MeshConfig(pipe=2, data=-1), jax.devices()[:8])
        pm2 = PipelineModule(mlp_layers(hetero), loss_fn=xent,
                             num_microbatches=2, partition_method="uniform")
        params2 = pm2.init_params(jax.random.PRNGKey(1))
        loss2 = float(pm2._dense_loss(params2, jnp.asarray(x), jnp.asarray(y)))

        from deepspeed_tpu.parallel import mesh as mesh_mod
        mesh_mod.reset_topology()
        initialize_topology(MeshConfig(pipe=4, data=-1), jax.devices()[:8])
        pm4 = PipelineModule(mlp_layers(hetero), loss_fn=xent,
                             num_microbatches=2, partition_method="uniform")
        params4 = PipelineModule.reshard_params(pm2, params2, pm4)
        loss4 = float(pm4._dense_loss(params4, jnp.asarray(x), jnp.asarray(y)))
        np.testing.assert_allclose(loss4, loss2, rtol=1e-6)

        # and back down: 4 -> 2 roundtrips to the identical leaves
        back = PipelineModule.reshard_params(pm4, params4, pm2)
        for a, b in zip(jax.tree_util.tree_leaves(back),
                        jax.tree_util.tree_leaves(params2)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        mesh_mod.reset_topology()


# ---------------------------------------------------------------------------
# Pipe perf-path lifecycle: overlap stand-down, EF hop residual checkpointing
# ---------------------------------------------------------------------------


def _pipe_engine(zero, pipeline=None, lr=1e-2):
    cfg = {"train_micro_batch_size_per_gpu": 2,
           "optimizer": {"type": "Adam", "params": {"lr": lr}},
           "zero_optimization": zero,
           "mesh": {"pipe": 2, "data": 2}}
    if pipeline is not None:
        cfg["pipeline"] = pipeline
    model = pipelined_causal_lm(_cfg(), num_microbatches=2)
    engine, *_ = deepspeed_tpu.initialize(
        model=model, config=cfg, topology=deepspeed_tpu.get_topology())
    return engine


def test_pipe_overlap_stand_down_both_directions(devices8, caplog):
    """Unsupported pipe x overlap combos must stand DOWN loudly (one warning
    naming pipe, fp in-scan reduce disabled), and supported combos must
    actually arm the in-scan bucketed reducer — tested in both directions so
    a silently-always-off (or always-on) plan can't pass."""
    from deepspeed_tpu.utils.logging import logger as ds_logger

    initialize_topology(MeshConfig(pipe=2, data=2), jax.devices()[:4])

    ds_logger.propagate = True  # DeepSpeedTPU logger is non-propagating
    try:
        # stage 2 shards grads over data: incompatible with the per-stage
        # in-scan reduce -> plan absent, warning names pipe
        with caplog.at_level("WARNING", logger="DeepSpeedTPU"):
            e_down = _pipe_engine({"stage": 2, "overlap_grad_reduce": True})
        assert e_down._pipe_plan is None
        down_msgs = [r.getMessage() for r in caplog.records
                     if "overlap disabled" in r.getMessage()]
        assert down_msgs and any("pipe:" in m for m in down_msgs), down_msgs

        # supported direction: ZeRO-1 + overlap arms the plan, no stand-down
        caplog.clear()
        with caplog.at_level("WARNING", logger="DeepSpeedTPU"):
            e_up = _pipe_engine({"stage": 1, "overlap_grad_reduce": True,
                                 "overlap_compression": "int8",
                                 "overlap_bucket_mb": 1})
        assert e_up._pipe_plan is not None
        assert not [r.getMessage() for r in caplog.records
                    if "overlap disabled" in r.getMessage()]
    finally:
        ds_logger.propagate = False


def test_pipe_hop_ef_checkpoint_roundtrip(devices8):
    """The hop-EF residual lifecycle contract (same chaos-drill shape as the
    overlap EF tests): train with int8 activation hops, checkpoint mid-run,
    resume into a FRESH engine — comm_errors['pipe'] rides the checkpoint
    bit-exactly and the post-resume trajectory equals an uninterrupted run."""
    import tempfile

    initialize_topology(MeshConfig(pipe=2, data=2), jax.devices()[:4])
    pipeline = {"hop_compression": "int8"}
    ids = [_ids(m=2, b=2, seed=20 + i).reshape(1, 4, SEQ) for i in range(4)]
    batches = [{"input_ids": jnp.asarray(x)} for x in ids]

    e_ctrl = _pipe_engine({"stage": 1}, pipeline)
    assert "pipe" in (e_ctrl.state.comm_errors or {})
    ctrl = [float(e_ctrl.train_batch(b)) for b in batches]

    d = tempfile.mkdtemp()
    e1 = _pipe_engine({"stage": 1}, pipeline)
    part1 = [float(e1.train_batch(b)) for b in batches[:2]]
    r_saved = [np.asarray(jax.device_get(leaf)) for leaf in
               jax.tree_util.tree_leaves(e1.state.comm_errors["pipe"])]
    assert max(np.abs(r).max() for r in r_saved) > 0, \
        "hop EF residual never populated"
    e1.save_checkpoint(d, tag="mid")

    e2 = _pipe_engine({"stage": 1}, pipeline)
    e2.load_checkpoint(d, tag="mid")
    r_loaded = [np.asarray(jax.device_get(leaf)) for leaf in
                jax.tree_util.tree_leaves(e2.state.comm_errors["pipe"])]
    for a, b in zip(r_saved, r_loaded):
        np.testing.assert_array_equal(a, b,
                                      "residual round-trip not bit-exact")
    part2 = [float(e2.train_batch(b)) for b in batches[2:]]
    assert ctrl == part1 + part2, (ctrl, part1 + part2)


def test_generic_module_hop_compression_knob(devices8):
    """PipelineModule(hop_compression=...) compresses the generic module's
    activation hops through the same differentiated ppermute: the model
    still matches dense execution to quantization tolerance, and grads
    still flow through the compressed boundary."""
    initialize_topology(MeshConfig(pipe=4, data=-1), jax.devices()[:8])
    pm = PipelineModule(_mlp_layers(8), loss_fn=_mse, num_microbatches=4,
                        partition_method="uniform", hop_compression="int8")
    assert pm.hop_spec is not None and pm.hop_spec.format == "int8"
    params = pm.init_params(jax.random.PRNGKey(0))
    x, y = _xy(8)
    with deepspeed_tpu.get_topology().mesh:
        loss_q = jax.jit(pm.loss_fn)(params, (x, y))
        g_q = jax.jit(jax.grad(lambda p: pm.loss_fn(p, (x, y))))(params)
    loss_d = float(pm._dense_loss(params, x, y))
    # int8 blockwise hops bound the boundary error to ~1% of the block
    # scale; the MSE loss on tanh activations stays within a few percent
    np.testing.assert_allclose(float(loss_q), loss_d, rtol=0.05, atol=0.02)
    g_dense = jax.grad(lambda p: pm._dense_loss(p, x, y))(params)
    for (kp, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(g_q)[0],
            jax.tree_util.tree_flatten_with_path(g_dense)[0]):
        a, b = np.asarray(a), np.asarray(b)
        assert np.isfinite(a).all(), jax.tree_util.keystr(kp)
        # grads through the quantized boundary track dense direction
        denom = np.abs(b).max() + 1e-8
        assert np.abs(a - b).max() / denom < 0.2, jax.tree_util.keystr(kp)
    assert max(np.abs(np.asarray(v)).max()
               for v in jax.tree_util.tree_leaves(g_q)) > 0


def test_pipelined_lm_composes_with_tensor_parallel(devices8):
    """pipe x model x data on the transformer pipe path: only pipe+batch
    axes are MANUAL in the shard_map; the model axis stays auto, so GSPMD
    partitions the stage matmuls and inserts the TP collectives (a fully
    manual map hands the body a half-sized wqkv that the global-head
    reshape would corrupt).  Loss must match the pipe x data run."""
    from deepspeed_tpu.runtime.pipe.engine import pipelined_causal_lm

    if jax.default_backend() == "cpu":
        pytest.skip(
            "XLA CPU cannot compile the partial-manual pipe x TP program: "
            "sharding propagation aborts with 'Check failed: "
            "sharding.IsManualSubgroup()' (hlo_sharding_util.cc); the "
            "partial-manual form is TPU-targeted")

    cfg = llama_config("tiny", max_seq_len=32)
    # 8 global rows both runs: 4/rank at dp=2 (TP mesh), 2/rank at dp=4 —
    # num_microbatches must divide the per-rank batch
    ids = np.random.RandomState(0).randint(
        0, cfg.vocab_size, (1, 8, 32)).astype(np.int32)

    def run(mesh_cfg, mesh_dict, micro_bs):
        from deepspeed_tpu.parallel import mesh as mesh_mod
        mesh_mod.reset_topology()
        initialize_topology(mesh_cfg, jax.devices()[:8])
        model = pipelined_causal_lm(cfg, num_microbatches=2)
        engine, *_ = deepspeed_tpu.initialize(
            model=model,
            config={"train_micro_batch_size_per_gpu": micro_bs,
                    "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                    "zero_optimization": {"stage": 1},
                    "mesh": mesh_dict},
            topology=deepspeed_tpu.get_topology())
        return [float(engine.train_batch({"input_ids": jnp.asarray(ids)}))
                for _ in range(3)]

    l_tp = run(MeshConfig(pipe=2, model=2, data=-1),
               {"pipe": 2, "model": 2, "data": -1}, micro_bs=4)
    l_dp = run(MeshConfig(pipe=2, data=-1), {"pipe": 2, "data": -1},
               micro_bs=2)
    np.testing.assert_allclose(l_tp, l_dp, rtol=2e-4)
    assert l_tp[-1] < l_tp[0]
