"""Pipeline engine tests (reference tests/unit/pipe/).

The key correctness property: the pipelined loss/gradients equal the
non-pipelined model's (same params, same data), because the pipeline is
just an execution schedule.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.llama import llama_config
from deepspeed_tpu.models.transformer import causal_lm_loss
from deepspeed_tpu.parallel.mesh import MeshConfig, initialize_topology
from deepspeed_tpu.runtime.pipe.engine import pipelined_causal_lm

SEQ = 16
VOCAB = 64


def _cfg():
    return llama_config("tiny", max_seq_len=SEQ, vocab_size=VOCAB,
                        n_layers=4, attn_impl="xla")


def _ids(m=4, b=2, seed=0):
    return np.random.RandomState(seed).randint(0, VOCAB, (m * b, SEQ)).astype(np.int32)


def test_pipeline_loss_matches_dense(devices8):
    initialize_topology(MeshConfig(pipe=4, data=-1), jax.devices()[:8])
    cfg = _cfg()
    model = pipelined_causal_lm(cfg, num_microbatches=4)
    params = model.init_params(jax.random.PRNGKey(0))
    ids = jnp.asarray(_ids())

    with deepspeed_tpu.get_topology().mesh:
        pipe_loss = jax.jit(model.loss_fn)(params, {"input_ids": ids}, None)
    dense_loss = causal_lm_loss(cfg, params, {"input_ids": ids}, None)
    np.testing.assert_allclose(float(pipe_loss), float(dense_loss), rtol=1e-5)


def test_pipeline_grads_match_dense(devices8):
    initialize_topology(MeshConfig(pipe=4, data=-1), jax.devices()[:8])
    cfg = _cfg()
    model = pipelined_causal_lm(cfg, num_microbatches=2)
    params = model.init_params(jax.random.PRNGKey(1))
    ids = jnp.asarray(_ids(m=2))

    with deepspeed_tpu.get_topology().mesh:
        g_pipe = jax.jit(jax.grad(
            lambda p: model.loss_fn(p, {"input_ids": ids}, None)))(params)
    g_dense = jax.grad(
        lambda p: causal_lm_loss(cfg, p, {"input_ids": ids}, None))(params)
    flat_p, _ = jax.tree_util.tree_flatten_with_path(g_pipe)
    flat_d, _ = jax.tree_util.tree_flatten_with_path(g_dense)
    for (kp, a), (_, b) in zip(flat_p, flat_d):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=3e-5, rtol=2e-3,
            err_msg=jax.tree_util.keystr(kp))


def test_pipeline_trains_end_to_end(devices8):
    initialize_topology(MeshConfig(pipe=2, data=-1), jax.devices()[:8])
    cfg = _cfg()
    model = pipelined_causal_lm(cfg, num_microbatches=2)
    engine, *_ = deepspeed_tpu.initialize(
        model=model,
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 5e-3}},
                "zero_optimization": {"stage": 1},
                "mesh": {"pipe": 2, "data": -1}},
        topology=deepspeed_tpu.get_topology())
    # global batch per step: micro_bs(2) * dp(4) * num_micro... engine sees
    # [1, dp*micro, seq]; pipeline splits micro dim internally
    ids = _ids(m=2, b=4, seed=3).reshape(1, 8, SEQ)
    losses = [float(engine.train_batch({"input_ids": jnp.asarray(ids)}))
              for _ in range(6)]
    assert losses[-1] < losses[0]


def test_pipeline_param_sharded_over_pipe(devices8):
    initialize_topology(MeshConfig(pipe=4, data=-1), jax.devices()[:8])
    cfg = _cfg()
    model = pipelined_causal_lm(cfg, num_microbatches=2)
    engine, *_ = deepspeed_tpu.initialize(
        model=model,
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "mesh": {"pipe": 4, "data": -1}},
        topology=deepspeed_tpu.get_topology())
    wq = engine.state.params["layers"]["attn"]["wq"]
    assert wq.sharding.spec[0] == "pipe"
