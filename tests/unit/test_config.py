"""Config system tests (reference tests/unit/runtime/test_ds_config_dict.py)."""

import pytest

from deepspeed_tpu.runtime.config import DeepSpeedConfig


def test_batch_triangle_all_given():
    cfg = DeepSpeedConfig({
        "train_batch_size": 32,
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 2,
    }, dp_world_size=8)
    assert cfg.train_batch_size == 32


def test_batch_triangle_infer_gas():
    cfg = DeepSpeedConfig({"train_batch_size": 32, "train_micro_batch_size_per_gpu": 2},
                          dp_world_size=8)
    assert cfg.gradient_accumulation_steps == 2


def test_batch_triangle_infer_train():
    cfg = DeepSpeedConfig({"train_micro_batch_size_per_gpu": 4}, dp_world_size=8)
    assert cfg.train_batch_size == 32
    assert cfg.gradient_accumulation_steps == 1


def test_batch_triangle_inconsistent():
    with pytest.raises(ValueError):
        DeepSpeedConfig({
            "train_batch_size": 33,
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 2,
        }, dp_world_size=8)


def test_fp16_bf16_exclusive():
    with pytest.raises(ValueError):
        DeepSpeedConfig({"fp16": {"enabled": True}, "bf16": {"enabled": True}})


def test_zero_config():
    cfg = DeepSpeedConfig({"zero_optimization": {"stage": 3,
                                                 "stage3_prefetch_bucket_size": 1000}})
    assert cfg.zero_config.stage == 3
    assert cfg.zero_config.stage3_prefetch_bucket_size == 1000
    assert cfg.zero_enabled


def test_zero_invalid_stage():
    with pytest.raises(ValueError):
        DeepSpeedConfig({"zero_optimization": {"stage": 5}})


def test_deprecated_key_warns():
    cfg = DeepSpeedConfig({"zero_optimization": {"stage": 1, "cpu_offload": {"device": "cpu"}}})
    assert cfg.zero_config.offload_optimizer.device == "cpu"


def test_optimizer_scheduler_blocks():
    cfg = DeepSpeedConfig({
        "optimizer": {"type": "AdamW", "params": {"lr": 0.001, "betas": [0.9, 0.95]}},
        "scheduler": {"type": "WarmupLR", "params": {"warmup_num_steps": 10}},
    })
    assert cfg.optimizer.type == "AdamW"
    assert cfg.scheduler.type == "WarmupLR"


def test_mesh_config():
    cfg = DeepSpeedConfig({"mesh": {"model": 2, "data": -1}})
    assert cfg.mesh.model == 2
    assert cfg.mesh.data == -1
