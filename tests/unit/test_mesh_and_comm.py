"""Mesh topology + comm verb tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import deepspeed_tpu.comm as comm
from deepspeed_tpu.utils.jax_compat import shard_map
from deepspeed_tpu.runtime.config import MeshConfig
from deepspeed_tpu.parallel.mesh import (DATA_AXIS, MODEL_AXIS, MeshTopology,
                                         SEQ_AXIS)


def test_mesh_sizes(devices8):
    topo = MeshTopology(MeshConfig(data=-1, model=2), devices8)
    assert topo.axis_size("data") == 4
    assert topo.model_parallel_size == 2
    assert topo.world_size == 8


def test_mesh_all_fixed(devices8):
    topo = MeshTopology(MeshConfig(pipe=2, data=2, model=2), devices8)
    assert topo.axis_size("data") == 2
    with pytest.raises(ValueError):
        MeshTopology(MeshConfig(pipe=3, data=-1), devices8)


def test_all_reduce_psum(devices8):
    topo = MeshTopology(MeshConfig(data=-1), devices8)

    def body(x):
        return comm.all_reduce(x, "sum", DATA_AXIS)

    f = shard_map(body, check_vma=False, mesh=topo.mesh, in_specs=P(DATA_AXIS),
              out_specs=P(DATA_AXIS))
    x = jnp.arange(8.0)
    out = f(x)
    np.testing.assert_allclose(np.asarray(out), np.full(8, x.sum()))


def test_all_gather_and_reduce_scatter(devices8):
    topo = MeshTopology(MeshConfig(data=-1), devices8)

    def gather_body(x):
        return comm.all_gather(x, DATA_AXIS, tensor_axis=0)

    f = shard_map(gather_body, check_vma=False, mesh=topo.mesh, in_specs=P(DATA_AXIS, None),
              out_specs=P(None, None))
    x = jnp.arange(16.0).reshape(8, 2)
    out = f(x)
    # per-rank result is the full (8, 2); replicated -> global (8, 2)
    assert out.shape == (8, 2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))

    def rs_body(x):
        return comm.reduce_scatter(x, "sum", DATA_AXIS, scatter_dim=0)

    g = shard_map(rs_body, check_vma=False, mesh=topo.mesh, in_specs=P(None, None),
              out_specs=P(DATA_AXIS, None))
    y = jnp.ones((8, 2))
    out = g(y)
    np.testing.assert_allclose(np.asarray(out), np.full((8, 2), 8.0))


def test_all_to_all(devices8):
    topo = MeshTopology(MeshConfig(data=1, sequence=8), devices8)

    def body(x):
        # x per-rank: [seq_shard, heads] -> [full seq, heads/ranks]
        return comm.all_to_all_single(x, SEQ_AXIS, split_dim=1, concat_dim=0)

    f = shard_map(body, check_vma=False, mesh=topo.mesh, in_specs=P(SEQ_AXIS, None),
              out_specs=P(None, SEQ_AXIS))
    x = jnp.arange(64.0).reshape(8, 8)
    out = f(x)
    assert out.shape == (8, 8)
    # round trip back
    def inv(x):
        return comm.all_to_all_single(x, SEQ_AXIS, split_dim=0, concat_dim=1)

    finv = shard_map(inv, check_vma=False, mesh=topo.mesh, in_specs=P(None, SEQ_AXIS),
                 out_specs=P(SEQ_AXIS, None))
    np.testing.assert_allclose(np.asarray(finv(out)), np.asarray(x))


def test_broadcast(devices8):
    topo = MeshTopology(MeshConfig(data=-1), devices8)

    def body(x):
        return comm.broadcast(x, src_index=3, axis=DATA_AXIS)

    f = shard_map(body, check_vma=False, mesh=topo.mesh, in_specs=P(DATA_AXIS), out_specs=P(DATA_AXIS))
    x = jnp.arange(8.0)
    out = f(x)
    np.testing.assert_allclose(np.asarray(out), np.full(8, 3.0))


def test_ppermute_ring(devices8):
    topo = MeshTopology(MeshConfig(data=1, pipe=8), devices8)

    def body(x):
        return comm.send_recv_next(x, "pipe")

    f = shard_map(body, check_vma=False, mesh=topo.mesh, in_specs=P("pipe"), out_specs=P("pipe"))
    x = jnp.arange(8.0)
    out = np.asarray(f(x))
    np.testing.assert_allclose(out, np.roll(np.arange(8.0), 1))


def test_comms_logger(devices8):
    logger = comm.configure_comms_logger(enabled=True)
    logger.reset()
    topo = MeshTopology(MeshConfig(data=-1), devices8)
    f = shard_map(lambda x: comm.all_reduce(x, "sum", DATA_AXIS), check_vma=False,
                  mesh=topo.mesh, in_specs=P(DATA_AXIS), out_specs=P(DATA_AXIS))
    f(jnp.arange(8.0))
    assert "all_reduce" in logger.comms_dict
    logger.configure(enabled=False)


def test_object_collectives_single_process():
    """Host control-plane object collectives (reference all_gather_object /
    broadcast_object_list); single-process path returns inputs."""
    from deepspeed_tpu.comm import comm

    objs = [{"a": 1}, "two"]
    assert comm.broadcast_object_list(objs) == objs
    assert comm.broadcast_object_list(objs) is not objs  # copy, like torch
    assert comm.all_gather_object({"rank": 0}) == [{"rank": 0}]


def test_p2p_send_recv_edge(devices8):
    """send/recv SPMD pair: src rank's value lands on dst, zeros elsewhere."""
    from jax.sharding import PartitionSpec as P

    from deepspeed_tpu.comm import comm
    from deepspeed_tpu.parallel.mesh import MeshConfig, initialize_topology

    topo = initialize_topology(MeshConfig(pipe=8, data=1), devices8)

    def body(x):
        return comm.send(x, src=2, dst=5, axis="pipe")

    x = jnp.arange(8, dtype=jnp.float32).reshape(8, 1) + 1.0  # rank r holds r+1
    fn = shard_map(body, mesh=topo.mesh, in_specs=P("pipe", None),
                   out_specs=P("pipe", None), check_vma=False)
    out = np.asarray(fn(x)).ravel()
    assert out[5] == 3.0, out  # src rank 2 held value 3.0
    assert out[2] == 0.0 and out[0] == 0.0


def test_monitored_barrier_single_process():
    from deepspeed_tpu.comm import comm

    comm.monitored_barrier("t")  # no-op single host
    comm.monitored_barrier("t")  # reentrant under the same name


def test_monitored_barrier_deferred_stamp_retirement(monkeypatch):
    """KV-fallback barrier: each round rnd retires the process's own stamp
    from round rnd - _MB_RETIRE_LAG at ENTRY (deleting at exit would race
    slower peers into misreporting THIS process as missing); coordinator
    memory stays bounded across timeout/retry loops (advisor r3)."""
    import deepspeed_tpu.comm.comm as C

    store = {}

    class FakeClient:
        # no wait_at_barrier attr -> the KV-store fallback path
        def key_value_set(self, k, v):
            store[k] = v

        def blocking_key_value_get(self, k, timeout_ms):
            if k in store:
                return store[k]
            raise RuntimeError("DEADLINE_EXCEEDED waiting for key")

        def key_value_delete(self, k):
            store.pop(k, None)

    # patch the internals monitored_barrier consults for multi-process mode
    monkeypatch.setattr(C.jax, "process_count", lambda: 2)
    monkeypatch.setattr(C.jax, "process_index", lambda: 0)
    monkeypatch.setattr(
        C.jax._src.distributed.global_state, "client", FakeClient(),
        raising=False)
    C._MB_ROUNDS.pop("ret", None)

    # peer (rank 1) always pre-stamps, so every round succeeds
    lag = C._MB_RETIRE_LAG
    for rnd in range(lag + 3):
        store[f"dstpu_mb/ret/{rnd}/1"] = "peer"
        C.monitored_barrier("ret", timeout_s=1.0)
        own = [k for k in store if k.endswith("/0")]
        # own stamps live for at most _MB_RETIRE_LAG rounds
        assert len(own) <= lag, (rnd, sorted(own))
    # the oldest own stamps were retired
    assert "dstpu_mb/ret/0/0" not in store
    assert f"dstpu_mb/ret/{lag + 2}/0" in store
