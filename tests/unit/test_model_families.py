"""Model family tests: mistral/qwen/phi/opt/falcon (reference:
inference/v2/model_implementations/*, module_inject/containers/*)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import (bloom_model, falcon_model,
                                  gpt_neox_model, mistral_model, opt_model,
                                  phi_model, qwen_model)

SEQ = 32
FAMILIES = [mistral_model, qwen_model, phi_model, opt_model,
            falcon_model, bloom_model, gpt_neox_model]


def _batch(vocab, seed=0, bs=2):
    rng = np.random.RandomState(seed)
    return {"input_ids": jnp.asarray(
        rng.randint(0, vocab, (1, bs, SEQ)), jnp.int32)}


@pytest.mark.parametrize("family", FAMILIES, ids=lambda f: f.__name__)
def test_family_trains(family):
    model = family("tiny", max_seq_len=SEQ)
    engine, *_ = deepspeed_tpu.initialize(
        model=model,
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "AdamW", "params": {"lr": 5e-3}},
                "zero_optimization": {"stage": 1}})
    b = _batch(model.config.vocab_size)
    losses = [float(engine.train_batch(b)) for _ in range(8)]
    assert losses[-1] < losses[0], losses


def test_family_structure_flags():
    assert qwen_model("tiny").config.qkv_bias
    assert not qwen_model("tiny").config.use_bias
    assert phi_model("tiny").config.parallel_block
    assert phi_model("tiny").config.rotary_pct == 0.4
    assert opt_model("tiny").config.activation == "relu"
    assert falcon_model("tiny").config.kv_heads == 1  # multi-query
    assert mistral_model("tiny").config.kv_heads == 2  # GQA


@pytest.mark.parametrize("family", [phi_model, falcon_model, qwen_model,
                                    gpt_neox_model],
                         ids=lambda f: f.__name__)
def test_family_paged_inference_matches_dense(family):
    """The paged (inference v2) path must agree with the dense cached
    decode for the structural variants (parallel block, partial rotary,
    qkv bias, multi-query)."""
    from deepspeed_tpu.inference.v2 import (InferenceEngineV2,
                                            RaggedInferenceConfig,
                                            RaggedRequest)
    from tests.unit.test_inference_v2 import _dense_greedy

    model = family("tiny", max_seq_len=256)
    params = model.init_params(jax.random.PRNGKey(0))
    prompt = list(np.random.RandomState(3).randint(0, model.config.vocab_size, 11))
    want = _dense_greedy(model, params, prompt, 6)
    eng = InferenceEngineV2(model, RaggedInferenceConfig(
        dtype="fp32", page_size=8, num_pages=32, max_seqs=2,
        max_pages_per_seq=8), params=params)
    got = eng.generate_all([RaggedRequest(prompt_ids=prompt, max_new_tokens=6)])
    assert got[0] == want


def test_partial_rotary_only_rotates_prefix():
    from deepspeed_tpu.models.transformer import _rope

    x = jnp.asarray(np.random.RandomState(0).randn(1, 4, 2, 8), jnp.float32)
    pos = jnp.broadcast_to(jnp.arange(4), (1, 4))
    full = _rope(x, 10000.0, pos, pct=1.0)
    part = _rope(x, 10000.0, pos, pct=0.5)
    # pass-through tail unchanged
    np.testing.assert_array_equal(np.asarray(part[..., 4:]),
                                  np.asarray(x[..., 4:]))
    assert not np.allclose(np.asarray(part[..., :4]), np.asarray(x[..., :4]))
    assert not np.allclose(np.asarray(full), np.asarray(part))


def test_parallel_block_shares_single_norm():
    """falcon/phi parallel blocks carry ONE shared input layernorm (no
    norm2), matching the real architectures (ADVICE r1 families.py)."""
    import jax

    from deepspeed_tpu.models.families import falcon_model, phi_model

    for fam in (falcon_model, phi_model):
        model = fam("tiny", max_seq_len=64)
        params = model.init_params(jax.random.PRNGKey(0))
        assert "norm2" not in params["layers"], fam.__name__
        loss = model.loss_fn(
            params, {"input_ids": jnp.zeros((2, 16), jnp.int32)}, None)
        assert jnp.isfinite(loss)


def test_alibi_distance_penalty_and_v1_decode():
    """ALiBi (bloom): more distant keys get linearly more negative scores
    per-head; dense cached decode (v1 path) matches the full forward."""
    from deepspeed_tpu.models.transformer import (alibi_slopes,
                                                  forward_with_cache,
                                                  logits_fn,
                                                  transformer_forward)

    s = np.asarray(alibi_slopes(4))
    assert (s > 0).all() and (np.diff(s) < 0).all()  # decreasing, positive
    s8 = np.asarray(alibi_slopes(8))
    np.testing.assert_allclose(s8[0], 2 ** -1.0, rtol=1e-6)

    model = bloom_model("tiny", max_seq_len=64)
    cfg = model.config
    params = model.init_params(jax.random.PRNGKey(0))
    ids = np.random.RandomState(4).randint(0, 256, (2, 12)).astype(np.int32)
    hidden, _ = transformer_forward(cfg, params, jnp.asarray(ids))
    full = np.asarray(logits_fn(cfg, params, hidden), np.float32)

    import dataclasses

    from deepspeed_tpu.models.transformer import init_kv_cache
    cache = init_kv_cache(cfg, 2, 32, jnp.float32)
    step, cache = forward_with_cache(cfg, params, jnp.asarray(ids), cache,
                                     jnp.zeros((2,), jnp.int32))
    np.testing.assert_allclose(np.asarray(step, np.float32), full,
                               atol=2e-4, rtol=2e-3)


def test_bloom_paged_inference_matches_dense(monkeypatch):
    """ALiBi through the v2 paged engine: whole-prompt and chunked
    prefill, XLA fallback AND Pallas kernels (interpret mode), must all
    reproduce the dense cached decode."""
    from deepspeed_tpu.inference.v2 import (InferenceEngineV2,
                                            RaggedInferenceConfig,
                                            RaggedRequest)
    from tests.unit.test_inference_v2 import _dense_greedy

    model = bloom_model("tiny", max_seq_len=256)
    params = model.init_params(jax.random.PRNGKey(0))
    prompt = list(np.random.RandomState(8).randint(
        0, model.config.vocab_size, 21))
    want = _dense_greedy(model, params, prompt, 6)

    for kernel in ("0", "1"):
        monkeypatch.setenv("DSTPU_PAGED_KERNEL", kernel)
        # quant rides along so the kernel's alibi+int8 operand ordering
        # (slopes popped from *rest before the scales) stays covered
        for chunk, quant in ((0, False), (16, False), (0, True), (16, True)):
            eng = InferenceEngineV2(model, RaggedInferenceConfig(
                dtype="fp32", page_size=8, num_pages=32, max_seqs=2,
                max_pages_per_seq=8, prefill_chunk=chunk,
                kv_quant=quant), params=params)
            got = eng.generate_all(
                [RaggedRequest(prompt_ids=prompt, max_new_tokens=6)])
            assert got[0] == want, (kernel, chunk, quant, got[0], want)
