"""ZeRO++ tests: qwZ / qgZ / hpZ (reference zero++ — partition_parameters.py
quantized allgather, coalesced_collectives.all_to_all_quant_reduce,
engine.py:1101-1113 hpz keys).

The wire format is asserted from the compiled HLO: the collective ops that
move weights/gradients must carry s8 operands.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.llama import llama_model
from deepspeed_tpu.parallel.mesh import MeshConfig, initialize_topology
from deepspeed_tpu.runtime.zero.zeropp import (dequantize_lastdim,
                                               quantize_lastdim)

pytestmark = pytest.mark.slow  # multi-minute integration tier

SEQ = 16
VOCAB = 64


def _model(**over):
    return llama_model("tiny", max_seq_len=SEQ, vocab_size=VOCAB,
                       n_layers=2, attn_impl="xla", **over)


def _engine(zero_extra, mesh, model=None, lr=5e-3):
    cfg = {"train_micro_batch_size_per_gpu": 4,
           "gradient_accumulation_steps": 1,
           "optimizer": {"type": "Adam", "params": {"lr": lr}},
           "zero_optimization": dict(zero_extra),
           "mesh": mesh}
    return deepspeed_tpu.initialize(
        model=model or _model(), config=cfg,
        topology=deepspeed_tpu.get_topology())[0]


def _ids(n, seed=0):
    return jnp.asarray(np.random.RandomState(seed).randint(
        0, VOCAB, (1, n, SEQ)).astype(np.int32))


def _losses(engine, steps=6, bs=8):
    out = []
    for i in range(steps):
        out.append(float(engine.train_batch({"input_ids": _ids(bs, seed=i % 3)})))
    return out


def _train_hlo(engine, bs=8):
    batch = {"input_ids": _ids(bs)}
    with engine.topology.mesh:
        return engine._train_batch.lower(
            engine.state, batch, jax.random.PRNGKey(0)
        ).compile().as_text()


def test_quantize_lastdim_roundtrip():
    rng = np.random.RandomState(0)
    for shape in [(4, 256), (3, 130), (2, 5, 128), (7,)]:
        x = rng.randn(*shape).astype(np.float32) * 3.0
        q, s, d = quantize_lastdim(jnp.asarray(x))
        assert q.dtype == jnp.int8
        y = np.asarray(dequantize_lastdim(q, s, d, jnp.float32))
        assert y.shape == x.shape
        # blockwise symmetric int8: max error <= scale/2 = absmax/254
        err = np.abs(y - x).max()
        assert err <= np.abs(x).max() / 254 + 1e-6


def test_qwz_int8_on_the_wire_and_trains(devices8):
    """stage-3 + qwZ: weight all-gathers move s8 codes; loss tracks fp."""
    initialize_topology(MeshConfig(data=4, model=2), jax.devices()[:8])
    e_fp = _engine({"stage": 3}, {"data": 4, "model": 2})
    initialize_topology(MeshConfig(data=4, model=2), jax.devices()[:8])
    e_q = _engine({"stage": 3, "zero_quantized_weights": True},
                  {"data": 4, "model": 2})
    assert e_q._qwz is True
    # the engine flag is NOT a sticky mutation of the shared model config
    assert e_q.model.config.qwz is False

    hlo = _train_hlo(e_q)
    ag = [ln for ln in hlo.splitlines() if "all-gather" in ln]
    assert any("s8[" in ln for ln in ag), "no int8 all-gather in HLO"

    lf = _losses(e_fp)
    lq = _losses(e_q)
    assert np.isfinite(lq).all()
    # same data order, int8-blockwise weight noise only: trajectories agree
    # to a few percent and both go down
    for a, b in zip(lf, lq):
        assert abs(a - b) / max(abs(a), 1e-6) < 0.05, (lf, lq)
    assert lq[-1] < lq[0]


def test_qwz_weights_receive_gradients(devices8):
    """jax.grad through the qwZ gather equals the fp gradient up to the
    forward quantization noise — NOT the 1/128-sparse garbage a plain
    round() would give (code-review r3 finding)."""
    initialize_topology(MeshConfig(data=4, model=2), jax.devices()[:8])
    e_q = _engine({"stage": 3, "zero_quantized_weights": True},
                  {"data": 4, "model": 2})
    batch = {"input_ids": _ids(8, seed=1)[0]}  # [B, S] (no gas dim)

    def loss_q(params):
        return e_q._model_loss(params, batch, None)

    with e_q.topology.mesh:
        p32 = jax.tree_util.tree_map(lambda x: x.astype(jnp.float32),
                                     e_q.state.params)
        g_q = jax.jit(jax.grad(loss_q))(p32)
        e_q._qwz = False  # same engine, quantization off -> fp reference
        g_fp = jax.jit(jax.grad(loss_q))(p32)
    wq_q = np.asarray(g_q["layers"]["attn"]["wq"], np.float32)
    wq_f = np.asarray(g_fp["layers"]["attn"]["wq"], np.float32)
    nz = float((np.abs(wq_q) > 0).mean())
    assert nz > 0.5, f"qwZ gradient is {nz:.1%} nonzero — STE broken"
    cos = float((wq_q * wq_f).sum() /
                (np.linalg.norm(wq_q) * np.linalg.norm(wq_f) + 1e-12))
    assert cos > 0.99, f"qwZ grad diverges from fp grad (cos={cos:.3f})"


def test_qgz_int8_all_to_all_and_matches_fp(devices8):
    """stage-2 + qgZ: gradient reduction rides an s8 all-to-all; loss
    trajectory within tolerance of the fp reduce."""
    initialize_topology(MeshConfig(data=8), jax.devices()[:8])
    e_fp = _engine({"stage": 2}, {"data": 8})
    initialize_topology(MeshConfig(data=8), jax.devices()[:8])
    e_q = _engine({"stage": 2, "zero_quantized_gradients": True}, {"data": 8})
    assert e_q._qgz is True

    hlo = _train_hlo(e_q)
    a2a = [ln for ln in hlo.splitlines() if "all-to-all" in ln]
    assert any("s8[" in ln for ln in a2a), "no int8 all-to-all in HLO"
    # the scattered partition IS the result: data-sharded grad leaves must
    # not be gathered back after the reduce (reference
    # all_to_all_quant_reduce returns the partition; VERDICT r3 weak #5 —
    # hop 2 doubled the wire bytes).  Any s8 all-gather would be that hop.
    ag = [ln for ln in hlo.splitlines() if "all-gather" in ln]
    assert not any("s8[" in ln for ln in ag), (
        "qgZ hop-2 int8 all-gather still present:\n" +
        "\n".join(ln for ln in ag if "s8[" in ln))

    lf = _losses(e_fp)
    lq = _losses(e_q)
    assert np.isfinite(lq).all()
    for a, b in zip(lf, lq):
        assert abs(a - b) / max(abs(a), 1e-6) < 0.05, (lf, lq)
    assert lq[-1] < lq[0]


def test_qgz_loss_value_matches_unchunked(devices8):
    """The vmap-chunked loss equals the global-mean loss (equal chunks)."""
    initialize_topology(MeshConfig(data=4), jax.devices()[:4])
    e_fp = _engine({"stage": 1}, {"data": 4})
    initialize_topology(MeshConfig(data=4), jax.devices()[:4])
    e_q = _engine({"stage": 1, "zero_quantized_gradients": True}, {"data": 4})
    b = {"input_ids": _ids(8, seed=42)}
    l_fp = float(e_fp.train_batch(b))
    l_q = float(e_q.train_batch(b))
    # first step: identical params, loss computed before any update noise
    np.testing.assert_allclose(l_q, l_fp, rtol=1e-5)


def test_hpz_secondary_partition_shardings(devices8):
    """hpZ: master/opt shard over the FULL repl x data group; stage-3 live
    param gathers ride only the small data axis."""
    initialize_topology(MeshConfig(repl=2, data=2, model=2), jax.devices()[:8])
    e = _engine({"stage": 3, "zero_hpz_partition_size": 2},
                {"repl": 2, "data": 2, "model": 2})
    plan = e.zero_plan
    m_spec = plan.master_spec("layers/attn/wq", (2, 64, 64))
    p_spec = plan.param_spec("layers/attn/wq", (2, 64, 64))
    m_axes = {a for ent in m_spec if ent for a in
              (ent if isinstance(ent, tuple) else (ent,))}
    p_axes = {a for ent in p_spec if ent for a in
              (ent if isinstance(ent, tuple) else (ent,))}
    assert "repl" in m_axes, m_spec    # optimizer sharded over full dp
    assert "repl" not in p_axes, p_spec  # gathers ride the hpz group only
    assert "data" in p_axes, p_spec
    # trains
    ls = _losses(e, steps=5, bs=8)
    assert np.isfinite(ls).all() and ls[-1] < ls[0]


def test_hpz_mesh_contract_enforced(devices8):
    initialize_topology(MeshConfig(data=8), jax.devices()[:8])
    with pytest.raises(ValueError, match="zero_hpz_partition_size"):
        _engine({"stage": 3, "zero_hpz_partition_size": 2}, {"data": 8})


def _hlo_components(hlo):
    """HLO text -> {computation name: text}."""
    comps, name = {}, None
    for ln in hlo.splitlines():
        m = re.match(r"^(?:ENTRY )?%?([\w\.\-]+) \(.*\{", ln)
        if m:
            name = m.group(1)
            comps[name] = []
        if name:
            comps[name].append(ln)
    return {k: "\n".join(v) for k, v in comps.items()}


def _loop_reachable(comps, hlo):
    """Computations transitively referenced from while-loop bodies
    (async-wrapped / outlined collectives live in called computations)."""
    bodies = set(re.findall(r"body=%([\w\.\-]+)", hlo))
    reachable = set(bodies)
    frontier = list(bodies)
    while frontier:
        c = frontier.pop()
        for other in comps:
            # full-token match: "%name" must not be followed by more name
            # chars, or "%body" would falsely match a "%body.1" reference
            if other not in reachable and re.search(
                    rf"%{re.escape(other)}(?![\w.\-])", comps.get(c, "")):
                reachable.add(other)
                frontier.append(other)
    return bodies, reachable


_DT_BYTES = {"bf16": 2, "f16": 2, "f32": 4, "f64": 8, "s8": 1, "u8": 1,
             "s32": 4}


def _gather_bytes(text):
    """Static all-gather output bytes in HLO text.  Sync form: the output
    type precedes the op; async (all-gather-start) form: the output is an
    (operands..., results...) tuple — count only the result half (each
    result is N-times its operand for an N-way gather)."""
    def shapes_in(t):
        return [int(np.prod([int(d) for d in dims.split(",") if d] or [1]))
                * _DT_BYTES.get(dt, 4)
                for dt, dims in re.findall(r"([a-z][a-z0-9]*)\[([\d,]*)\]", t)]

    total = 0
    for ln in text.splitlines():
        if re.search(r"= .*? all-gather\(", ln):
            total += sum(shapes_in(ln.split(" all-gather")[0]))
        elif re.search(r"= .*? all-gather-start\(", ln):
            ss = shapes_in(ln.split(" all-gather-start")[0])
            total += sum(ss[len(ss) // 2:])
    return total


def test_stage3_gathers_stay_inside_layer_loop(devices8):
    """Stage-3 memory property of the XLA-delegated param coordinator
    (SURVEY §7 hard part #2, VERDICT r3 coverage row 16): the compiled
    train step must gather params PER LAYER inside the scan loops — a
    gather hoisted to top level would materialize every layer's params at
    once, the exact failure the reference's prefetch coordinator exists to
    prevent.  (Overlap timing needs hardware; the memory property is
    structural and checkable here.)

    gas=1 here, so the only while loops ARE the layer scans; gathers are
    classified by REACHABILITY from the loop bodies.  Hoisted gathers are
    judged by BYTES against a per-layer budget, not by count: GSPMD
    legitimately emits small activation-sized top-level gathers (e.g. the
    embedding-grad scatter-add's cotangent gather), and whether it does
    varies with its cost model — an exact-zero assert made this test
    compilation-order-sensitive (failed in isolation, passed in suite
    order at PR 11 HEAD).  The failure this test exists to catch — the
    full layer stack's params gathered outside the loop — is orders of
    magnitude over the budget either way."""
    initialize_topology(MeshConfig(data=8), jax.devices()[:8])
    e = _engine({"stage": 3}, {"data": 8})
    hlo = _train_hlo(e)
    comps = _hlo_components(hlo)
    bodies, reachable = _loop_reachable(comps, hlo)
    assert bodies, "no scan loops in the compiled step?"
    gather_comps = {k for k, v in comps.items() if "all-gather" in v}
    assert gather_comps & reachable, \
        "stage-3 step compiled with no per-layer gathers"
    hoisted = sum(_gather_bytes(comps[c]) for c in gather_comps - reachable)
    layers = e.state.params["layers"]
    layer_bytes = sum(l.size * 2 // l.shape[0]
                      for l in jax.tree_util.tree_leaves(layers))
    assert hoisted <= 3 * layer_bytes, (
        f"hoisted all-gather bytes {hoisted} exceed the ~one-layer budget "
        f"({layer_bytes} per layer x3) — stage-3 is materializing the "
        "layer stack's params outside the loop")


def test_stage3_gather_bytes_bounded(devices8):
    """Wire-volume change-detector for stage-3: the compiled step's
    all-gather output bytes, counted STATICALLY (once per HLO occurrence,
    on this fixture's fixed 2-layer model), stay near the fwd+bwd ideal.
    This is not exact wire accounting — loop-body gathers execute once per
    scan trip — but a remat misconfiguration, duplicated gather sites, or
    an accidental fp32 gather all move the static ratio far outside the
    measured 2.54x (bound 0.5..3.5).  Tuple-typed outputs (XLA's
    all-gather combiner) are summed element-wise."""
    initialize_topology(MeshConfig(data=8), jax.devices()[:8])
    e = _engine({"stage": 3}, {"data": 8})
    hlo = _train_hlo(e)
    total = _gather_bytes(hlo)
    pbytes = sum(l.size * 2 for l in jax.tree_util.tree_leaves(e.state.params))
    ratio = total / pbytes
    assert 0.5 < ratio < 3.5, (
        f"stage-3 gather bytes {total} vs param bytes {pbytes} "
        f"(ratio {ratio:.2f}) — expected ~2.5x static on this fixture")


def test_stage3_manual_prefetch_trains_and_keeps_loop_gathers(devices8):
    """zero3_param_prefetch (VERDICT r4 item 2 / SURVEY §7 hard part #2):
    the double-buffered gather path must (a) change the compiled program
    (the knob actually reaches the scan), (b) keep every all-gather inside
    the layer loops (memory property unchanged), and (c) train to the same
    losses as the XLA-delegated path — it is a schedule change, not a math
    change."""
    model = llama_model("tiny", max_seq_len=SEQ, vocab_size=VOCAB,
                        n_layers=4, attn_impl="xla")
    initialize_topology(MeshConfig(data=8), jax.devices()[:8])
    e_plain = _engine({"stage": 3}, {"data": 8}, model=model)
    hlo_plain = _train_hlo(e_plain)
    l_plain = _losses(e_plain, steps=5)

    model = llama_model("tiny", max_seq_len=SEQ, vocab_size=VOCAB,
                        n_layers=4, attn_impl="xla")
    initialize_topology(MeshConfig(data=8), jax.devices()[:8])
    e_pf = _engine({"stage": 3, "zero3_param_prefetch": True}, {"data": 8},
                   model=model)
    assert e_pf._zero3_prefetch
    hlo_pf = _train_hlo(e_pf)
    l_pf = _losses(e_pf, steps=5)

    assert hlo_pf != hlo_plain, "prefetch knob produced an identical program"
    np.testing.assert_allclose(l_pf, l_plain, rtol=2e-2)

    # the memory property of test_stage3_gathers_stay_inside_layer_loop,
    # on the prefetch program
    comps = _hlo_components(hlo_pf)
    _, reachable = _loop_reachable(comps, hlo_pf)
    gather_comps = {k for k, v in comps.items() if "all-gather" in v}
    assert gather_comps & reachable, "prefetch program lost its loop gathers"
    # outside the loops nothing bigger than ~one layer slice may be
    # gathered (unroll keeps every gather in the body; the bound gives
    # slack for partial-unroll remainders without letting the full stack
    # leak out — the failure mode of the carry-based design this replaced)
    hoisted = sum(_gather_bytes(comps[c]) for c in gather_comps - reachable)
    layers = e_pf.state.params["layers"]
    layer_bytes = sum(l.size * 2 // l.shape[0]
                      for l in jax.tree_util.tree_leaves(layers))
    assert hoisted <= 3 * layer_bytes, (
        f"hoisted gather bytes {hoisted} exceed the layer-0 seed budget "
        f"({layer_bytes} per layer) — the full stack leaked out of the loop")
