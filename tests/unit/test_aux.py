"""Aux subsystem tests: launcher, elasticity, autotuner, activation
checkpointing, eigenvalue (reference tests/unit/{launcher,elasticity,
autotuning})."""

import numpy as np
import pytest

from deepspeed_tpu.autotuning.autotuner import Autotuner
from deepspeed_tpu.elasticity.elasticity import (compute_elastic_config,
                                                 ensure_immutable_elastic_config,
                                                 get_compatible_gpus)
from deepspeed_tpu.launcher.runner import (build_launch_commands, filter_hosts,
                                           parse_hostfile)


# ------------------------------ launcher -----------------------------------
def test_parse_hostfile():
    hosts = parse_hostfile("worker-1 slots=4\nworker-2 slots=8\n# comment\n",
                           is_text=True)
    assert hosts == {"worker-1": 4, "worker-2": 8}


def test_parse_hostfile_duplicate_raises():
    with pytest.raises(ValueError):
        parse_hostfile("a slots=1\na slots=2", is_text=True)


def test_filter_include_exclude():
    hosts = parse_hostfile("a slots=1\nb slots=1\nc slots=1", is_text=True)
    assert list(filter_hosts(hosts, include="a@c")) == ["a", "c"]
    assert list(filter_hosts(hosts, exclude="b")) == ["a", "c"]
    with pytest.raises(ValueError):
        filter_hosts(hosts, include="zzz")
    with pytest.raises(ValueError):
        filter_hosts(hosts, exclude="a@b@c")


def test_build_launch_commands_env():
    hosts = parse_hostfile("h1 slots=4\nh2 slots=4", is_text=True)
    cmds = build_launch_commands(hosts, "train.py", ["--foo", "1"])
    assert len(cmds) == 2
    joined = " ".join(cmds[0])
    assert "DSTPU_COORDINATOR=h1:29500" in joined
    assert "DSTPU_NUM_PROCESSES=2" in joined
    assert "DSTPU_PROCESS_ID=0" in joined
    assert "DSTPU_PROCESS_ID=1" in " ".join(cmds[1])
    assert cmds[0][0] == "ssh"


def test_single_host_local_command():
    cmds = build_launch_commands({"localhost": 8}, "t.py", [])
    assert cmds[0][0] == "bash"


# ------------------------------ elasticity ---------------------------------
def test_elastic_batch_divisibility():
    batch, gpus = get_compatible_gpus([2, 4], max_train_batch_size=64,
                                      min_gpus=1, max_gpus=64)
    assert batch <= 64
    for g in gpus:
        assert batch % g == 0


def test_compute_elastic_config_resolves_micro_batch():
    cfg = {"elasticity": {"enabled": True, "max_train_batch_size": 128,
                          "micro_batch_sizes": [2, 4], "min_gpus": 1,
                          "max_gpus": 32}}
    batch, gpus, info = compute_elastic_config(cfg, world_size=gpus_pick(cfg))
    assert info["micro_batch_per_gpu"] in (2, 4)
    assert batch == info["micro_batch_per_gpu"] * \
        info["gradient_accumulation_steps"] * gpus_pick(cfg)


def gpus_pick(cfg):
    batch, gpus, _ = compute_elastic_config(cfg)
    return gpus[len(gpus) // 2]


def test_elastic_disabled_raises():
    with pytest.raises(ValueError):
        compute_elastic_config({"elasticity": {"enabled": False}})


def test_elastic_immutability():
    a = {"elasticity": {"enabled": True, "max_train_batch_size": 100}}
    b = {"elasticity": {"enabled": True, "max_train_batch_size": 200}}
    ensure_immutable_elastic_config(a, a)
    with pytest.raises(ValueError):
        ensure_immutable_elastic_config(a, b)


# ------------------------------ autotuner ----------------------------------
def test_autotuner_picks_working_config():
    from tests.unit.simple_model import random_batch, simple_mlp_spec

    tuner = Autotuner(
        model_factory=simple_mlp_spec,
        base_config={"optimizer": {"type": "Adam", "params": {"lr": 1e-3}}},
        batch_factory=lambda mb: random_batch(batch_size=mb * 8, gas=1),
        tuning_space={"zero_stage": [0, 1], "micro_batch": [2, 4]},
        steps_per_trial=1)
    result = tuner.tune()
    assert result["best"] is not None
    assert result["throughput"] > 0
    assert len(result["trials"]) == 4


# -------------------------- activation checkpointing ------------------------
def test_checkpoint_module_api():
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.runtime.activation_checkpointing import checkpointing

    checkpointing.configure(policy="nothing_saveable")

    def f(x):
        return jnp.sum(jnp.tanh(x @ x.T))

    x = jnp.ones((8, 8))
    out = checkpointing.checkpoint(f, x)
    g = jax.grad(lambda x: checkpointing.checkpoint(f, x))(x)
    assert np.isfinite(float(out))
    assert g.shape == x.shape


# ------------------------- multinode runners --------------------------------
def test_multinode_runner_commands():
    """Command construction for every backend (reference
    tests/unit/launcher/test_multinode_runner.py over
    multinode_runner.py:55-411)."""
    from collections import OrderedDict

    from deepspeed_tpu.launcher.multinode_runner import RUNNERS, get_runner

    hosts = OrderedDict([("worker-0", 1), ("worker-1", 1)])
    for name, cls in RUNNERS.items():
        r = get_runner(name, hosts, master_port=1234,
                       export_env={"FOO": "bar"})
        cmd = r.get_cmd("train.py", ["--x", "1"])
        joined = " ".join(cmd)
        assert cmd[0] == cls.launcher_binary, (name, cmd)
        assert "train.py" in joined and "--x" in joined, (name, cmd)
        # every backend must deliver coordinator + world size
        assert "DSTPU_COORDINATOR" in joined, (name, cmd)
        assert "worker-0:1234" in joined, (name, cmd)
        assert "DSTPU_NUM_PROCESSES" in joined and "2" in joined, (name, cmd)
        assert "FOO" in joined, (name, cmd)

    # backend-specific shapes
    slurm = get_runner("slurm", hosts).get_cmd("t.py", [])
    assert "--ntasks" in slurm and "worker-0,worker-1" in " ".join(slurm)
    ompi = get_runner("openmpi", hosts).get_cmd("t.py", [])
    assert "-n" in ompi and "worker-0:1,worker-1:1" in " ".join(ompi)
    pdsh = get_runner("pdsh", hosts).get_cmd("t.py", [])
    assert "DSTPU_PROCESS_ID=%n" in " ".join(pdsh)  # pdsh rank substitution

    with pytest.raises(ValueError, match="unknown launcher"):
        get_runner("nope", hosts)


def test_comm_env_rank_discovery(monkeypatch):
    """comm.init_distributed resolves rank/size from MPI/SLURM env when
    DSTPU_* is absent (the runners' rank contract)."""
    from deepspeed_tpu.comm import comm as C

    captured = {}

    def fake_init(coordinator_address, num_processes, process_id):
        captured.update(addr=coordinator_address, n=num_processes,
                        pid=process_id)

    monkeypatch.setattr(C, "_INITIALIZED", False)
    monkeypatch.setattr(C.jax.distributed, "initialize", fake_init)
    monkeypatch.setenv("DSTPU_COORDINATOR", "w0:29500")
    monkeypatch.delenv("DSTPU_NUM_PROCESSES", raising=False)
    monkeypatch.delenv("DSTPU_PROCESS_ID", raising=False)
    monkeypatch.setenv("SLURM_NTASKS", "4")
    monkeypatch.setenv("SLURM_PROCID", "3")
    C.init_distributed()
    assert captured == {"addr": "w0:29500", "n": 4, "pid": 3}
    monkeypatch.setattr(C, "_INITIALIZED", True)  # leave global as the suite expects


def test_autotuner_model_based_mode(devices8):
    """Model-based tuning (reference ModelBasedTuner): seeds + cost-model
    proposals find the grid's best without exhausting it."""
    import deepspeed_tpu
    from deepspeed_tpu.autotuning.autotuner import Autotuner
    from tests.unit.simple_model import random_batch, simple_mlp_spec

    tuner = Autotuner(
        model_factory=simple_mlp_spec,
        base_config={"optimizer": {"type": "Adam", "params": {"lr": 1e-3}}},
        batch_factory=lambda bs: random_batch(batch_size=bs * 8, gas=1),
        tuning_space={"zero_stage": [0, 1, 2], "micro_batch": [1, 2]},
        steps_per_trial=2, max_trials=5, mode="model")
    out = tuner.tune()
    assert out["best"] in [{"zero_stage": s, "micro_batch": m}
                           for s in (0, 1, 2) for m in (1, 2)]
    ran = [r for r in tuner.results if not r.get("pruned")]
    assert 3 <= len(ran) <= 5  # seeds + proposals, under budget
    assert out["throughput"] > 0


def test_autotuner_memory_pruning(monkeypatch, devices8):
    """Candidates whose analytical state floor exceeds HBM are skipped
    without compiling (reference fast-mode memory estimators)."""
    from deepspeed_tpu.autotuning.autotuner import Autotuner
    from tests.unit.simple_model import random_batch, simple_mlp_spec

    tuner = Autotuner(
        model_factory=simple_mlp_spec,
        base_config={"optimizer": {"type": "Adam", "params": {"lr": 1e-3}}},
        batch_factory=lambda bs: random_batch(batch_size=bs * 8, gas=1),
        tuning_space={"zero_stage": [0, 1], "micro_batch": [1]},
        steps_per_trial=1, mode="grid")
    # pretend the device has 1KB of HBM: every stage-0 candidate's floor
    # exceeds it; sharded stages divide by the mesh and may also exceed
    monkeypatch.setattr(tuner, "_device_memory", lambda: 1024)
    with pytest.raises(RuntimeError, match="all autotuning trials failed"):
        tuner.tune()
    assert all(r.get("pruned") for r in tuner.results), tuner.results


def test_set_random_seed():
    """Reference runtime/utils.py set_random_seed: host RNGs seeded, device
    key returned."""
    import random

    import numpy as np

    from deepspeed_tpu.runtime.utils import set_random_seed

    k1 = set_random_seed(1234)
    a = (random.random(), np.random.rand())
    k2 = set_random_seed(1234)
    b = (random.random(), np.random.rand())
    assert a == b
    np.testing.assert_array_equal(np.asarray(k1), np.asarray(k2))


# -- parallel experiment scheduler (reference autotuning/scheduler.py:32) ---
def _tracking_runner(delay=0.05, tputs=None):
    """Mock runner that records concurrency and returns canned metrics."""
    import threading as _th
    import time as _t

    lock = _th.Lock()
    state = {"cur": 0, "peak": 0, "calls": []}

    def runner(exp, res):
        with lock:
            state["cur"] += 1
            state["peak"] = max(state["peak"], state["cur"])
            state["calls"].append(exp["name"])
        _t.sleep(delay)
        with lock:
            state["cur"] -= 1
        if tputs is None:
            return 100.0
        v = tputs.get(exp["name"], None)
        if isinstance(v, Exception):
            raise v
        return v

    return runner, state


def test_scheduler_respects_slots_and_max_parallel():
    """Concurrent trials over mock hosts: concurrency reaches the cap but
    never exceeds min(slot capacity, max_parallel)."""
    from deepspeed_tpu.autotuning.scheduler import Node, ResourceManager

    runner, state = _tracking_runner()
    rm = ResourceManager([Node("h0", 2), Node("h1", 2)], runner,
                         slots_per_exp=1, max_parallel=3)
    assert rm.parallel_peak() == 3
    rm.schedule_experiments([{"name": f"e{i}", "config": {"i": i}}
                             for i in range(10)])
    finished = rm.run()
    assert len(finished) == 10
    assert state["peak"] <= 3, state
    assert state["peak"] >= 2, f"never ran concurrently: {state}"
    # all slots restored
    assert all(n.free == n.slots for n in rm.nodes)


def test_scheduler_multi_slot_experiments_fit_per_node():
    """An experiment never spans nodes: 2-slot trials on 2-slot nodes run
    one per node."""
    from deepspeed_tpu.autotuning.scheduler import Node, ResourceManager

    runner, state = _tracking_runner()
    rm = ResourceManager([Node("h0", 2), Node("h1", 2)], runner,
                         slots_per_exp=2)
    rm.schedule_experiments([{"name": f"e{i}"} for i in range(6)])
    rm.run()
    assert state["peak"] <= 2
    assert all(n.free == n.slots for n in rm.nodes)


def test_scheduler_dedup_failures_and_early_stop():
    from deepspeed_tpu.autotuning.scheduler import Node, ResourceManager

    # dedup: the same experiment name scheduled twice runs once
    runner, state = _tracking_runner(delay=0.0)
    rm = ResourceManager([Node("h0", 1)], runner)
    rm.schedule_experiments([{"name": "same"}, {"name": "same"}])
    assert len(rm.run()) == 1

    # failures recorded, scheduler survives
    runner, _ = _tracking_runner(
        delay=0.0, tputs={"ok": 5.0, "bad": RuntimeError("boom")})
    rm = ResourceManager([Node("h0", 1)], runner)
    rm.schedule_experiments([{"name": "bad"}, {"name": "ok"}])
    recs = {r["name"]: r for r in rm.run()}
    assert recs["bad"]["throughput"] is None and "boom" in recs["bad"]["error"]
    assert recs["ok"]["throughput"] == 5.0

    # early stop: monotonically worse results drop the queued tail
    tputs = {f"e{i}": float(100 - i) for i in range(12)}
    runner, _ = _tracking_runner(delay=0.0, tputs=tputs)
    rm = ResourceManager([Node("h0", 1)], runner)
    rm.schedule_experiments([{"name": f"e{i}"} for i in range(12)])
    finished = rm.run(early_stop_patience=3)
    assert len(finished) < 12, "early stop never dropped the queue"


def test_autotuner_tune_parallel_picks_best(devices8):
    """tune_parallel over mock hosts: grid candidates dispatched through
    the ResourceManager; best survives; model mode refuses (sequential)."""
    from deepspeed_tpu.autotuning.autotuner import Autotuner
    from deepspeed_tpu.autotuning.scheduler import Node
    from tests.unit.simple_model import random_batch, simple_mlp_spec

    def make(mode="grid"):
        return Autotuner(
            model_factory=simple_mlp_spec,
            base_config={"optimizer": {"type": "Adam", "params": {"lr": 1e-3}}},
            batch_factory=lambda bs: random_batch(batch_size=bs * 8, gas=1),
            tuning_space={"zero_stage": [0, 1], "micro_batch": [1, 2, 4]},
            mode=mode)

    def runner(exp, res):
        c = exp["cand"]
        return 100.0 * c["micro_batch"] - 10.0 * c["zero_stage"]

    out = make().tune_parallel(runner, nodes=[Node("h0", 2), Node("h1", 2)],
                               max_parallel=4)
    assert out["best"] == {"zero_stage": 0, "micro_batch": 4}
    assert out["config"]["train_micro_batch_size_per_gpu"] == 4

    with pytest.raises(ValueError, match="sequential"):
        make("model").tune_parallel(runner)


def test_subprocess_trial_runner(tmp_path):
    """Real out-of-process trial: config handed via JSON file, metrics read
    from the last JSON stdout line (reference user_script contract)."""
    from deepspeed_tpu.autotuning.scheduler import (Node, Reservation,
                                                    SubprocessTrialRunner)

    script = tmp_path / "user_script.py"
    script.write_text(
        "import argparse, json, os\n"
        "p = argparse.ArgumentParser(); p.add_argument('--exp_config')\n"
        "a = p.parse_args()\n"
        "cfg = json.load(open(a.exp_config))\n"
        "print('noise line')\n"
        "print(json.dumps({'throughput': 7.0 * cfg['train_micro_batch_size_per_gpu'],\n"
        "                  'slots': os.environ['DSTPU_TRIAL_SLOTS']}))\n")
    runner = SubprocessTrialRunner(str(script),
                                   results_dir=str(tmp_path / "results"))
    node = Node("localhost", 2)
    node.free -= 1
    tput = runner({"name": "t0",
                   "config": {"train_micro_batch_size_per_gpu": 3}},
                  Reservation(node, 1))
    assert tput == 21.0
    assert (tmp_path / "results" / "t0" / "exp.json").exists()


def test_autotuner_tunes_fused_kernel():
    """fused_kernel rides the tuning space into the trial's optimizer
    params (single-device trials use the Pallas path when True)."""
    from tests.unit.simple_model import random_batch, simple_mlp_spec

    tuner = Autotuner(
        model_factory=simple_mlp_spec,
        base_config={"optimizer": {"type": "FusedAdam",
                                   "params": {"lr": 1e-3}}},
        batch_factory=lambda mb: random_batch(batch_size=mb * 8, gas=1),
        tuning_space={"fused_kernel": [False, True], "micro_batch": [2]},
        steps_per_trial=1)
    cfg_on = tuner._trial_config({"fused_kernel": True, "micro_batch": 2})
    assert cfg_on["optimizer"]["params"]["fused_kernel"] is True
    assert cfg_on["optimizer"]["params"]["lr"] == 1e-3  # params merged
    result = tuner.tune()
    assert result["best"] is not None and len(result["trials"]) == 2


def test_trial_runner_cross_host_launcher(tmp_path):
    """Cross-host dispatch (reference ResourceManager + pdsh/ssh launcher,
    autotuning/scheduler.py:32): a trial reserved on a remote node is
    launched through the launcher template with the trial env crossing as
    env(1) tokens; local nodes bypass the launcher."""
    import os
    import sys

    from deepspeed_tpu.autotuning.scheduler import (Node, Reservation,
                                                    SubprocessTrialRunner)

    fake_ssh = tmp_path / "fake_ssh.py"
    # mirror REAL ssh semantics: the trailing args are space-joined into
    # ONE string interpreted by the remote shell — this is what catches
    # unquoted paths/metachars (json-derived exp names contain both)
    fake_ssh.write_text(
        "import os, sys\n"
        "open(os.environ['FAKE_SSH_LOG'], 'a').write(sys.argv[1] + '\\n')\n"
        "os.execvp('/bin/sh', ['/bin/sh', '-c', ' '.join(sys.argv[2:])])\n")
    trial = tmp_path / "trial.py"
    trial.write_text(
        "import json, os, sys\n"
        "cfg = json.load(open(sys.argv[sys.argv.index('--exp_config') + 1]))\n"
        "print(json.dumps({'throughput': cfg['bs'] * 10.0,"
        " 'host': os.environ['DSTPU_TRIAL_HOST'],"
        " 'slots': os.environ['DSTPU_TRIAL_SLOTS']}))\n")
    log = tmp_path / "hosts.log"
    os.environ["FAKE_SSH_LOG"] = str(log)
    try:
        runner = SubprocessTrialRunner(
            str(trial), results_dir=str(tmp_path / "results"),
            launcher=[sys.executable, str(fake_ssh), "{host}"])
        # a default exp name is json.dumps(config): spaces AND quotes must
        # survive the remote shell (the repo quoting contract)
        remote = runner({"name": '{"bs": 4}', "config": {"bs": 4}},
                        Reservation(Node("worker-7", 4), 2))
        assert remote == 40.0
        assert log.read_text().splitlines() == ["worker-7"]
        local = runner({"name": "e2", "config": {"bs": 2}},
                       Reservation(Node("localhost", 4), 1))
        assert local == 20.0
        assert log.read_text().splitlines() == ["worker-7"]  # no new entry
    finally:
        os.environ.pop("FAKE_SSH_LOG", None)
