"""ZeRO-Offload path tests (reference tests/unit/runtime/zero offload tests)."""

import jax
import os
import numpy as np
import pytest

import deepspeed_tpu
from tests.unit.simple_model import random_batch, simple_mlp_spec


def _engine(device="cpu", nvme_path=None, **extra):
    off = {"device": device}
    if nvme_path:
        off["nvme_path"] = nvme_path
    cfg = {
        "train_micro_batch_size_per_gpu": 4,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2, "weight_decay": 0.01}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2, "offload_optimizer": off},
        "gradient_clipping": 1.0,
    }
    cfg.update(extra)
    engine, *_ = deepspeed_tpu.initialize(model=simple_mlp_spec(), config=cfg)
    return engine


def test_offload_cpu_trains():
    engine = _engine()
    assert engine.offload_optimizer is not None
    losses = [float(engine.train_batch(random_batch(batch_size=16, seed=i % 4, gas=1)))
              for i in range(15)]
    assert losses[-1] < losses[0]
    assert engine.get_global_grad_norm() > 0


def test_offload_matches_device_path():
    """Host C++ Adam and the compiled device update converge the same way."""
    e_dev = deepspeed_tpu.initialize(
        model=simple_mlp_spec(),
        config={"train_micro_batch_size_per_gpu": 4,
                "optimizer": {"type": "AdamW",
                              "params": {"lr": 1e-2, "weight_decay": 0.01}},
                "gradient_clipping": 1.0})[0]
    e_off = _engine()
    # bf16 on the offload engine vs fp32 device: compare loss trajectories
    dev_losses, off_losses = [], []
    for i in range(10):
        b = random_batch(batch_size=16, seed=i % 2, gas=1)
        dev_losses.append(float(e_dev.train_batch(b)))
        off_losses.append(float(e_off.train_batch(b)))
    assert abs(dev_losses[-1] - off_losses[-1]) < 0.1 * (1 + dev_losses[-1])


def test_offload_nvme_spills(tmp_path):
    engine = _engine(device="nvme", nvme_path=str(tmp_path / "nvme"))
    for i in range(4):
        engine.train_batch(random_batch(batch_size=8, seed=i, gas=1))
    import os

    spilled = os.listdir(tmp_path / "nvme")
    assert any(f.startswith("m_") for f in spilled)


def test_offload_fp16_contract():
    """Plain offload + fp16 is supported (host-side scaler); the selective/
    async update paths (zenflow, super_offload) still reject fp16."""
    engine, *_ = deepspeed_tpu.initialize(
        model=simple_mlp_spec(),
        config={"train_micro_batch_size_per_gpu": 2,
                "fp16": {"enabled": True},
                "zero_optimization": {"stage": 2,
                                      "offload_optimizer": {"device": "cpu"}}})
    assert engine.offload_optimizer is not None and engine.fp16_enabled
    with pytest.raises(NotImplementedError, match="zenflow|super_offload"):
        deepspeed_tpu.initialize(
            model=simple_mlp_spec(),
            config={"train_micro_batch_size_per_gpu": 2,
                    "fp16": {"enabled": True},
                    "zero_optimization": {
                        "stage": 2,
                        "offload_optimizer": {"device": "cpu",
                                              "super_offload": True}}})


def test_nvme_swap_is_pipelined(tmp_path, monkeypatch):
    """The boundary step overlaps NVMe reads with compute (reference
    PipelinedOptimizerSwapper, swap_tensor/pipelined_optimizer_swapper.py:52):
    leaf i+1's moment fetch must be ISSUED before leaf i's Adam step runs,
    and spill drains happen in windows, not per leaf."""
    from deepspeed_tpu.runtime.zero.offload import HostOffloadedOptimizer
    import deepspeed_tpu.ops.cpu.aio as aio_mod

    events = []

    class FakeAIO:
        def __init__(self, thread_count=1, **kw):
            self._pending = []

        def async_pread(self, array, path, offset=0):
            events.append(("pread", os.path.basename(path)))
            array[...] = np.fromfile(path, np.float32)

        def async_pwrite(self, array, path, offset=0):
            events.append(("pwrite", os.path.basename(path)))
            np.asarray(array, np.float32).tofile(path)

        def drain(self):
            events.append(("drain", ""))

    monkeypatch.setattr(aio_mod, "AsyncIOHandle", FakeAIO)
    import jax.numpy as jnp_

    leaves = {f"p{i}": jnp_.zeros((64,)) for i in range(6)}
    opt = HostOffloadedOptimizer(
        leaves, {"type": "adamw", "params": {"lr": 1e-3}},
        nvme_path=str(tmp_path / "nv"))
    opt.spill_window = 2
    opt.initialize_master(leaves)

    orig_step = opt.cpu_adam.step

    def rec_step(master, g, key, lr):
        events.append(("step", str(key)))
        return orig_step(master, g, key=key, lr=lr)

    opt.cpu_adam = type("W", (), {"step": staticmethod(rec_step),
                                  "_m": opt.cpu_adam._m,
                                  "_v": opt.cpu_adam._v,
                                  "state_dict": opt.cpu_adam.state_dict,
                                  "load_state_dict": opt.cpu_adam.load_state_dict})()
    gs = [np.ones(64, np.float32) for _ in range(6)]
    opt.apply_step([g.copy() for g in gs], lr=1e-3, denom=1.0)  # spills all
    events.clear()
    opt.apply_step([g.copy() for g in gs], lr=1e-3, denom=1.0)  # fetch+step

    def first(kind, key):
        return next(i for i, (k, p) in enumerate(events)
                    if k == kind and (key in p if key else True))

    # prefetch-ahead: leaf 1's (and 2's) reads issued before leaf 0 steps
    assert first("pread", "_1.bin") < first("step", "0"), events
    assert first("pread", "_2.bin") < first("step", "1"), events
    # windowed spill: 6 per-leaf fetch commits + ceil(6/2)=3 spill flushes;
    # the old per-leaf fetch+spill drains would be >= 12
    n_drains = sum(1 for k, _ in events if k == "drain")
    assert n_drains <= 9, (n_drains, events)


def test_nvme_pipelined_matches_cpu_offload(tmp_path):
    """The pipelined disk round-trip must be numerically invisible: NVMe
    and plain-CPU offload engines produce identical loss trajectories."""
    e_cpu = _engine(device="cpu")
    e_nvme = _engine(device="nvme", nvme_path=str(tmp_path / "nv2"))
    for i in range(6):
        b = random_batch(batch_size=8, seed=i % 2, gas=1)
        lc = float(e_cpu.train_batch(b))
        ln = float(e_nvme.train_batch(b))
        assert abs(lc - ln) < 1e-6, (i, lc, ln)


def test_offload_boundary_batched_h2d_push(monkeypatch):
    """The boundary's param push must be ONE batched device_put (transfers
    issued together, async) — not leaf-serial (VERDICT r3 weak #6)."""
    engine = _engine()
    calls = []
    orig = jax.device_put

    def rec(x, device=None, **kw):
        calls.append(x)
        return orig(x, device, **kw)

    monkeypatch.setattr(jax, "device_put", rec)
    engine.train_batch(random_batch(batch_size=4, gas=1))
    batched = [c for c in calls if isinstance(c, (list, tuple)) and len(c) > 1]
    assert batched, "param push not batched: device_put never got a list"
    n_leaves = len(jax.tree_util.tree_leaves(engine.state.params))
    assert any(len(c) == n_leaves for c in batched), (
        [len(c) for c in batched], n_leaves)


def test_superoffload_nvme_io_runs_concurrently(tmp_path, monkeypatch):
    """With per-worker private AIO handles, NVMe fetch/spill of different
    leaves overlap (the old single _io_lock serialized them, so the worker
    pool only helped the pure-RAM case — VERDICT r3 weak #6)."""
    import threading
    import time as _t

    import deepspeed_tpu.ops.cpu.aio as aio_mod
    from deepspeed_tpu.runtime.superoffload import SuperOffloadOptimizer

    lock = threading.Lock()
    conc = {"cur": 0, "peak": 0}

    class FakeAIO:
        def __init__(self, thread_count=1, **kw):
            pass

        def _enter(self):
            with lock:
                conc["cur"] += 1
                conc["peak"] = max(conc["peak"], conc["cur"])
            _t.sleep(0.04)  # models device latency; releases the GIL
            with lock:
                conc["cur"] -= 1

        def async_pread(self, array, path, offset=0):
            self._enter()
            array[...] = np.fromfile(path, np.float32)

        def async_pwrite(self, array, path, offset=0):
            self._enter()
            np.asarray(array, np.float32).tofile(path)

        def drain(self):
            pass

    monkeypatch.setattr(aio_mod, "AsyncIOHandle", FakeAIO)
    leaves = {f"p{i}": np.zeros(64, np.float32) for i in range(8)}
    opt = SuperOffloadOptimizer(
        leaves, {"type": "adamw", "params": {"lr": 1e-3}},
        nvme_path=str(tmp_path / "nv"), cpu_worker_count=4)
    opt.initialize_master(leaves)
    gs = [np.ones(64, np.float32) for _ in range(8)]
    opt.apply_step([g.copy() for g in gs], lr=1e-3, denom=1.0)  # create+spill
    opt.apply_step([g.copy() for g in gs], lr=1e-3, denom=1.0)  # fetch+step
    opt.shutdown()
    assert conc["peak"] >= 2, f"NVMe IO never overlapped: {conc}"


def test_offload_fp16_dynamic_scaling_survives_overflow():
    """fp16 + ZeRO-Offload (reference zero/stage_1_and_2.py loss scaler +
    CPU-Adam): grads reach the host scaled, the unscale rides the
    denominator, and an injected overflow SKIPS the host update (params
    and step untouched), halves the scale past hysteresis, and training
    resumes cleanly."""
    import dataclasses

    import jax.numpy as jnp

    engine = _engine(**{"bf16": {"enabled": False},
                        "fp16": {"enabled": True, "initial_scale_power": 10,
                                 "hysteresis": 1}})
    losses = [float(engine.train_batch(random_batch(batch_size=16,
                                                    seed=i % 4, gas=1)))
              for i in range(8)]
    # seed-matched epochs (seeds cycle 0-3): losses[0:4] and losses[4:8]
    # see the same batches — the raw losses[-1] < losses[0] comparison
    # of two DIFFERENT batches was env-numerics-dependent and flaked
    assert np.isfinite(losses).all()
    assert np.mean(losses[4:8]) < np.mean(losses[0:4]), losses
    scale_before = float(engine.state.loss_scale.cur_scale)
    step_before = int(engine.state.step)
    params_before = jax.tree_util.tree_map(np.asarray, engine.state.params)

    # inject an overflow into the accumulated grads at the boundary
    engine.state = dataclasses.replace(
        engine.state, grad_acc=jax.tree_util.tree_map(
            lambda g: jnp.full_like(g, jnp.inf), engine.state.grad_acc),
        micro_step=jnp.asarray(engine.config.gradient_accumulation_steps - 1, jnp.int32))
    engine._apply_step_offload()

    assert int(engine.state.step) == step_before  # skipped, not applied
    assert int(engine.state.skipped_steps) >= 1
    assert float(engine.state.loss_scale.cur_scale) < scale_before
    for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_leaves_with_path(params_before),
            jax.tree_util.tree_leaves_with_path(engine.state.params)):
        np.testing.assert_array_equal(a, np.asarray(b), err_msg=str(pa))

    # training resumes and the grad_acc was re-zeroed
    l2 = [float(engine.train_batch(random_batch(batch_size=16, seed=i % 4,
                                                gas=1))) for i in range(4)]
    assert np.isfinite(l2).all()
