"""ZeRO-Offload path tests (reference tests/unit/runtime/zero offload tests)."""

import jax
import numpy as np
import pytest

import deepspeed_tpu
from tests.unit.simple_model import random_batch, simple_mlp_spec


def _engine(device="cpu", nvme_path=None, **extra):
    off = {"device": device}
    if nvme_path:
        off["nvme_path"] = nvme_path
    cfg = {
        "train_micro_batch_size_per_gpu": 4,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2, "weight_decay": 0.01}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2, "offload_optimizer": off},
        "gradient_clipping": 1.0,
    }
    cfg.update(extra)
    engine, *_ = deepspeed_tpu.initialize(model=simple_mlp_spec(), config=cfg)
    return engine


def test_offload_cpu_trains():
    engine = _engine()
    assert engine.offload_optimizer is not None
    losses = [float(engine.train_batch(random_batch(batch_size=16, seed=i % 4, gas=1)))
              for i in range(15)]
    assert losses[-1] < losses[0]
    assert engine.get_global_grad_norm() > 0


def test_offload_matches_device_path():
    """Host C++ Adam and the compiled device update converge the same way."""
    e_dev = deepspeed_tpu.initialize(
        model=simple_mlp_spec(),
        config={"train_micro_batch_size_per_gpu": 4,
                "optimizer": {"type": "AdamW",
                              "params": {"lr": 1e-2, "weight_decay": 0.01}},
                "gradient_clipping": 1.0})[0]
    e_off = _engine()
    # bf16 on the offload engine vs fp32 device: compare loss trajectories
    dev_losses, off_losses = [], []
    for i in range(10):
        b = random_batch(batch_size=16, seed=i % 2, gas=1)
        dev_losses.append(float(e_dev.train_batch(b)))
        off_losses.append(float(e_off.train_batch(b)))
    assert abs(dev_losses[-1] - off_losses[-1]) < 0.1 * (1 + dev_losses[-1])


def test_offload_nvme_spills(tmp_path):
    engine = _engine(device="nvme", nvme_path=str(tmp_path / "nvme"))
    for i in range(4):
        engine.train_batch(random_batch(batch_size=8, seed=i, gas=1))
    import os

    spilled = os.listdir(tmp_path / "nvme")
    assert any(f.startswith("m_") for f in spilled)


def test_offload_fp16_rejected():
    with pytest.raises(NotImplementedError):
        deepspeed_tpu.initialize(
            model=simple_mlp_spec(),
            config={"train_micro_batch_size_per_gpu": 2,
                    "fp16": {"enabled": True},
                    "zero_optimization": {"stage": 2,
                                          "offload_optimizer": {"device": "cpu"}}})
