"""ZenFlow + SuperOffload tests (reference: runtime/zenflow/, runtime/superoffload/)."""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.runtime.zenflow import ZenFlowConfig, ZenFlowOptimizer
from tests.unit.simple_model import random_batch, simple_mlp_spec


def _np_adamw(master, gs_seq, lr, b1=0.9, b2=0.999, eps=1e-8, wd=0.0):
    m = [np.zeros_like(x) for x in master]
    v = [np.zeros_like(x) for x in master]
    for t, gs in enumerate(gs_seq, start=1):
        for i, g in enumerate(gs):
            m[i] = b1 * m[i] + (1 - b1) * g
            v[i] = b2 * v[i] + (1 - b2) * g * g
            mh = m[i] / (1 - b1 ** t)
            vh = v[i] / (1 - b2 ** t)
            if wd:
                master[i] *= (1 - lr * wd)
            master[i] -= lr * mh / (np.sqrt(vh) + eps)
    return master


def test_zenflow_full_ratio_matches_adamw():
    """topk_ratio=1.0 puts everything on the fast path -> exact AdamW."""
    rng = np.random.RandomState(0)
    shapes = [(8, 16), (16,), (16, 4)]
    init = [rng.randn(*s).astype(np.float32) for s in shapes]
    opt = ZenFlowOptimizer(
        None, {"type": "adamw", "params": {"lr": 1e-2, "weight_decay": 0.0}},
        zenflow_config=ZenFlowConfig(enabled=True, topk_ratio=1.0))
    opt.initialize_master([x.copy() for x in init])
    gs_seq = [[rng.randn(*s).astype(np.float32) for s in shapes] for _ in range(5)]
    for gs in gs_seq:
        master, norm = opt.apply_step([g.copy() for g in gs], lr=1e-2, denom=1.0)
        assert norm > 0
    want = _np_adamw([x.copy() for x in init], gs_seq, lr=1e-2)
    for got, ref in zip(master, want):
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("overlap", [True, False])
def test_zenflow_selective_converges(overlap):
    """Partial fast path + deferred slow pass still optimizes (values move,
    every gradient is applied exactly once across the two paths)."""
    rng = np.random.RandomState(1)
    init = [rng.randn(8, 8).astype(np.float32)]
    opt = ZenFlowOptimizer(
        None, {"type": "adamw", "params": {"lr": 1e-2}},
        zenflow_config=ZenFlowConfig(enabled=True, topk_ratio=0.25,
                                     update_interval=2, overlap_step=overlap))
    opt.initialize_master([x.copy() for x in init])
    # constant gradient: after interval boundaries every element must move
    g = np.ones((8, 8), np.float32)
    for _ in range(6):
        master, _ = opt.apply_step([g.copy()], lr=1e-2, denom=1.0)
    opt._join_slow()
    assert (np.abs(init[0] - opt.master[0]) > 1e-4).all()


def test_zenflow_state_roundtrip():
    rng = np.random.RandomState(2)
    opt = ZenFlowOptimizer(None, {"type": "adamw", "params": {"lr": 1e-2}},
                           zenflow_config=ZenFlowConfig(enabled=True))
    opt.initialize_master([rng.randn(4, 4).astype(np.float32)])
    opt.apply_step([rng.randn(4, 4).astype(np.float32)], lr=1e-2, denom=1.0)
    sd = opt.state_dict()
    opt2 = ZenFlowOptimizer(None, {"type": "adamw", "params": {"lr": 1e-2}},
                            zenflow_config=ZenFlowConfig(enabled=True))
    opt2.load_state_dict(sd)
    g = np.ones((4, 4), np.float32)
    m1, _ = opt.apply_step([g.copy()], lr=1e-2, denom=1.0)
    m2, _ = opt2.apply_step([g.copy()], lr=1e-2, denom=1.0)
    np.testing.assert_allclose(m1[0], m2[0], rtol=1e-6)


def _engine(**zero_extra):
    cfg = {
        "train_micro_batch_size_per_gpu": 4,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2, **zero_extra},
        "gradient_clipping": 1.0,
    }
    engine, *_ = deepspeed_tpu.initialize(model=simple_mlp_spec(), config=cfg)
    return engine


def test_zenflow_engine_trains():
    engine = _engine(zenflow={"enabled": True, "topk_ratio": 0.25,
                              "update_interval": 2})
    assert isinstance(engine.offload_optimizer, ZenFlowOptimizer)
    losses = [float(engine.train_batch(random_batch(batch_size=16, seed=i % 4, gas=1)))
              for i in range(12)]
    # seed-matched epochs: batches cycle seeds 0-3, so losses[0:4] and
    # losses[8:12] see the SAME batches — compare epoch means, not the
    # raw losses[-1] < losses[0] of two different random batches (that
    # comparison is env-numerics-dependent and flaked on some hosts)
    assert np.isfinite(losses).all()
    assert np.mean(losses[8:12]) < np.mean(losses[0:4]), losses


def test_superoffload_engine_matches_plain_offload():
    from deepspeed_tpu.runtime.superoffload import SuperOffloadOptimizer

    e_super = _engine(offload_optimizer={"device": "cpu", "super_offload": True,
                                         "cpu_worker_count": 3})
    assert isinstance(e_super.offload_optimizer, SuperOffloadOptimizer)
    e_plain = _engine(offload_optimizer={"device": "cpu"})
    for i in range(6):
        b = random_batch(batch_size=16, seed=i % 2, gas=1)
        ls = float(e_super.train_batch(b))
        lp = float(e_plain.train_batch(b))
        assert abs(ls - lp) < 1e-5, (i, ls, lp)  # identical math, fanned out


def test_cpu_adam_per_key_step_counts():
    """Bias correction is per-parameter: two keys fed identical inputs must
    produce identical results (a shared global step count breaks this)."""
    from deepspeed_tpu.ops.cpu.adam import DeepSpeedCPUAdam

    adam = DeepSpeedCPUAdam(lr=1e-2)
    rng = np.random.RandomState(3)
    p0 = rng.randn(64).astype(np.float32)
    p1 = p0.copy()
    for _ in range(3):
        g = rng.randn(64).astype(np.float32)
        adam.step(p0, g, key=0)
        adam.step(p1, g, key=1)
    np.testing.assert_array_equal(p0, p1)
    assert adam.step_count == 3


def test_zenflow_selection_change_keeps_residual():
    """A column newly entering the top-k must not lose its previously
    accumulated slow-path gradient (only the current step's contribution
    moves to the fast path)."""
    opt = ZenFlowOptimizer(
        None, {"type": "adamw", "params": {"lr": 1e-2}},
        zenflow_config=ZenFlowConfig(enabled=True, topk_ratio=0.25,
                                     update_interval=100))  # no slow launch
    opt.initialize_master([np.zeros((4, 4), np.float32)])
    g1 = np.zeros((4, 4), np.float32)
    g1[:, 0] = 10.0  # col 0 selected
    g1[:, 1] = 1.0   # col 1 accumulates
    opt.apply_step([g1.copy()], lr=1e-2, denom=1.0)
    np.testing.assert_allclose(opt._accum[0][:, 1], 1.0)
    g2 = np.zeros((4, 4), np.float32)
    g2[:, 1] = 10.0  # col 1 now selected
    opt.apply_step([g2.copy()], lr=1e-2, denom=1.0)
    # col 1's step-1 residual must survive the selection change
    np.testing.assert_allclose(opt._accum[0][:, 1], 1.0)
    # and col 1's step-2 gradient went to the fast path, not the buffer
    assert (np.abs(opt.master[0][:, 1]) > 0).all()


def test_zenflow_slow_pass_decays_moments_of_zero_grad_elements():
    """A zero gradient on an element in a slow-path (unselected) column must
    still decay the Adam moments (ADVICE r1: g!=0 proxy froze such elements).
    With a constant column selection, run long enough for the slow pass to
    apply: the zero-grad element's momentum must shrink, and the element
    still moves (mh/(sqrt(vh)+eps) with decayed moments)."""
    opt = ZenFlowOptimizer(
        None, {"type": "adamw", "params": {"lr": 1e-2}},
        zenflow_config=ZenFlowConfig(enabled=True, topk_ratio=0.5,
                                     update_interval=2, overlap_step=False))
    x = np.ones((4, 4), np.float32)
    opt.initialize_master([x.copy()])
    g = np.zeros((4, 4), np.float32)
    g[:, :2] = 10.0  # columns 0,1 fast-selected every step
    g[0, 2] = 1e-3   # column 2: tiny grad on one element, 0 on the others
    for _ in range(4):
        opt.apply_step([g.copy()], lr=1e-2, denom=1.0)
    # element (1, 2): zero grad, in a slow-path column with residual ->
    # after the slow pass its m/v were stepped (decay toward 0 from 0 stays
    # 0 for m; the REAL check: master moved for (0,2) and the column's
    # moments updated without freezing the zero-grad rows' update path)
    assert opt.master[0][0, 2] != x[0, 2]
    # zero-grad element: Adam with g=0 keeps m=v=0 -> no movement, but it
    # must NOT have been excluded from the update (weight decay case);
    # verify with weight decay that zero-grad elements decay too
    opt2 = ZenFlowOptimizer(
        None, {"type": "adamw", "params": {"lr": 1e-2, "weight_decay": 0.1}},
        zenflow_config=ZenFlowConfig(enabled=True, topk_ratio=0.5,
                                     update_interval=2, overlap_step=False))
    opt2.initialize_master([x.copy()])
    for _ in range(4):
        opt2.apply_step([g.copy()], lr=1e-2, denom=1.0)
    # (1,2) has zero grad but sits in touched column 2: AdamW weight decay
    # must have shrunk it below its initial 1.0
    assert opt2.master[0][1, 2] < 1.0


def test_zenflow_overlap_window_preserves_fast_updates():
    """With overlap_step=True the slow pass now spans the whole interval;
    fast-path updates (including 1-D always-fast params) landing during the
    window must survive the merge (ADVICE r1: dead fast-mask machinery)."""
    opt = ZenFlowOptimizer(
        None, {"type": "adamw", "params": {"lr": 1e-2}},
        zenflow_config=ZenFlowConfig(enabled=True, topk_ratio=0.25,
                                     update_interval=2, overlap_step=True))
    rng = np.random.RandomState(3)
    init = [rng.randn(8, 8).astype(np.float32),
            rng.randn(8).astype(np.float32)]  # 1-D: always fast path
    opt.initialize_master([x.copy() for x in init])
    for step in range(1, 7):
        gs = [np.ones((8, 8), np.float32), np.ones((8,), np.float32)]
        opt.apply_step(gs, lr=1e-2, denom=1.0)
        # the 1-D param must reflect every boundary's fast update even while
        # a slow thread is in flight: 6 AdamW steps with g=1 move it by
        # roughly step * lr each; check monotone movement
        moved = np.abs(opt.master[1] - init[1]).min()
        assert moved > 0.008 * step, (step, moved)
    opt._join_slow()
    # every element of the 2-D param moved too (fast + slow merged)
    assert (np.abs(opt.master[0] - init[0]) > 1e-4).all()


def test_zenflow_requeues_residual_for_columns_claimed_by_fast_path():
    """A column that accumulated slow residual in interval N and then became
    fast-selected during interval N+1's overlap window must not lose that
    residual: it is re-queued and applied by a later slow pass."""
    def run(phase1_col1):
        opt = ZenFlowOptimizer(
            None, {"type": "adamw", "params": {"lr": 1e-2}},
            zenflow_config=ZenFlowConfig(enabled=True, topk_ratio=0.25,
                                         update_interval=2, overlap_step=True))
        opt.initialize_master([np.zeros((4, 4), np.float32)])
        g1 = np.zeros((4, 4), np.float32)
        g1[:, 0] = 10.0           # col 0 fast-selected in phase 1
        g1[0, 1] = phase1_col1    # col 1 slow residual (or none, control)
        g2 = np.zeros((4, 4), np.float32)
        g2[:, 1] = 10.0           # col 1 fast-selected in phase 2
        for g in (g1, g1, g2, g2, g2 * 0 + np.eye(4, dtype=np.float32)):
            opt.apply_step([g.copy()], lr=1e-2, denom=1.0)
        opt._join_slow()
        return opt.master[0].copy()

    with_residual = run(1.0)
    control = run(0.0)
    # the phase-1 residual on (0, 1) must eventually land despite col 1
    # being fast-owned during the overlap window in which its slow pass ran
    assert abs(with_residual[0, 1] - control[0, 1]) > 1e-4


def test_superoffload_workers_run_concurrently():
    """The worker pool must actually overlap per-leaf Adam steps (the
    multicore claim of superoffload_utils.py:145): with the C++ kernel
    stubbed by a GIL-releasing sleep, max observed concurrency > 1."""
    import threading
    import time as _t

    from deepspeed_tpu.runtime.superoffload import SuperOffloadOptimizer

    opt = SuperOffloadOptimizer(
        {"p%d" % i: np.zeros(32, np.float32) for i in range(6)},
        {"type": "adamw", "params": {"lr": 1e-3}}, cpu_worker_count=3)
    opt.initialize_master({f"p{i}": np.zeros(32, np.float32) for i in range(6)})

    lock = threading.Lock()
    state = {"cur": 0, "peak": 0}
    orig = opt.cpu_adam.step

    def slow_step(master, g, key, lr):
        with lock:
            state["cur"] += 1
            state["peak"] = max(state["peak"], state["cur"])
        _t.sleep(0.05)  # releases the GIL like the ctypes SIMD kernel
        with lock:
            state["cur"] -= 1
        return orig(master, g, key=key, lr=lr)

    opt.cpu_adam.step = slow_step
    gs = [np.ones(32, np.float32) for _ in range(6)]
    opt.apply_step(gs, lr=1e-3, denom=1.0)
    opt.shutdown()
    assert state["peak"] >= 2, f"workers never overlapped: {state}"
