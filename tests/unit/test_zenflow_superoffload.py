"""ZenFlow + SuperOffload tests (reference: runtime/zenflow/, runtime/superoffload/)."""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.runtime.zenflow import ZenFlowConfig, ZenFlowOptimizer
from tests.unit.simple_model import random_batch, simple_mlp_spec


def _np_adamw(master, gs_seq, lr, b1=0.9, b2=0.999, eps=1e-8, wd=0.0):
    m = [np.zeros_like(x) for x in master]
    v = [np.zeros_like(x) for x in master]
    for t, gs in enumerate(gs_seq, start=1):
        for i, g in enumerate(gs):
            m[i] = b1 * m[i] + (1 - b1) * g
            v[i] = b2 * v[i] + (1 - b2) * g * g
            mh = m[i] / (1 - b1 ** t)
            vh = v[i] / (1 - b2 ** t)
            if wd:
                master[i] *= (1 - lr * wd)
            master[i] -= lr * mh / (np.sqrt(vh) + eps)
    return master


def test_zenflow_full_ratio_matches_adamw():
    """topk_ratio=1.0 puts everything on the fast path -> exact AdamW."""
    rng = np.random.RandomState(0)
    shapes = [(8, 16), (16,), (16, 4)]
    init = [rng.randn(*s).astype(np.float32) for s in shapes]
    opt = ZenFlowOptimizer(
        None, {"type": "adamw", "params": {"lr": 1e-2, "weight_decay": 0.0}},
        zenflow_config=ZenFlowConfig(enabled=True, topk_ratio=1.0))
    opt.initialize_master([x.copy() for x in init])
    gs_seq = [[rng.randn(*s).astype(np.float32) for s in shapes] for _ in range(5)]
    for gs in gs_seq:
        master, norm = opt.apply_step([g.copy() for g in gs], lr=1e-2, denom=1.0)
        assert norm > 0
    want = _np_adamw([x.copy() for x in init], gs_seq, lr=1e-2)
    for got, ref in zip(master, want):
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("overlap", [True, False])
def test_zenflow_selective_converges(overlap):
    """Partial fast path + deferred slow pass still optimizes (values move,
    every gradient is applied exactly once across the two paths)."""
    rng = np.random.RandomState(1)
    init = [rng.randn(8, 8).astype(np.float32)]
    opt = ZenFlowOptimizer(
        None, {"type": "adamw", "params": {"lr": 1e-2}},
        zenflow_config=ZenFlowConfig(enabled=True, topk_ratio=0.25,
                                     update_interval=2, overlap_step=overlap))
    opt.initialize_master([x.copy() for x in init])
    # constant gradient: after interval boundaries every element must move
    g = np.ones((8, 8), np.float32)
    for _ in range(6):
        master, _ = opt.apply_step([g.copy()], lr=1e-2, denom=1.0)
    opt._join_slow()
    assert (np.abs(init[0] - opt.master[0]) > 1e-4).all()


def test_zenflow_state_roundtrip():
    rng = np.random.RandomState(2)
    opt = ZenFlowOptimizer(None, {"type": "adamw", "params": {"lr": 1e-2}},
                           zenflow_config=ZenFlowConfig(enabled=True))
    opt.initialize_master([rng.randn(4, 4).astype(np.float32)])
    opt.apply_step([rng.randn(4, 4).astype(np.float32)], lr=1e-2, denom=1.0)
    sd = opt.state_dict()
    opt2 = ZenFlowOptimizer(None, {"type": "adamw", "params": {"lr": 1e-2}},
                            zenflow_config=ZenFlowConfig(enabled=True))
    opt2.load_state_dict(sd)
    g = np.ones((4, 4), np.float32)
    m1, _ = opt.apply_step([g.copy()], lr=1e-2, denom=1.0)
    m2, _ = opt2.apply_step([g.copy()], lr=1e-2, denom=1.0)
    np.testing.assert_allclose(m1[0], m2[0], rtol=1e-6)


def _engine(**zero_extra):
    cfg = {
        "train_micro_batch_size_per_gpu": 4,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2, **zero_extra},
        "gradient_clipping": 1.0,
    }
    engine, *_ = deepspeed_tpu.initialize(model=simple_mlp_spec(), config=cfg)
    return engine


def test_zenflow_engine_trains():
    engine = _engine(zenflow={"enabled": True, "topk_ratio": 0.25,
                              "update_interval": 2})
    assert isinstance(engine.offload_optimizer, ZenFlowOptimizer)
    losses = [float(engine.train_batch(random_batch(batch_size=16, seed=i % 4, gas=1)))
              for i in range(12)]
    assert losses[-1] < losses[0]


def test_superoffload_engine_matches_plain_offload():
    from deepspeed_tpu.runtime.superoffload import SuperOffloadOptimizer

    e_super = _engine(offload_optimizer={"device": "cpu", "super_offload": True,
                                         "cpu_worker_count": 3})
    assert isinstance(e_super.offload_optimizer, SuperOffloadOptimizer)
    e_plain = _engine(offload_optimizer={"device": "cpu"})
    for i in range(6):
        b = random_batch(batch_size=16, seed=i % 2, gas=1)
        ls = float(e_super.train_batch(b))
        lp = float(e_plain.train_batch(b))
        assert abs(ls - lp) < 1e-5, (i, ls, lp)  # identical math, fanned out


def test_cpu_adam_per_key_step_counts():
    """Bias correction is per-parameter: two keys fed identical inputs must
    produce identical results (a shared global step count breaks this)."""
    from deepspeed_tpu.ops.cpu.adam import DeepSpeedCPUAdam

    adam = DeepSpeedCPUAdam(lr=1e-2)
    rng = np.random.RandomState(3)
    p0 = rng.randn(64).astype(np.float32)
    p1 = p0.copy()
    for _ in range(3):
        g = rng.randn(64).astype(np.float32)
        adam.step(p0, g, key=0)
        adam.step(p1, g, key=1)
    np.testing.assert_array_equal(p0, p1)
    assert adam.step_count == 3


def test_zenflow_selection_change_keeps_residual():
    """A column newly entering the top-k must not lose its previously
    accumulated slow-path gradient (only the current step's contribution
    moves to the fast path)."""
    opt = ZenFlowOptimizer(
        None, {"type": "adamw", "params": {"lr": 1e-2}},
        zenflow_config=ZenFlowConfig(enabled=True, topk_ratio=0.25,
                                     update_interval=100))  # no slow launch
    opt.initialize_master([np.zeros((4, 4), np.float32)])
    g1 = np.zeros((4, 4), np.float32)
    g1[:, 0] = 10.0  # col 0 selected
    g1[:, 1] = 1.0   # col 1 accumulates
    opt.apply_step([g1.copy()], lr=1e-2, denom=1.0)
    np.testing.assert_allclose(opt._accum[0][:, 1], 1.0)
    g2 = np.zeros((4, 4), np.float32)
    g2[:, 1] = 10.0  # col 1 now selected
    opt.apply_step([g2.copy()], lr=1e-2, denom=1.0)
    # col 1's step-1 residual must survive the selection change
    np.testing.assert_allclose(opt._accum[0][:, 1], 1.0)
    # and col 1's step-2 gradient went to the fast path, not the buffer
    assert (np.abs(opt.master[0][:, 1]) > 0).all()
