"""AutoTP / module injection tests.

Mirrors the reference's tests/unit/model_parallelism + module_inject
coverage: policy detection per architecture, generic Linear classification,
numeric parity of column/row parallel forms, and tp_model_init training.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import deepspeed_tpu
from deepspeed_tpu.module_inject import (AutoTP, apply_injection_policy,
                                         column_parallel, row_parallel,
                                         column_parallel_explicit,
                                         row_parallel_explicit, infer_tp_rules)
from deepspeed_tpu.module_inject.auto_tp import get_policy
from deepspeed_tpu.parallel.mesh import MODEL_AXIS


def hf_llama_tree(h=16, ffn=32, vocab=64, layers=2):
    """Parameter structure shaped like HF-flax llama."""
    k = lambda i, o: jnp.zeros((i, o))
    layer = {
        "self_attn": {n: {"kernel": k(h, h)} for n in
                      ("q_proj", "k_proj", "v_proj", "o_proj")},
        "mlp": {"gate_proj": {"kernel": k(h, ffn)},
                "up_proj": {"kernel": k(h, ffn)},
                "down_proj": {"kernel": k(ffn, h)}},
        "input_layernorm": {"weight": jnp.ones((h,))},
    }
    return {"model": {"embed_tokens": {"embedding": jnp.zeros((vocab, h))},
                      "layers": {str(i): jax.tree_util.tree_map(lambda x: x, layer)
                                 for i in range(layers)},
                      "norm": {"weight": jnp.ones((h,))}},
            "lm_head": {"kernel": k(h, vocab)}}


def hf_bert_tree(h=16, ffn=32):
    k = lambda i, o: {"kernel": jnp.zeros((i, o)), "bias": jnp.zeros((o,))}
    layer = {
        "attention": {"self": {"query": k(h, h), "key": k(h, h), "value": k(h, h)},
                      "output": {"dense": k(h, h)}},
        "intermediate": {"dense": k(h, ffn)},
        "output": {"dense": k(ffn, h)},
    }
    return {"bert": {"encoder": {"layer": {"0": layer}}}}


def _match(rules, path):
    import re
    for pat, spec in rules:
        if re.search(pat, path):
            return spec
    return None


def test_autotp_detects_llama_policy():
    tree = hf_llama_tree()
    assert AutoTP.detect_arch(tree) == "llama"
    rules = AutoTP().parse(tree)
    assert _match(rules, "model/layers/0/self_attn/q_proj/kernel") == P(None, MODEL_AXIS)
    assert _match(rules, "model/layers/1/self_attn/o_proj/kernel") == P(MODEL_AXIS, None)
    assert _match(rules, "model/layers/0/mlp/down_proj/kernel") == P(MODEL_AXIS, None)
    assert _match(rules, "lm_head/kernel") == P(None, MODEL_AXIS)
    assert _match(rules, "model/norm/weight") is None


def test_generic_parser_bert():
    tree = hf_bert_tree()
    rules = infer_tp_rules(tree)
    assert _match(rules, "bert/encoder/layer/0/intermediate/dense/kernel") == P(None, MODEL_AXIS)
    assert _match(rules, "bert/encoder/layer/0/attention/output/dense/kernel") == P(MODEL_AXIS, None)
    assert _match(rules, "bert/encoder/layer/0/output/dense/kernel") == P(MODEL_AXIS, None)
    # column bias sharded, row bias replicated
    assert _match(rules, "bert/encoder/layer/0/intermediate/dense/bias") == P(MODEL_AXIS)
    assert _match(rules, "bert/encoder/layer/0/output/dense/bias") is None


def test_policy_registry_covers_major_archs():
    for arch in ("llama", "gpt2", "gptneox", "bloom", "bert", "opt", "t5",
                 "mixtral", "falcon", "phi", "chatglm"):
        assert get_policy(arch), arch


def test_row_column_parallel_numerics(devices8):
    """col→row pair under a 4-way model mesh == dense reference."""
    mesh = Mesh(np.array(devices8[:4]).reshape(4), (MODEL_AXIS,))
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 8, 16), jnp.float32)
    w1 = jnp.asarray(rng.randn(16, 32), jnp.float32)
    b1 = jnp.asarray(rng.randn(32), jnp.float32)
    w2 = jnp.asarray(rng.randn(32, 16), jnp.float32)
    b2 = jnp.asarray(rng.randn(16), jnp.float32)

    ref = jnp.maximum(x @ w1 + b1, 0.0) @ w2 + b2

    @jax.jit
    def spmd(x, w1, b1, w2, b2):
        h = column_parallel(x, w1, b1, mesh=mesh)
        return row_parallel(jnp.maximum(h, 0.0), w2, b2, mesh=mesh)

    with mesh:
        got = spmd(x, w1, b1, w2, b2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)

    # explicit shard_map form
    from deepspeed_tpu.utils.jax_compat import shard_map

    body = shard_map(
        lambda x, w1, b1, w2, b2: row_parallel_explicit(
            jnp.maximum(column_parallel_explicit(x, w1, b1), 0.0), w2, b2),
        mesh=mesh,
        in_specs=(P(), P(None, MODEL_AXIS), P(MODEL_AXIS), P(MODEL_AXIS, None), P()),
        out_specs=P())
    got2 = jax.jit(body)(x, w1, b1, w2, b2)
    np.testing.assert_allclose(np.asarray(got2), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_apply_injection_policy_merges_rules():
    tree = hf_llama_tree()
    spec = deepspeed_tpu.ModelSpec(
        init_params=lambda rng: tree,
        loss_fn=lambda p, b, r: jnp.float32(0.0),
        partition_rules=[("lm_head/kernel", P(None, None))])
    out = apply_injection_policy(spec)
    # user-provided rule survives; autotp rules appended after
    assert out.partition_rules()[0] == ("lm_head/kernel", P(None, None))
    assert len(out.partition_rules()) > 1


def test_tp_model_init_trains(devices8):
    """tp_model_init + engine: one step with 2-way TP on the native llama."""
    from deepspeed_tpu.models.llama import llama_model

    model = llama_model("tiny", max_seq_len=32)
    spec = deepspeed_tpu.tp_model_init(model, tp_size=2)
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 1},
        "mesh": {"model": 2, "data": -1},
    }
    engine, *_ = deepspeed_tpu.initialize(model=spec, config=config)
    ids = jnp.asarray(np.random.RandomState(0).randint(
        0, model.config.vocab_size, (1, 2, 32)), dtype=jnp.int32)
    batch = {"input_ids": ids}
    loss0 = float(engine.train_batch(batch))
    loss1 = float(engine.train_batch(batch))
    assert np.isfinite(loss0) and loss1 < loss0
