"""Inference v2 (ragged/paged continuous batching) tests.

Oracle: the paged engine must produce token-for-token the same greedy
generations as the dense KV-cache path (inference v1), for sequences of
different lengths running concurrently.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.v2 import (BlockAllocator, InferenceEngineV2,
                                        RaggedInferenceConfig, RaggedRequest)
from deepspeed_tpu.models.llama import llama_model
from deepspeed_tpu.models.transformer import forward_with_cache, init_kv_cache

pytestmark = pytest.mark.slow  # multi-minute integration tier


def test_block_allocator():
    a = BlockAllocator(8)
    p = a.alloc(5)
    assert len(set(p)) == 5 and a.free_pages == 3
    a.free(p[:2])
    assert a.free_pages == 5
    with pytest.raises(MemoryError):
        a.alloc(6)
    with pytest.raises(ValueError):
        a.free([99])


def _dense_greedy(model, params, prompt, n_new):
    """Reference generation through the dense cache path."""
    cfg = model.config
    cache = init_kv_cache(cfg, 1, 256, jnp.float32)
    ids = jnp.asarray(np.array(prompt)[None], jnp.int32)
    logits, cache = forward_with_cache(cfg, params, ids,
                                       cache, jnp.zeros((1,), jnp.int32))
    toks = [int(jnp.argmax(logits[0, len(prompt) - 1]))]
    for i in range(n_new - 1):
        pos = jnp.asarray([len(prompt) + i], jnp.int32)
        logits, cache = forward_with_cache(
            cfg, params, jnp.asarray([[toks[-1]]], jnp.int32), cache, pos)
        toks.append(int(jnp.argmax(logits[0, 0])))
    return toks


def test_paged_matches_dense_single():
    model = llama_model("tiny", max_seq_len=256)
    params = model.init_params(jax.random.PRNGKey(0))
    prompt = list(np.random.RandomState(1).randint(0, model.config.vocab_size, 13))
    want = _dense_greedy(model, params, prompt, 8)

    eng = InferenceEngineV2(model, RaggedInferenceConfig(
        dtype="fp32", page_size=8, num_pages=32, max_seqs=2,
        max_pages_per_seq=8), params=params)
    got = eng.generate_all([RaggedRequest(prompt_ids=prompt, max_new_tokens=8)])
    assert got[0] == want, (got, want)


def test_paged_kernel_path_matches_dense(monkeypatch):
    """Same oracle with the Pallas paged-decode kernel forced on
    (interpret mode on CPU) — the TPU hot path, token-for-token."""
    monkeypatch.setenv("DSTPU_PAGED_KERNEL", "1")
    model = llama_model("tiny", max_seq_len=256)
    params = model.init_params(jax.random.PRNGKey(0))
    prompt = list(np.random.RandomState(5).randint(0, model.config.vocab_size, 13))
    want = _dense_greedy(model, params, prompt, 8)

    eng = InferenceEngineV2(model, RaggedInferenceConfig(
        dtype="fp32", page_size=8, num_pages=32, max_seqs=2,
        max_pages_per_seq=8), params=params)
    got = eng.generate_all([RaggedRequest(prompt_ids=prompt, max_new_tokens=8)])
    assert got[0] == want, (got, want)


def test_continuous_batching_mixed_lengths():
    """Three prompts of different lengths, admitted together; results must
    match per-sequence dense generation exactly."""
    model = llama_model("tiny", max_seq_len=256)
    params = model.init_params(jax.random.PRNGKey(0))
    rng = np.random.RandomState(2)
    prompts = [list(rng.randint(0, model.config.vocab_size, n))
               for n in (5, 17, 30)]
    wants = [_dense_greedy(model, params, p, 6) for p in prompts]

    eng = InferenceEngineV2(model, RaggedInferenceConfig(
        dtype="fp32", page_size=8, num_pages=64, max_seqs=4,
        max_pages_per_seq=8), params=params)
    got = eng.generate_all(
        [RaggedRequest(prompt_ids=p, max_new_tokens=6) for p in prompts])
    for uid, want in enumerate(wants):
        assert got[uid] == want, (uid, got[uid], want)


def test_queueing_beyond_slots():
    """More requests than decode slots: later ones wait, all finish."""
    model = llama_model("tiny", max_seq_len=256)
    params = model.init_params(jax.random.PRNGKey(0))
    rng = np.random.RandomState(3)
    prompts = [list(rng.randint(0, model.config.vocab_size, 9)) for _ in range(5)]

    eng = InferenceEngineV2(model, RaggedInferenceConfig(
        dtype="fp32", page_size=8, num_pages=16, max_seqs=2,
        max_pages_per_seq=4), params=params)
    got = eng.generate_all(
        [RaggedRequest(prompt_ids=p, max_new_tokens=4) for p in prompts])
    assert len(got) == 5
    assert all(len(v) == 4 for v in got.values())
    # all pages returned to the pool
    assert eng.allocator.free_pages == 16


def test_eos_stops_generation():
    model = llama_model("tiny", max_seq_len=256)
    params = model.init_params(jax.random.PRNGKey(0))
    prompt = list(np.random.RandomState(4).randint(0, model.config.vocab_size, 6))
    want = _dense_greedy(model, params, prompt, 8)
    eos = want[2]  # third generated token acts as EOS

    eng = InferenceEngineV2(model, RaggedInferenceConfig(
        dtype="fp32", page_size=8, num_pages=32, max_seqs=2,
        max_pages_per_seq=8), params=params)
    got = eng.generate_all([RaggedRequest(prompt_ids=prompt, max_new_tokens=8,
                                          eos_id=eos)])
    assert got[0] == want[:3]


def test_rejects_oversized_prompt():
    model = llama_model("tiny", max_seq_len=256)
    eng = InferenceEngineV2(model, RaggedInferenceConfig(
        dtype="fp32", page_size=8, num_pages=16, max_seqs=2,
        max_pages_per_seq=2))
    with pytest.raises(ValueError):
        eng.put(RaggedRequest(prompt_ids=list(range(16)), max_new_tokens=1))


def test_kv_pressure_preempts_instead_of_crashing():
    """Decode-time page growth under a full pool must preempt + recompute,
    never raise (reference: v2 scheduler holds requests under KV pressure)."""
    model = llama_model("tiny", max_seq_len=256)
    params = model.init_params(jax.random.PRNGKey(0))
    rng = np.random.RandomState(2)
    # pool of 8 pages, two prompts of 28 tokens -> 4 pages each: pool full at
    # admission; the first boundary-crossing generated token forces preemption
    eng = InferenceEngineV2(model, RaggedInferenceConfig(
        dtype="fp32", page_size=8, num_pages=8, max_seqs=2,
        max_pages_per_seq=8), params=params)
    prompts = [list(rng.randint(0, model.config.vocab_size, 28)) for _ in range(2)]
    got = eng.generate_all([RaggedRequest(prompt_ids=p, max_new_tokens=10)
                            for p in prompts])
    for uid, p in enumerate(prompts):
        assert len(got[uid]) == 10
        # preempted sequences recompute their prefix; result must equal the
        # uninterrupted dense generation
        want = _dense_greedy(model, params, p, 10)
        assert got[uid] == want


def test_pool_smaller_than_one_seq_rejected():
    model = llama_model("tiny", max_seq_len=256)
    with pytest.raises(ValueError):
        InferenceEngineV2(model, RaggedInferenceConfig(
            page_size=8, num_pages=4, max_seqs=2, max_pages_per_seq=8))


def test_learned_pos_window_capped_to_model_context():
    from deepspeed_tpu.models.gpt2 import gpt2_model
    model = gpt2_model("tiny", max_seq_len=32)
    eng = InferenceEngineV2(model, RaggedInferenceConfig(
        dtype="fp32", page_size=16, num_pages=32, max_seqs=2,
        max_pages_per_seq=16))  # paged window 256 >> model context 32
    assert eng.max_seq_len == 32
    with pytest.raises(ValueError):
        eng.put(RaggedRequest(prompt_ids=list(range(40))))


def test_prefill_bucket_capped_to_model_context():
    """The prefill bucket caps at the page-rounded MODEL window, not the
    (possibly much larger) paged window (ADVICE r1 engine_v2.py:135): a
    learned-position model must not prefill past its position table."""
    from deepspeed_tpu.models.gpt2 import gpt2_model
    model = gpt2_model("tiny", max_seq_len=40)  # not a page multiple
    eng = InferenceEngineV2(model, RaggedInferenceConfig(
        dtype="fp32", page_size=16, num_pages=32, max_seqs=2,
        max_pages_per_seq=16))  # paged window 256 >> model context 40
    assert eng._bucket(33) == 48  # page-rounded model window, not 64/256
    # end-to-end: a prompt near the context edge still prefills + decodes
    out = eng.generate_all(
        [RaggedRequest(prompt_ids=list(range(1, 34)), max_new_tokens=4)])
    (toks,) = out.values()
    assert len(toks) >= 1


# ----------------- weight-only quantized inference (ZeRO++-adjacent) -------
def test_wq_matmul_matches_dequant():
    """Pallas/XLA weight-quantized matmul == explicit dequant matmul, int8
    and packed int4 (reference inference/quantization weight-only path)."""
    from deepspeed_tpu.ops.pallas.wq_matmul import (dequantize_weight,
                                                    quantize_weight,
                                                    wq_matmul)
    rng = np.random.RandomState(0)
    for bits in (8, 4):
        for K, N in [(128, 64), (200, 96)]:  # 200: padded packing
            w = jnp.asarray(rng.randn(K, N).astype(np.float32))
            x = jnp.asarray(rng.randn(5, K).astype(np.float32))
            codes, scale = quantize_weight(w, bits, group=64)
            wd = dequantize_weight(codes, scale, bits=bits, group=64, k=K,
                                   dtype=jnp.float32)
            # quantization error bounded by half a step per group
            assert float(jnp.abs(wd - w).max()) <= \
                float(jnp.abs(w).max()) / (254 if bits == 8 else 14) + 1e-6
            for impl in ("xla", "pallas"):  # pallas: interpret mode on CPU
                y = wq_matmul(x, codes, scale, bits=bits, group=64, impl=impl)
                np.testing.assert_allclose(np.asarray(y), np.asarray(x @ wd),
                                           rtol=2e-5, atol=2e-5,
                                           err_msg=f"{bits}b {impl}")


@pytest.mark.parametrize("bits", [8, 4])
def test_v2_engine_generates_with_quantized_weights(bits):
    """The paged engine generates with int8/int4 weights: logits close to
    bf16, weight bytes measurably lower."""
    from deepspeed_tpu.models.llama import llama_model

    model = llama_model("tiny", max_seq_len=64, attn_impl="xla")
    params = model.init_params(jax.random.PRNGKey(0))
    cfg = RaggedInferenceConfig(dtype="fp32", page_size=8, num_pages=32,
                                max_seqs=2, max_pages_per_seq=8)
    qcfg = RaggedInferenceConfig(dtype="fp32", page_size=8, num_pages=32,
                                 max_seqs=2, max_pages_per_seq=8,
                                 quant_bits=bits, quant_group=64,
                                 quant_min_size=1024)  # tiny test matrices
    e_fp = InferenceEngineV2(model, cfg, params=params)
    e_q = InferenceEngineV2(model, qcfg, params=params)
    # flags stay on the engine's own config copy
    assert model.config.wq_bits == 0
    # HBM at rest: int8 ~2x lower, int4 ~4x lower on the quantized leaves
    assert e_q.param_bytes < e_fp.param_bytes * (0.72 if bits == 8 else 0.6)

    prompt = list(range(1, 20))
    from deepspeed_tpu.inference.v2.model_runner import paged_prefill
    ids = np.zeros((32,), np.int32)
    ids[:len(prompt)] = prompt
    rows = np.arange(4, dtype=np.int32)
    lf, _ = paged_prefill(e_fp.cfg, e_fp.params, e_fp._pools,
                          jnp.asarray(ids), jnp.asarray(rows),
                          jnp.int32(len(prompt)))
    lq, _ = paged_prefill(e_q.cfg, e_q.params, e_q._pools,
                          jnp.asarray(ids), jnp.asarray(rows),
                          jnp.int32(len(prompt)))
    lf, lq = np.asarray(lf, np.float64), np.asarray(lq, np.float64)
    cos = float((lf * lq).sum() / (np.linalg.norm(lf) * np.linalg.norm(lq)))
    assert cos > (0.999 if bits == 8 else 0.98), cos

    out = e_q.generate_all([RaggedRequest(prompt_ids=prompt, max_new_tokens=8)])
    toks = list(out.values())[0]
    assert len(toks) == 8 and all(0 <= t < 256 for t in toks)


def test_kv_quant_int8_pool(monkeypatch):
    """int8 KV pages: pool bytes < half of fp32, prefill logits exact
    (storage-only quantization), decode logits close to the fp pool."""
    from deepspeed_tpu.inference.v2.model_runner import (paged_decode,
                                                         paged_prefill)

    model = llama_model("tiny", max_seq_len=256)
    params = model.init_params(jax.random.PRNGKey(0))
    mk = lambda **kw: InferenceEngineV2(model, RaggedInferenceConfig(  # noqa: E731
        dtype="fp32", page_size=8, num_pages=32, max_seqs=2,
        max_pages_per_seq=8, **kw), params=params)
    e_fp, e_q = mk(), mk(kv_quant=True)
    nbytes = lambda pools: sum(x.size * x.dtype.itemsize  # noqa: E731
                               for x in jax.tree_util.tree_leaves(pools))
    assert nbytes(e_q._pools) < nbytes(e_fp._pools) * 0.5

    prompt = list(np.random.RandomState(6).randint(0, 256, 13))
    ids = np.zeros((16,), np.int32)
    ids[:13] = prompt
    rows = np.arange(2, dtype=np.int32)
    args = (jnp.asarray(ids), jnp.asarray(rows), jnp.int32(13))
    lf, pools_fp = paged_prefill(e_fp.cfg, e_fp.params, e_fp._pools, *args)
    lq, pools_q = paged_prefill(e_q.cfg, e_q.params, e_q._pools, *args)
    np.testing.assert_allclose(np.asarray(lf), np.asarray(lq), rtol=1e-5,
                               atol=1e-5)  # prefill attends pre-quant k/v

    table = np.full((2, 8), e_fp.block.trash_page, np.int32)
    table[0, :2] = rows
    tok = jnp.asarray([int(np.argmax(np.asarray(lf))), 0], jnp.int32)
    dargs = (tok, jnp.asarray([13, 0], jnp.int32), jnp.asarray(table),
             jnp.asarray([True, False]))
    df, _ = paged_decode(e_fp.cfg, e_fp.params, pools_fp, *dargs)
    dq, _ = paged_decode(e_q.cfg, e_q.params, pools_q, *dargs)
    a, b = np.asarray(df[0], np.float64), np.asarray(dq[0], np.float64)
    cos = float((a * b).sum() / (np.linalg.norm(a) * np.linalg.norm(b)))
    assert cos > 0.999, cos

    # end-to-end generation with quantized KV completes
    out = e_q.generate_all([RaggedRequest(prompt_ids=prompt, max_new_tokens=6)])
    assert len(list(out.values())[0]) == 6


def test_on_device_temperature_sampling_reproducible():
    """Decode samples on device (Gumbel-max in the jitted program): same
    seed => same generation; valid token ids; greedy unaffected."""
    model = llama_model("tiny", max_seq_len=128)
    params = model.init_params(jax.random.PRNGKey(0))
    prompt = list(range(1, 17))

    def gen(seed, temp):
        eng = InferenceEngineV2(model, RaggedInferenceConfig(
            page_size=16, num_pages=32, max_seqs=2, max_pages_per_seq=4),
            params=params, seed=seed)
        got = eng.generate_all([RaggedRequest(prompt_ids=prompt,
                                              max_new_tokens=12,
                                              temperature=temp)])
        return list(got.values())[0]

    a = gen(7, 0.8)
    b = gen(7, 0.8)
    c = gen(8, 0.8)
    assert a == b, "same seed must reproduce"
    assert all(0 <= t < model.config.vocab_size for t in a)
    assert len(a) == 12
    # different seed: overwhelmingly likely to diverge somewhere at T=0.8
    assert a != c or len(set(a)) == 1


@pytest.mark.parametrize("kernel", ["0", "1"])
def test_chunked_prefill_matches_whole_prompt(kernel, monkeypatch):
    monkeypatch.setenv("DSTPU_PAGED_KERNEL", kernel)
    """Dynamic-SplitFuse-style chunked prefill (prefill_chunk > 0): long
    prompts processed in page-aligned chunks, decode interleaving between
    chunks — generations must equal the whole-prompt path exactly, and
    the number of engine steps a long prompt can monopolize must drop to
    ceil(len/chunk) chunk-steps with other sequences decoding between."""
    model = llama_model("tiny", max_seq_len=256)
    params = model.init_params(jax.random.PRNGKey(0))
    rng = np.random.RandomState(5)
    prompts = [list(rng.randint(0, model.config.vocab_size, n))
               for n in (37, 9, 52)]
    wants = [_dense_greedy(model, params, p, 6) for p in prompts]

    eng = InferenceEngineV2(model, RaggedInferenceConfig(
        dtype="fp32", page_size=8, num_pages=64, max_seqs=4,
        max_pages_per_seq=8, prefill_chunk=16), params=params)
    got = eng.generate_all(
        [RaggedRequest(prompt_ids=p, max_new_tokens=6) for p in prompts])
    for uid, want in enumerate(wants):
        assert got[uid] == want, (uid, got[uid], want)


def test_chunked_prefill_interleaves_decode():
    """While a long prompt chunk-prefills, an already-running sequence
    keeps generating: the long prompt must NOT stall running streams for
    its whole prefill (the FastGen latency property, host-observable)."""
    model = llama_model("tiny", max_seq_len=256)
    params = model.init_params(jax.random.PRNGKey(0))
    rng = np.random.RandomState(6)
    short = list(rng.randint(0, model.config.vocab_size, 4))
    long = list(rng.randint(0, model.config.vocab_size, 60))

    eng = InferenceEngineV2(model, RaggedInferenceConfig(
        dtype="fp32", page_size=8, num_pages=64, max_seqs=4,
        max_pages_per_seq=8, prefill_chunk=16), params=params)
    u_short = eng.put(RaggedRequest(prompt_ids=short, max_new_tokens=20))
    got = {u_short: []}
    for uid, rec in eng.step().items():  # short admitted+prefilled: token 1
        got[uid].extend(rec["tokens"])
    u_long = eng.put(RaggedRequest(prompt_ids=long, max_new_tokens=2))
    got[u_long] = []
    # 60-token prompt at chunk 16 = 4 chunk-steps; the short stream must
    # receive a token on EVERY one of those steps (no prefill stall)
    for i in range(4):
        res = eng.step()
        assert u_short in res and res[u_short]["tokens"], (i, res)
        for uid, rec in res.items():
            got[uid].extend(rec["tokens"])
    assert got[u_long], "long prompt should have sampled by chunk 4"
    while eng.has_work():
        for uid, rec in eng.step().items():
            got[uid].extend(rec["tokens"])
    assert got[u_short] == _dense_greedy(model, params, short, 20)
    assert got[u_long] == _dense_greedy(model, params, long, 2)
