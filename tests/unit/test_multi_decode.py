"""Fused multi-step decode (docs/SERVING.md "Multi-step decode").

Fast tier: the pure horizon-scheduling arithmetic (headroom pages,
halving-chain shrink, deadline clamp), the allocator's headroom
reservation API, config validation, and the hazard-lint fixture (a
host sync seeded INSIDE the horizon scheduling loop still fails by
name).

Slow tier: engine oracles — the headline contract is that a K-step
fused dispatch is BIT-IDENTICAL to K single steps, greedy and sampled
alike, across {plain, prefix cache, chunked prefill, kv_quant,
kv_tier, mid-horizon EOS, mid-horizon deadline, preemption recovery,
pool-pressure horizon shrink} — plus the speculative stand-down guard.
"""

import importlib.util
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from deepspeed_tpu.inference.v2.engine_v2 import (  # noqa: E402
    _deadline_clamp, _horizon_pages_needed, _shrink_horizon)
from deepspeed_tpu.inference.v2.ragged import BlockAllocator  # noqa: E402
from deepspeed_tpu.serving.config import ServingConfig  # noqa: E402


# ------------------------------------------------ fast: pure scheduling math
def test_horizon_pages_needed():
    # the t-th emitted token writes KV at position length - 2 + t
    ps = 8
    # one pending token at position length-1: the page the _step_impl
    # boundary loop already guarantees
    assert _horizon_pages_needed(17, 1, ps) == 3   # position 16: 3 pages
    assert _horizon_pages_needed(17, 8, ps) == 3   # position 23 still fits
    assert _horizon_pages_needed(16, 1, ps) == 2   # position 15: 2 pages
    assert _horizon_pages_needed(16, 2, ps) == 3   # position 16 crosses
    # budget exactly filling a page boundary
    assert _horizon_pages_needed(10, 8, 4) == 5    # position 16 -> page 5


def test_shrink_horizon_walks_the_halving_chain():
    assert _shrink_horizon(8, 8) == 8
    assert _shrink_horizon(8, 5) == 8     # 4 < 5: stay at 8
    assert _shrink_horizon(8, 4) == 4
    assert _shrink_horizon(8, 3) == 4
    assert _shrink_horizon(8, 2) == 2
    assert _shrink_horizon(8, 1) == 1
    assert _shrink_horizon(1, 1) == 1
    # non-power-of-two chains still land on chain values only
    assert _shrink_horizon(6, 2) == 2     # 6 -> 3 -> 2
    assert _shrink_horizon(6, 3) == 3
    # cap 0 / degenerate floors at 1, never 0
    assert _shrink_horizon(8, 0) == 1


def test_deadline_clamp():
    # no TPOT estimate yet (first dispatch): budget passes through
    assert _deadline_clamp(8, 0.001, None) == 8
    assert _deadline_clamp(8, 0.001, 0.0) == 8
    # deadline lands mid-horizon: only the tokens that fit remain
    assert _deadline_clamp(8, 0.05, 0.01) == 5
    assert _deadline_clamp(8, 1.0, 0.01) == 8   # deadline far out
    # floor 1: a single step would emit one token too
    assert _deadline_clamp(8, 0.0, 0.01) == 1
    assert _deadline_clamp(8, -5.0, 0.01) == 1


def test_allocator_try_alloc_headroom_reservation():
    a = BlockAllocator(4)
    assert a.try_alloc(5) is None          # refused, allocator untouched
    assert a.free_pages == 4
    pages = a.try_alloc(3)
    assert pages is not None and len(pages) == 3
    assert a.free_pages == 1
    assert a.try_alloc(2) is None          # refused again
    assert a.free_pages == 1
    a.free(pages)
    a.assert_no_leaks()


def test_try_alloc_uncached_only_never_evicts_prefix_cache():
    """Horizon headroom backs tokens a row may never produce: with
    ``uncached_only=True`` the reservation spends TRULY-free pages only
    — a request covered only by evicting LRU-parked prefix-cache
    content is refused (the engine shrinks the horizon instead), while
    the plain budget would have granted it."""
    a = BlockAllocator(4)
    pages = a.alloc(2)
    a.register(pages[0], b"key0")
    a.free(pages)                      # page 0 parks in the LRU
    assert a.lru_pages == 1 and a.uncached_free_pages == 3
    assert a.try_alloc(4, uncached_only=True) is None
    assert a.lru_pages == 1            # cache content untouched
    got = a.try_alloc(3, uncached_only=True)
    assert got is not None and a.lru_pages == 1
    a.free(got)
    # the plain budget MAY claim the LRU page (the K=1 pending-token
    # path): it evicts the cached page to serve the request
    got = a.try_alloc(4)
    assert got is not None and a.lru_pages == 0
    a.free(got)
    a.assert_no_leaks()


def test_serving_config_decode_horizon_validation():
    ServingConfig(decode_horizon=None).validate()
    ServingConfig(decode_horizon=1).validate()
    ServingConfig(decode_horizon=8).validate()
    with pytest.raises(ValueError, match="decode_horizon"):
        ServingConfig(decode_horizon=0).validate()


# --------------------------------------------------- fast: hazard-lint fixture
def _hazard_lint():
    path = os.path.join(REPO, "deepspeed_tpu", "analysis", "lint.py")
    if "dstpu_hazard_lint" in sys.modules:
        return sys.modules["dstpu_hazard_lint"]
    spec = importlib.util.spec_from_file_location("dstpu_hazard_lint", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["dstpu_hazard_lint"] = mod
    spec.loader.exec_module(mod)
    return mod


def test_lint_catches_sync_inside_horizon_scheduling_loop(tmp_path):
    """The multi-step acceptance mutation: a ``.item()`` (or
    ``device_get``) seeded INSIDE the horizon scheduling helper — which
    _step_impl reaches through the same-file call graph — still fails
    the hazard lint BY NAME, even though the designed ``[B, K]`` pull
    moved into ``_multi_decode``."""
    hl = _hazard_lint()
    p = tmp_path / "deepspeed_tpu" / "inference" / "v2" / "engine_v2.py"
    p.parent.mkdir(parents=True)
    p.write_text(
        "def _step_impl(self):\n"
        "    self._multi_decode([], {})\n"
        "def _multi_decode(self, seqs, out):\n"
        "    for seq in seqs:\n"
        "        k = budgets.item()\n"
        "    return out\n")
    (tmp_path / "tools").mkdir()
    violations = hl.check(str(tmp_path))
    assert len(violations) == 1, violations
    v = violations[0]
    assert v.rule == "host-sync" and ".item()" in v.message
    assert "_multi_decode" in v.message
    # jax.device_get seeded the same way also fails
    p.write_text(
        "import jax\n"
        "def _step_impl(self):\n"
        "    self._multi_decode([], {})\n"
        "def _multi_decode(self, seqs, out):\n"
        "    toks = jax.device_get(out)\n"
        "    return toks\n")
    violations = hl.check(str(tmp_path))
    assert [v.rule for v in violations] == ["host-sync"]
    assert "jax.device_get" in violations[0].message


def test_package_multi_decode_pull_is_the_annotated_sync():
    """The shipped tree lints clean, and the horizon's [B,K] pull
    carries its own documented allow marker (the annotation moved WITH
    the sync, reason updated)."""
    hl = _hazard_lint()
    assert hl.check(REPO) == []
    rel = os.path.join("deepspeed_tpu", "inference", "v2", "engine_v2.py")
    marks = [(ln, rules, reason) for f, ln, rules, reason
             in hl.suppressions(REPO) if f == rel]
    horizon_marks = [r for _ln, rules, r in marks
                     if "host-sync" in rules and "horizon" in r]
    assert horizon_marks, marks


# ----------------------------- slow: engine oracles -------------------------
jax = pytest.importorskip("jax")

from deepspeed_tpu.inference.v2 import (  # noqa: E402
    InferenceEngineV2, RaggedInferenceConfig, RaggedRequest,
    SpeculativeConfig)
from deepspeed_tpu.models.llama import llama_model  # noqa: E402
from deepspeed_tpu.serving.config import KVTierConfig  # noqa: E402
from deepspeed_tpu.telemetry import get_registry  # noqa: E402


@pytest.fixture(scope="module")
def model_and_params():
    model = llama_model("tiny", max_seq_len=256)
    return model, model.init_params(jax.random.PRNGKey(0))


def _drive(eng, reqs, max_steps=500):
    """put + step loop, collecting streams AND finish reasons."""
    uids = [eng.put(r) for r in reqs]
    toks = {u: [] for u in uids}
    fin = {}
    for _ in range(max_steps):
        if not eng.has_work():
            break
        for u, rec in eng.step().items():
            toks[u].extend(rec["tokens"])
            if rec.get("done"):
                fin[u] = rec.get("finish_reason")
    return [toks[u] for u in uids], [fin.get(u) for u in uids]


_CONFIGS = {
    "plain": {},
    "prefix_cache": {"enable_prefix_cache": True},
    "chunked_prefill": {"prefill_chunk": 16},
    "kv_quant": {"kv_quant": True},
}


@pytest.mark.slow
@pytest.mark.parametrize("name", sorted(_CONFIGS))
def test_fused_horizon_bit_identical_to_single_step(name, model_and_params):
    """The headline contract: K-step fused decode == K single steps,
    token for token, across the engine's feature matrix."""
    model, params = model_and_params
    rng = np.random.RandomState(11)
    vocab = model.config.vocab_size
    prompts = [list(rng.randint(1, vocab, n)) for n in (13, 29, 7, 40)]
    # the page-aligned prompt resubmitted verbatim: under prefix_cache
    # it is a FULL hit — the copy-on-write decode-entry row samples its
    # first token through the fused scan's first iteration
    prompts.append(list(prompts[3]))

    def run(h):
        eng = InferenceEngineV2(model, RaggedInferenceConfig(
            dtype="fp32", page_size=8, num_pages=96, max_seqs=4,
            max_pages_per_seq=16, decode_horizon=h, **_CONFIGS[name]),
            params=params)
        got, fin = _drive(eng, [RaggedRequest(prompt_ids=p,
                                              max_new_tokens=17)
                                for p in prompts])
        eng.assert_no_leaks()
        eng.close()
        return got, fin

    g1, f1 = run(1)
    g8, f8 = run(8)
    assert g1 == g8
    assert f1 == f8 == ["length"] * 5


@pytest.mark.slow
def test_fused_horizon_bit_identical_under_kv_tier(model_and_params):
    """Horizons compose with the host-RAM KV tier: two prefix families
    cycling through a capped device cache spill & restore, and the
    fused streams still match the K=1 run bit for bit."""
    model, params = model_and_params
    rng = np.random.RandomState(13)
    vocab = model.config.vocab_size
    fams = [list(rng.randint(1, vocab, 16)) for _ in range(2)]
    waves = []
    for _round in range(2):
        for f in fams:
            waves.append([f + list(rng.randint(1, vocab, 3 + i))
                          for i in range(2)])

    def run(h):
        eng = InferenceEngineV2(model, RaggedInferenceConfig(
            dtype="fp32", page_size=8, num_pages=40, max_seqs=2,
            max_pages_per_seq=12, decode_horizon=h,
            enable_prefix_cache=True, prefix_cache_pages=3,
            kv_tier=KVTierConfig(enabled=True)), params=params)
        out = []
        for wave in waves:
            got, _ = _drive(eng, [RaggedRequest(prompt_ids=p,
                                                max_new_tokens=9)
                                  for p in wave])
            out.append(got)
        stats = eng.tier_stats()
        eng.flush_spills()
        eng.assert_no_leaks()
        eng.close()
        return out, stats

    g1, _ = run(1)
    g8, st8 = run(8)
    assert g1 == g8
    assert st8["spilled_pages"] > 0 and st8["restored_pages"] > 0, st8


@pytest.mark.slow
def test_mid_horizon_eos_stops_in_scan(model_and_params):
    """A row hitting EOS mid-horizon emits the EOS token and stops —
    in-scan — exactly where the K=1 loop retires it; trailing scan
    iterations must not leak tokens past it."""
    model, params = model_and_params
    rng = np.random.RandomState(17)
    vocab = model.config.vocab_size
    prompts = [list(rng.randint(1, vocab, n)) for n in (12, 21)]

    def run(h, eos=None):
        eng = InferenceEngineV2(model, RaggedInferenceConfig(
            dtype="fp32", page_size=8, num_pages=64, max_seqs=2,
            max_pages_per_seq=16, decode_horizon=h), params=params)
        got, fin = _drive(eng, [RaggedRequest(prompt_ids=p,
                                              max_new_tokens=20,
                                              eos_id=eos)
                                for p in prompts])
        eng.assert_no_leaks()
        eng.close()
        return got, fin

    ref, _ = run(1)
    # pick a token that appears mid-stream (not at a horizon boundary)
    eos = ref[0][2]
    g1, f1 = run(1, eos=eos)
    g8, f8 = run(8, eos=eos)
    assert g1 == g8
    assert f1 == f8
    assert f8[0] == "eos" and g8[0][-1] == eos
    assert len(g8[0]) < len(ref[0])  # it really stopped early


@pytest.mark.slow
def test_mid_horizon_deadline_expires_without_overshoot(model_and_params):
    """A deadline landing mid-horizon clamps the row's effective K (the
    TPOT-estimate clamp) and the boundary sweep expires it with
    ``finish_reason="deadline"``; the emitted tokens are a prefix of
    the undeadlined stream (bit-identity holds right up to expiry)."""
    model, params = model_and_params
    rng = np.random.RandomState(19)
    vocab = model.config.vocab_size
    prompt = list(rng.randint(1, vocab, 12))

    def engine():
        return InferenceEngineV2(model, RaggedInferenceConfig(
            dtype="fp32", page_size=8, num_pages=64, max_seqs=2,
            max_pages_per_seq=16, decode_horizon=8), params=params)

    eng = engine()
    ref, _ = _drive(eng, [RaggedRequest(prompt_ids=prompt,
                                        max_new_tokens=120)])
    eng.close()

    eng = engine()
    # warm the horizon programs + the TPOT estimate on a short request,
    # then a deadlined one: its budget clamps mid-horizon
    _drive(eng, [RaggedRequest(prompt_ids=prompt[:8], max_new_tokens=12)])
    got, fin = _drive(eng, [RaggedRequest(prompt_ids=prompt,
                                          max_new_tokens=120,
                                          deadline_s=0.03)])
    assert eng._tpot_ema is not None and eng._tpot_ema > 0.0
    eng.assert_no_leaks()
    eng.close()
    assert fin == ["deadline"]
    assert 0 < len(got[0]) < 120
    assert got[0] == ref[0][:len(got[0])]  # a prefix, never divergent


@pytest.mark.slow
@pytest.mark.parametrize("temperature", [0.0, 0.8])
def test_preemption_recovery_matches_single_step(temperature,
                                                 model_and_params):
    """KV-pool pressure preempting a running sequence (recompute on
    re-admission) composes with the fused horizon: streams still match
    the K=1 run — SAMPLED rows included, because the sampling fold is
    keyed by request uid, not by whichever slot the re-admission found."""
    model, params = model_and_params
    rng = np.random.RandomState(23)
    vocab = model.config.vocab_size
    prompts = [list(rng.randint(1, vocab, 25)) for _ in range(3)]
    preempt = get_registry().counter(
        "deepspeed_tpu_serving_preemptions_total",
        "sequences evicted to the queue under KV-pool pressure")

    def run(h):
        p0 = preempt.total()
        eng = InferenceEngineV2(model, RaggedInferenceConfig(
            dtype="fp32", page_size=8, num_pages=14, max_seqs=2,
            max_pages_per_seq=10, decode_horizon=h), params=params)
        got, fin = _drive(eng, [RaggedRequest(prompt_ids=p,
                                              max_new_tokens=16,
                                              temperature=temperature)
                                for p in prompts])
        eng.assert_no_leaks()
        eng.close()
        return got, fin, preempt.total() - p0

    g1, f1, _n1 = run(1)
    g8, f8, _n8 = run(8)
    assert g1 == g8 and f1 == f8


@pytest.mark.slow
def test_horizon_shrinks_under_pool_pressure_not_preempts(model_and_params):
    """When the pool cannot cover the full horizon's headroom the
    dispatch SHRINKS along the halving chain (counted) instead of
    preempting mid-scan — and stays bit-identical to K=1."""
    model, params = model_and_params
    rng = np.random.RandomState(29)
    vocab = model.config.vocab_size
    prompts = [list(rng.randint(1, vocab, 10)) for _ in range(2)]

    def run(h):
        eng = InferenceEngineV2(model, RaggedInferenceConfig(
            dtype="fp32", page_size=4, num_pages=9, max_seqs=2,
            max_pages_per_seq=8, decode_horizon=h), params=params)
        got, _ = _drive(eng, [RaggedRequest(prompt_ids=p,
                                            max_new_tokens=12)
                              for p in prompts])
        st = eng.decode_stats()
        eng.assert_no_leaks()
        eng.close()
        return got, st

    g1, st1 = run(1)
    g8, st8 = run(8)
    assert g1 == g8
    assert st1["decode_horizon_shrinks"] == 0
    assert st8["decode_horizon_shrinks"] > 0, st8
    assert st8["decode_host_syncs"] < st1["decode_host_syncs"]


@pytest.mark.slow
def test_sampled_rows_identical_across_horizons(model_and_params):
    """The per-(request uid, position) key fold: SAMPLED streams — not
    just greedy — are bit-identical across decode horizons."""
    model, params = model_and_params
    rng = np.random.RandomState(31)
    vocab = model.config.vocab_size
    prompts = [list(rng.randint(1, vocab, n)) for n in (9, 14, 11)]

    def run(h):
        eng = InferenceEngineV2(model, RaggedInferenceConfig(
            dtype="fp32", page_size=8, num_pages=64, max_seqs=4,
            max_pages_per_seq=16, decode_horizon=h), params=params,
            seed=5)
        got, _ = _drive(eng, [RaggedRequest(prompt_ids=p,
                                            max_new_tokens=13,
                                            temperature=0.8)
                              for p in prompts])
        eng.close()
        return got

    a = run(1)
    b = run(8)
    assert a == b
    assert all(0 <= t < vocab for s in a for t in s)


@pytest.mark.slow
def test_speculative_engine_stands_horizon_down(model_and_params):
    """One designed exclusive decode path at a time: a configured
    proposer wins and the horizon stands down LOUDLY to 1."""
    import io
    import logging

    from deepspeed_tpu.utils.logging import logger as ds_logger

    model, params = model_and_params
    buf = io.StringIO()
    handler = logging.StreamHandler(buf)
    ds_logger.addHandler(handler)
    try:
        eng = InferenceEngineV2(model, RaggedInferenceConfig(
            dtype="fp32", page_size=8, num_pages=64, max_seqs=2,
            max_pages_per_seq=16, decode_horizon=8,
            speculative=SpeculativeConfig(mode="ngram", k=4)),
            params=params)
    finally:
        ds_logger.removeHandler(handler)
    assert eng._horizon == 1 and eng._multi is None
    assert "stands down" in buf.getvalue()
    # and the engine still serves correctly through the verify path
    got = eng.generate_all([RaggedRequest(
        prompt_ids=[1, 2, 3, 4, 1, 2, 3, 4], max_new_tokens=6)])
    assert len(list(got.values())[0]) == 6
    eng.assert_no_leaks()
    eng.close()


@pytest.mark.slow
def test_decode_horizon_validation(model_and_params):
    model, params = model_and_params
    with pytest.raises(ValueError, match="decode_horizon"):
        InferenceEngineV2(model, RaggedInferenceConfig(
            decode_horizon=0), params=params)
