"""Expert-parallel MoE dispatch (moe/ep_dispatch.py): the explicit
all-to-all shard_map path vs the SPMD einsum/sort path.

Reference behavior being pinned: expert compute runs behind an all-to-all
inside the expert-parallel group (deepspeed/moe/sharded_moe.py:96
``_AllToAll``) so expert-weight grads are BORN expert-sharded — the SPMD
formulation instead hits XLA's "involuntary full rematerialization" on
the expert-weight grad scatter under EP + ZeRO-2/3 (docs/PERF_NOTES.md).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.moe.sharded_moe import MoEConfig, moe_ffn
from deepspeed_tpu.parallel.mesh import initialize_topology, reset_topology
from deepspeed_tpu.runtime.config import MeshConfig

B, S, H, F, E = 8, 4, 16, 24, 4


def _inputs(seed=0):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(B, S, H).astype(np.float32))
    gate_w = jnp.asarray(rng.randn(H, E).astype(np.float32) * 0.1)
    experts = {k: jnp.asarray(rng.randn(E, H, F).astype(np.float32) * 0.1)
               for k in ("w_gate", "w_up")}
    experts["w_down"] = jnp.asarray(rng.randn(E, F, H).astype(np.float32) * 0.1)
    return x, gate_w, experts


def _spmd_then_ep(cfg, devices, mesh_cfg=None):
    x, gate_w, experts = _inputs()
    reset_topology()
    out_s, aux_s = moe_ffn(x, gate_w, experts,
                           dataclasses.replace(cfg, ep_dispatch="spmd"))
    initialize_topology(mesh_cfg or MeshConfig(expert=2, data=2), devices[:4])
    out_e, aux_e = moe_ffn(x, gate_w, experts, cfg)
    return out_s, aux_s, out_e, aux_e


def test_ep_dropless_matches_spmd_exactly(devices8):
    """Dropless routing is per-token deterministic: the all-to-all path
    must reproduce the SPMD path's output bit-for-bit (fp32 tolerance)."""
    cfg = MoEConfig(num_experts=E, top_k=2, drop_tokens=False)
    out_s, aux_s, out_e, aux_e = _spmd_then_ep(cfg, devices8)
    np.testing.assert_allclose(np.asarray(out_e), np.asarray(out_s),
                               rtol=1e-5, atol=1e-5)
    # aux: per-rank mean (reference multi-rank semantics) vs global
    # product-of-means — close on balanced data, not identical
    assert abs(float(aux_e) - float(aux_s)) < 0.3 * abs(float(aux_s)) + 1e-4


def test_ep_capacity_matches_spmd_when_nothing_drops(devices8):
    """With capacity ample enough that NO token drops under either the
    global or the per-rank position count, the two capacity paths agree."""
    cfg = MoEConfig(num_experts=E, top_k=2, drop_tokens=True,
                    capacity_factor=float(E))  # cap >= T*K per rank
    out_s, _, out_e, _ = _spmd_then_ep(cfg, devices8)
    np.testing.assert_allclose(np.asarray(out_e), np.asarray(out_s),
                               rtol=1e-5, atol=1e-5)


def test_ep_gelu_no_wgate(devices8):
    """Non-swiglu experts (no w_gate) ride the same dispatch."""
    x, gate_w, experts = _inputs()
    experts = {k: experts[k] for k in ("w_up", "w_down")}
    cfg = MoEConfig(num_experts=E, top_k=1, drop_tokens=False)
    reset_topology()
    out_s, _ = moe_ffn(x, gate_w, experts,
                       dataclasses.replace(cfg, ep_dispatch="spmd"),
                       activation="gelu")
    initialize_topology(MeshConfig(expert=2, data=2), devices8[:4])
    out_e, _ = moe_ffn(x, gate_w, experts, cfg, activation="gelu")
    np.testing.assert_allclose(np.asarray(out_e), np.asarray(out_s),
                               rtol=1e-5, atol=1e-5)


def test_ep_grads_match_and_born_expert_sharded(devices8):
    """The deliverable: expert-weight grads through the EP path (a) equal
    the SPMD path's grads and (b) come out of the compiled program already
    sharded over the expert axis, with the dispatch pinned as all-to-all
    in the HLO — no partitioner-driven resharding of the cotangent."""
    x, gate_w, experts = _inputs()
    cfg = MoEConfig(num_experts=E, top_k=2, drop_tokens=False)

    def loss(ex, mode):
        o, _ = moe_ffn(x, gate_w, ex,
                       dataclasses.replace(cfg, ep_dispatch=mode))
        return jnp.sum(o * o)

    reset_topology()
    g_spmd = jax.grad(lambda ex: loss(ex, "spmd"))(experts)

    topo = initialize_topology(MeshConfig(expert=2, data=2), devices8[:4])
    ex_sharded = {
        k: jax.device_put(v, NamedSharding(topo.mesh, P("expert", None, None)))
        for k, v in experts.items()}
    gf = jax.jit(jax.grad(lambda ex: loss(ex, "auto")))
    g_ep = gf(ex_sharded)
    for k in g_spmd:
        np.testing.assert_allclose(np.asarray(g_ep[k]), np.asarray(g_spmd[k]),
                                   rtol=1e-4, atol=1e-6, err_msg=k)
        spec_axes = [a for s in g_ep[k].sharding.spec if s
                     for a in (s if isinstance(s, tuple) else (s,))]
        assert "expert" in spec_axes, (k, g_ep[k].sharding)
    hlo = gf.lower(ex_sharded).compile().as_text()
    assert "all-to-all" in hlo, "EP dispatch not lowered to all-to-all"


@pytest.mark.slow
def test_ep_dropless_stage2_no_involuntary_remat(devices8, capfd):
    """End-to-end: dropless mixtral, expert2 x data4, ZeRO-2 — the exact
    composition that used to trigger XLA's 'Involuntary full
    rematerialization' on the expert-weight grad scatter.  The EP
    all-to-all path must compile clean and train."""
    import deepspeed_tpu
    from deepspeed_tpu.models import mixtral_model

    model = mixtral_model("tiny", max_seq_len=32, moe_drop_tokens=False)
    config = {"train_micro_batch_size_per_gpu": 8,
              "optimizer": {"type": "Adam", "params": {"lr": 5e-3}},
              "bf16": {"enabled": True},
              "mesh": {"expert": 2, "data": -1},
              "zero_optimization": {"stage": 2}}
    engine, *_ = deepspeed_tpu.initialize(model=model, config=config)
    ids = np.random.RandomState(0).randint(0, 256, (1, 8, 32)).astype(np.int32)
    batch = {"input_ids": jnp.asarray(ids)}
    losses = [float(engine.train_batch(batch)) for _ in range(5)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0], losses
    err = capfd.readouterr().err
    assert "Involuntary full rematerialization" not in err


def test_ep_uneven_tp_ffn_falls_back_to_spmd(devices8):
    """EP + TP with an FFN dim that does not divide the model axis must
    fall back to the SPMD path (GSPMD handles uneven shardings) instead of
    failing shard_map spec validation."""
    rng = np.random.RandomState(2)
    Fo = 25  # not divisible by model=2
    x = jnp.asarray(rng.randn(B, S, H).astype(np.float32))
    gate_w = jnp.asarray(rng.randn(H, E).astype(np.float32) * 0.1)
    experts = {k: jnp.asarray(rng.randn(E, H, Fo).astype(np.float32) * 0.1)
               for k in ("w_gate", "w_up")}
    experts["w_down"] = jnp.asarray(rng.randn(E, Fo, H).astype(np.float32) * 0.1)
    cfg = MoEConfig(num_experts=E, top_k=2, drop_tokens=False)
    reset_topology()
    out_s, _ = moe_ffn(x, gate_w, experts,
                       dataclasses.replace(cfg, ep_dispatch="spmd"))
    initialize_topology(MeshConfig(expert=2, data=2, model=2), devices8)
    out_e, _ = moe_ffn(x, gate_w, experts, cfg)  # must not raise
    np.testing.assert_allclose(np.asarray(out_e), np.asarray(out_s),
                               rtol=1e-5, atol=1e-5)
