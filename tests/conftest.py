"""Test harness configuration.

The reference simulates multi-node as multi-process-single-host with a
file-store rendezvous (tests/unit/common.py DistributedTest).  The TPU
analogue: ONE process with 8 virtual CPU devices
(``--xla_force_host_platform_device_count``) and real XLA collectives over a
``jax.sharding.Mesh`` — the "Gloo-equivalent" device-free CI mode
(SURVEY.md §4).
"""

import os

# Must happen before any CPU backend is created.  Tests always run on the
# virtual CPU mesh (set DSTPU_TEST_PLATFORM to override, e.g. to run on a
# real chip).  jax.config.update is needed (not just the env var) because a
# site plugin may have already pinned jax_platforms.
_platform = os.environ.get("DSTPU_TEST_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _platform
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import sys  # noqa: E402

# CI wrappers run this suite under `timeout ... | tee log` and count
# progress dots from the log.  Two buffering layers can eat that
# progress when the timeout SIGTERMs the interpreter mid-run: the plain
# stdio block buffer, and — with pytest's default fd-capture — the
# dup'd stream the terminal reporter writes through (which `python -u`
# does NOT reach).  Line-buffer the visible streams here, and flush the
# terminal reporter after every test below, so every completed test's
# dot is already on disk when the axe falls.
for _stream in (sys.stdout, sys.stderr):
    try:
        _stream.reconfigure(line_buffering=True)
    except (AttributeError, ValueError):
        pass

import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", _platform)

_terminal_reporter = None


def pytest_configure(config):
    global _terminal_reporter
    _terminal_reporter = config.pluginmanager.get_plugin("terminalreporter")


@pytest.hookimpl(trylast=True)
def pytest_runtest_logreport(report):
    # runs on every phase report; by teardown the test's progress dot has
    # been written to the reporter's (possibly capture-dup'd) stream
    if report.when == "teardown" and _terminal_reporter is not None:
        try:
            _terminal_reporter._tw.flush()
        except Exception:
            pass


# The <2-minute smoke tier for perf-round edit loops (README "Testing"):
# engine/config/mesh cores in full plus one representative each from the
# pipeline, MoE-EP and ZeRO-3 structural suites.  Run: pytest -m smoke
_SMOKE = (
    "unit/test_engine.py",
    "unit/test_config.py",
    "unit/test_mesh_and_comm.py",
    "unit/test_pipeline.py::test_pipeline_loss_matches_dense",
    "unit/test_pipeline.py::test_partition_balanced",
    "unit/test_moe_ep.py::test_ep_dropless_matches_spmd_exactly",
    "unit/test_zeropp.py::test_stage3_gathers_stay_inside_layer_loop",
)


def pytest_collection_modifyitems(config, items):
    for item in items:
        if any(item.nodeid.startswith(p) for p in _SMOKE):
            item.add_marker(pytest.mark.smoke)


@pytest.fixture(autouse=True)
def _reset_topology():
    """Each test builds its own mesh topology."""
    from deepspeed_tpu.parallel import mesh

    mesh.reset_topology()
    yield
    mesh.reset_topology()


@pytest.fixture
def devices8():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return devs[:8]
