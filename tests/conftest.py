"""Test harness configuration.

The reference simulates multi-node as multi-process-single-host with a
file-store rendezvous (tests/unit/common.py DistributedTest).  The TPU
analogue: ONE process with 8 virtual CPU devices
(``--xla_force_host_platform_device_count``) and real XLA collectives over a
``jax.sharding.Mesh`` — the "Gloo-equivalent" device-free CI mode
(SURVEY.md §4).
"""

import os

# Must happen before any CPU backend is created.  Tests always run on the
# virtual CPU mesh (set DSTPU_TEST_PLATFORM to override, e.g. to run on a
# real chip).  jax.config.update is needed (not just the env var) because a
# site plugin may have already pinned jax_platforms.
_platform = os.environ.get("DSTPU_TEST_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _platform
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", _platform)


@pytest.fixture(autouse=True)
def _reset_topology():
    """Each test builds its own mesh topology."""
    from deepspeed_tpu.parallel import mesh

    mesh.reset_topology()
    yield
    mesh.reset_topology()


@pytest.fixture
def devices8():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return devs[:8]
