// SIMD CPU Adam for host-offloaded optimizer state.
//
// TPU-native counterpart of the reference's AVX CPU-Adam
// (csrc/adam/cpu_adam_impl.cpp, csrc/includes/cpu_adam.h): the workhorse of
// ZeRO-Offload.  Vectorized with compiler auto-vectorization hints +
// explicit AVX2/AVX-512 paths, threaded with OpenMP, exposed as a plain C
// ABI consumed via ctypes (no pybind11 in this image).
//
// Semantics match ops/pallas/fused_adam.py (fp32 master params, decoupled
// or L2 weight decay, bias correction) so device and host paths are
// numerically interchangeable.

#include <cmath>
#include <cstddef>
#include <cstdint>

#if defined(__AVX512F__) || defined(__AVX2__)
#include <immintrin.h>
#endif

extern "C" {

// One fused Adam step over a contiguous fp32 shard.
// step is 1-based.  Returns 0 on success.
int dstpu_adam_step(float* params, const float* grads, float* exp_avg,
                    float* exp_avg_sq, int64_t n, int64_t step, float lr,
                    float beta1, float beta2, float eps, float weight_decay,
                    int adamw_mode, int bias_correction) {
  float bc1 = 1.0f, bc2 = 1.0f;
  if (bias_correction) {
    bc1 = 1.0f - std::pow(beta1, (float)step);
    bc2 = 1.0f - std::pow(beta2, (float)step);
  }
  const float step_size = lr / bc1;
  const float bc2_sqrt = std::sqrt(bc2);
  const float b1 = beta1, b2 = beta2;
  const float omb1 = 1.0f - beta1, omb2 = 1.0f - beta2;

#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    float g = grads[i];
    float p = params[i];
    if (weight_decay != 0.0f && !adamw_mode) g += weight_decay * p;
    float m = b1 * exp_avg[i] + omb1 * g;
    float v = b2 * exp_avg_sq[i] + omb2 * g * g;
    exp_avg[i] = m;
    exp_avg_sq[i] = v;
    float denom = std::sqrt(v) / bc2_sqrt + eps;
    // decoupled decay scales by lr, not lr/bias_correction1
    if (weight_decay != 0.0f && adamw_mode) p -= lr * weight_decay * p;
    params[i] = p - step_size * (m / denom);
  }
  return 0;
}

// Adam step where grads arrive in bf16 (as uint16 view) and a bf16 copy of
// the updated params is produced alongside the fp32 master — the exact
// data path of a bf16 ZeRO-Offload boundary (one pass, no temporaries).
int dstpu_adam_step_bf16g(float* params, const uint16_t* grads_bf16,
                          float* exp_avg, float* exp_avg_sq,
                          uint16_t* params_bf16_out, int64_t n, int64_t step,
                          float lr, float beta1, float beta2, float eps,
                          float weight_decay, int adamw_mode,
                          int bias_correction) {
  float bc1 = 1.0f, bc2 = 1.0f;
  if (bias_correction) {
    bc1 = 1.0f - std::pow(beta1, (float)step);
    bc2 = 1.0f - std::pow(beta2, (float)step);
  }
  const float step_size = lr / bc1;
  const float bc2_sqrt = std::sqrt(bc2);
  const float b1 = beta1, b2 = beta2;
  const float omb1 = 1.0f - beta1, omb2 = 1.0f - beta2;

#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    uint32_t gbits = ((uint32_t)grads_bf16[i]) << 16;
    float g;
    __builtin_memcpy(&g, &gbits, 4);
    float p = params[i];
    if (weight_decay != 0.0f && !adamw_mode) g += weight_decay * p;
    float m = b1 * exp_avg[i] + omb1 * g;
    float v = b2 * exp_avg_sq[i] + omb2 * g * g;
    exp_avg[i] = m;
    exp_avg_sq[i] = v;
    float denom = std::sqrt(v) / bc2_sqrt + eps;
    if (weight_decay != 0.0f && adamw_mode) p -= lr * weight_decay * p;
    p -= step_size * (m / denom);
    params[i] = p;
    // round-to-nearest-even bf16
    uint32_t pbits;
    __builtin_memcpy(&pbits, &p, 4);
    uint32_t rounded = (pbits + 0x7FFF + ((pbits >> 16) & 1)) >> 16;
    params_bf16_out[i] = (uint16_t)rounded;
  }
  return 0;
}

int dstpu_simd_width() {
#if defined(__AVX512F__)
  return 16;
#elif defined(__AVX2__)
  return 8;
#else
  return 1;
#endif
}

}  // extern "C"
