// SIMD CPU Adagrad for host-offloaded optimizer state.
//
// TPU-native counterpart of the reference's CPU Adagrad
// (csrc/adagrad/cpu_adagrad.cpp): accumulate squared gradients, scale by
// 1/sqrt(acc); OpenMP-threaded, auto-vectorized, plain C ABI for ctypes.

#include <cmath>
#include <cstddef>
#include <cstdint>

extern "C" {

// One Adagrad step over a contiguous fp32 shard.  Returns 0 on success.
int dstpu_adagrad_step(float* params, const float* grads, float* exp_avg_sq,
                       int64_t n, float lr, float eps, float weight_decay) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    float g = grads[i];
    float p = params[i];
    if (weight_decay != 0.0f) g += weight_decay * p;
    float v = exp_avg_sq[i] + g * g;
    exp_avg_sq[i] = v;
    params[i] = p - lr * g / (std::sqrt(v) + eps);
  }
  return 0;
}

}  // extern "C"
