// SIMD CPU Lion for host-offloaded optimizer state.
//
// TPU-native counterpart of the reference's CPU Lion
// (csrc/lion/cpu_lion_impl.cpp, fused_lion kernels): the Lion update
// (sign of the interpolated momentum) for ZeRO-Offload, OpenMP-threaded
// with compiler auto-vectorization (sign/copysign vectorize cleanly),
// exposed as a plain C ABI for ctypes.

#include <cmath>
#include <cstddef>
#include <cstdint>

extern "C" {

// One fused Lion step over a contiguous fp32 shard.  Returns 0 on success.
// update  c = b1*m + (1-b1)*g ;  p -= lr * (sign(c) + wd*p) ;
// moment  m = b2*m + (1-b2)*g
int dstpu_lion_step(float* params, const float* grads, float* exp_avg,
                    int64_t n, float lr, float beta1, float beta2,
                    float weight_decay) {
  const float b1 = beta1, omb1 = 1.0f - beta1;
  const float b2 = beta2, omb2 = 1.0f - beta2;

#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    float g = grads[i];
    float m = exp_avg[i];
    float c = b1 * m + omb1 * g;
    float p = params[i];
    // decoupled weight decay (Lion is always decoupled)
    if (weight_decay != 0.0f) p -= lr * weight_decay * p;
    params[i] = p - lr * ((c > 0.0f) - (c < 0.0f));
    exp_avg[i] = b2 * m + omb2 * g;
  }
  return 0;
}

}  // extern "C"
