// Async file I/O engine ("DeepNVMe"-equivalent).
//
// TPU-host counterpart of the reference AIO stack (csrc/aio/common,
// csrc/aio/py_lib: libaio/io_uring handles, thread pools, pinned buffers,
// op descriptors) backing ZeRO-Infinity NVMe swap and fast checkpointing.
//
// Two backends behind one C ABI:
//   * io_uring (preferred): kernel async I/O via raw syscalls — no liburing
//     dependency.  One submission mutex, a reaper thread draining the CQ,
//     short-transfer resubmission, per-(path,mode) fd cache.
//   * worker-thread pool draining a pread/pwrite queue — fallback when
//     io_uring is unavailable (seccomp'd containers, old kernels).
// Plus a pinned-buffer allocator (page-aligned + mlock'd, the host-side
// analogue of the reference's deepspeed_pin_tensor.cpp) so O_DIRECT and
// DMA-friendly staging buffers come from a reusable pool.
//
// Completion tracking is per-op (ids), so Python can overlap compute with
// I/O and wait for a specific tensor's swap instead of a global drain.

#include <atomic>
#include <cerrno>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <linux/io_uring.h>
#include <mutex>
#include <string>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/syscall.h>
#include <sys/uio.h>
#include <thread>
#include <unistd.h>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// common interface
// ---------------------------------------------------------------------------
struct EngineBase {
  virtual ~EngineBase() = default;
  virtual int64_t submit(bool write, const char* path, void* buf,
                         int64_t nbytes, int64_t offset) = 0;
  virtual int64_t drain() = 0;              // block until empty; n errors
  virtual int wait_op(int64_t id) = 0;      // block until op done; 0 ok
  virtual int64_t pending() = 0;
  virtual int kind() = 0;                   // 0 = threads, 1 = io_uring
};

struct FdCache {
  // one fd per (path, write|odirect) — reopening per op costs ~2us each and
  // defeats the kernel's per-file write pipelining.  Entries are
  // ref-counted (acquire/release around each op) and idle entries are
  // evicted LRU-ish beyond ``max_open`` so checkpoint workloads that touch
  // one file per tensor per step cannot exhaust RLIMIT_NOFILE.
  struct Entry {
    int fd;
    int refs;
    uint64_t last_use;
  };
  std::unordered_map<std::string, Entry> fds;
  // fds whose path was unlinked/replaced while ops were inflight: kept open
  // until their last op releases them
  std::unordered_map<int, int> retired;  // fd -> refs
  std::mutex mu;
  uint64_t tick = 0;
  size_t max_open;

  explicit FdCache(size_t cap = 128) : max_open(cap) {}

  static std::string key_of(const std::string& path, bool write, bool odirect) {
    return path + (write ? "|w" : "|r") + (odirect ? "|d" : "");
  }

  // returns fd (or <0) with the entry's refcount incremented
  int acquire(const std::string& path, bool write, bool odirect) {
    std::string key = key_of(path, write, odirect);
    std::lock_guard<std::mutex> l(mu);
    auto it = fds.find(key);
    if (it != fds.end()) {
      // a cached fd may point at a stale inode if the path was unlinked or
      // replaced (checkpoint rotation); verify dev/ino before reuse
      struct stat fs, ps;
      bool fresh = ::fstat(it->second.fd, &fs) == 0 &&
                   ::stat(path.c_str(), &ps) == 0 &&
                   fs.st_dev == ps.st_dev && fs.st_ino == ps.st_ino;
      if (fresh) {
        it->second.refs++;
        it->second.last_use = ++tick;
        return it->second.fd;
      }
      if (it->second.refs > 0)
        retired[it->second.fd] = it->second.refs;  // close at last release
      else
        ::close(it->second.fd);
      fds.erase(it);
    }
    if (fds.size() >= max_open) evict_idle_locked();
    int flags = write ? (O_WRONLY | O_CREAT) : O_RDONLY;
#ifdef O_DIRECT
    if (odirect) flags |= O_DIRECT;
#endif
    int fd = ::open(path.c_str(), flags, 0644);
    if (fd < 0 && odirect)
      fd = ::open(path.c_str(), write ? (O_WRONLY | O_CREAT) : O_RDONLY, 0644);
    if (fd >= 0) fds[key] = Entry{fd, 1, ++tick};
    return fd;
  }

  void release_fd(const std::string& path, bool write, bool odirect, int fd) {
    std::string key = key_of(path, write, odirect);
    std::lock_guard<std::mutex> l(mu);
    auto it = fds.find(key);
    if (it != fds.end() && it->second.fd == fd) {
      if (it->second.refs > 0) it->second.refs--;
      return;
    }
    auto rit = retired.find(fd);  // entry was replaced by a fresh inode
    if (rit != retired.end() && --rit->second <= 0) {
      ::close(rit->first);
      retired.erase(rit);
    }
  }

  void evict_idle_locked() {
    // close the least-recently-used entries with no inflight ops
    while (fds.size() >= max_open) {
      auto victim = fds.end();
      for (auto it = fds.begin(); it != fds.end(); ++it)
        if (it->second.refs == 0 &&
            (victim == fds.end() ||
             it->second.last_use < victim->second.last_use))
          victim = it;
      if (victim == fds.end()) return;  // everything busy: allow overshoot
      ::close(victim->second.fd);
      fds.erase(victim);
    }
  }

  ~FdCache() {
    for (auto& kv : fds) ::close(kv.second.fd);
    for (auto& kv : retired) ::close(kv.first);
  }
};

// ---------------------------------------------------------------------------
// io_uring backend (raw syscalls)
// ---------------------------------------------------------------------------
static int sys_io_uring_setup(unsigned entries, struct io_uring_params* p) {
  return (int)syscall(__NR_io_uring_setup, entries, p);
}
static int sys_io_uring_enter(int fd, unsigned to_submit, unsigned min_complete,
                              unsigned flags) {
  return (int)syscall(__NR_io_uring_enter, fd, to_submit, min_complete, flags,
                      nullptr, 0);
}
// bounded wait (EXT_ARG, kernel 5.11+): lets the reaper wake periodically
// even when no CQE ever arrives (hard-submit-error shutdown)
static int sys_io_uring_enter_timeout(int fd, unsigned min_complete,
                                      unsigned flags, long timeout_ns) {
  struct __kernel_timespec {
    long long tv_sec;
    long long tv_nsec;
  } ts{0, timeout_ns};
  struct io_uring_getevents_arg arg{};
  arg.ts = (uint64_t)(uintptr_t)&ts;
  return (int)syscall(__NR_io_uring_enter, fd, 0, min_complete,
                      flags | IORING_ENTER_EXT_ARG, &arg, sizeof(arg));
}
static int sys_io_uring_register(int fd, unsigned opcode, void* arg,
                                 unsigned nr_args) {
  return (int)syscall(__NR_io_uring_register, fd, opcode, arg, nr_args);
}

struct UringEngine : EngineBase {
  int ring_fd = -1;
  unsigned sq_entries = 0, cq_entries = 0;
  // sq ring
  unsigned *sq_head = nullptr, *sq_tail = nullptr, *sq_mask = nullptr,
           *sq_array = nullptr;
  io_uring_sqe* sqes = nullptr;
  // cq ring
  unsigned *cq_head = nullptr, *cq_tail = nullptr, *cq_mask = nullptr;
  io_uring_cqe* cqes = nullptr;
  void *sq_mm = nullptr, *cq_mm = nullptr, *sqe_mm = nullptr;
  size_t sq_mm_len = 0, cq_mm_len = 0, sqe_mm_len = 0;

  struct OpState {
    int chunks_pending;
    bool failed;
    std::string fd_key_path;  // for fd release when the op retires
    bool fd_write;
    int fd;
  };

  struct ChunkState {
    int64_t op_id;
    int fd;
    bool write;
    char* buf;        // next byte of THIS chunk
    int64_t left;     // bytes of this chunk not yet transferred
    int64_t off;
  };

  FdCache fd_cache;
  std::mutex mu;                 // guards sq + tables
  std::condition_variable done_cv;
  std::unordered_map<int64_t, OpState> inflight;
  std::unordered_map<int64_t, ChunkState> chunks;  // keyed by sqe user_data
  std::unordered_set<int64_t> completed_err;  // finished with error
  std::atomic<int64_t> next_id{1};
  std::atomic<int64_t> next_chunk_id{1};
  int64_t submitted_ops = 0, completed_ops = 0, errors = 0;
  std::thread reaper;
  std::atomic<bool> stop{false};
  bool ext_arg = false;  // IORING_FEAT_EXT_ARG: timed reaper waits
  bool odirect;
  int64_t max_chunk;

  explicit UringEngine(unsigned depth, bool use_odirect, int64_t chunk)
      : odirect(use_odirect), max_chunk(chunk < (1 << 16) ? (1 << 16) : chunk) {
    io_uring_params p;
    std::memset(&p, 0, sizeof(p));
    ring_fd = sys_io_uring_setup(depth, &p);
    if (ring_fd < 0) throw 1;
    sq_entries = p.sq_entries;
    cq_entries = p.cq_entries;
    ext_arg = (p.features & IORING_FEAT_EXT_ARG) != 0;

    sq_mm_len = p.sq_off.array + p.sq_entries * sizeof(unsigned);
    cq_mm_len = p.cq_off.cqes + p.cq_entries * sizeof(io_uring_cqe);
    bool single_mmap = p.features & IORING_FEAT_SINGLE_MMAP;
    if (single_mmap && cq_mm_len > sq_mm_len) sq_mm_len = cq_mm_len;
    sq_mm = ::mmap(nullptr, sq_mm_len, PROT_READ | PROT_WRITE,
                   MAP_SHARED | MAP_POPULATE, ring_fd, IORING_OFF_SQ_RING);
    if (sq_mm == MAP_FAILED) { ::close(ring_fd); throw 1; }
    cq_mm = single_mmap ? sq_mm
                        : ::mmap(nullptr, cq_mm_len, PROT_READ | PROT_WRITE,
                                 MAP_SHARED | MAP_POPULATE, ring_fd,
                                 IORING_OFF_CQ_RING);
    if (cq_mm == MAP_FAILED) { cleanup(); throw 1; }
    sqe_mm_len = p.sq_entries * sizeof(io_uring_sqe);
    sqe_mm = ::mmap(nullptr, sqe_mm_len, PROT_READ | PROT_WRITE,
                    MAP_SHARED | MAP_POPULATE, ring_fd, IORING_OFF_SQES);
    if (sqe_mm == MAP_FAILED) { cleanup(); throw 1; }

    char* sqp = static_cast<char*>(sq_mm);
    sq_head = (unsigned*)(sqp + p.sq_off.head);
    sq_tail = (unsigned*)(sqp + p.sq_off.tail);
    sq_mask = (unsigned*)(sqp + p.sq_off.ring_mask);
    sq_array = (unsigned*)(sqp + p.sq_off.array);
    sqes = static_cast<io_uring_sqe*>(sqe_mm);
    char* cqp = static_cast<char*>(cq_mm);
    cq_head = (unsigned*)(cqp + p.cq_off.head);
    cq_tail = (unsigned*)(cqp + p.cq_off.tail);
    cq_mask = (unsigned*)(cqp + p.cq_off.ring_mask);
    cqes = (io_uring_cqe*)(cqp + p.cq_off.cqes);

    // io_uring_setup existing is not enough: IORING_OP_READ/WRITE need
    // kernel 5.6+.  Probe opcode support so auto-mode falls back to the
    // thread pool on 5.1–5.5 kernels instead of failing every op EINVAL.
    {
      constexpr unsigned n_ops = 64;
      std::vector<char> buf(sizeof(io_uring_probe) +
                            n_ops * sizeof(io_uring_probe_op), 0);
      auto* probe = reinterpret_cast<io_uring_probe*>(buf.data());
      if (sys_io_uring_register(ring_fd, IORING_REGISTER_PROBE, probe,
                                n_ops) < 0 ||
          probe->last_op < IORING_OP_WRITE ||
          !(probe->ops[IORING_OP_READ].flags & IO_URING_OP_SUPPORTED) ||
          !(probe->ops[IORING_OP_WRITE].flags & IO_URING_OP_SUPPORTED)) {
        cleanup();
        throw 1;
      }
    }

    reaper = std::thread([this] { this->reap_loop(); });
  }

  void cleanup() {
    if (sqe_mm && sqe_mm != MAP_FAILED) ::munmap(sqe_mm, sqe_mm_len);
    if (cq_mm && cq_mm != MAP_FAILED && cq_mm != sq_mm)
      ::munmap(cq_mm, cq_mm_len);
    if (sq_mm && sq_mm != MAP_FAILED) ::munmap(sq_mm, sq_mm_len);
    if (ring_fd >= 0) ::close(ring_fd);
  }

  ~UringEngine() override {
    stop = true;
    {  // wake the reaper with a NOP
      std::unique_lock<std::mutex> l(mu);
      push_sqe(l, IORING_OP_NOP, -1, nullptr, 0, 0, /*user_data=*/0);
      flush_locked(l);
    }
    if (reaper.joinable()) reaper.join();
    cleanup();
  }

  std::atomic<bool> broken{false};  // poisoned by a hard submit error

  unsigned unsubmitted = 0;  // pushed SQEs not yet handed to the kernel

  // must hold ``l`` (locking mu).  Hand all pushed SQEs to the kernel,
  // handling partial submission and CQ-overflow backpressure (-EBUSY):
  // drops the lock while backing off so the reaper can drain the CQ.
  void flush_locked(std::unique_lock<std::mutex>& l) {
    while (unsubmitted > 0) {
      int r = sys_io_uring_enter(ring_fd, unsubmitted, 0, 0);
      if (r > 0) {
        unsubmitted -= (unsigned)r;
        continue;
      }
      int err = errno;
      if (r < 0 && (err == EBUSY || err == EAGAIN || err == EINTR)) {
        l.unlock();  // let the reaper drain completions
        ::usleep(200);
        l.lock();
        continue;
      }
      if (r == 0) {  // nothing consumed (shouldn't happen without SQPOLL)
        l.unlock();
        ::usleep(200);
        l.lock();
        continue;
      }
      // hard submit error (ring fd gone bad): the kernel will never produce
      // CQEs for the still-queued SQEs — retire their chunks as failed so
      // drain/wait cannot hang, and poison the engine so later submissions
      // fail fast instead of racing stale ring state
      broken = true;
      unsigned t = *sq_tail;
      for (unsigned i = t - unsubmitted; i != t; ++i) {
        io_uring_sqe* sqe = &sqes[sq_array[i & *sq_mask]];
        on_cqe_locked(l, (int64_t)sqe->user_data, /*res=*/-1);
      }
      unsubmitted = 0;
      return;
    }
  }

  // must hold ``l``; waits for sq space (flushing first — SQEs are consumed
  // by the kernel at submit time, so a successful flush empties the ring).
  // Returns false (nothing pushed) once the engine is broken: the queued
  // tail entries will never be consumed, so waiting for space would
  // livelock — the caller must retire the chunk itself.
  bool push_sqe(std::unique_lock<std::mutex>& l, unsigned op, int fd,
                void* buf, unsigned len, int64_t off, uint64_t user_data) {
    if (broken) return false;
    unsigned head = __atomic_load_n(sq_head, __ATOMIC_ACQUIRE);
    unsigned tail = *sq_tail;
    while (tail - head >= sq_entries) {  // ring full
      flush_locked(l);
      if (broken) return false;
      head = __atomic_load_n(sq_head, __ATOMIC_ACQUIRE);
      tail = *sq_tail;
      if (tail - head >= sq_entries) {
        l.unlock();
        ::usleep(200);
        l.lock();
        if (broken) return false;
        head = __atomic_load_n(sq_head, __ATOMIC_ACQUIRE);
        tail = *sq_tail;
      }
    }
    unsigned idx = tail & *sq_mask;
    io_uring_sqe* sqe = &sqes[idx];
    std::memset(sqe, 0, sizeof(*sqe));
    sqe->opcode = (uint8_t)op;
    sqe->fd = fd;
    sqe->addr = (uint64_t)(uintptr_t)buf;
    sqe->len = len;
    sqe->off = (uint64_t)off;
    sqe->user_data = user_data;
    sq_array[idx] = idx;
    __atomic_store_n(sq_tail, tail + 1, __ATOMIC_RELEASE);
    unsubmitted++;
    return true;
  }

  int64_t submit(bool write, const char* path, void* buf, int64_t nbytes,
                 int64_t offset) override {
    int fd = fd_cache.acquire(path, write, odirect);
    int64_t id = next_id++;
    std::unique_lock<std::mutex> l(mu);
    if (fd < 0 || broken) {  // surface as a completed-with-error op
      if (fd >= 0) fd_cache.release_fd(path, write, odirect, fd);
      completed_err.insert(id);
      submitted_ops++;
      completed_ops++;
      errors++;
      done_cv.notify_all();
      return id;
    }
    submitted_ops++;
    if (nbytes == 0) {  // zero-byte op: complete immediately
      fd_cache.release_fd(path, write, odirect, fd);
      completed_ops++;
      done_cv.notify_all();
      return id;
    }
    // register the op FIRST: a chunk retired synchronously inside push_sqe
    // (hard submit error) must find its OpState
    int n_chunks = (int)((nbytes + max_chunk - 1) / max_chunk);
    inflight[id] = OpState{n_chunks, false, path, write, fd};
    // split into <=max_chunk sqes; each chunk tracks its own window so
    // out-of-order completions and short transfers resubmit correctly
    int64_t left = nbytes, off = offset;
    char* p = static_cast<char*>(buf);
    while (left > 0) {
      int64_t chunk = left < max_chunk ? left : max_chunk;
      int64_t cid = next_chunk_id++;
      chunks[cid] = ChunkState{id, fd, write, p, chunk, off};
      if (!push_sqe(l, write ? IORING_OP_WRITE : IORING_OP_READ, fd, p,
                    (unsigned)chunk, off, (uint64_t)cid))
        on_cqe_locked(l, cid, /*res=*/-1);  // broken engine: retire now
      p += chunk;
      off += chunk;
      left -= chunk;
    }
    flush_locked(l);
    return id;
  }

  // must hold ``l``.  Retire one chunk's CQE; resubmit short transfers.
  void on_cqe_locked(std::unique_lock<std::mutex>& l, int64_t cid, int res) {
    auto cit = chunks.find(cid);
    if (cit == chunks.end()) return;
    ChunkState& ch = cit->second;
    bool chunk_done = false, chunk_failed = false;
    if (res <= 0) {
      chunk_done = chunk_failed = true;  // error or EOF-at-start
    } else if ((int64_t)res >= ch.left) {
      chunk_done = true;
    } else if (!ch.write && ch.left - res > 0 && (ch.off + res) % 512 != 0) {
      // short read ending off block boundary: EOF inside the range — a
      // fixed-size swap round-trip can never satisfy this op
      chunk_done = chunk_failed = true;
    } else {
      // genuine short transfer: resubmit the remainder
      ch.buf += res;
      ch.off += res;
      ch.left -= res;
      if (!push_sqe(l, ch.write ? IORING_OP_WRITE : IORING_OP_READ, ch.fd,
                    ch.buf, (unsigned)ch.left, ch.off, (uint64_t)cid))
        chunk_done = chunk_failed = true;  // broken engine: retire as failed
    }
    if (chunk_done) {
      int64_t op_id = ch.op_id;
      chunks.erase(cit);
      auto oit = inflight.find(op_id);
      if (oit != inflight.end()) {
        OpState& st = oit->second;
        if (chunk_failed) st.failed = true;
        if (--st.chunks_pending == 0) {
          bool failed = st.failed;
          fd_cache.release_fd(st.fd_key_path, st.fd_write, odirect, st.fd);
          inflight.erase(oit);
          completed_ops++;
          if (failed) {
            errors++;
            completed_err.insert(op_id);
          }
          done_cv.notify_all();
        }
      }
    }
  }

  void reap_loop() {
    std::vector<std::pair<int64_t, int>> batch;
    for (;;) {
      if (broken.load()) {
        // no CQE will ever arrive for locally-retired chunks, and the
        // destructor cannot wake us with a NOP (push_sqe refuses once
        // broken) — poll instead of blocking so stop is honored
        ::usleep(500);
      } else if (ext_arg) {
        // bounded wait: a hard submit error can flip ``broken`` while we
        // are parked here with no CQE ever coming; wake every 50ms to
        // re-check instead of blocking forever
        int r = sys_io_uring_enter_timeout(ring_fd, 1, IORING_ENTER_GETEVENTS,
                                           50'000'000L);
        if (r < 0 && errno != EINTR && errno != EBUSY && errno != EAGAIN &&
            errno != ETIME)
          ::usleep(1000);
      }
      // pre-5.11 fallback (no timed enter): sweep first, sleep only when
      // the CQ was empty — pending completions never pay a poll delay
      std::unique_lock<std::mutex> l(mu);
      // Sweep the CQ and ADVANCE cq_head before retiring chunks: retirement
      // may resubmit (short transfers), and a resubmission backoff must not
      // deadlock against a full CQ we haven't released yet.
      unsigned head = *cq_head;
      unsigned tail = __atomic_load_n(cq_tail, __ATOMIC_ACQUIRE);
      batch.clear();
      while (head != tail) {
        io_uring_cqe* cqe = &cqes[head & *cq_mask];
        if (cqe->user_data != 0)  // 0 = shutdown NOP
          batch.emplace_back((int64_t)cqe->user_data, (int)cqe->res);
        head++;
      }
      __atomic_store_n(cq_head, head, __ATOMIC_RELEASE);
      bool swept_nothing = batch.empty();
      for (auto& [cid, res] : batch) on_cqe_locked(l, cid, res);
      flush_locked(l);  // hand any resubmissions to the kernel
      if (stop && (inflight.empty() || broken)) return;
      l.unlock();
      if (!ext_arg && !broken.load() && swept_nothing) ::usleep(500);
    }
  }

  int64_t drain() override {
    std::unique_lock<std::mutex> l(mu);
    done_cv.wait(l, [this] { return completed_ops == submitted_ops; });
    int64_t e = errors;
    errors = 0;
    completed_err.clear();
    return e;
  }

  int wait_op(int64_t id) override {
    std::unique_lock<std::mutex> l(mu);
    done_cv.wait(l, [this, id] { return inflight.find(id) == inflight.end(); });
    if (completed_err.erase(id)) {  // consumed: a later drain is clean
      errors--;
      return 1;
    }
    return 0;
  }

  int64_t pending() override {
    std::lock_guard<std::mutex> l(mu);
    return submitted_ops - completed_ops;
  }

  int kind() override { return 1; }
};

// ---------------------------------------------------------------------------
// worker-thread fallback backend
// ---------------------------------------------------------------------------
struct ThreadEngine : EngineBase {
  struct Op {
    int64_t id;
    bool write;
    std::string path;
    void* buf;
    int64_t nbytes;
    int64_t offset;
  };

  std::vector<std::thread> workers;
  std::deque<Op> queue;
  FdCache fd_cache;
  std::mutex mu;
  std::condition_variable cv;
  std::condition_variable done_cv;
  std::atomic<bool> stop{false};
  std::atomic<int64_t> next_id{1};
  std::unordered_set<int64_t> inflight_ids;
  std::unordered_set<int64_t> completed_err;
  int64_t completed = 0, submitted = 0, errors = 0;
  int block_size;
  bool use_odirect;

  ThreadEngine(int nthreads, int block, bool odirect)
      : block_size(block), use_odirect(odirect) {
    for (int i = 0; i < nthreads; ++i)
      workers.emplace_back([this] { this->run(); });
  }

  ~ThreadEngine() override {
    {
      std::lock_guard<std::mutex> l(mu);
      stop = true;
    }
    cv.notify_all();
    for (auto& t : workers) t.join();
  }

  void run() {
    for (;;) {
      Op op;
      {
        std::unique_lock<std::mutex> l(mu);
        cv.wait(l, [this] { return stop || !queue.empty(); });
        if (stop && queue.empty()) return;
        op = queue.front();
        queue.pop_front();
      }
      bool ok = execute(op);
      {
        std::lock_guard<std::mutex> l(mu);
        completed++;
        inflight_ids.erase(op.id);
        if (!ok) {
          errors++;
          completed_err.insert(op.id);
        }
      }
      done_cv.notify_all();
    }
  }

  bool execute(const Op& op) {
    int fd = fd_cache.acquire(op.path, op.write, use_odirect);
    if (fd < 0) return false;
    char* p = static_cast<char*>(op.buf);
    int64_t left = op.nbytes, off = op.offset;
    bool ok = true;
    while (left > 0) {
      int64_t chunk = left < (int64_t)block_size ? left : (int64_t)block_size;
      ssize_t r = op.write ? ::pwrite(fd, p, chunk, off)
                           : ::pread(fd, p, chunk, off);
      if (r <= 0) {
        ok = false;
        break;
      }
      p += r;
      off += r;
      left -= r;
    }
    fd_cache.release_fd(op.path, op.write, use_odirect, fd);
    return ok;
  }

  int64_t submit(bool write, const char* path, void* buf, int64_t nbytes,
                 int64_t offset) override {
    int64_t id = next_id++;
    {
      std::lock_guard<std::mutex> l(mu);
      queue.push_back(Op{id, write, path, buf, nbytes, offset});
      inflight_ids.insert(id);
      submitted++;
    }
    cv.notify_one();
    return id;
  }

  int64_t drain() override {
    std::unique_lock<std::mutex> l(mu);
    done_cv.wait(l, [this] { return completed == submitted; });
    int64_t e = errors;
    errors = 0;
    completed_err.clear();
    return e;
  }

  int wait_op(int64_t id) override {
    std::unique_lock<std::mutex> l(mu);
    done_cv.wait(l, [this, id] {
      return inflight_ids.find(id) == inflight_ids.end();
    });
    if (completed_err.erase(id)) {  // consumed: a later drain is clean
      errors--;
      return 1;
    }
    return 0;
  }

  int64_t pending() override {
    std::lock_guard<std::mutex> l(mu);
    return submitted - completed;
  }

  int kind() override { return 0; }
};

}  // namespace

extern "C" {

// backend: 0 = auto (io_uring, fallback threads), 1 = force threads,
//          2 = force io_uring (null on failure)
void* dstpu_aio_create_ex(int nthreads, int block_size, int use_odirect,
                          int backend) {
  if (backend != 1) {
    try {
      return new UringEngine(/*depth=*/256, use_odirect != 0, block_size);
    } catch (...) {
      if (backend == 2) return nullptr;
    }
  }
  return new ThreadEngine(nthreads, block_size, use_odirect != 0);
}

void* dstpu_aio_create(int nthreads, int block_size, int use_odirect) {
  return dstpu_aio_create_ex(nthreads, block_size, use_odirect, 0);
}

void dstpu_aio_destroy(void* h) { delete static_cast<EngineBase*>(h); }

int64_t dstpu_aio_pwrite(void* h, const char* path, void* buf, int64_t nbytes,
                         int64_t offset) {
  return static_cast<EngineBase*>(h)->submit(true, path, buf, nbytes, offset);
}

int64_t dstpu_aio_pread(void* h, const char* path, void* buf, int64_t nbytes,
                        int64_t offset) {
  return static_cast<EngineBase*>(h)->submit(false, path, buf, nbytes, offset);
}

int64_t dstpu_aio_drain(void* h) { return static_cast<EngineBase*>(h)->drain(); }

int dstpu_aio_wait(void* h, int64_t op_id) {
  return static_cast<EngineBase*>(h)->wait_op(op_id);
}

int64_t dstpu_aio_pending(void* h) {
  return static_cast<EngineBase*>(h)->pending();
}

int dstpu_aio_backend_kind(void* h) { return static_cast<EngineBase*>(h)->kind(); }

// ---------------------------------------------------------------------------
// pinned buffers (reference deepspeed_pin_tensor.cpp): page-aligned, mlock'd
// ---------------------------------------------------------------------------
void* dstpu_pin_alloc(int64_t nbytes) {
  void* p = nullptr;
  if (posix_memalign(&p, 4096, (size_t)nbytes) != 0) return nullptr;
  ::mlock(p, (size_t)nbytes);  // best effort: RLIMIT_MEMLOCK may cap it
  return p;
}

void dstpu_pin_free(void* p, int64_t nbytes) {
  if (!p) return;
  ::munlock(p, (size_t)nbytes);
  ::free(p);
}

}  // extern "C"
