// Async file I/O engine ("DeepNVMe"-equivalent).
//
// TPU-host counterpart of the reference AIO stack (csrc/aio/common,
// csrc/aio/py_lib: thread-pooled libaio handles, pinned buffers, op
// descriptors) backing ZeRO-Infinity NVMe swap and fast checkpointing.
// Implementation: a worker-thread pool draining a submission queue of
// pread/pwrite ops (optionally O_DIRECT), completion tracked per-handle so
// Python can overlap compute with I/O — same role, portable plumbing
// (io_uring-style queue semantics without the liburing dependency).
// Exposed as a C ABI for ctypes.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <mutex>
#include <string>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Op {
  int64_t id;
  bool write;
  std::string path;
  void* buf;
  int64_t nbytes;
  int64_t offset;
};

struct Engine {
  std::vector<std::thread> workers;
  std::deque<Op> queue;
  std::mutex mu;
  std::condition_variable cv;
  std::condition_variable done_cv;
  std::atomic<bool> stop{false};
  std::atomic<int64_t> next_id{1};
  int64_t completed = 0;   // count of finished ops
  int64_t submitted = 0;
  int64_t errors = 0;
  int block_size;
  bool use_odirect;

  Engine(int nthreads, int block, bool odirect)
      : block_size(block), use_odirect(odirect) {
    for (int i = 0; i < nthreads; ++i)
      workers.emplace_back([this] { this->run(); });
  }

  ~Engine() {
    {
      std::lock_guard<std::mutex> l(mu);
      stop = true;
    }
    cv.notify_all();
    for (auto& t : workers) t.join();
  }

  void run() {
    for (;;) {
      Op op;
      {
        std::unique_lock<std::mutex> l(mu);
        cv.wait(l, [this] { return stop || !queue.empty(); });
        if (stop && queue.empty()) return;
        op = queue.front();
        queue.pop_front();
      }
      bool ok = execute(op);
      {
        std::lock_guard<std::mutex> l(mu);
        completed++;
        if (!ok) errors++;
      }
      done_cv.notify_all();
    }
  }

  bool execute(const Op& op) {
    int flags = op.write ? (O_WRONLY | O_CREAT) : O_RDONLY;
#ifdef O_DIRECT
    if (use_odirect) flags |= O_DIRECT;
#endif
    int fd = ::open(op.path.c_str(), flags, 0644);
    if (fd < 0 && use_odirect) {  // fall back without O_DIRECT
      fd = ::open(op.path.c_str(), op.write ? (O_WRONLY | O_CREAT) : O_RDONLY, 0644);
    }
    if (fd < 0) return false;
    char* p = static_cast<char*>(op.buf);
    int64_t left = op.nbytes, off = op.offset;
    bool ok = true;
    while (left > 0) {
      int64_t chunk = left < (int64_t)block_size ? left : (int64_t)block_size;
      ssize_t r = op.write ? ::pwrite(fd, p, chunk, off) : ::pread(fd, p, chunk, off);
      if (r <= 0) {
        ok = false;
        break;
      }
      p += r;
      off += r;
      left -= r;
    }
    ::close(fd);
    return ok;
  }

  int64_t submit(bool write, const char* path, void* buf, int64_t nbytes,
                 int64_t offset) {
    int64_t id = next_id++;
    {
      std::lock_guard<std::mutex> l(mu);
      queue.push_back(Op{id, write, path, buf, nbytes, offset});
      submitted++;
    }
    cv.notify_one();
    return id;
  }

  // wait until all submitted ops completed; returns number of errors
  int64_t drain() {
    std::unique_lock<std::mutex> l(mu);
    done_cv.wait(l, [this] { return completed == submitted; });
    return errors;
  }

  int64_t pending() {
    std::lock_guard<std::mutex> l(mu);
    return submitted - completed;
  }
};

}  // namespace

extern "C" {

void* dstpu_aio_create(int nthreads, int block_size, int use_odirect) {
  return new Engine(nthreads, block_size, use_odirect != 0);
}

void dstpu_aio_destroy(void* h) { delete static_cast<Engine*>(h); }

int64_t dstpu_aio_pwrite(void* h, const char* path, void* buf, int64_t nbytes,
                         int64_t offset) {
  return static_cast<Engine*>(h)->submit(true, path, buf, nbytes, offset);
}

int64_t dstpu_aio_pread(void* h, const char* path, void* buf, int64_t nbytes,
                        int64_t offset) {
  return static_cast<Engine*>(h)->submit(false, path, buf, nbytes, offset);
}

int64_t dstpu_aio_drain(void* h) { return static_cast<Engine*>(h)->drain(); }

int64_t dstpu_aio_pending(void* h) { return static_cast<Engine*>(h)->pending(); }

}  // extern "C"
