"""Benchmark: llama causal-LM training throughput on the local chip(s).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The comparator: the reference's headline sustained utilization is 54% of
hardware peak (Ulysses blog, BASELINE.md) — ``vs_baseline`` is our achieved
model-flops-utilization divided by 0.54, i.e. >1.0 means we beat the
reference's utilization on our hardware.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

def _int_env(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


# How long to give the configured (possibly tunneled-TPU) backend to come up
# before falling back to CPU.  Backend init through the axon relay can be
# slow; a hung tunnel must not zero out the benchmark (round-1 BENCH rc=1).
_PROBE_TIMEOUT_S = _int_env("DSTPU_BENCH_PROBE_TIMEOUT", 240)

#: XLA latency-hiding-scheduler flags pinned into every TPU CHILD rung —
#: the backstop that lets the scheduler actually hide the in-loop
#: collectives the overlap wrap issues (runtime/zero/overlap.py).  This
#: is a deliberate copy of compile/backend.py LATENCY_HIDING_FLAGS: the
#: parent process never imports the package (a site TPU plugin could
#: wedge at import), and tests/unit/test_overlap.py asserts the copies
#: match.  TPU-only — never pinned into CPU children, where unknown
#: flags abort XLA startup.  DSTPU_BENCH_NO_LHS_FLAGS=1 opts out.
_LATENCY_HIDING_FLAGS = {
    "--xla_tpu_enable_latency_hiding_scheduler": "true",
    "--xla_tpu_enable_async_collective_fusion": "true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather": "true",
}


def _pin_overlap_flags(env: dict) -> dict:
    """Child-env copy with the missing latency-hiding flags appended to
    XLA_FLAGS (explicit operator values are left alone).  Presence is
    token-parsed, not substring-matched — a flag that prefixes a longer
    flag's name (fusion vs fusion_fuse_all_gather) must still pin."""
    if os.environ.get("DSTPU_BENCH_NO_LHS_FLAGS") == "1":
        return env
    cur = env.get("XLA_FLAGS", "")
    present = {tok.split("=", 1)[0] for tok in cur.split()
               if tok.startswith("--")}
    missing = [f"{k}={v}" for k, v in _LATENCY_HIDING_FLAGS.items()
               if k not in present]
    if not missing:
        return env
    return dict(env, XLA_FLAGS=" ".join([cur.strip()] + missing).strip())


def _pin_cpu() -> None:
    """Force the CPU platform, overriding any site-plugin pin."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass


def _backend_usable() -> tuple:
    """Probe the configured backend in a subprocess with a hard timeout.

    jax backend init happens inside a C call that cannot be interrupted
    in-process, so a hung TPU plugin would hang the benchmark itself; the
    subprocess is the only safe way to find out.

    Returns ``(ok, reason, backend)``: ``reason`` is "" when the backend is
    usable, else a short description of why the bench is falling back to
    CPU — it is recorded inside the JSON artifact so a CPU run can never
    masquerade as a chip number.  ``backend`` is the platform name the
    probe subprocess saw ("" when the probe failed) — the parent process
    itself never initializes jax, so this is how it learns what hardware
    the children will run on.
    """
    # Probe unless explicitly pinned to cpu: a site PJRT plugin can select a
    # TPU backend via jax.config even when JAX_PLATFORMS is unset, and the
    # subprocess (same sitecustomize) reproduces whatever main() would see.
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        return True, "", "cpu"
    code = ("import jax, jax.numpy as jnp; "
            "x = jnp.ones((128, 128), jnp.bfloat16); "
            "x = (x @ x); "
            "print(float(x.sum()), jax.default_backend())")
    # Retry budget is ADAPTIVE to the failure mode (VERDICT r3 weak #1):
    #   - fast non-zero exit: usually "chip busy / claim failed" from a
    #     process about to release it — cheap to retry, default 1 retry.
    #   - probe TIMEOUT: a backend was trying to init (CPU init is
    #     instant), i.e. a TPU is EXPECTED but its lease is wedged; wedges
    #     observed in round 3 cleared on minutes timescale, so spend a
    #     larger budget (default 3 retries, 90s apart) before giving up
    #     the only hardware number of the round.
    # (A machine with no TPU at all never reaches here: jax falls back to
    # cpu and the probe SUCCEEDS, reporting backend=cpu.)
    fast_retries = max(0, _int_env("DSTPU_BENCH_PROBE_RETRIES", 1))
    # An explicit base knob is a fast-fail contract: it caps the timeout
    # budget too unless the TPU knob is ALSO explicit.
    if "DSTPU_BENCH_PROBE_RETRIES_TPU" in os.environ:
        timeout_retries = max(0, _int_env("DSTPU_BENCH_PROBE_RETRIES_TPU", 3))
    elif "DSTPU_BENCH_PROBE_RETRIES" in os.environ:
        timeout_retries = fast_retries
    else:
        timeout_retries = 3
    err = ""
    timeouts = 0
    attempt = 0
    while True:
        try:
            proc = subprocess.run([sys.executable, "-c", code],
                                  capture_output=True, text=True,
                                  timeout=_PROBE_TIMEOUT_S)
            if proc.returncode == 0:
                out = proc.stdout.split()
                return True, "", (out[-1] if out else "")
            err = proc.stderr[-2000:]
        except subprocess.TimeoutExpired:
            timeouts += 1
            err = f"probe timed out after {_PROBE_TIMEOUT_S}s"
        # permanent failures (no plugin/backend at all) never clear —
        # don't pay the retry sleeps for them
        permanent = any(s in err for s in
                        ("Unknown backend", "ModuleNotFoundError",
                         "ImportError", "not in the list of known backends"))
        if permanent:
            break
        budget = timeout_retries if timeouts else fast_retries
        if attempt >= budget:
            break
        wait = 90 if timeouts else 60
        print(f"bench: backend probe failed ({err[-200:]}); retrying in "
              f"{wait}s ({attempt + 1}/{budget} retries used)",
              file=sys.stderr)
        time.sleep(wait)
        attempt += 1
    reason = (f"TPU expected but unreachable: {err} "
              f"({timeouts} timeouts, {attempt + 1} probes)"
              if timeouts else f"backend probe failed: {err[-300:]}")
    print(f"bench: backend probe failed; falling back to cpu\n{err}",
          file=sys.stderr)
    return False, reason, ""

def _peak_for(device) -> float:
    # canonical per-generation table lives in telemetry/mfu.py (one copy,
    # shared with the engine's MFU gauge and tools/tune_mfu.py); imported
    # lazily so --cpu pinning happens before any jax-touching import
    from deepspeed_tpu.telemetry.mfu import peak_flops_for_device

    return peak_flops_for_device(device)



def build_model_and_config(size: str, seq: int, micro_bs: int, env=None,
                           attn_impl=None, scan_layers=None):
    """Model + ds-config for a bench rung — the SINGLE source of truth,
    shared with tools/bench_estimate.py (an estimate must compile the same
    program the bench runs; a drifted copy estimates the wrong rung).

    ``env``: mapping of DSTPU_BENCH_* knobs (default os.environ).
    ``scan_layers``: estimator override (cost analysis is while-loop
    trip-count-unaware, so estimates compile unrolled layers)."""
    env = os.environ if env is None else env
    # big models need remat + bf16 grad accumulation + tiled loss to fit
    # one chip's HBM; 160m runs leaner without them (see docs/PERF_NOTES.md)
    big = size in ("1b", "7b", "13b", "70b")
    remat = env.get("DSTPU_BENCH_REMAT", "1" if big else "0") == "1"
    acc = env.get("DSTPU_BENCH_ACC", "bf16" if big else "fp32")
    if env.get("DSTPU_BENCH_LOSS_CHUNK"):
        chunk = int(env["DSTPU_BENCH_LOSS_CHUNK"])
    elif big and seq > 2:
        # largest divisor of seq-1 (the shifted-label length) up to 512;
        # a near-prime seq-1 would degenerate into thousands of tiny
        # chunks — then materializing the logits beats tiling
        n = seq - 1
        chunk = max(d for d in range(1, min(n, 512) + 1) if n % d == 0)
        if chunk < 32:
            chunk = 0
    else:
        chunk = 0
    over = {}
    if scan_layers is not None:
        over["scan_layers"] = scan_layers
    if remat:
        over.update(remat=True,
                    remat_policy=env.get("DSTPU_BENCH_REMAT_POLICY",
                                         "nothing_saveable"))
    if chunk:
        over["loss_chunk"] = chunk
    attn_impl = attn_impl or env.get("DSTPU_BENCH_ATTN")
    if attn_impl:
        over["attn_impl"] = attn_impl
    # family knob (VERDICT r3 weak #3: MoE perf must be measurable on the
    # same harness): mixtral routes tokens through the dropless MoE path;
    # flops_per_token counts only the active (top-k) experts
    family = env.get("DSTPU_BENCH_MODEL", "llama")
    # pipeline rungs (docs/PIPELINE.md): DSTPU_BENCH_PIPE=P runs the
    # 1F1B pipe scan over P stages; DSTPU_BENCH_PIPE_HOP compresses the
    # activation hops (int8/fp8, EF on by default)
    pipe = int(env.get("DSTPU_BENCH_PIPE", "0") or 0)
    if pipe > 1:
        if family != "llama":
            raise ValueError(
                f"DSTPU_BENCH_PIPE={pipe} supports only the llama family "
                f"(got DSTPU_BENCH_MODEL={family!r})")
        from deepspeed_tpu.models.llama import llama_config
        from deepspeed_tpu.runtime.pipe.engine import pipelined_causal_lm

        num_micro = int(env.get("DSTPU_BENCH_PIPE_MICRO", "4") or 4)
        model = pipelined_causal_lm(llama_config(size, max_seq_len=seq,
                                                 **over),
                                    num_microbatches=num_micro)
    elif family == "mixtral":
        from deepspeed_tpu.models.mixtral import mixtral_model

        # dropless: the grouped-matmul MoE path — the capacity-factor
        # default would drop overflow tokens and run dispatch einsums,
        # a different algorithm than the top_k-priced MFU metric
        model = mixtral_model(size, max_seq_len=seq, moe_drop_tokens=False,
                              **over)
    elif family == "llama":
        from deepspeed_tpu.models.llama import llama_model

        model = llama_model(size, max_seq_len=seq, **over)
    else:
        # the family name is interpolated into the published metric — a
        # typo must not run llama and label the artifact with another name
        raise ValueError(f"unknown DSTPU_BENCH_MODEL {family!r}")
    # stage/offload rungs are env-selectable (VERDICT r3 next #2): stage-3
    # and the offload boundary must be measurable on the same model/chip,
    # not hardcoded out of the artifact
    stage = int(env.get("DSTPU_BENCH_STAGE", "1") or 1)
    zero_cfg = {"stage": stage}
    if env.get("DSTPU_BENCH_OFFLOAD") == "1":
        zero_cfg["offload_optimizer"] = {"device": "cpu"}
    if env.get("DSTPU_BENCH_PREFETCH") == "1":
        # stage-3 manual prefetch A/B (explicit in-loop gathers on the
        # 2x-unrolled layer scan)
        zero_cfg["zero3_param_prefetch"] = True
    if env.get("DSTPU_BENCH_OVERLAP") == "1":
        # compute/collective overlap A/B (runtime/zero/overlap.py):
        # per-layer-bucket grad reduce inside the backward loop
        zero_cfg["overlap_grad_reduce"] = True
    if env.get("DSTPU_BENCH_OVERLAP_BUCKET_MB"):
        zero_cfg["overlap_bucket_mb"] = float(
            env["DSTPU_BENCH_OVERLAP_BUCKET_MB"])
    if env.get("DSTPU_BENCH_OVERLAP_COMPRESSION"):
        # compressed overlap A/B (docs/COMM.md "Compressed overlap"):
        # int8/fp8 codes + per-bucket EF residuals inside the loop
        zero_cfg["overlap_compression"] = \
            env["DSTPU_BENCH_OVERLAP_COMPRESSION"]
    opt_params = {"lr": 1e-4, "weight_decay": 0.1}
    if env.get("DSTPU_BENCH_MU_DTYPE"):
        # bf16 exp_avg: -2 bytes/param of optimizer HBM (helps the 1b
        # model fit one chip without offload)
        opt_params["mu_dtype"] = env["DSTPU_BENCH_MU_DTYPE"]
    if env.get("DSTPU_BENCH_FUSED_OPT") == "1":
        opt_params["fused_kernel"] = True
    config = {
        "train_micro_batch_size_per_gpu": micro_bs,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": opt_params},
        "bf16": {"enabled": True},
        "zero_optimization": zero_cfg,
        "gradient_clipping": 1.0,
        "data_types": {"grad_accum_dtype": acc},
    }
    if env.get("DSTPU_BENCH_NUMERICS", "1") == "1":
        # numerics observatory (docs/OBSERVABILITY.md): per-layer health
        # stats ride the fused step as extra tiny outputs, pulled only at
        # the steps_per_print boundary.  Shared here so the estimator
        # compiles the same program the bench runs; the cadence is pinned
        # low enough that even the short CPU rung crosses a boundary.
        config["telemetry"] = {"enabled": True,
                               "numerics": {"enabled": True}}
        config["steps_per_print"] = int(env.get("DSTPU_BENCH_SPP", "5") or 5)
    if pipe > 1:
        # pipe stages claim their axis; data absorbs the remaining chips
        config["mesh"] = {"pipe": pipe, "data": -1}
        if env.get("DSTPU_BENCH_PIPE_HOP"):
            config["pipeline"] = {
                "hop_compression": env["DSTPU_BENCH_PIPE_HOP"]}
    return model, config, {"family": family, "stage": stage,
                           "zero_cfg": zero_cfg, "pipe": pipe}


def _run(size: str, seq: int, micro_bs: int, steps: int,
         attn_impl=None) -> dict:
    import jax
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.models.transformer import flops_per_token

    model, config, _meta = build_model_and_config(
        size, seq, micro_bs, attn_impl=attn_impl)
    family, stage, zero_cfg = _meta["family"], _meta["stage"], _meta["zero_cfg"]
    engine, *_ = deepspeed_tpu.initialize(model=model, config=config)
    dp = engine.topology.dp_world_size
    n_chips = engine.topology.world_size

    rng = np.random.RandomState(0)
    vocab = model.config.vocab_size

    def batch():
        ids = rng.randint(0, vocab, (1, micro_bs * dp, seq)).astype(np.int32)
        return {"input_ids": jnp.asarray(ids)}

    # warmup / compile.  Several steps, not one: donation-variant compiles
    # and device-queue ramp land in steps 2-4, and a single warmup step let
    # them pollute the timed window (round-2's 0.236 "MFU" was this —
    # steady state measured 0.384 with a proper warmup, docs/PERF_NOTES.md)
    warmup = int(os.environ.get("DSTPU_BENCH_WARMUP", "5"))
    # run-level goodput of this bench process (buckets sum to the
    # ledger's lifetime): warmup/compile is badput, the timed window is
    # productive — created HERE so its lifetime covers both phases
    gp = None
    try:
        from deepspeed_tpu.telemetry.goodput import GoodputLedger
        from deepspeed_tpu.telemetry.registry import MetricsRegistry

        gp = GoodputLedger(registry=MetricsRegistry())
    except Exception:
        pass
    loss = None
    t_warm0 = time.perf_counter()
    for _ in range(warmup):
        loss = engine.train_batch(batch())
    # real host roundtrip: see the tail comment — block_until_ready alone
    # can return early through the tunnel
    if loss is not None:
        float(loss)

    warmup_dt = time.perf_counter() - t_warm0

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine.train_batch(batch())
    jax.block_until_ready(loss)
    # force a host roundtrip of real data: on remote/tunneled devices a bare
    # block_until_ready can return before execution finishes, which would
    # report impossible (>1) MFU
    final_loss = float(loss)
    dt = time.perf_counter() - t0
    assert np.isfinite(final_loss)

    # measured step-time attribution (telemetry/timeline.py): one extra
    # profiled step OUTSIDE the timed window — the decomposition says
    # where the wall went (CPU runs stamp measured: false honestly)
    timeline_rec = None
    try:
        from deepspeed_tpu.telemetry.timeline import capture_thunk

        _, timeline_rec = capture_thunk(
            lambda: float(engine.train_batch(batch())),
            step=engine.global_steps,
            pipe_struct=getattr(engine, "_pipe_struct", None))
    except Exception as e:  # attribution must never sink a bench run
        print(f"bench: timeline capture failed ({e}); omitting", file=sys.stderr)

    tokens = steps * micro_bs * dp * seq
    tok_per_sec_chip = tokens / dt / n_chips
    model_flops = flops_per_token(model.config, seq) * tokens
    dev = jax.devices()[0]
    mfu = model_flops / dt / (n_chips * _peak_for(dev))

    tag = f"zero{stage}" \
        + (f"-pipe{_meta['pipe']}" if _meta.get("pipe") else "") \
        + ("-offload" if "offload_optimizer" in zero_cfg else "")
    result = {
        "metric": f"{family}-{size} bf16 {tag} tokens/sec/chip "
                  f"(seq={seq}, bs={micro_bs}, mfu={mfu:.3f})",
        "value": round(tok_per_sec_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.54, 3),
        # provenance: a CPU fallback must be self-describing, never able to
        # masquerade as a chip number (VERDICT r3 next #1)
        "backend": jax.default_backend(),
        "device_kind": str(getattr(dev, "device_kind", "unknown")),
        "mfu": round(mfu, 4),
    }
    if stage != 1 or "offload_optimizer" in zero_cfg:
        # the 0.54 comparator was measured under the zero1-style dense
        # regime; flag it so non-default rungs aren't read as regressions
        result["comparator_note"] = "vs_baseline divides by the 0.54 zero1 comparator"
    # "comparable": may this artifact be read against the TPU baseline
    # trajectory (BASELINE.md / BENCH_r02)?  A CPU run — deliberate or a
    # probe-timeout fallback to the tiny model — measures different
    # hardware AND a different rung, so it must stamp itself out of the
    # perf trajectory instead of silently masquerading as a regression
    # (BENCH_r03–r05 did exactly that; ROADMAP item 5).
    result["comparable"] = jax.default_backend() != "cpu"
    # exposure accounting (telemetry/overlap.py): the perf trajectory
    # records how much of the grad exchange is overlap-scheduled, not
    # just walls — a wall regression with an unchanged fraction is not
    # an overlap regression (tools/bench_sweep.py carries these into
    # every rung record)
    rep = engine.overlap_report()
    if rep is not None:
        result["overlapped_fraction"] = round(rep.overlapped_fraction, 4)
        result["exposed_collective_seconds_per_step_est"] = round(
            rep.exposed_seconds_per_step, 6)
    # measured decomposition of one profiled step (estimated-vs-measured
    # semantics: docs/OBSERVABILITY.md "Step-time attribution & goodput")
    if timeline_rec is not None:
        result["timeline"] = {
            "measured": timeline_rec["measured"],
            "wall_seconds": round(timeline_rec["wall_seconds"], 6),
            "categories": {k: round(v, 6)
                           for k, v in timeline_rec["categories"].items()},
            "exposed_collective_seconds":
                timeline_rec["exposed_collective_seconds"],
            "overlapped_collective_seconds":
                timeline_rec["overlapped_collective_seconds"],
        }
    if gp is not None:
        try:
            gp.observe_phase("compile", warmup_dt)
            for _ in range(steps):
                gp.observe_step(dt / steps)
            result["goodput"] = gp.summary()
        except Exception as e:
            print(f"bench: goodput ledger failed ({e}); omitting",
                  file=sys.stderr)
    # schedule-shape provenance for pipe rungs: the bubble is structural
    # ((P-1)/(M+P-1)), so a wall regression with an unchanged bubble is
    # not a schedule regression
    struct = getattr(engine, "_pipe_struct", None)
    if struct:
        result["pipe_bubble_fraction"] = round(struct["bubble_fraction"], 4)
        result["pipe_stages"] = struct["stages"]
    # numerics annex: a perf rung doubles as a training-health artifact —
    # layer-norm medians, anomaly counts, and the cross-rank divergence
    # verdict are stamped into the bench JSON so a throughput number that
    # rode a silently-diverging or overflow-storming run is self-labelled
    num = None
    try:
        num = engine.numerics_report()
    except Exception as e:  # the annex must never sink a bench run
        print(f"bench: numerics report failed ({e}); omitting",
              file=sys.stderr)
    if num:
        last = num.get("last_report") or {}
        div = num.get("divergence")

        def _layer_median(key):
            vals = (last.get("layers") or {}).get(key) or []
            return round(float(np.median(vals)), 6) if vals else None

        result["numerics"] = {
            "boundaries": num["boundaries"],
            "anomaly_counts": num["anomaly_counts"],
            "grad_norm_median": num.get("grad_norm_median"),
            "grad_layer_norm_median": _layer_median("grad_norm"),
            "act_layer_norm_median": _layer_median("act_norm"),
            "param_layer_norm_median": _layer_median("param_norm"),
            "grad_nonfinite": last.get("grad_nonfinite"),
            "divergence_ok": None if div is None else bool(div.get("ok")),
            "first_diverging_leaf": (div or {}).get("first_diverging_leaf"),
        }
    # provenance: which program contracts (tests/contracts/*.json) this
    # result ran under — a perf claim is only comparable to another run
    # with the same contract-set hash (same collectives, same donation)
    from deepspeed_tpu.analysis.contracts import contract_set_hash

    result["contract_set_hash"] = contract_set_hash(
        os.path.dirname(os.path.abspath(__file__)))
    reason = os.environ.get("DSTPU_BENCH_FALLBACK_REASON", "")
    if reason and jax.default_backend() == "cpu":
        # gate on backend: a leaked env var must not mislabel a real TPU run
        result["fallback_reason"] = reason
    return result


def _ab_compression() -> None:
    """Deterministic CPU *training* tier (the trainer's sibling of
    ``bench_serving.py --ab-speculative``): fixed tiny model/seq/batch on
    the 8-virtual-device harness, pinned seeds, median-of-k walls,
    ``comparable: true`` — run as an A/B of the compressed-collective
    layer (docs/COMM.md).

    Arm A: stage-1 + hierarchical grad reduce, full-precision hops (the
    explicit-verb path, so the comms logger sees every byte).
    Arm B: the same with the int8 inter-slice exchange
    (``zero_quantized_gradients``).

    Machine-checked claims in the JSON:
      * determinism — arm A re-run from scratch reproduces its loss curve
        bit-for-bit (pinned seeds, CPU);
      * ``wire_reduction`` — logical/wire byte ratio of the compressed
        collectives from the comms-logger columns (>= 2x is the
        acceptance bar; int8 + block scales gives ~3.9x);
      * ``loss_parity_max_rel`` — seed-matched quantized-vs-fp curve gap.
    """
    import jax
    import jax.numpy as jnp

    import deepspeed_tpu
    import deepspeed_tpu.comm as comm
    from deepspeed_tpu.models.llama import llama_model
    from deepspeed_tpu.parallel.mesh import reset_topology

    steps = _int_env("DSTPU_BENCH_AB_STEPS", 6)
    repeats = _int_env("DSTPU_BENCH_AB_REPEATS", 3)
    seq, micro_bs = 32, 1

    cl = comm.configure_comms_logger(enabled=True)

    def run(qgz: bool):
        reset_topology()
        cl.reset()
        model = llama_model("tiny", max_seq_len=seq)
        engine, *_ = deepspeed_tpu.initialize(model=model, config={
            "train_micro_batch_size_per_gpu": micro_bs,
            "gradient_accumulation_steps": 1,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 1,
                                  "zero_hierarchical_grad_reduce": True,
                                  "zero_hierarchy_inner": 2,
                                  "zero_quantized_gradients": qgz},
        })
        dp = engine.topology.dp_world_size
        rng = np.random.RandomState(0)  # pinned: both arms see one stream
        vocab = model.config.vocab_size
        batches = [{"input_ids": jnp.asarray(
            rng.randint(0, vocab, (1, micro_bs * dp, seq)).astype(np.int32))}
            for _ in range(steps)]
        losses = [float(engine.train_batch(b)) for b in batches]
        # bytes are TRACE-time: captured once while the curve ran compiles
        logical = sum(r[1] for axes in cl.comms_dict.values()
                      for r in axes.values())
        wire = sum(r[2] for axes in cl.comms_dict.values()
                   for r in axes.values())
        comp_logical = sum(r[3] for axes in cl.comms_dict.values()
                           for r in axes.values())
        comp_wire = sum(r[4] for axes in cl.comms_dict.values()
                        for r in axes.values())
        # steady-state walls: same shapes, no recompiles
        walls = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            for b in batches:
                loss = engine.train_batch(b)
            jax.block_until_ready(loss)
            walls.append(time.perf_counter() - t0)
        return {"losses": losses, "logical": logical, "wire": wire,
                "comp_logical": comp_logical, "comp_wire": comp_wire,
                "wall_median_s": sorted(walls)[len(walls) // 2]}

    fp = run(qgz=False)
    fp2 = run(qgz=False)  # determinism gate: pinned seeds reproduce exactly
    assert fp["losses"] == fp2["losses"], "CPU tier is not deterministic"
    q = run(qgz=True)
    cl.configure(enabled=False)

    parity = max(abs(a - b) / max(abs(a), 1e-9)
                 for a, b in zip(fp["losses"], q["losses"]))
    wire_reduction = (q["comp_logical"] / q["comp_wire"]
                      if q["comp_wire"] else 1.0)
    from deepspeed_tpu.analysis.contracts import contract_set_hash

    print(json.dumps({
        "metric": "ab-compression: hierarchical stage-1 grad reduce, "
                  "int8 vs fp inter-slice exchange (tiny llama, "
                  f"seq={seq}, steps={steps})",
        "value": round(wire_reduction, 3),
        "unit": "x wire-bytes reduction (compressed collectives)",
        "comparable": True,  # deterministic pinned-seed CPU tier
        "backend": jax.default_backend(),
        "wire_reduction": round(wire_reduction, 3),
        "total_bytes_fp": fp["wire"],
        "total_bytes_int8": q["wire"],
        "total_wire_reduction": round(fp["wire"] / max(q["wire"], 1), 3),
        "loss_parity_max_rel": round(parity, 5),
        "loss_parity_ok": parity < 0.05,
        "final_loss_fp": fp["losses"][-1],
        "final_loss_int8": q["losses"][-1],
        "wall_median_s": {"fp": round(fp["wall_median_s"], 4),
                          "int8": round(q["wall_median_s"], 4)},
        "contract_set_hash": contract_set_hash(
            os.path.dirname(os.path.abspath(__file__))),
    }))


def _ab_overlap() -> None:
    """Deterministic CPU *training* tier for the compute/collective
    overlap (docs/COMM.md "Overlap & scheduling"): fixed tiny scanned
    llama on the 8-virtual-device harness, pinned seeds, median-of-k
    walls, ``comparable: true``.

    Arms, per ZeRO stage in {1, 3}:
      * ``off``        — the legacy GSPMD step (no wrap);
      * ``unbucketed`` — overlap wrap with ``overlap_bucket_mb=0``
        (per-leaf buckets, no coalescing);
      * ``on``         — overlap wrap, default buckets (+
        ``zero3_param_prefetch`` at stage 3);
      * ``int8``       — COMPRESSED overlap (docs/COMM.md "Compressed
        overlap"): the in-loop exchange moves int8 codes + scales with
        ONE error-feedback residual per bucket in train state (stage 1
        via ``zero_quantized_gradients``, stage 3 via
        ``overlap_compression``), plus its own unbucketed twin.

    Machine-checked claims in the JSON:
      * determinism — the ``on`` AND ``int8`` arms re-run from scratch
        reproduce their loss curves bit-for-bit;
      * ``identical_to_unbucketed`` — per compression setting, bucketed
        vs unbucketed losses are BIT-EXACT (fp: scheduling only; int8:
        block-aligned coalescing + layout-stable hop-1 residuals);
      * ``loss_parity_max_rel`` — ``on`` vs ``off`` is fp reassociation
        noise, asserted < 1e-4; ``int8`` vs ``on`` is codec noise,
        asserted at the PR-11 tolerance (< 0.05);
      * ``wire_reduction`` — compressed-subset logical/wire bytes from
        the comms logger during the ``int8`` arm, gated >= 2x vs the
        fp32-overlap payloads;
      * ``overlapped_fraction`` per arm (0 for ``off``), the bucket
        count, compression + residual bytes, traceable to the
        ``train_step_zero*_overlap*`` goldens via ``contract_set_hash``.
    """
    import jax
    import jax.numpy as jnp

    import deepspeed_tpu
    import deepspeed_tpu.comm as comm
    from deepspeed_tpu.models.llama import llama_model
    from deepspeed_tpu.parallel.mesh import reset_topology

    steps = _int_env("DSTPU_BENCH_AB_STEPS", 6)
    repeats = _int_env("DSTPU_BENCH_AB_REPEATS", 3)
    seq, micro_bs = 32, 1
    cl = comm.configure_comms_logger(enabled=True)

    def run(stage, overlap, bucket_mb=4.0, prefetch=False,
            compressed=False):
        reset_topology()
        cl.reset()
        model = llama_model("tiny", max_seq_len=seq)
        zero_cfg = {"stage": stage, "overlap_grad_reduce": overlap,
                    "overlap_bucket_mb": bucket_mb}
        if prefetch:
            zero_cfg["zero3_param_prefetch"] = True
        if compressed:
            if stage <= 2:
                zero_cfg["zero_quantized_gradients"] = True
            else:
                zero_cfg["overlap_compression"] = "int8"
        engine, *_ = deepspeed_tpu.initialize(model=model, config={
            "train_micro_batch_size_per_gpu": micro_bs,
            "gradient_accumulation_steps": 1,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": zero_cfg,
        })
        dp = engine.topology.dp_world_size
        rng = np.random.RandomState(0)  # pinned: every arm sees one stream
        vocab = model.config.vocab_size
        batches = [{"input_ids": jnp.asarray(
            rng.randint(0, vocab, (1, micro_bs * dp, seq)).astype(np.int32))}
            for _ in range(steps)]
        losses = [float(engine.train_batch(b)) for b in batches]
        # compressed-subset bytes are TRACE-time (captured while the
        # curve ran its compiles): what the quantized payloads moved vs
        # what fp32 would have moved for the same payloads
        comp_logical = sum(r[3] for axes in cl.comms_dict.values()
                           for r in axes.values())
        comp_wire = sum(r[4] for axes in cl.comms_dict.values()
                        for r in axes.values())
        walls = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            for b in batches:
                loss = engine.train_batch(b)
            jax.block_until_ready(loss)
            walls.append(time.perf_counter() - t0)
        rep = engine.overlap_report()
        # measured exposed-collective seconds (profiled extra step,
        # outside the timed window) next to the modeled byte-model
        # number; None when the backend yields no device trace (CPU)
        measured_exposed, tl_measured = None, False
        try:
            from deepspeed_tpu.telemetry.timeline import capture_thunk

            _, tl_rec = capture_thunk(
                lambda: float(engine.train_batch(batches[0])))
            if tl_rec is not None and tl_rec["measured"]:
                tl_measured = True
                measured_exposed = round(
                    tl_rec["exposed_collective_seconds"], 6)
        except Exception:
            pass  # attribution must never sink the A/B
        return {"losses": losses,
                "wall_median_s": sorted(walls)[len(walls) // 2],
                "overlapped_fraction": (round(rep.overlapped_fraction, 4)
                                        if rep else 0.0),
                "exposed_seconds_per_step_est": (
                    round(rep.exposed_seconds_per_step, 6) if rep else None),
                "exposed_seconds_per_step_measured": measured_exposed,
                "timeline_measured": tl_measured,
                "buckets": rep.buckets if rep else 0,
                "compression": rep.compression if rep else None,
                "residual_bytes": rep.residual_bytes if rep else 0,
                "comp_logical": comp_logical, "comp_wire": comp_wire}

    out = {"metric": "ab-overlap: per-layer-bucket grad reduce + stage-3 "
                     f"gather prefetch vs the post-backward block, with a "
                     f"compressed (int8-in-loop + EF) arm (tiny llama, "
                     f"seq={seq}, steps={steps})",
           "unit": "overlapped fraction of grad-exchange bytes",
           "comparable": True,  # deterministic pinned-seed CPU tier
           "stages": {}}
    worst_parity = 0.0
    worst_qparity = 0.0
    worst_wire = float("inf")
    for stage in (1, 3):
        off = run(stage, overlap=False)
        unb = run(stage, overlap=True, bucket_mb=0.0,
                  prefetch=(stage == 3))
        on = run(stage, overlap=True, prefetch=(stage == 3))
        on2 = run(stage, overlap=True, prefetch=(stage == 3))
        assert on["losses"] == on2["losses"], \
            f"stage {stage}: CPU tier is not deterministic"
        identical = on["losses"] == unb["losses"]
        assert identical, (
            f"stage {stage}: bucketed overlap diverged from the "
            f"unbucketed path — scheduling changed the math\n"
            f"on:  {on['losses']}\nunb: {unb['losses']}")
        q = run(stage, overlap=True, prefetch=(stage == 3),
                compressed=True)
        q2 = run(stage, overlap=True, prefetch=(stage == 3),
                 compressed=True)
        assert q["losses"] == q2["losses"], \
            f"stage {stage}: compressed arm is not deterministic"
        q_unb = run(stage, overlap=True, bucket_mb=0.0,
                    prefetch=(stage == 3), compressed=True)
        q_identical = q["losses"] == q_unb["losses"]
        assert q_identical, (
            f"stage {stage}: compressed bucketed overlap diverged from "
            f"its unbucketed twin — the block-aligned coalesce / "
            f"layout-stable residual contract broke\n"
            f"int8:  {q['losses']}\nunb:   {q_unb['losses']}")
        assert q["compression"] == "int8", q["compression"]
        # wire claim: the quantized in-loop payloads move >= 2x fewer
        # bytes than the same payloads at fp32 width (the fp32-overlap
        # arm's wire volume for the compressed subset)
        wire_reduction = (q["comp_logical"] / q["comp_wire"]
                          if q["comp_wire"] else 0.0)
        assert wire_reduction >= 2.0, (
            f"stage {stage}: compressed overlap wire reduction "
            f"{wire_reduction:.2f}x < 2x")
        worst_wire = min(worst_wire, wire_reduction)
        parity = max(abs(a - b) / max(abs(a), 1e-9)
                     for a, b in zip(off["losses"], on["losses"]))
        worst_parity = max(worst_parity, parity)
        qparity = max(abs(a - b) / max(abs(a), 1e-9)
                      for a, b in zip(on["losses"], q["losses"]))
        worst_qparity = max(worst_qparity, qparity)
        out["stages"][f"zero{stage}"] = {
            "contract": ("train_step_zero1_overlap" if stage == 1
                         else "train_step_zero3_prefetch"),
            "contract_int8": ("train_step_zero1_overlap_int8" if stage == 1
                              else "train_step_zero3_prefetch_int8"),
            "identical_to_unbucketed": identical,
            "int8_identical_to_unbucketed": q_identical,
            "loss_parity_max_rel_vs_off": round(parity, 7),
            "loss_parity_max_rel_int8_vs_fp_overlap": round(qparity, 7),
            "final_loss_off": off["losses"][-1],
            "final_loss_on": on["losses"][-1],
            "final_loss_int8": q["losses"][-1],
            "overlapped_fraction": on["overlapped_fraction"],
            "overlapped_fraction_int8": q["overlapped_fraction"],
            # modeled (byte-model) vs measured (device-trace) exposure:
            # est comes from the overlap report, measured from one
            # profiled step (null on CPU — measured: false)
            "exposed_seconds_per_step_est": {
                "on": on["exposed_seconds_per_step_est"],
                "int8": q["exposed_seconds_per_step_est"]},
            "exposed_seconds_per_step_measured": {
                "on": on["exposed_seconds_per_step_measured"],
                "int8": q["exposed_seconds_per_step_measured"]},
            "timeline_measured": on["timeline_measured"],
            "buckets": on["buckets"],
            "wire_reduction_int8": round(wire_reduction, 3),
            "residual_bytes_int8": q["residual_bytes"],
            "wall_median_s": {"off": round(off["wall_median_s"], 4),
                              "unbucketed": round(unb["wall_median_s"], 4),
                              "on": round(on["wall_median_s"], 4),
                              "int8": round(q["wall_median_s"], 4)},
        }
    cl.configure(enabled=False)
    assert worst_parity < 1e-4, \
        f"overlap-on vs overlap-off loss gap {worst_parity} is not " \
        "reassociation-sized"
    assert worst_qparity < 0.05, \
        f"int8-overlap vs fp32-overlap loss gap {worst_qparity} exceeds " \
        "the PR-11 codec tolerance"
    import jax as _jax

    out["backend"] = _jax.default_backend()
    out["value"] = out["stages"]["zero1"]["overlapped_fraction"]
    out["loss_parity_ok"] = worst_parity < 1e-4 and worst_qparity < 0.05
    out["wire_reduction_min"] = round(worst_wire, 3)
    out["wire_reduction_ok"] = worst_wire >= 2.0
    from deepspeed_tpu.analysis.contracts import contract_set_hash

    out["contract_set_hash"] = contract_set_hash(
        os.path.dirname(os.path.abspath(__file__)))
    print(json.dumps(out))


def _ab_pipe() -> None:
    """Deterministic CPU *training* tier for pipeline parallelism
    (docs/PIPELINE.md): fixed tiny llama on the 8-virtual-device
    harness, pinned seeds, median-of-k walls, ``comparable: true``.

    Arms, at EQUAL global batch (8 rows/step):
      * ``control`` — single-stage (pipe=1) with the pipe schedule
        FORCED, data=2: the same scan/ppermute program shape with
        identity hops, so any pipe-vs-control gap is the schedule's
        math, not a different program;
      * ``pipe2``   — 2 stages x 2 data, full-precision hops;
      * ``int8hop`` — 2 stages x 2 data, int8 activation hops with
        error feedback (``pipeline.hop_compression``) PLUS the
        bubble-overlapped int8 in-scan grad reduce (stage 1 +
        ``overlap_grad_reduce`` + ``overlap_compression``).

    Machine-checked claims in the JSON:
      * determinism — the control arm re-run from scratch reproduces
        its loss curve bit-for-bit;
      * ``pipe_bit_exact`` — pipe2 vs control losses are BIT-EXACT (the
        1F1B schedule is a reassociation-free reshuffle of the same
        microbatch math; arms share initial params by value because
        jitted init is sharding-dependent under non-partitionable
        threefry);
      * ``hop_wire_reduction`` — logical/wire bytes of the compressed
        ppermute rows from the comms logger during the int8 arm,
        gated >= 2x;
      * ``loss_parity_max_rel`` — int8hop vs pipe2 codec gap, < 0.05;
      * ``bubble_fraction`` — the published (P-1)/(M+P-1) schedule
        bubble, traceable to the ``train_step_pipe2`` golden via
        ``contract_set_hash``.
    """
    import dataclasses

    import jax
    import jax.numpy as jnp

    import deepspeed_tpu
    import deepspeed_tpu.comm as comm
    from deepspeed_tpu.models.llama import llama_config
    from deepspeed_tpu.parallel.mesh import (MeshConfig, initialize_topology,
                                             reset_topology)
    from deepspeed_tpu.runtime.pipe.engine import pipelined_causal_lm

    steps = _int_env("DSTPU_BENCH_AB_STEPS", 6)
    repeats = _int_env("DSTPU_BENCH_AB_REPEATS", 3)
    seq, vocab, micro_bs, num_micro = 32, 64, 4, 2
    cl = comm.configure_comms_logger(enabled=True)
    ref_params = {}

    def run(mesh_cfg, n_dev, extra_cfg, force_schedule=False):
        reset_topology()
        cl.reset()
        topo = initialize_topology(mesh_cfg, jax.devices()[:n_dev])
        cfg = llama_config("tiny", max_seq_len=seq, vocab_size=vocab,
                           n_layers=2, attn_impl="xla")
        model = pipelined_causal_lm(cfg, num_microbatches=num_micro,
                                    force_schedule=force_schedule)
        config = {"train_micro_batch_size_per_gpu": micro_bs,
                  "gradient_accumulation_steps": 1,
                  "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}}
        config.update(extra_cfg)
        engine, *_ = deepspeed_tpu.initialize(model=model, config=config,
                                              topology=topo)
        # equal-global-batch arms must share initial params BY VALUE:
        # jitted init with out_shardings draws DIFFERENT randoms per
        # mesh under the non-partitionable threefry
        if not ref_params:
            ref_params["p"] = jax.device_get(engine.state.params)
        else:
            shared = jax.tree_util.tree_map(
                lambda r, p: jax.device_put(r, p.sharding),
                ref_params["p"], engine.state.params)
            engine.state = dataclasses.replace(engine.state, params=shared)
        dp = engine.topology.dp_world_size
        rng = np.random.RandomState(0)  # pinned: every arm sees one stream
        batches = [{"input_ids": jnp.asarray(
            rng.randint(0, vocab, (1, micro_bs * dp, seq)).astype(np.int32))}
            for _ in range(steps)]
        losses = [float(engine.train_batch(b)) for b in batches]
        # hop bytes are TRACE-time: the compressed-subset columns of the
        # ppermute rows are exactly the int8 activation hops (plain fp
        # hops go through lax.ppermute and never log)
        hop_rows = cl.comms_dict.get("ppermute", {})
        hop_logical = sum(r[3] for r in hop_rows.values())
        hop_wire = sum(r[4] for r in hop_rows.values())
        walls = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            for b in batches:
                loss = engine.train_batch(b)
            jax.block_until_ready(loss)
            walls.append(time.perf_counter() - t0)
        # measured bubble/exposure from one profiled step (outside the
        # timed window) next to the structural (P-1)/(M+P-1) claim;
        # None when the backend yields no device trace (CPU)
        struct = getattr(engine, "_pipe_struct", None)
        measured_exposed, measured_bubble, tl_measured = None, None, False
        try:
            from deepspeed_tpu.telemetry.timeline import capture_thunk

            _, tl_rec = capture_thunk(
                lambda: float(engine.train_batch(batches[0])),
                pipe_struct=struct)
            if tl_rec is not None and tl_rec["measured"]:
                tl_measured = True
                measured_exposed = round(
                    tl_rec["exposed_collective_seconds"], 6)
                measured_bubble = round(
                    tl_rec["categories"].get("pipe_bubble", 0.0), 6)
        except Exception:
            pass  # attribution must never sink the A/B
        return {"losses": losses, "hop_logical": hop_logical,
                "hop_wire": hop_wire,
                "wall_median_s": sorted(walls)[len(walls) // 2],
                "exposed_seconds_per_step_measured": measured_exposed,
                "pipe_bubble_seconds_measured": measured_bubble,
                "timeline_measured": tl_measured,
                "pipe_struct": struct}

    ctl = run(MeshConfig(data=2), 2, {"mesh": {"data": 2}},
              force_schedule=True)
    ctl2 = run(MeshConfig(data=2), 2, {"mesh": {"data": 2}},
               force_schedule=True)
    assert ctl["losses"] == ctl2["losses"], "CPU tier is not deterministic"
    pipe = run(MeshConfig(pipe=2, data=2), 4, {"mesh": {"pipe": 2, "data": 2}})
    bit_exact = ctl["losses"] == pipe["losses"]
    assert bit_exact, (
        "pipe=2 diverged from the single-stage control at equal global "
        "batch — the 1F1B schedule changed the math\n"
        f"ctl:  {ctl['losses']}\npipe: {pipe['losses']}")
    # block=64 matches the tiny model's hidden dim: the default 128-wide
    # blocks would PAD each 64-element hop row to 128 codes and cap the
    # measurable reduction at 1.94x on this toy — a harness artifact, not
    # a codec property (real hidden dims are multiples of 128)
    q = run(MeshConfig(pipe=2, data=2), 4,
            {"mesh": {"pipe": 2, "data": 2},
             "pipeline": {"hop_compression": {"format": "int8",
                                              "block": 64}},
             "zero_optimization": {"stage": 1, "overlap_grad_reduce": True,
                                   "overlap_compression": "int8",
                                   "overlap_bucket_mb": 1}})
    cl.configure(enabled=False)
    parity = max(abs(a - b) / max(abs(a), 1e-9)
                 for a, b in zip(pipe["losses"], q["losses"]))
    assert parity < 0.05, (
        f"int8-hop loss gap {parity} vs the fp pipe arm exceeds the codec "
        "tolerance")
    hop_reduction = (q["hop_logical"] / q["hop_wire"]
                     if q["hop_wire"] else 0.0)
    assert hop_reduction >= 2.0, (
        f"int8 activation hops moved only {hop_reduction:.2f}x fewer "
        "wire bytes (< 2x): the compressed ppermute fell back to fp")
    struct = q["pipe_struct"] or {}
    from deepspeed_tpu.analysis.contracts import contract_set_hash

    print(json.dumps({
        "metric": "ab-pipe: 2-stage 1F1B pipeline vs single-stage control "
                  "at equal global batch, int8 activation hops + "
                  f"bubble-overlapped int8 grad reduce (tiny llama, "
                  f"seq={seq}, steps={steps})",
        "value": round(hop_reduction, 3),
        "unit": "x wire-bytes reduction (int8 activation hops)",
        "comparable": True,  # deterministic pinned-seed CPU tier
        "backend": jax.default_backend(),
        "pipe_bit_exact": bit_exact,
        "loss_parity_max_rel": round(parity, 7),
        "loss_parity_ok": parity < 0.05,
        "hop_wire_reduction": round(hop_reduction, 3),
        "hop_bytes_logical": q["hop_logical"],
        "hop_bytes_wire": q["hop_wire"],
        "bubble_fraction": struct.get("bubble_fraction"),
        # measured (device-trace) columns next to the modeled ones:
        # null on CPU, where the profiler yields no device timeline
        "pipe_bubble_seconds_measured": {
            "control": ctl["pipe_bubble_seconds_measured"],
            "pipe2": pipe["pipe_bubble_seconds_measured"],
            "int8hop": q["pipe_bubble_seconds_measured"]},
        "exposed_seconds_per_step_measured": {
            "control": ctl["exposed_seconds_per_step_measured"],
            "pipe2": pipe["exposed_seconds_per_step_measured"],
            "int8hop": q["exposed_seconds_per_step_measured"]},
        "timeline_measured": q["timeline_measured"],
        "stages": struct.get("stages"),
        "num_micro": struct.get("num_micro"),
        "final_loss_control": ctl["losses"][-1],
        "final_loss_pipe2": pipe["losses"][-1],
        "final_loss_int8hop": q["losses"][-1],
        "wall_median_s": {"control": round(ctl["wall_median_s"], 4),
                          "pipe2": round(pipe["wall_median_s"], 4),
                          "int8hop": round(q["wall_median_s"], 4)},
        "contract": "train_step_pipe2",
        "contract_set_hash": contract_set_hash(
            os.path.dirname(os.path.abspath(__file__))),
    }))


def _release_device_memory() -> None:
    """Free every live device array before retrying a smaller rung.

    A failed rung's engine (params + fp32 master + Adam state, ~2 GB for
    the 160m model) is pinned by the exception traceback's frames while
    the handler runs, and jax frees buffers asynchronously after that —
    so without an explicit sweep the NEXT rung's init races against the
    previous rung's deallocation and can OOM at a size that fits fine in
    a fresh process (observed: bs=8 OOM inside the ladder, fine alone).
    """
    import gc

    import jax

    # drop traceback -> frame -> engine references first, then delete
    # whatever arrays remain alive (nothing is reused across rungs)
    gc.collect()
    for arr in jax.live_arrays():
        try:
            arr.delete()
        except Exception:
            pass


def main() -> None:
    import jax

    on_tpu = jax.default_backend() != "cpu"
    size = os.environ.get("DSTPU_BENCH_SIZE", "160m" if on_tpu else "tiny")
    seq = int(os.environ.get("DSTPU_BENCH_SEQ", 1024 if on_tpu else 64))
    steps = int(os.environ.get("DSTPU_BENCH_STEPS", 20 if on_tpu else 3))
    if os.environ.get("DSTPU_BENCH_BS"):
        ladder = [int(os.environ["DSTPU_BENCH_BS"])]
    else:
        # larger micro-batch feeds the MXU better (M = bs*seq rows); fall
        # back on OOM so a too-ambitious first rung can't zero the bench
        ladder = [32, 16, 8] if on_tpu else [2]
    result = None
    # phase 1: default kernels; phase 2 (entered only on a Pallas/Mosaic
    # lowering failure): XLA attention, still on the accelerator — slower,
    # but far better than the final CPU fallback.  OOM checks run FIRST at
    # every rung: a RESOURCE_EXHAUSTED whose message mentions the pallas
    # kernel is memory pressure, not a lowering failure.
    env_attn = os.environ.get("DSTPU_BENCH_ATTN")
    phases = (None,) if env_attn else (None, "xla")
    bs_pinned = bool(os.environ.get("DSTPU_BENCH_BS"))
    for attn in phases:
        if attn is None:
            bs_ladder = ladder
        elif bs_pinned:
            bs_ladder = ladder  # honor an explicit bs pin in phase 2 too
        else:
            # xla attention needs more HBM than flash; dedup after capping
            bs_ladder = list(dict.fromkeys(min(b, 8) for b in ladder))
        mosaic_failure = False
        for i, bs in enumerate(bs_ladder):
            try:
                result = _run(size, seq, bs, steps, attn_impl=attn)
                break
            except Exception as e:
                msg = str(e)
                _release_device_memory()
                oom = "RESOURCE_EXHAUSTED" in msg or "memory" in msg.lower()
                if oom:
                    if i + 1 >= len(bs_ladder):
                        raise
                    print(f"bench: bs={bs} OOM; trying bs={bs_ladder[i + 1]}",
                          file=sys.stderr)
                    continue
                if attn is None and ("mosaic" in msg.lower()
                                     or "pallas" in msg.lower()):
                    print("bench: Pallas kernel failed to lower; retrying "
                          "with attn_impl=xla", file=sys.stderr)
                    mosaic_failure = True
                    break
                raise
        if result is not None or not mosaic_failure:
            break
    print(json.dumps(result))


def _cpu_fallback(reason: str) -> int:
    """Re-run the whole bench on CPU in a fresh process, recording why."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               DSTPU_BENCH_FALLBACK_REASON=reason)
    return subprocess.run([sys.executable, __file__, "--cpu"],
                          env=env).returncode


def _parent_ladder() -> int:
    """Run each accelerator rung in a CHILD process with a hard timeout.

    Round-4 field observation: a rung can HANG mid-run (bs=16 sat >400s
    inside a dispatch the lease never served) — an in-process ladder then
    hangs the whole benchmark and the round records no artifact at all.
    The parent never initializes jax itself; it probes in a subprocess,
    spawns one child per rung, kills a wedged rung at the budget, and
    classifies the child's failure (OOM -> smaller bs; Pallas lowering ->
    XLA attention; hang -> re-probe, and straight to the CPU fallback if
    the kill wedged the lease).
    """
    size = os.environ.get("DSTPU_BENCH_SIZE", "160m")
    seq = int(os.environ.get("DSTPU_BENCH_SEQ", 1024))
    steps = int(os.environ.get("DSTPU_BENCH_STEPS", 20))
    bs_pinned = bool(os.environ.get("DSTPU_BENCH_BS"))
    ladder = ([int(os.environ["DSTPU_BENCH_BS"])] if bs_pinned
              else [32, 16, 8])
    # budget per rung: compile (~40s on the tunneled chip, more for big
    # models) + warmup + timed steps; generous but finite
    rung_timeout = _int_env("DSTPU_BENCH_RUNG_TIMEOUT", 900)
    env_attn = os.environ.get("DSTPU_BENCH_ATTN")
    # children get an EXPLICIT attn pin either way ("flash" = phase 1) so
    # a child never runs its own in-process phase fallback
    phases = (env_attn,) if env_attn else ("flash", "xla")
    for attn in phases:
        if attn == "xla" and not env_attn and not bs_pinned:
            # xla attention needs more HBM than flash; dedup after capping
            bs_ladder = list(dict.fromkeys(min(b, 8) for b in ladder))
        else:
            bs_ladder = ladder
        mosaic_failure = False
        for i, bs in enumerate(bs_ladder):
            env = _pin_overlap_flags(dict(
                os.environ, DSTPU_BENCH_SIZE=size,
                DSTPU_BENCH_SEQ=str(seq), DSTPU_BENCH_STEPS=str(steps),
                DSTPU_BENCH_BS=str(bs), DSTPU_BENCH_ATTN=attn))
            try:
                proc = subprocess.run([sys.executable, __file__, "--child"],
                                      capture_output=True, text=True, env=env,
                                      timeout=rung_timeout)
            except subprocess.TimeoutExpired:
                print(f"bench: rung bs={bs} attn={attn} hung "
                      f">{rung_timeout}s; killed", file=sys.stderr)
                # a killed client can wedge the tunnel lease — one quick
                # probe decides between the next rung and the CPU fallback
                os.environ["DSTPU_BENCH_PROBE_RETRIES"] = "0"
                ok, _, _ = _backend_usable()
                if not ok:
                    return _cpu_fallback(
                        f"rung bs={bs} hung >{rung_timeout}s and the kill "
                        f"wedged the backend lease")
                continue
            lines = proc.stdout.strip().splitlines()
            last = lines[-1] if lines else ""
            if proc.returncode == 0 and last:
                print(last)
                return 0
            # classify on the child's own error marker; stderr tail only
            # as a last resort (e.g. the child was killed by a signal)
            try:
                err = json.loads(last)["child_error"]
            except (ValueError, TypeError, KeyError):
                err = proc.stderr[-2000:]
            oom = "RESOURCE_EXHAUSTED" in err or "memory" in err.lower()
            if oom and (i + 1 < len(bs_ladder)):
                print(f"bench: bs={bs} OOM; trying bs={bs_ladder[i + 1]}",
                      file=sys.stderr)
                continue
            if attn != "xla" and not env_attn and (
                    "mosaic" in err.lower() or "pallas" in err.lower()):
                print("bench: Pallas kernel failed to lower; retrying with "
                      "attn_impl=xla", file=sys.stderr)
                mosaic_failure = True
                break
            if oom:  # smallest rung: xla attention would only need MORE
                return _cpu_fallback(
                    f"OOM at the smallest rung (bs={bs}, attn={attn})")
            return _cpu_fallback(f"mid-run failure on configured backend: "
                                 f"{err[-300:]}")
        if not mosaic_failure:
            # every rung of this phase hung; phase 2 would hang the same
            return _cpu_fallback("all accelerator rungs hung past the "
                                 f"{rung_timeout}s budget")
    return _cpu_fallback("Pallas lowering failed and the XLA-attention "
                         "phase found no usable rung")


if __name__ == "__main__":
    if "--ab-overlap" in sys.argv:
        # deterministic CPU tier: 8 virtual devices, pinned platform
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        _pin_cpu()
        _ab_overlap()
    elif "--ab-pipe" in sys.argv:
        # deterministic CPU tier: 8 virtual devices (2-stage x 2-data
        # pipe mesh + the single-stage control), pinned platform
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        _pin_cpu()
        _ab_pipe()
    elif "--ab-compression" in sys.argv:
        # the deterministic CPU training tier needs the 8-virtual-device
        # harness (hierarchy split of the data axis) — pin BEFORE jax loads
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        _pin_cpu()
        _ab_compression()
    elif "--child" in sys.argv:
        # one pinned rung on the configured backend; a failure exits
        # nonzero with a machine-readable marker as the LAST stdout line,
        # so the parent classifies the exception message itself — not the
        # raw stderr tail, where jax runtime log noise (e.g. a benign
        # "memory_space" line) could masquerade as an OOM
        if "--cpu" in sys.argv:
            _pin_cpu()
        try:
            main()
        except Exception as e:
            import traceback

            traceback.print_exc()
            print(json.dumps(
                {"child_error": f"{type(e).__name__}: {str(e)[:500]}"}))
            sys.exit(1)
    elif "--cpu" in sys.argv:
        _pin_cpu()
        main()
    else:
        usable, reason, backend = _backend_usable()
        if not usable:
            os.environ["DSTPU_BENCH_FALLBACK_REASON"] = reason
            _pin_cpu()
            main()
        elif backend == "cpu":
            # the probe short-circuits on JAX_PLATFORMS=cpu, but a site
            # PJRT plugin may have pinned another platform via jax.config
            # (env var alone does not override) — pin for real or main()
            # hangs on the very backend the probe promised to avoid
            _pin_cpu()
            main()  # no accelerator: in-process, nothing can wedge
        else:
            sys.exit(_parent_ladder())
