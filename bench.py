"""Benchmark: llama causal-LM training throughput on the local chip(s).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The comparator: the reference's headline sustained utilization is 54% of
hardware peak (Ulysses blog, BASELINE.md) — ``vs_baseline`` is our achieved
model-flops-utilization divided by 0.54, i.e. >1.0 means we beat the
reference's utilization on our hardware.
"""

from __future__ import annotations

import json
import time

import numpy as np

PEAK_BF16_FLOPS = {
    # per-chip peak bf16 FLOP/s
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v4": 275e12,
    "TPU v6 lite": 918e12,
    "cpu": 1e12,  # nominal, so CPU runs still report something
}


def _peak_for(device) -> float:
    kind = getattr(device, "device_kind", "cpu")
    for name, peak in PEAK_BF16_FLOPS.items():
        if name.lower() in str(kind).lower():
            return peak
    return PEAK_BF16_FLOPS["cpu"]


def main() -> None:
    import jax
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.models.llama import llama_model
    from deepspeed_tpu.models.transformer import flops_per_token

    on_tpu = jax.default_backend() != "cpu"
    size = "160m" if on_tpu else "tiny"
    seq = 1024 if on_tpu else 64
    micro_bs = 8 if on_tpu else 2
    steps = 20 if on_tpu else 3

    model = llama_model(size, max_seq_len=seq)
    config = {
        "train_micro_batch_size_per_gpu": micro_bs,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4, "weight_decay": 0.1}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "gradient_clipping": 1.0,
    }
    engine, *_ = deepspeed_tpu.initialize(model=model, config=config)
    dp = engine.topology.dp_world_size
    n_chips = engine.topology.world_size

    rng = np.random.RandomState(0)
    vocab = model.config.vocab_size

    def batch():
        ids = rng.randint(0, vocab, (1, micro_bs * dp, seq)).astype(np.int32)
        return {"input_ids": jnp.asarray(ids)}

    # warmup / compile
    loss = engine.train_batch(batch())
    jax.block_until_ready(loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine.train_batch(batch())
    jax.block_until_ready(loss)
    # force a host roundtrip of real data: on remote/tunneled devices a bare
    # block_until_ready can return before execution finishes, which would
    # report impossible (>1) MFU
    final_loss = float(loss)
    dt = time.perf_counter() - t0
    assert np.isfinite(final_loss)

    tokens = steps * micro_bs * dp * seq
    tok_per_sec_chip = tokens / dt / n_chips
    model_flops = flops_per_token(model.config, seq) * tokens
    mfu = model_flops / dt / (n_chips * _peak_for(jax.devices()[0]))

    print(json.dumps({
        "metric": f"llama-{size} bf16 zero1 tokens/sec/chip (seq={seq}, mfu={mfu:.3f})",
        "value": round(tok_per_sec_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.54, 3),
    }))


if __name__ == "__main__":
    main()
