"""Perf probe: time each piece of the training step on the real chip.

Every timed jit returns ONE SCALAR so the tunnel transfers nothing big;
the scalar depends on every output we care about (no DCE).

Usage: python tools/perf_probe.py [--size 160m] [--seq 1024] [--bs 16]
"""

from __future__ import annotations

import argparse
import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn, *args, steps=10, warmup=2):
    for _ in range(warmup):
        out = fn(*args)
    float(out)  # real host roundtrip (tunneled block_until_ready lies)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(*args)
    float(out)
    return (time.perf_counter() - t0) / steps


def tree_sumsq(tree):
    return sum(jnp.sum(jnp.square(x.astype(jnp.float32)))
               for x in jax.tree_util.tree_leaves(tree))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", default="160m")
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--bs", type=int, default=16)
    ap.add_argument("--steps", type=int, default=10)
    args = ap.parse_args()

    from deepspeed_tpu.models.llama import llama_config
    from deepspeed_tpu.models.transformer import (causal_lm_loss,
                                                  flops_per_token,
                                                  init_transformer_params,
                                                  logits_fn,
                                                  transformer_forward)

    cfg = llama_config(args.size, max_seq_len=args.seq)
    rng = jax.random.PRNGKey(0)
    params32 = init_transformer_params(cfg, rng)
    params = jax.tree_util.tree_map(lambda x: x.astype(jnp.bfloat16), params32)
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    ids = jnp.asarray(
        np.random.RandomState(0).randint(0, cfg.vocab_size, (args.bs, args.seq)),
        jnp.int32)
    batch = {"input_ids": ids}

    tokens = args.bs * args.seq
    fpt = flops_per_token(cfg, args.seq)
    peak = 197e12
    fwd_frac = 1.0 / 3.0

    def report(name, dt, frac=1.0):
        mfu = fpt * tokens * frac / dt / peak
        print(f"{name:44s} {dt*1e3:8.2f} ms   mfu={mfu:.3f}", flush=True)

    print(f"size={args.size} params={n_params/1e6:.1f}M seq={args.seq} "
          f"bs={args.bs} flops/tok={fpt/1e9:.2f}G ideal_fwdbwd="
          f"{fpt*tokens/peak*1e3:.1f}ms", flush=True)

    def make_loss(c):
        return lambda p, b: causal_lm_loss(c, p, b)

    c = llama_config(args.size, max_seq_len=args.seq, attn_impl="flash")
    report("fwd-only [flash512]",
           timeit(jax.jit(make_loss(c)), params, batch, steps=args.steps),
           fwd_frac)

    def grad_scalar(loss_fn):
        def f(p, b):
            g = jax.grad(loss_fn)(p, b)
            return tree_sumsq(g)
        return jax.jit(f)

    report("fwd+bwd  [flash512]",
           timeit(grad_scalar(make_loss(c)), params, batch, steps=args.steps))

    # flash block sweep
    for bq, bk in [(512, 1024), (1024, 512), (256, 1024), (1024, 256)]:
        def loss_blk(p, b, _bq=bq, _bk=bk):
            return _loss_custom(cfg, p, b, ce="plain", bq=_bq, bk=_bk)
        try:
            report(f"fwd+bwd flash bq={bq} bk={bk}",
                   timeit(grad_scalar(loss_blk), params, batch,
                          steps=args.steps))
        except Exception as e:
            print(f"flash bq={bq} bk={bk}: {type(e).__name__}: {str(e)[:100]}",
                  flush=True)

    # CE variants at flash 512/1024
    for ce in ["lse", "chunk"]:
        def loss_ce(p, b, _ce=ce):
            return _loss_custom(cfg, p, b, ce=_ce, bq=512, bk=1024)
        report(f"fwd+bwd CE={ce} flash512/1024",
               timeit(grad_scalar(loss_ce), params, batch, steps=args.steps))

    # forward without the lm_head/loss at all (isolate trunk vs head)
    def trunk_only(p, b):
        h, aux = transformer_forward(cfg, p, b["input_ids"])
        return jnp.sum(h.astype(jnp.float32)) + aux
    report("fwd+bwd trunk-only (no head/CE)",
           timeit(grad_scalar(trunk_only), params, batch, steps=args.steps))

    # head+CE only (frozen hidden)
    hidden = jax.jit(lambda p, b: transformer_forward(
        cfg, p, b["input_ids"])[0])(params, batch)

    def head_only(p, h):
        logits = logits_fn(cfg, p, h[:, :-1]).astype(jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, ids[:, 1:][..., None], -1)[..., 0]
        return jnp.mean(lse - tgt)

    def head_grad(p, h):
        return tree_sumsq(jax.grad(head_only)(p, h))
    report("fwd+bwd head+CE only",
           timeit(jax.jit(head_grad), params, hidden, steps=args.steps))

    # optimizer apply
    import optax
    opt = optax.adamw(1e-4, weight_decay=0.1)
    opt_state = opt.init(params32)
    grads = jax.tree_util.tree_map(jnp.ones_like, params32)

    @jax.jit
    def apply(p, s, g):
        u, s2 = opt.update(g, s, p)
        p2 = optax.apply_updates(p, u)
        return tree_sumsq(p2) + tree_sumsq(jax.tree_util.tree_leaves(s2)[0])

    dt = timeit(apply, params32, opt_state, grads, steps=args.steps)
    print(f"{'adamw apply (fp32 master)':44s} {dt*1e3:8.2f} ms", flush=True)


def _loss_custom(cfg, params, batch, ce: str, bq: int, bk: int):
    """causal LM loss with pinned flash blocks and a chosen CE formulation."""
    import deepspeed_tpu.models.transformer as tf_mod
    from deepspeed_tpu.models.transformer import logits_fn, transformer_forward
    from deepspeed_tpu.ops.pallas.flash_attention import flash_attention

    orig = tf_mod._pick_attn
    tf_mod._pick_attn = lambda c: (
        lambda q, k, v, causal, mask=None: flash_attention(
            q, k, v, causal=causal, segment_mask=mask, block_q=bq, block_k=bk))
    try:
        ids = batch["input_ids"]
        hidden, aux = transformer_forward(cfg, params, ids)
        hidden = hidden[:, :-1]
        targets = ids[:, 1:]
        if ce == "plain":
            logits = logits_fn(cfg, params, hidden)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            nll = -jnp.take_along_axis(logp, targets[..., None], -1)[..., 0]
            return jnp.mean(nll) + aux
        if ce == "lse":
            logits = logits_fn(cfg, params, hidden).astype(jnp.float32)
            lse = jax.nn.logsumexp(logits, axis=-1)
            tgt = jnp.take_along_axis(logits, targets[..., None], -1)[..., 0]
            return jnp.mean(lse - tgt) + aux
        if ce == "chunk":
            B, S, H = hidden.shape
            n, chunk = 16, S // 16
            h_c = hidden.reshape(B, n, chunk, H).transpose(1, 0, 2, 3)
            t_c = targets.reshape(B, n, chunk).transpose(1, 0, 2)

            @jax.checkpoint
            def chunk_nll(h, t):
                logits = logits_fn(cfg, params, h).astype(jnp.float32)
                lse = jax.nn.logsumexp(logits, axis=-1)
                tgt = jnp.take_along_axis(logits, t[..., None], -1)[..., 0]
                return jnp.sum(lse - tgt)

            def body(carry, xs):
                return carry + chunk_nll(*xs), None

            tot, _ = jax.lax.scan(body, jnp.asarray(0.0, jnp.float32),
                                  (h_c, t_c))
            return tot / (B * S) + aux
        raise ValueError(ce)
    finally:
        tf_mod._pick_attn = orig


if __name__ == "__main__":
    main()
