#!/usr/bin/env python
"""Unified static-analysis driver (docs/STATIC_ANALYSIS.md).

One command, one merged report, one exit code over the three lints:

* metric/span-name lint    (``deepspeed_tpu/analysis/metric_lint.py``)
* JAX-hazard AST lint      (``deepspeed_tpu/analysis/lint.py``)
* HLO cost-contract check  (``tools/check_contracts.py``; jax + compile)

Usage::

    python -m tools.dstpu_lint              # metric + hazard (fast, no jax)
    python -m tools.dstpu_lint --all        # + contract check (lowers on CPU)
    python -m tools.dstpu_lint --contracts  # contract check only
    python -m tools.dstpu_lint --all --update-goldens
    python -m tools.dstpu_lint --list-allows  # audit every suppression

The AST lints are loaded by FILE PATH, not package import — they run
without jax or a package install (the same property
``tools/check_metric_names.py`` always had; that script is now a thin
shim over the same module).
"""

from __future__ import annotations

import argparse
import importlib.util
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_ANALYSIS = os.path.join(REPO, "deepspeed_tpu", "analysis")


def load_by_path(module_name: str, path: str):
    """Load an analysis module without importing the deepspeed_tpu
    package (which would pull jax)."""
    if module_name in sys.modules:
        return sys.modules[module_name]
    spec = importlib.util.spec_from_file_location(module_name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[module_name] = mod
    spec.loader.exec_module(mod)
    return mod


def metric_lint():
    return load_by_path("dstpu_metric_lint",
                        os.path.join(_ANALYSIS, "metric_lint.py"))


def hazard_lint():
    return load_by_path("dstpu_hazard_lint",
                        os.path.join(_ANALYSIS, "lint.py"))


def _section(title: str) -> None:
    print(f"-- {title} " + "-" * max(0, 60 - len(title)))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--all", action="store_true",
                    help="run every lint including the contract check")
    ap.add_argument("--contracts", action="store_true",
                    help="run only the HLO contract check")
    ap.add_argument("--update-goldens", action="store_true",
                    help="with --all/--contracts: regenerate the golden "
                         "contracts instead of diffing")
    ap.add_argument("--list-allows", action="store_true",
                    help="list every dstpu-lint allow marker with its reason")
    ap.add_argument("--root", default=REPO)
    args = ap.parse_args(argv)
    root = args.root

    if args.list_allows:
        hl = hazard_lint()
        for rel, ln, rules, reason in hl.suppressions(root):
            print(f"{rel}:{ln}: allow[{','.join(sorted(rules))}] {reason}")
        return 0

    if args.update_goldens and not (args.all or args.contracts):
        # regenerating goldens without running the contract section would
        # silently do nothing — that must never exit 0 looking like success
        args.contracts = True

    failures = 0
    run_ast = not args.contracts or args.all
    run_contracts = args.all or args.contracts

    if run_ast:
        ml = metric_lint()
        _section("metric/span-name lint")
        errors = ml.check(root)
        if errors:
            failures += 1
            print(f"FAIL: {len(errors)} violation(s)")
            for e in errors:
                print(f"  ERROR: {e}")
        else:
            print(f"OK ({len(ml.collect(root))} metric names, "
                  f"{len(ml.collect_spans(root))} span names)")

        hl = hazard_lint()
        _section("jax-hazard lint")
        violations = hl.check(root)
        if violations:
            failures += 1
            print(f"FAIL: {len(violations)} violation(s)")
            for v in violations:
                print(f"  ERROR: {v}")
        else:
            print(f"OK ({len(hl.suppressions(root))} documented "
                  "suppressions)")

    if run_contracts:
        _section("hlo cost contracts")
        if REPO not in sys.path:  # `python tools/dstpu_lint.py` from anywhere
            sys.path.insert(0, REPO)
        from tools import check_contracts as cc

        cc.ensure_cpu_harness()
        errors, n = cc.run_check(root, update=args.update_goldens)
        if args.update_goldens:
            print(f"regenerated {n} golden contract(s)")
        elif errors:
            failures += 1
            print(f"FAIL: {len(errors)} contract violation(s)")
            for e in errors:
                print(f"  ERROR: {e}")
        else:
            print(f"OK ({n} program contracts hold)")

    _section("summary")
    if failures:
        print(f"dstpu_lint: FAIL ({failures} section(s) with violations)")
        return 1
    print("dstpu_lint: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
