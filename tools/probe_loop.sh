#!/bin/bash
# Patient chip-probe loop per the lease discipline:
#   - ONE probe per cycle, generous budget (1500s), in a subprocess
#   - >=45 min quiet between probes (never rapid kill-polling)
#   - the moment the chip answers, chain straight into chip_session.sh
# Run from repo root:  bash tools/probe_loop.sh >> docs/PROBE_LOOP.log 2>&1 &
set -u
cd "$(dirname "$0")/.."

stamp() { echo "=== [$(date -u +%H:%M:%S)] $*"; }

for attempt in 1 2 3 4 5 6 7 8 9 10 11 12; do
  stamp "probe attempt $attempt start (budget 1500s)"
  timeout 1500 python - <<'EOF'
import time, jax, jax.numpy as jnp
t0 = time.time()
devs = jax.devices()
print("devices:", devs, flush=True)
x = jnp.ones((512, 512), jnp.bfloat16)
y = (x @ x).sum()
print("probe ok: %s (%.1fs)" % (float(y), time.time() - t0), flush=True)
EOF
  rc=$?
  stamp "probe attempt $attempt rc=$rc"
  if [ $rc -eq 0 ]; then
    stamp "chip healthy -> launching chip_session.sh"
    bash tools/chip_session.sh >> docs/CHIP_SESSION.log 2>&1
    stamp "chip_session.sh finished"
    exit 0
  fi
  stamp "chip dark; sleeping 45 min before next probe"
  sleep 2700
done
stamp "probe loop exhausted (12 attempts)"
exit 1
