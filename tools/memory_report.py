#!/usr/bin/env python
"""Memory ledger / OOM-forensics demo CLI.

``--demo`` runs the memory-observability path end-to-end on a tiny CPU
model and verifies every acceptance property:

* **Attribution exactness** — after a few training steps (fused +
  incremental, so forward/backward/optimizer_step watermarks populate),
  the ledger's training component sum (master params + optimizer state
  + grads + scalars) must equal the structural bytes of the engine's
  TrainState EXACTLY, and after a serving run the ``kv_pool`` /
  ``serving_params`` components must equal the structural bytes of the
  KV page pool and the weight copy.
* **Watermark monotonicity** — the per-phase exit samples of the
  process peak are non-decreasing within a step.
* **Pool gauges** — the serving KV occupancy gauges agree with the
  allocator's used/free/pinned counts.
* **OOM forensics** — a simulated XLA RESOURCE_EXHAUSTED inside
  ``engine.train_batch`` must produce a flight-recorder incident JSONL
  holding the ledger breakdown, raw ``memory_stats()``, top live
  buffers, and actionable hints.

Writes ``memory_report.json`` (the ledger reading) plus the incident
dump under ``--out``, prints ONE JSON summary line, and exits non-zero
when any check fails — the acceptance gate for the memory subsystem.

Knobs: ``--out DIR`` (default ./memory_demo), ``--steps N`` training
steps (default 4), ``--serve-requests N`` (default 3).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

#: record kinds a memory incident dump must contain
REQUIRED_INCIDENT_KINDS = ("flight_header", "memory", "oom_incident")

#: training components whose sum must equal the TrainState's bytes
TRAIN_COMPONENTS = ("master_params", "optimizer_state", "grads",
                    "train_scalars")


def _mlp_spec(hidden: int = 16, nlayers: int = 2):
    """Tiny MLP ModelSpec (mirrors tests/unit/simple_model.py, which
    tools must not import)."""
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.runtime.module import ModelSpec

    def init_params(rng):
        keys = jax.random.split(rng, nlayers)
        return {f"layer_{i}": {
            "w": jax.random.normal(k, (hidden, hidden)) * 0.1,
            "b": jnp.zeros((hidden,))} for i, k in enumerate(keys)}

    def forward(params, x):
        for i in range(nlayers):
            layer = params[f"layer_{i}"]
            x = x @ layer["w"] + layer["b"]
            if i < nlayers - 1:
                x = jax.nn.relu(x)
        return x

    def loss_fn(params, batch, rng):
        x, y = batch
        return jnp.mean((forward(params, x) - y) ** 2)

    return ModelSpec(init_params, loss_fn)


def _structural_bytes(tree) -> int:
    """Independent structural measurement the ledger must match: sum of
    every leaf's addressable-shard nbytes."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        try:
            total += sum(s.data.nbytes for s in leaf.addressable_shards)
        except Exception:
            total += int(getattr(leaf, "nbytes", 0) or 0)
    return total


def _train_demo(out_dir: str, steps: int):
    import jax.numpy as jnp

    import deepspeed_tpu

    engine, *_ = deepspeed_tpu.initialize(
        model=_mlp_spec(),
        config={
            "train_micro_batch_size_per_gpu": 2,
            "steps_per_print": 2,
            "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
            "telemetry": {
                "enabled": True,
                "flight_recorder": {"path": os.path.join(out_dir, "flight")},
            },
        })
    B = engine.config.train_batch_size
    hidden = 16
    rng = np.random.RandomState(0)

    def batch(gas_dim=True):
        x = rng.randn(B, hidden).astype(np.float32)
        y = x * 0.5
        if gas_dim:
            return (jnp.asarray(x[None]), jnp.asarray(y[None]))
        return (jnp.asarray(x), jnp.asarray(y))

    for _ in range(steps):  # fused path: train_batch watermark
        engine.train_batch(batch())
    for _ in range(2):  # incremental path: forward/optimizer_step marks
        loss = engine.forward(batch(gas_dim=False))
        engine.backward(loss)
        engine.step()
    return engine


def _serving_demo(n_requests: int):
    from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                      RaggedInferenceConfig,
                                                      RaggedRequest)
    from deepspeed_tpu.models.llama import llama_model

    model = llama_model("tiny", max_seq_len=128)
    eng = InferenceEngineV2(model, RaggedInferenceConfig(
        page_size=16, num_pages=64, max_seqs=4, max_pages_per_seq=8,
        enable_prefix_cache=True))
    rng = np.random.RandomState(0)
    vocab = model.config.vocab_size
    prefix = rng.randint(1, vocab, 32).tolist()
    eng.generate_all([RaggedRequest(
        prompt_ids=prefix + rng.randint(1, vocab, 8).tolist(),
        max_new_tokens=4)])
    eng.generate_all([RaggedRequest(
        prompt_ids=prefix + rng.randint(1, vocab, 8).tolist(),
        max_new_tokens=4) for _ in range(max(1, n_requests - 1))])
    return eng


def _force_oom(engine):
    """Simulate an XLA RESOURCE_EXHAUSTED inside the compiled step: the
    engine's exception path must route it through OOM forensics."""

    def _raise(*_a, **_k):
        raise RuntimeError(
            "RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
            "9437184 bytes.")

    engine._train_batch = _raise
    try:
        engine.train_batch((np.zeros((1, 2, 16), np.float32),
                            np.zeros((1, 2, 16), np.float32)))
    except RuntimeError:
        return True  # propagated, as it must
    return False


def _verify_incident(flight_dir: str):
    """Find the oom dump and check the forensics schema."""
    problems = []
    dumps = sorted(glob.glob(os.path.join(flight_dir, "flight_*oom*.jsonl")))
    if not dumps:
        return None, ["no oom incident dump written under " + flight_dir]
    path = dumps[-1]
    recs = [json.loads(line) for line in open(path)]
    kinds = {r.get("kind") for r in recs}
    for k in REQUIRED_INCIDENT_KINDS:
        if k not in kinds:
            problems.append(f"incident dump missing a {k!r} record")
    inc = next((r for r in recs if r.get("kind") == "oom_incident"), {})
    if not inc.get("hints"):
        problems.append("oom_incident carries no hints")
    if not inc.get("ledger", {}).get("components"):
        problems.append("oom_incident carries no ledger breakdown")
    if "memory_stats" not in inc:
        problems.append("oom_incident carries no raw memory_stats")
    if inc.get("where") != "engine.train_batch":
        problems.append(f"oom_incident where={inc.get('where')!r}, "
                        "expected 'engine.train_batch'")
    return path, problems


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--demo", action="store_true",
                    help="run the tiny-CPU end-to-end demo workload")
    ap.add_argument("--out", default="./memory_demo")
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--serve-requests", type=int, default=3)
    args = ap.parse_args(argv)
    if not args.demo:
        ap.error("only --demo mode is implemented; pass --demo")
    out_dir = os.path.abspath(args.out)
    os.makedirs(out_dir, exist_ok=True)
    problems = []

    from deepspeed_tpu.telemetry import get_memory_ledger, get_registry

    engine = _train_demo(out_dir, args.steps)
    led = get_memory_ledger()

    # ---- training attribution is exact ---------------------------------
    report = led.publish()
    comp = report["components"]
    train_sum = sum(comp[c]["device"] + comp[c]["host"]
                    for c in TRAIN_COMPONENTS if c in comp)
    train_expected = _structural_bytes(engine.state)
    if train_sum != train_expected:
        problems.append(f"training component sum {train_sum} != structural "
                        f"TrainState bytes {train_expected}")

    # ---- phase watermarks: present and monotone within the step --------
    marks = report["watermarks"]
    for phase in ("train_batch", "forward", "optimizer_step"):
        if marks.get(phase, 0) <= 0:
            problems.append(f"no {phase} watermark recorded")
    exit_peaks = [p for _name, p in led.phase_exit_log()]
    if not exit_peaks:
        problems.append("empty phase exit log")
    elif any(a > b for a, b in zip(exit_peaks, exit_peaks[1:])):
        problems.append(f"phase exit peaks not monotone: {exit_peaks}")

    # ---- serving attribution + pool gauges -----------------------------
    serve = _serving_demo(args.serve_requests)
    report = led.publish()
    comp = report["components"]
    kv_expected = _structural_bytes(serve._pools)
    if comp.get("kv_pool", {}).get("device") != kv_expected:
        problems.append(f"kv_pool component {comp.get('kv_pool')} != "
                        f"structural pool bytes {kv_expected}")
    params_expected = _structural_bytes(serve.params)
    if comp.get("serving_params", {}).get("device") != params_expected:
        problems.append(f"serving_params component "
                        f"{comp.get('serving_params')} != structural "
                        f"weight bytes {params_expected}")
    reg = get_registry()
    gauge_view = {
        "used": reg.get("deepspeed_tpu_serving_kv_pages_used").value(),
        "free": reg.get("deepspeed_tpu_serving_kv_pages_free").value(),
        "pinned": reg.get("deepspeed_tpu_serving_kv_pages_pinned").value()}
    alloc_view = {"used": serve.allocator.used_pages,
                  "free": serve.allocator.free_pages,
                  "pinned": serve.allocator.lru_pages}
    if {k: int(v) for k, v in gauge_view.items()} != alloc_view:
        problems.append(f"pool gauges {gauge_view} != allocator "
                        f"{alloc_view}")
    for phase in ("prefill", "decode"):
        if report["watermarks"].get(phase, 0) <= 0:
            problems.append(f"no {phase} watermark recorded")

    # ---- ledger report artifact ----------------------------------------
    report_path = os.path.join(out_dir, "memory_report.json")
    with open(report_path, "w") as f:
        json.dump(report, f, indent=2, default=float)
    back = json.load(open(report_path))
    if set(TRAIN_COMPONENTS) - set(back.get("components", {})):
        problems.append("memory_report.json is missing training components")

    # ---- forced OOM -> incident dump -----------------------------------
    if not _force_oom(engine):
        problems.append("simulated RESOURCE_EXHAUSTED did not propagate")
    incident_path, inc_problems = _verify_incident(
        os.path.join(out_dir, "flight"))
    problems += inc_problems

    oom_total = reg.get(
        "deepspeed_tpu_memory_oom_incidents_total").total()
    summary = {
        "report_path": report_path,
        "incident_path": incident_path,
        "train_component_bytes": train_sum,
        "train_structural_bytes": train_expected,
        "kv_pool_bytes": kv_expected,
        "bytes_in_use": report["bytes_in_use"],
        "unattributed_bytes": report["unattributed_bytes"],
        "watermarks": report["watermarks"],
        "pool_pages": alloc_view,
        "oom_incidents": oom_total,
        "problems": problems,
        "ok": not problems,
    }
    print(json.dumps(summary, default=float))
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
