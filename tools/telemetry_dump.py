#!/usr/bin/env python
"""Telemetry dump / demo CLI.

``--demo`` runs the full observability path end-to-end on a tiny CPU
model: a few training steps through ``DeepSpeedTPUEngine`` (fused +
incremental API, so fwd/bwd/step AND train_batch phase timings land in
the registry), a small shared-prefix serving run through
``InferenceEngineV2`` (prefill/decode latency histograms, prefix-cache
counters), explicit collectives through ``deepspeed_tpu.comm`` verbs
(comms per-op totals + algorithmic bus bytes), then writes the
Prometheus textfile + JSONL event log and verifies the output: every
metric name passes ``tools/check_metric_names.py`` and the exposition
text round-trips through the parser.

Prints ONE JSON summary line (paths, metric counts, MFU, serving
percentiles) and exits non-zero if a required metric family is missing
— this is the acceptance gate for the telemetry subsystem, and a
smoke-debuggable artifact generator for dashboard work.

Knobs: ``--out DIR`` (default ./telemetry_demo), ``--steps N`` training
steps (default 6), ``--serve-requests N`` (default 4).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# a multi-device virtual mesh makes the comms demo meaningful (bus
# factors are 0 on a 1-rank axis); must be set before jax initializes
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402


def _mlp_spec(hidden: int = 16, nlayers: int = 2):
    """Tiny MLP ModelSpec (mirrors tests/unit/simple_model.py, which
    tools must not import)."""
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.runtime.module import ModelSpec

    def init_params(rng):
        keys = jax.random.split(rng, nlayers)
        return {f"layer_{i}": {
            "w": jax.random.normal(k, (hidden, hidden)) * 0.1,
            "b": jnp.zeros((hidden,))} for i, k in enumerate(keys)}

    def forward(params, x):
        for i in range(nlayers):
            layer = params[f"layer_{i}"]
            x = x @ layer["w"] + layer["b"]
            if i < nlayers - 1:
                x = jax.nn.relu(x)
        return x

    def loss_fn(params, batch, rng):
        x, y = batch
        return jnp.mean((forward(params, x) - y) ** 2)

    return ModelSpec(init_params, loss_fn)


def _train_demo(out_dir: str, steps: int):
    import jax
    import jax.numpy as jnp

    import deepspeed_tpu

    engine, *_ = deepspeed_tpu.initialize(
        model=_mlp_spec(),
        config={
            "train_micro_batch_size_per_gpu": 2,
            "steps_per_print": 2,
            "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
            "comms_logger": {"enabled": True},
            "telemetry": {
                "enabled": True,
                "prometheus_path": os.path.join(out_dir, "metrics.prom"),
                "jsonl_path": os.path.join(out_dir, "events.jsonl"),
                "export_interval": 2,
                "stall_watchdog": {"enabled": True, "multiple": 3.0},
                "flight_recorder": {"enabled": True,
                                    "path": os.path.join(out_dir, "flight")},
                "numerics": {"enabled": True, "min_history": 2},
            },
        })
    B = engine.config.train_batch_size
    hidden = 16
    rng = np.random.RandomState(0)

    def batch(seed, gas_dim=True):
        x = rng.randn(B, hidden).astype(np.float32)
        y = (x @ np.eye(hidden, dtype=np.float32) * 0.5)
        if gas_dim:
            return (jnp.asarray(x[None]), jnp.asarray(y[None]))
        return (jnp.asarray(x), jnp.asarray(y))

    for i in range(steps):  # fused path: train_batch phase + MFU window
        engine.train_batch(batch(i))
    for i in range(2):  # incremental path: fwd/bwd/step phase timers
        loss = engine.forward(batch(i, gas_dim=False))
        engine.backward(loss)
        engine.step()
    return engine


def _numerics_demo(engine, out_dir: str):
    """Numerics observatory end-to-end: poison one batch with NaNs, let
    the next reporting boundary's stats pull trip the `nonfinite`
    sentinel (anomaly counter + flight dump with the per-leaf
    breakdown), then save a checkpoint and read the incident back out
    of the tag's commit manifest — the full anomaly → dump → manifest
    triage loop, in-process."""
    import jax.numpy as jnp

    B = engine.config.train_batch_size
    hidden = 16
    x = np.full((1, B, hidden), np.nan, np.float32)
    y = np.zeros((1, B, hidden), np.float32)
    for _ in range(2):  # two steps always cross a steps_per_print=2 boundary
        engine.train_batch((jnp.asarray(x), jnp.asarray(y)))
    report = engine.numerics_report()
    ckpt_dir = os.path.join(out_dir, "ckpt")
    engine.save_checkpoint(ckpt_dir, tag="numerics_demo")

    from deepspeed_tpu.resilience.commit import manifest_meta

    incident = manifest_meta(ckpt_dir, "numerics_demo").get(
        "numerics_incident")
    return report, incident


def _serving_demo(n_requests: int):
    from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                      RaggedInferenceConfig,
                                                      RaggedRequest)
    from deepspeed_tpu.models.llama import llama_model

    model = llama_model("tiny", max_seq_len=128)
    eng = InferenceEngineV2(model, RaggedInferenceConfig(
        page_size=16, num_pages=64, max_seqs=4, max_pages_per_seq=8,
        enable_prefix_cache=True))
    rng = np.random.RandomState(0)
    vocab = model.config.vocab_size
    prefix = rng.randint(1, vocab, 32).tolist()
    # sequential first request registers the prefix pages; the rest hit
    eng.generate_all([RaggedRequest(
        prompt_ids=prefix + rng.randint(1, vocab, 8).tolist(),
        max_new_tokens=4)])
    eng.generate_all([RaggedRequest(
        prompt_ids=prefix + rng.randint(1, vocab, 8).tolist(),
        max_new_tokens=4) for _ in range(max(1, n_requests - 1))])
    return eng.cache_stats()


def _comms_demo(topology):
    """Record real trace-time collectives through the comm verbs (an
    8-virtual-device CPU mesh gives the bus factors a non-trivial n)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from deepspeed_tpu import comm

    mesh = topology.mesh
    n = topology.axis_size("data")
    x = jnp.ones((8 * n, 8), jnp.float32)

    def body(a):
        s = comm.all_reduce(a, "sum", "data")
        g = comm.all_gather(a, "data")
        r = comm.reduce_scatter(s, "sum", "data")
        return r + g[:r.shape[0]]

    from deepspeed_tpu.utils.jax_compat import shard_map

    smap = shard_map(body, mesh=mesh, in_specs=P("data"),
                     out_specs=P("data"), check_vma=False)
    np.asarray(jax.jit(smap)(x))
    return comm.get_comms_logger()


REQUIRED_FAMILIES = (
    "deepspeed_tpu_train_phase_seconds_bucket",   # training phase timings
    "deepspeed_tpu_train_mfu",                    # MFU gauge
    "deepspeed_tpu_serving_prefill_seconds_bucket",
    "deepspeed_tpu_serving_decode_seconds_bucket",  # latency histograms
    "deepspeed_tpu_comm_ops_total",               # comms per-op totals
    "deepspeed_tpu_comm_bytes_total",
    "deepspeed_tpu_memory_bytes_in_use",          # memory ledger gauges
    "deepspeed_tpu_memory_component_bytes",
    "deepspeed_tpu_train_numerics_boundaries_total",  # numerics observatory
    "deepspeed_tpu_train_numerics_anomalies_total",   # (the demo trips one)
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--demo", action="store_true",
                    help="run the tiny-CPU end-to-end demo workload")
    ap.add_argument("--out", default="./telemetry_demo")
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--serve-requests", type=int, default=4)
    args = ap.parse_args(argv)
    if not args.demo:
        ap.error("only --demo mode is implemented; pass --demo")
    out_dir = os.path.abspath(args.out)
    os.makedirs(out_dir, exist_ok=True)

    from deepspeed_tpu.telemetry import get_registry, parse_prometheus_text

    engine = _train_demo(out_dir, args.steps)
    numerics, incident = _numerics_demo(engine, out_dir)
    cache = _serving_demo(args.serve_requests)
    cl = _comms_demo(engine.topology)
    if cl is not None:
        cl.publish(get_registry(), axis_sizes=engine.topology.axis_sizes)
        cl.log_summary(axis_sizes=engine.topology.axis_sizes)

    tm = engine.telemetry
    if tm.jsonl is not None:
        tm.jsonl.emit("demo_complete", steps=args.steps,
                      serve_requests=args.serve_requests)
    from deepspeed_tpu.telemetry import get_memory_ledger

    # read the ledger BEFORE close(): close releases the engine's
    # component slots (they would otherwise pin the TrainState forever)
    mem = get_memory_ledger().collect()
    engine.close()  # final forced export + handle release

    # ---- verify the artifacts ------------------------------------------
    prom_path = os.path.join(out_dir, "metrics.prom")
    jsonl_path = os.path.join(out_dir, "events.jsonl")
    samples = parse_prometheus_text(open(prom_path).read())
    names = {n for n, _labels in samples}
    missing = [f for f in REQUIRED_FAMILIES if f not in names]

    from check_metric_names import check as lint_check

    lint_errors = lint_check(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    # runtime names must pass the same rule the static lint enforces
    import re

    name_re = re.compile(r"^deepspeed_tpu_[a-z][a-z0-9_]*(_bucket|_sum|_count)?$")
    bad_names = sorted(n for n in names if not name_re.match(n))

    reg = get_registry()
    dec = reg.get("deepspeed_tpu_serving_decode_seconds")
    summary = {
        "prometheus_path": prom_path,
        "jsonl_path": jsonl_path,
        "jsonl_lines": sum(1 for _ in open(jsonl_path)),
        "metric_samples": len(samples),
        "metric_families": len(names),
        "mfu": reg.get("deepspeed_tpu_train_mfu").value(),
        "decode_latency_s": dec.percentiles() if dec.count() else None,
        "prefix_hit_rate": cache["prefix_hit_rate"],
        "memory": {
            "bytes_in_use": mem["bytes_in_use"],
            "unattributed_bytes": mem["unattributed_bytes"],
            "components": {k: v["device"] + v["host"]
                           for k, v in mem["components"].items()},
            "watermarks": mem["watermarks"],
        },
        "numerics": {
            "boundaries": numerics["boundaries"] if numerics else 0,
            "anomaly_counts": numerics["anomaly_counts"] if numerics else {},
            "first_nonfinite_leaf": ((numerics.get("last_report") or {})
                                     .get("first_nonfinite_leaf")
                                     if numerics else None),
            "divergence_ok": ((numerics.get("divergence") or {}).get("ok")
                              if numerics else None),
            "incident_annotated": bool(incident),
        },
        "missing_required": missing,
        "lint_errors": lint_errors,
        "bad_runtime_names": bad_names,
        "ok": not (missing or lint_errors or bad_names)
        and bool(incident),
    }
    print(json.dumps(summary, default=float))
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
