#!/usr/bin/env python
"""Back-compat shim: the metric/span-name lint implementation moved to
``deepspeed_tpu/analysis/metric_lint.py`` (PR 9) so the unified driver
``python -m tools.dstpu_lint --all`` can run it alongside the JAX-hazard
lint and the HLO contract check with one merged report.

This script keeps the original entry point and module API
(``check``/``collect``/``collect_spans``/``METRIC_NAME_RE``/...) —
tests and CI that load it by path keep working unchanged.  Loaded by
FILE PATH, not package import, so it still needs neither jax nor a
package install.
"""

from __future__ import annotations

import importlib.util
import os
import sys

_IMPL = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "deepspeed_tpu", "analysis", "metric_lint.py")


def _load():
    name = "dstpu_metric_lint"
    if name in sys.modules:
        return sys.modules[name]
    spec = importlib.util.spec_from_file_location(name, _IMPL)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


_impl = _load()

METRIC_NAME_RE = _impl.METRIC_NAME_RE
SPAN_NAME_RE = _impl.SPAN_NAME_RE
Site = _impl.Site
collect = _impl.collect
collect_spans = _impl.collect_spans
check = _impl.check
main = _impl.main

if __name__ == "__main__":
    sys.exit(main())
