#!/usr/bin/env python
"""Chaos drill: prove kill -> relaunch -> verified-resume end-to-end.

``--demo`` runs a tiny CPU training job through the full resilience
story (docs/RESILIENCE.md) and verifies every acceptance property:

* **Kill leg** — attempt 1 is hard-killed (``os._exit(137)``, the
  SIGKILL exit) mid-run, right after fabricating a partial ``tmp.*``
  staging dir (the debris of a save killed mid-commit).  The elastic
  agent relaunches; attempt 2 auto-resumes from the latest *verified*
  checkpoint, and the partial staging dir is garbage-collected, never
  loaded.
* **Preemption leg** — attempt 2 receives a simulated maintenance
  notice; at the next step boundary the engine writes an emergency
  checkpoint and exits with the resumable code (75).  The agent
  relaunches WITHOUT consuming its failure budget; attempt 3 resumes
  from the emergency tag and runs to completion.
* **Loss-trajectory continuity** — the union of per-step losses across
  attempts matches an uninterrupted control run step-for-step (exact
  fp32 state round-trips; batches are keyed by absolute step).
* **Corruption leg** — the newest tag is bit-flipped; a fresh
  auto-resuming engine detects it (checksum mismatch), counts it in
  ``deepspeed_tpu_resilience_corrupt_checkpoints_total``, and resumes
  from the previous good tag instead of crashing or loading garbage.

Writes ``chaos_drill.json`` (the summary) under ``--out``, prints ONE
JSON summary line, and exits non-zero when any check fails — the
acceptance gate for the resilience subsystem.

Knobs: ``--out DIR`` (default ./chaos_drill_demo), ``--steps N`` total
optimizer steps (default 8), ``--kill-step`` / ``--preempt-step``,
``--seed S`` (default 0: threads through the elastic agent's restart
jitter, the staged-debris fabrication, and the bit-flip offset; logged
in the summary so any chaos failure replays exactly).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys

_TOOLS_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO_DIR = os.path.dirname(_TOOLS_DIR)
sys.path.insert(0, _REPO_DIR)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

HIDDEN = 16
LOSS_RTOL = 1e-5

#: the generated per-attempt training script: all logic lives in
#: worker_main() below so the drill and its workers share one codebase
WORKER_SCRIPT = """\
import os, sys
sys.path.insert(0, os.environ["DRILL_TOOLS"])
import chaos_drill
sys.exit(chaos_drill.worker_main())
"""


def _mlp_spec(hidden: int = HIDDEN, nlayers: int = 2):
    """Tiny MLP ModelSpec (mirrors tests/unit/simple_model.py, which
    tools must not import)."""
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.runtime.module import ModelSpec

    def init_params(rng):
        keys = jax.random.split(rng, nlayers)
        params = {}
        for i, k in enumerate(keys):
            params[f"layer_{i}"] = {
                "w": jax.random.normal(k, (hidden, hidden)) * 0.1,
                "b": jnp.zeros((hidden,)),
            }
        return params

    def forward(params, x):
        for i in range(nlayers):
            layer = params[f"layer_{i}"]
            x = x @ layer["w"] + layer["b"]
            if i < nlayers - 1:
                x = jax.nn.relu(x)
        return x

    def loss_fn(params, batch, rng):
        x, y = batch
        return jnp.mean((forward(params, x) - y) ** 2)

    return ModelSpec(init_params, loss_fn)


def drill_batch(step: int, batch_size: int = 8, hidden: int = HIDDEN):
    """Deterministic batch keyed by ABSOLUTE step: a resumed run and the
    uninterrupted control see identical data at every step."""
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.RandomState(1000 + step)
    xs = rng.randn(1, batch_size, hidden).astype(np.float32)  # gas=1 leading dim
    w = (np.random.RandomState(42).randn(hidden, hidden) * 0.3).astype(np.float32)
    return jnp.asarray(xs), jnp.asarray(xs @ w)


def build_engine(ckpt_dir: str, resilient: bool = True, keep_n: int = 4):
    import deepspeed_tpu

    cfg = {
        "train_micro_batch_size_per_gpu": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "seed": 7,
    }
    # goodput ledger on for every engine: resilient attempts auto-attach
    # the union run file into resilience.save_dir (the wiring under
    # test), the control run keeps a plain per-lifetime ledger; flight
    # dumps stay inside the drill dir, never the CWD
    cfg["telemetry"] = {
        "enabled": True,
        "flight_recorder": {"path": os.path.join(ckpt_dir, "flight")},
    }
    if resilient:
        cfg["resilience"] = {"enabled": True, "save_dir": ckpt_dir,
                             "auto_resume": True, "emergency_save": True,
                             "keep_n": keep_n, "io_retries": 2,
                             "watch_signals": False}
    engine, *_ = deepspeed_tpu.initialize(model=_mlp_spec(), config=cfg)
    return engine


# --------------------------------------------------------------- worker side
def worker_main() -> int:
    """One elastic-agent attempt: train to DRILL_STEPS with per-step
    verified checkpoint saves; attempt 1 hard-kills itself, attempt 2
    takes a simulated preemption notice (exits 75 after the emergency
    save), attempt 3 finishes."""
    from deepspeed_tpu.resilience import chaos

    workdir = os.environ["DRILL_DIR"]
    total = int(os.environ["DRILL_STEPS"])
    kill_at = int(os.environ["DRILL_KILL_STEP"])
    preempt_at = int(os.environ["DRILL_PREEMPT_STEP"])
    ckpt_dir = os.path.join(workdir, "ckpt")

    marker = os.path.join(workdir, "attempt")
    attempt = (int(open(marker).read()) if os.path.exists(marker) else 0) + 1
    with open(marker, "w") as f:
        f.write(str(attempt))

    engine = build_engine(ckpt_dir)

    def log(rec):
        with open(os.path.join(workdir, "losses.jsonl"), "a") as f:
            f.write(json.dumps(rec) + "\n")

    log({"attempt": attempt, "event": "start",
         "resumed_at": engine.global_steps})
    while engine.global_steps < total:
        step = engine.global_steps
        # may raise PreemptionInterrupt (SystemExit rc=75) at the
        # boundary once a notice is pending — after the emergency save
        loss = float(engine.train_batch(drill_batch(step)))
        log({"attempt": attempt, "step": step, "loss": loss})
        if attempt == 1 and engine.global_steps == kill_at:
            # simulate a SIGKILL landing mid-commit: partial staging
            # debris on disk, no atexit, no flushes (seeded content)
            chaos.make_partial_staging(ckpt_dir, f"killed_step{step}",
                                       seed=int(os.environ.get(
                                           "DRILL_SEED", "0")))
            log({"attempt": attempt, "event": "hard_kill", "step": step})
            chaos.kill_point(step, step)
        engine.save_checkpoint(ckpt_dir)
        if attempt == 2 and engine.global_steps == preempt_at:
            log({"attempt": attempt, "event": "preemption_notice",
                 "step": step})
            chaos.simulate_preemption(engine.resilience)
    log({"attempt": attempt, "event": "done", "steps": engine.global_steps})
    return 0


# ---------------------------------------------------------------- drill side
def _check(checks, name, ok, detail=""):
    checks.append({"check": name, "ok": bool(ok), "detail": str(detail)})
    status = "ok" if ok else "FAIL"
    print(f"  [{status}] {name}" + (f" — {detail}" if detail else ""))
    return ok


def run_demo(out: str, steps: int, kill_step: int, preempt_step: int,
             seed: int = 0) -> int:
    from deepspeed_tpu.elasticity.elastic_agent import ElasticAgent
    from deepspeed_tpu.resilience import chaos
    from deepspeed_tpu.resilience import metrics as res_metrics
    from deepspeed_tpu.resilience.commit import list_tags, resolve_tag

    shutil.rmtree(out, ignore_errors=True)
    os.makedirs(out)
    ckpt_dir = os.path.join(out, "ckpt")
    script = os.path.join(out, "drill_worker.py")
    with open(script, "w") as f:
        f.write(WORKER_SCRIPT)

    env = {"DRILL_DIR": out, "DRILL_TOOLS": _TOOLS_DIR,
           "DRILL_STEPS": str(steps), "DRILL_KILL_STEP": str(kill_step),
           "DRILL_PREEMPT_STEP": str(preempt_step),
           "DRILL_SEED": str(seed),
           "JAX_PLATFORMS": "cpu"}
    agent = ElasticAgent(max_restarts=2, restart_delay_s=0.05,
                         export_env=env, seed=seed)
    print(f"chaos drill: {steps} steps, hard-kill at {kill_step}, "
          f"preemption at {preempt_step}, seed {seed} -> {out}")
    rc = agent.run(script)

    checks = []
    _check(checks, "elastic_agent_rc0", rc == 0, f"rc={rc}")
    _check(checks, "three_attempts", agent.attempts == 3,
           f"attempts={agent.attempts}")
    _check(checks, "preemption_not_counted_as_failure",
           agent.preemptions == 1, f"preemptions={agent.preemptions}")

    records = []
    losses_path = os.path.join(out, "losses.jsonl")
    if os.path.exists(losses_path):
        with open(losses_path) as f:
            records = [json.loads(line) for line in f]
    events = {r["event"] for r in records if "event" in r}
    _check(checks, "kill_and_preempt_legs_ran",
           {"hard_kill", "preemption_notice"} <= events, sorted(events))

    # emergency checkpoint from the preemption leg exists and verifies
    tags = list_tags(ckpt_dir)
    emergency = [t for t in tags if t.startswith("emergency_step")]
    _check(checks, "emergency_checkpoint_committed", bool(emergency), tags)
    # the mid-commit kill's partial staging dir was GC'd, never loaded
    debris = [d for d in os.listdir(ckpt_dir) if d.startswith("tmp.")]
    _check(checks, "partial_staging_gced", not debris, debris)

    # loss-trajectory continuity: union of logged losses (last attempt
    # wins) vs an uninterrupted control run on identical batches
    logged = {}
    for r in records:
        if "step" in r and "loss" in r:
            logged[r["step"]] = r["loss"]
    control = build_engine(os.path.join(out, "control_ckpt"), resilient=False)
    control_losses = [float(control.train_batch(drill_batch(i)))
                      for i in range(steps)]
    missing = [i for i in range(steps) if i not in logged]
    # the preempted step's loss is computed but never returned to the
    # worker loop (the boundary raises first) — at most that one missing
    _check(checks, "at_most_one_unlogged_step", len(missing) <= 1, missing)
    drift = max((abs(logged[i] - control_losses[i])
                 / max(1e-12, abs(control_losses[i]))
                 for i in logged), default=float("inf"))
    _check(checks, "loss_trajectory_continuity",
           logged and drift <= LOSS_RTOL, f"max rel drift {drift:.2e}")

    # goodput leg: union-of-attempts accounting across the kill->resume
    # cycle (docs/OBSERVABILITY.md "Step-time attribution & goodput").
    # The killed step's checkpoint was lost, so attempt 2 re-runs it —
    # that recompute must land in the `restart` badput bucket, and the
    # productive-step union across all three attempts must still match
    # the uninterrupted control run exactly.
    run_rec = {}
    run_path = os.path.join(ckpt_dir, "goodput_run.json")
    if os.path.exists(run_path):
        with open(run_path) as f:
            run_rec = json.load(f)
    control_gp = control.goodput_summary() or {}
    _check(checks, "goodput_run_file_unions_attempts",
           run_rec.get("attempts") == 3, f"attempts={run_rec.get('attempts')}")
    _check(checks, "goodput_recompute_attributed_to_restart",
           run_rec.get("recomputed_steps") == 1
           and (run_rec.get("buckets") or {}).get("restart", 0) > 0,
           f"recomputed={run_rec.get('recomputed_steps')} "
           f"restart_s={(run_rec.get('buckets') or {}).get('restart', 0):.4f}")
    _check(checks, "goodput_union_matches_control",
           run_rec.get("productive_steps") == control_gp.get(
               "productive_steps") == steps,
           f"union={run_rec.get('productive_steps')} "
           f"control={control_gp.get('productive_steps')} steps={steps}")

    # corruption leg: bit-flip the newest tag; auto-resume must detect
    # it, count it, and fall back to the previous good tag
    newest = tags[0]
    flipped_file, flip_off = chaos.bitflip_array(ckpt_dir, newest,
                                                 seed=seed + 11)
    corrupt_before = res_metrics.corrupt_checkpoints_total().total()
    resolved, report = resolve_tag(ckpt_dir)
    corrupt_after = res_metrics.corrupt_checkpoints_total().total()
    _check(checks, "corrupt_newest_detected_and_skipped",
           resolved is not None and resolved != newest,
           f"{newest} ({flipped_file}@{flip_off}) -> {resolved}")
    _check(checks, "corrupt_checkpoints_total_incremented",
           corrupt_after == corrupt_before + 1,
           f"{corrupt_before} -> {corrupt_after}")
    resumed = build_engine(ckpt_dir, resilient=True)
    good_step = int(report["meta"].get("global_steps", -1))
    _check(checks, "resumed_from_previous_good_tag",
           resumed.global_steps == good_step and resumed.global_steps < steps,
           f"resumed at step {resumed.global_steps} (tag {resolved})")

    ok = all(c["ok"] for c in checks)
    summary = {"demo": "chaos_drill", "ok": ok, "out": out, "steps": steps,
               "seed": seed,
               "attempts": agent.attempts, "preemptions": agent.preemptions,
               "world_sizes": agent.world_sizes, "tags": tags,
               "checks": checks}
    with open(os.path.join(out, "chaos_drill.json"), "w") as f:
        json.dump(summary, f, indent=2)
    print(json.dumps({k: v for k, v in summary.items() if k != "checks"}))
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--demo", action="store_true",
                    help="run the kill->relaunch->verified-resume drill "
                         "on a tiny CPU model")
    ap.add_argument("--out", default="./chaos_drill_demo")
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--kill-step", type=int, default=3,
                    help="hard-kill attempt 1 when global_steps hits this")
    ap.add_argument("--preempt-step", type=int, default=5,
                    help="simulated maintenance notice in attempt 2 at this step")
    ap.add_argument("--seed", type=int, default=0,
                    help="threads through agent restart jitter, staging "
                         "debris and the bit-flip offset; logged in the "
                         "summary so any chaos failure replays exactly")
    args = ap.parse_args(argv)
    if not args.demo:
        ap.print_help()
        return 2
    if not (0 < args.kill_step < args.preempt_step < args.steps):
        ap.error("need 0 < --kill-step < --preempt-step < --steps")
    return run_demo(os.path.abspath(args.out), args.steps, args.kill_step,
                    args.preempt_step, seed=args.seed)


if __name__ == "__main__":
    sys.exit(main())
