#!/usr/bin/env python
"""Measured-goodput report + perf-regression gate.

``--demo`` runs the step-time-attribution and goodput-accounting story
end-to-end on a tiny CPU model (docs/OBSERVABILITY.md "Step-time
attribution & goodput") and hard-gates its invariants:

* **Step-time attribution** — a forced ``StepTimeline`` capture around
  one train step must yield a decomposition whose categories sum to the
  step's wall clock within tolerance, with the ``measured`` flag honest
  (CPU/interpreter backends yield no device timeline -> the record must
  say ``measured: false`` and fall back to the span-derived host
  timeline, never crash).  When a device trace IS available the
  measured exposed/overlapped split must be internally consistent and
  sane against the structural ``overlapped_fraction``.
* **Goodput ledger** — after steps + checkpoint save/load + eval, the
  badput buckets (+ computed idle residual) must sum to the engine
  lifetime within tolerance, the compile bucket must have absorbed the
  demo's XLA compiles, and ``goodput_fraction`` must clear a small
  floor (compile dominates a tiny CPU demo, so the floor is low; the
  arithmetic, not the throughput, is the gate).
* **Artifacts** — each capture leaves a merged Chrome-trace JSON (host
  spans + device ops in ONE Perfetto file) that must parse and carry
  ``traceEvents``.

Writes ``goodput_report.json`` under ``--out``, prints ONE JSON summary
line, exits non-zero when any check fails — the acceptance gate for the
measured-goodput subsystem (wired into bench.py / tools/bench_serving.py
JSON via their ``timeline`` + ``goodput`` sections).

Knobs: ``--out DIR`` (default ./goodput_demo), ``--steps N`` (default
8), ``--seed S``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

HIDDEN = 16
#: categories-sum-to-wall tolerance: relative to wall plus an absolute
#: floor for micro-second-scale CPU steps
SUM_RTOL, SUM_ATOL = 0.01, 1e-3
#: goodput floor for the tiny demo: compile dominates an 8-step CPU
#: run, so this gates the accounting arithmetic, not throughput
GOODPUT_FLOOR = 0.02
#: buckets-sum-to-lifetime tolerance (idle is a computed residual, so
#: the sum is exact up to fp noise; keep a loose belt anyway)
LIFETIME_RTOL = 0.02


def _mlp_spec(hidden: int = HIDDEN, nlayers: int = 2):
    """Tiny MLP ModelSpec (mirrors tests/unit/simple_model.py, which
    tools must not import)."""
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.runtime.module import ModelSpec

    def init_params(rng):
        keys = jax.random.split(rng, nlayers)
        return {f"layer_{i}": {
            "w": jax.random.normal(k, (hidden, hidden)) * 0.1,
            "b": jnp.zeros((hidden,))} for i, k in enumerate(keys)}

    def forward(params, x):
        for i in range(nlayers):
            layer = params[f"layer_{i}"]
            x = x @ layer["w"] + layer["b"]
            if i < nlayers - 1:
                x = jax.nn.relu(x)
        return x

    def loss_fn(params, batch, rng):
        x, y = batch
        return jnp.mean((forward(params, x) - y) ** 2)

    return ModelSpec(init_params, loss_fn)


def _check(checks, name, ok, detail=""):
    checks.append({"check": name, "ok": bool(ok), "detail": str(detail)})
    status = "ok" if ok else "FAIL"
    print(f"  [{status}] {name}" + (f" — {detail}" if detail else ""))
    return ok


def run_demo(out: str, steps: int, seed: int = 0) -> int:
    import shutil

    import jax
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.telemetry.exporter import snapshot_metrics

    shutil.rmtree(out, ignore_errors=True)
    os.makedirs(out)
    artifact_dir = os.path.join(out, "timeline")

    cfg = {
        "train_micro_batch_size_per_gpu": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "seed": 7 + seed,
        "telemetry": {
            "enabled": True,
            # capture every 4th step: the demo proves the periodic path
            # AND the forced path below
            "timeline": {"every_n_steps": 4, "artifact_dir": artifact_dir},
            "goodput": {"run_file": os.path.join(out, "goodput_run.json")},
            # keep incident dumps inside --out, never the CWD
            "flight_recorder": {"path": os.path.join(out, "flight")},
        },
    }
    engine, *_ = deepspeed_tpu.initialize(model=_mlp_spec(), config=cfg)

    rng = np.random.RandomState(seed)
    w = (np.random.RandomState(42).randn(HIDDEN, HIDDEN) * 0.3
         ).astype(np.float32)

    def batch():
        xs = rng.randn(1, 8, HIDDEN).astype(np.float32)
        return jnp.asarray(xs), jnp.asarray(xs @ w)

    print(f"goodput report: {steps} steps + save/load + eval -> {out}")
    for _ in range(steps):
        engine.train_batch(batch())
    _, forced = engine.capture_timeline(batch())
    engine.save_checkpoint(os.path.join(out, "ckpt"))
    engine.load_checkpoint(os.path.join(out, "ckpt"))
    engine.eval_batch(batch())
    summary = engine.goodput_summary()
    periodic = engine.timeline_record()
    engine.close()

    checks = []
    # ---------------------------------------------------- timeline gates
    _check(checks, "timeline_capture_produced", forced is not None)
    rec = forced or {}
    cats = rec.get("categories") or {}
    wall = float(rec.get("wall_seconds") or 0.0)
    gap = abs(sum(cats.values()) - wall)
    _check(checks, "categories_sum_to_wall",
           cats and gap <= SUM_RTOL * wall + SUM_ATOL,
           f"|sum-wall|={gap:.2e} wall={wall:.4f}")
    on_cpu = jax.default_backend() == "cpu"
    measured = bool(rec.get("measured"))
    _check(checks, "measured_flag_honest",
           (not measured) if on_cpu else True,
           f"backend={jax.default_backend()} measured={measured}")
    if measured:
        # device-trace path: the exposed/overlapped split must cover the
        # collective busy time and never exceed it
        exp = float(rec.get("exposed_collective_seconds") or 0.0)
        ovl = float(rec.get("overlapped_collective_seconds") or 0.0)
        coll = sum(v for k, v in cats.items()
                   if k in ("all_reduce", "all_gather", "reduce_scatter",
                            "all_to_all", "collective_permute"))
        _check(checks, "measured_overlap_consistent",
               exp >= 0 and ovl >= 0 and exp <= wall + SUM_ATOL
               and exp + SUM_ATOL >= coll * 0.0,  # exposed ⊆ wall
               f"exposed={exp:.4f} overlapped={ovl:.4f} coll_cat={coll:.4f}")
        rep = engine.overlap_report()
        if rep is not None and (exp + ovl) > 0:
            # structural golden: measured overlapped share vs the
            # byte-model overlapped_fraction, loosely (same order)
            m_frac = ovl / (exp + ovl)
            _check(checks, "measured_overlap_vs_structural",
                   abs(m_frac - rep.overlapped_fraction) < 0.5,
                   f"measured={m_frac:.2f} "
                   f"structural={rep.overlapped_fraction:.2f}")
    else:
        _check(checks, "fallback_is_host_timeline",
               set(cats) >= {"host_compute", "host_gap"}
               and all(cats.get(c, 0.0) == 0.0
                       for c in ("gemm", "attention")),
               sorted(k for k, v in cats.items() if v))
    _check(checks, "periodic_capture_fired",
           periodic is not None
           and (periodic.get("step") == steps or forced is not None),
           f"last capture step={periodic.get('step') if periodic else None}")
    arts = (sorted(os.listdir(artifact_dir))
            if os.path.isdir(artifact_dir) else [])
    _check(checks, "chrome_trace_artifacts_written", bool(arts), arts[:4])
    art_ok, n_events = False, 0
    if arts:
        try:
            with open(os.path.join(artifact_dir, arts[-1])) as f:
                trace = json.load(f)
            evs = trace.get("traceEvents") or []
            n_events = len(evs)
            art_ok = n_events > 0 and all(
                "ts" in e and "name" in e for e in evs
                if e.get("ph") == "X")
        except Exception:
            art_ok = False
    _check(checks, "chrome_trace_parses", art_ok, f"{n_events} events")

    # ----------------------------------------------------- goodput gates
    _check(checks, "goodput_summary_produced", summary is not None)
    s = summary or {}
    buckets = s.get("buckets") or {}
    lifetime = float(s.get("lifetime_seconds") or 0.0)
    bgap = abs(sum(buckets.values()) - lifetime)
    _check(checks, "buckets_sum_to_lifetime",
           buckets and bgap <= LIFETIME_RTOL * max(lifetime, 1e-9),
           f"|sum-lifetime|={bgap:.2e} lifetime={lifetime:.3f}")
    _check(checks, "productive_steps_counted",
           s.get("productive_steps") == steps + 1,  # +1 forced capture
           f"productive={s.get('productive_steps')} expected={steps + 1}")
    _check(checks, "checkpoint_phases_accounted",
           buckets.get("checkpoint_save", 0) > 0
           and buckets.get("checkpoint_load", 0) > 0,
           f"save={buckets.get('checkpoint_save', 0):.4f} "
           f"load={buckets.get('checkpoint_load', 0):.4f}")
    _check(checks, "eval_accounted", buckets.get("eval", 0) > 0,
           f"eval={buckets.get('eval', 0):.4f}")
    _check(checks, "compile_absorbed", buckets.get("compile", 0) > 0,
           f"compile={buckets.get('compile', 0):.3f}")
    frac = float(s.get("goodput_fraction") or 0.0)
    _check(checks, "goodput_fraction_above_floor", frac >= GOODPUT_FLOOR,
           f"{frac:.3f} >= {GOODPUT_FLOOR}")
    run_path = os.path.join(out, "goodput_run.json")
    run_rec = {}
    if os.path.exists(run_path):
        with open(run_path) as f:
            run_rec = json.load(f)
    _check(checks, "union_run_file_persisted",
           run_rec.get("productive_steps") == steps + 1
           and run_rec.get("attempts") == 1,
           f"run={ {k: run_rec.get(k) for k in ('high_water', 'productive_steps', 'attempts')} }")

    # ------------------------------------------------------ metric gates
    snap = snapshot_metrics()
    names = set(snap)
    need = {"deepspeed_tpu_timeline_category_seconds",
            "deepspeed_tpu_timeline_measured",
            "deepspeed_tpu_timeline_captures_total",
            "deepspeed_tpu_goodput_seconds_total",
            "deepspeed_tpu_goodput_fraction"}
    _check(checks, "metrics_registered", need <= names,
           sorted(need - names))

    ok = all(c["ok"] for c in checks)
    report = {"demo": "goodput_report", "ok": ok, "out": out,
              "steps": steps, "seed": seed,
              "backend": jax.default_backend(),
              "timeline": rec, "goodput": s, "run_file": run_rec,
              "checks": checks}
    with open(os.path.join(out, "goodput_report.json"), "w") as f:
        json.dump(report, f, indent=2, default=str)
    print(json.dumps({k: v for k, v in report.items()
                      if k in ("demo", "ok", "out", "steps", "backend")}))
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--demo", action="store_true",
                    help="run the measured-goodput gate on a tiny CPU model")
    ap.add_argument("--out", default="./goodput_demo")
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    if not args.demo:
        ap.print_help()
        return 2
    if args.steps < 4:
        ap.error("--steps must be >= 4 (the periodic capture cadence)")
    return run_demo(os.path.abspath(args.out), args.steps, seed=args.seed)


if __name__ == "__main__":
    sys.exit(main())
