#!/usr/bin/env python
"""Fleet drill: prove routed disaggregated serving is lossless and
bit-identical under replica failure.

``--demo`` runs the whole serving-fleet story on CPU with a tiny fp32
llama (greedy decoding), against a single-engine control on the same
weights:

* **Disaggregation leg** — 1 prefill + 2 decode replicas; requests are
  routed by prefix-cache-affinity hashing, chunk-prefilled on the
  prefill replica, and their KV pages migrate to decode replicas
  (ref-count adoption on import).
* **Kill leg** — one decode replica is hard-killed mid-stream (its
  engine state, including every in-flight KV page, is gone).  The
  router re-dispatches the lost streams; every request must complete
  and every stream must be **bit-identical** to the single-engine
  control.
* **Preemption leg** — a second wave of requests; the surviving decode
  replica gets a PR-5 maintenance notice mid-stream.  The router
  evacuates it (KV migration where possible, re-dispatch otherwise);
  streams again complete bit-identically, with the fleet degraded to
  the prefill replica decoding as a mixed fallback.
* **Metric-name lint** — the run registers the
  ``deepspeed_tpu_serving_fleet_*`` family, then
  ``tools/check_metric_names.py`` must pass over the tree and see it.

Writes ``fleet_drill.json`` under ``--out``, prints ONE JSON summary
line, and exits non-zero when any check fails — the acceptance gate for
the serving-fleet subsystem.

Knobs: ``--out DIR`` (default ./fleet_drill_demo), ``--requests N``
(default 6), ``--new-tokens N`` (default 10).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys

_TOOLS_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO_DIR = os.path.dirname(_TOOLS_DIR)
sys.path.insert(0, _REPO_DIR)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

PAGE_SIZE = 8
PREFIX_TOKENS = 16  # two full pages shared per request family


def _check(checks, name, ok, detail=""):
    checks.append({"check": name, "ok": bool(ok), "detail": str(detail)})
    print(f"  [{'ok' if ok else 'FAIL'}] {name}"
          + (f" — {detail}" if detail else ""))
    return ok


def _build(n_requests: int, new_tokens: int):
    import jax
    import numpy as np

    from deepspeed_tpu.inference.v2 import (InferenceEngineV2,
                                            RaggedInferenceConfig,
                                            RaggedRequest)
    from deepspeed_tpu.models.llama import llama_model
    from deepspeed_tpu.serving import ServingConfig, build_fleet

    model = llama_model("tiny", max_seq_len=128)
    params = model.init_params(jax.random.PRNGKey(0))
    base = RaggedInferenceConfig(dtype="fp32", page_size=PAGE_SIZE,
                                 num_pages=64, max_seqs=4,
                                 max_pages_per_seq=12,
                                 enable_prefix_cache=True)
    serving = ServingConfig(enabled=True, prefill_replicas=1,
                            decode_replicas=2, disaggregated=True,
                            affinity_pages=2, prefill_chunk=PAGE_SIZE)
    fleet = build_fleet(model, serving, engine_config=base, params=params)

    rng = np.random.RandomState(7)
    vocab = model.config.vocab_size
    prefix = list(rng.randint(0, vocab, PREFIX_TOKENS))

    def make_requests(n, salt):
        rq = np.random.RandomState(100 + salt)
        return [RaggedRequest(
            prompt_ids=prefix + list(rq.randint(0, vocab, 3 + i)),
            max_new_tokens=new_tokens) for i in range(n)]

    def control_run(requests):
        """Fresh single engine on the same weights; greedy, so the
        fleet must reproduce these streams token-for-token."""
        eng = InferenceEngineV2(model, base, params=params)
        got = eng.generate_all([RaggedRequest(
            prompt_ids=list(r.prompt_ids),
            max_new_tokens=r.max_new_tokens) for r in requests])
        eng.close()
        return [got[i] for i in range(len(requests))]

    return fleet, make_requests, control_run


def run_demo(out: str, n_requests: int, new_tokens: int) -> int:
    from deepspeed_tpu.telemetry import get_registry

    shutil.rmtree(out, ignore_errors=True)
    os.makedirs(out)
    print(f"fleet drill: {n_requests} requests x {new_tokens} tokens, "
          f"1 prefill + 2 decode replicas -> {out}")
    fleet, make_requests, control_run = _build(n_requests, new_tokens)
    reg = get_registry()

    def counter(name):
        return reg.counter(name, "").total()

    checks = []

    # ---- leg 1: disaggregated serving + mid-stream decode-replica kill
    reqs = make_requests(n_requests, salt=1)
    want = control_run(reqs)
    uids = [fleet.submit(r) for r in reqs]
    mid_stream = False
    for _ in range(200):
        fleet.step()
        states = [fleet.request_state(u) for u in uids]
        on_decode = [s for s in states if (s["replica"] or "").startswith("decode")]
        if on_decode and all(1 <= len(s["emitted"]) < new_tokens
                             for s in states):
            mid_stream = True
            break
    _check(checks, "streams_mid_flight_on_decode_pool", mid_stream,
           f"{len([1 for s in states if s['replica']])} placed")
    hosts = {}
    for u in uids:
        rep = fleet.request_state(u)["replica"] or ""
        if rep.startswith("decode"):
            hosts[rep] = hosts.get(rep, 0) + 1
    victim = max(hosts, key=hosts.get) if hosts else "decode0"
    d0, r0 = counter("deepspeed_tpu_serving_fleet_replica_deaths_total"), \
        counter("deepspeed_tpu_serving_fleet_redispatches_total")
    print(f"  killing {victim} mid-stream "
          f"(hosting {hosts.get(victim, 0)} stream(s))")
    fleet.kill_replica(victim)
    for _ in range(400):
        if not fleet.has_work():
            break
        fleet.step()
    got = [fleet.request_state(u)["emitted"] for u in uids]
    _check(checks, "all_streams_complete_after_kill",
           not fleet.has_work()
           and all(not fleet.request_state(u)["failed"] for u in uids))
    _check(checks, "kill_leg_bit_identical_to_single_engine",
           got == want,
           f"{sum(g == w for g, w in zip(got, want))}/{len(want)} match")
    _check(checks, "replica_death_detected",
           counter("deepspeed_tpu_serving_fleet_replica_deaths_total") == d0 + 1)
    _check(checks, "streams_recovered_via_redispatch",
           counter("deepspeed_tpu_serving_fleet_redispatches_total") > r0,
           f"{counter('deepspeed_tpu_serving_fleet_redispatches_total') - r0} "
           "re-dispatched")
    _check(checks, "kv_migrations_ran",
           counter("deepspeed_tpu_serving_fleet_migrations_total")
           >= n_requests,
           f"{counter('deepspeed_tpu_serving_fleet_migrations_total')} "
           "migrations, "
           f"{counter('deepspeed_tpu_serving_fleet_migrated_pages_total')} "
           "pages")

    # ---- leg 2: preemption notice on the surviving decode replica
    reqs2 = make_requests(max(2, n_requests // 2), salt=2)
    want2 = control_run(reqs2)
    uids2 = [fleet.submit(r) for r in reqs2]
    for _ in range(3):
        fleet.step()
    survivors = [n for n, r in fleet.replicas.items()
                 if r.alive and not r.retired and r.role == "decode"]
    p0 = counter("deepspeed_tpu_serving_fleet_replica_preemptions_total")
    if survivors:
        print(f"  preemption notice -> {survivors[0]}")
        fleet.replicas[survivors[0]].watcher.notify("maintenance-sim")
    for _ in range(400):
        if not fleet.has_work():
            break
        fleet.step()
    got2 = [fleet.request_state(u)["emitted"] for u in uids2]
    _check(checks, "preempted_replica_evacuated",
           bool(survivors)
           and counter("deepspeed_tpu_serving_fleet_replica_preemptions_total")
           == p0 + 1, survivors)
    _check(checks, "preempt_leg_bit_identical_to_single_engine",
           got2 == want2,
           f"{sum(g == w for g, w in zip(got2, want2))}/{len(want2)} match")

    # ---- allocator integrity: after two legs of KV churn (migration,
    # re-dispatch, evacuation) no surviving replica may hold a leaked
    # page or refcount — the BlockAllocator debug audit is exact
    leak_errs = []
    for name, rep in fleet.replicas.items():
        if not rep.alive:
            continue  # a hard-killed replica's state is gone by design
        try:
            rep.engine.assert_no_leaks()
        except AssertionError as e:
            leak_errs.append(f"{name}: {e}")
    _check(checks, "allocator_no_leaks_after_churn", not leak_errs,
           leak_errs[:2] if leak_errs else
           f"{sum(1 for r in fleet.replicas.values() if r.alive)} "
           "replicas audited")

    # ---- metric-name lint over the tree (fleet family included)
    import check_metric_names as lint

    errors = lint.check(_REPO_DIR)
    fleet_names = sorted(n for n in lint.collect(_REPO_DIR)
                         if n.startswith("deepspeed_tpu_serving_fleet_"))
    _check(checks, "check_metric_names_passes", not errors,
           errors[:3] if errors else f"{len(fleet_names)} fleet metrics")
    _check(checks, "fleet_metric_family_registered", len(fleet_names) >= 8,
           fleet_names[:4])

    ok = all(c["ok"] for c in checks)
    summary = {"demo": "fleet_drill", "ok": ok, "out": out,
               "requests": n_requests + len(reqs2),
               "victim": victim, "health": fleet.health(),
               "fleet_metrics": fleet_names, "checks": checks}
    with open(os.path.join(out, "fleet_drill.json"), "w") as f:
        json.dump(summary, f, indent=2)
    print(json.dumps({k: v for k, v in summary.items()
                      if k not in ("checks", "health", "fleet_metrics")}))
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--demo", action="store_true",
                    help="run the disaggregation + kill + preemption drill "
                         "on a tiny CPU model")
    ap.add_argument("--out", default="./fleet_drill_demo")
    ap.add_argument("--requests", type=int, default=6)
    ap.add_argument("--new-tokens", type=int, default=10)
    args = ap.parse_args(argv)
    if not args.demo:
        ap.print_help()
        return 2
    if args.requests < 2 or args.new_tokens < 4:
        ap.error("need --requests >= 2 and --new-tokens >= 4 for a "
                 "meaningful mid-stream kill")
    return run_demo(os.path.abspath(args.out), args.requests, args.new_tokens)


if __name__ == "__main__":
    sys.exit(main())
