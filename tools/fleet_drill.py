#!/usr/bin/env python
"""Fleet drill: prove routed disaggregated serving is lossless and
bit-identical under replica failure.

``--demo`` runs the whole serving-fleet story on CPU with a tiny fp32
llama (greedy decoding), against a single-engine control on the same
weights:

* **Disaggregation leg** — 1 prefill + 2 decode replicas; requests are
  routed by prefix-cache-affinity hashing, chunk-prefilled on the
  prefill replica, and their KV pages migrate to decode replicas
  (ref-count adoption on import).
* **Kill leg** — one decode replica is hard-killed mid-stream (its
  engine state, including every in-flight KV page, is gone).  The
  router re-dispatches the lost streams; every request must complete
  and every stream must be **bit-identical** to the single-engine
  control.
* **Preemption leg** — a second wave of requests; the surviving decode
  replica gets a PR-5 maintenance notice mid-stream.  The router
  evacuates it (KV migration where possible, re-dispatch otherwise);
  streams again complete bit-identically, with the fleet degraded to
  the prefill replica decoding as a mixed fallback.
* **Overload leg** (fresh SLO fleet) — a burst past the bounded queue:
  low-priority submissions are shed loudly (``RejectedError`` with a
  retry-after hint, counted in ``slo_shed_total``), high-priority ones
  are never shed; a chaos ``PoolSqueeze`` then drives the KV pool over
  the shed threshold and proves the pool-pressure rule too.
* **Deadline leg** — requests with an exhausted ``deadline_s`` budget
  expire at the step boundary with ``finish_reason="deadline"``
  (counted in ``slo_deadline_exceeded_total``) instead of waiting
  forever; undeadlined requests in the same wave run to completion
  bit-identically.
* **Slow-replica leg** — a chaos ``SlowReplica`` drags one decode
  replica's step latency; the circuit breaker trips (sustained MEDIAN
  step latency > k x the same-role fleet median — a lone spike lifts
  only p95 and never trips), the replica is drained of placement, its
  streams finish
  elsewhere **bit-identical** to the control, and after the cooldown
  the breaker recovers through half-open probing on live traffic.
* **Tiered-KV leg** (fresh 1+1 fleet) — the device prefix cache is
  capped BELOW the leg's distinct-prefix working set with the host-RAM
  KV tier on (``serving.kv_tier`` through ``build_fleet``): families
  cycle, cold prefixes spill to host on LRU eviction and restore
  (CRC-verified) when their family returns; streams must be
  **bit-identical** to an UNCAPPED single-engine control, the
  allocator audit must stay green with in-flight spill pins accounted,
  and the host-tier occupancy must surface in replica ``health()``.
* **Tracing leg** (fresh fleet, fresh request-trace ledger) — the
  disaggregated prefill→decode handoff plus a mid-stream replica kill
  must each read as ONE connected trace per request in the merged
  fleet Perfetto artifact (``fleet_trace.json``: prefill, KV transit,
  decode and recompute as distinct slices keyed by the router-minted
  ``trace_id``); every request's phase ledger must sum to its
  end-to-end latency; and the forced TTFT violations (unmeetable
  ``slo_ttft_s``) must carry exemplars resolving to traces present in
  the artifact.
* **NVMe-tier leg** (fresh 1+1 fleet) — the host-RAM tier itself is
  budgeted at three page records with the NVMe third tier on: cold
  families demote host -> ``.kvpage`` file on LRU pressure and promote
  back (CRC re-verified) when they return; streams must be
  **bit-identical** to the uncapped single-engine control with zero
  corrupt records and no leaked pages.
* **Cross-process leg** — a REAL child-process replica is spawned
  behind the socket transport; the autoscaler grows it into the fleet
  under queue pressure, live decode rebalancing migrates running
  streams across the process boundary, and the scale-down path retires
  it mid-run via drain/evacuation (its streams come BACK over the
  socket).  Every stream must complete **bit-identical** to the
  single-engine control, the allocator audit must pass on BOTH sides
  of the socket (the remote audited over the wire), and the child must
  exit 0.
* **Metric-name lint** — the run registers the
  ``deepspeed_tpu_serving_fleet_*`` + ``deepspeed_tpu_serving_slo_*``
  + ``deepspeed_tpu_serving_kv_tier_*`` +
  ``deepspeed_tpu_serving_kv_nvme_*`` +
  ``deepspeed_tpu_serving_transport_*`` +
  ``deepspeed_tpu_serving_autoscale_*`` families, then
  ``tools/check_metric_names.py`` must pass over the tree and see
  them.

Writes ``fleet_drill.json`` under ``--out``, prints ONE JSON summary
line, and exits non-zero when any check fails — the acceptance gate for
the serving-fleet subsystem.

Knobs: ``--out DIR`` (default ./fleet_drill_demo), ``--requests N``
(default 6), ``--new-tokens N`` (default 10), ``--seed S`` (default 7:
threads through prompt generation AND every chaos injector, so any
failure replays from the seed logged in the summary).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys

_TOOLS_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO_DIR = os.path.dirname(_TOOLS_DIR)
sys.path.insert(0, _REPO_DIR)
if _TOOLS_DIR not in sys.path:  # in-process entrypoint call (tests)
    sys.path.insert(1, _TOOLS_DIR)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

PAGE_SIZE = 8
PREFIX_TOKENS = 16  # two full pages shared per request family


def _check(checks, name, ok, detail=""):
    checks.append({"check": name, "ok": bool(ok), "detail": str(detail)})
    print(f"  [{'ok' if ok else 'FAIL'}] {name}"
          + (f" — {detail}" if detail else ""))
    return ok


def _build(n_requests: int, new_tokens: int, seed: int = 7):
    import jax
    import numpy as np

    from deepspeed_tpu.inference.v2 import (InferenceEngineV2,
                                            RaggedInferenceConfig,
                                            RaggedRequest)
    from deepspeed_tpu.models.llama import llama_model
    from deepspeed_tpu.serving import ServingConfig, build_fleet

    model = llama_model("tiny", max_seq_len=128)
    params = model.init_params(jax.random.PRNGKey(0))
    base = RaggedInferenceConfig(dtype="fp32", page_size=PAGE_SIZE,
                                 num_pages=64, max_seqs=4,
                                 max_pages_per_seq=12,
                                 enable_prefix_cache=True)
    serving = ServingConfig(enabled=True, prefill_replicas=1,
                            decode_replicas=2, disaggregated=True,
                            affinity_pages=2, prefill_chunk=PAGE_SIZE)
    fleet = build_fleet(model, serving, engine_config=base, params=params)

    rng = np.random.RandomState(seed)
    vocab = model.config.vocab_size
    prefix = list(rng.randint(0, vocab, PREFIX_TOKENS))

    def make_requests(n, salt, **kw):
        rq = np.random.RandomState(seed * 100 + salt)
        return [RaggedRequest(
            prompt_ids=prefix + list(rq.randint(0, vocab, 3 + i)),
            max_new_tokens=new_tokens, **kw) for i in range(n)]

    def control_run(requests):
        """Fresh single engine on the same weights; greedy, so the
        fleet must reproduce these streams token-for-token."""
        eng = InferenceEngineV2(model, base, params=params)
        got = eng.generate_all([RaggedRequest(
            prompt_ids=list(r.prompt_ids),
            max_new_tokens=r.max_new_tokens) for r in requests])
        eng.close()
        return [got[i] for i in range(len(requests))]

    def build_slo_fleet():
        """Fresh 1-prefill + 2-decode fleet with the overload knobs on:
        bounded queue, pool-pressure shedding, tight breaker windows.
        Prefix cache off so a PoolSqueeze can drive occupancy to 1.0
        (no LRU-parked pages keeping ``free_pages`` high)."""
        slo_base = RaggedInferenceConfig(dtype="fp32", page_size=PAGE_SIZE,
                                         num_pages=48, max_seqs=4,
                                         max_pages_per_seq=12)
        slo_serving = ServingConfig(
            enabled=True, prefill_replicas=1, decode_replicas=2,
            disaggregated=True, affinity_pages=2, prefill_chunk=PAGE_SIZE,
            max_queue_depth=4, shed_occupancy=0.85, protect_priority=0,
            breaker_latency_factor=3.0, breaker_window=16,
            breaker_min_samples=4, breaker_consec_errors=3,
            breaker_cooldown_pumps=6, breaker_probe_steps=3,
            breaker_min_latency_s=0.0005)
        fl = build_fleet(model, slo_serving, engine_config=slo_base,
                         params=params)
        ctl = InferenceEngineV2(model, slo_base, params=params)

        def slo_control(requests):
            # one long-lived control engine: generate_all returns only
            # this call's uids (auto-increment => sorted = submit order)
            got = ctl.generate_all([RaggedRequest(
                prompt_ids=list(r.prompt_ids),
                max_new_tokens=r.max_new_tokens) for r in requests])
            return [got[u] for u in sorted(got)]

        return fl, slo_control

    def build_tier_fleet():
        """Fresh 1-prefill + 1-decode fleet with the device prefix
        cache capped BELOW the tier leg's working set and the host-RAM
        KV tier on — the ``serving.kv_tier`` block flows through
        ``build_fleet`` to every replica.  The control is an UNCAPPED
        single engine (no tier): the tier must make the capped fleet
        reproduce its streams bit-identically."""
        from deepspeed_tpu.serving import KVTierConfig

        tier_base = RaggedInferenceConfig(
            dtype="fp32", page_size=PAGE_SIZE, num_pages=48, max_seqs=4,
            max_pages_per_seq=12, enable_prefix_cache=True,
            prefix_cache_pages=3)  # 1.5 families of 2 prefix pages
        tier_serving = ServingConfig(
            enabled=True, prefill_replicas=1, decode_replicas=1,
            disaggregated=True, affinity_pages=2, prefill_chunk=PAGE_SIZE,
            kv_tier=KVTierConfig(enabled=True))
        fl = build_fleet(model, tier_serving, engine_config=tier_base,
                         params=params)
        uncapped = RaggedInferenceConfig(
            dtype="fp32", page_size=PAGE_SIZE, num_pages=64, max_seqs=4,
            max_pages_per_seq=12, enable_prefix_cache=True)
        ctl = InferenceEngineV2(model, uncapped, params=params)

        def tier_control(requests):
            got = ctl.generate_all([RaggedRequest(
                prompt_ids=list(r.prompt_ids),
                max_new_tokens=r.max_new_tokens) for r in requests])
            return [got[u] for u in sorted(got)]

        return fl, tier_control

    def make_tier_waves(new_tokens, n_fams=3, per_fam=2, rounds=2,
                        salt=12):
        """Distinct-prefix FAMILY waves for the tier leg: each wave is
        one family's burst; families cycle over ``rounds`` so the
        capped device cache must evict (spill) a family before it comes
        around again (restore)."""
        rq = np.random.RandomState(seed * 100 + salt)
        fams = [list(rq.randint(0, vocab, PREFIX_TOKENS))
                for _ in range(n_fams)]
        waves = []
        for _r in range(rounds):
            for f in fams:
                waves.append([RaggedRequest(
                    prompt_ids=f + list(rq.randint(0, vocab, 3 + i)),
                    max_new_tokens=new_tokens) for i in range(per_fam)])
        return waves

    def build_mp_fleet():
        """One-replica MIXED fleet with live decode rebalancing on,
        plus the spawn spec for a cross-process peer: the child
        re-derives the SAME weights from ``init_params(PRNGKey(0))``
        and the same engine config, so a stream decodes bit-identically
        on either side of the socket."""
        from deepspeed_tpu.inference.v2 import InferenceEngineV2 as Eng
        from deepspeed_tpu.serving.replica import EngineReplica
        from deepspeed_tpu.serving.router import FleetRouter

        mp_serving = ServingConfig(
            enabled=True, disaggregated=False, rebalance_enabled=True,
            rebalance_load_gap=1, rebalance_max_per_pump=2)
        local = EngineReplica("local0", Eng(model, base, params=params))
        fl = FleetRouter([local], mp_serving)
        spec = {"model": "tiny", "max_seq_len": 128, "seed": 0,
                "engine_config": base}
        return fl, spec

    def build_nvme_fleet(nvme_dir):
        """Fresh 1-prefill + 1-decode fleet with BOTH spill tiers
        capped: the device prefix cache below the working set (as the
        tier leg) AND the host-RAM tier budgeted at three page records,
        with the NVMe third tier on under ``nvme_dir`` — cold families
        must demote host -> file and promote back (CRC-verified,
        bit-identical) when they return.  Control stays the UNCAPPED
        single engine."""
        from deepspeed_tpu.serving import KVTierConfig

        mc = model.config
        # one spilled prefix-page record: per-layer K+V blocks of
        # [page_size, n_kv_heads, head_dim] fp32
        page_nb = (mc.n_layers * 2 * PAGE_SIZE * mc.n_kv_heads
                   * (mc.hidden_size // mc.n_heads) * 4)
        nvme_base = RaggedInferenceConfig(
            dtype="fp32", page_size=PAGE_SIZE, num_pages=48, max_seqs=4,
            max_pages_per_seq=12, enable_prefix_cache=True,
            prefix_cache_pages=3)
        nvme_serving = ServingConfig(
            enabled=True, prefill_replicas=1, decode_replicas=1,
            disaggregated=True, affinity_pages=2, prefill_chunk=PAGE_SIZE,
            kv_tier=KVTierConfig(enabled=True,
                                 host_bytes=3 * page_nb + 64,
                                 nvme_enabled=True, nvme_dir=nvme_dir))
        fl = build_fleet(model, nvme_serving, engine_config=nvme_base,
                         params=params)
        uncapped = RaggedInferenceConfig(
            dtype="fp32", page_size=PAGE_SIZE, num_pages=64, max_seqs=4,
            max_pages_per_seq=12, enable_prefix_cache=True)
        ctl = InferenceEngineV2(model, uncapped, params=params)

        def nvme_control(requests):
            got = ctl.generate_all([RaggedRequest(
                prompt_ids=list(r.prompt_ids),
                max_new_tokens=r.max_new_tokens) for r in requests])
            return [got[u] for u in sorted(got)]

        return fl, nvme_control

    def build_trace_fleet():
        """Fresh 1-prefill + 2-decode disaggregated fleet on a FRESH
        request-trace ledger, with an unmeetable TTFT SLO
        (``slo_ttft_s`` = 1µs) so every stream records a violation
        exemplar — the tracing leg proves each exemplar resolves to a
        trace in the merged artifact."""
        from deepspeed_tpu.telemetry.reqtrace import (ReqTraceLedger,
                                                      set_reqtrace_ledger)

        led = ReqTraceLedger()
        set_reqtrace_ledger(led)
        tr_base = RaggedInferenceConfig(
            dtype="fp32", page_size=PAGE_SIZE, num_pages=64, max_seqs=4,
            max_pages_per_seq=12, enable_prefix_cache=True,
            slo_ttft_s=1e-6)
        tr_serving = ServingConfig(
            enabled=True, prefill_replicas=1, decode_replicas=2,
            disaggregated=True, affinity_pages=2, prefill_chunk=PAGE_SIZE)
        return build_fleet(model, tr_serving, engine_config=tr_base,
                           params=params), led

    def build_multistep_fleet():
        """Fresh 1-prefill + 1-decode fleet with the fused multi-step
        decode horizon applied fleet-wide (``serving.decode_horizon``
        flows through ``build_fleet`` to every replica): the decode
        pool pulls K tokens per host round-trip and must reproduce the
        single-engine K=1 control's greedy streams bit-identically."""
        ms_serving = ServingConfig(
            enabled=True, prefill_replicas=1, decode_replicas=1,
            disaggregated=True, affinity_pages=2, prefill_chunk=PAGE_SIZE,
            decode_horizon=8)
        return build_fleet(model, ms_serving, engine_config=base,
                           params=params)

    return (fleet, make_requests, control_run, build_slo_fleet,
            build_tier_fleet, make_tier_waves, build_multistep_fleet,
            build_trace_fleet, build_nvme_fleet, build_mp_fleet)


def run_demo(out: str, n_requests: int, new_tokens: int,
             seed: int = 7) -> int:
    from deepspeed_tpu.telemetry import get_registry

    shutil.rmtree(out, ignore_errors=True)
    os.makedirs(out)
    print(f"fleet drill: {n_requests} requests x {new_tokens} tokens, "
          f"1 prefill + 2 decode replicas, seed {seed} -> {out}")
    (fleet, make_requests, control_run, build_slo_fleet,
     build_tier_fleet, make_tier_waves, build_multistep_fleet,
     build_trace_fleet, build_nvme_fleet, build_mp_fleet) = \
        _build(n_requests, new_tokens, seed)
    reg = get_registry()

    def counter(name):
        m = reg.get(name)  # get, not get-or-create: some slo_* metrics
        return m.total() if m is not None else 0.0  # carry labels

    checks = []

    # ---- leg 1: disaggregated serving + mid-stream decode-replica kill
    reqs = make_requests(n_requests, salt=1)
    want = control_run(reqs)
    uids = [fleet.submit(r) for r in reqs]
    mid_stream = False
    for _ in range(200):
        fleet.step()
        states = [fleet.request_state(u) for u in uids]
        on_decode = [s for s in states if (s["replica"] or "").startswith("decode")]
        if on_decode and all(1 <= len(s["emitted"]) < new_tokens
                             for s in states):
            mid_stream = True
            break
    _check(checks, "streams_mid_flight_on_decode_pool", mid_stream,
           f"{len([1 for s in states if s['replica']])} placed")
    hosts = {}
    for u in uids:
        rep = fleet.request_state(u)["replica"] or ""
        if rep.startswith("decode"):
            hosts[rep] = hosts.get(rep, 0) + 1
    victim = max(hosts, key=hosts.get) if hosts else "decode0"
    d0, r0 = counter("deepspeed_tpu_serving_fleet_replica_deaths_total"), \
        counter("deepspeed_tpu_serving_fleet_redispatches_total")
    print(f"  killing {victim} mid-stream "
          f"(hosting {hosts.get(victim, 0)} stream(s))")
    fleet.kill_replica(victim)
    for _ in range(400):
        if not fleet.has_work():
            break
        fleet.step()
    got = [fleet.request_state(u)["emitted"] for u in uids]
    _check(checks, "all_streams_complete_after_kill",
           not fleet.has_work()
           and all(not fleet.request_state(u)["failed"] for u in uids))
    _check(checks, "kill_leg_bit_identical_to_single_engine",
           got == want,
           f"{sum(g == w for g, w in zip(got, want))}/{len(want)} match")
    _check(checks, "replica_death_detected",
           counter("deepspeed_tpu_serving_fleet_replica_deaths_total") == d0 + 1)
    _check(checks, "streams_recovered_via_redispatch",
           counter("deepspeed_tpu_serving_fleet_redispatches_total") > r0,
           f"{counter('deepspeed_tpu_serving_fleet_redispatches_total') - r0} "
           "re-dispatched")
    _check(checks, "kv_migrations_ran",
           counter("deepspeed_tpu_serving_fleet_migrations_total")
           >= n_requests,
           f"{counter('deepspeed_tpu_serving_fleet_migrations_total')} "
           "migrations, "
           f"{counter('deepspeed_tpu_serving_fleet_migrated_pages_total')} "
           "pages")

    # ---- leg 2: preemption notice on the surviving decode replica
    reqs2 = make_requests(max(2, n_requests // 2), salt=2)
    want2 = control_run(reqs2)
    uids2 = [fleet.submit(r) for r in reqs2]
    for _ in range(3):
        fleet.step()
    survivors = [n for n, r in fleet.replicas.items()
                 if r.alive and not r.retired and r.role == "decode"]
    p0 = counter("deepspeed_tpu_serving_fleet_replica_preemptions_total")
    if survivors:
        print(f"  preemption notice -> {survivors[0]}")
        fleet.replicas[survivors[0]].watcher.notify("maintenance-sim")
    for _ in range(400):
        if not fleet.has_work():
            break
        fleet.step()
    got2 = [fleet.request_state(u)["emitted"] for u in uids2]
    _check(checks, "preempted_replica_evacuated",
           bool(survivors)
           and counter("deepspeed_tpu_serving_fleet_replica_preemptions_total")
           == p0 + 1, survivors)
    _check(checks, "preempt_leg_bit_identical_to_single_engine",
           got2 == want2,
           f"{sum(g == w for g, w in zip(got2, want2))}/{len(want2)} match")

    # ---- allocator integrity: after two legs of KV churn (migration,
    # re-dispatch, evacuation) no surviving replica may hold a leaked
    # page or refcount — the BlockAllocator debug audit is exact
    leak_errs = []
    for name, rep in fleet.replicas.items():
        if not rep.alive:
            continue  # a hard-killed replica's state is gone by design
        try:
            rep.engine.assert_no_leaks()
        except AssertionError as e:
            leak_errs.append(f"{name}: {e}")
    _check(checks, "allocator_no_leaks_after_churn", not leak_errs,
           leak_errs[:2] if leak_errs else
           f"{sum(1 for r in fleet.replicas.values() if r.alive)} "
           "replicas audited")

    # ======== SLO legs: fresh fleet with overload knobs on ========
    from deepspeed_tpu.inference.v2 import (PRIORITY_BATCH,
                                            PRIORITY_INTERACTIVE,
                                            RejectedError)
    from deepspeed_tpu.resilience.chaos import PoolSqueeze, SlowReplica

    slo_fleet, slo_control = build_slo_fleet()

    # ---- leg 3: overload -> bounded-queue shedding by priority
    print("  leg 3: overload (bounded queue + pool squeeze)")
    shed0 = counter("deepspeed_tpu_serving_slo_shed_total")
    lows = make_requests(4, salt=3, priority=PRIORITY_BATCH)
    low_uids = [slo_fleet.submit(r) for r in lows]  # fills queue to 4
    shed_lows = 0
    for r in make_requests(2, salt=4, priority=PRIORITY_BATCH):
        try:
            slo_fleet.submit(r)
        except RejectedError as e:
            shed_lows += 1
            _check(checks, "shed_carries_retry_hint_and_reason",
                   e.retry_after_s > 0 and e.reason == "queue_full",
                   f"reason={e.reason} retry_after={e.retry_after_s}")
    highs = make_requests(2, salt=5, priority=PRIORITY_INTERACTIVE)
    high_shed = 0
    high_uids = []
    for r in highs:
        try:
            high_uids.append(slo_fleet.submit(r))
        except RejectedError:
            high_shed += 1
    _check(checks, "overload_sheds_only_low_priority",
           shed_lows == 2 and high_shed == 0,
           f"{shed_lows} low shed, {high_shed} high shed")
    want_slo = slo_control(lows + highs)
    for _ in range(400):
        if not slo_fleet.has_work():
            break
        slo_fleet.step()
    got_slo = [slo_fleet.request_state(u)["emitted"]
               for u in low_uids + high_uids]
    _check(checks, "admitted_overload_streams_bit_identical",
           got_slo == want_slo,
           f"{sum(g == w for g, w in zip(got_slo, want_slo))}"
           f"/{len(want_slo)} match")
    # pool-pressure rule: squeeze the prefill pool's free pages, then a
    # low-priority submit sheds while a high-priority one is admitted
    pf = slo_fleet.replicas["prefill0"]
    with PoolSqueeze(pf.engine, pf.engine.allocator.num_pages):
        try:
            slo_fleet.submit(make_requests(1, salt=6,
                                           priority=PRIORITY_BATCH)[0])
            squeezed_shed = False
        except RejectedError as e:
            squeezed_shed = (e.reason == "pool_pressure")
        hp = make_requests(1, salt=7, priority=PRIORITY_INTERACTIVE)[0]
        hp_uid = slo_fleet.submit(hp)  # protected: admitted, waits
    for _ in range(200):  # squeeze released: the protected request runs
        if not slo_fleet.has_work():
            break
        slo_fleet.step()
    _check(checks, "pool_squeeze_sheds_low_admits_high",
           squeezed_shed
           and slo_fleet.request_state(hp_uid)["emitted"]
           == slo_control([hp])[0])
    shed_delta = counter("deepspeed_tpu_serving_slo_shed_total") - shed0
    _check(checks, "every_shed_counted", shed_delta == shed_lows + 1,
           f"slo_shed_total +{shed_delta} for {shed_lows + 1} sheds")

    # ---- leg 4: deadlines fire at the step boundary
    print("  leg 4: deadlines")
    dl0 = counter("deepspeed_tpu_serving_slo_deadline_exceeded_total")
    doomed = make_requests(2, salt=8, priority=PRIORITY_BATCH,
                           deadline_s=0.0)
    healthy = make_requests(2, salt=9)
    doomed_uids = [slo_fleet.submit(r) for r in doomed]
    healthy_uids = [slo_fleet.submit(r) for r in healthy]
    want_h = slo_control(healthy)
    for _ in range(200):
        if not slo_fleet.has_work():
            break
        slo_fleet.step()
    doomed_states = [slo_fleet.request_state(u) for u in doomed_uids]
    _check(checks, "deadlines_fire_with_finish_reason",
           all(s["done"] and s["finish_reason"] == "deadline"
               and s["emitted"] == [] for s in doomed_states),
           [s["finish_reason"] for s in doomed_states])
    dl_delta = counter(
        "deepspeed_tpu_serving_slo_deadline_exceeded_total") - dl0
    _check(checks, "every_expiry_counted", dl_delta == len(doomed_uids),
           f"slo_deadline_exceeded_total +{dl_delta}")
    _check(checks, "undeadlined_wave_bit_identical",
           [slo_fleet.request_state(u)["emitted"]
            for u in healthy_uids] == want_h)

    # ---- leg 5: slow replica -> breaker trip -> bit-identical finish
    # -> half-open recovery on live traffic
    print("  leg 5: slow replica (gray failure)")
    trips0 = counter("deepspeed_tpu_serving_slo_breaker_trips_total")
    rec0 = counter("deepspeed_tpu_serving_slo_breaker_recoveries_total")
    # interactive priority: the SLO fleet's bounded queue stays armed
    # (max_queue_depth=4) and this wave is submitted in one burst —
    # protected traffic must ride through, which is itself the contract
    wave = make_requests(n_requests, salt=10, priority=PRIORITY_INTERACTIVE)
    want_w = slo_control(wave)
    wave_uids = [slo_fleet.submit(r) for r in wave]
    for _ in range(200):  # get streams decoding on the decode pool
        slo_fleet.step()
        states = [slo_fleet.request_state(u) for u in wave_uids]
        if any((s["replica"] or "").startswith("decode")
               and 1 <= len(s["emitted"]) < new_tokens for s in states):
            break
    hosts = {}
    for s in states:
        if (s["replica"] or "").startswith("decode"):
            hosts[s["replica"]] = hosts.get(s["replica"], 0) + 1
    slow_name = max(hosts, key=hosts.get) if hosts else "decode0"
    print(f"    injecting 80ms step delay into {slow_name} "
          f"(hosting {hosts.get(slow_name, 0)} stream(s))")
    slow = slo_fleet.replicas[slow_name]
    slow.inject_chaos(SlowReplica(delay_s=0.08, seed=seed))
    tripped = False
    for _ in range(100):
        slo_fleet.step()
        if slow.breaker == "open":
            tripped = True
            break
    _check(checks, "slow_replica_breaker_tripped", tripped,
           f"{slow_name} p50={slow.step_p50() * 1e3:.1f}ms "
           f"p95={slow.step_p95() * 1e3:.1f}ms")
    _check(checks, "breaker_trip_counted",
           counter("deepspeed_tpu_serving_slo_breaker_trips_total")
           == trips0 + 1)
    slow.clear_chaos()  # the operator fixed the host
    for _ in range(400):
        if not slo_fleet.has_work():
            break
        slo_fleet.step()
    got_w = [slo_fleet.request_state(u)["emitted"] for u in wave_uids]
    _check(checks, "slow_leg_bit_identical_to_single_engine",
           got_w == want_w,
           f"{sum(g == w for g, w in zip(got_w, want_w))}/{len(want_w)} "
           "match")
    # recovery: cooldown -> half_open probe on live traffic -> closed
    wave2 = make_requests(max(2, n_requests // 2), salt=11,
                          priority=PRIORITY_INTERACTIVE)
    want_w2 = slo_control(wave2)
    w2_uids = [slo_fleet.submit(r) for r in wave2]
    for _ in range(400):
        if not slo_fleet.has_work() and slow.breaker == "closed":
            break
        slo_fleet.step()
    _check(checks, "breaker_recovered_via_half_open_probe",
           slow.breaker == "closed" and slow.accepts_new()
           and counter("deepspeed_tpu_serving_slo_breaker_recoveries_total")
           == rec0 + 1, f"breaker={slow.breaker}")
    _check(checks, "post_recovery_wave_bit_identical",
           [slo_fleet.request_state(u)["emitted"]
            for u in w2_uids] == want_w2)
    slo_leaks = []
    for name, rep in slo_fleet.replicas.items():
        if rep.alive:
            try:
                rep.engine.assert_no_leaks()
            except AssertionError as e:
                slo_leaks.append(f"{name}: {e}")
    _check(checks, "slo_fleet_no_leaks", not slo_leaks, slo_leaks[:2])

    # ---- leg 6: tiered KV cache — capped device cache + host-RAM tier
    print("  leg 6: tiered KV cache (host-RAM spill & restore)")
    tier_fleet, tier_control = build_tier_fleet()
    sp0 = counter("deepspeed_tpu_serving_kv_tier_spilled_pages_total")
    rs0 = counter("deepspeed_tpu_serving_kv_tier_restored_pages_total")
    got_tier, want_tier = [], []
    for wave in make_tier_waves(new_tokens):
        want_tier.extend(tier_control(wave))
        wave_uids = [tier_fleet.submit(r) for r in wave]
        for _ in range(300):
            if not tier_fleet.has_work():
                break
            tier_fleet.step()
        got_tier.extend(tier_fleet.request_state(u)["emitted"]
                        for u in wave_uids)
    sp = counter("deepspeed_tpu_serving_kv_tier_spilled_pages_total") - sp0
    rs = counter("deepspeed_tpu_serving_kv_tier_restored_pages_total") - rs0
    _check(checks, "kv_tier_spills_and_restores_ran", sp > 0 and rs > 0,
           f"{sp:.0f} pages spilled, {rs:.0f} restored")
    _check(checks, "kv_tier_streams_bit_identical_to_uncapped_control",
           got_tier == want_tier,
           f"{sum(g == w for g, w in zip(got_tier, want_tier))}"
           f"/{len(want_tier)} match")
    tier_leaks = []
    for name, rep in tier_fleet.replicas.items():
        try:
            rep.engine.assert_no_leaks()  # accounts in-flight spill pins
        except AssertionError as e:
            tier_leaks.append(f"{name}: {e}")
    _check(checks, "kv_tier_no_leaks_after_churn", not tier_leaks,
           tier_leaks[:2] if tier_leaks else
           f"{len(tier_fleet.replicas)} replicas audited (spill pins "
           "accounted)")
    tier_health = tier_fleet.health()
    _check(checks, "kv_tier_occupancy_in_replica_health",
           any(h.get("kv_tier_host_pages", 0) > 0
               for h in tier_health.values()),
           {n: h.get("kv_tier_host_pages") for n, h in tier_health.items()})

    # ---- leg 7: fused multi-step decode pool vs single-step control
    print("  leg 7: fused multi-step decode (decode_horizon=8)")
    ms_reqs = make_requests(4, salt=21)
    # control FIRST: its K=1 engine pays one host sync per token on the
    # same process-shared counter the fused pool is measured against
    want_ms = control_run(ms_reqs)
    ms_fleet = build_multistep_fleet()
    sync0 = counter("deepspeed_tpu_serving_decode_host_syncs_total")
    ms_uids = [ms_fleet.submit(r) for r in ms_reqs]
    for _ in range(300):
        if not ms_fleet.has_work():
            break
        ms_fleet.step()
    got_ms = [ms_fleet.request_state(u)["emitted"] for u in ms_uids]
    ms_tokens = len(ms_reqs) * new_tokens
    ms_syncs = counter("deepspeed_tpu_serving_decode_host_syncs_total") \
        - sync0
    _check(checks, "multistep_pool_bit_identical_to_single_step_control",
           got_ms == want_ms,
           f"{sum(g == w for g, w in zip(got_ms, want_ms))}"
           f"/{len(want_ms)} match")
    _check(checks, "multistep_decode_amortizes_host_syncs",
           0 < ms_syncs <= ms_tokens / 2,
           f"{ms_syncs:.0f} decode host pulls for {ms_tokens} tokens")
    ms_leaks = []
    for name, rep in ms_fleet.replicas.items():
        try:
            rep.engine.assert_no_leaks()
        except AssertionError as e:
            ms_leaks.append(f"{name}: {e}")
    _check(checks, "multistep_no_leaks_after_horizon_churn", not ms_leaks,
           ms_leaks[:2] if ms_leaks else
           f"{len(ms_fleet.replicas)} replicas audited")

    # ---- leg 8: fleet-wide request tracing — fresh disaggregated fleet
    # + mid-stream kill on a fresh ledger; every request must read as ONE
    # connected trace in the merged artifact, its phase ledger must sum
    # to end-to-end latency, and the forced TTFT violations must carry
    # exemplars that resolve INTO the artifact
    print("  leg 8: request tracing (merged fleet trace + phase ledger)")
    from deepspeed_tpu.telemetry.reqtrace import write_merged_trace

    tr_fleet, tr_led = build_trace_fleet()
    tr_reqs = make_requests(n_requests, salt=31)
    tr_uids = [tr_fleet.submit(r) for r in tr_reqs]
    tr_states = []
    for _ in range(200):
        tr_fleet.step()
        tr_states = [tr_fleet.request_state(u) for u in tr_uids]
        if any((s["replica"] or "").startswith("decode")
               and 1 <= len(s["emitted"]) < new_tokens for s in tr_states):
            break
    tr_hosts = {}
    for s in tr_states:
        if (s["replica"] or "").startswith("decode"):
            tr_hosts[s["replica"]] = tr_hosts.get(s["replica"], 0) + 1
    tr_victim = max(tr_hosts, key=tr_hosts.get) if tr_hosts else "decode0"
    print(f"    killing {tr_victim} mid-stream for the recompute slice")
    tr_fleet.kill_replica(tr_victim)
    for _ in range(400):
        if not tr_fleet.has_work():
            break
        tr_fleet.step()
    tids = [tr_fleet.request_state(u)["trace_id"] for u in tr_uids]
    _check(checks, "trace_ids_minted_and_fleet_unique",
           all(tids) and len(set(tids)) == len(tids),
           f"{len(set(tids))} unique / {len(tids)}")
    redisp_tids = [t for t, u in zip(tids, tr_uids)
                   if tr_fleet.request_state(u)["redispatches"] >= 1]
    ledger_ok, ledger_err = True, f"{len(tids)} ledgers closed"
    for tid in tids:
        tr = tr_led.lookup(tid)
        if tr is None or not tr.done:
            ledger_ok, ledger_err = False, f"{tid}: missing or still open"
            break
        gap = abs(sum(tr.phase_seconds().values()) - tr.elapsed_s())
        if gap > 1e-3:
            ledger_ok, ledger_err = \
                False, f"{tid}: phases off end-to-end by {gap:.6f}s"
            break
    _check(checks, "ledger_phases_sum_to_end_to_end", ledger_ok,
           ledger_err)
    trace_path = os.path.join(out, "fleet_trace.json")
    n_ev = write_merged_trace(trace_path, ledger=tr_led)
    with open(trace_path) as f:
        tr_events = json.load(f)["traceEvents"]
    schema_bad = [e for e in tr_events if not all(
        k in e for k in ("ph", "ts", "dur", "pid", "tid", "name"))]
    _check(checks, "merged_trace_event_schema",
           n_ev > 0 and len(tr_events) == n_ev and not schema_bad,
           f"{n_ev} events -> {trace_path}")
    tr_slices = {}
    for e in tr_events:
        e_tid = (e.get("args") or {}).get("trace_id")
        if e.get("ph") == "X" and e_tid:
            tr_slices.setdefault(e_tid, set()).add(e["name"])
    need = {"prefill", "kv_transfer", "decode"}
    connected = [t for t in tids if need <= tr_slices.get(t, set())]
    _check(checks, "every_request_one_connected_trace",
           len(connected) == len(tids),
           f"{len(connected)}/{len(tids)} traces carry {sorted(need)}")
    _check(checks, "redispatch_produces_recompute_slice",
           bool(redisp_tids)
           and all("recompute" in tr_slices.get(t, set())
                   for t in redisp_tids),
           f"{len(redisp_tids)} stream(s) re-dispatched")
    exs = [e for ring in tr_led.exemplars().values() for e in ring]
    resolved = [e for e in exs if e["trace_id"] in tr_slices]
    _check(checks, "slo_exemplars_resolve_into_merged_artifact",
           bool(exs) and len(resolved) == len(exs),
           f"{len(resolved)}/{len(exs)} exemplars resolve "
           f"({sorted(tr_led.exemplars())})")

    # ---- leg 9: NVMe third tier — host budget capped at 3 page records
    print("  leg 9: NVMe third KV tier (host -> file demote & promote)")
    nvme_dir = os.path.join(out, "kv_nvme")
    nvme_fleet, nvme_control = build_nvme_fleet(nvme_dir)
    nsp0 = counter("deepspeed_tpu_serving_kv_nvme_spilled_pages_total")
    nrs0 = counter("deepspeed_tpu_serving_kv_nvme_restored_pages_total")
    nbad0 = counter("deepspeed_tpu_serving_kv_nvme_corrupt_pages_total")
    got_nv, want_nv = [], []
    for wave in make_tier_waves(new_tokens, salt=14):
        want_nv.extend(nvme_control(wave))
        wave_uids = [nvme_fleet.submit(r) for r in wave]
        for _ in range(300):
            if not nvme_fleet.has_work():
                break
            nvme_fleet.step()
        got_nv.extend(nvme_fleet.request_state(u)["emitted"]
                      for u in wave_uids)
    nsp = counter("deepspeed_tpu_serving_kv_nvme_spilled_pages_total") - nsp0
    nrs = counter("deepspeed_tpu_serving_kv_nvme_restored_pages_total") - nrs0
    nbad = counter("deepspeed_tpu_serving_kv_nvme_corrupt_pages_total") \
        - nbad0
    _check(checks, "kv_nvme_demotes_and_promotes_ran",
           nsp > 0 and nrs > 0,
           f"{nsp:.0f} pages demoted to file, {nrs:.0f} promoted back")
    _check(checks, "kv_nvme_no_corrupt_records", nbad == 0,
           f"{nbad:.0f} refused")
    nvme_files = [f for f in os.listdir(nvme_dir)
                  if f.endswith(".kvpage")] if os.path.isdir(nvme_dir) \
        else []
    _check(checks, "kv_nvme_records_on_disk", bool(nvme_files),
           f"{len(nvme_files)} .kvpage files under {nvme_dir}")
    _check(checks, "kv_nvme_streams_bit_identical_to_uncapped_control",
           got_nv == want_nv,
           f"{sum(g == w for g, w in zip(got_nv, want_nv))}"
           f"/{len(want_nv)} match")
    nv_stats = {}
    for name, rep in nvme_fleet.replicas.items():
        tier = getattr(rep.engine, "kv_tier", None)
        if tier is not None:
            nv_stats[name] = {k: v for k, v in tier.stats().items()
                              if k.startswith("nvme_")}
    _check(checks, "kv_nvme_occupancy_in_tier_stats",
           any(s.get("nvme_spilled_pages", 0) > 0
               for s in nv_stats.values()),
           {n: s.get("nvme_pages") for n, s in nv_stats.items()})
    nv_leaks = []
    for name, rep in nvme_fleet.replicas.items():
        try:
            rep.engine.assert_no_leaks()
        except AssertionError as e:
            nv_leaks.append(f"{name}: {e}")
    _check(checks, "kv_nvme_no_leaks_after_churn", not nv_leaks,
           nv_leaks[:2] if nv_leaks else
           f"{len(nvme_fleet.replicas)} replicas audited")

    # ---- leg 10: cross-process replica — KV over a real socket, elastic
    # grow (autoscaler spawns the remote into the fleet), live decode
    # rebalancing across the process boundary, then scale-down
    # evacuating the remote's streams BACK over the socket; hard-gated
    # bit-identical against the single-engine control
    print("  leg 10: cross-process replica (socket transport + elastic "
          "scale)")
    from deepspeed_tpu.serving import (AutoscaleConfig, FleetAutoscaler,
                                       RemoteEngineProxy,
                                       spawn_engine_server)
    from deepspeed_tpu.serving.replica import EngineReplica

    mp_fleet, mp_spec = build_mp_fleet()
    print("    spawning child engine server (cold JAX import; "
          "this takes a while)...")
    proc, address = spawn_engine_server(mp_spec)
    proxy = RemoteEngineProxy(address, seed=seed)
    mp_reqs = make_requests(6, salt=41)
    want_mp = control_run(mp_reqs)
    fs0 = counter("deepspeed_tpu_serving_transport_frames_sent_total")
    bs0 = counter("deepspeed_tpu_serving_transport_bytes_sent_total")
    rb0 = counter("deepspeed_tpu_serving_fleet_rebalanced_total")
    ad0 = counter("deepspeed_tpu_serving_fleet_replicas_added_total")
    gr0 = counter("deepspeed_tpu_serving_autoscale_grow_total")
    sh0 = counter("deepspeed_tpu_serving_autoscale_shrink_total")
    scaler = FleetAutoscaler(
        mp_fleet,
        AutoscaleConfig(enabled=True, min_replicas=1, max_replicas=2,
                        grow_queue_per_replica=1.0, grow_streak=1,
                        grow_on_ttft_violations=False,
                        shrink_queue_per_replica=0.25, shrink_streak=3,
                        cooldown_pumps=2),
        spawn_replica=lambda i: EngineReplica(f"remote{i}", proxy),
        seed=seed)
    mp_uids = [mp_fleet.submit(r) for r in mp_reqs]
    remote_saw = 0
    for _ in range(400):
        if not mp_fleet.has_work():
            break
        mp_fleet.step()
        scaler.evaluate()
        for name, rep in mp_fleet.replicas.items():
            if name.startswith("remote") and rep.alive and not rep.retired:
                remote_saw = max(remote_saw, rep.load())
    got_mp = [mp_fleet.request_state(u)["emitted"] for u in mp_uids]
    # grow/shrink can legitimately cycle under these aggressive knobs
    # (evacuated streams re-queue and re-trigger pressure), so gate on
    # "at least one" of each, not an exact count
    _check(checks, "mp_autoscaler_grew_remote_replica_into_fleet",
           counter("deepspeed_tpu_serving_autoscale_grow_total") >= gr0 + 1
           and counter("deepspeed_tpu_serving_fleet_replicas_added_total")
           >= ad0 + 1,
           f"replicas now {sorted(mp_fleet.replicas)}")
    _check(checks, "mp_rebalance_moved_streams_across_socket",
           counter("deepspeed_tpu_serving_fleet_rebalanced_total") > rb0
           and remote_saw > 0,
           f"{counter('deepspeed_tpu_serving_fleet_rebalanced_total') - rb0:.0f}"
           f" stream(s) rebalanced, remote peak load {remote_saw}")
    _check(checks, "mp_scale_down_evacuated_remote_mid_run",
           counter("deepspeed_tpu_serving_autoscale_shrink_total")
           >= sh0 + 1
           and any(r.retired for n, r in mp_fleet.replicas.items()
                   if n.startswith("remote")),
           "remote retired via drain/evacuation")
    _check(checks, "mp_all_streams_complete_no_drops",
           not mp_fleet.has_work()
           and all(not mp_fleet.request_state(u)["failed"]
                   for u in mp_uids))
    _check(checks, "mp_bit_identical_to_single_engine",
           got_mp == want_mp,
           f"{sum(g == w for g, w in zip(got_mp, want_mp))}"
           f"/{len(want_mp)} match")
    mp_frames = \
        counter("deepspeed_tpu_serving_transport_frames_sent_total") - fs0
    mp_bytes = \
        counter("deepspeed_tpu_serving_transport_bytes_sent_total") - bs0
    _check(checks, "mp_kv_actually_crossed_the_wire",
           mp_frames > 0 and mp_bytes > 0,
           f"{mp_frames:.0f} frames / {mp_bytes:.0f} B sent")
    mp_leaks = []
    try:
        mp_fleet.replicas["local0"].engine.assert_no_leaks()
    except AssertionError as e:
        mp_leaks.append(f"local0: {e}")
    try:
        proxy.assert_no_leaks()  # audits the CHILD engine over the wire
    except AssertionError as e:
        mp_leaks.append(f"remote: {e}")
    _check(checks, "mp_no_leaks_both_sides_of_socket", not mp_leaks,
           mp_leaks[:2] if mp_leaks else "local + remote audited")
    proxy.close()  # shuts the child server down cleanly
    proc.join(timeout=60)
    _check(checks, "mp_child_process_exited_clean", proc.exitcode == 0,
           f"exitcode {proc.exitcode}")

    # ---- metric-name lint over the tree (fleet family included)
    import check_metric_names as lint

    errors = lint.check(_REPO_DIR)
    fleet_names = sorted(n for n in lint.collect(_REPO_DIR)
                         if n.startswith("deepspeed_tpu_serving_fleet_"))
    _check(checks, "check_metric_names_passes", not errors,
           errors[:3] if errors else f"{len(fleet_names)} fleet metrics")
    _check(checks, "fleet_metric_family_registered", len(fleet_names) >= 8,
           fleet_names[:4])
    slo_names = sorted(n for n in lint.collect(_REPO_DIR)
                       if n.startswith("deepspeed_tpu_serving_slo_"))
    _check(checks, "slo_metric_family_registered", len(slo_names) >= 8,
           slo_names[:4])
    tier_names = sorted(n for n in lint.collect(_REPO_DIR)
                        if n.startswith("deepspeed_tpu_serving_kv_tier_"))
    _check(checks, "kv_tier_metric_family_registered",
           len(tier_names) >= 5, tier_names[:4])
    reqtrace_names = sorted(
        n for n in lint.collect(_REPO_DIR)
        if n.startswith("deepspeed_tpu_serving_reqtrace_"))
    _check(checks, "reqtrace_metric_family_registered",
           len(reqtrace_names) >= 4, reqtrace_names[:4])
    ms_family = ("deepspeed_tpu_serving_decode_tokens_per_dispatch",
                 "deepspeed_tpu_serving_decode_host_syncs_total",
                 "deepspeed_tpu_serving_decode_horizon_shrink_total")
    ms_names = sorted(n for n in lint.collect(_REPO_DIR) if n in ms_family)
    _check(checks, "multistep_metric_family_registered",
           len(ms_names) == len(ms_family), ms_names)
    tp_names = sorted(n for n in lint.collect(_REPO_DIR)
                      if n.startswith("deepspeed_tpu_serving_transport_"))
    _check(checks, "transport_metric_family_registered",
           len(tp_names) >= 8, tp_names[:4])
    as_names = sorted(n for n in lint.collect(_REPO_DIR)
                      if n.startswith("deepspeed_tpu_serving_autoscale_"))
    _check(checks, "autoscale_metric_family_registered",
           len(as_names) >= 4, as_names[:4])
    nv_names = sorted(n for n in lint.collect(_REPO_DIR)
                      if n.startswith("deepspeed_tpu_serving_kv_nvme_"))
    _check(checks, "kv_nvme_metric_family_registered",
           len(nv_names) >= 5, nv_names[:4])

    ok = all(c["ok"] for c in checks)
    summary = {"demo": "fleet_drill", "ok": ok, "out": out, "seed": seed,
               "requests": n_requests + len(reqs2),
               "victim": victim, "slow_replica": slow_name,
               "mp_child_exit": proc.exitcode,
               "nvme_stats": nv_stats,
               "health": fleet.health(),
               "slo_health": slo_fleet.health(),
               "fleet_metrics": fleet_names, "slo_metrics": slo_names,
               "trace_artifact": trace_path, "reqtrace": tr_led.summary(),
               "checks": checks}
    with open(os.path.join(out, "fleet_drill.json"), "w") as f:
        json.dump(summary, f, indent=2)
    print(json.dumps({k: v for k, v in summary.items()
                      if k not in ("checks", "health", "slo_health",
                                   "fleet_metrics", "slo_metrics",
                                   "reqtrace")}))
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--demo", action="store_true",
                    help="run the disaggregation + kill + preemption drill "
                         "on a tiny CPU model")
    ap.add_argument("--out", default="./fleet_drill_demo")
    ap.add_argument("--requests", type=int, default=6)
    ap.add_argument("--new-tokens", type=int, default=10)
    ap.add_argument("--seed", type=int, default=7,
                    help="threads through prompt generation and every "
                         "chaos injector; logged in the summary so any "
                         "failure replays exactly")
    args = ap.parse_args(argv)
    if not args.demo:
        ap.print_help()
        return 2
    if args.requests < 2 or args.new_tokens < 4:
        ap.error("need --requests >= 2 and --new-tokens >= 4 for a "
                 "meaningful mid-stream kill")
    return run_demo(os.path.abspath(args.out), args.requests,
                    args.new_tokens, seed=args.seed)


if __name__ == "__main__":
    sys.exit(main())
