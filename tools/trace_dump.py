#!/usr/bin/env python
"""Span-trace / flight-recorder demo CLI.

``--demo`` runs the timeline-observability path end-to-end on a tiny
CPU model and writes BOTH artifacts:

* a **Chrome-trace JSON** (``trace.json``, loadable in Perfetto /
  ``chrome://tracing``) holding the demo's spans: training
  ``train_batch`` phases, serving request lifecycles
  (request/admit/prefill/decode), trace-time collective events, and
  ``xla_compile`` spans from the recompilation sentinel's
  ``jax.monitoring`` listener;
* a **flight-recorder JSONL** (``flight/..jsonl``) with the final span
  ring, recent log events, and a full registry snapshot — the black box
  a crashed run would leave.

It also forces ONE re-jit (a train step with a changed batch shape) and
asserts the recompile counter moved by exactly one — the acceptance gate
for step-attributed compile accounting.

The **fleet leg** then runs a tiny 1-prefill + 1-decode disaggregated
fleet on a fresh request-trace ledger and writes the MERGED
multi-replica Perfetto artifact (``fleet_trace.json``): one process row
per owning replica, one thread track per router-minted ``trace_id``,
KV transit as its own slice — schema-verified (every event carries the
required Chrome-trace keys) and gated on every request reading as one
connected prefill → kv_transfer → decode trace.

The output is ONE JSON summary line; exit status is non-zero when a
required span family, Chrome-trace key, flight record, or the
exactly-once recompile increment is missing.

Knobs: ``--out DIR`` (default ./trace_demo), ``--steps N`` training
steps (default 5), ``--serve-requests N`` (default 3).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

#: every Chrome-trace event must carry these for Perfetto to load it
TRACE_EVENT_KEYS = ("ph", "ts", "dur", "pid", "tid", "name")

#: span families the demo must have produced
REQUIRED_SPANS = ("train_batch", "prefill", "decode", "request")


def _mlp_spec(hidden: int = 16, nlayers: int = 2):
    """Tiny MLP ModelSpec (mirrors tests/unit/simple_model.py, which
    tools must not import)."""
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.runtime.module import ModelSpec

    def init_params(rng):
        keys = jax.random.split(rng, nlayers)
        return {f"layer_{i}": {
            "w": jax.random.normal(k, (hidden, hidden)) * 0.1,
            "b": jnp.zeros((hidden,))} for i, k in enumerate(keys)}

    def forward(params, x):
        for i in range(nlayers):
            layer = params[f"layer_{i}"]
            x = x @ layer["w"] + layer["b"]
            if i < nlayers - 1:
                x = jax.nn.relu(x)
        return x

    def loss_fn(params, batch, rng):
        x, y = batch
        return jnp.mean((forward(params, x) - y) ** 2)

    return ModelSpec(init_params, loss_fn)


def _train_demo(out_dir: str, steps: int):
    import jax.numpy as jnp

    import deepspeed_tpu

    engine, *_ = deepspeed_tpu.initialize(
        model=_mlp_spec(),
        config={
            "train_micro_batch_size_per_gpu": 2,
            "steps_per_print": 2,
            "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
            "comms_logger": {"enabled": True},
            "telemetry": {
                "enabled": True,
                "spans": {"ring_size": 2048},
                "flight_recorder": {"path": os.path.join(out_dir, "flight")},
                "recompile_sentinel": {"steady_after": 3},
            },
        })
    hidden = 16
    rng = np.random.RandomState(0)

    def batch(bs):
        x = rng.randn(bs, hidden).astype(np.float32)
        y = x * 0.5
        return (jnp.asarray(x[None]), jnp.asarray(y[None]))

    B = engine.config.train_batch_size
    for _ in range(steps):
        engine.train_batch(batch(B))

    # forced re-jit: a NEW batch shape retraces the fused step — the
    # sentinel must attribute it as exactly ONE recompiled step
    reg = engine.telemetry.registry
    rc = reg.get("deepspeed_tpu_recompiles_total")
    before = rc.value(loop="train")
    engine.train_batch(batch(B + 2))
    recompile_delta = rc.value(loop="train") - before
    return engine, recompile_delta


def _serving_demo(n_requests: int):
    from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                      RaggedInferenceConfig,
                                                      RaggedRequest)
    from deepspeed_tpu.models.llama import llama_model

    model = llama_model("tiny", max_seq_len=128)
    eng = InferenceEngineV2(model, RaggedInferenceConfig(
        page_size=16, num_pages=64, max_seqs=4, max_pages_per_seq=8,
        enable_prefix_cache=True))
    rng = np.random.RandomState(0)
    vocab = model.config.vocab_size
    prefix = rng.randint(1, vocab, 32).tolist()
    eng.generate_all([RaggedRequest(
        prompt_ids=prefix + rng.randint(1, vocab, 8).tolist(),
        max_new_tokens=4)])
    eng.generate_all([RaggedRequest(
        prompt_ids=prefix + rng.randint(1, vocab, 8).tolist(),
        max_new_tokens=4) for _ in range(max(1, n_requests - 1))])
    return eng


def _fleet_demo(out_dir: str, n_requests: int):
    """Fleet tracing leg: 1-prefill + 1-decode disaggregated fleet on a
    FRESH request-trace ledger; writes the merged multi-replica Perfetto
    artifact and returns (path, trace_ids)."""
    import jax

    from deepspeed_tpu.inference.v2.engine_v2 import (RaggedInferenceConfig,
                                                      RaggedRequest)
    from deepspeed_tpu.models.llama import llama_model
    from deepspeed_tpu.serving import ServingConfig, build_fleet
    from deepspeed_tpu.telemetry.reqtrace import (ReqTraceLedger,
                                                  set_reqtrace_ledger,
                                                  write_merged_trace)

    led = ReqTraceLedger()
    set_reqtrace_ledger(led)
    model = llama_model("tiny", max_seq_len=128)
    params = model.init_params(jax.random.PRNGKey(0))
    base = RaggedInferenceConfig(dtype="fp32", page_size=8, num_pages=64,
                                 max_seqs=4, max_pages_per_seq=12,
                                 enable_prefix_cache=True)
    fleet = build_fleet(
        model, ServingConfig(enabled=True, prefill_replicas=1,
                             decode_replicas=1, disaggregated=True,
                             prefill_chunk=8),
        engine_config=base, params=params)
    rng = np.random.RandomState(1)
    vocab = model.config.vocab_size
    prefix = rng.randint(1, vocab, 16).tolist()
    uids = [fleet.submit(RaggedRequest(
        prompt_ids=prefix + rng.randint(1, vocab, 3 + i).tolist(),
        max_new_tokens=4)) for i in range(max(2, n_requests))]
    for _ in range(400):
        if not fleet.has_work():
            break
        fleet.step()
    tids = [fleet.request_state(u)["trace_id"] for u in uids]
    path = os.path.join(out_dir, "fleet_trace.json")
    write_merged_trace(path, ledger=led)
    return path, tids


def _verify_merged_trace(path: str, tids):
    """Schema + connectivity gate for the merged fleet artifact: every
    event carries the Chrome-trace keys, every submitted trace_id reads
    as one connected prefill → kv_transfer → decode track, and the
    merge spans more than one owner row (it IS cross-replica)."""
    problems = []
    with open(path) as f:
        events = json.load(f).get("traceEvents", [])
    if not events:
        problems.append("merged fleet trace has no traceEvents")
    for ev in events:
        missing = [k for k in TRACE_EVENT_KEYS if k not in ev]
        if missing:
            problems.append(f"fleet event {ev.get('name')!r} missing "
                            f"{missing}")
            break
        if ev["ph"] not in ("X", "M") \
                or not isinstance(ev["ts"], (int, float)) \
                or not isinstance(ev["dur"], (int, float)):
            problems.append(f"fleet event {ev.get('name')!r} malformed: "
                            f"ph={ev['ph']!r} ts={ev['ts']!r}")
            break
    slices = {}
    for ev in events:
        tid = (ev.get("args") or {}).get("trace_id")
        if ev.get("ph") == "X" and tid:
            slices.setdefault(tid, set()).add(ev["name"])
    need = {"prefill", "kv_transfer", "decode"}
    broken = [t for t in tids if not need <= slices.get(t, set())]
    if broken:
        problems.append(f"fleet traces missing {sorted(need)} slices: "
                        f"{broken}")
    owners = {ev["args"]["name"] for ev in events
              if ev.get("ph") == "M" and ev.get("name") == "process_name"}
    if len(owners) < 2:
        problems.append(f"merged trace has {len(owners)} owner row(s); a "
                        "cross-replica merge needs at least 2")
    return len(events), sorted(owners), problems


def _verify_trace(path: str):
    """Perfetto-loadability gate: the file parses, every event carries
    the required keys with numeric ts/dur, and the demo's span families
    are all present."""
    problems = []
    with open(path) as f:
        doc = json.load(f)
    events = doc.get("traceEvents", [])
    if not events:
        problems.append("trace has no traceEvents")
    for ev in events:
        missing = [k for k in TRACE_EVENT_KEYS if k not in ev]
        if missing:
            problems.append(f"event {ev.get('name')!r} missing {missing}")
            break
        if ev["ph"] != "X" or not isinstance(ev["ts"], (int, float)) \
                or not isinstance(ev["dur"], (int, float)):
            problems.append(f"event {ev.get('name')!r} malformed: "
                            f"ph={ev['ph']!r} ts={ev['ts']!r}")
            break
    names = {ev.get("name") for ev in events}
    missing_spans = [s for s in REQUIRED_SPANS if s not in names]
    if missing_spans:
        problems.append(f"missing span families: {missing_spans}")
    return len(events), sorted(n for n in names if n), problems


def _verify_flight(path: str):
    """The black box holds the final spans + a registry snapshot."""
    problems = []
    recs = [json.loads(line) for line in open(path)]
    kinds = [r.get("kind") for r in recs]
    if not recs or kinds[0] != "flight_header":
        problems.append("flight dump does not start with a flight_header")
    if kinds.count("span") == 0:
        problems.append("flight dump holds no spans")
    snaps = [r for r in recs if r.get("kind") == "snapshot"]
    if not snaps or not snaps[-1].get("metrics"):
        problems.append("flight dump holds no registry snapshot")
    return len(recs), problems


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--demo", action="store_true",
                    help="run the tiny-CPU end-to-end demo workload")
    ap.add_argument("--out", default="./trace_demo")
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--serve-requests", type=int, default=3)
    args = ap.parse_args(argv)
    if not args.demo:
        ap.error("only --demo mode is implemented; pass --demo")
    out_dir = os.path.abspath(args.out)
    os.makedirs(out_dir, exist_ok=True)

    from deepspeed_tpu.telemetry import get_registry, trace_dump

    engine, recompile_delta = _train_demo(out_dir, args.steps)
    serve = _serving_demo(args.serve_requests)
    fleet_trace_path, fleet_tids = _fleet_demo(out_dir,
                                               args.serve_requests)

    # ---- write both artifacts ------------------------------------------
    trace_path = trace_dump(os.path.join(out_dir, "trace.json"))
    flight = engine.telemetry.flight
    flight.note("demo_complete", steps=args.steps,
                serve_requests=args.serve_requests)
    flight_path = flight.dump(reason="demo")
    engine.close()

    # ---- verify them ---------------------------------------------------
    n_events, span_names, trace_problems = _verify_trace(trace_path)
    n_flight, flight_problems = _verify_flight(flight_path)
    n_fleet_events, fleet_owners, fleet_problems = _verify_merged_trace(
        fleet_trace_path, fleet_tids)
    problems = trace_problems + flight_problems + fleet_problems
    if recompile_delta != 1:
        problems.append(f"forced re-jit moved the recompile counter by "
                        f"{recompile_delta}, expected exactly 1")

    reg = get_registry()
    ttft = reg.get("deepspeed_tpu_serving_ttft_seconds")
    tpot = reg.get("deepspeed_tpu_serving_tpot_seconds")
    if ttft is None or ttft.count() == 0:
        problems.append("no TTFT observations from the serving demo")
    summary = {
        "trace_path": trace_path,
        "flight_path": flight_path,
        "trace_events": n_events,
        "span_families": span_names,
        "flight_records": n_flight,
        "fleet_trace_path": fleet_trace_path,
        "fleet_trace_events": n_fleet_events,
        "fleet_trace_owners": fleet_owners,
        "fleet_trace_ids": fleet_tids,
        "recompile_delta": recompile_delta,
        "compiles_total": (reg.get("deepspeed_tpu_compiles_total").total()
                           if reg.get("deepspeed_tpu_compiles_total") else 0),
        "ttft_s": ttft.percentiles() if ttft and ttft.count() else None,
        "tpot_s": tpot.percentiles() if tpot and tpot.count() else None,
        "prefix_hit_rate": serve.cache_stats()["prefix_hit_rate"],
        "problems": problems,
        "ok": not problems,
    }
    print(json.dumps(summary, default=float))
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
