"""Run the benchmark rung ladder and collect one JSON record per rung.

Usage (on a machine with the TPU reachable):

    python tools/bench_sweep.py            # all rungs
    python tools/bench_sweep.py flagship   # just the headline rung

Writes ``docs/BENCH_SWEEP.json`` (list of {rung, env, result|error}) and
prints a compact table.  Each rung is a bench.py invocation with the
env-selectable knobs (size/seq/bs/stage/offload), so the sweep measures
exactly what the driver's bench measures.
"""

from __future__ import annotations

import importlib.util
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _contract_gate() -> str:
    """Refuse to sweep against stale golden contracts (ROADMAP item 5):
    a perf artifact measured under program contracts that no longer match
    the tree is exactly the silent lie the contracts exist to prevent.
    Runs ``tools/check_contracts.py`` in a subprocess (it pins its own
    CPU harness) and returns the ``contract_set_hash`` stamped into every
    sweep record — same provenance bench.py already carries.  Skippable
    with DSTPU_SWEEP_SKIP_CONTRACTS=1 (the hash is stamped regardless).
    """
    # contract_set_hash is stdlib-only; load by file path so the sweep
    # driver itself never imports jax.  The module comes from THIS tree
    # (next to the tool — ROOT may be redirected to an artifact dir);
    # the hash is computed over ROOT's goldens.
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "dstpu_contracts_hash",
        os.path.join(here, "deepspeed_tpu", "analysis", "contracts.py"))
    contracts = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(contracts)
    h = contracts.contract_set_hash(ROOT)
    if os.environ.get("DSTPU_SWEEP_SKIP_CONTRACTS") == "1":
        print("bench_sweep: contract check SKIPPED "
              "(DSTPU_SWEEP_SKIP_CONTRACTS=1)", file=sys.stderr)
        return h
    print("bench_sweep: checking golden contracts before sweeping...",
          file=sys.stderr, flush=True)
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "check_contracts.py")],
        capture_output=True, text=True, timeout=1800)
    if proc.returncode != 0:
        print(proc.stdout[-3000:], file=sys.stderr)
        sys.exit("bench_sweep: REFUSING to sweep — golden contracts are "
                 "stale (see violations above).  Fix the regression or "
                 "regenerate with tools/check_contracts.py "
                 "--update-goldens, then re-run.")
    return h

RUNGS = {
    # headline: the round-3 PERF_NOTES configuration; bs unpinned so the
    # ladder can probe 32 first (OOM falls back to 16/8)
    "flagship": {"DSTPU_BENCH_SIZE": "160m", "DSTPU_BENCH_SEQ": "1024",
                 "DSTPU_BENCH_STEPS": "20"},
    # the shape PERF_NOTES predicts feeds the MXU better (hidden 2048)
    "1b": {"DSTPU_BENCH_SIZE": "1b", "DSTPU_BENCH_SEQ": "1024",
           "DSTPU_BENCH_STEPS": "10"},
    # fp32 master + m + v for 1.1B params is ~13GB before activations —
    # two fallbacks if the pure-HBM rung OOMs: bf16 exp_avg (-2.2GB,
    # stays on-chip) and host-offloaded optimizer states (ZeRO-Infinity)
    "1b-mu16": {"DSTPU_BENCH_SIZE": "1b", "DSTPU_BENCH_SEQ": "1024",
                "DSTPU_BENCH_STEPS": "10", "DSTPU_BENCH_MU_DTYPE": "bf16"},
    "1b-offload": {"DSTPU_BENCH_SIZE": "1b", "DSTPU_BENCH_SEQ": "1024",
                   "DSTPU_BENCH_BS": "8", "DSTPU_BENCH_STEPS": "5",
                   "DSTPU_BENCH_OFFLOAD": "1"},
    # ZeRO-3 on the same model/chip: settles the stage-3 XLA-prefetch bet
    "160m-zero3": {"DSTPU_BENCH_SIZE": "160m", "DSTPU_BENCH_SEQ": "1024",
                   "DSTPU_BENCH_BS": "16", "DSTPU_BENCH_STEPS": "20",
                   "DSTPU_BENCH_STAGE": "3"},
    # the A/B for the manual prefetch (2x-unrolled layer scan): compare
    # against 160m-zero3 — if XLA already overlaps, the delta is ~0
    "160m-zero3-prefetch": {"DSTPU_BENCH_SIZE": "160m",
                            "DSTPU_BENCH_SEQ": "1024",
                            "DSTPU_BENCH_BS": "16", "DSTPU_BENCH_STEPS": "20",
                            "DSTPU_BENCH_STAGE": "3",
                            "DSTPU_BENCH_PREFETCH": "1"},
    # compute/collective overlap A/Bs (runtime/zero/overlap.py): compare
    # against 160m-zero1 / 160m-zero3-prefetch — every rung record now
    # carries overlapped_fraction + the exposed-seconds estimate, so the
    # perf trajectory records EXPOSURE, not just walls (a wall delta
    # with an unchanged fraction is not an overlap regression)
    "160m-zero1-overlap": {"DSTPU_BENCH_SIZE": "160m",
                           "DSTPU_BENCH_SEQ": "1024",
                           "DSTPU_BENCH_BS": "16", "DSTPU_BENCH_STEPS": "20",
                           "DSTPU_BENCH_STAGE": "1",
                           "DSTPU_BENCH_OVERLAP": "1"},
    "160m-zero3-overlap": {"DSTPU_BENCH_SIZE": "160m",
                           "DSTPU_BENCH_SEQ": "1024",
                           "DSTPU_BENCH_BS": "16", "DSTPU_BENCH_STEPS": "20",
                           "DSTPU_BENCH_STAGE": "3",
                           "DSTPU_BENCH_PREFETCH": "1",
                           "DSTPU_BENCH_OVERLAP": "1"},
    # compressed overlap (docs/COMM.md "Compressed overlap"): int8 codes
    # + per-bucket EF residuals riding the in-loop exchange — compare
    # against the fp 160m-zero{1,3}-overlap rungs; the wire claim is
    # proven by bench.py --ab-overlap, these measure the wall on chip
    "160m-zero1-overlap-int8": {"DSTPU_BENCH_SIZE": "160m",
                                "DSTPU_BENCH_SEQ": "1024",
                                "DSTPU_BENCH_BS": "16",
                                "DSTPU_BENCH_STEPS": "20",
                                "DSTPU_BENCH_STAGE": "1",
                                "DSTPU_BENCH_OVERLAP": "1",
                                "DSTPU_BENCH_OVERLAP_COMPRESSION": "int8"},
    "160m-zero3-overlap-int8": {"DSTPU_BENCH_SIZE": "160m",
                                "DSTPU_BENCH_SEQ": "1024",
                                "DSTPU_BENCH_BS": "16",
                                "DSTPU_BENCH_STEPS": "20",
                                "DSTPU_BENCH_STAGE": "3",
                                "DSTPU_BENCH_PREFETCH": "1",
                                "DSTPU_BENCH_OVERLAP": "1",
                                "DSTPU_BENCH_OVERLAP_COMPRESSION": "int8"},
    # pipeline-parallel training (runtime/pipe/engine.py): the 2-stage
    # 1F1B pipe scan over the same 160m trunk — compare against flagship
    # (pipe claims 2 chips; data absorbs the rest).  Bit-exactness, EF
    # parity and the hop wire claim are proven by bench.py --ab-pipe on
    # the CPU tier; these rungs measure the wall on chip, and each
    # record carries pipe_bubble_fraction so a wall delta with an
    # unchanged bubble is not a schedule regression
    "160m-pipe2": {"DSTPU_BENCH_SIZE": "160m", "DSTPU_BENCH_SEQ": "1024",
                   "DSTPU_BENCH_BS": "16", "DSTPU_BENCH_STEPS": "20",
                   "DSTPU_BENCH_PIPE": "2"},
    # + int8 activation hops (EF on) and the bubble-overlapped int8
    # in-scan grad reduce — the full compressed-pipe configuration
    "160m-pipe2-int8hop": {"DSTPU_BENCH_SIZE": "160m",
                           "DSTPU_BENCH_SEQ": "1024",
                           "DSTPU_BENCH_BS": "16", "DSTPU_BENCH_STEPS": "20",
                           "DSTPU_BENCH_PIPE": "2",
                           "DSTPU_BENCH_PIPE_HOP": "int8",
                           "DSTPU_BENCH_OVERLAP": "1",
                           "DSTPU_BENCH_OVERLAP_COMPRESSION": "int8"},
    # optimizer offload boundary cost on hardware
    "160m-offload": {"DSTPU_BENCH_SIZE": "160m", "DSTPU_BENCH_SEQ": "1024",
                     "DSTPU_BENCH_BS": "16", "DSTPU_BENCH_STEPS": "10",
                     "DSTPU_BENCH_OFFLOAD": "1"},
    # dropless-MoE kernel throughput (VERDICT r3 weak #3: MoE perf was
    # unmeasured anywhere); 8 experts top-2 on the 160m trunk, ~600M
    # params total, ~320M active — MFU counts active flops only
    "moe-8x160m": {"DSTPU_BENCH_MODEL": "mixtral", "DSTPU_BENCH_SIZE": "8x160m",
                   "DSTPU_BENCH_SEQ": "1024", "DSTPU_BENCH_BS": "8",
                   "DSTPU_BENCH_STEPS": "10"},
    # long-sequence MFU: the Ulysses headline regime (attention-heavy);
    # remat + bf16 accumulation to fit seq=8k activations on one chip
    "160m-seq8k": {"DSTPU_BENCH_SIZE": "160m", "DSTPU_BENCH_SEQ": "8192",
                   "DSTPU_BENCH_BS": "2", "DSTPU_BENCH_STEPS": "10",
                   "DSTPU_BENCH_REMAT": "1", "DSTPU_BENCH_ACC": "bf16"},
    # serving: continuous-batching decode tok/s on the paged v2 engine
    # (runs tools/bench_inference.py instead of bench.py)
    "serving-160m": {"_tool": "bench_inference", "DSTPU_IBENCH_SIZE": "160m",
                     "DSTPU_IBENCH_PROMPT": "512", "DSTPU_IBENCH_GEN": "128",
                     "DSTPU_IBENCH_NREQ": "32"},
    # quantized serving: int8 KV pages + int8 weight-only matmuls — the
    # FastGen-style memory-bound regime where quantization buys capacity
    "serving-160m-int8": {"_tool": "bench_inference",
                          "DSTPU_IBENCH_SIZE": "160m",
                          "DSTPU_IBENCH_PROMPT": "512",
                          "DSTPU_IBENCH_GEN": "128",
                          "DSTPU_IBENCH_NREQ": "32",
                          "DSTPU_IBENCH_KVQ": "1", "DSTPU_IBENCH_WQ": "8"},
    # chunked prefill (Dynamic SplitFuse): same load, 128-token chunks —
    # compare per-step latency tail vs serving-160m
    "serving-160m-chunked": {"_tool": "bench_inference",
                             "DSTPU_IBENCH_SIZE": "160m",
                             "DSTPU_IBENCH_PROMPT": "512",
                             "DSTPU_IBENCH_GEN": "128",
                             "DSTPU_IBENCH_NREQ": "32",
                             "DSTPU_IBENCH_CHUNK": "128"},
    # tiered KV cache (serving/kv_tier.py): prefix families cycling
    # through a device prefix cache capped below the working set, host
    # tier off vs on — prefill tokens computed at the FIXED device pool
    # is the figure of merit; the run hard-gates bit-identity and zero
    # steady-state recompiles
    "serving-160m-kvtier": {"_tool": "bench_serving",
                            "_args": ["--ab-kv-tier"],
                            "DSTPU_SBENCH_SIZE": "160m",
                            "DSTPU_SBENCH_PREFIX": "256",
                            "DSTPU_SBENCH_SUFFIX": "32",
                            "DSTPU_SBENCH_GEN": "32"},
    # NVMe third KV tier (serving/kv_tier.py): same tiered A/B but with
    # the host tier itself byte-budgeted and the file-backed third tier
    # under it — demote/promote traffic must be real and the run
    # additionally hard-gates zero corrupt NVMe records
    "serving-160m-nvme": {"_tool": "bench_serving",
                          "_args": ["--ab-kv-tier"],
                          "DSTPU_SBENCH_SIZE": "160m",
                          "DSTPU_SBENCH_PREFIX": "256",
                          "DSTPU_SBENCH_SUFFIX": "32",
                          "DSTPU_SBENCH_GEN": "32",
                          "DSTPU_SBENCH_NVME": "1"},
    # fused multi-step decode (decode_horizon): K tokens per host
    # round-trip through one on-device decode scan — host syncs per
    # token is the figure of merit; the run hard-gates bit-identity
    # vs the K=1 loop and zero steady-state recompiles
    "serving-160m-multistep": {"_tool": "bench_serving",
                               "_args": ["--ab-multistep"],
                               "DSTPU_SBENCH_SIZE": "160m",
                               "DSTPU_SBENCH_PREFIX": "256",
                               "DSTPU_SBENCH_SUFFIX": "32",
                               "DSTPU_SBENCH_GEN": "128",
                               "DSTPU_SBENCH_HORIZON": "8"},
}


def main() -> int:
    names = sys.argv[1:] or list(RUNGS)
    # test hook: JSON dict merged over every rung (e.g. shrink sizes on CPU)
    overrides = json.loads(os.environ.get("DSTPU_SWEEP_OVERRIDES", "{}"))
    contract_hash = _contract_gate()
    out = []
    # DSTPU_SWEEP_CPU=1 forces bench.py's --cpu pin (the site TPU plugin
    # pins the platform via jax.config, so the env var alone can't)
    args = ["--cpu"] if os.environ.get("DSTPU_SWEEP_CPU") == "1" else []
    for name in names:
        # ambient DSTPU_BENCH_* exports must not silently reshape a rung:
        # the rung definition + DSTPU_SWEEP_OVERRIDES are the only knobs
        ambient = {k: v for k, v in os.environ.items()
                   if not (k.startswith("DSTPU_BENCH_")
                           or k.startswith("DSTPU_IBENCH_"))}
        rung = dict(RUNGS[name])
        tool = rung.pop("_tool", None)
        extra_args = rung.pop("_args", [])
        env = {**ambient, **rung, **overrides}
        script = os.path.join(ROOT, "tools", tool + ".py") if tool \
            else os.path.join(ROOT, "bench.py")
        print(f"=== rung {name}: {rung}", file=sys.stderr, flush=True)
        rec = {"rung": name, "env": rung,
               "contract_set_hash": contract_hash}
        try:
            # budget: the hang-proof ladder's worst case is
            # 3 rungs x (rung_timeout + 240s post-hang probe) + a CPU
            # fallback run — keep the rung budget small enough that the
            # whole ladder plus fallback fits the rung-set timeout
            env.setdefault("DSTPU_BENCH_RUNG_TIMEOUT", "600")
            proc = subprocess.run(
                [sys.executable, script, *extra_args, *args],
                capture_output=True, text=True, env=env, timeout=5400)
            line = (proc.stdout.strip().splitlines() or [""])[-1]
            try:
                rec["result"] = json.loads(line)
            except ValueError:
                rec["error"] = (proc.stderr[-500:] or "no output")
        except subprocess.TimeoutExpired:
            # one hung rung must not discard the completed rungs' results
            rec["error"] = "rung timed out after 5400s"
        out.append(rec)
        print(json.dumps(rec), file=sys.stderr)
        # write incrementally, MERGING over any previous sweep file: a
        # session runs one rung per invocation, and each must extend the
        # artifact, not clobber the earlier rungs' records
        path = os.path.join(ROOT, "docs", "BENCH_SWEEP.json")
        merged = []
        try:
            with open(path) as f:
                merged = [r for r in json.load(f)
                          if r.get("rung") not in {o["rung"] for o in out}]
        except (OSError, ValueError):
            pass
        with open(path, "w") as f:
            json.dump(merged + out, f, indent=1)
    for rec in out:
        r = rec.get("result", {})
        ovl = (f" ovl={r.get('overlapped_fraction')}"
               if r.get("overlapped_fraction") is not None else "")
        print(f"{rec['rung']:>14}: "
              + (f"{r.get('value')} {r.get('unit')} mfu={r.get('mfu')} "
                 f"backend={r.get('backend')}{ovl}" if r else
                 f"ERROR {rec.get('error', '')[:120]}"))
    return 0


if __name__ == "__main__":
    sys.exit(main())
