#!/usr/bin/env python
"""HLO cost-contract checker (docs/STATIC_ANALYSIS.md).

Lowers the representative tiny programs (train step at ZeRO stages
0/1/3 with offload/ZeRO++ variants; engine_v2 prefill/decode/
paged_verify) on CPU and diffs their contracts — collective counts,
FLOPs, bytes accessed, donation, shape signature, replay recompiles —
against the goldens under ``tests/contracts/``.

    python tools/check_contracts.py                  # check all programs
    python tools/check_contracts.py --programs decode,prefill
    python tools/check_contracts.py --update-goldens # regenerate goldens

Exit is non-zero on any contract violation, with a named delta per
failure ("train_step_zero3: grew all-gather 24 -> 26 ...").  Runs
standalone (pins the tier-1 CPU harness: JAX_PLATFORMS=cpu + 8 virtual
devices) and inside tier-1 via tests/unit/test_static_analysis.py.
"""

from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def ensure_cpu_harness() -> None:
    """Pin the tier-1 lowering environment BEFORE jax is imported: CPU
    platform, 8 virtual devices (same as tests/conftest.py).  No-op when
    a jax is already configured (e.g. under pytest)."""
    if "jax" in sys.modules:
        return
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()


def run_check(root: str = REPO, programs=None, update: bool = False):
    """Returns ``(errors, n_programs)``; writes goldens when ``update``.

    Import of the contracts module (and so jax) happens here, after
    :func:`ensure_cpu_harness` had its chance to pin the platform.
    """
    if root not in sys.path:
        sys.path.insert(0, root)
    from deepspeed_tpu.analysis import contracts

    extracted = contracts.extract_all(programs)
    if update:
        written = contracts.write_goldens(root, extracted)
        for path in written:
            print(f"check_contracts: wrote {os.path.relpath(path, root)}")
        return [], len(extracted)
    goldens = contracts.load_goldens(root)
    if programs:
        goldens = {k: v for k, v in goldens.items() if k in set(programs)}
    errors = contracts.diff_all(goldens, extracted)
    return errors, len(extracted)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--update-goldens", action="store_true",
                    help="regenerate tests/contracts/*.json from the "
                         "current tree")
    ap.add_argument("--programs", default="",
                    help="comma-separated subset of programs to check")
    ap.add_argument("--root", default=REPO)
    args = ap.parse_args(argv)

    ensure_cpu_harness()
    programs = [p for p in args.programs.split(",") if p] or None
    errors, n = run_check(args.root, programs, update=args.update_goldens)
    if args.update_goldens:
        print(f"check_contracts: regenerated {n} golden contract(s)")
        return 0
    if errors:
        print(f"check_contracts: {len(errors)} contract violation(s) "
              f"over {n} program(s)")
        for e in errors:
            print(f"  ERROR: {e}")
        return 1
    print(f"check_contracts: OK ({n} program contracts hold)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
