"""Shared-prefix serving bench: automatic prefix caching A/B.

Realistic serving traffic shares prompt prefixes (system prompts,
few-shot templates) across thousands of requests.  This bench measures
what the prefix cache buys on exactly that shape: N requests sharing one
P-token prefix with unique suffixes, run through InferenceEngineV2 twice
— ``enable_prefix_cache=false`` then ``true`` — on the same weights, and
checked token-for-token identical.

Prints ONE JSON line: end-to-end tokens/s for both runs, prefill tokens
admitted vs. computed (the FLOP story), cache hit/miss/eviction
counters, and the computed-prefill reduction factor.  Knobs (env):
    DSTPU_SBENCH_SIZE    model size (default 160m on TPU, tiny on CPU)
    DSTPU_SBENCH_PREFIX  shared prefix tokens    (default 256)
    DSTPU_SBENCH_SUFFIX  unique suffix tokens    (default 16)
    DSTPU_SBENCH_GEN     new tokens per request  (default 64 TPU / 8 CPU)
    DSTPU_SBENCH_NREQ    total requests          (default 32)
    DSTPU_SBENCH_SLOTS   concurrent decode slots (default 8)
    DSTPU_SBENCH_CHUNK   chunked-prefill tokens  (default 0 = whole)
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from bench import _backend_usable, _int_env as _int, _pin_cpu


def main() -> None:
    import jax

    from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                      RaggedInferenceConfig,
                                                      RaggedRequest)
    from deepspeed_tpu.models.llama import llama_model

    on_tpu = jax.default_backend() != "cpu"
    size = os.environ.get("DSTPU_SBENCH_SIZE", "160m" if on_tpu else "tiny")
    n_prefix = _int("DSTPU_SBENCH_PREFIX", 256)
    n_suffix = _int("DSTPU_SBENCH_SUFFIX", 16)
    gen = _int("DSTPU_SBENCH_GEN", 64 if on_tpu else 8)
    nreq = _int("DSTPU_SBENCH_NREQ", 32)
    slots = _int("DSTPU_SBENCH_SLOTS", 8)
    chunk = _int("DSTPU_SBENCH_CHUNK", 0)

    page = 16
    seq_len = n_prefix + n_suffix + gen
    pages_per_seq = -(-seq_len // page) + 1
    model = llama_model(size, max_seq_len=seq_len + page)
    params = model.init_params(jax.random.PRNGKey(0))

    rng = np.random.RandomState(0)
    vocab = model.config.vocab_size
    prefix = rng.randint(1, vocab, n_prefix).tolist()
    requests = [prefix + rng.randint(1, vocab, n_suffix).tolist()
                for _ in range(nreq)]
    # warmup workload: DIFFERENT shared prefix, same shapes — compiles the
    # whole-prompt, suffix-chunk, and decode programs without seeding the
    # measured cache state with the real prefix
    warm_prefix = rng.randint(1, vocab, n_prefix).tolist()
    warm = [warm_prefix + rng.randint(1, vocab, n_suffix).tolist()
            for _ in range(2)]

    def run(cache: bool):
        eng = InferenceEngineV2(model, RaggedInferenceConfig(
            page_size=page, max_pages_per_seq=pages_per_seq,
            num_pages=pages_per_seq * slots + 2 * pages_per_seq,
            max_seqs=slots, prefill_chunk=chunk,
            enable_prefix_cache=cache), params=params)
        # sequentially, so the second warm request HITS the warm prefix
        # and compiles the suffix-only prefill program — batching them
        # would admit both before either registered its pages
        for p in warm:
            eng.generate_all([RaggedRequest(prompt_ids=p, max_new_tokens=2)])
        eng.reset_cache_stats()
        t0 = time.perf_counter()
        got = eng.generate_all([RaggedRequest(prompt_ids=p,
                                              max_new_tokens=gen)
                                for p in requests])
        dt = time.perf_counter() - t0
        toks = [got[u] for u in sorted(got)]
        assert sum(len(t) for t in toks) == nreq * gen
        return toks, dt, eng.cache_stats()

    toks_off, dt_off, st_off = run(False)
    toks_on, dt_on, st_on = run(True)
    identical = toks_off == toks_on
    mismatched = sum(1 for a, b in zip(toks_off, toks_on) if a != b)

    out_tokens = nreq * gen
    reduction = (st_off["prefill_computed_tokens"]
                 / max(st_on["prefill_computed_tokens"], 1))
    dev = jax.devices()[0]
    from deepspeed_tpu.accelerator import get_accelerator

    # peak HBM alongside tokens/s: process-aggregate accelerator stats
    # (on CPU fallback this is host RSS — still the capacity signal)
    mem_stats = get_accelerator().aggregate_memory_stats()
    result = {
        "metric": f"llama-{size} shared-prefix serving tok/s with prefix "
                  f"cache (prefix={n_prefix}, suffix={n_suffix}, gen={gen}, "
                  f"nreq={nreq}, slots={slots}, chunk={chunk})",
        "value": round(out_tokens / dt_on, 1),
        "unit": "tokens/s",
        "tokens_per_s": {"cache_off": round(out_tokens / dt_off, 1),
                         "cache_on": round(out_tokens / dt_on, 1)},
        "speedup": round(dt_off / dt_on, 2),
        "prefill_tokens": {
            "admitted": int(st_on["prefill_admitted_tokens"]),
            "computed_cache_off": int(st_off["prefill_computed_tokens"]),
            "computed_cache_on": int(st_on["prefill_computed_tokens"])},
        "prefill_reduction": round(reduction, 2),
        "prefix_hit_rate": round(st_on["prefix_hit_rate"], 3),
        "cache": {"hits": int(st_on["cache_hits"]),
                  "misses": int(st_on["cache_misses"]),
                  "evictions": int(st_on["cache_evictions"])},
        "identical_generations": identical,
        "mismatched_requests": mismatched,
        "peak_hbm_bytes": int(mem_stats.get("peak_bytes_in_use", 0)),
        "hbm_bytes_in_use": int(mem_stats.get("bytes_in_use", 0)),
        "backend": jax.default_backend(),
        "device_kind": str(getattr(dev, "device_kind", "unknown")),
    }
    reason = os.environ.get("DSTPU_BENCH_FALLBACK_REASON", "")
    if reason and jax.default_backend() == "cpu":
        result["fallback_reason"] = reason
    print(json.dumps(result))
    # hard identity gate on CPU only: XLA-CPU is deterministic across the
    # two paths, while kernel backends may flip a near-tie greedy pick at
    # ULP level (docs/SERVING.md) — there the mismatch COUNT is the signal
    if not identical and jax.default_backend() == "cpu":
        sys.exit(1)


if __name__ == "__main__":
    # same wedged-chip discipline as bench.py: probe the backend in a
    # subprocess (a hung TPU lease hangs backend init uninterruptibly
    # in-process) and fall back to a self-describing CPU run
    if "--cpu" in sys.argv:
        _pin_cpu()
    else:
        usable, reason, _backend = _backend_usable()
        if not usable:
            os.environ["DSTPU_BENCH_FALLBACK_REASON"] = reason
            _pin_cpu()
        elif _backend == "cpu":
            _pin_cpu()
    main()
