"""Shared-prefix serving bench: prefix-cache and speculative A/B.

Realistic serving traffic shares prompt prefixes (system prompts,
few-shot templates) across thousands of requests.  This bench measures
what the serving optimizations buy on exactly that shape, always as an
A/B on the same weights checked token-for-token identical:

* default — automatic prefix caching: ``enable_prefix_cache`` off vs on;
  prefill tokens admitted vs computed is the FLOP story.
* ``--ab-speculative`` — speculative decoding (n-gram self-speculation):
  ``speculative.mode`` off vs on; **decode tokens per model invocation**
  is the figure of merit, with end-to-end tokens/s as the wall-clock
  check.  This is the *deterministic CPU tier*: pinned seeds, fixed
  model/seq/batch, generations asserted identical across repeats, wall
  time as median-of-k — the emitted JSON carries ``comparable: true``
  plus machine-readable ``decode_model_invocations`` /
  ``accepted_tokens_per_step`` so the speculative claim is
  machine-checked, not eyeballed.
* ``--ab-multistep`` — fused multi-step decode (``decode_horizon``,
  docs/SERVING.md "Multi-step decode"): ``decode_horizon`` 1 vs K on
  identical greedy traffic; **decode host syncs per token** is the
  figure of merit (the fused scan pays ONE ``[B, K]`` pull per horizon
  where the K=1 loop pays one ``[B]`` pull per token).  Deterministic
  CPU tier: the run hard-gates ``identical_generations`` (the fused
  scan is bit-identical to K single steps by contract), a >= 3x
  host-sync reduction per token at the default K=8, and ZERO
  steady-state recompiles in the measured region.
* ``--ab-kv-tier`` — tiered KV cache (host-RAM spill & restore,
  serving/kv_tier.py): several prefix FAMILIES cycle through a device
  prefix cache capped BELOW the distinct-prefix working set, host tier
  off vs on; **prefill tokens computed** at the fixed device pool size
  is the figure of merit (the tier must recover the prefix savings the
  cap destroyed).  Same deterministic CPU tier contract as
  ``--ab-speculative``; the run additionally asserts bit-identical
  generations between the legs, >= 1.5x prefill-token reduction, and
  ZERO steady-state recompiles (the sentinel counter) in the measured
  region.

Prints ONE JSON line.  Knobs (env):
    DSTPU_SBENCH_SIZE    model size (default 160m on TPU, tiny on CPU)
    DSTPU_SBENCH_PREFIX  shared prefix tokens    (default 256; spec: 32)
    DSTPU_SBENCH_SUFFIX  unique suffix tokens    (default 16; spec: 8)
    DSTPU_SBENCH_GEN     new tokens per request  (default 64 TPU / 8 CPU;
                         spec: 96)
    DSTPU_SBENCH_NREQ    total requests          (default 32; spec: 8)
    DSTPU_SBENCH_SLOTS   concurrent decode slots (default 8)
    DSTPU_SBENCH_CHUNK   chunked-prefill tokens  (default 0 = whole)
    DSTPU_SBENCH_K       speculative draft tokens per step (default 8)
    DSTPU_SBENCH_REPEATS median-of-k wall-time repeats     (default 3)
    DSTPU_SBENCH_NVME    1 = --ab-kv-tier caps the host tier and adds
                         the file-backed NVMe third tier under it
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from bench import _backend_usable, _int_env as _int, _pin_cpu

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _stamp_contract_hash(result: dict) -> dict:
    """Provenance: tie the bench artifact to the exact program contracts
    (tests/contracts/*.json) it ran under — see docs/STATIC_ANALYSIS.md."""
    from deepspeed_tpu.analysis.contracts import contract_set_hash

    result["contract_set_hash"] = contract_set_hash(_REPO)
    return result


def _capture_serving_timeline(eng, prompt, max_new_tokens: int = 2):
    """Force a step-time attribution capture on ONE short generate
    (OUTSIDE any timed window) and return the record, or None.  Only the
    first engine step of the generate is profiled (force_next arms a
    single capture)."""
    try:
        from deepspeed_tpu.inference.v2.engine_v2 import RaggedRequest

        eng.force_timeline_capture()
        eng.generate_all([RaggedRequest(prompt_ids=list(prompt),
                                        max_new_tokens=max_new_tokens)])
        return eng.timeline_record()
    except Exception:
        return None  # attribution must never sink a bench


def _observability_sections(timeline_rec, goodput_ledger,
                            warmup_s: float, measured_s: float,
                            measured_steps: int) -> dict:
    """``timeline`` + ``goodput`` sections for the bench JSON
    (docs/OBSERVABILITY.md "Step-time attribution & goodput").  The
    timeline record stamps ``measured: false`` honestly on CPU; the
    goodput ledger (created at leg start so its lifetime covers the
    phases) books warmup/compile as badput and the timed window as
    productive steps."""
    sections = {}
    if timeline_rec is not None:
        sections["timeline"] = {
            "measured": timeline_rec["measured"],
            "wall_seconds": round(timeline_rec["wall_seconds"], 6),
            "categories": {k: round(v, 6)
                           for k, v in timeline_rec["categories"].items()},
            "exposed_collective_seconds":
                timeline_rec["exposed_collective_seconds"],
            "overlapped_collective_seconds":
                timeline_rec["overlapped_collective_seconds"],
        }
    if goodput_ledger is not None:
        try:
            goodput_ledger.observe_phase("compile", max(0.0, warmup_s))
            n = max(1, int(measured_steps))
            for _ in range(n):
                goodput_ledger.observe_step(measured_s / n)
            sections["goodput"] = goodput_ledger.summary()
        # dstpu-lint: allow[swallow] observability sections are a bench
        # annex; a broken ledger must not sink the benchmark numbers
        except Exception:
            pass
    return sections


def _reqtrace_annex(model, params, page: int) -> dict:
    """``reqtrace`` section for the bench JSON: a short fleet-routed
    wave on a FRESH request-trace ledger (docs/OBSERVABILITY.md
    "Request tracing") — writes the merged multi-replica trace artifact
    (``DSTPU_SBENCH_TRACE_OUT``, default ./bench_serving_trace.json)
    and reports per-phase ledger medians.  Runs OUTSIDE every timed
    window, on the bench's own model and weights."""
    try:
        import statistics

        from deepspeed_tpu.inference.v2 import (RaggedInferenceConfig,
                                                RaggedRequest)
        from deepspeed_tpu.serving import ServingConfig, build_fleet
        from deepspeed_tpu.telemetry.reqtrace import (ReqTraceLedger,
                                                      set_reqtrace_ledger,
                                                      write_merged_trace)

        led = ReqTraceLedger()
        set_reqtrace_ledger(led)
        fleet = build_fleet(
            model, ServingConfig(enabled=True, prefill_replicas=1,
                                 decode_replicas=1, disaggregated=True,
                                 prefill_chunk=page),
            engine_config=RaggedInferenceConfig(
                page_size=page, num_pages=64, max_seqs=4,
                max_pages_per_seq=12, enable_prefix_cache=True),
            params=params)
        rng = np.random.RandomState(2)
        vocab = model.config.vocab_size
        prefix = rng.randint(1, vocab, 2 * page).tolist()
        uids = [fleet.submit(RaggedRequest(
            prompt_ids=prefix + rng.randint(1, vocab, 3 + i).tolist(),
            max_new_tokens=4)) for i in range(3)]
        for _ in range(400):
            if not fleet.has_work():
                break
            fleet.step()
        out_path = os.path.abspath(os.environ.get(
            "DSTPU_SBENCH_TRACE_OUT", "bench_serving_trace.json"))
        write_merged_trace(out_path, ledger=led)
        per_phase = {}
        for u in uids:
            tr = led.lookup(fleet.request_state(u)["trace_id"])
            if tr is None:
                continue
            for p, s in tr.phase_seconds().items():
                per_phase.setdefault(p, []).append(s)
        medians = {p: round(statistics.median(v), 6)
                   for p, v in sorted(per_phase.items())}
        return {"reqtrace": {"merged_trace_path": out_path,
                             "phase_medians_s": medians}}
    except Exception:
        return {}  # tracing must never sink the benchmark numbers


def _new_goodput_ledger():
    """Fresh private-registry ledger, or None when telemetry is broken."""
    try:
        from deepspeed_tpu.telemetry.goodput import GoodputLedger
        from deepspeed_tpu.telemetry.registry import MetricsRegistry

        return GoodputLedger(registry=MetricsRegistry())
    except Exception:
        return None


def main() -> None:
    import jax

    from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                      RaggedInferenceConfig,
                                                      RaggedRequest)
    from deepspeed_tpu.models.llama import llama_model

    on_tpu = jax.default_backend() != "cpu"
    size = os.environ.get("DSTPU_SBENCH_SIZE", "160m" if on_tpu else "tiny")
    n_prefix = _int("DSTPU_SBENCH_PREFIX", 256)
    n_suffix = _int("DSTPU_SBENCH_SUFFIX", 16)
    gen = _int("DSTPU_SBENCH_GEN", 64 if on_tpu else 8)
    nreq = _int("DSTPU_SBENCH_NREQ", 32)
    slots = _int("DSTPU_SBENCH_SLOTS", 8)
    chunk = _int("DSTPU_SBENCH_CHUNK", 0)

    page = 16
    seq_len = n_prefix + n_suffix + gen
    pages_per_seq = -(-seq_len // page) + 1
    model = llama_model(size, max_seq_len=seq_len + page)
    params = model.init_params(jax.random.PRNGKey(0))

    rng = np.random.RandomState(0)
    vocab = model.config.vocab_size
    prefix = rng.randint(1, vocab, n_prefix).tolist()
    requests = [prefix + rng.randint(1, vocab, n_suffix).tolist()
                for _ in range(nreq)]
    # warmup workload: DIFFERENT shared prefix, same shapes — compiles the
    # whole-prompt, suffix-chunk, and decode programs without seeding the
    # measured cache state with the real prefix
    warm_prefix = rng.randint(1, vocab, n_prefix).tolist()
    warm = [warm_prefix + rng.randint(1, vocab, n_suffix).tolist()
            for _ in range(2)]

    def run(cache: bool):
        eng = InferenceEngineV2(model, RaggedInferenceConfig(
            page_size=page, max_pages_per_seq=pages_per_seq,
            num_pages=pages_per_seq * slots + 2 * pages_per_seq,
            max_seqs=slots, prefill_chunk=chunk,
            enable_prefix_cache=cache), params=params)
        # sequentially, so the second warm request HITS the warm prefix
        # and compiles the suffix-only prefill program — batching them
        # would admit both before either registered its pages
        tw0 = time.perf_counter()
        for p in warm:
            eng.generate_all([RaggedRequest(prompt_ids=p, max_new_tokens=2)])
        warm_dt = time.perf_counter() - tw0
        eng.reset_cache_stats()
        t0 = time.perf_counter()
        got = eng.generate_all([RaggedRequest(prompt_ids=p,
                                              max_new_tokens=gen)
                                for p in requests])
        dt = time.perf_counter() - t0
        toks = [got[u] for u in sorted(got)]
        assert sum(len(t) for t in toks) == nreq * gen
        st = eng.cache_stats()  # read BEFORE the capture generate below
        tl = _capture_serving_timeline(eng, warm[0]) if cache else None
        return toks, dt, st, warm_dt, tl

    gp = _new_goodput_ledger()  # lifetime covers both legs below
    toks_off, dt_off, st_off, warm_off, _ = run(False)
    toks_on, dt_on, st_on, warm_on, tl_rec = run(True)
    identical = toks_off == toks_on
    mismatched = sum(1 for a, b in zip(toks_off, toks_on) if a != b)

    out_tokens = nreq * gen
    reduction = (st_off["prefill_computed_tokens"]
                 / max(st_on["prefill_computed_tokens"], 1))
    dev = jax.devices()[0]
    from deepspeed_tpu.accelerator import get_accelerator

    # peak HBM alongside tokens/s: process-aggregate accelerator stats
    # (on CPU fallback this is host RSS — still the capacity signal)
    mem_stats = get_accelerator().aggregate_memory_stats()
    result = {
        "metric": f"llama-{size} shared-prefix serving tok/s with prefix "
                  f"cache (prefix={n_prefix}, suffix={n_suffix}, gen={gen}, "
                  f"nreq={nreq}, slots={slots}, chunk={chunk})",
        "value": round(out_tokens / dt_on, 1),
        "unit": "tokens/s",
        "tokens_per_s": {"cache_off": round(out_tokens / dt_off, 1),
                         "cache_on": round(out_tokens / dt_on, 1)},
        "speedup": round(dt_off / dt_on, 2),
        "prefill_tokens": {
            "admitted": int(st_on["prefill_admitted_tokens"]),
            "computed_cache_off": int(st_off["prefill_computed_tokens"]),
            "computed_cache_on": int(st_on["prefill_computed_tokens"])},
        "prefill_reduction": round(reduction, 2),
        "prefix_hit_rate": round(st_on["prefix_hit_rate"], 3),
        "cache": {"hits": int(st_on["cache_hits"]),
                  "misses": int(st_on["cache_misses"]),
                  "evictions": int(st_on["cache_evictions"])},
        "identical_generations": identical,
        "mismatched_requests": mismatched,
        "peak_hbm_bytes": int(mem_stats.get("peak_bytes_in_use", 0)),
        "hbm_bytes_in_use": int(mem_stats.get("bytes_in_use", 0)),
        "backend": jax.default_backend(),
        "device_kind": str(getattr(dev, "device_kind", "unknown")),
    }
    result.update(_observability_sections(
        tl_rec, gp, warm_off + warm_on, dt_off + dt_on, measured_steps=2))
    result.update(_reqtrace_annex(model, params, page))
    reason = os.environ.get("DSTPU_BENCH_FALLBACK_REASON", "")
    if reason and jax.default_backend() == "cpu":
        result["fallback_reason"] = reason
    print(json.dumps(_stamp_contract_hash(result)))
    # hard identity gate on CPU only: XLA-CPU is deterministic across the
    # two paths, while kernel backends may flip a near-tie greedy pick at
    # ULP level (docs/SERVING.md) — there the mismatch COUNT is the signal
    if not identical and jax.default_backend() == "cpu":
        sys.exit(1)


def main_speculative() -> None:
    """Speculative-decoding A/B on the shared-prefix workload
    (deterministic CPU tier — see module docstring)."""
    import statistics

    import jax

    from deepspeed_tpu.inference.v2 import (InferenceEngineV2,
                                            RaggedInferenceConfig,
                                            RaggedRequest, SpeculativeConfig)
    from deepspeed_tpu.models.llama import llama_model

    on_tpu = jax.default_backend() != "cpu"
    size = os.environ.get("DSTPU_SBENCH_SIZE", "160m" if on_tpu else "tiny")
    n_prefix = _int("DSTPU_SBENCH_PREFIX", 32)
    n_suffix = _int("DSTPU_SBENCH_SUFFIX", 8)
    gen = _int("DSTPU_SBENCH_GEN", 96)
    nreq = _int("DSTPU_SBENCH_NREQ", 8)
    slots = _int("DSTPU_SBENCH_SLOTS", 8)
    k = _int("DSTPU_SBENCH_K", 8)
    repeats = max(1, _int("DSTPU_SBENCH_REPEATS", 3))

    page = 16
    seq_len = n_prefix + n_suffix + gen
    pages_per_seq = -(-seq_len // page) + 1
    model = llama_model(size, max_seq_len=seq_len + page)
    params = model.init_params(jax.random.PRNGKey(0))  # pinned seed

    rng = np.random.RandomState(0)  # pinned workload seed
    vocab = model.config.vocab_size
    prefix = rng.randint(1, vocab, n_prefix).tolist()
    requests = [prefix + rng.randint(1, vocab, n_suffix).tolist()
                for _ in range(nreq)]
    warm_prefix = rng.randint(1, vocab, n_prefix).tolist()
    warm = [warm_prefix + rng.randint(1, vocab, n_suffix).tolist()
            for _ in range(2)]

    class _EchoProposer:
        def propose(self, tokens, k_):
            return [int(tokens[-1])] * k_

    def run(spec: bool):
        """One leg: fresh engine per repeat (no cache/jit state leaks
        between repeats), warmup excluded from timing, token streams
        asserted identical ACROSS repeats (the determinism proof), wall
        time reported as the median."""
        toks_ref, stats, times = None, None, []
        warm_s, tl = 0.0, None
        for _ in range(repeats):
            eng = InferenceEngineV2(model, RaggedInferenceConfig(
                dtype="fp32" if not on_tpu else "bf16",
                page_size=page, max_pages_per_seq=pages_per_seq,
                num_pages=pages_per_seq * slots + 2 * pages_per_seq,
                max_seqs=slots, enable_prefix_cache=True,
                speculative=SpeculativeConfig(
                    mode="ngram" if spec else "off", k=k)), params=params)
            tw0 = time.perf_counter()
            for p in warm:
                eng.generate_all([RaggedRequest(prompt_ids=p,
                                                max_new_tokens=4)])
            if spec:
                # a speculative engine runs TWO decode-phase programs —
                # verify on drafting rounds, plain decode on all-empty
                # rounds — and the 4-token warmup requests draft (or
                # don't) at the whim of the tiny model, so force one
                # request through EACH program (lossless for any
                # proposer) to keep both compiles out of the timed region
                prop = eng._proposer
                eng._proposer = None  # plain decode
                eng.generate_all([RaggedRequest(prompt_ids=warm[0],
                                                max_new_tokens=4)])
                eng._proposer = _EchoProposer()  # always-drafting: verify
                eng.generate_all([RaggedRequest(prompt_ids=warm[1],
                                                max_new_tokens=4)])
                eng._proposer = prop
            warm_s += time.perf_counter() - tw0
            eng.reset_cache_stats()
            t0 = time.perf_counter()
            got = eng.generate_all([RaggedRequest(prompt_ids=p,
                                                  max_new_tokens=gen)
                                    for p in requests])
            times.append(time.perf_counter() - t0)
            toks = [got[u] for u in sorted(got)]
            assert sum(len(t) for t in toks) == nreq * gen
            if toks_ref is None:
                toks_ref, stats = toks, eng.decode_stats()
                # stats are read: the capture generate below can no
                # longer pollute the leg's invocation counts
                tl = _capture_serving_timeline(eng, warm[0])
            else:
                assert toks == toks_ref, \
                    "non-deterministic generations across repeats"
            eng.assert_no_leaks()
        return toks_ref, statistics.median(times), stats, warm_s, tl

    gp = _new_goodput_ledger()  # lifetime covers both legs below
    toks_off, dt_off, st_off, warm_off, _ = run(False)
    toks_on, dt_on, st_on, warm_on, tl_rec = run(True)
    identical = toks_off == toks_on
    mismatched = sum(1 for a, b in zip(toks_off, toks_on) if a != b)

    out_tokens = nreq * gen
    inv_off = int(st_off["decode_model_invocations"])
    inv_on = int(st_on["decode_model_invocations"])
    tpi_off = st_off["decode_tokens_per_invocation"]
    tpi_on = st_on["decode_tokens_per_invocation"]
    dev = jax.devices()[0]
    result = {
        "metric": f"llama-{size} shared-prefix speculative decoding A/B "
                  f"(prefix={n_prefix}, suffix={n_suffix}, gen={gen}, "
                  f"nreq={nreq}, slots={slots}, k={k}, "
                  f"median_of={repeats})",
        "value": round(tpi_on / max(tpi_off, 1e-9), 2),
        "unit": "x decode tokens per model invocation",
        # deterministic CPU tier contract: pinned seeds, fixed
        # model/seq/batch, per-leg determinism asserted above,
        # median-of-k wall times — the numbers below are comparable
        # run-to-run on the same backend
        "comparable": True,
        "tier": ("tpu" if on_tpu else "cpu-deterministic"),
        "tokens_per_s": {"spec_off": round(out_tokens / dt_off, 1),
                         "spec_on": round(out_tokens / dt_on, 1)},
        "speedup": round(dt_off / dt_on, 2),
        "decode_model_invocations": {"spec_off": inv_off,
                                     "spec_on": inv_on},
        "decode_tokens_per_invocation": {"spec_off": round(tpi_off, 2),
                                         "spec_on": round(tpi_on, 2)},
        "invocation_reduction": round(inv_off / max(inv_on, 1), 2),
        # decode tokens the spec engine banked per verify/decode call,
        # normalized per sequence: the accepted-draft + bonus average
        "accepted_tokens_per_step": round(
            st_on["decode_tokens"] / max(inv_on, 1) / min(slots, nreq), 2),
        "spec": {
            "proposed_tokens": int(st_on["spec_proposed_tokens"]),
            "accepted_tokens": int(st_on["spec_accepted_tokens"]),
            "acceptance_rate": round(st_on["spec_acceptance_rate"], 3),
            "verify_calls": int(st_on["spec_verify_calls"]),
            "rollback_pages": int(st_on["spec_rollback_pages"])},
        "identical_generations": identical,
        "mismatched_requests": mismatched,
        "backend": jax.default_backend(),
        "device_kind": str(getattr(dev, "device_kind", "unknown")),
    }
    result.update(_observability_sections(
        tl_rec, gp, warm_off + warm_on,
        (dt_off + dt_on) * repeats, measured_steps=2 * repeats))
    reason = os.environ.get("DSTPU_BENCH_FALLBACK_REASON", "")
    if reason and jax.default_backend() == "cpu":
        result["fallback_reason"] = reason
    print(json.dumps(_stamp_contract_hash(result)))
    # lossless contract: greedy speculative decoding must be
    # bit-identical to the baseline — hard gate on CPU (XLA-CPU is
    # deterministic; kernel backends may flip ULP-level near-ties)
    if not identical and jax.default_backend() == "cpu":
        sys.exit(1)


def main_multistep() -> None:
    """Fused multi-step decode A/B on the shared-prefix workload
    (deterministic CPU tier — see module docstring): ``decode_horizon``
    1 vs K, same weights, same greedy traffic, ``nreq == slots`` so
    every request is admitted up front and the decode phase dominates.
    """
    import statistics

    import jax

    from deepspeed_tpu.inference.v2 import (InferenceEngineV2,
                                            RaggedInferenceConfig,
                                            RaggedRequest)
    from deepspeed_tpu.models.llama import llama_model
    from deepspeed_tpu.telemetry import get_registry

    on_tpu = jax.default_backend() != "cpu"
    size = os.environ.get("DSTPU_SBENCH_SIZE", "160m" if on_tpu else "tiny")
    n_prefix = _int("DSTPU_SBENCH_PREFIX", 32)
    n_suffix = _int("DSTPU_SBENCH_SUFFIX", 8)
    gen = _int("DSTPU_SBENCH_GEN", 64)
    nreq = _int("DSTPU_SBENCH_NREQ", 8)
    slots = _int("DSTPU_SBENCH_SLOTS", 8)
    horizon = _int("DSTPU_SBENCH_HORIZON", 8)
    repeats = max(1, _int("DSTPU_SBENCH_REPEATS", 3))

    page = 16
    seq_len = n_prefix + n_suffix + gen
    pages_per_seq = -(-seq_len // page) + 1
    model = llama_model(size, max_seq_len=seq_len + page)
    params = model.init_params(jax.random.PRNGKey(0))  # pinned seed

    rng = np.random.RandomState(0)  # pinned workload seed
    vocab = model.config.vocab_size
    prefix = rng.randint(1, vocab, n_prefix).tolist()
    requests = [prefix + rng.randint(1, vocab, n_suffix).tolist()
                for _ in range(nreq)]
    warm_prefix = rng.randint(1, vocab, n_prefix).tolist()
    warm = [warm_prefix + rng.randint(1, vocab, n_suffix).tolist()
            for _ in range(2)]

    def steady_recompiles() -> float:
        m = get_registry().get("deepspeed_tpu_steady_recompiles_total")
        return m.total() if m is not None else 0.0

    def run(h: int):
        """One leg: fresh engine per repeat, warmup (full-length so the
        whole horizon halving chain compiles out of the timed region)
        excluded from timing, token streams asserted identical ACROSS
        repeats, wall time as the median."""
        toks_ref, stats, times = None, None, []
        steady_delta, warm_s, tl = 0.0, 0.0, None
        for _ in range(repeats):
            eng = InferenceEngineV2(model, RaggedInferenceConfig(
                dtype="fp32" if not on_tpu else "bf16",
                page_size=page, max_pages_per_seq=pages_per_seq,
                num_pages=pages_per_seq * slots + 2 * pages_per_seq,
                max_seqs=slots, enable_prefix_cache=True,
                decode_horizon=h), params=params)
            # warm sequentially at the FULL generation length: the
            # fused leg's shrink chain (K, K/2, ..., 1) compiles on the
            # tail of the warm streams, not in the measured region
            tw0 = time.perf_counter()
            for p in warm:
                eng.generate_all([RaggedRequest(prompt_ids=p,
                                                max_new_tokens=gen)])
            warm_s += time.perf_counter() - tw0
            eng.reset_cache_stats()
            s0 = steady_recompiles()
            t0 = time.perf_counter()
            got = eng.generate_all([RaggedRequest(prompt_ids=p,
                                                  max_new_tokens=gen)
                                    for p in requests])
            times.append(time.perf_counter() - t0)
            steady_delta = max(steady_delta,
                               steady_recompiles() - s0)
            toks = [got[u] for u in sorted(got)]
            assert sum(len(t) for t in toks) == nreq * gen
            if toks_ref is None:
                toks_ref, stats = toks, eng.decode_stats()
                # stats are read: the capture generate below can no
                # longer pollute the leg's sync counts
                tl = _capture_serving_timeline(eng, warm[0])
            else:
                assert toks == toks_ref, \
                    "non-deterministic generations across repeats"
            eng.assert_no_leaks()
            eng.close()
        return toks_ref, statistics.median(times), stats, steady_delta, \
            warm_s, tl

    gp = _new_goodput_ledger()  # lifetime covers both legs below
    toks_off, dt_off, st_off, steady_off, warm_off, _ = run(1)
    toks_on, dt_on, st_on, steady_on, warm_on, tl_rec = run(horizon)
    identical = toks_off == toks_on
    mismatched = sum(1 for a, b in zip(toks_off, toks_on) if a != b)

    out_tokens = nreq * gen
    syncs_off = int(st_off["decode_host_syncs"])
    syncs_on = int(st_on["decode_host_syncs"])
    # identical traffic on both legs: syncs-per-token reduction is the
    # plain sync-count ratio
    sync_reduction = syncs_off / max(syncs_on, 1)
    steady = max(steady_off, steady_on)
    dev = jax.devices()[0]
    result = {
        "metric": f"llama-{size} fused multi-step decode A/B "
                  f"(prefix={n_prefix}, suffix={n_suffix}, gen={gen}, "
                  f"nreq={nreq}, slots={slots}, horizon={horizon}, "
                  f"median_of={repeats})",
        "value": round(sync_reduction, 2),
        "unit": "x fewer decode host syncs per token",
        # deterministic CPU tier contract (see --ab-speculative)
        "comparable": True,
        "tier": ("tpu" if on_tpu else "cpu-deterministic"),
        "tokens_per_s": {"horizon_1": round(out_tokens / dt_off, 1),
                         f"horizon_{horizon}": round(out_tokens / dt_on, 1)},
        "speedup": round(dt_off / dt_on, 2),
        "decode_host_syncs": {"horizon_1": syncs_off,
                              f"horizon_{horizon}": syncs_on},
        "decode_tokens_per_host_sync": {
            "horizon_1": round(st_off["decode_tokens_per_host_sync"], 2),
            f"horizon_{horizon}": round(
                st_on["decode_tokens_per_host_sync"], 2)},
        "host_sync_reduction": round(sync_reduction, 2),
        "horizon_shrinks": int(st_on["decode_horizon_shrinks"]),
        "identical_generations": identical,
        "mismatched_requests": mismatched,
        "steady_state_recompiles": int(steady),
        "backend": jax.default_backend(),
        "device_kind": str(getattr(dev, "device_kind", "unknown")),
    }
    result.update(_observability_sections(
        tl_rec, gp, warm_off + warm_on,
        (dt_off + dt_on) * repeats, measured_steps=2 * repeats))
    reason = os.environ.get("DSTPU_BENCH_FALLBACK_REASON", "")
    if reason and jax.default_backend() == "cpu":
        result["fallback_reason"] = reason
    print(json.dumps(_stamp_contract_hash(result)))
    # hard gates on the deterministic CPU tier: bit-identity (the fused
    # scan's headline contract), the >= 3x host-sync bar at K=8, and
    # zero steady-state recompiles — machine-checked, not eyeballed
    if jax.default_backend() == "cpu" and (
            not identical or sync_reduction < 3.0 or steady > 0):
        sys.exit(1)


def main_kv_tier() -> None:
    """Tiered-KV-cache A/B on a multi-family shared-prefix workload
    (deterministic CPU tier — see module docstring).

    Workload shape: ``families`` distinct shared prefixes, visited
    round-robin in ``rounds`` waves of ``nreq`` unique-suffix requests
    each.  The device prefix cache is capped at ~1.5 families' pages,
    so by the time a family comes around again the LRU has evicted it —
    tier-off recomputes the whole prefix, tier-on restores it from host
    RAM and computes only the suffix."""
    import statistics

    import jax

    from deepspeed_tpu.inference.v2 import (InferenceEngineV2,
                                            RaggedInferenceConfig,
                                            RaggedRequest)
    from deepspeed_tpu.models.llama import llama_model
    from deepspeed_tpu.serving.config import KVTierConfig
    from deepspeed_tpu.telemetry import get_registry

    on_tpu = jax.default_backend() != "cpu"
    size = os.environ.get("DSTPU_SBENCH_SIZE", "160m" if on_tpu else "tiny")
    n_prefix = _int("DSTPU_SBENCH_PREFIX", 64)
    n_suffix = _int("DSTPU_SBENCH_SUFFIX", 16)
    gen = _int("DSTPU_SBENCH_GEN", 8)
    n_fam = _int("DSTPU_SBENCH_FAMILIES", 4)
    rounds = max(2, _int("DSTPU_SBENCH_ROUNDS", 3))
    per_fam = _int("DSTPU_SBENCH_NREQ", 2)  # requests per family per round
    slots = _int("DSTPU_SBENCH_SLOTS", 4)
    repeats = max(1, _int("DSTPU_SBENCH_REPEATS", 3))
    # DSTPU_SBENCH_NVME=1: cap the host tier itself (at the device cache
    # capacity, below the spilled working set) and hang the NVMe third
    # tier under it — the same A/B then also proves file demote/promote
    # keeps bit-identity at a bounded host-RAM budget
    nvme = os.environ.get("DSTPU_SBENCH_NVME", "") not in ("", "0")

    page = 16
    seq_len = n_prefix + n_suffix + gen
    pages_per_seq = -(-seq_len // page) + 1
    prefix_pages = n_prefix // page
    # the acceptance geometry: device cache capped BELOW the
    # distinct-prefix working set (n_fam x prefix_pages)
    cache_cap = prefix_pages + max(1, prefix_pages // 2)
    model = llama_model(size, max_seq_len=seq_len + page)
    params = model.init_params(jax.random.PRNGKey(0))  # pinned seed

    rng = np.random.RandomState(0)  # pinned workload seed
    vocab = model.config.vocab_size
    families = [rng.randint(1, vocab, n_prefix).tolist()
                for _ in range(n_fam)]
    suffixes = [[[rng.randint(1, vocab, n_suffix).tolist()
                  for _ in range(per_fam)] for _ in range(n_fam)]
                for _ in range(rounds)]
    # warm-pass suffixes: same LENGTH, different content — replaying
    # round 0 verbatim would take the fully-cached (copy-on-write
    # decode-entry) path and never compile the restore + suffix-only
    # prefill programs the measured rounds run
    warm_sufs = [[rng.randint(1, vocab, n_suffix).tolist()
                  for _ in range(per_fam)] for _ in range(n_fam)]

    def steady_recompiles() -> float:
        m = get_registry().get("deepspeed_tpu_steady_recompiles_total")
        return m.total() if m is not None else 0.0

    def _tier_cfg(tmp_dirs):
        if not nvme:
            return KVTierConfig(enabled=True)
        import tempfile
        mc = model.config
        # one spilled page record: per-layer K+V of
        # [page, n_kv_heads, head_dim] at the leg's dtype width
        page_rec = (mc.n_layers * 2 * page * mc.n_kv_heads
                    * (mc.hidden_size // mc.n_heads)
                    * (2 if on_tpu else 4))
        d = tempfile.mkdtemp(prefix="dstpu_sbench_nvme_")
        tmp_dirs.append(d)
        return KVTierConfig(enabled=True,
                            host_bytes=cache_cap * page_rec,
                            nvme_enabled=True, nvme_dir=d)

    def run(tier: bool):
        """One leg: fresh engine per repeat, warmup (cold fill + one
        warm-restore pass) excluded from timing, token streams asserted
        identical ACROSS repeats, wall time as the median."""
        toks_ref, stats, tstats, times = None, None, None, []
        steady_delta, warm_s, tl = 0.0, 0.0, None
        tmp_dirs = []  # fresh NVMe dir per repeat: no stale-record hits
        for _ in range(repeats):
            eng = InferenceEngineV2(model, RaggedInferenceConfig(
                dtype="fp32" if not on_tpu else "bf16",
                page_size=page, max_pages_per_seq=pages_per_seq,
                num_pages=pages_per_seq * slots + 2 * pages_per_seq,
                max_seqs=slots, enable_prefix_cache=True,
                prefix_cache_pages=cache_cap,
                kv_tier=(_tier_cfg(tmp_dirs) if tier else None)),
                params=params)

            def play(r, sufs=None):
                got_rounds = []
                for f in range(n_fam):
                    got = eng.generate_all(
                        [RaggedRequest(prompt_ids=families[f] + s,
                                       max_new_tokens=gen)
                         for s in (sufs or suffixes[r])[f]])
                    got_rounds.append([got[u] for u in sorted(got)])
                return got_rounds

            tw0 = time.perf_counter()
            all_toks = [play(0)]   # cold fill: compiles + populates host
            # warm pass: fresh suffixes on the now-evicted families
            # compile the restore scatter + suffix-only prefill shapes
            all_toks.append(play(0, sufs=warm_sufs))
            eng.flush_spills()
            warm_s += time.perf_counter() - tw0
            eng.reset_cache_stats()
            s0 = steady_recompiles()
            t0 = time.perf_counter()
            for r in range(1, rounds):
                all_toks.append(play(r))
            times.append(time.perf_counter() - t0)
            steady_delta = max(steady_delta, steady_recompiles() - s0)
            if toks_ref is None:
                toks_ref = all_toks
                stats, tstats = eng.cache_stats(), eng.tier_stats()
                # stats are read: the capture generate below can no
                # longer pollute the leg's prefill-token counts
                tl = _capture_serving_timeline(
                    eng, families[0] + warm_sufs[0][0])
            else:
                assert all_toks == toks_ref, \
                    "non-deterministic generations across repeats"
            eng.assert_no_leaks()
            eng.close()
        for d in tmp_dirs:
            shutil.rmtree(d, ignore_errors=True)
        return toks_ref, statistics.median(times), stats, tstats, \
            steady_delta, warm_s, tl

    gp = _new_goodput_ledger()  # lifetime covers both legs below
    toks_off, dt_off, st_off, _, steady_off, warm_off, _tl = run(False)
    toks_on, dt_on, st_on, ts_on, steady_on, warm_on, tl_rec = run(True)
    identical = toks_off == toks_on
    flat_off = [t for rnd in toks_off for fam in rnd for t in fam]
    flat_on = [t for rnd in toks_on for fam in rnd for t in fam]
    mismatched = sum(1 for a, b in zip(flat_off, flat_on) if a != b)

    out_tokens = (rounds - 1) * n_fam * per_fam * gen  # measured region
    reduction = (st_off["prefill_computed_tokens"]
                 / max(st_on["prefill_computed_tokens"], 1))
    steady = max(steady_off, steady_on)
    dev = jax.devices()[0]
    result = {
        "metric": f"llama-{size} tiered-KV-cache A/B, device cache capped "
                  f"below working set (families={n_fam}, prefix={n_prefix}, "
                  f"suffix={n_suffix}, gen={gen}, per_fam={per_fam}, "
                  f"rounds={rounds}, cache_cap={cache_cap} pages, "
                  f"working_set={n_fam * prefix_pages} pages, "
                  f"median_of={repeats})",
        "value": round(reduction, 2),
        "unit": "x prefill-token reduction at fixed device pool",
        # deterministic CPU tier contract (see --ab-speculative)
        "comparable": True,
        "tier": ("tpu" if on_tpu else "cpu-deterministic"),
        "tokens_per_s": {"tier_off": round(out_tokens / dt_off, 1),
                         "tier_on": round(out_tokens / dt_on, 1)},
        "speedup": round(dt_off / dt_on, 2),
        "prefill_tokens": {
            "admitted": int(st_on["prefill_admitted_tokens"]),
            "computed_tier_off": int(st_off["prefill_computed_tokens"]),
            "computed_tier_on": int(st_on["prefill_computed_tokens"])},
        "prefill_reduction": round(reduction, 2),
        "prefix_hit_rate": round(st_on["prefix_hit_rate"], 3),
        "kv_tier": {
            "spilled_pages": int(ts_on["spilled_pages"]),
            "restored_pages": int(ts_on["restored_pages"]),
            "host_pages": int(ts_on["host_pages"]),
            "host_bytes": int(ts_on["host_bytes"]),
            "hit_rate": round(ts_on["hit_rate"], 3),
            "corrupt_pages": int(ts_on["corrupt_pages"]),
            "dropped_spills": int(ts_on["dropped_spills"])},
        "nvme": nvme,
        "identical_generations": identical,
        "mismatched_requests": mismatched,
        "steady_state_recompiles": int(steady),
        "backend": jax.default_backend(),
        "device_kind": str(getattr(dev, "device_kind", "unknown")),
    }
    if nvme:
        result["kv_nvme"] = {
            k: (round(v, 3) if k == "nvme_hit_rate" else int(v))
            for k, v in ts_on.items() if k.startswith("nvme_")}
    result.update(_observability_sections(
        tl_rec, gp, warm_off + warm_on,
        (dt_off + dt_on) * repeats,
        measured_steps=2 * repeats * (rounds - 1)))
    reason = os.environ.get("DSTPU_BENCH_FALLBACK_REASON", "")
    if reason and jax.default_backend() == "cpu":
        result["fallback_reason"] = reason
    print(json.dumps(_stamp_contract_hash(result)))
    # hard gates on the deterministic CPU tier: bit-identity, the
    # >= 1.5x acceptance bar, and zero steady-state recompiles — the
    # tier's claims are machine-checked, not eyeballed.  The NVMe arm
    # additionally requires real file demote/promote traffic with zero
    # corrupt records
    nvme_ok = (not nvme) or (
        ts_on.get("nvme_spilled_pages", 0) > 0
        and ts_on.get("nvme_restored_pages", 0) > 0
        and ts_on.get("nvme_corrupt_pages", 0) == 0)
    if jax.default_backend() == "cpu" and (
            not identical or reduction < 1.5 or steady > 0
            or not nvme_ok):
        sys.exit(1)


if __name__ == "__main__":
    # same wedged-chip discipline as bench.py: probe the backend in a
    # subprocess (a hung TPU lease hangs backend init uninterruptibly
    # in-process) and fall back to a self-describing CPU run
    if "--cpu" in sys.argv:
        _pin_cpu()
    else:
        usable, reason, _backend = _backend_usable()
        if not usable:
            os.environ["DSTPU_BENCH_FALLBACK_REASON"] = reason
            _pin_cpu()
        elif _backend == "cpu":
            _pin_cpu()
    if "--ab-speculative" in sys.argv:
        main_speculative()
    elif "--ab-kv-tier" in sys.argv:
        main_kv_tier()
    elif "--ab-multistep" in sys.argv:
        main_multistep()
    else:
        main()
