"""Serving throughput bench: continuous-batching decode on the local chip.

Measures the InferenceEngineV2 ragged path end to end — paged KV, Pallas
paged-decode kernel, flash prefill, preemption — the way the reference's
inference-v2 (DeepSpeed-FastGen) benchmarks measure theirs: N concurrent
requests, fixed prompt/generation lengths, report decode tokens/sec and
per-token latency.

Prints ONE JSON line.  Knobs (env):
    DSTPU_IBENCH_SIZE   model size (default 160m on TPU, tiny on CPU)
    DSTPU_IBENCH_PROMPT prompt length   (default 512 TPU / 32 CPU)
    DSTPU_IBENCH_GEN    new tokens/req  (default 128 TPU / 16 CPU)
    DSTPU_IBENCH_NREQ   total requests  (default 32 TPU / 4 CPU)
    DSTPU_IBENCH_SLOTS  concurrent decode slots (default 8)
    DSTPU_IBENCH_KVQ    1 = int8 KV pages
    DSTPU_IBENCH_WQ     weight-only bits (4/8; 0 = off)
    DSTPU_IBENCH_CHUNK  chunked-prefill tokens per step (0 = whole prompt)
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from bench import _backend_usable, _int_env as _int, _pin_cpu


def main() -> None:
    import jax

    from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                      RaggedInferenceConfig,
                                                      RaggedRequest)
    from deepspeed_tpu.models.llama import llama_model

    on_tpu = jax.default_backend() != "cpu"
    size = os.environ.get("DSTPU_IBENCH_SIZE", "160m" if on_tpu else "tiny")
    prompt = _int("DSTPU_IBENCH_PROMPT", 512 if on_tpu else 32)
    gen = _int("DSTPU_IBENCH_GEN", 128 if on_tpu else 16)
    nreq = _int("DSTPU_IBENCH_NREQ", 32 if on_tpu else 4)
    slots = _int("DSTPU_IBENCH_SLOTS", 8)

    page = 16
    pages_per_seq = -(-(prompt + gen) // page) + 1
    cfg = RaggedInferenceConfig(
        page_size=page, max_pages_per_seq=pages_per_seq,
        num_pages=pages_per_seq * slots + slots,  # full pool + slack
        max_seqs=slots,
        kv_quant=os.environ.get("DSTPU_IBENCH_KVQ") == "1",
        quant_bits=_int("DSTPU_IBENCH_WQ", 0),
        prefill_chunk=_int("DSTPU_IBENCH_CHUNK", 0))
    model = llama_model(size, max_seq_len=prompt + gen + page)
    engine = InferenceEngineV2(model, cfg)

    rng = np.random.RandomState(0)
    vocab = model.config.vocab_size

    def requests(n):
        return [RaggedRequest(prompt_ids=rng.randint(1, vocab, prompt).tolist(),
                              max_new_tokens=gen) for _ in range(n)]

    # warmup: compile the prompt-length prefill bucket + the decode
    # program on a SHORT wave — full-length generations would double the
    # session for no extra compile coverage
    warm = requests(min(2, nreq))
    for r in warm:
        r.max_new_tokens = min(8, gen)
    engine.generate_all(warm)

    t0 = time.perf_counter()
    got = engine.generate_all(requests(nreq))
    dt = time.perf_counter() - t0
    out_tokens = sum(len(v) for v in got.values())
    assert out_tokens == nreq * gen, (out_tokens, nreq * gen)

    dev = jax.devices()[0]
    result = {
        "metric": f"llama-{size} serving decode tok/s "
                  f"(prompt={prompt}, gen={gen}, nreq={nreq}, slots={slots}, "
                  f"kvq={int(cfg.kv_quant)}, wq={cfg.quant_bits}, "
                  f"chunk={cfg.prefill_chunk})",
        "value": round(out_tokens / dt, 1),
        "unit": "tokens/s",
        "ms_per_token": round(1000.0 * dt * slots / out_tokens, 2),
        "backend": jax.default_backend(),
        "device_kind": str(getattr(dev, "device_kind", "unknown")),
    }
    reason = os.environ.get("DSTPU_BENCH_FALLBACK_REASON", "")
    if reason and jax.default_backend() == "cpu":
        result["fallback_reason"] = reason
    print(json.dumps(result))


if __name__ == "__main__":
    # same wedged-chip discipline as bench.py: probe the backend in a
    # subprocess (a hung TPU lease hangs backend init uninterruptibly
    # in-process) and fall back to a self-describing CPU run
    if "--cpu" in sys.argv:
        _pin_cpu()
    else:
        usable, reason, _backend = _backend_usable()
        if not usable:
            os.environ["DSTPU_BENCH_FALLBACK_REASON"] = reason
            _pin_cpu()
        elif _backend == "cpu":
            # the probe short-circuits on JAX_PLATFORMS=cpu, but a site
            # PJRT plugin may have pinned another platform via jax.config
            # (env var alone does not override) — pin for real or main()
            # hangs on the very backend the probe promised to avoid
            _pin_cpu()
    main()
