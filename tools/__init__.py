"""Repo tooling as a package so drivers run as ``python -m tools.<name>``
(e.g. ``python -m tools.dstpu_lint --all``) from the repo root."""
