#!/bin/bash
# Unattended TPU measurement session, priority-ordered so an early wedge
# still leaves the most important artifacts behind.  Run from repo root:
#     bash tools/chip_session.sh >> docs/CHIP_SESSION.log 2>&1 &
# Each stage appends to docs/CHIP_SESSION.log; bench_sweep also writes
# docs/BENCH_SWEEP.json incrementally.
set -u
cd "$(dirname "$0")/.."

stamp() { echo "=== [$(date -u +%H:%M:%S)] $*"; }

stamp "chip session start"

# 1. the headline artifact: flagship rung first, then the 1b shape
stamp "bench_sweep flagship"
timeout 2000 python tools/bench_sweep.py flagship
stamp "bench_sweep 1b"
timeout 2400 python tools/bench_sweep.py 1b
stamp "bench_sweep 1b-mu16"
timeout 2400 python tools/bench_sweep.py 1b-mu16
stamp "bench_sweep 1b-offload"
timeout 2400 python tools/bench_sweep.py 1b-offload

# 2. decomposition + bwd-tile sweep on the flagship shape
stamp "tune_mfu bwd tiles + fused adam"
timeout 3600 python tools/tune_mfu.py 160m-bs16 160m-bwd256x256 \
    160m-bwd256x512 160m-bwd512x256 160m-bwd1024x512 160m-fusedadam \
    160m-xla-attn
stamp "profile_step 160m bs16"
timeout 1200 python tools/profile_step.py --size 160m --seq 1024 --bs 16 \
    --outdir /tmp/dstpu_trace_160m --top 25
stamp "profile_step 160m bs16 zero3 (stage-3 gather/compute overlap trace)"
timeout 1200 python tools/profile_step.py --size 160m --seq 1024 --bs 16 \
    --stage 3 --outdir /tmp/dstpu_trace_160m_z3 --top 25

# 3. the stage/offload/MoE/long-seq/serving rungs
stamp "bench_sweep 160m-zero3"
timeout 2000 python tools/bench_sweep.py 160m-zero3
stamp "bench_sweep 160m-zero3-prefetch (manual prefetch A/B)"
timeout 2000 python tools/bench_sweep.py 160m-zero3-prefetch
stamp "bench_sweep 160m-offload"
timeout 2000 python tools/bench_sweep.py 160m-offload
stamp "bench_sweep moe-8x160m"
timeout 2400 python tools/bench_sweep.py moe-8x160m
stamp "bench_sweep 160m-seq8k"
timeout 2400 python tools/bench_sweep.py 160m-seq8k
stamp "bench_sweep serving-160m"
timeout 2400 python tools/bench_sweep.py serving-160m
stamp "bench_sweep serving-160m-int8"
timeout 2400 python tools/bench_sweep.py serving-160m-int8
stamp "bench_sweep serving-160m-chunked"
timeout 2400 python tools/bench_sweep.py serving-160m-chunked

# 4. remaining tune variants (bs ladder, loss chunking, stock-kernel ref)
stamp "tune_mfu remainder"
timeout 3600 python tools/tune_mfu.py base-160m-flash512 160m-bs32 \
    160m-losschunk341 160m-flash-jaxstock 1b-bs8-remat 1b-bs4

stamp "chip session done"
