"""Capture an XLA op-level profile of one train_batch and print top ops.

Usage: python tools/profile_step.py [--size 160m] [--seq 1024] [--bs 16]
       [--steps 3] [--outdir /tmp/dstpu_trace]

Writes a jax.profiler trace (xplane) and prints the top-N ops by self
time, parsed with tensorboard_plugin_profile's converter — no TensorBoard
UI needed.  Works on CPU (for plumbing tests) and TPU (real numbers).
"""

from __future__ import annotations

import argparse
import glob
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# --platform must take effect BEFORE backend init; a site plugin may have
# pre-pinned jax_platforms (the env var alone cannot override it)
_platform = None
if "--platform" in sys.argv:
    _platform = sys.argv[sys.argv.index("--platform") + 1]
    os.environ["JAX_PLATFORMS"] = _platform

import jax

if _platform:
    jax.config.update("jax_platforms", _platform)

import jax.numpy as jnp
import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", default="160m")
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--bs", type=int, default=16)
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--outdir", default="/tmp/dstpu_trace")
    ap.add_argument("--top", type=int, default=15)
    ap.add_argument("--platform", default=None, help="cpu | tpu (pin early)")
    ap.add_argument("--stage", type=int, default=1,
                    help="ZeRO stage — stage 3 captures the gather/compute "
                         "overlap trace the prefetch bet needs")
    ap.add_argument("--offload", action="store_true",
                    help="host-offload optimizer states (boundary overlap)")
    args = ap.parse_args()

    import deepspeed_tpu
    from deepspeed_tpu.models.llama import llama_model

    zero_cfg = {"stage": args.stage}
    if args.offload:
        zero_cfg["offload_optimizer"] = {"device": "cpu"}
    model = llama_model(args.size, max_seq_len=args.seq)
    engine, *_ = deepspeed_tpu.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": args.bs,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
        "zero_optimization": zero_cfg,
        "gradient_clipping": 1.0,
    })
    rng = np.random.RandomState(0)
    batch = {"input_ids": jnp.asarray(rng.randint(
        0, model.config.vocab_size,
        (1, args.bs * engine.topology.dp_world_size, args.seq)).astype(np.int32))}

    for _ in range(3):  # compile + warm
        loss = engine.train_batch(batch)
    float(loss)

    with jax.profiler.trace(args.outdir):
        for _ in range(args.steps):
            loss = engine.train_batch(batch)
        float(loss)
    print(f"trace written to {args.outdir}")
    report(args.outdir, args.top)


def report(outdir: str, top: int) -> None:
    """Parse the newest xplane.pb and print the top ops by self time."""
    planes = sorted(glob.glob(f"{outdir}/**/*.xplane.pb", recursive=True),
                    key=os.path.getmtime)
    if not planes:
        print("no xplane.pb captured (profiler unsupported on this backend?)")
        return
    from tensorflow.python.profiler.internal import _pywrap_profiler_plugin

    try:
        raw = _pywrap_profiler_plugin.xspace_to_tools_data(
            [planes[-1]], "op_profile")
    except Exception as e:  # tool name varies across versions
        print(f"op_profile conversion failed ({e}); trying overview")
        raw = _pywrap_profiler_plugin.xspace_to_tools_data(
            [planes[-1]], "overview_page")
    data = raw[0] if isinstance(raw, tuple) else raw
    import json

    try:
        parsed = json.loads(data)
    except Exception:
        # op_profile returns a serialized proto on some versions; fall back
        # to the framework_op_stats csv-like tool
        raw = _pywrap_profiler_plugin.xspace_to_tools_data(
            [planes[-1]], "framework_op_stats")
        data = raw[0] if isinstance(raw, tuple) else raw
        print(data[:4000] if isinstance(data, (str, bytes)) else data)
        return

    # op_profile json: byProgram/byCategory tree of {name, metrics}
    def walk(node, out):
        m = node.get("metrics") or {}
        if m.get("selfTimePs"):
            out.append((m["selfTimePs"], node.get("name", "?")))
        for c in node.get("children", []) or []:
            walk(c, out)

    ops = []
    root = (parsed.get("byCategory") or parsed.get("byProgram") or parsed)
    walk(root, ops)
    if not ops:
        print("trace parsed but carries no per-op metrics — the XLA op "
              "profile is populated on TPU/GPU backends only; rerun on the "
              "chip for real numbers")
        return
    ops.sort(reverse=True)
    total = sum(t for t, _ in ops) or 1
    print(f"{'self time':>12}  {'%':>6}  op")
    for t, name in ops[:top]:
        print(f"{t/1e6:9.3f} ms  {100*t/total:5.1f}%  {name[:90]}")


if __name__ == "__main__":
    main()
