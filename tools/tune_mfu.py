"""MFU tuning harness: A/B-times train_batch variants on the real chip.

Usage: python tools/tune_mfu.py [variant ...]   (no args = all)
Prints one line per variant: name, step_ms, tok/s/chip, mfu.

Findings are recorded in docs/PERF_NOTES.md.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def timed_variant(name, size, seq, micro_bs, steps=12, **model_overrides):
    import jax
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.models.llama import llama_model
    from deepspeed_tpu.models.transformer import flops_per_token

    fused_opt = bool(model_overrides.pop("fused_opt", False))
    mu_dtype = model_overrides.pop("mu_dtype", None)
    # zero-config override (the overlap before/after variants): merged
    # over the default stage-1 block
    zero_cfg = {"stage": 1, **model_overrides.pop("zero", {})}
    model = llama_model(size, max_seq_len=seq, **model_overrides)
    config = {
        "train_micro_batch_size_per_gpu": micro_bs,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "FusedAdam" if fused_opt else "AdamW",
                      "params": {"lr": 1e-4, "weight_decay": 0.1,
                                 **({"fused_kernel": True} if fused_opt else {}),
                                 **({"mu_dtype": mu_dtype} if mu_dtype else {})}},
        "bf16": {"enabled": True},
        "zero_optimization": zero_cfg,
        "gradient_clipping": 1.0,
    }
    engine, *_ = deepspeed_tpu.initialize(model=model, config=config)
    if fused_opt:
        # on a multi-chip mesh the engine falls back to optax — that would
        # silently A/B the identical path; fail loudly instead
        assert getattr(engine.optimizer, "direct_update", None) is not None, \
            "fused_kernel fell back to optax (multi-device mesh?)"
    rng = np.random.RandomState(0)
    vocab = model.config.vocab_size

    def batch():
        ids = rng.randint(0, vocab, (1, micro_bs, seq)).astype(np.int32)
        return {"input_ids": jnp.asarray(ids)}

    loss = engine.train_batch(batch())
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine.train_batch(batch())
    final = float(loss)  # host roundtrip: real completion
    dt = time.perf_counter() - t0
    assert np.isfinite(final), name

    tokens = steps * micro_bs * seq
    tok_s = tokens / dt
    flops = flops_per_token(model.config, seq) * tokens
    import bench
    peak = bench._peak_for(jax.devices()[0])  # per-chip bf16 peak by device kind
    mfu = flops / dt / peak
    rep = engine.overlap_report()
    ovl = f"  ovl={rep.overlapped_fraction:.2f}" if rep is not None else ""
    print(f"{name:36s} step={dt/steps*1e3:8.1f}ms  tok/s={tok_s:9.0f}  "
          f"mfu={mfu:.3f}{ovl}", flush=True)
    del engine
    return mfu


VARIANTS = {
    # name: (size, seq, bs, overrides)
    "base-160m-flash512": ("160m", 1024, 8, {}),
    "160m-xla-attn": ("160m", 1024, 8, {"attn_impl": "xla"}),
    "160m-flash-jaxstock": ("160m", 1024, 8, {"attn_impl": "flash_jax"}),
    "160m-flash-bq256": ("160m", 1024, 8, {"attn_impl": "flash_bq256"}),
    "160m-losschunk341": ("160m", 1024, 8, {"loss_chunk": 341}),
    "160m-bs32": ("160m", 1024, 32, {}),
    "160m-bs16": ("160m", 1024, 16, {}),
    # bwd-tile decoupling: fwd stays 512/512 (the measured optimum), bwd
    # kernels sweep their own tiles — targets the 27ms bwd/fwd slack in
    # docs/PERF_NOTES.md's decomposition
    "160m-bwd256x256": ("160m", 1024, 16, {"attn_impl": "flash_bwd256x256"}),
    "160m-bwd256x512": ("160m", 1024, 16, {"attn_impl": "flash_bwd256x512"}),
    "160m-bwd512x256": ("160m", 1024, 16, {"attn_impl": "flash_bwd512x256"}),
    "160m-bwd1024x512": ("160m", 1024, 16, {"attn_impl": "flash_bwd1024x512"}),
    # single-pass Pallas Adam vs the XLA-fused optax chain (~10ms of the
    # 195ms step is optimizer+clip in PERF_NOTES' decomposition)
    "160m-fusedadam": ("160m", 1024, 16, {"fused_opt": True}),
    "1b-bs8-remat": ("1b", 1024, 8, {"remat": True}),
    "1b-bs4": ("1b", 1024, 4, {}),
    # memory-lean 1b: bf16 exp_avg + fused single-pass update — the
    # config the 1b-mu16 bench rung runs if plain 1b OOMs
    "1b-bs8-mu16-fused": ("1b", 1024, 8, {"remat": True, "fused_opt": True,
                                          "mu_dtype": "bf16"}),
    # remat policy tradeoff: keeping matmul outputs costs HBM but saves
    # recompute FLOPs — worth an A/B at the 1b shape
    "1b-bs8-remat-dots": ("1b", 1024, 8, {
        "remat": True, "mu_dtype": "bf16", "fused_opt": True,
        "remat_policy": "dots_with_no_batch_dims_saveable"}),
    # compute/collective overlap before/after (runtime/zero/overlap.py;
    # docs/COMM.md "Overlap & scheduling"): run the off/on pairs in ONE
    # session so the chip + flag state is identical — the wall delta IS
    # the exposed-comm recovery, and the printed ovl= column shows the
    # structural fraction backing it
    "160m-z1-overlap-off": ("160m", 1024, 16, {"zero": {"stage": 1}}),
    "160m-z1-overlap": ("160m", 1024, 16, {
        "zero": {"stage": 1, "overlap_grad_reduce": True}}),
    "160m-z3-overlap-off": ("160m", 1024, 16, {"zero": {"stage": 3}}),
    "160m-z3-overlap": ("160m", 1024, 16, {
        "zero": {"stage": 3, "overlap_grad_reduce": True,
                 "zero3_param_prefetch": True}}),
}


def _tpu_expected() -> bool:
    """Whether a TPU backend will initialize in this process — the
    latency-hiding flags are TPU-only and abort CPU/GPU XLA startup, so
    pin them only when a TPU plugin is actually present (an unset
    JAX_PLATFORMS is the common case on CPU boxes and must NOT pin)."""
    import importlib.util

    plat = os.environ.get("JAX_PLATFORMS", "")
    if "cpu" in plat:
        return False
    if "tpu" in plat:
        return True
    return importlib.util.find_spec("libtpu") is not None


def main():
    # pin the latency-hiding scheduler flags BEFORE the backend comes up
    # (compile/backend.py; the overlap variants are meaningless without
    # them)
    if _tpu_expected():
        from deepspeed_tpu.compile.backend import pin_latency_hiding_flags

        added = pin_latency_hiding_flags()
        if added:
            print(f"tune_mfu: pinned XLA flags {added}", flush=True)
    names = sys.argv[1:] or list(VARIANTS)
    # patch the special attn impl variants in via TransformerConfig.attn_impl
    import deepspeed_tpu.models.transformer as T

    orig_pick = T._pick_attn

    def pick(cfg):
        if cfg.attn_impl == "flash_jax":
            from deepspeed_tpu.ops.pallas.flash_attention import flash_attention
            return lambda q, k, v, causal, mask=None: flash_attention(
                q, k, v, causal=causal, segment_mask=mask, impl="jax")
        if cfg.attn_impl == "flash_bq256":
            from deepspeed_tpu.ops.pallas.flash_attention import flash_attention
            return lambda q, k, v, causal, mask=None: flash_attention(
                q, k, v, causal=causal, segment_mask=mask,
                block_q=256, block_k=256)
        if cfg.attn_impl.startswith("flash_bwd"):
            from deepspeed_tpu.ops.pallas.flash_attention import flash_attention
            bq, bk = map(int, cfg.attn_impl[len("flash_bwd"):].split("x"))
            fn = lambda q, k, v, causal, mask=None: flash_attention(  # noqa: E731
                q, k, v, causal=causal, segment_mask=mask,
                bwd_block_q=bq, bwd_block_k=bk)
            fn.handles_gqa = True  # GQA-native kernel, kv heads unrepeated
            return fn
        return orig_pick(cfg)

    T._pick_attn = pick
    for n in names:
        size, seq, bs, ov = VARIANTS[n]
        try:
            timed_variant(n, size, seq, bs, **ov)
        except Exception as e:  # OOM etc: report and continue
            print(f"{n:36s} FAILED: {type(e).__name__}: {e}", flush=True)


if __name__ == "__main__":
    main()
