"""CPU-mesh contingency sweep: compiled-HLO cost-model MFU ESTIMATES.

When the tunneled chip is dark for a whole round (rounds 3 and 4), the
driver artifact records a CPU fallback and every perf question stays
open.  This tool compiles each training rung's REAL step program (same
model, config and shapes as tools/bench_sweep.py) for a single CPU
device, reads XLA's cost analysis (flops + bytes accessed), and converts
to a v5e-one-chip time estimate via a two-term roofline:

    t_est = max(hw_flops / (PEAK * mxu_eff), bytes / (HBM_BW * bw_eff))
    mfu_est = model_flops / (t_est * PEAK)

EVERY number this tool emits is an ESTIMATE (method field says so):
XLA's CPU fusion differs from TPU, cost analysis counts post-fusion
bytes approximately, and the efficiency factors are assumptions
(defaults: mxu_eff 0.6 — between the round-2 measured 0.445 fwd+bwd and
the 0.54 reference comparator; bw_eff 0.8).  The point is to rank rungs
and bound expectations for round 6, not to claim hardware results.

Serving rungs are estimated analytically (decode is bandwidth-bound:
tok/s <= HBM_BW * bw_eff / bytes-touched-per-token).

Usage:  python tools/bench_estimate.py [rung ...]   (default: all)
Writes docs/BENCH_ESTIMATE.json incrementally, one entry per rung.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PEAK = 197e12       # v5e bf16 (bench.py PEAK_BF16_FLOPS)
HBM_BW = 819e9      # v5e HBM bytes/s
MXU_EFF = float(os.environ.get("DSTPU_EST_MXU_EFF", "0.6"))
BW_EFF = float(os.environ.get("DSTPU_EST_BW_EFF", "0.8"))
OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "docs", "BENCH_ESTIMATE.json")

_CHILD = """
import json, os, sys
os.environ.pop("XLA_FLAGS", None)
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
sys.path.insert(0, %(root)r)
env = %(env)r
for k, v in env.items():
    os.environ[k] = v
import deepspeed_tpu
from deepspeed_tpu.models.transformer import flops_per_token
from bench import build_model_and_config

size = env.get("DSTPU_BENCH_SIZE", "160m")
seq = int(env.get("DSTPU_BENCH_SEQ", "1024"))
bs = int(env.get("DSTPU_BENCH_BS", "16"))
# scan_layers=False: XLA cost analysis is while-loop trip-count-unaware —
# a scanned program's per-layer flops/bytes would be counted ONCE
# (estimate-only variant; the bench itself runs the scanned program)
model, config, _meta = build_model_and_config(size, seq, bs, env=env,
                                              scan_layers=False)
engine, *_ = deepspeed_tpu.initialize(model=model, config=config)
ids = jnp.asarray(np.random.RandomState(0).randint(
    0, model.config.vocab_size, (1, bs, seq)), jnp.int32)
batch = {"input_ids": ids}
fn = engine._train_batch
lowered = fn.lower(engine.state, batch, jax.random.PRNGKey(0))
cost = lowered.compile().cost_analysis()
if isinstance(cost, list):
    cost = cost[0]
tokens = bs * seq
print(json.dumps({
    "hlo_flops": float(cost.get("flops", -1)),
    "hlo_bytes": float(cost.get("bytes accessed", -1)),
    "model_flops": float(flops_per_token(model.config, seq)) * tokens,
    "tokens": tokens,
    "n_params": int(sum(x.size for x in jax.tree_util.tree_leaves(
        engine.state.params))),
}))
"""


def _load():
    if os.path.exists(OUT):
        with open(OUT) as f:
            return json.load(f)
    return {}


def _save(data):
    with open(OUT, "w") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")


def estimate_training(name: str, env: dict) -> dict:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    child = subprocess.run(
        [sys.executable, "-c", _CHILD % {"root": root, "env": env}],
        capture_output=True, text=True,
        timeout=int(os.environ.get("DSTPU_EST_TIMEOUT", "1800")))
    line = child.stdout.strip().splitlines()[-1] if child.stdout.strip() else ""
    if child.returncode != 0 or not line.startswith("{"):
        return {"rung": name, "error": (child.stderr or "no output")[-500:]}
    c = json.loads(line)
    model_flops = c["model_flops"]
    # hw flops: XLA's own count of what the compiled program executes
    # (includes remat recompute); fall back to model flops if unreported
    hw_flops = c["hlo_flops"] if c["hlo_flops"] > 0 else model_flops
    ideal_bytes = c["hlo_bytes"]
    t_flops = hw_flops / (PEAK * MXU_EFF)
    t_bytes = ideal_bytes / (HBM_BW * BW_EFF) if ideal_bytes > 0 else 0.0
    return {
        "rung": name,
        "method": "ESTIMATE: XLA CPU-compiled cost analysis + v5e roofline "
                  f"(peak {PEAK:.3g} flops/s, bw {HBM_BW:.3g} B/s, "
                  f"mxu_eff {MXU_EFF}, bw_eff {BW_EFF}) — NOT a hardware "
                  "measurement.  The calibrated fields anchor to the ONE "
                  "on-chip measurement that exists (round-2 flagship, MFU "
                  "0.384 => 0.198 s/step) and transfer cross-rung by "
                  "relative compiled flops; byte counts come from the CPU "
                  "backend's fusion and overstate TPU traffic.",
        "model_flops_per_step": model_flops,
        "hw_flops_per_step_hlo": hw_flops,
        "bytes_per_step_hlo": ideal_bytes,
        "tokens_per_step": c["tokens"],
        "n_params": c["n_params"],
        "bound_hint": "memory" if t_bytes > t_flops else "compute",
        "est_step_seconds_flops_roofline": t_flops,
        "est_step_seconds_bytes_roofline": t_bytes,
    }


# the one hardware anchor: round-2 on-chip flagship (docs/PERF_NOTES.md)
ANCHOR_RUNG = "flagship"
ANCHOR_MEASURED_STEP_S = 0.198  # 160m seq1024 bs16, MFU 0.384 on v5e


def _calibrate(data: dict) -> None:
    anchor = data.get(ANCHOR_RUNG)
    if not anchor or "est_step_seconds_flops_roofline" not in anchor:
        return
    k = ANCHOR_MEASURED_STEP_S / anchor["est_step_seconds_flops_roofline"]
    data["_calibration"] = {
        "anchor_rung": ANCHOR_RUNG,
        "anchor_measured_step_seconds": ANCHOR_MEASURED_STEP_S,
        "scale_vs_flops_roofline": k,
        "note": "calibrated fields = flops-roofline time scaled so the "
                "anchor matches its round-2 on-chip measurement; offload/"
                "host-bound rungs will be optimistic (the anchor embeds "
                "no host traffic)",
    }
    for name, entry in data.items():
        if isinstance(entry, dict) and \
                "est_step_seconds_flops_roofline" in entry:
            t = entry["est_step_seconds_flops_roofline"] * k
            entry["est_step_seconds_calibrated"] = t
            entry["est_tokens_per_second_calibrated"] = \
                entry["tokens_per_step"] / t
            entry["est_mfu_calibrated"] = \
                entry["model_flops_per_step"] / (t * PEAK)


def estimate_serving(name: str, env: dict) -> dict:
    """Decode is memory-bound: every batched decode step streams the
    weights plus the live slots' KV pages; tok/s/chip <= batch * BW /
    bytes-per-step."""
    from deepspeed_tpu.models.llama import llama_config
    from deepspeed_tpu.models.transformer import param_count

    size = env.get("DSTPU_IBENCH_SIZE", "160m")
    cfg = llama_config(size, max_seq_len=4096)
    n_params = param_count(cfg)
    wq = env.get("DSTPU_IBENCH_WQ")
    if wq and wq not in ("4", "8"):
        return {"rung": name, "error": f"unsupported DSTPU_IBENCH_WQ {wq!r}"}
    wbytes = int(wq) / 8 if wq else 2
    kv_el = 1 if env.get("DSTPU_IBENCH_KVQ") == "1" else 2
    ctx = int(env.get("DSTPU_IBENCH_PROMPT", "512")) + \
        int(env.get("DSTPU_IBENCH_GEN", "128")) // 2
    nreq = int(env.get("DSTPU_IBENCH_NREQ", "32"))
    # bench_inference decodes DSTPU_IBENCH_SLOTS concurrent slots (its
    # default 8), not the whole request queue
    batch = min(int(env.get("DSTPU_IBENCH_SLOTS", "8")), nreq)
    kv_bytes = (2 * cfg.n_layers * cfg.kv_heads * cfg.head_dim
                * ctx * kv_el) * batch
    per_step = n_params * wbytes + kv_bytes  # one batched decode step
    t_step = per_step / (HBM_BW * BW_EFF)
    return {
        "rung": name,
        "method": "ESTIMATE: analytic bandwidth roofline for batched "
                  f"decode (bw {HBM_BW:.3g} * {BW_EFF}) — NOT a hardware "
                  "measurement",
        "batch": batch,
        "weight_bytes": n_params * wbytes,
        "kv_bytes_at_mid_gen": kv_bytes,
        "est_decode_steps_per_second": 1.0 / t_step,
        "est_tokens_per_second": batch / t_step,
        "bound": "memory",
    }


def main() -> int:
    from tools.bench_sweep import RUNGS

    names = sys.argv[1:] or list(RUNGS)
    data = _load()
    # the anchor rung is always computed (calibration needs it); a stored
    # FAILED anchor (error entry) is re-queued, not kept forever
    anchor_ok = "est_step_seconds_flops_roofline" in data.get(ANCHOR_RUNG, {})
    if ANCHOR_RUNG not in names and not anchor_ok:
        names = [ANCHOR_RUNG] + names
    for name in names:
        if name not in RUNGS:
            print(f"unknown rung {name}", file=sys.stderr)
            continue
        env = {k: v for k, v in RUNGS[name].items() if not k.startswith("_")}
        print(f"[bench_estimate] {name} ...", flush=True)
        try:
            if RUNGS[name].get("_tool") == "bench_inference":
                entry = estimate_serving(name, env)
            else:
                entry = estimate_training(name, env)
        except subprocess.TimeoutExpired:
            entry = {"rung": name, "error": "compile timeout"}
        data[name] = entry
        _calibrate(data)
        _save(data)
        print(json.dumps(entry), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
